"""Manipulations for DCSR matrices.

Parity with /root/reference/heat/sparse/manipulations.py: ``to_dense``
(:52) and ``to_sparse`` (:16), both attached to the array classes."""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp

from typing import Optional

from ..core import types
from ..core.dndarray import DNDarray
from .dcsr_matrix import DCSR_matrix
from ._operations import rows_from_indptr
from .factories import sparse_csr_matrix

__all__ = ["to_dense", "to_sparse"]


def to_sparse(array: DNDarray) -> DCSR_matrix:
    """DNDarray → DCSR_matrix (reference manipulations.py:16). The sparsity
    pattern is data-dependent, so extraction happens host-side at
    construction time — the same eager boundary the reference crosses with
    torch's ``to_sparse_csr``."""
    if array.ndim != 2:
        raise ValueError(f"to_sparse requires a 2-D DNDarray, got {array.ndim}-D")
    split = 0 if array.split is not None else None
    return sparse_csr_matrix(
        array.numpy(), dtype=array.dtype, split=split, device=array.device, comm=array.comm
    )


DNDarray.to_sparse = to_sparse


@functools.lru_cache(maxsize=128)
def _scatter_dense(m: int, n: int, nnz: int, jdtype: str):
    @jax.jit
    def kernel(indptr, cols, data):
        rows = rows_from_indptr(indptr, nnz)
        return jnp.zeros((m, n), dtype=data.dtype).at[rows, cols].set(data)

    return kernel


def to_dense(sparse_matrix: DCSR_matrix, order: str = "C", out: Optional[DNDarray] = None) -> DNDarray:
    """DCSR_matrix → dense DNDarray with the same distribution (reference
    manipulations.py:52): one jitted scatter on device."""
    if order not in ("C",):
        raise NotImplementedError("XLA owns physical layout; only order='C' semantics exist")
    m, n = sparse_matrix.shape
    if sparse_matrix.gnnz == 0:
        dense = jnp.zeros((m, n), dtype=sparse_matrix.dtype.jax_type())
    else:
        kernel = _scatter_dense(m, n, sparse_matrix.gnnz, np.dtype(sparse_matrix.dtype.jax_type()).name)
        dense = kernel(sparse_matrix.indptr, sparse_matrix.indices, sparse_matrix.data)
    comm = sparse_matrix.comm
    result = DNDarray(
        comm.shard(dense, sparse_matrix.split),
        (m, n),
        sparse_matrix.dtype,
        sparse_matrix.split,
        sparse_matrix.device,
        comm,
    )
    if out is not None:
        if out.shape != result.shape:
            raise ValueError(f"out has shape {out.shape}, expected {result.shape}")
        if out.split != result.split:
            raise ValueError(f"out has split {out.split}, expected {result.split}")
        out._set_phys(result._phys.astype(out.dtype.jax_type()))
        return out
    return result
