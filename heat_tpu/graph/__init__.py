"""Distributed graph algorithms (reference: /root/reference/heat/graph/).

``Laplacian`` is the reference-parity similarity-graph Laplacian; the
rest EXCEEDS the reference — sparse-engine analytics on the mesh:
PageRank as a streamed SpMV fixpoint (:func:`pagerank`, and
:func:`pagerank_stream` for host-resident edge lists riding the staging
windows) and :func:`spectral_embedding` feeding the DBCSR brick
operator to the Lanczos solver."""

from .laplacian import *
from .pagerank import PageRankResult, pagerank, pagerank_stream
from .spectral import spectral_embedding
