"""Distributed graph algorithms (reference: /root/reference/heat/graph/)."""

from .laplacian import *
