"""PageRank as a streamed SpMV fixpoint on the sparse engine.

The power iteration over the column-stochastic transition operator

    r  <-  alpha * (M @ r  +  dangling_mass / n)  +  (1 - alpha) / n

with ``M = A^T D_out^{-1}`` — every iteration is ONE distributed SpMV
on the DBCSR brick engine (kernels/spmm.py), so the damping/teleport
arithmetic rides for free on the host between multiplies and the whole
fixpoint inherits the engine's 0-collective local census and the
``HEAT_TPU_SPMM_KERNEL`` gate.

Two forms:

* :func:`pagerank` — the transition matrix lives on the mesh as a
  ``DBCSR_matrix``; right for graphs whose edge structure fits HBM.
* :func:`pagerank_stream` — the edge list never materializes on
  device: a :class:`~heat_tpu.redistribution.staging.HostArray` of
  (src, dst) pairs streams through the PR 11 staging windows
  (depth-2 double-buffered ``stream_windows``, plan-stamped by
  ``plan_staged_passes``) and each window's contribution lands via a
  segment-sum — PageRank on graphs larger than HBM, the ROADMAP's
  "larger-than-HBM" scenario applied to edges instead of samples.

Both forms converge to the same fixpoint (same operator, different
storage tier); ``tests/test_graph.py`` pins them against a dense numpy
oracle on seeded random graphs.
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Union

import numpy as np

import jax
import jax.numpy as jnp

from ..core import factories, types
from ..core.dndarray import DNDarray
from ..core.devices import Device
from ..core.communication import Communication
from ..sparse.dbcsr_matrix import DBCSR_matrix, sparse_dbcsr_matrix
from ..sparse.dcsr_matrix import DCSR_matrix
from ..sparse.factories import _to_scipy_csr
from ..redistribution import staging as _staging

__all__ = ["PageRankResult", "pagerank", "pagerank_stream"]


class PageRankResult(NamedTuple):
    """Outcome of a PageRank fixpoint run."""

    ranks: DNDarray          # (n,) — sums to 1
    iterations: int          # SpMV sweeps taken
    converged: bool          # l1 delta fell under tol before max_iter
    delta: float             # final l1 step size


def _adjacency_to_scipy(A) -> "np.ndarray":
    """Adjacency (A[i, j] != 0 is an edge i -> j) to host scipy CSR."""
    import scipy.sparse as sp

    if isinstance(A, DBCSR_matrix):
        return A._to_scipy_bsr().tocsr()[: A.shape[0], : A.shape[1]]
    if isinstance(A, DCSR_matrix):
        indptr = np.asarray(jax.device_get(A.indptr))
        indices = np.asarray(jax.device_get(A.indices))
        data = np.asarray(jax.device_get(A.data))
        return sp.csr_matrix((data, indices, indptr), shape=A.shape)
    if isinstance(A, DNDarray):
        return sp.csr_matrix(np.asarray(A.numpy()))
    return _to_scipy_csr(A, None)


def _transition(csr, dtype_np):
    """Column-stochastic M = A^T D_out^{-1} plus the dangling mask.

    Rows of A with no out-edges (dangling nodes) have no column in M;
    their rank mass teleports uniformly — handled in the iteration, not
    the matrix, so M keeps the graph's sparsity exactly."""
    import scipy.sparse as sp

    n = csr.shape[0]
    outdeg = np.asarray(csr.sum(axis=1)).ravel()
    dangling = outdeg == 0
    inv = np.where(dangling, 0.0, 1.0 / np.where(dangling, 1.0, outdeg))
    M = (sp.diags(inv) @ csr).T.tocsr().astype(dtype_np)
    return M, dangling.astype(dtype_np), n


def pagerank(
    A: Union[DBCSR_matrix, DCSR_matrix, DNDarray, "object"],
    alpha: float = 0.85,
    tol: float = 1e-8,
    max_iter: int = 200,
    split: Optional[int] = 0,
    device: Optional[Device] = None,
    comm: Optional[Communication] = None,
) -> PageRankResult:
    """PageRank of a directed graph given its adjacency structure.

    ``A[i, j] != 0`` is an edge ``i -> j`` (weights count as edge
    multiplicity). The transition matrix is built once host-side, lands
    on the mesh as a row-distributed ``DBCSR_matrix``, and the fixpoint
    runs one brick-engine SpMV per iteration. ``alpha`` is the damping
    factor, ``tol`` the l1 convergence threshold on the rank delta.
    """
    if not 0.0 < alpha < 1.0:
        raise ValueError(f"alpha must be in (0, 1), got {alpha}")
    csr = _adjacency_to_scipy(A)
    if csr.shape[0] != csr.shape[1]:
        raise ValueError(f"adjacency must be square, got {csr.shape}")
    M_host, dangling, n = _transition(csr, np.float32)
    M = sparse_dbcsr_matrix(M_host, dtype=types.float32, split=split,
                            device=device, comm=comm)
    r = np.full(n, 1.0 / n, np.float32)
    delta = np.inf
    it = 0
    for it in range(1, max_iter + 1):
        mass = float(dangling @ r)  # dangling rank teleports uniformly
        y = M @ jnp.asarray(r)
        r_new = np.asarray(y.numpy()) * alpha + np.float32(
            (alpha * mass + (1.0 - alpha)) / n
        )
        delta = float(np.abs(r_new - r).sum())
        r = r_new
        if delta < tol:
            break
    ranks = factories.array(r / r.sum(), dtype=types.float32, split=split,
                            device=device, comm=comm)
    return PageRankResult(ranks, it, delta < tol, delta)


def pagerank_stream(
    edges: Union[_staging.HostArray, np.ndarray],
    n: int,
    alpha: float = 0.85,
    tol: float = 1e-8,
    max_iter: int = 200,
    slab: Optional[int] = None,
) -> PageRankResult:
    """PageRank from a host-resident edge list that never fully lands
    on device.

    ``edges`` is an (E, 2) int32 ``HostArray`` (or ndarray, wrapped) of
    ``(src, dst)`` pairs — duplicates count as multiplicity, matching
    the weighted adjacency of :func:`pagerank`. One streamed pass
    computes the out-degrees; each fixpoint iteration then re-streams
    the edges through the PR 11 depth-2 windows, accumulating
    ``segment_sum(r[src] / outdeg[src], dst)`` per window. The staged
    plan is stamped (``plan_staged_passes`` + ``prove_fits``), so the
    stream shows up in attribution like every other staged workload.
    """
    if not 0.0 < alpha < 1.0:
        raise ValueError(f"alpha must be in (0, 1), got {alpha}")
    if not isinstance(edges, _staging.HostArray):
        edges = _staging.HostArray(np.ascontiguousarray(edges, np.int32))
    if edges.shape[1] != 2:
        raise ValueError(f"edges must be (E, 2) (src, dst), got {edges.shape}")
    n = int(n)
    sched = _staging.plan_staged_passes(
        edges.shape,
        edges.dtype,
        [{"tag": "outdeg", "axis": 0}, {"tag": "power", "axis": 0}],
        out_bytes=3 * n * 4 + (1 << 20),  # r, outdeg, accumulator in HBM
        slab=slab,
    )
    _staging.prove_fits(sched)
    slab_b = int(sched.staging["slab_bytes"])
    wins = _staging.window_extents(edges.shape, edges.dtype.itemsize, 0, slab_b)

    @jax.jit
    def _deg_window(acc, slab_arr):
        return acc + jax.ops.segment_sum(
            jnp.ones(slab_arr.shape[0], jnp.float32), slab_arr[:, 0],
            num_segments=n,
        )

    @jax.jit
    def _power_window(acc, slab_arr, w):
        return acc + jax.ops.segment_sum(
            w[slab_arr[:, 0]], slab_arr[:, 1], num_segments=n
        )

    # pass 1: out-degrees (windowed bincount of the src column)
    outdeg = jnp.zeros(n, jnp.float32)

    def _consume_deg(k, slab_arr, win):
        nonlocal outdeg
        outdeg = _deg_window(outdeg, slab_arr)

    _staging.stream_windows(edges, 0, wins, _consume_deg, plan_id=sched.plan_id)
    dangling = np.asarray(jax.device_get(outdeg)) == 0
    inv = jnp.asarray(np.where(dangling, 0.0, 1.0 / np.maximum(
        np.asarray(jax.device_get(outdeg)), 1e-30)).astype(np.float32))

    r = np.full(n, 1.0 / n, np.float32)
    delta = np.inf
    it = 0
    for it in range(1, max_iter + 1):
        w = jnp.asarray(r) * inv
        acc = jnp.zeros(n, jnp.float32)

        def _consume_pow(k, slab_arr, win):
            nonlocal acc
            acc = _power_window(acc, slab_arr, w)

        _staging.stream_windows(edges, 0, wins, _consume_pow,
                                plan_id=sched.plan_id)
        mass = float(r[dangling].sum())
        r_new = np.asarray(jax.device_get(acc)) * alpha + np.float32(
            (alpha * mass + (1.0 - alpha)) / n
        )
        delta = float(np.abs(r_new - r).sum())
        r = r_new
        if delta < tol:
            break
    ranks = factories.array(r / r.sum(), dtype=types.float32, split=None)
    return PageRankResult(ranks, it, delta < tol, delta)
