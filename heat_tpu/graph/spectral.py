"""Spectral embedding: the DBCSR operator fed to the Lanczos solver.

The classical pipeline — symmetrically-normalized graph Laplacian,
smallest-k eigenvectors, rows as coordinates — but the operator is
never densified: each Lanczos step's matvec is the brick-sparse

    L_sym v  =  v  -  D^{-1/2} A D^{-1/2} v

evaluated on the DBCSR components inside the solver's jitted scan
(``core.linalg.solver._lanczos_program`` grew a ``matvec`` parameter
for exactly this; with it unset the dense program is trace-identical
to before). The brick contraction uses the same einsum/segment-sum
formulation as the kernel oracle, with the bmask routing non-owned
boundary-brick rows to a dropped sentinel segment, so straddle
duplication never double-counts.

Degrees come from one engine SpMV (``A @ 1``); the small tridiagonal
eigenproblem solves host-side (``numpy.linalg.eigh`` on an (m, m)
matrix is microseconds); the embedding ``V @ W_k`` stays on device.
"""

from __future__ import annotations

import functools

from typing import Optional, Tuple, Union

import numpy as np

import jax
import jax.numpy as jnp

from ..core import factories, types
from ..core.dndarray import DNDarray
from ..sparse.dbcsr_matrix import BRICK_SHAPE, DBCSR_matrix, to_dbcsr

__all__ = ["spectral_embedding"]

_BR, _BC = BRICK_SHAPE


@functools.lru_cache(maxsize=64)
def _lap_matvec(n: int, mb: int, nb: int, normalized: bool):
    """The Laplacian matvec the Lanczos scan calls — cached so the
    callable's identity is stable across calls with the same geometry
    (``_lanczos_program`` keys its program cache on it).

    ``ops = (bdata, bcol, brow, bmask, dvec)``: the DBCSR physical
    components plus ``dvec = D^{-1/2}`` (normalized) or ``D``
    (simple). Global formulation: every stored brick contributes
    ``brick @ x[bcol]`` rows at ``brow * 8 + lane``, the bmask sends
    rows a device does not own (straddle duplicates, slab pad) to the
    ``mb * 8`` sentinel segment, and one segment-sum assembles the
    product."""

    def mv(ops, v):
        bdata, bcol, brow, bmask, dvec = ops
        x = v * dvec if normalized else v
        xp = jnp.pad(x, (0, nb * _BC - n))
        xg = xp.reshape(nb, _BC)[bcol]                      # (B, 128)
        contrib = jnp.einsum("bij,bj->bi", bdata, xg)       # (B, 8)
        rows = (
            brow[:, None].astype(jnp.int32) * _BR
            + jnp.arange(_BR, dtype=jnp.int32)[None, :]
        )
        rows = jnp.where(bmask, rows, mb * _BR)
        Av = jax.ops.segment_sum(
            contrib.reshape(-1), rows.reshape(-1), num_segments=mb * _BR + 1
        )[:n]
        if normalized:
            return v - Av * dvec       # L_sym v = v - D^-1/2 A D^-1/2 v
        return dvec * v - Av           # L v = D v - A v

    return mv


def spectral_embedding(
    A: Union[DBCSR_matrix, "object"],
    k: int,
    m: Optional[int] = None,
    normalized: bool = True,
) -> Tuple[np.ndarray, DNDarray]:
    """Smallest-``k`` spectral coordinates of a symmetric graph.

    ``A`` is a symmetric adjacency (``DBCSR_matrix`` or anything
    :func:`~heat_tpu.sparse.to_dbcsr` accepts); ``m`` is the Lanczos
    subspace size (default ``min(n, max(2k + 1, 20))``). Returns
    ``(eigenvalues, embedding)``: the ``k`` Ritz values closest to the
    bottom of the Laplacian spectrum and the (n, k) coordinate matrix,
    distributed like ``A``.
    """
    from ..core.linalg import solver as _solver

    if not isinstance(A, DBCSR_matrix):
        A = to_dbcsr(A)
    n_rows, n_cols = A.shape
    if n_rows != n_cols:
        raise ValueError(f"adjacency must be square, got {A.shape}")
    n = n_rows
    k = int(k)
    if not 1 <= k <= n:
        raise ValueError(f"k must be in [1, {n}], got {k}")
    m = int(min(n, max(2 * k + 1, 20)) if m is None else m)
    if not k <= m <= n:
        raise ValueError(f"need k <= m <= n, got m={m}")

    Af = A if A.dtype == types.float32 else A.astype(types.float32)
    # degrees via one engine SpMV; the Laplacian then never materializes
    deg = np.asarray((Af @ np.ones(n, np.float32)).numpy())
    if normalized:
        dvec = jnp.asarray(
            np.where(deg > 0, 1.0 / np.sqrt(np.maximum(deg, 1e-30)), 0.0)
            .astype(np.float32)
        )
    else:
        dvec = jnp.asarray(deg.astype(np.float32))

    bdata, bcol, brow, bmask = Af._phys_components
    ops = (bdata, bcol, brow, bmask, dvec)
    mv = _lap_matvec(n, Af.mb, Af.nb, bool(normalized))

    rng = np.random.default_rng(0x5BED)
    v0 = rng.standard_normal(n).astype(np.float32)
    v0 = jnp.asarray(v0 / np.linalg.norm(v0))

    prog = _solver._lanczos_program(n, m, "float32", 1e-10, mv)
    key = jax.random.key(0x1A2C05)
    V_arr, alpha_d, beta_d = prog(ops, v0, key)

    alpha = np.asarray(jax.device_get(alpha_d))
    beta = np.asarray(jax.device_get(beta_d))
    T = np.diag(alpha) + np.diag(beta[1:], 1) + np.diag(beta[1:], -1)
    evals, evecs = np.linalg.eigh(T)      # ascending — smallest first
    W = jnp.asarray(evecs[:, :k].astype(np.float32))
    emb = V_arr @ W                        # (n, k) on device

    comm = Af.comm
    split = 0 if Af.split == 0 else None
    phys = comm.shard(emb, split) if split == 0 else emb
    embedding = DNDarray(phys, (n, k), types.float32, split, Af.device, comm)
    return evals[:k].astype(np.float32), embedding
