"""Graph Laplacian.

API parity with /root/reference/heat/graph/laplacian.py (``Laplacian``
:39-141): similarity-matrix construction (fully-connected or
ε-neighborhood) and simple / symmetrically-normalized Laplacians, all as
sharded array expressions.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from typing import Callable

from ..core import factories, types
from ..core.dndarray import DNDarray
from ..core.sanitation import sanitize_in

__all__ = ["Laplacian"]


class Laplacian:
    """Graph Laplacian of a similarity structure (reference:
    laplacian.py:14).

    Parameters follow the reference: ``similarity`` is a callable mapping
    the data X to a pairwise similarity matrix S; ``definition`` selects
    ``'simple'`` (L = D − A) or ``'norm_sym'`` (L = I − D^-1/2 A D^-1/2);
    ``mode`` selects ``'fully_connected'`` or ``'eNeighbour'`` adjacency;
    thresholding per ``threshold_key``/``threshold_value``.
    """

    def __init__(
        self,
        similarity: Callable,
        weighted: bool = True,
        definition: str = "norm_sym",
        mode: str = "fully_connected",
        threshold_key: str = "upper",
        threshold_value: float = 1.0,
        neighbours: int = 10,
    ):
        self.similarity_metric = similarity
        self.weighted = weighted
        if definition not in ("simple", "norm_sym"):
            raise NotImplementedError(
                "Only simple and normalized symmetric graph laplacians are supported at the moment"
            )
        if mode not in ("eNeighbour", "fully_connected"):
            raise NotImplementedError(
                "Only eNeighborhood and fully-connected graphs supported at the moment."
            )
        self.definition = definition
        self.mode = mode
        self.epsilon = (threshold_key, threshold_value)
        self.neighbours = neighbours

    def _normalized_symmetric_L(self, A: DNDarray) -> DNDarray:
        """L = I − D^−1/2 A D^−1/2 (reference: laplacian.py:90)."""
        arr = A.larray
        degree = jnp.sum(arr, axis=1)
        d_inv_sqrt = jnp.where(degree > 0, 1.0 / jnp.sqrt(degree), 0.0)
        L = -arr * d_inv_sqrt[:, None] * d_inv_sqrt[None, :]
        L = L + jnp.eye(arr.shape[0], dtype=arr.dtype)
        gshape = tuple(int(s) for s in L.shape)
        if A.split is not None:
            L = A.comm.shard(L, A.split)
        return DNDarray(L, gshape, A.dtype, A.split, A.device, A.comm)

    def _simple_L(self, A: DNDarray) -> DNDarray:
        """L = D − A (reference: laplacian.py:118)."""
        arr = A.larray
        degree = jnp.sum(arr, axis=1)
        L = jnp.diag(degree) - arr
        gshape = tuple(int(s) for s in L.shape)
        if A.split is not None:
            L = A.comm.shard(L, A.split)
        return DNDarray(L, gshape, A.dtype, A.split, A.device, A.comm)

    def construct(self, X: DNDarray) -> DNDarray:
        """Similarity graph + Laplacian of the data (reference:
        laplacian.py:126)."""
        sanitize_in(X)
        S = self.similarity_metric(X)
        arr = S.larray
        # no self-loops
        arr = arr - jnp.diag(jnp.diagonal(arr))
        if self.mode == "eNeighbour":
            key, value = self.epsilon
            if key == "upper":
                mask = S.larray < value
            else:
                mask = S.larray > value
            mask = mask & ~jnp.eye(arr.shape[0], dtype=bool)
            arr = jnp.where(mask, arr if self.weighted else jnp.ones_like(arr), 0.0)
        A = DNDarray(
            S.comm.shard(arr, S.split) if S.split is not None else arr,
            S.shape,
            S.dtype,
            S.split,
            S.device,
            S.comm,
        )
        if self.definition == "simple":
            return self._simple_L(A)
        return self._normalized_symmetric_L(A)
