"""Bundled example datasets (reference: /root/reference/heat/datasets —
iris and diabetes shipped as HDF5/CSV for tests and examples). The files
here are materialized from the public scikit-learn distributions of the
same classic datasets (Fisher's iris, the sklearn diabetes study), not
copied from the reference repository.

Use with the io layer::

    import heat_tpu as ht
    from heat_tpu import datasets

    x = ht.load_hdf5(datasets.path("iris.h5"), "data", split=0)
"""

import os

_DIR = os.path.dirname(os.path.abspath(__file__))

__all__ = ["path"]


def path(name: str) -> str:
    """Absolute path of a bundled dataset file (iris.h5, iris.csv,
    iris_labels.csv, diabetes.h5)."""
    p = os.path.join(_DIR, name)
    if not os.path.exists(p):
        raise FileNotFoundError(f"no bundled dataset {name!r} in {_DIR}")
    return p
