"""Tests for indexing (nonzero/where), signal (convolve), io (hdf5/csv)."""

import os
import tempfile

import numpy as np

import heat_tpu as ht

from test_suites.basic_test import TestCase


class TestIndexing(TestCase):
    def test_nonzero(self):
        d = np.array([[0, 1, 0], [2, 0, 3]], dtype=np.float32)
        for split in (None, 0, 1):
            x = ht.array(d, split=split)
            got = ht.nonzero(x)
            expected = np.stack(np.nonzero(d), axis=1)
            np.testing.assert_array_equal(got.numpy(), expected)

    def test_where(self):
        d = np.random.randn(5, 6).astype(np.float32)
        for split in (None, 0, 1):
            x = ht.array(d, split=split)
            got = ht.where(x > 0, x, 0.0)
            np.testing.assert_allclose(got.numpy(), np.where(d > 0, d, 0.0))
            got2 = ht.where(x > 0, 1.0, -1.0)
            np.testing.assert_allclose(got2.numpy(), np.where(d > 0, 1.0, -1.0))
        with self.assertRaises(TypeError):
            ht.where(x > 0, x)


class TestSignal(TestCase):
    def test_convolve(self):
        sig = np.random.randn(50).astype(np.float32)
        ker = np.random.randn(5).astype(np.float32)
        for split in (None, 0):
            a = ht.array(sig, split=split)
            v = ht.array(ker)
            for mode in ("full", "same", "valid"):
                got = ht.convolve(a, v, mode=mode)
                np.testing.assert_allclose(got.numpy(), np.convolve(sig, ker, mode=mode), rtol=1e-4)

    def test_convolve_int(self):
        sig = np.arange(16)
        ker = [1, 1, 1]
        got = ht.convolve(ht.array(sig, split=0, dtype=ht.int32), ht.array(ker, dtype=ht.int32))
        np.testing.assert_array_equal(got.numpy(), np.convolve(sig, ker))

    def test_convolve_errors(self):
        with self.assertRaises(ValueError):
            ht.convolve(ht.ones((3, 3)), ht.ones(2))
        with self.assertRaises(ValueError):
            ht.convolve(ht.ones(10), ht.ones(4), mode="same")


class TestIO(TestCase):
    def test_hdf5_roundtrip(self):
        self.assertTrue(ht.supports_hdf5())
        d = np.random.randn(16, 8).astype(np.float32)
        with tempfile.TemporaryDirectory() as tmp:
            path = os.path.join(tmp, "data.h5")
            x = ht.array(d, split=0)
            ht.save(x, path, "data")
            for split in (None, 0, 1):
                y = ht.load(path, "data", split=split)
                self.assertEqual(y.split, split)
                np.testing.assert_allclose(y.numpy(), d, rtol=1e-6)
            y = ht.load_hdf5(path, "data", split=0)
            np.testing.assert_allclose(y.numpy(), d, rtol=1e-6)

    def test_csv_roundtrip(self):
        d = np.random.randn(10, 4).astype(np.float32)
        with tempfile.TemporaryDirectory() as tmp:
            path = os.path.join(tmp, "data.csv")
            ht.save_csv(ht.array(d, split=0), path, decimals=6)
            y = ht.load_csv(path, split=0)
            np.testing.assert_allclose(y.numpy(), d, rtol=1e-4, atol=1e-5)

    def test_load_unknown_extension(self):
        with self.assertRaises(ValueError):
            ht.load("file.xyz")

    def test_netcdf_gated(self):
        self.assertFalse(ht.supports_netcdf())


class TestBundledDatasets(TestCase):
    """heat_tpu.datasets — the analog of the reference's bundled
    heat/datasets files (iris/diabetes), materialized from the public
    scikit-learn distributions."""

    def test_iris_hdf5(self):
        from heat_tpu import datasets

        x = ht.load_hdf5(datasets.path("iris.h5"), "data", split=0)
        assert x.shape == (150, 4)
        # classic iris sanity: sepal lengths within [4.3, 7.9]
        col0 = x.numpy()[:, 0]
        assert col0.min() >= 4.2 and col0.max() <= 8.0

    def test_iris_csv_and_labels(self):
        from heat_tpu import datasets

        x = ht.load_csv(datasets.path("iris.csv"), sep=";", split=0)
        assert x.shape == (150, 4)
        y = ht.load_csv(datasets.path("iris_labels.csv"), sep=";", split=0)
        assert int(ht.max(y)) == 2

    def test_diabetes_hdf5(self):
        from heat_tpu import datasets

        x = ht.load_hdf5(datasets.path("diabetes.h5"), "x", split=0)
        assert x.shape == (442, 10)

    def test_missing_raises(self):
        from heat_tpu import datasets

        with self.assertRaises(FileNotFoundError):
            datasets.path("nope.h5")

    def test_estimator_on_iris(self):
        # the reference's test pattern: fit estimators on the bundled data
        from heat_tpu import datasets

        x = ht.load_hdf5(datasets.path("iris.h5"), "data", split=0)
        km = ht.cluster.KMeans(n_clusters=3, init="kmeans++", random_state=0).fit(x)
        assert km.cluster_centers_.shape == (3, 4)
        assert km.labels_.shape == (150,)


if __name__ == "__main__":
    import unittest

    unittest.main()
