"""Self-calibrating cost lattice (ISSUE 16).

The contract pinned here, five ways:

1. **Envelope lifecycle** — build/save/load round-trips; a missing file
   is a miss; a corrupt, tampered, or version-mismatched file is
   counted, EVICTED, and priced as the constants — ``load_profile``
   never raises and a bad profile never takes the library down.
2. **Byte identity unset** — with ``HEAT_TPU_LATTICE_PROFILE`` unset
   (or empty, or pointing at a profile that fails its checks), every
   golden plan form is byte-identical to the constants era: same
   canonical_json, same plan_id, no ``calibration`` key. A profile
   sitting on disk but not activated changes nothing.
3. **Visible invalidation** — two different profiles stamp two
   different plan_ids (and both differ from the constants plan); the
   SAME profile replans deterministically; the stamped annotation
   carries the full resolved price map and ``verify_plan`` accepts it.
4. **Mutation classes** — ``verify_plan`` names ``calibration`` when
   the stamp drops its profile_id, prices an unknown edge, records a
   non-positive price, or disagrees with the topology's dcn_penalty.
5. **Loop closure** — probes measure this container; span/attribution
   ingestion folds real windows into prices; ``calibration_report``
   proves the calibrated column's mean |model_error| lands at or below
   the constants column on spans generated at the measured bandwidth.

Satellites: the ``heat_tpu_flight_dropped_total`` counter and the
per-leg ``model_error``/``calibrated_error`` gauges in
``prometheus_text``.
"""

import copy
import importlib
import json
import os
import tempfile

import pytest

import jax

from heat_tpu.analysis.planverify import verify_plan
from heat_tpu.core import tiers
from heat_tpu.observability import calibration, telemetry, tracing
from heat_tpu.redistribution import planner, staging

from test_suites.basic_test import TestCase, env_pin

attribution_mod = importlib.import_module("heat_tpu.observability.attribution")

P = len(jax.devices())
BUDGET = planner.DEFAULT_BUDGET_MB << 20
GATE = "HEAT_TPU_LATTICE_PROFILE"

#: tiny probe knobs — the suite must stay CPU-CI fast
NB, REP = 1 << 16, 2


def _mk_profile(tmpdir, name="p.json", edges=None, **kw):
    prof = calibration.build_profile(
        edges or {"dcn": {"bps": 50e9, "method": "test"}},
        platform=kw.pop("platform", "cpu"),
        topology=kw.pop("topology", "flat"),
    )
    path = os.path.join(tmpdir, name)
    calibration.save_profile(prof, path)
    return prof, path


class CalibrationCase(TestCase):
    """Every test starts under the constants and restores them: the
    gate is unset, the one-entry profile cache dropped, the planner's
    schedule cache cleared (plans built under a profile must not leak
    into a constants test)."""

    def setUp(self):
        os.environ.pop(GATE, None)
        tiers.reload_profile()
        planner.clear_plan_cache()
        calibration.reset_stats()

    tearDown = setUp


# --------------------------------------------------------------------- #
# 1. envelope lifecycle                                                 #
# --------------------------------------------------------------------- #
class TestProfileEnvelope(CalibrationCase):
    def test_roundtrip(self):
        with tempfile.TemporaryDirectory() as d:
            prof, path = _mk_profile(
                d, edges={
                    "dcn": {"bps": 50e9, "method": "test",
                            "samples": [48e9, 50e9]},
                    "pcie": {"bps": 12e9, "method": "test"},
                },
            )
            got = calibration.load_profile(path)
            self.assertEqual(got, prof)
            self.assertEqual(calibration.stats()["hit"], 1)
            # the stamp is over the measurement content
            self.assertEqual(
                prof["profile_id"],
                calibration.profile_digest("cpu", "flat", prof["edges"]),
            )

    def test_version_stamp_outside_digest(self):
        """Re-releasing heat_tpu must not re-key every plan: the
        library version rides in the envelope but not the digest."""
        with tempfile.TemporaryDirectory() as d:
            prof, path = _mk_profile(d)
            doc = json.load(open(path))
            doc["heat_tpu"] = "0.0.0-other"
            json.dump(doc, open(path, "w"))
            got = calibration.load_profile(path)
            self.assertIsNotNone(got)
            self.assertEqual(got["profile_id"], prof["profile_id"])

    def test_missing_is_miss(self):
        self.assertIsNone(calibration.load_profile("/nonexistent/p.json"))
        self.assertEqual(calibration.stats()["miss"], 1)

    def _expect_evicted(self, path, outcome):
        self.assertIsNone(calibration.load_profile(path))
        self.assertEqual(calibration.stats()[outcome], 1, calibration.stats())
        self.assertFalse(os.path.exists(path))

    def test_corrupt_evicts(self):
        with tempfile.TemporaryDirectory() as d:
            path = os.path.join(d, "p.json")
            with open(path, "w") as f:
                f.write("{not json")
            self._expect_evicted(path, "corrupt")

    def test_unknown_edge_is_corrupt(self):
        with tempfile.TemporaryDirectory() as d:
            _, path = _mk_profile(d)
            doc = json.load(open(path))
            doc["edges"]["warp"] = {"bps": 1e9}
            json.dump(doc, open(path, "w"))
            self._expect_evicted(path, "corrupt")

    def test_nonpositive_price_is_corrupt(self):
        with tempfile.TemporaryDirectory() as d:
            _, path = _mk_profile(d)
            doc = json.load(open(path))
            doc["edges"]["dcn"]["bps"] = 0.0
            json.dump(doc, open(path, "w"))
            self._expect_evicted(path, "corrupt")

    def test_tampered_evicts(self):
        with tempfile.TemporaryDirectory() as d:
            _, path = _mk_profile(d)
            doc = json.load(open(path))
            doc["edges"]["dcn"]["bps"] = 999e9  # edited price, stale stamp
            json.dump(doc, open(path, "w"))
            self._expect_evicted(path, "tampered")

    def test_version_mismatch_evicts(self):
        with tempfile.TemporaryDirectory() as d:
            _, path = _mk_profile(d)
            doc = json.load(open(path))
            doc["format"] = calibration._FORMAT + 1
            json.dump(doc, open(path, "w"))
            self._expect_evicted(path, "version_mismatch")

    def test_build_profile_validates(self):
        with pytest.raises(ValueError):
            calibration.build_profile({"warp": {"bps": 1e9}})
        with pytest.raises(ValueError):
            calibration.build_profile({"dcn": {"bps": -1.0}})


# --------------------------------------------------------------------- #
# 2. byte identity under the constants                                  #
# --------------------------------------------------------------------- #
class TestUnsetByteIdentity(CalibrationCase):
    def _golden_forms(self):
        forms = {}
        for topo in ("flat", "2x4"):
            for q in ("0", "int8"):
                for name, spec in planner.golden_specs():
                    sched = planner.plan(spec, BUDGET, quant=q, topology=topo)
                    forms[f"{name}@{topo}/q{q}"] = sched.canonical_json()
        for name, sched in staging.golden_staged_plans():
            forms[f"{name}@staged"] = sched.canonical_json()
        return forms

    def test_unset_empty_and_inactive_profile_are_identical(self):
        baseline = self._golden_forms()
        self.assertTrue(all('"calibration"' not in b for b in baseline.values()))
        with env_pin(GATE, ""):
            tiers.reload_profile()
            planner.clear_plan_cache()
            self.assertEqual(self._golden_forms(), baseline)
        with tempfile.TemporaryDirectory() as d:
            _mk_profile(d)  # on disk, NOT activated
            tiers.reload_profile()
            planner.clear_plan_cache()
            self.assertEqual(self._golden_forms(), baseline)
        self.assertEqual(tiers.active_profile(), None)
        self.assertEqual(tiers.profile_annotation(), None)

    def test_unset_pricing_is_the_constants(self):
        for edge, (_, _, bps) in tiers.EDGES.items():
            self.assertEqual(tiers.bandwidth(edge), bps)
        self.assertEqual(tiers.penalty("dcn"), int(tiers.ICI_BPS / tiers.DCN_BPS))

    def test_failed_profile_prices_as_constants(self):
        """A tampered activated profile degrades to the constants —
        same plan bytes as unset, file evicted, never an error."""
        spec = dict(planner.golden_specs())["resplit_chunked_2gb_p8"]
        s0 = planner.plan(spec, BUDGET, quant="0", topology="2x4")
        with tempfile.TemporaryDirectory() as d:
            _, path = _mk_profile(d)
            doc = json.load(open(path))
            doc["edges"]["dcn"]["bps"] = 999e9
            json.dump(doc, open(path, "w"))
            with env_pin(GATE, path):
                tiers.reload_profile()
                planner.clear_plan_cache()
                s1 = planner.plan(spec, BUDGET, quant="0", topology="2x4")
                self.assertEqual(s1.canonical_json(), s0.canonical_json())
                self.assertIsNone(s1.calibration)
                self.assertFalse(os.path.exists(path))


# --------------------------------------------------------------------- #
# 3. visible invalidation                                               #
# --------------------------------------------------------------------- #
class TestPlanInvalidation(CalibrationCase):
    def _spec(self):
        return dict(planner.golden_specs())["resplit_chunked_2gb_p8"]

    def test_two_profiles_two_plan_ids(self):
        spec = self._spec()
        s0 = planner.plan(spec, BUDGET, quant="0", topology="2x4")
        with tempfile.TemporaryDirectory() as d:
            p1, f1 = _mk_profile(
                d, "p1.json",
                edges={"dcn": {"bps": 50e9, "method": "t"},
                       "pcie": {"bps": 8e9, "method": "t"}},
            )
            p2, f2 = _mk_profile(
                d, "p2.json", edges={"dcn": {"bps": 12.5e9, "method": "t"}},
            )
            with env_pin(GATE, f1):
                tiers.reload_profile()
                planner.clear_plan_cache()
                s1 = planner.plan(spec, BUDGET, quant="0", topology="2x4")
                self.assertEqual(s1.calibration["profile_id"], p1["profile_id"])
                # the annotation records the FULL resolved price map
                self.assertEqual(
                    sorted(s1.calibration["edges"]), sorted(tiers.EDGES)
                )
                self.assertEqual(s1.calibration["edges"]["dcn"], 50e9)
                self.assertEqual(s1.calibration["edges"]["hbm"], tiers.HBM_BPS)
                # measured prices re-derive the topology penalty
                self.assertEqual(s1.topology["dcn_penalty"], 4)
                res = verify_plan(s1, topology="2x4")
                self.assertTrue(res["ok"], res)
                self.assertIn("calibration", res["checks"])
            with env_pin(GATE, f2):
                tiers.reload_profile()
                planner.clear_plan_cache()
                s2 = planner.plan(spec, BUDGET, quant="0", topology="2x4")
                self.assertEqual(s2.calibration["profile_id"], p2["profile_id"])
                self.assertEqual(s2.topology["dcn_penalty"], 16)
                # recalibration is a visible invalidation: three ids
                self.assertEqual(
                    len({s0.plan_id, s1.plan_id, s2.plan_id}), 3
                )
                # the same profile replans deterministically
                planner.clear_plan_cache()
                s2b = planner.plan(spec, BUDGET, quant="0", topology="2x4")
                self.assertEqual(s2b.canonical_json(), s2.canonical_json())
        # constants restored: the replan matches the original bytes
        tiers.reload_profile()
        planner.clear_plan_cache()
        s3 = planner.plan(spec, BUDGET, quant="0", topology="2x4")
        self.assertEqual(s3.canonical_json(), s0.canonical_json())

    def test_staged_plan_stamped_and_verifies(self):
        with tempfile.TemporaryDirectory() as d:
            prof, path = _mk_profile(
                d, edges={"pcie": {"bps": 4e9, "method": "t"}},
            )
            st0 = staging.plan_staged_passes(
                (4096, 4096), "float32", [{"tag": "sketch", "axis": 1}],
                slab=64 << 20, hbm_bytes=16 << 30,
            )
            with env_pin(GATE, path):
                tiers.reload_profile()
                st1 = staging.plan_staged_passes(
                    (4096, 4096), "float32", [{"tag": "sketch", "axis": 1}],
                    slab=64 << 20, hbm_bytes=16 << 30,
                )
            self.assertIsNone(st0.calibration)
            self.assertEqual(st1.calibration["profile_id"], prof["profile_id"])
            self.assertNotEqual(st0.plan_id, st1.plan_id)
            # the staging model was re-priced at the measured edge
            self.assertGreater(
                st1.staging["model"]["pcie_s"], st0.staging["model"]["pcie_s"]
            )
            self.assertTrue(verify_plan(st1)["ok"])

    def test_serialization_roundtrip_keeps_stamp(self):
        with tempfile.TemporaryDirectory() as d:
            _, path = _mk_profile(d)
            with env_pin(GATE, path):
                tiers.reload_profile()
                planner.clear_plan_cache()
                s1 = planner.plan(self._spec(), BUDGET, topology="2x4")
            d1 = json.loads(s1.canonical_json())
            self.assertEqual(d1["calibration"], s1.calibration)
            # verify accepts the dict form too
            self.assertTrue(verify_plan(d1, topology="2x4")["ok"])


# --------------------------------------------------------------------- #
# 4. verify_plan mutation classes                                       #
# --------------------------------------------------------------------- #
class TestVerifyMutations(CalibrationCase):
    def _calibrated_dict(self):
        spec = dict(planner.golden_specs())["resplit_chunked_2gb_p8"]
        with tempfile.TemporaryDirectory() as d:
            _, path = _mk_profile(
                d, edges={"dcn": {"bps": 50e9, "method": "t"}},
            )
            with env_pin(GATE, path):
                tiers.reload_profile()
                planner.clear_plan_cache()
                sched = planner.plan(spec, BUDGET, topology="2x4")
                return json.loads(sched.canonical_json())

    def _expect_calibration_violation(self, mutate):
        m = copy.deepcopy(self._calibrated_dict())
        mutate(m)
        res = verify_plan(m, raise_on_violation=False)
        self.assertFalse(res["ok"])
        self.assertIn(
            "calibration", [v["invariant"] for v in res["violations"]], res
        )

    def test_dropped_profile_id(self):
        self._expect_calibration_violation(
            lambda m: m["calibration"].pop("profile_id")
        )

    def test_no_edge_prices(self):
        self._expect_calibration_violation(
            lambda m: m["calibration"].update(edges={})
        )

    def test_unknown_edge(self):
        self._expect_calibration_violation(
            lambda m: m["calibration"]["edges"].update(warp=1e9)
        )

    def test_nonpositive_price(self):
        self._expect_calibration_violation(
            lambda m: m["calibration"]["edges"].update(dcn=0.0)
        )

    def test_penalty_profile_mismatch(self):
        # plan priced under one profile, stamped with another: the
        # topology's dcn_penalty no longer matches the recorded ratio
        self._expect_calibration_violation(
            lambda m: m["calibration"]["edges"].update(dcn=1e9)
        )


# --------------------------------------------------------------------- #
# 5. probes, ingestion, loop closure                                    #
# --------------------------------------------------------------------- #
class TestProbesAndIngestion(CalibrationCase):
    def test_probe_suite_on_this_container(self):
        out = calibration.run_probes(nbytes=NB, repeats=REP)
        for edge in ("hbm", "pcie", "disk"):
            self.assertIn(edge, out)
            self.assertGreater(out[edge]["bps"], 0)
            self.assertTrue(out[edge]["method"].startswith("probe:"))
            self.assertEqual(len(out[edge]["samples"]), REP)
        for edge in out:
            self.assertIn(edge, tiers.EDGES)

    @pytest.mark.skipif(P < 2, reason="needs a multi-device mesh")
    def test_collective_probe_ici(self):
        rec = calibration.probe_collective("ici", nbytes=NB, repeats=REP)
        self.assertIsNotNone(rec)
        self.assertGreater(rec["bps"], 0)
        self.assertIn("all_gather", rec["method"])

    def test_collective_probe_rejects_memory_edges(self):
        with pytest.raises(ValueError):
            calibration.probe_collective("hbm")

    def test_dcn_probe_none_on_flat(self):
        with env_pin("HEAT_TPU_TOPOLOGY", None):
            self.assertIsNone(
                calibration.probe_collective("dcn", nbytes=NB, repeats=REP)
            )

    def test_floor_retry_suspect(self):
        seq = iter([(100, 1.0), (100, 0.01), (100, 1.0)])
        rec = calibration._floor_retry(lambda: next(seq), 3)
        self.assertEqual(rec["bps"], 100 / 0.01)
        self.assertTrue(rec["measurement_suspect"])

    def test_ingest_spans(self):
        rows = [
            {"name": "staging.stage_in", "dur_s": 0.5,
             "attrs": {"tier": "pcie", "bytes": 5 << 30}},
            {"name": "staging.stage_in", "dur_s": 1.0,
             "attrs": {"tier": "pcie", "bytes": 5 << 30, "traced": True}},
            {"name": "staging.compute", "dur_s": 0.5, "attrs": {}},
        ]
        samples = calibration.ingest_spans(rows)
        self.assertEqual(sorted(samples), ["pcie"])
        self.assertEqual(samples["pcie"], [(5 << 30) / 0.5])

    def test_ingest_attribution(self):
        rep = {
            "model": {"dcn_bytes": 4 << 30},
            "legs": [
                {"tier": "dcn", "measured_s": 2.0},
                {"tier": None, "measured_s": 1.0},
            ],
        }
        samples = calibration.ingest_attribution([rep])
        self.assertEqual(samples, {"dcn": [(4 << 30) / 2.0]})

    def test_calibrate_end_to_end(self):
        rows = [
            {"name": "staging.stage_in", "dur_s": 1.0,
             "attrs": {"tier": "dcn", "bytes": 30 << 30}},
        ]
        with tempfile.TemporaryDirectory() as d:
            path = os.path.join(d, "prof.json")
            prof = calibration.calibrate(
                path=path, nbytes=NB, repeats=REP, span_rows=rows,
                platform="cpu", topology="flat",
            )
            # probed edges AND the span-only dcn edge are in the envelope
            self.assertIn("hbm", prof["edges"])
            self.assertEqual(prof["edges"]["dcn"]["method"], "spans")
            got = calibration.load_profile(path)
            self.assertEqual(got, prof)
            self.assertIn(
                f"lattice profile {prof['profile_id']}",
                calibration.describe_profile(prof),
            )

    def test_calibration_report_shrinks_model_error(self):
        """Spans generated at a measured bandwidth: the calibrated
        column must judge them (near-)perfectly while the constants
        column is off by the constants/measured ratio."""
        spec = dict(planner.golden_specs())["resplit_chunked_2gb_p8"]
        sched = planner.plan(spec, BUDGET, topology="2x4")
        true_bps = {e: bps / 3.0 for e, (_, _, bps) in tiers.EDGES.items()}
        prof = calibration.build_profile(
            {e: {"bps": b, "method": "t"} for e, b in true_bps.items()},
            platform="cpu", topology="2x4",
        )
        model = planner.tier_time_model(sched)
        rows = []
        for tier in ("ici", "dcn"):
            nb = model.get(f"{tier}_bytes")
            if nb:
                rows.append({
                    "name": f"redist.{tier}", "dur_s": nb / true_bps[tier],
                    "attrs": {"plan_id": sched.plan_id, "tier": tier,
                              "step": "exchange"},
                })
        self.assertTrue(rows)
        rep = calibration.calibration_report(sched, span_rows=rows, profile=prof)
        self.assertEqual(rep["profile_id"], prof["profile_id"])
        self.assertGreater(rep["n_legs"], 0)
        self.assertTrue(rep["improved"], rep)
        self.assertLess(
            rep["mean_abs_error_calibrated"], rep["mean_abs_error_constants"]
        )
        for leg in rep["legs"]:
            self.assertAlmostEqual(leg["calibrated_error"], 0.0, places=3)

    def test_attribution_constants_column_untouched_by_profile(self):
        """The baseline model column must not drift when a profile is
        passed — it is bench_compare's unchanged-field."""
        spec = dict(planner.golden_specs())["resplit_chunked_2gb_p8"]
        sched = planner.plan(spec, BUDGET, topology="2x4")
        rows = [{
            "name": "redist.exchange", "dur_s": 0.25,
            "attrs": {"plan_id": sched.plan_id, "tier": "dcn",
                      "step": "exchange"},
        }]
        base = attribution_mod.attribution(sched, span_rows=rows)
        prof = calibration.build_profile(
            {"dcn": {"bps": 1e9, "method": "t"}}, platform="cpu",
            topology="flat",
        )
        cal = attribution_mod.attribution(sched, span_rows=rows, profile=prof)
        self.assertNotIn("calibrated", base["model"])
        self.assertEqual(cal["model"]["calibrated"]["profile_id"],
                         prof["profile_id"])
        for b, c in zip(base["legs"], cal["legs"]):
            self.assertEqual(b.get("model_s"), c.get("model_s"))
            self.assertEqual(b.get("model_error"), c.get("model_error"))
        self.assertTrue(
            any("calibrated_error" in l for l in cal["legs"])
        )


# --------------------------------------------------------------------- #
# satellites: exposition                                                #
# --------------------------------------------------------------------- #
class TestExposition(CalibrationCase):
    def test_flight_dropped_counter_exported(self):
        before = tracing.flight_dropped()
        for i in range(tracing.flight_capacity() + 5):
            tracing.flight_record("test.fill", "x", i)
        self.assertGreaterEqual(tracing.flight_dropped(), before + 5)
        text = telemetry.prometheus_text()
        self.assertIn("heat_tpu_flight_dropped_total", text)

    def test_model_error_gauges(self):
        spec = dict(planner.golden_specs())["resplit_chunked_2gb_p8"]
        sched = planner.plan(spec, BUDGET, topology="2x4")
        rows = [{
            "name": "redist.exchange", "dur_s": 0.25,
            "attrs": {"plan_id": sched.plan_id, "tier": "dcn",
                      "step": "exchange"},
        }]
        prof = calibration.build_profile(
            {"dcn": {"bps": 1e9, "method": "t"}}, platform="cpu",
            topology="flat",
        )
        attribution_mod.attribution(sched, span_rows=rows, profile=prof)
        text = telemetry.prometheus_text()
        self.assertIn("heat_tpu_attribution_model_error", text)
        self.assertIn(f'plan_id="{sched.plan_id}"', text)
        self.assertIn("heat_tpu_attribution_calibrated_error", text)
