"""heat_tpu.kernels.sort — the TPU-native local radix/columnsort engines
(ISSUE 4 tentpole).

Four pins:

1. the key transform is a monotone bijection matching ``lax.sort``'s
   comparator order exactly (±0, ±inf, NaN payloads, subnormals, the
   full i32 range);
2. every kernel engine (XLA radix, Pallas block kernel in interpret
   mode, blocked columnsort) is STABLE-ARGSORT-IDENTICAL to the
   ``lax.sort`` oracle on adversarial inputs;
3. the distributed sort's collective census is UNTOUCHED by the kernel
   wiring (kernel-on HLO == kernel-off HLO collective-for-collective,
   zero all-gathers) and its numerics are bit-identical — the kernel
   only replaced local compute;
4. the ``HEAT_TPU_SORT_KERNEL`` escape hatch and the
   ``sort.kernel.{hit,fallback}`` telemetry counters behave.
"""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import heat_tpu as ht
from heat_tpu.kernels import sort as ksort

P = len(jax.devices())


@pytest.fixture
def kernel_mode(monkeypatch):
    def _set(mode):
        monkeypatch.setenv("HEAT_TPU_SORT_KERNEL", mode)

    return _set


def _adversarial(kind: str, n: int, dtype=np.float32) -> np.ndarray:
    rng = np.random.default_rng({"random": 0, "sorted": 1, "reverse": 2,
                                 "const": 3, "fewuniq": 4, "nan": 5}[kind])
    if np.issubdtype(dtype, np.floating):
        x = rng.standard_normal(n).astype(dtype)
    else:
        x = rng.integers(np.iinfo(dtype).min, np.iinfo(dtype).max, n, dtype=dtype)
    if kind == "sorted":
        x = np.sort(x)
    elif kind == "reverse":
        x = np.sort(x)[::-1].copy()
    elif kind == "const":
        x = np.full(n, x.flat[0])
    elif kind == "fewuniq":
        x = x[rng.integers(0, 7, n)]
    elif kind == "nan":
        x[rng.random(n) < 0.15] = np.nan
    return x


def _oracle(x: jnp.ndarray):
    iota = jnp.arange(x.shape[0], dtype=jnp.int32)
    return jax.lax.sort((x, iota), num_keys=1, is_stable=True)


def _assert_sorted_equal(got_v, got_i, ref_v, ref_i, dtype):
    """Indices must match the oracle EXACTLY (the argsort contract);
    values must match under the comparator's equality (bit-equal except
    NaN slots, where the kernel paths canonicalize the payload)."""
    np.testing.assert_array_equal(np.asarray(got_i), np.asarray(ref_i))
    gv, rv = np.asarray(got_v), np.asarray(ref_v)
    if np.issubdtype(np.dtype(dtype), np.floating):
        np.testing.assert_array_equal(np.isnan(gv), np.isnan(rv))
        m = ~np.isnan(rv)
        np.testing.assert_array_equal(gv[m], rv[m])
    else:
        np.testing.assert_array_equal(gv, rv)


class TestKeyTransform:
    """Property tests for the to_sortable/from_sortable bijection."""

    F32_SPECIALS = np.array(
        [
            0x00000000, 0x80000000,              # +0, -0
            0x7F800000, 0xFF800000,              # +inf, -inf
            0x7FC00000, 0xFFC00000,              # quiet NaN, -NaN
            0x7F800001, 0x7FFFFFFF, 0xFFFFFFFF,  # NaN payload extremes
            0x00000001, 0x007FFFFF,              # subnormal min/max
            0x00800000,                          # smallest normal
            0x7F7FFFFF, 0xFF7FFFFF,              # +-float32 max
            0x3F800000, 0xBF800000,              # +-1.0
        ],
        dtype=np.uint32,
    )

    def test_f32_roundtrip_and_tie_classes(self):
        x = jax.lax.bitcast_convert_type(jnp.asarray(self.F32_SPECIALS), jnp.float32)
        u = ksort.to_sortable(x)
        back = np.asarray(
            jax.lax.bitcast_convert_type(ksort.from_sortable(u, jnp.float32), jnp.uint32)
        )
        for pat, got in zip(self.F32_SPECIALS, back):
            if (pat & 0x7FFFFFFF) > 0x7F800000:   # NaN class: stays NaN
                assert (got & 0x7FFFFFFF) > 0x7F800000
            elif pat == 0x80000000:               # -0 canonicalizes to +0
                assert got == 0x00000000
            else:                                  # everything else: bit-exact
                assert got == pat

    def test_f32_order_matches_lax_comparator(self):
        rng = np.random.default_rng(0)
        x = np.concatenate(
            [
                self.F32_SPECIALS.view(np.float32),
                rng.standard_normal(500).astype(np.float32),
            ]
        )
        xj = jnp.asarray(x)
        _, oracle_idx = _oracle(xj)
        u = np.asarray(ksort.to_sortable(xj))
        np.testing.assert_array_equal(np.argsort(u, kind="stable"), np.asarray(oracle_idx))

    def test_subnormal_order_is_strict_refinement(self):
        """XLA's comparator runs on FTZ hardware and TIES every subnormal
        with zero; the transform keeps the strict IEEE magnitude order —
        a refinement: any transform-ordered array is still sorted under
        XLA's comparator, and values round-trip bit-exact."""
        rng = np.random.default_rng(2)
        x = (rng.standard_normal(200) * 1e-42).astype(np.float32)
        x[::17] = 0.0
        x[1::17] = -0.0
        xj = jnp.asarray(x)
        u = ksort.to_sortable(xj)
        back = np.asarray(
            jax.lax.bitcast_convert_type(ksort.from_sortable(u, jnp.float32), jnp.uint32)
        )
        keep = x.view(np.uint32) != 0x80000000  # -0 canonicalizes
        np.testing.assert_array_equal(back[keep], x.view(np.uint32)[keep])
        # strict numeric order (upcast to f64 where subnormals are exact)
        order = np.argsort(np.asarray(u), kind="stable")
        np.testing.assert_array_equal(
            order, np.argsort(x.astype(np.float64), kind="stable")
        )

    @pytest.mark.parametrize("dtype", [np.int32, np.int8, np.int16, np.uint32, np.uint8])
    def test_int_bijection_and_order(self, dtype):
        info = np.iinfo(dtype)
        rng = np.random.default_rng(1)
        x = np.concatenate(
            [
                np.array([info.min, info.min + 1, -1 if info.min < 0 else 0, 0, 1, info.max - 1, info.max], dtype=dtype),
                rng.integers(info.min, info.max, 300, dtype=dtype, endpoint=True),
            ]
        )
        xj = jnp.asarray(x)
        u = ksort.to_sortable(xj)
        np.testing.assert_array_equal(np.asarray(ksort.from_sortable(u, dtype)), x)
        np.testing.assert_array_equal(
            np.argsort(np.asarray(u), kind="stable"), np.argsort(x, kind="stable")
        )

    def test_unsupported_dtype_not_transformable(self):
        assert not ksort.transformable(jnp.complex64)


ENGINE_KINDS = ["random", "sorted", "reverse", "const", "fewuniq", "nan"]


class TestEngineParity:
    """Stable-argsort parity of every engine vs the lax.sort oracle."""

    @pytest.mark.parametrize("kind", ENGINE_KINDS)
    def test_radix_xla(self, kind):
        x = jnp.asarray(_adversarial(kind, 999))
        u = ksort.to_sortable(x)
        idx = jnp.arange(999, dtype=jnp.int32)
        su, si = ksort._radix_sort_xla((0, 1), (u, idx), (4, 4))
        ov, oi = _oracle(x)
        _assert_sorted_equal(ksort.from_sortable(su, x.dtype), si, ov, oi, np.float32)

    @pytest.mark.parametrize("kind", ENGINE_KINDS)
    def test_pallas_block_interpret(self, kind):
        """The Pallas kernel logic on CPU via interpret=True — histogram,
        triangular-matmul scan, rank and one-hot permutation matmul are
        the exact ops the TPU lowering runs."""
        n = 509  # non-multiple of the 512 block: exercises sentinel padding
        x = jnp.asarray(_adversarial(kind, n))
        u = ksort.to_sortable(x)
        su, si = ksort._pallas_pair_sort(u, jnp.arange(n, dtype=jnp.uint32))
        ov, oi = _oracle(x)
        _assert_sorted_equal(
            ksort.from_sortable(su, x.dtype), si.astype(jnp.int32), ov, oi, np.float32
        )

    @pytest.mark.parametrize("kind", ENGINE_KINDS)
    @pytest.mark.parametrize("n", [1600, 1601, 6500])
    def test_columnsort_local(self, kind, n):
        x = jnp.asarray(_adversarial(kind, n))
        u = ksort.to_sortable(x)
        idx = jnp.arange(n, dtype=jnp.int32)
        p, b = ksort._columnsort_p(n)
        assert p is not None and b % p == 0 and b >= 2 * (p - 1) ** 2
        su, si = ksort._columnsort_local((u, idx), 2, p, b, n)
        ov, oi = _oracle(x)
        _assert_sorted_equal(ksort.from_sortable(su, x.dtype), si, ov, oi, np.float32)

    def test_columnsort_scrambled_second_key(self):
        """The distributed programs sort (value, global-position) pairs
        whose positions are NOT presorted — the 2-key lexicographic
        contract must hold for arbitrary index operands."""
        rng = np.random.default_rng(7)
        n = 3200
        v = jnp.asarray(rng.integers(0, 5, n).astype(np.int32))
        i = jnp.asarray(rng.permutation(n).astype(np.int32))
        got = ksort.block_sort((v, i), 0, num_keys=2, impl="1")
        ref = jax.lax.sort((v, i), num_keys=2)
        for g, r in zip(got, ref):
            np.testing.assert_array_equal(np.asarray(g), np.asarray(r))

    @pytest.mark.parametrize("dtype", [np.int32, np.uint32])
    def test_engines_int_dtypes(self, dtype):
        x = jnp.asarray(_adversarial("random", 2100, dtype))
        idx = jnp.arange(2100, dtype=jnp.int32)
        got = ksort.block_sort((x, idx), 0, num_keys=2, impl="1")
        ref = jax.lax.sort((x, idx), num_keys=2)
        for g, r in zip(got, ref):
            np.testing.assert_array_equal(np.asarray(g), np.asarray(r))

    def test_values_only_block_sort(self):
        x = jnp.asarray(_adversarial("fewuniq", 3000))
        (got,) = ksort.block_sort((x,), 0, num_keys=1, impl="1")
        (ref,) = jax.lax.sort((x,), is_stable=True)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))

    @pytest.mark.parametrize("n", [300, 5000])
    def test_type_max_keys_survive_sentinel_padding(self, n):
        """Regression (code review): real (NaN/type-max key, index) pairs
        must sort BEFORE the engines' internal sentinel pads — the pad
        tuple is all-max, and a real index never reaches its type-max —
        so the [:n] truncation can only ever drop pads."""
        rng = np.random.default_rng(13)
        v = rng.standard_normal(n).astype(np.float32)
        v[-3:] = np.nan                      # ties the key with the pad sentinel
        gi = jnp.asarray(np.arange(n, dtype=np.int32) + 50_000)  # offset indices
        got = ksort.block_sort((jnp.asarray(v), gi), 0, num_keys=2, impl="1")
        ref = jax.lax.sort((jnp.asarray(v), gi), num_keys=2)
        np.testing.assert_array_equal(np.asarray(got[1]), np.asarray(ref[1]))
        np.testing.assert_array_equal(
            np.isnan(np.asarray(got[0])), np.isnan(np.asarray(ref[0]))
        )

    def test_pallas_pair_full_width_second_key(self):
        """Regression (code review): tied first keys whose second keys
        differ only ABOVE bit 15 (indices ≥ 65536) must still order by
        the full 32-bit second key on the Pallas block path."""
        n = 300
        v = jnp.zeros((n,), jnp.float32)
        gi = jnp.asarray((np.arange(n)[::-1] * 300 + 1).astype(np.int32))  # up to 89701
        got = ksort.block_sort((v, gi), 0, num_keys=2, impl="1")
        ref = jax.lax.sort((v, gi), num_keys=2)
        np.testing.assert_array_equal(np.asarray(got[1]), np.asarray(ref[1]))


class TestDispatch:
    """local_sort dispatcher: gates, escape hatch, descending one-pass,
    telemetry counters."""

    def test_escape_hatch_is_oracle_identical(self, kernel_mode):
        x = jnp.asarray(_adversarial("random", 4000))
        kernel_mode("0")
        v0, i0 = ksort.local_sort(x)
        ov, oi = _oracle(x)
        np.testing.assert_array_equal(np.asarray(v0), np.asarray(ov))
        np.testing.assert_array_equal(np.asarray(i0), np.asarray(oi))
        kernel_mode("1")
        v1, i1 = ksort.local_sort(x)
        _assert_sorted_equal(v1, i1, ov, oi, np.float32)

    @pytest.mark.parametrize("kind", ["random", "fewuniq", "const"])
    @pytest.mark.parametrize("mode", ["0", "1"])
    def test_descending_one_pass_stable(self, kind, mode, kernel_mode):
        """The descending satellite: one sort on the complemented
        transform must equal the old two-pass stable-descending argsort
        (ties in original order) — on both the oracle and kernel paths."""
        kernel_mode(mode)
        x = jnp.asarray(_adversarial(kind, 3000))
        v, i = ksort.local_sort(x, descending=True)
        ref_i = jnp.argsort(x, descending=True, stable=True)
        np.testing.assert_array_equal(np.asarray(i), np.asarray(ref_i))
        np.testing.assert_array_equal(
            np.asarray(v), np.asarray(jnp.take_along_axis(x, ref_i, axis=0))
        )

    def test_escape_hatch_descending_preserves_value_bits(self, kernel_mode):
        """Regression (code review): HEAT_TPU_SORT_KERNEL=0 must restore
        the PRE-kernel two-pass descending route byte-identically —
        including -0.0's sign bit, which the transform-based one-pass
        canonicalizes."""
        kernel_mode("0")
        x = np.array([-0.0, 1.0, 0.0, -1.0], dtype=np.float32)
        v, _ = ksort.local_sort(jnp.asarray(x), descending=True)
        ref_i = np.asarray(jnp.argsort(jnp.asarray(x), descending=True, stable=True))
        np.testing.assert_array_equal(
            np.asarray(v).view(np.uint32), x[ref_i].view(np.uint32)
        )

    def test_ht_sort_descending_ties_match_two_pass(self, kernel_mode):
        kernel_mode("0")
        x = np.array([3.0, 1.0, 3.0, 2.0, 1.0, 3.0], dtype=np.float32)
        v, i = ht.sort(ht.array(x), descending=True)
        ref = np.argsort(-x, kind="stable")
        np.testing.assert_array_equal(i.numpy(), ref)
        np.testing.assert_array_equal(v.numpy(), x[ref])

    def test_multidim_descending(self, kernel_mode):
        kernel_mode("0")
        x = jnp.asarray(np.random.default_rng(3).integers(0, 4, (8, 16)).astype(np.int32))
        v, i = ksort.local_sort(x, axis=1, descending=True)
        ref_i = jnp.argsort(x, axis=1, descending=True, stable=True)
        np.testing.assert_array_equal(np.asarray(i), np.asarray(ref_i))

    def test_telemetry_counters(self, kernel_mode):
        ht.telemetry.enable()
        try:
            ht.telemetry.reset()
            x = jnp.asarray(_adversarial("random", 1000))
            kernel_mode("1")
            ksort.local_sort(x)
            kernel_mode("0")
            ksort.local_sort(x)
            counters = ht.telemetry.snapshot()["counters"]
            assert counters.get("sort.kernel.hit", 0) >= 1
            assert counters.get("sort.kernel.fallback", 0) >= 1
        finally:
            ht.telemetry.disable()
            ht.telemetry.reset()

    def test_forced_decision_does_not_poison_autotune(self, kernel_mode, monkeypatch):
        """Regression (code review): a path cached by a FORCED kernel
        call carries no timing evidence — auto mode must not reuse it
        (only entries the autotuner wrote may answer for auto)."""
        kernel_mode("auto")
        monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
        n = 1 << 22
        key = (n, "float32", "pairs")
        try:
            ksort._DECISIONS[key] = {"path": "columnsort", "forced": True}
            # tracing context (concrete=False): no autotune possible, and
            # the forced entry must be ignored -> the oracle serves
            assert ksort._decide(n, "float32", concrete=False) == "lax"
            ksort._DECISIONS[key] = {"path": "columnsort", "autotuned": True,
                                     "timings": {}}
            assert ksort._decide(n, "float32", concrete=False) == "columnsort"
        finally:
            ksort._DECISIONS.pop(key, None)

    def test_sort_plan_models(self):
        lax_plan = ksort.sort_plan(1 << 27, "float32", path="lax")
        col_plan = ksort.sort_plan(1 << 27, "float32", path="columnsort")
        radix_plan = ksort.sort_plan(400, "float32", path="radix_xla")
        assert lax_plan["passes"] > col_plan["passes"] > radix_plan["passes"]
        for plan in (lax_plan, col_plan, radix_plan):
            assert plan["hbm_bytes"] > 0 and plan["model"]

    def test_pallas_gate_is_shape_level(self):
        assert ksort.pallas_serviceable(512)
        assert not ksort.pallas_serviceable(513)


@pytest.mark.skipif(P < 2, reason="needs a real mesh")
class TestDistributedCensusPin:
    """ISSUE 4 acceptance: the distributed sort's collective census is
    UNCHANGED by the kernel wiring — columnsort keeps its 2 all-to-alls
    + 2 half-shard ppermutes per operand, odd-even its p rounds, and
    ZERO all-gathers appear — and the executed numerics are identical,
    proving the kernel only touched local compute."""

    def _census(self, n, mode, monkeypatch):
        monkeypatch.setenv("HEAT_TPU_SORT_KERNEL", mode)
        x = ht.random.randn(n, split=0)
        rep = ht.observability.collective_counts(lambda v: ht.sort(v)[0], x)
        return {
            op: rep.counts[op]
            for op in ("all-gather", "all-to-all", "collective-permute")
        }

    def test_columnsort_census_kernel_on_equals_off(self, monkeypatch):
        n = 4 * P * P * max(2 * (P - 1) ** 2, P)  # large-shard: columnsort route
        off = self._census(n, "0", monkeypatch)
        on = self._census(n, "1", monkeypatch)
        assert off == on
        assert off["all-gather"] == 0
        assert off["all-to-all"] >= 2  # the two deal exchanges

    def test_oddeven_census_kernel_on_equals_off(self, monkeypatch):
        n = 3 * P  # tiny shards: odd-even route
        off = self._census(n, "0", monkeypatch)
        on = self._census(n, "1", monkeypatch)
        assert off == on
        assert off["all-gather"] == 0
        assert off["all-to-all"] == 0  # odd-even is ppermute-only

    @pytest.mark.parametrize("n_extra", [0, 3])
    def test_distributed_numerics_kernel_on_equals_off(self, n_extra, monkeypatch):
        """Bit-identical (values, indices) with the kernel on vs off —
        including non-divisible extents (NaN pad sentinels in flight)."""
        n = 8 * P * max(2 * (P - 1) ** 2 // 8 + 1, 2) * P + n_extra
        x = np.random.default_rng(11).standard_normal(n).astype(np.float32)
        monkeypatch.setenv("HEAT_TPU_SORT_KERNEL", "0")
        v0, i0 = ht.sort(ht.array(x, split=0))
        monkeypatch.setenv("HEAT_TPU_SORT_KERNEL", "1")
        v1, i1 = ht.sort(ht.array(x, split=0))
        np.testing.assert_array_equal(v0.numpy(), v1.numpy())
        np.testing.assert_array_equal(i0.numpy(), i1.numpy())
        np.testing.assert_array_equal(v0.numpy(), np.sort(x, kind="stable"))
        np.testing.assert_array_equal(i0.numpy(), np.argsort(x, kind="stable"))

    def test_shardlint_sort_stays_clean(self, monkeypatch):
        """shardlint pin: ht.sort compiles with zero error-severity
        findings (no implicit reshard / replicated materialization is
        introduced by the kernel wiring)."""
        monkeypatch.setenv("HEAT_TPU_SORT_KERNEL", "1")
        x = ht.random.randn(16 * P, split=0)
        report = ht.analysis.check(lambda v: ht.sort(v)[0], x)
        errors = [f for f in report.findings if f.severity == "error"]
        assert errors == [], errors
