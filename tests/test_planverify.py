"""Schedule-IR plan verifier + memcheck cross-check tests (ISSUE 10).

Contracts pinned here:

- ``ht.analysis.verify_plan`` passes on EVERY golden-matrix plan — all
  topologies (flat / 2x4 / 2x8), quant on and off, both as Schedule
  objects and as their canonical-JSON dumps (the exact lines the ci.sh
  ``scripts/verify_plans.py`` sweep consumes).
- Every mutation class a malformed plan can carry is caught with the
  violated invariant NAMED: accounting, composition, conservation,
  quant-pairing, tier-labels, overlap-structure, plan-id, step-kinds
  (the ISSUE 14 ``progress`` invariant's mutation classes live in
  tests/test_commcheck.py).
- ``scripts/verify_plans.py`` exits 0 over a fresh dump and 1 over a
  corrupted one, naming the invariant — the CI leg's contract.
- memcheck's static peak on the three GATED redistribution programs is
  within 2x of the compiler's own ``Compiled.memory_analysis()`` on the
  tier-1 CPU mesh — the model stays honest against XLA.
- The ``Schedule.liveness`` hook agrees with the step accounting and
  never perturbs the canonical serialization (flat plans stay
  byte-identical — the ISSUE 10 escape-hatch clause).
"""

import copy
import json
import os
import subprocess
import sys

import pytest

import jax

import heat_tpu as ht

from heat_tpu.analysis.planverify import PlanVerificationError, verify_plan
from heat_tpu.redistribution import planner
from heat_tpu.redistribution.spec import RedistSpec

from test_suites.basic_test import TestCase

P = len(jax.devices())
ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BUDGET = planner.DEFAULT_BUDGET_MB << 20


class TestGoldenMatrixVerifies(TestCase):
    """The tentpole acceptance: every golden plan, every topology,
    quant on and off, proves well-formed."""

    def test_all_golden_plans_all_topologies_all_codecs(self):
        n = 0
        for topo in ("flat", "2x4", "2x8"):
            for q in ("0", "int8"):
                for name, spec in planner.golden_specs():
                    sched = planner.plan(spec, BUDGET, quant=q, topology=topo)
                    res = verify_plan(sched, topology=topo)
                    self.assertTrue(res["ok"], f"{name}@{topo} quant={q}")
                    # the serialized form (what ci.sh sweeps) verifies too
                    res_json = verify_plan(sched.canonical_json(), topology=topo)
                    self.assertTrue(res_json["ok"], f"{name}@{topo} quant={q} (json)")
                    self.assertEqual(res_json["plan_id"], sched.plan_id)
                    n += 1
        self.assertEqual(n, 3 * 2 * len(planner.golden_specs()))

    def test_bf16_codec_plans_verify(self):
        spec = RedistSpec.normalize((32768, 16384), "float32", 0, 1, 8)
        sched = planner.plan(spec, BUDGET, quant="bf16", topology="flat")
        self.assertEqual(sched.quant["mode"], "bf16")
        self.assertTrue(verify_plan(sched, topology="flat")["ok"])

    def test_report_shape_and_checks(self):
        sched = planner.plan(
            planner.golden_specs()[1][1], BUDGET, quant="0", topology="flat"
        )
        res = verify_plan(sched)
        for key in ("ok", "plan_id", "strategy", "checks", "violations"):
            self.assertIn(key, res)
        for inv in ("composition", "conservation", "accounting",
                    "quant-pairing", "tier-labels", "overlap-structure",
                    "progress", "plan-id"):
            self.assertIn(inv, res["checks"])


class TestMalformedPlansFail(TestCase):
    """Every corruption class fails with the violated invariant named —
    what byte-level dump diffing can never see."""

    def _base(self, name="resplit_chunked_2gb_p8", quant="0"):
        spec = dict(planner.golden_specs())[name]
        sched = planner.plan(spec, BUDGET, quant=quant, topology="flat")
        return json.loads(sched.canonical_json())

    def _expect(self, plan_dict, invariant):
        with self.assertRaises(PlanVerificationError) as cm:
            verify_plan(plan_dict)
        self.assertEqual(cm.exception.invariant, invariant, str(cm.exception))
        self.assertIn(invariant, str(cm.exception))
        # non-raising mode collects the same violation
        res = verify_plan(plan_dict, raise_on_violation=False)
        self.assertFalse(res["ok"])
        self.assertIn(invariant, [v["invariant"] for v in res["violations"]])

    def test_unknown_step_kind(self):
        m = self._base()
        m["steps"][0]["kind"] = "teleport"
        self._expect(m, "step-kinds")

    def test_corrupted_peak_accounting(self):
        m = self._base()
        m["peak_bytes"] += 1
        self._expect(m, "accounting")

    def test_corrupted_census(self):
        m = self._base()
        m["collective_counts"] = {"all-gather": 99}
        self._expect(m, "accounting")

    def test_wrong_strategy_composition(self):
        m = self._base()
        m["strategy"] = "ring"  # steps are a2a laps, not p-1 ppermutes
        self._expect(m, "composition")

    def test_byte_conservation(self):
        m = self._base()
        for st in m["steps"]:
            if st["kind"] == "all_to_all":
                st["bytes_moved"] += 4096
        m["bytes_moved"] = sum(s["bytes_moved"] for s in m["steps"])
        # accounting now self-consistent — only the GEOMETRY recompute
        # (and the stale overlap/plan-id) can catch it; conservation
        # must be among the named violations
        res = verify_plan(m, raise_on_violation=False)
        self.assertFalse(res["ok"])
        self.assertIn("conservation", [v["invariant"] for v in res["violations"]])

    def test_dropped_dequantize(self):
        m = self._base(quant="int8")
        m["steps"] = [s for s in m["steps"] if s["kind"] != "dequantize"]
        self._expect(m, "quant-pairing")

    def test_inconsistent_wire_ratio(self):
        m = self._base(quant="int8")
        m["quant"]["ratio"] = 0.9999
        self._expect(m, "quant-pairing")

    def test_tier_label_on_flat_plan(self):
        m = self._base()
        for st in m["steps"]:
            if st["kind"] == "all_to_all":
                st["tier"] = "dcn"
                break
        self._expect(m, "tier-labels")

    def test_tiered_plan_against_wrong_expected_topology(self):
        spec = dict(planner.golden_specs())["resplit_1gb_p16"]
        sched = planner.plan(spec, BUDGET, quant="0", topology="2x8")
        self.assertIsNotNone(sched.topology)
        with self.assertRaises(PlanVerificationError) as cm:
            verify_plan(sched, topology="flat")
        self.assertEqual(cm.exception.invariant, "tier-labels")

    def test_hierarchical_tier_order(self):
        spec = dict(planner.golden_specs())["resplit_1gb_p16"]
        sched = planner.plan(spec, BUDGET, quant="0", topology="2x8")
        self.assertEqual(sched.strategy, "hierarchical-a2a")
        m = json.loads(sched.canonical_json())
        colls = [s for s in m["steps"] if s["kind"] == "all_to_all"]
        colls[0]["tier"], colls[1]["tier"] = colls[1]["tier"], colls[0]["tier"]
        self._expect(m, "tier-labels")

    def test_corrupted_overlap_arithmetic(self):
        m = self._base()
        self.assertTrue(m.get("overlap"), "fixture spec must pipeline")
        m["overlap"]["groups"][0]["critical_path_bytes"] += 1
        self._expect(m, "overlap-structure")

    def test_forged_plan_id(self):
        m = self._base()
        m["plan_id"] = "deadbeef0000"
        self._expect(m, "plan-id")


class TestLivenessHooks(TestCase):
    """The ISSUE 10 liveness hooks on the Schedule IR: per-step live
    accounting consistent with the step peaks, and INVISIBLE to the
    canonical serialization (flat plans stay byte-identical)."""

    def test_liveness_account(self):
        spec = dict(planner.golden_specs())["resplit_chunked_2gb_p8"]
        sched = planner.plan(spec, BUDGET, quant="0", topology="flat")
        live = sched.liveness()
        self.assertEqual(len(live), sched.n_steps)
        self.assertEqual(
            max(e["transient_bytes"] for e in live), sched.peak_bytes
        )
        resident = sched.resident_bytes
        self.assertEqual(resident, spec.src_shard_bytes + spec.dst_shard_bytes)
        for e in live:
            self.assertEqual(e["live_bytes"], resident + e["transient_bytes"])
        self.assertEqual(
            sched.liveness_peak_bytes, resident + sched.peak_bytes
        )

    def test_src_shard_bytes_geometry(self):
        spec = RedistSpec.normalize((63, 48), "float32", 0, 1, 8)
        # padded source shard: 63 -> 64 rows over 8 devices
        self.assertEqual(spec.src_shard_bytes, 64 * 48 * 4 // 8)
        rep = RedistSpec.normalize((64, 48), "float32", None, 1, 8)
        self.assertEqual(rep.src_shard_bytes, 64 * 48 * 4)

    def test_liveness_never_touches_serialization(self):
        spec = dict(planner.golden_specs())["resplit_0_to_1_p8"]
        sched = planner.plan(spec, BUDGET, quant="0", topology="flat")
        before = sched.canonical_json()
        sched.liveness()
        _ = sched.liveness_peak_bytes
        self.assertEqual(sched.canonical_json(), before)
        self.assertNotIn("liveness", before)
        self.assertNotIn("resident", before)


class TestVerifyPlansCLI(TestCase):
    """scripts/verify_plans.py: exit 0 over a fresh dump, exit 1 with
    the invariant named over a corrupted one — the ci.sh leg contract."""

    def test_cli_ok_and_malformed(self):
        import tempfile

        env = dict(os.environ, JAX_PLATFORMS="cpu")
        dump = subprocess.run(
            [sys.executable, os.path.join(ROOT, "scripts", "redist_plans.py")],
            capture_output=True, text=True, env=env,
        )
        self.assertEqual(dump.returncode, 0, dump.stderr)
        with tempfile.TemporaryDirectory() as td:
            good = os.path.join(td, "plans.txt")
            with open(good, "w") as f:
                f.write(dump.stdout)
            ok = subprocess.run(
                [sys.executable, os.path.join(ROOT, "scripts", "verify_plans.py"), good],
                capture_output=True, text=True, env=env,
            )
            self.assertEqual(ok.returncode, 0, ok.stdout + ok.stderr)
            self.assertIn("well-formed", ok.stdout)

            # corrupt one plan's accounting; the sweep must fail and
            # name the invariant
            lines = dump.stdout.strip().splitlines()
            name, _, payload = lines[1].partition("\t")
            plan = json.loads(payload)
            plan["peak_bytes"] += 1
            lines[1] = f"{name}\t{json.dumps(plan, sort_keys=True, separators=(',', ':'))}"
            bad = os.path.join(td, "bad.txt")
            with open(bad, "w") as f:
                f.write("\n".join(lines) + "\n")
            r = subprocess.run(
                [sys.executable, os.path.join(ROOT, "scripts", "verify_plans.py"), bad],
                capture_output=True, text=True, env=env,
            )
            self.assertEqual(r.returncode, 1, r.stdout + r.stderr)
            self.assertIn("accounting", r.stdout)
            self.assertIn("FAIL", r.stdout)


class TestMemcheckXLACrossCheck(TestCase):
    """The acceptance pin: memcheck's static peak on the three GATED
    redistribution bench programs is within 2x of the compiler's own
    memory_analysis() on the tier-1 CPU mesh. Compile-only (ht.zeros
    operands; nothing executes beyond the zeros placement)."""

    @pytest.mark.skipif(P != 8, reason="pinned on the tier-1 8-device mesh")
    def test_gated_rows_within_2x_of_xla(self):
        cases = {
            "resplit_1gb": (
                ht.zeros((1000, 250000), split=0),
                lambda y: y.resplit(1),
            ),
            "reshape_split1_1gb": (
                ht.zeros((1000, 250000), split=1),
                lambda y: ht.reshape(y, (10_000_000, -1), new_split=1),
            ),
            "reshape_lane_1gb": (
                ht.zeros((65536, 4096), split=1),
                lambda y: ht.reshape(y, (131072, 2048), new_split=1),
            ),
        }
        for row, (x, fn) in cases.items():
            rep = ht.analysis.memcheck(fn, x)
            ctx = rep.context
            self.assertGreater(ctx["static_peak_bytes"], 0, row)
            self.assertIn("xla_peak_bytes", ctx, f"{row}: no memory_analysis on this backend")
            ratio = ctx["static_peak_bytes"] / max(ctx["xla_peak_bytes"], 1)
            self.assertGreaterEqual(ratio, 0.5, f"{row}: model under XLA/2 ({ratio:.2f})")
            self.assertLessEqual(ratio, 2.0, f"{row}: model over 2x XLA ({ratio:.2f})")
            # the gated rows themselves stay finding-free
            self.assertEqual([str(f) for f in rep.errors], [], row)


if __name__ == "__main__":
    import unittest

    unittest.main()
