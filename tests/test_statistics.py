"""Statistics parity tests vs NumPy oracle across splits (the reference's
per-module test pattern, core/tests/test_statistics.py)."""

import numpy as np

import heat_tpu as ht

from test_suites.basic_test import TestCase


class TestStatistics(TestCase):
    def setUp(self):
        np.random.seed(42)
        self.data = np.random.randn(7, 9).astype(np.float32)

    def test_mean_var_std(self):
        d = self.data
        for split in (None, 0, 1):
            x = ht.array(d, split=split)
            np.testing.assert_allclose(float(ht.mean(x)), d.mean(), rtol=1e-5)
            np.testing.assert_allclose(float(ht.var(x)), d.var(), rtol=1e-5)
            np.testing.assert_allclose(float(ht.std(x)), d.std(), rtol=1e-5)
            self.assert_array_equal(ht.mean(x, axis=0), d.mean(axis=0))
            self.assert_array_equal(ht.mean(x, axis=1), d.mean(axis=1))
            self.assert_array_equal(ht.var(x, axis=0, ddof=1), d.var(axis=0, ddof=1))
            self.assert_array_equal(ht.std(x, axis=1), d.std(axis=1))

    def test_min_max(self):
        d = self.data
        for split in (None, 0, 1):
            x = ht.array(d, split=split)
            np.testing.assert_allclose(float(ht.max(x)), d.max())
            np.testing.assert_allclose(float(ht.min(x)), d.min())
            self.assert_array_equal(ht.max(x, axis=0), d.max(axis=0))
            self.assert_array_equal(ht.min(x, axis=1), d.min(axis=1))
            self.assert_array_equal(ht.maximum(x, -x), np.maximum(d, -d))
            self.assert_array_equal(ht.minimum(x, -x), np.minimum(d, -d))

    def test_argmax_argmin(self):
        d = self.data
        for split in (None, 0, 1):
            x = ht.array(d, split=split)
            self.assertEqual(int(ht.argmax(x)), int(d.argmax()))
            self.assertEqual(int(ht.argmin(x)), int(d.argmin()))
            self.assert_array_equal(ht.argmax(x, axis=0), d.argmax(axis=0))
            self.assert_array_equal(ht.argmin(x, axis=1), d.argmin(axis=1))

    def test_average(self):
        d = self.data
        w = np.random.rand(9).astype(np.float32)
        for split in (None, 0):
            x = ht.array(d, split=split)
            np.testing.assert_allclose(float(ht.average(x)), np.average(d), rtol=1e-5)
            got = ht.average(x, axis=1, weights=ht.array(w))
            np.testing.assert_allclose(got.numpy(), np.average(d, axis=1, weights=w), rtol=1e-5)

    def test_percentile_median(self):
        d = self.data
        for split in (None, 0, 1):
            x = ht.array(d, split=split)
            np.testing.assert_allclose(
                float(ht.percentile(x, 30)), np.percentile(d, 30), rtol=1e-5
            )
            np.testing.assert_allclose(float(ht.median(x)), np.median(d), rtol=1e-5)
            got = ht.percentile(x, 75, axis=0)
            np.testing.assert_allclose(got.numpy(), np.percentile(d, 75, axis=0), rtol=1e-5)

    def test_bincount_digitize(self):
        v = np.array([0, 1, 1, 2, 2, 2, 5], dtype=np.int32)
        x = ht.array(v, split=0)
        self.assert_array_equal(ht.bincount(x), np.bincount(v))
        self.assert_array_equal(ht.bincount(x, minlength=10), np.bincount(v, minlength=10))
        bins = np.array([0.0, 1.0, 2.0, 3.0])
        data = np.array([0.5, 1.5, 2.5, 3.5], dtype=np.float32)
        hx = ht.array(data, split=0)
        self.assert_array_equal(ht.digitize(hx, ht.array(bins)), np.digitize(data, bins))

    def test_histogram(self):
        d = self.data.ravel()
        x = ht.array(d, split=0)
        h, e = ht.histogram(x, bins=12)
        nh, ne = np.histogram(d, bins=12)
        np.testing.assert_array_equal(h.numpy(), nh)
        np.testing.assert_allclose(e.numpy(), ne, rtol=1e-6)

    def test_cov(self):
        d = self.data
        x = ht.array(d, split=0)
        np.testing.assert_allclose(ht.cov(x).numpy(), np.cov(d), rtol=1e-4)

    def test_skew_kurtosis(self):
        from scipy import stats

        d = self.data.ravel()
        x = ht.array(d, split=0)
        np.testing.assert_allclose(
            float(ht.skew(x, unbiased=False)), stats.skew(d, bias=True), rtol=1e-4
        )
        np.testing.assert_allclose(
            float(ht.kurtosis(x, unbiased=False, Fischer=True)),
            stats.kurtosis(d, fisher=True, bias=True),
            rtol=1e-4,
        )


if __name__ == "__main__":
    import unittest

    unittest.main()
