"""PRNG tests: reproducibility, state management, distribution sanity
(reference: core/tests/test_random.py patterns)."""

import numpy as np

import heat_tpu as ht

from test_suites.basic_test import TestCase


class TestRandom(TestCase):
    def test_seed_reproducibility(self):
        ht.random.seed(123)
        a = ht.random.rand(5, 4, split=0)
        ht.random.seed(123)
        b = ht.random.rand(5, 4, split=0)
        np.testing.assert_array_equal(a.numpy(), b.numpy())

    def test_split_invariance(self):
        # same sequence regardless of how the result is distributed — the
        # property the reference builds its counter machinery for
        ht.random.seed(7)
        a = ht.random.rand(6, 6, split=0)
        ht.random.seed(7)
        b = ht.random.rand(6, 6, split=1)
        ht.random.seed(7)
        c = ht.random.rand(6, 6)
        np.testing.assert_array_equal(a.numpy(), b.numpy())
        np.testing.assert_array_equal(a.numpy(), c.numpy())

    def test_state(self):
        ht.random.seed(99)
        state = ht.random.get_state()
        self.assertEqual(state[0], "Threefry")
        self.assertEqual(state[1], 99)
        x = ht.random.rand(10)
        ht.random.set_state(state)
        y = ht.random.rand(10)
        np.testing.assert_array_equal(x.numpy(), y.numpy())
        # counter advances
        self.assertGreater(ht.random.get_state()[2], state[2])

    def test_rand_range_and_dtype(self):
        ht.random.seed(0)
        x = ht.random.rand(100, split=0)
        self.assertEqual(x.dtype, ht.float32)
        self.assertTrue(bool((x >= 0).all()) and bool((x < 1).all()))
        with self.assertRaises(ValueError):
            ht.random.rand(3, dtype=ht.int32)

    def test_randn_moments(self):
        ht.random.seed(1)
        x = ht.random.randn(10000, split=0)
        self.assertAlmostEqual(float(x.mean()), 0.0, delta=0.05)
        self.assertAlmostEqual(float(x.std()), 1.0, delta=0.05)

    def test_randint(self):
        ht.random.seed(2)
        x = ht.random.randint(3, 10, size=(50,), split=0)
        self.assertEqual(x.dtype, ht.int32)
        arr = x.numpy()
        self.assertTrue(arr.min() >= 3 and arr.max() < 10)
        with self.assertRaises(ValueError):
            ht.random.randint(5, 2)

    def test_randperm_permutation(self):
        ht.random.seed(3)
        p = ht.random.randperm(20, split=0)
        self.assertEqual(p.dtype, ht.int64)
        np.testing.assert_array_equal(np.sort(p.numpy()), np.arange(20))
        x = ht.arange(10, split=0)
        shuffled = ht.random.permutation(x)
        np.testing.assert_array_equal(np.sort(shuffled.numpy()), np.arange(10))

    def test_normal(self):
        ht.random.seed(4)
        x = ht.random.normal(5.0, 2.0, (5000,), split=0)
        self.assertAlmostEqual(float(x.mean()), 5.0, delta=0.1)
        self.assertAlmostEqual(float(x.std()), 2.0, delta=0.1)


if __name__ == "__main__":
    import unittest

    unittest.main()
