"""Direct tests of the communication layer — the analog of the
reference's ``core/tests/test_communication.py`` (37 tests exercising
chunk, buffers and every collective against known results). Here the
layer is geometry + sharding construction: ``chunk``/``counts_displs``
must agree EXACTLY with where ``jax.Array`` shards land on the mesh,
and ``shard``/``reshard_phys`` must preserve values across layouts."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import heat_tpu as ht
from heat_tpu.core import _padding
from heat_tpu.core.communication import MeshCommunication

from test_suites.basic_test import TestCase


class TestChunkGeometry(TestCase):
    def test_chunk_matches_jax_placement(self):
        """chunk's slices must equal the actual addressable-shard indices
        of a sharded jax.Array — the core contract of the layer."""
        comm = ht.get_comm()
        for n in (comm.size * 4, comm.size * 4 + 3, comm.size - 1 or 1, 1):
            x = ht.arange(n, split=0, dtype=ht.float32)
            block = x._phys.shape[0] // comm.size
            for s in x._phys.addressable_shards:
                r = s.index[0].start or 0
                rank = r // block if block else 0
                off, lshape, slices = comm.chunk((n,), 0, rank=rank)
                valid = np.asarray(s.data)[: lshape[0]]
                np.testing.assert_array_equal(valid, np.arange(n)[slices[0]])

    def test_chunk_replicated_and_single(self):
        comm = ht.get_comm()
        off, lshape, slices = comm.chunk((10, 4), None)
        assert off == 0 and lshape == (10, 4)
        off, lshape, _ = comm.chunk((10, 4), 0, w_size=1)
        assert lshape == (10, 4)

    def test_chunk_short_and_empty_tail(self):
        comm = ht.get_comm()
        p = comm.size
        if p < 2:
            pytest.skip("needs >1 device")
        n = p + 1  # ceil-block 2: device 0 full, middle short/empty tail
        sizes = [comm.chunk((n,), 0, rank=r)[1][0] for r in range(p)]
        assert sum(sizes) == n
        assert sizes[0] == 2
        assert all(s >= 0 for s in sizes)

    def test_counts_displs_conserve(self):
        comm = ht.get_comm()
        for n in (17, 64, 3, 1):
            counts, displs, lshape = comm.counts_displs_shape((n, 2), 0)
            assert sum(counts) == n
            assert len(counts) == comm.size
            assert displs[0] == 0
            for c, d in zip(counts[1:], displs[1:]):
                assert d <= n
            assert lshape[0] == counts[0]

    def test_lshape_map_geometry(self):
        comm = ht.get_comm()
        lmap = comm.lshape_map((13, 5), 0)
        assert lmap.shape == (comm.size, 2)
        assert lmap[:, 0].sum() == 13
        assert (lmap[:, 1] == 5).all()


class TestShardingConstruction(TestCase):
    def test_spec_places_axis(self):
        comm = ht.get_comm()
        assert tuple(comm.spec(3, 1)) == (None, comm.axis_name, None)
        # replicated: no partitioned dims in the spec
        assert comm.axis_name not in tuple(comm.spec(2, None))

    def test_shard_roundtrip_values(self):
        comm = ht.get_comm()
        rng = np.random.default_rng(0)
        for shape, split in (((13, 4), 0), ((4, 13), 1), ((9,), 0), ((3, 3), None)):
            arr = jnp.asarray(rng.standard_normal(shape).astype(np.float32))
            phys = comm.shard(arr, split)
            back = _padding.unpad(phys, shape, split)
            np.testing.assert_array_equal(np.asarray(back), np.asarray(arr))
            if split is not None:
                assert phys.shape[split] % comm.size == 0 or shape[split] == 0

    def test_shard_zero_extent(self):
        comm = ht.get_comm()
        arr = jnp.zeros((0, 4), dtype=jnp.float32)
        phys = comm.shard(arr, 0)
        assert phys.shape == (0, 4)

    def test_reshard_phys_roundtrip(self):
        comm = ht.get_comm()
        rng = np.random.default_rng(1)
        arr = jnp.asarray(rng.standard_normal((11, 6)).astype(np.float32))
        p0 = comm.shard(arr, 0)
        p1 = comm.reshard_phys(p0, (11, 6), 0, 1)
        back = comm.reshard_phys(p1, (11, 6), 1, 0)
        np.testing.assert_array_equal(
            np.asarray(_padding.unpad(back, (11, 6), 0)), np.asarray(arr)
        )
        # pad invariant holds after every reshard
        np.testing.assert_array_equal(np.asarray(p1)[:, 6:], 0.0)


class TestCommunicatorManagement(TestCase):
    def test_world_and_self(self):
        assert ht.MPI_WORLD.size == len(jax.devices())
        assert ht.MPI_SELF.size == 1

    def test_use_comm_get_comm(self):
        prev = ht.get_comm()
        try:
            ht.use_comm(ht.MPI_SELF)
            assert ht.get_comm().size == 1
        finally:
            ht.use_comm(prev)

    def test_use_comm_rejects_garbage(self):
        with pytest.raises(TypeError):
            ht.use_comm(42)

    def test_sub_mesh_from_split(self):
        comm = ht.get_comm()
        p = comm.size
        if p < 2:
            pytest.skip("needs >1 device")
        groups = comm.Split([0] * (p // 2) + [1] * (p - p // 2))
        sub = groups[0]
        assert isinstance(sub, MeshCommunication)
        # arrays created on the sub-communicator shard over its devices only
        x = ht.arange(sub.size * 2, split=0, comm=sub)
        devs = {s.device for s in x._phys.addressable_shards}
        assert devs == set(sub.devices)
        assert int(ht.sum(x)) == sum(range(sub.size * 2))


class TestSingleDevicePlacement:
    """Zero-input jitted builders must pin placement even on a 1-device
    mesh — a Split sub-communicator's device is not the default device
    (regression: the single-chip dispatch fast path must not apply to
    factories/random, whose programs have no committed array inputs)."""

    def test_factory_on_size1_subcomm_lands_on_its_device(self):
        comm = ht.get_comm()
        if comm.size < 2:
            pytest.skip("needs >1 device")
        # one group per device → every sub-communicator has size 1
        groups = comm.Split(list(range(comm.size)))
        sub = groups[comm.size - 1]  # a NON-default device
        assert sub.size == 1
        target = set(sub.devices)
        for arr in (
            ht.zeros((5,), comm=sub),
            ht.arange(5, comm=sub),
            ht.random.randn(5, comm=sub),
        ):
            devs = {s.device for s in arr._phys.addressable_shards}
            assert devs == target, f"landed on {devs}, expected {target}"

    def test_ops_on_size1_subcomm_stay_on_its_device(self):
        comm = ht.get_comm()
        if comm.size < 2:
            pytest.skip("needs >1 device")
        sub = comm.Split(list(range(comm.size)))[comm.size - 1]
        x = ht.arange(7, dtype=ht.float32, comm=sub)
        y = ht.exp(x * 2.0 + x)  # committed inputs pin the fast-path programs
        devs = {s.device for s in y._phys.addressable_shards}
        assert devs == set(sub.devices)
