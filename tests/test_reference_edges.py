"""Edge-case behaviors the reference test suite exercises, collected from
a systematic divergence hunt against numpy/sklearn oracles (the hunt found
one real bug — diff's pad leak, regression-tested in
test_op_parity_sweep.py — and these probes pin the rest)."""

import numpy as np
import pytest

import heat_tpu as ht

A = np.arange(24, dtype=np.float32).reshape(4, 6)
B = np.random.default_rng(0).standard_normal((6, 6)).astype(np.float32)
SPD = B @ B.T + 6 * np.eye(6, dtype=np.float32)
G = np.arange(48, dtype=np.float32).reshape(8, 6)


class TestManipulationEdges:
    def test_unique_axis(self):
        x = np.array([[1, 2], [1, 2], [3, 4]], np.float32)
        got = ht.unique(ht.array(x, split=0), axis=0)
        np.testing.assert_array_equal(np.asarray(got.numpy()), np.unique(x, axis=0))

    def test_unique_return_inverse_reconstructs(self):
        x = np.array([3, 1, 1, 2], np.float32)
        u, inv = ht.unique(ht.array(x, split=0), sorted=True, return_inverse=True)
        np.testing.assert_array_equal(np.asarray(u.numpy())[np.asarray(inv.numpy())], x)

    def test_roll_two_axes(self):
        got = ht.roll(ht.array(A, split=0), (1, -2), axis=(0, 1))
        np.testing.assert_array_equal(np.asarray(got.numpy()), np.roll(A, (1, -2), (0, 1)))

    def test_pad_asymmetric_with_value(self):
        got = ht.pad(ht.array(A, split=0), ((1, 2), (0, 1)), constant_values=7)
        np.testing.assert_array_equal(
            np.asarray(got.numpy()), np.pad(A, ((1, 2), (0, 1)), constant_values=7)
        )

    def test_flip_negative_axis_on_split(self):
        got = ht.flip(ht.array(A, split=1), (-1,))
        np.testing.assert_array_equal(np.asarray(got.numpy()), np.flip(A, -1))

    def test_moveaxis(self):
        x = np.arange(24, dtype=np.float32).reshape(2, 3, 4)
        got = ht.moveaxis(ht.array(x, split=0), 0, -1)
        np.testing.assert_array_equal(np.asarray(got.numpy()), np.moveaxis(x, 0, -1))

    def test_column_stack_and_vstack(self):
        got = ht.column_stack([ht.array(A[:, 0], split=0), ht.array(A[:, 1], split=0)])
        np.testing.assert_array_equal(
            np.asarray(got.numpy()), np.column_stack([A[:, 0], A[:, 1]])
        )
        got = ht.vstack([ht.array(A, split=0), ht.array(A, split=0)])
        np.testing.assert_array_equal(np.asarray(got.numpy()), np.vstack([A, A]))

    def test_repeat_axis(self):
        got = ht.repeat(ht.array(A, split=0), 3, axis=1)
        np.testing.assert_array_equal(np.asarray(got.numpy()), np.repeat(A, 3, axis=1))

    def test_expand_squeeze_split_bookkeeping(self):
        got = ht.expand_dims(ht.array(B, split=1), -1)
        np.testing.assert_array_equal(np.asarray(got.numpy()), np.expand_dims(B, -1))
        got = ht.squeeze(ht.array(B[:1], split=1), 0)
        np.testing.assert_array_equal(np.asarray(got.numpy()), B[0])


class TestStatisticsEdges:
    def test_average_weighted_axis(self):
        w = np.arange(4, dtype=np.float32)
        got = ht.average(ht.array(A, split=0), weights=ht.array(w), axis=0)
        np.testing.assert_allclose(
            np.asarray(got.numpy()), np.average(A, weights=w, axis=0), rtol=1e-5
        )

    def test_digitize_and_bucketize_right(self):
        x = np.array([1.0, 2.0, 3.0], np.float32)
        bins = np.array([1.5, 2.5], np.float32)
        got = ht.digitize(ht.array(x, split=0), ht.array(bins), right=True)
        np.testing.assert_array_equal(np.asarray(got.numpy()), np.digitize(x, bins, right=True))
        got = ht.bucketize(
            ht.array(np.array([1.0, 2.5, 7.0], np.float32), split=0),
            ht.array(np.array([2.0, 5.0], np.float32)),
        )
        np.testing.assert_array_equal(np.asarray(got.numpy()), [0, 1, 2])

    def test_median_keepdims(self):
        got = ht.median(ht.array(A, split=0), axis=1, keepdims=True)
        np.testing.assert_allclose(np.asarray(got.numpy()), np.median(A, axis=1, keepdims=True))

    def test_percentile_vector_q(self):
        got = ht.percentile(ht.array(A.ravel(), split=0), [10.0, 50.0, 90.0])
        np.testing.assert_allclose(
            np.asarray(got.numpy()), np.percentile(A.ravel(), [10, 50, 90]), rtol=1e-5
        )

    def test_cov_default_rowvar(self):
        got = ht.cov(ht.array(A, split=0))
        np.testing.assert_allclose(np.asarray(got.numpy()), np.cov(A), rtol=1e-5)


class TestLinalgEdges:
    def test_inv_det(self):
        np.testing.assert_allclose(
            np.asarray(ht.linalg.inv(ht.array(SPD, split=0)).numpy()),
            np.linalg.inv(SPD), rtol=1e-3, atol=1e-4,
        )
        np.testing.assert_allclose(
            float(ht.linalg.det(ht.array(SPD, split=0))), np.linalg.det(SPD), rtol=1e-3
        )

    def test_norm_orders(self):
        np.testing.assert_allclose(float(ht.linalg.norm(ht.array(B, split=0))), np.linalg.norm(B), rtol=1e-5)
        np.testing.assert_allclose(
            float(ht.linalg.matrix_norm(ht.array(B, split=0), ord=1).numpy()),
            np.linalg.norm(B, 1), rtol=1e-5,
        )
        np.testing.assert_allclose(
            float(ht.linalg.vector_norm(ht.array(B.ravel(), split=0), ord=np.inf)),
            np.linalg.norm(B.ravel(), np.inf), rtol=1e-6,
        )

    def test_trace_offset_tril_k(self):
        np.testing.assert_allclose(float(ht.trace(ht.array(B, split=0), offset=1)), np.trace(B, offset=1), rtol=1e-4)
        np.testing.assert_array_equal(
            np.asarray(ht.tril(ht.array(B, split=0), k=-1).numpy()), np.tril(B, -1)
        )

    def test_cross_vecdot(self):
        np.testing.assert_allclose(
            np.asarray(ht.cross(ht.array(B[:, :3], split=0), ht.array(B[:, 3:], split=0)).numpy()),
            np.cross(B[:, :3], B[:, 3:]), rtol=1e-5,
        )
        np.testing.assert_allclose(
            np.asarray(ht.vecdot(ht.array(B, split=0), ht.array(B, split=0)).numpy()),
            np.sum(B * B, -1), rtol=1e-4,
        )

    def test_outer_split_operand(self):
        got = ht.outer(ht.arange(5, dtype=ht.float32, split=0), ht.arange(3, dtype=ht.float32))
        np.testing.assert_allclose(np.asarray(got.numpy()), np.outer(np.arange(5), np.arange(3)), rtol=1e-6)


class TestIndexingEdges:
    @pytest.mark.parametrize("key", [np.s_[::2], np.s_[::-1], np.s_[None, :, :]])
    def test_slice_forms(self, key):
        got = ht.array(G, split=0)[key]
        np.testing.assert_array_equal(np.asarray(got.numpy()), G[key])

    def test_integer_array_rows(self):
        got = ht.array(G, split=0)[np.array([5, 0, 2])]
        np.testing.assert_array_equal(np.asarray(got.numpy()), G[[5, 0, 2]])

    def test_coordinate_advanced_pair(self):
        got = ht.array(G, split=0)[np.array([1, 2]), np.array([3, 4])]
        np.testing.assert_array_equal(np.asarray(got.numpy()), G[[1, 2], [3, 4]])


class TestRandomEdges:
    def test_randperm_permutation(self):
        np.testing.assert_array_equal(
            np.sort(np.asarray(ht.random.randperm(17, split=0).numpy())), np.arange(17)
        )
        np.testing.assert_array_equal(
            np.sort(np.asarray(ht.random.permutation(ht.arange(12, split=0)).numpy())),
            np.arange(12),
        )

    def test_randint_bounds_and_normal_moments(self):
        r = np.asarray(ht.random.randint(2, 9, (200,), split=0).numpy())
        assert r.min() >= 2 and r.max() < 9
        n = np.asarray(ht.random.normal(5.0, 2.0, (10000,), split=0).numpy())
        np.testing.assert_allclose([n.mean(), n.std()], [5.0, 2.0], atol=0.2)

    def test_state_round_trip(self):
        st = ht.random.get_state()
        x1 = np.asarray(ht.random.rand(5).numpy())
        ht.random.set_state(st)
        np.testing.assert_array_equal(np.asarray(ht.random.rand(5).numpy()), x1)


class TestCSVEdges:
    def test_round_trip_with_header(self, tmp_path):
        p = str(tmp_path / "t.csv")
        ht.save_csv(ht.array(G, split=0), p, header_lines=["c1", "c2"])
        back = ht.load_csv(p, header_lines=2, split=0)
        np.testing.assert_allclose(np.asarray(back.numpy()), G)


class TestEstimatorEdges:
    def test_scaler_inverses_and_oracles(self):
        from sklearn.preprocessing import MinMaxScaler as SkMM, RobustScaler as SkRS

        X = np.random.default_rng(1).standard_normal((40, 5)).astype(np.float32)
        xs = ht.array(X, split=0)
        s = ht.preprocessing.StandardScaler().fit(xs)
        np.testing.assert_allclose(
            np.asarray(s.inverse_transform(s.transform(xs)).numpy()), X, rtol=1e-4, atol=1e-5
        )
        np.testing.assert_allclose(
            np.asarray(ht.preprocessing.MinMaxScaler().fit(xs).transform(xs).numpy()),
            SkMM().fit_transform(X), rtol=1e-4, atol=1e-5,
        )
        np.testing.assert_allclose(
            np.asarray(ht.preprocessing.RobustScaler().fit(xs).transform(xs).numpy()),
            SkRS().fit_transform(X), rtol=1e-3, atol=1e-3,
        )

    def test_gaussian_nb_chunked_partial_fit(self):
        from sklearn.naive_bayes import GaussianNB as SkNB

        X = np.random.default_rng(2).standard_normal((40, 5)).astype(np.float32)
        y = (X[:, 0] > 0).astype(np.int32)
        nb = ht.naive_bayes.GaussianNB()
        nb.partial_fit(ht.array(X[:20], split=0), ht.array(y[:20], split=0),
                       classes=ht.array(np.array([0, 1])))
        nb.partial_fit(ht.array(X[20:], split=0), ht.array(y[20:], split=0))
        pred = np.asarray(nb.predict(ht.array(X, split=0)).numpy())
        ref = SkNB().fit(X, y).predict(X)
        assert (pred == ref).mean() > 0.95

    def test_spectral_separates_with_adequate_krylov(self):
        from heat_tpu.utils.data.spherical import create_spherical_dataset

        data = create_spherical_dataset(
            num_samples_cluster=40, radius=1.0, offset=6.0, dtype=ht.float32, random_state=3
        )
        sp = ht.cluster.Spectral(
            n_clusters=4, gamma=1.0, metric="rbf", laplacian="fully_connected", n_lanczos=60
        )
        labels = np.asarray(sp.fit_predict(data).numpy()).ravel().reshape(4, 40)
        majorities = []
        for block in labels:
            vals, counts = np.unique(block, return_counts=True)
            assert counts.max() / block.size > 0.9
            majorities.append(vals[np.argmax(counts)])
        # all four planted clusters must get DISTINCT labels (a collapsed
        # one-cluster model would pass the purity check alone)
        assert len(set(majorities)) == 4, majorities

    def test_knn_tiny_train_set(self):
        X = np.random.default_rng(3).standard_normal((12, 5)).astype(np.float32)
        y = (X[:, 0] > 0).astype(np.int32)
        clf = ht.classification.KNeighborsClassifier(n_neighbors=3)
        clf.fit(ht.array(X[:7], split=0), ht.array(y[:7], split=0))
        assert np.asarray(clf.predict(ht.array(X[7:], split=0)).numpy()).shape[0] == 5


class TestSparseEdges:
    """Only behavior NOT already pinned in tests/test_sparse.py: gnnz."""

    def test_gnnz(self):
        import scipy.sparse as sp

        rng = np.random.default_rng(0)
        dense = ((rng.random((9, 7)) < 0.4) * rng.standard_normal((9, 7))).astype(np.float32)
        m = ht.sparse.sparse_csr_matrix(sp.csr_matrix(dense), split=0)
        assert m.gnnz == sp.csr_matrix(dense).nnz


class TestSignalEdges:
    """Only the operand-swap path (kernel longer than signal) — the mode
    sweep lives in tests/test_parallel_primitives.py."""

    def test_convolve_kernel_longer_than_signal(self):
        rng = np.random.default_rng(3)
        sig = rng.standard_normal(37).astype(np.float32)
        ker = rng.standard_normal(5).astype(np.float32)
        got = ht.convolve(ht.array(ker, split=0), ht.array(sig), mode="full")
        np.testing.assert_allclose(
            np.asarray(got.numpy()), np.convolve(ker, sig, mode="full"), rtol=1e-4, atol=1e-5
        )


class TestMatmulPrecision:
    def test_precision_kwarg_and_ambient_context(self):
        """f32 matmul on TPU runs bf16 MXU passes by default (the same
        trade as torch-CUDA's tf32); precision='highest' forces f32-exact
        accumulation, and jax.default_matmul_precision applies as ambient
        context. On CPU both paths are exact — this pins the API."""
        import jax

        a = np.random.default_rng(5).standard_normal((16, 8)).astype(np.float32)
        x, y = ht.array(a, split=0), ht.array(a.T, split=1)
        got = ht.matmul(x, y, precision="highest")
        np.testing.assert_allclose(np.asarray(got.numpy()), a @ a.T, rtol=1e-5, atol=1e-5)
        with jax.default_matmul_precision("highest"):
            got2 = ht.matmul(x, y)
        np.testing.assert_allclose(np.asarray(got2.numpy()), a @ a.T, rtol=1e-5, atol=1e-5)


class TestMethodParity:
    """Class-method-level parity closures from the method audit."""

    def test_knn_one_hot_encoding(self):
        y = ht.array(np.array([0, 2, 1, 2], np.int32), split=0)
        oh = ht.classification.KNeighborsClassifier.one_hot_encoding(y)
        np.testing.assert_array_equal(
            np.asarray(oh.numpy()), [[1, 0, 0], [0, 0, 1], [0, 1, 0], [0, 0, 1]]
        )

    def test_gaussiannb_logsumexp(self):
        import scipy.special

        a = np.random.default_rng(0).standard_normal((5, 4)).astype(np.float32)
        nb = ht.naive_bayes.GaussianNB()
        out = nb.logsumexp(ht.array(a, split=0), axis=1)
        np.testing.assert_allclose(
            np.asarray(out.numpy()), scipy.special.logsumexp(a, axis=1), rtol=1e-5
        )
        out2, sign = nb.logsumexp(
            ht.array(a, split=0), axis=0, b=ht.array(np.abs(a)), return_sign=True
        )
        ref2, refsign = scipy.special.logsumexp(a, axis=0, b=np.abs(a), return_sign=True)
        np.testing.assert_allclose(np.asarray(out2.numpy()), ref2, rtol=1e-5)
        np.testing.assert_array_equal(np.asarray(sign.numpy()), refsign)

    def test_dcsr_global_aliases(self):
        import scipy.sparse as sp

        csr = sp.csr_matrix(np.eye(5, dtype=np.float32))
        m = ht.sparse.sparse_csr_matrix(csr, split=0)
        np.testing.assert_array_equal(np.asarray(m.gdata), np.asarray(m.data))
        np.testing.assert_array_equal(np.asarray(m.gindices), np.asarray(m.indices))
