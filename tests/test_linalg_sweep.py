"""Linear-algebra breadth sweep: dense ops, factorizations and solvers
across splits, shapes (tall/wide/uneven) and dtypes — the shape-loop
coverage of the reference's core/linalg/tests (test_qr.py loops
split × tiles_per_proc; test_basics.py loops split pairs)."""

import numpy as np
import pytest

import heat_tpu as ht

_RNG = np.random.default_rng(11)


class TestDenseOpsSweep:
    @pytest.mark.parametrize("split", [None, 0, 1])
    def test_norms(self, split):
        d = _RNG.standard_normal((9, 5)).astype(np.float32)
        x = ht.array(d, split=split)
        np.testing.assert_allclose(float(ht.linalg.norm(x)), np.linalg.norm(d), rtol=1e-5)
        v = _RNG.standard_normal(23).astype(np.float32)
        y = ht.array(v, split=0 if split is not None else None)
        np.testing.assert_allclose(float(ht.linalg.norm(y)), np.linalg.norm(v), rtol=1e-5)

    @pytest.mark.parametrize("split", [None, 0, 1])
    def test_inv_det_trace(self, split):
        d = _RNG.standard_normal((6, 6)).astype(np.float64)
        d = d @ d.T + 6 * np.eye(6)
        x = ht.array(d, split=split)
        np.testing.assert_allclose(ht.linalg.inv(x).numpy(), np.linalg.inv(d), rtol=1e-6, atol=1e-8)
        np.testing.assert_allclose(float(ht.linalg.det(x)), np.linalg.det(d), rtol=1e-6)
        np.testing.assert_allclose(float(ht.trace(x)), np.trace(d), rtol=1e-8)

    @pytest.mark.parametrize("split", [None, 0])
    def test_dot_vdot_outer(self, split):
        a = _RNG.standard_normal(17).astype(np.float32)
        b = _RNG.standard_normal(17).astype(np.float32)
        x, y = ht.array(a, split=split), ht.array(b, split=split)
        np.testing.assert_allclose(float(ht.dot(x, y)), a @ b, rtol=1e-4)
        np.testing.assert_allclose(float(ht.vdot(x, y)), np.vdot(a, b), rtol=1e-4)
        np.testing.assert_allclose(ht.outer(x, y).numpy(), np.outer(a, b), rtol=1e-5)

    @pytest.mark.parametrize("split", [None, 0, 1])
    def test_tril_triu_transpose(self, split):
        d = _RNG.standard_normal((7, 10)).astype(np.float32)
        x = ht.array(d, split=split)
        np.testing.assert_array_equal(ht.tril(x).numpy(), np.tril(d))
        np.testing.assert_array_equal(ht.triu(x, 1).numpy(), np.triu(d, 1))
        np.testing.assert_array_equal(ht.transpose(x).numpy(), d.T)

    @pytest.mark.parametrize("sa", [None, 0, 1])
    @pytest.mark.parametrize("sb", [None, 0, 1])
    def test_matmul_split_matrix_uneven(self, sa, sb):
        a = _RNG.standard_normal((11, 7)).astype(np.float32)
        b = _RNG.standard_normal((7, 13)).astype(np.float32)
        x, y = ht.array(a, split=sa), ht.array(b, split=sb)
        np.testing.assert_allclose(ht.matmul(x, y).numpy(), a @ b, rtol=2e-4, atol=2e-4)


class TestFactorizationsSweep:
    @pytest.mark.parametrize("m,n", [(40, 7), (23, 5), (8, 8), (5, 9)])
    @pytest.mark.parametrize("split", [0, None])
    def test_qr_shapes(self, m, n, split):
        d = _RNG.standard_normal((m, n)).astype(np.float32)
        q, r = ht.linalg.qr(ht.array(d, split=split))
        np.testing.assert_allclose(q.numpy() @ r.numpy(), d, rtol=2e-3, atol=2e-3)
        np.testing.assert_allclose(
            q.numpy().T @ q.numpy(), np.eye(q.shape[1]), atol=2e-3
        )

    @pytest.mark.parametrize("split", [None, 0, 1])
    def test_full_svd(self, split):
        d = _RNG.standard_normal((24, 9)).astype(np.float32)
        u, s, vt = ht.linalg.svd(ht.array(d, split=split))
        rec = u.numpy() @ np.diag(s.numpy()) @ vt.numpy()
        np.testing.assert_allclose(rec, d, rtol=2e-3, atol=2e-3)
        np.testing.assert_allclose(
            np.sort(s.numpy())[::-1], np.linalg.svd(d, compute_uv=False), rtol=2e-3
        )

    @pytest.mark.parametrize("split", [0, 1])
    def test_hsvd_rtol_bound_holds(self, split):
        d = _RNG.standard_normal((40, 24)).astype(np.float32)
        x = ht.array(d, split=split)
        u, s, v, err = ht.linalg.hsvd_rtol(x, 0.3, compute_sv=True)
        rec = u.numpy() @ np.diag(s.numpy()) @ v.numpy().T
        rel = np.linalg.norm(d - rec) / np.linalg.norm(d)
        assert rel <= 0.3 + 1e-2, (rel, float(err))

    @pytest.mark.parametrize("split", [0, 1])
    def test_hsvd_rank_known_rank(self, split):
        from heat_tpu.utils.data.matrixgallery import random_known_rank

        data, (u_t, s_t, v_t) = random_known_rank(36, 16, 3, split=split)
        u, s, v, err = ht.linalg.hsvd_rank(data, 3, compute_sv=True)
        np.testing.assert_allclose(
            np.sort(s.numpy())[::-1], np.sort(s_t.numpy())[::-1], rtol=1e-2
        )
        assert float(err) < 1e-3


class TestSolversSweep:
    @pytest.mark.parametrize("split", [None, 0])
    @pytest.mark.parametrize("n", [8, 19])
    def test_cg_sizes(self, split, n):
        a = _RNG.standard_normal((n, n)).astype(np.float32)
        a = a @ a.T + n * np.eye(n, dtype=np.float32)
        b = _RNG.standard_normal(n).astype(np.float32)
        x = ht.linalg.cg(ht.array(a, split=split), ht.array(b), ht.zeros(n))
        np.testing.assert_allclose(x.numpy(), np.linalg.solve(a, b), atol=5e-3)

    @pytest.mark.parametrize("m", [4, 8])
    def test_lanczos_partial_krylov(self, m):
        n = 12
        a = _RNG.standard_normal((n, n)).astype(np.float64)
        a = (a + a.T) / 2
        V, T = ht.linalg.lanczos(ht.array(a, split=0, dtype=ht.float64), m)
        # V orthonormal and T tridiagonal-symmetric
        vtv = V.numpy().T @ V.numpy()
        np.testing.assert_allclose(vtv, np.eye(m), atol=1e-8)
        t = T.numpy()
        np.testing.assert_allclose(t, t.T, atol=1e-12)
        # Krylov projection: V^T A V == T
        np.testing.assert_allclose(V.numpy().T @ a @ V.numpy(), t, atol=1e-7)
