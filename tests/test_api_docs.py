"""docs/API.md must stay in sync with the actual public surface: the
test regenerates the index and diffs it against the committed file
(the analog of the reference's CI-built sphinx autosummary)."""

import importlib.util
import os

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_api_reference_is_fresh():
    spec = importlib.util.spec_from_file_location(
        "gen_api_docs", os.path.join(ROOT, "scripts", "gen_api_docs.py")
    )
    gen_api_docs = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(gen_api_docs)
    with open(os.path.join(ROOT, "docs", "API.md")) as f:
        committed = f.read()
    fresh = gen_api_docs.render()
    assert fresh == committed, (
        "docs/API.md is stale — regenerate with scripts/gen_api_docs.py"
    )
