"""docs/API.md must stay in sync with the actual public surface: the
test regenerates the index and diffs it against the committed file
(the analog of the reference's CI-built sphinx autosummary)."""

import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_api_reference_is_fresh():
    sys.path.insert(0, os.path.join(ROOT, "scripts"))
    try:
        import gen_api_docs
    finally:
        sys.path.pop(0)
    with open(os.path.join(ROOT, "docs", "API.md")) as f:
        committed = f.read()
    fresh = gen_api_docs.render()
    assert fresh == committed, (
        "docs/API.md is stale — regenerate with scripts/gen_api_docs.py"
    )
