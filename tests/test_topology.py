"""Two-tier DCN-aware topology (ISSUE 8): the ``Topology`` abstraction,
hierarchical redistribution planning, tier-priced collectives, the
slice-major TSQR grouping, the hierarchical DP wire, and rule SL107.

The contract pinned here, four ways:

1. **Topology** — ``HEAT_TPU_TOPOLOGY`` resolution (auto-on-CPU = flat,
   forced ``SxC``, product-mismatch = flat), the slice/chip subgroup
   helpers, and the edge classification.
2. **Plans** — at a tiered topology the big cross-slice moves plan
   ``hierarchical-a2a`` (intra-slice pivot + inter-slice exchange), the
   tiers are priced (DCN ≈ 8× ICI), plans that keep their flat strategy
   differ from the flat plan ONLY via the tier/topology annotations,
   and with the topology unset/flat every plan is byte-identical to the
   PR 7 era (the ci.sh auto-on-CPU parity leg diffs the full dump).
3. **Acceptance** — at the simulated 2×8 mesh the 1 GB split-1 reshape
   (its 16-divisible view) and the 1 GB resplit plan
   ``hierarchical-a2a`` with int8-encoded cross-slice bytes ≤ 1/4 of
   the flat plan's payload; the compiled HLO census equals the tiered
   plan at 2×4 (executable on the 8-device test mesh) and the executed
   result is bit-identical to the flat-topology program.
4. **Tiers elsewhere** — ring hops classify ``tier="dcn"`` (the
   ``axis_index ± 1`` wraparound crosses the slice boundary), the TSQR
   tree groups slice-major, the DP quant step decomposes hierarchically,
   and SL107 flags an undecomposed flat cross-tier collective while the
   planner-stamped programs downgrade to info.
"""

import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import heat_tpu as ht

from heat_tpu.core import _padding
from heat_tpu.core.communication import (
    DCN_BPS,
    DCN_PENALTY,
    ICI_BPS,
    Topology,
    topology_for,
)
from heat_tpu.kernels import quant
from heat_tpu.observability.hlo import _count_ops
from heat_tpu.redistribution import RedistSpec, executor, planner

from test_suites.basic_test import TestCase, env_pin

P = len(jax.devices())
BUDGET = planner.DEFAULT_BUDGET_MB << 20


def _spec(name):
    return dict(planner.golden_specs())[name]


class TestTopologyAbstraction(TestCase):
    def test_parse_and_str(self):
        t = Topology.parse("2x8")
        self.assertEqual((t.n_slices, t.chips_per_slice), (2, 8))
        self.assertEqual(str(t), "2x8")
        self.assertIsNone(Topology.parse("garbage"))
        self.assertIsNone(Topology.parse("0x8"))

    def test_subgroup_helpers(self):
        t = Topology(2, 4)
        self.assertEqual(t.chip_axis_groups(), [[0, 1, 2, 3], [4, 5, 6, 7]])
        self.assertEqual(t.slice_axis_groups(), [[0, 4], [1, 5], [2, 6], [3, 7]])
        self.assertEqual([t.slice_of(i) for i in range(8)], [0, 0, 0, 0, 1, 1, 1, 1])
        self.assertTrue(t.crosses(3, 4))
        self.assertFalse(t.crosses(0, 3))
        self.assertTrue(t.spans([0, 7]))
        self.assertFalse(t.spans([4, 5, 6, 7]))

    def test_env_resolution(self):
        with env_pin("HEAT_TPU_TOPOLOGY", "2x4"):
            t = topology_for(8)
            self.assertEqual((t.n_slices, t.chips_per_slice), (2, 4))
            self.assertTrue(t.tiered)
            # product mismatch resolves FLAT, never an unrealizable mesh
            self.assertFalse(topology_for(16).tiered)
        with env_pin("HEAT_TPU_TOPOLOGY", "flat"):
            self.assertFalse(topology_for(8).tiered)
        with env_pin("HEAT_TPU_TOPOLOGY", None):
            # auto on the CPU test mesh: no slice_index -> flat
            self.assertFalse(topology_for(P).tiered)

    def test_bandwidth_constants(self):
        self.assertEqual(DCN_PENALTY, int(ICI_BPS / DCN_BPS))
        self.assertGreaterEqual(DCN_PENALTY, 4)
        t = Topology(2, 8)
        self.assertEqual(t.bandwidth("ici"), ICI_BPS)
        self.assertEqual(t.bandwidth("dcn"), DCN_BPS)

    def test_resolve_topology_forms(self):
        self.assertIsNone(planner.resolve_topology(8, "flat"))
        self.assertEqual(planner.resolve_topology(8, "2x4"), (2, 4))
        self.assertEqual(planner.resolve_topology(8, (2, 4)), (2, 4))
        self.assertIsNone(planner.resolve_topology(8, "2x8"))  # mismatch
        with self.assertRaises(ValueError):
            planner.resolve_topology(8, "nonsense")

    def test_comm_topology_property(self):
        with env_pin("HEAT_TPU_TOPOLOGY", "2x4"):
            t = self.comm.topology
            if self.comm.size == 8:
                self.assertTrue(t.tiered)
            self.assertEqual(t.size, self.comm.size)


class TestTieredPlans(TestCase):
    """Pure-Python planner pins — no mesh needed."""

    # the golden strategies under a forced 2x4 factorization of the
    # p=8 matrix: big cross-slice moves decompose, small ones stay on
    # their (now DCN-priced) flat forms because ALPHA dominates
    TIERED_2X4_PINS = {
        "resplit_0_to_1_p8": "all-to-all",
        "resplit_chunked_2gb_p8": "hierarchical-a2a",
        "resplit_ring_8gb_p8": "hierarchical-a2a",
        "reshape_pivot_p8": "hierarchical-a2a",
        "reshape_split1_1gb_p8": "hierarchical-a2a",
        "reshape_packed_rev_p8": "hierarchical-a2a",
        "reshape_lane_1gb_p8": "hierarchical-a2a",
        "replicate_p8": "replicate",
        "reshape_gather_fallback_p8": "gather-reshape",
    }

    def test_tiered_golden_strategies(self):
        for name, want in self.TIERED_2X4_PINS.items():
            sched = planner.plan(_spec(name), BUDGET, quant="0", topology="2x4")
            self.assertEqual(sched.strategy, want, name)
            if sched.n_collectives:
                self.assertIsNotNone(sched.topology, name)
                self.assertTrue(
                    all(st.tier in ("ici", "dcn") for st in sched.steps if st.is_collective),
                    name,
                )

    def test_flat_strategy_differs_only_by_tier_annotation(self):
        """A spec that keeps its flat strategy at a tiered topology must
        serialize identically to the flat plan once the tier/topology
        keys are stripped — the tier annotation is the WHOLE diff."""
        spec = _spec("resplit_0_to_1_p8")
        flat = planner.plan(spec, BUDGET, quant="0", topology="flat")
        tiered = planner.plan(spec, BUDGET, quant="0", topology="2x4")
        self.assertNotEqual(flat.plan_id, tiered.plan_id)
        d_flat = flat.as_dict(with_plan_id=False)
        d_tiered = tiered.as_dict(with_plan_id=False)
        d_tiered.pop("topology")
        for st in d_tiered["steps"]:
            st.pop("tier", None)
        self.assertEqual(d_flat, d_tiered)

    def test_flat_topology_byte_identical_to_ambient_flat(self):
        """topology="flat" == ambient resolution on this (flat) world ==
        the pre-ISSUE-8 serialization: no tier keys, no topology key."""
        for name, spec in planner.golden_specs():
            forced = planner.plan(spec, BUDGET, quant="0", topology="flat")
            self.assertNotIn('"tier"', forced.canonical_json(), name)
            self.assertNotIn('"topology"', forced.canonical_json(), name)

    def test_hierarchical_decomposition_structure(self):
        """Each hierarchical lap is an (ici, dcn) all-to-all pair; the
        intra hop carries L(C-1)/C, the inter hop L(S-1)/S — the
        portable-redistribution factorization across tiers."""
        spec = RedistSpec.normalize((4096, 2048), "float32", 0, 1, 8)
        sched = planner.plan(spec, BUDGET, quant="0", topology="2x4")
        self.assertEqual(sched.strategy, "hierarchical-a2a")
        colls = [st for st in sched.steps if st.is_collective]
        self.assertEqual([st.tier for st in colls], ["ici", "dcn"])
        L = 4096 * 2048 * 4 // 8
        self.assertEqual(colls[0].bytes_moved, L * 3 // 4)  # (C-1)/C
        self.assertEqual(colls[1].bytes_moved, L * 1 // 2)  # (S-1)/S
        tb = sched.tier_bytes()
        self.assertEqual(tb, {"ici": L * 3 // 4, "dcn": L * 1 // 2})

    def test_tier_pricing_beats_flat_on_big_moves(self):
        """The cost model's point: at 2x4 the hierarchical plan's
        modeled byte-equivalents undercut the slice-spanning flat form
        (whose every byte pays the DCN penalty)."""
        spec = _spec("resplit_chunked_2gb_p8")
        hier = planner.plan(spec, BUDGET, quant="0", topology="2x4")
        self.assertEqual(hier.strategy, "hierarchical-a2a")
        flat_cost = planner._cost(
            planner._tier_flat(
                planner.plan(spec, BUDGET, quant="0", topology="flat"), (2, 4)
            )
        )
        self.assertLess(planner._cost(hier), flat_cost)

    def test_tiered_overlap_group_arithmetic(self):
        """A tiered chunk group prices a pipelined lap at
        max(ici, dcn*penalty, copy) with the first wires / last copy
        exposed (the ISSUE 8 extension of the max(wire, copy) model)."""
        spec = _spec("resplit_chunked_2gb_p8")
        sched = planner.plan(spec, BUDGET, quant="0", topology="2x4")
        self.assertIsNotNone(sched.overlap)
        for g in sched.overlap["groups"]:
            self.assertIn("ici_bytes", g)
            pen = g["dcn_penalty"]
            wi = g["ici_bytes"] // g["laps"]
            wd = g["dcn_bytes"] * pen // g["laps"]
            c = g["copy_bytes"] // g["laps"]
            self.assertEqual(
                g["critical_path_bytes"],
                wi + wd + c + (g["laps"] - 1) * max(wi, wd, c),
            )
            self.assertEqual(g["wire_bytes"], g["ici_bytes"] + g["dcn_bytes"] * pen)
        self.assertEqual(DCN_PENALTY, sched.topology["dcn_penalty"])

    def test_ring_hops_tier_classified(self):
        """Satellite: the ring's ``axis_index ± 1`` wraparound crosses
        the slice boundary at any tiered factorization — every hop is
        classified (and priced) ``tier="dcn"``."""
        spec = RedistSpec.normalize((4096, 2048), "float32", 0, 1, 8)
        sched = planner.plan(spec, 1 << 20, quant="0", topology="2x4")
        if sched.strategy != "ring":  # the race is budget-dependent
            ring = [
                c for c in planner._resplit_candidates(spec, 1 << 20, (2, 4))
                if c.strategy == "ring"
            ][0]
            sched = ring
        hops = [st for st in sched.steps if st.kind == "ppermute"]
        self.assertTrue(hops)
        for st in hops:
            self.assertEqual(st.tier, "dcn")

    def test_describe_renders_tiers(self):
        spec = _spec("resplit_chunked_2gb_p8")
        text = planner.plan(spec, BUDGET, quant="0", topology="2x4").describe()
        self.assertIn("tier=ici", text)
        self.assertIn("tier=dcn", text)
        self.assertIn("topology: 2x4 two-tier", text)
        self.assertIn("model=max(ici", text)

    def test_quant_targets_the_dcn_hop_only(self):
        """ISSUE 8 codec policy: in a hierarchical plan the inter-slice
        exchange is the FIRST (and only) group the wire codec targets —
        the ICI pivot ships exact, and the DCN hop's encoded bytes come
        in at the int8 ratio."""
        spec = RedistSpec.normalize((4096, 2048), "float32", 0, 1, 8)
        plain = planner.plan(spec, BUDGET, quant="0", topology="2x4")
        q = planner.plan(spec, BUDGET, quant="int8", topology="2x4")
        self.assertIsNotNone(q.quant)
        self.assertEqual(q.collective_counts(), plain.collective_counts())
        self.assertEqual(q.tier_bytes()["ici"], plain.tier_bytes()["ici"])
        self.assertLessEqual(
            q.tier_bytes()["dcn"], 0.26 * plain.tier_bytes()["dcn"]
        )
        kinds = [st.kind for st in q.steps]
        self.assertIn("quantize", kinds)
        # the quantize step sits right before the dcn hop, not the ici one
        qi = kinds.index("quantize")
        self.assertEqual(q.steps[qi + 1].tier, "dcn")

    def test_plan_cache_keyed_on_topology(self):
        spec = RedistSpec.normalize((4096, 2048), "float32", 0, 1, 8)
        a = planner.plan(spec, BUDGET, quant="0", topology="flat")
        b = planner.plan(spec, BUDGET, quant="0", topology="2x4")
        self.assertNotEqual(a.plan_id, b.plan_id)
        # and a repeat serve is the cached object
        self.assertIs(planner.plan(spec, BUDGET, quant="0", topology="2x4"), b)

    def test_tier_time_model(self):
        spec = RedistSpec.normalize((4096, 2048), "float32", 0, 1, 8)
        sched = planner.plan(spec, BUDGET, quant="0", topology="2x4")
        m = planner.tier_time_model(sched)
        tb = sched.tier_bytes()
        self.assertEqual(m["ici_bytes"], tb["ici"])
        self.assertEqual(m["dcn_bytes"], tb["dcn"])
        self.assertAlmostEqual(m["total_s"], tb["ici"] / ICI_BPS + tb["dcn"] / DCN_BPS)


class TestAcceptance2x8(TestCase):
    """The ISSUE 8 acceptance pins at the simulated 2×8 (16-chip,
    two-slice) mesh — pure planner arithmetic, no devices."""

    def test_1gb_reshape_plans_hierarchical_with_quarter_dcn_bytes(self):
        spec = _spec("reshape_split1_1gb_p16")
        self.assertEqual(spec.logical_bytes, 10**9)
        flat = planner.plan(spec, BUDGET, quant="0", topology="flat")
        hier = planner.plan(spec, BUDGET, quant="int8", topology="2x8")
        self.assertEqual(hier.strategy, "hierarchical-a2a")
        # a topology-blind plan's collectives all span slices: its WHOLE
        # payload rides DCN. The hierarchical plan's int8-encoded
        # inter-slice exchange ships <= 1/4 of that.
        self.assertLessEqual(
            hier.tier_bytes()["dcn"], 0.25 * flat.bytes_moved,
            (hier.tier_bytes(), flat.bytes_moved),
        )
        # raw (codec off) the DCN hop still ships only the (S-1)/S
        # crossing fraction — ~0.53 of the flat payload
        raw = planner.plan(spec, BUDGET, quant="0", topology="2x8")
        self.assertLessEqual(raw.tier_bytes()["dcn"], 0.6 * flat.bytes_moved)
        self.assertTrue(hier.within_budget)

    def test_1gb_resplit_plans_hierarchical_with_quarter_dcn_bytes(self):
        spec = _spec("resplit_1gb_p16")
        flat = planner.plan(spec, BUDGET, quant="0", topology="flat")
        hier = planner.plan(spec, BUDGET, quant="int8", topology="2x8")
        self.assertEqual(hier.strategy, "hierarchical-a2a")
        self.assertLessEqual(hier.tier_bytes()["dcn"], 0.25 * flat.bytes_moved)

    def test_bench_row_models_at_least_2x(self):
        """Satellite floor: the analytic 2x8 rows model >= 2x
        hierarchical+int8 vs flat+f32."""
        spec = _spec("resplit_1gb_p16")
        flat = planner.plan(spec, BUDGET, quant="0", topology="flat")
        hier = planner.plan(spec, BUDGET, quant="int8", topology="2x8")
        t_flat = flat.bytes_moved / DCN_BPS
        m = planner.tier_time_model(hier)
        self.assertGreaterEqual(t_flat / m["total_s"], 2.0)
        dp = quant.dp_step_model_2tier(400_000_000, compute_s=1e-3)
        self.assertTrue(dp["dcn_bound"])
        self.assertGreaterEqual(dp["model_speedup"], 2.0)
        # compute-bound layers gain exactly nothing — max(), not magic
        dp2 = quant.dp_step_model_2tier(1_000_000, compute_s=1e-2)
        self.assertEqual(dp2["model_speedup"], 1.0)

    def test_tsqr_grouping_slice_major(self):
        from heat_tpu.core.linalg.qr import _tsqr_grouping

        self.assertEqual(_tsqr_grouping(16, (2, 8)), 8)
        self.assertEqual(_tsqr_grouping(8, (2, 4)), 4)
        # flat keeps the pre-ISSUE-8 rule verbatim
        self.assertEqual(_tsqr_grouping(8, None), 1)
        self.assertEqual(_tsqr_grouping(16, None), 4)
        # degenerate factorizations fall back flat
        self.assertEqual(_tsqr_grouping(8, (8, 1)), 1)


@pytest.mark.skipif(P != 8, reason="executable tier pins are 8-mesh-shaped")
class TestTieredExecutor(TestCase):
    """The 2x4 factorization of the REAL 8-device test mesh: compiled
    census == tiered plan, executed result bit-identical to the
    flat-topology program (the acceptance criteria, executable here)."""

    def _census_of(self, prog, spec):
        phys = _padding.phys_shape(spec.gshape, spec.src_split, spec.mesh_size)
        arg = jax.ShapeDtypeStruct(
            phys,
            np.dtype(spec.dtype),
            sharding=self.comm.sharding(len(phys), spec.src_split),
        )
        text = prog.lower(arg).compile().as_text()
        return {k: v for k, v in _count_ops(text).items() if v}

    def test_census_matches_tiered_plan(self):
        spec = RedistSpec.normalize((4096, 2048), "float32", 0, 1, 8)
        sched = planner.plan(spec, BUDGET, quant="0", topology="2x4")
        self.assertEqual(sched.strategy, "hierarchical-a2a")
        for pipelined in (False, True):
            prog = executor._move_program(
                self.comm, spec, BUDGET, pipelined, None, (2, 4)
            )
            self.assertEqual(self._census_of(prog, spec), sched.collective_counts())

    def test_executed_bit_identical_to_flat_program(self):
        rng = np.random.default_rng(0)
        cases = [
            RedistSpec.normalize((4096, 2048), "float32", 0, 1, 8),
            RedistSpec.normalize(
                (40960, 40), "float32", 1, 1, 8, reshape_to=(20480, 80)
            ),
        ]
        for spec in cases:
            hier = planner.plan(spec, BUDGET, quant="0", topology="2x4")
            flat = planner.plan(spec, BUDGET, quant="0", topology="flat")
            self.assertEqual(hier.strategy, "hierarchical-a2a", spec)
            oracle = rng.standard_normal(spec.gshape).astype(np.float32)
            x = ht.array(oracle, split=spec.src_split)
            y_hier = executor.execute(self.comm, x._phys, spec, hier)
            y_flat = executor.execute(self.comm, x._phys, spec, flat)
            np.testing.assert_array_equal(np.asarray(y_hier), np.asarray(y_flat))
            logical = np.asarray(
                _padding.unpad(y_hier, spec.out_shape, spec.dst_split)
            )
            np.testing.assert_array_equal(
                logical, oracle.reshape(spec.out_shape), str(spec)
            )

    def test_quantized_dcn_hop_within_tolerance(self):
        spec = RedistSpec.normalize((4096, 2048), "float32", 0, 1, 8)
        q = planner.plan(spec, BUDGET, quant="int8", topology="2x4")
        rng = np.random.default_rng(1)
        oracle = rng.standard_normal((4096, 2048)).astype(np.float32)
        x = ht.array(oracle, split=0)
        y = executor.execute(self.comm, x._phys, spec, q)
        got = np.asarray(_padding.unpad(y, (4096, 2048), 1))
        err = np.abs(got - oracle).max()
        self.assertGreater(err, 0.0)  # the DCN hop really encoded
        self.assertLessEqual(err, quant.tolerance("int8") * np.abs(oracle).max())

    def test_seq_vs_pipelined_bit_identical(self):
        spec = RedistSpec.normalize((4096, 2048), "float32", 0, 1, 8)
        sched = planner.plan(spec, 4 << 20, quant="0", topology="2x4")
        self.assertTrue(any(st.overlap for st in sched.steps))
        oracle = np.arange(4096 * 2048, dtype=np.float32).reshape(4096, 2048)
        x = ht.array(oracle, split=0)
        outs = {}
        for mode in ("0", "1"):
            with env_pin(planner.OVERLAP_ENV, mode):
                outs[mode] = np.asarray(
                    executor.execute(self.comm, x._phys, spec, sched)
                )
        np.testing.assert_array_equal(outs["0"], outs["1"])

    def test_hierarchical_allreduce_sum_matches_psum(self):
        from heat_tpu.core._jax_compat import shard_map
        from jax.sharding import PartitionSpec as PS

        rng = np.random.default_rng(2)
        h = rng.standard_normal((8, 5000)).astype(np.float32)
        comm = self.comm

        def body(hl):
            out, resid = quant.hierarchical_allreduce_sum(
                hl[0], comm.axis_name, 2, 4, "int8"
            )
            return out[None], resid[None]

        f = shard_map(
            body,
            mesh=comm.mesh,
            in_specs=(PS(comm.axis_name, None),),
            out_specs=(PS(comm.axis_name, None), PS(comm.axis_name, None)),
            check_vma=False,
        )
        out, resid = f(comm.shard(jnp.asarray(h), 0))
        want = h.sum(axis=0)
        got = np.asarray(out)
        for d in range(8):
            err = np.abs(got[d] - want).max()
            self.assertLessEqual(err, quant.tolerance("int8") * np.abs(want).max() * 2)
        # the residuals reconstruct the compression error: sum of all
        # carries == exact - decoded (each chip position owns a block)
        approx = got[0] + np.asarray(resid).sum(axis=0)
        np.testing.assert_allclose(approx, want, rtol=1e-5, atol=1e-4)


class TestShardlintSL107(TestCase):
    @pytest.mark.skipif(P % 2, reason="an odd mesh has no 2-slice factorization")
    def test_fixture_trips_at_tiered_topology_only(self):
        sys.path.insert(0, "tests")
        import analysis_fixtures as fx

        x = ht.zeros((4096, 2048), split=0)
        rep_flat = ht.analysis.check(fx.flat_dcn_a2a_program, x, topology="flat")
        self.assertFalse([f for f in rep_flat.findings if f.rule == "SL107"])
        rep = ht.analysis.check(fx.flat_dcn_a2a_program, x, topology=f"2x{P // 2}")
        sl107 = [f for f in rep.findings if f.rule == "SL107"]
        self.assertTrue(sl107)
        for f in sl107:
            self.assertIn(f.severity, ("warning", "error"))
            self.assertIn("cross-tier", f.message)

    @pytest.mark.skipif(P < 8, reason="hierarchical plans need the 8-mesh")
    def test_planner_stamped_program_downgrades_to_info(self):
        x = ht.zeros((4096, 2048), split=0)
        with env_pin("HEAT_TPU_TOPOLOGY", "2x4"):
            planner.clear_plan_cache()
            try:
                sched = ht.redistribution.explain(x, 1)
                self.assertEqual(sched.strategy, "hierarchical-a2a")
                rep = ht.analysis.check(lambda v: v.resplit(1), x)
                sl107 = [f for f in rep.findings if f.rule == "SL107"]
                self.assertTrue(sl107)
                for f in sl107:
                    self.assertEqual(f.severity, "info")
                    self.assertIn(sched.plan_id, f.message)
                self.assertTrue(rep.ok)
            finally:
                planner.clear_plan_cache()

    def test_encoded_dp_wire_downgrades_to_info(self):
        """The hierarchical DP gradient wire's inter-slice gather runs
        under the wire-codec stamp: SL107 reports it as the sanctioned
        encoded cross-tier exchange."""
        from heat_tpu.analysis.boundaries import wire_codec_stamped

        self.assertTrue(wire_codec_stamped("transpose/wire_codec_int8/all_gather"))


if __name__ == "__main__":
    import unittest

    unittest.main()
