"""Two-level TSQR (r5): at mesh widths ≥ 16 the R-factor merge runs as a
group tree — all-gather WITHIN each √p-wide group, merge, all-gather the
group R's ACROSS groups, merge — cutting ICI bytes and replicated merge
FLOPs from p·K² to (s + p/s)·K² (docs/PERF.md named the flat merge's
(p·r)² growth as the mesh-width wall; this is the promised fix).

The suite's 8-device mesh keeps the flat single-gather schedule (its HLO
contract is pinned elsewhere), so the two-level path is exercised in a
SUBPROCESS forcing 16 host devices — the same pattern test_x64_policy
uses for the degraded mode."""

import os
import subprocess
import sys

_WORKER = r"""
import jax
jax.config.update('jax_platforms', 'cpu')
import numpy as np
import jax.numpy as jnp
import heat_tpu as ht
from heat_tpu.core.linalg.qr import _tsqr_fn, _tsqr_group_size

comm = ht.get_comm()
assert comm.size == 16, comm.size
assert _tsqr_group_size(16) == 4

rng = np.random.default_rng(0)
# QR parity incl. uneven (padded) rows
for m, n in ((16 * 40, 24), (16 * 33 + 5, 16), (16 * 8, 8)):
    a = rng.standard_normal((m, n)).astype(np.float32)
    q, r = ht.linalg.qr(ht.array(a, split=0))
    qn, rn = q.numpy(), r.numpy()
    assert np.allclose(qn @ rn, a, atol=1e-4), (m, n)
    assert np.allclose(qn.T @ qn, np.eye(qn.shape[1]), atol=1e-4), (m, n, 'orth')
    assert np.allclose(np.triu(rn), rn, atol=1e-5), (m, n, 'upper')

# HLO contract: exactly TWO all-gathers (one per tree level), no other
# collectives — and each carries s*K^2 / (p/s)*K^2, never the operand
fn = _tsqr_fn(comm.mesh, comm.axis_name, 40, 24, 'float32', True)
phys = comm.shard(jnp.ones((16 * 40, 24), jnp.float32), 0)
txt = fn.lower(phys).compile().as_text()
ag_lines = [l for l in txt.splitlines() if ' all-gather(' in l or 'all-gather-start(' in l]
assert len(ag_lines) == 2, len(ag_lines)
assert ' all-to-all(' not in txt
assert ' collective-permute(' not in txt
# the gathers carry s*K^2 and (p/s)*K^2 floats — never the operand
import re
K, s_w, G_w = 24, 4, 4
sizes = sorted(
    int(np.prod([int(d) for d in re.search(r'f32\[([\d,]+)\]', l).group(1).split(',')]))
    for l in ag_lines
)
assert sizes == sorted([s_w * K * K, G_w * K * K]), sizes

# hSVD merges through the same TSQR: the tree must be invisible to it
lr = (rng.standard_normal((16 * 24, 6)) @ rng.standard_normal((6, 128))).astype(np.float32)
u, s, v, err = ht.linalg.hsvd_rank(ht.array(lr, split=0), 8, compute_sv=True)
rec = (u.numpy() * s.numpy()) @ v.numpy().T
assert np.linalg.norm(rec - lr) / np.linalg.norm(lr) < 1e-3

# collective-matmul form (ISSUE 6): BOTH tree levels decompose into
# grouped ppermute rings — (s-1) + (G-1) = 6 hops, zero all-gathers —
# and Q/R stay bit-identical to the barrier form (the rings assemble
# the identical stacked R arrays)
fn_ring = _tsqr_fn(comm.mesh, comm.axis_name, 40, 24, 'float32', True, ring=True)
txt_r = fn_ring.lower(phys).compile().as_text()
assert ' all-gather(' not in txt_r and 'all-gather-start(' not in txt_r
n_cp = txt_r.count(' collective-permute(') + txt_r.count('collective-permute-start(')
assert n_cp == (s_w - 1) + (G_w - 1), n_cp
a = rng.standard_normal((16 * 40, 24)).astype(np.float32)
pa = comm.shard(jnp.asarray(a), 0)
qg, rg = fn(pa)
qr_, rr_ = fn_ring(pa)
assert (np.asarray(qg) == np.asarray(qr_)).all()
assert (np.asarray(rg) == np.asarray(rr_)).all()

print('TSQR_TWO_LEVEL_OK')
"""


def test_two_level_tsqr_subprocess():
    env = {k: v for k, v in os.environ.items() if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=16"
    out = subprocess.run(
        [sys.executable, "-c", _WORKER], capture_output=True, text=True, env=env,
        timeout=600,
    )
    assert out.returncode == 0, out.stdout[-2000:] + out.stderr[-2000:]
    assert "TSQR_TWO_LEVEL_OK" in out.stdout
