"""Worker program for the multi-process tests (tests/test_multiprocess.py).

Each process hosts ``local_devices`` CPU devices; ``init_distributed``
wires the world to nprocs x local_devices devices spanning all processes.
The import deliberately happens BEFORE init_distributed — the lazy device
registry / world singletons exist precisely so that ordering works.

Covers every shard_map primitive family cross-process (VERDICT r2 #6):
factories/reductions, hyperslab HDF5 ingest + single-writer saves,
byte-range CSV ingest, the odd-even AND columnsort networks and percentile on top of
it, ring attention, a KMeans fit, and DP + DASO training steps.
"""

import os
import sys

proc_id = int(sys.argv[1])
nprocs = int(sys.argv[2])
port = sys.argv[3]
h5path = sys.argv[4]
tmpdir = sys.argv[5]
local_devices = int(sys.argv[6]) if len(sys.argv) > 6 else 2

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = f"--xla_force_host_platform_device_count={local_devices}"
import jax

jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import heat_tpu as ht

ht.core.communication.init_distributed(
    coordinator_address=f"localhost:{port}", num_processes=nprocs, process_id=proc_id
)

import numpy as np

comm = ht.get_comm()
assert comm.size == local_devices * nprocs, comm.size
assert jax.process_count() == nprocs

ref = np.arange(13 * 3, dtype=np.float32).reshape(13, 3)

# factories + reduction over the multi-host mesh
x0 = ht.arange(13, split=0, dtype=ht.float32)
assert float(ht.sum(x0)) == 78.0

# per-host hyperslab HDF5 ingest (each process reads only addressable slabs)
x = ht.load_hdf5(h5path, "d", dtype=ht.float32, split=0)
np.testing.assert_allclose(np.asarray(x.numpy()), ref)  # cross-process allgather

# elementwise chain + reduction
y = ht.exp(ht.sin(x) * 0.5)
np.testing.assert_allclose(np.asarray(y.numpy()), np.exp(np.sin(ref) * 0.5), rtol=1e-5)
np.testing.assert_allclose(float(ht.sum(x)), ref.sum(), rtol=1e-5)

# shard_map collectives across processes: gather-free distributed sort
sv, si = ht.sort(ht.array(np.asarray(ref[:, 0].copy()), split=0))
np.testing.assert_allclose(np.asarray(sv.numpy()), np.sort(ref[:, 0]))

# columnsort (r5): shard size large enough for the O(1)-round program
# (B >= 2(p-1)^2, p | B) — its tiled all_to_alls must work across REAL
# process boundaries, incl. the pre-sorted input a splitter scheme
# would degenerate on
from heat_tpu.core.parallel import _columnsort_applicable

_cs_B = 4 * comm.size * comm.size
_cs_big = np.sort(
    np.random.default_rng(11).standard_normal(_cs_B * comm.size).astype(np.float32)
)
if _columnsort_applicable(comm.size, _cs_B):
    _cs_v, _cs_i = ht.sort(ht.array(_cs_big, split=0))
    np.testing.assert_array_equal(np.asarray(_cs_v.numpy()), _cs_big)
    np.testing.assert_array_equal(
        np.asarray(_cs_i.numpy()), np.argsort(_cs_big, kind="stable")
    )

# percentile rides the values-only sort network
med = ht.percentile(ht.array(np.asarray(ref[:, 0].copy()), split=0), 50.0)
np.testing.assert_allclose(np.asarray(med.numpy()), np.percentile(ref[:, 0], 50.0), rtol=1e-6)

# sharded matmul spanning both hosts
m = ht.matmul(x, ht.array(ref.T, split=1))
np.testing.assert_allclose(np.asarray(m.numpy()), ref @ ref.T, rtol=1e-4, atol=1e-4)

# multi-process saves are single-writer (collective allgather, process 0
# writes, cross-process sync): HDF5 and CSV round-trips
if ht.io.supports_hdf5():
    h5out = os.path.join(tmpdir, "mp_out.h5")
    ht.io.save_hdf5(x, h5out, "d")
    back = ht.load_hdf5(h5out, "d", dtype=ht.float32, split=0)
    np.testing.assert_allclose(np.asarray(back.numpy()), ref)

csv_out = os.path.join(tmpdir, "mp_out.csv")
ht.io.save_csv(x, csv_out)
# byte-range parallel ingest: every host scans only its range
xc = ht.load_csv(csv_out, split=0, dtype=ht.float32)
assert xc.split == 0 and xc.shape == ref.shape, (xc.shape, xc.split)
np.testing.assert_allclose(np.asarray(xc.numpy()), ref, rtol=1e-6)

# ring attention: K/V circulate the full cross-process ring
S, D = 4 * comm.size, 4
rng = np.random.default_rng(3)
qkv_np = rng.standard_normal((3, 1, 2, S, D)).astype(np.float32)
qkv = [ht.array(qkv_np[i], split=2) for i in range(3)]
out = ht.nn.ring_attention(*qkv, causal=True)
scores = qkv_np[0] @ qkv_np[1].transpose(0, 1, 3, 2) / np.sqrt(D)
mask = np.tril(np.ones((S, S), dtype=bool))
scores = np.where(mask, scores, -np.inf)
p = np.exp(scores - scores.max(-1, keepdims=True))
p = p / p.sum(-1, keepdims=True)
oracle = p @ qkv_np[2]
np.testing.assert_allclose(np.asarray(out.numpy()), oracle, rtol=1e-4, atol=1e-5)

# estimator fit across processes
blob = np.concatenate(
    [rng.standard_normal((32, 3)) + 4.0, rng.standard_normal((32, 3)) - 4.0]
).astype(np.float32)
km = ht.cluster.KMeans(n_clusters=2, init="kmeans++", max_iter=10, random_state=0)
km.fit(ht.array(blob, split=0))
cents = np.asarray(km.cluster_centers_.numpy())
assert cents.shape == (2, 3) and np.isfinite(cents).all()
assert abs(abs(cents[:, 0]).mean() - 4.0) < 1.5, cents

# data-parallel training step across hosts
from heat_tpu import nn, optim

dp = nn.DataParallel(nn.Sequential(nn.Linear(3, 8), nn.ReLU(), nn.Linear(8, 2)), key=0)
opt = optim.DataParallelOptimizer(optim.SGD(lr=0.1), dp)
yb = ht.array((ref[:, 0] > 6).astype(np.int32), split=0)
l0 = float(opt.step(x, yb))
l1 = float(opt.step(x, yb))
assert np.isfinite(l0) and l1 < l0, (l0, l1)

# DASO: staggered two-level sync on a ("node", "local") mesh across the
# real process boundary
if comm.size % 2 == 0:
    xd = ht.array(rng.standard_normal((16 * comm.size, 3)).astype(np.float32), split=0)
    yd = ht.array((np.asarray(xd.numpy())[:, 0] > 0).astype(np.int32), split=0)
    dp2 = nn.DataParallel(nn.Sequential(nn.Linear(3, 8), nn.ReLU(), nn.Linear(8, 2)), key=1)
    daso = optim.DASO(optim.SGD(lr=0.05), dp2, n_nodes=2, global_skip=2)
    dl = [float(daso.step(xd, yd)) for _ in range(4)]
    assert all(np.isfinite(v) for v in dl), dl

# gather-free data-dependent-shape ops across the process boundary
uq = ht.unique(ht.array(np.array([3.0, 1.0, 3.0, 7.0, 1.0, 0.0, 7.0, 5.0], np.float32), split=0))
np.testing.assert_array_equal(np.asarray(uq.numpy()), [0.0, 1.0, 3.0, 5.0, 7.0])
hm = ht.array(ref[:, 0].copy(), split=0)
sel = hm[hm > 17.0]
np.testing.assert_allclose(np.asarray(sel.numpy()), ref[:, 0][ref[:, 0] > 17.0])
nz = ht.nonzero(ht.array((ref % 5.0 == 0).astype(np.float32), split=0))
np.testing.assert_array_equal(np.asarray(nz.numpy()), np.stack(np.nonzero(ref % 5.0 == 0), axis=1))

# MPI_SELF must resolve to THIS process's device (jax.devices()[0]
# belongs to process 0; using it on process 1 would be non-addressable)
self_comm = ht.MPI_SELF
assert self_comm.size == 1
assert self_comm.devices[0].process_index == jax.process_index(), self_comm.devices
z = ht.arange(5, split=0, comm=self_comm)
assert float(ht.sum(z)) == 10.0

print(f"[p{proc_id}] MULTIHOST_OK", flush=True)
