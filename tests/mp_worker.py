"""Worker program for the multi-process tests (tests/test_multiprocess.py).

Each process: 2 local CPU devices; ``init_distributed`` wires the world to
2 processes x 2 devices = a 4-device mesh spanning both. The import
deliberately happens BEFORE init_distributed — the lazy device registry /
world singletons exist precisely so that ordering works.
"""

import os
import sys

proc_id = int(sys.argv[1])
nprocs = int(sys.argv[2])
port = sys.argv[3]
h5path = sys.argv[4]

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
import jax

jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import heat_tpu as ht

ht.core.communication.init_distributed(
    coordinator_address=f"localhost:{port}", num_processes=nprocs, process_id=proc_id
)

import numpy as np

comm = ht.get_comm()
assert comm.size == 2 * nprocs, comm.size
assert jax.process_count() == nprocs

ref = np.arange(13 * 3, dtype=np.float32).reshape(13, 3)

# factories + reduction over the multi-host mesh
x0 = ht.arange(13, split=0, dtype=ht.float32)
assert float(ht.sum(x0)) == 78.0

# per-host hyperslab HDF5 ingest (each process reads only addressable slabs)
x = ht.load_hdf5(h5path, "d", dtype=ht.float32, split=0)
np.testing.assert_allclose(np.asarray(x.numpy()), ref)  # cross-process allgather

# elementwise chain + reduction
y = ht.exp(ht.sin(x) * 0.5)
np.testing.assert_allclose(np.asarray(y.numpy()), np.exp(np.sin(ref) * 0.5), rtol=1e-5)
np.testing.assert_allclose(float(ht.sum(x)), ref.sum(), rtol=1e-5)

# shard_map collectives across processes: gather-free distributed sort
sv, si = ht.sort(ht.array(np.asarray(ref[:, 0].copy()), split=0))
np.testing.assert_allclose(np.asarray(sv.numpy()), np.sort(ref[:, 0]))

# sharded matmul spanning both hosts
m = ht.matmul(x, ht.array(ref.T, split=1))
np.testing.assert_allclose(np.asarray(m.numpy()), ref @ ref.T, rtol=1e-4, atol=1e-4)

# data-parallel training step across hosts
from heat_tpu import nn, optim

dp = nn.DataParallel(nn.Sequential(nn.Linear(3, 8), nn.ReLU(), nn.Linear(8, 2)), key=0)
opt = optim.DataParallelOptimizer(optim.SGD(lr=0.1), dp)
yb = ht.array((ref[:, 0] > 6).astype(np.int32), split=0)
l0 = float(opt.step(x, yb))
l1 = float(opt.step(x, yb))
assert np.isfinite(l0) and l1 < l0, (l0, l1)

# MPI_SELF must resolve to THIS process's device (jax.devices()[0]
# belongs to process 0; using it on process 1 would be non-addressable)
self_comm = ht.MPI_SELF
assert self_comm.size == 1
assert self_comm.devices[0].process_index == jax.process_index(), self_comm.devices
z = ht.arange(5, split=0, comm=self_comm)
assert float(ht.sum(z)) == 10.0

print(f"[p{proc_id}] MULTIHOST_OK", flush=True)
