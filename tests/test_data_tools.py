"""Data-tools tests: Dataset/DataLoader, global shuffle, PartialH5Dataset
streaming, MNIST IDX reader, vision transforms. Mirrors the reference's
utils/data usage (datatools feeding the DL training loop)."""

import gzip
import os
import struct

import numpy as np
import pytest

import jax

import heat_tpu as ht
from heat_tpu import nn as htnn
from heat_tpu import optim as htoptim
from heat_tpu.utils.data import (
    DataLoader,
    Dataset,
    MNISTDataset,
    PartialH5Dataset,
    dataset_shuffle,
)
from heat_tpu.utils import vision_transforms


class TestDatasetDataLoader:
    def _data(self, n=96, d=6):
        x = ht.arange(n * d, dtype=ht.float32, split=0).reshape((n, d))
        y = ht.arange(n, dtype=ht.int32, split=0)
        return x, y

    def test_batches_are_sharded_slices(self):
        x, y = self._data()
        loader = DataLoader(Dataset(x, targets=y), batch_size=32)
        batches = list(loader)
        assert len(batches) == 3 == len(loader)
        xb, yb = batches[1]
        assert xb.shape == (32, 6)
        assert xb.split == 0
        np.testing.assert_array_equal(yb.numpy(), np.arange(32, 64))

    def test_drop_last(self):
        x, _ = self._data(n=100)
        assert len(DataLoader(Dataset(x), batch_size=32, drop_last=True)) == 3
        loader = DataLoader(Dataset(x), batch_size=32, drop_last=False)
        assert len(loader) == 4
        assert list(loader)[-1].shape == (4, 6)

    def test_shuffle_preserves_pairing_and_set(self):
        x, y = self._data()
        ds = Dataset(x, targets=y)
        ht.random.seed(5)
        ds.Shuffle()
        xs, ys = ds.htdata.numpy(), ds.httargets.numpy()
        assert not np.array_equal(ys, np.arange(96))  # actually permuted
        assert set(ys.tolist()) == set(range(96))     # a permutation
        # pairing intact: row i of x must still be the block of label y_i
        np.testing.assert_array_equal(xs, (ys[:, None] * 6 + np.arange(6)).astype(np.float32))

    def test_shuffle_uneven_keeps_pad_clean(self):
        n = 101  # pads to 104 on 8 devices
        x = ht.arange(n, dtype=ht.float32, split=0)
        ds = Dataset(x)
        ds.Shuffle()
        phys = np.asarray(jax.device_get(ds.htdata._phys))
        np.testing.assert_array_equal(phys[n:], 0.0)
        assert set(ds.htdata.numpy().tolist()) == set(float(i) for i in range(n))

    def test_shuffle_with_replicated_targets_uneven(self):
        """Attrs with different splits (hence pad extents) must shuffle
        with the same logical permutation."""
        n = 101
        x = ht.arange(n, dtype=ht.float32, split=0)
        y = ht.arange(n, dtype=ht.int32, split=None)  # replicated: no pad
        ds = Dataset(x, targets=y)
        ht.random.seed(9)
        ds.Shuffle()
        np.testing.assert_array_equal(ds.htdata.numpy().astype(np.int32), ds.httargets.numpy())
        assert set(ds.httargets.numpy().tolist()) == set(range(n))

    def test_loader_shuffles_between_epochs(self):
        x, y = self._data()
        loader = DataLoader(Dataset(x, targets=y), batch_size=96, shuffle=True)
        ht.random.seed(1)
        (x1, y1) = next(iter(loader))
        (x2, y2) = next(iter(loader))
        assert not np.array_equal(y1.numpy(), y2.numpy())

    def test_test_set_never_shuffles(self):
        x, y = self._data()
        loader = DataLoader(Dataset(x, targets=y, test_set=True), batch_size=96, shuffle=True)
        (x1, y1) = next(iter(loader))
        np.testing.assert_array_equal(y1.numpy(), np.arange(96))

    def test_end_to_end_training(self):
        """BASELINE config #5 shape: DataLoader feeding the DP optimizer."""
        rng = np.random.default_rng(0)
        w = rng.standard_normal((8, 3)).astype(np.float32)
        x_np = rng.standard_normal((192, 8)).astype(np.float32)
        y_np = np.argmax(x_np @ w, axis=1).astype(np.int32)
        ds = Dataset(ht.array(x_np, split=0), targets=ht.array(y_np, split=0))
        loader = DataLoader(ds, batch_size=48, shuffle=True)
        dp = htnn.DataParallel(htnn.Sequential(htnn.Linear(8, 32), htnn.ReLU(), htnn.Linear(32, 3)), key=1)
        opt = htoptim.DataParallelOptimizer(htoptim.Adam(lr=0.02), dp)
        first = last = None
        for epoch in range(15):
            for xb, yb in loader:
                loss = float(opt.step(xb, yb))
                first = loss if first is None else first
                last = loss
        assert last < 0.5 * first, (first, last)


class TestPartialH5:
    @pytest.fixture
    def h5file(self, tmp_path):
        import h5py

        path = os.path.join(str(tmp_path), "stream.h5")
        rng = np.random.default_rng(0)
        data = rng.standard_normal((1000, 4)).astype(np.float32)
        labels = np.arange(1000, dtype=np.int32)
        with h5py.File(path, "w") as f:
            f.create_dataset("data", data=data)
            f.create_dataset("labels", data=labels)
        return path, data, labels

    def test_streams_all_batches(self, h5file):
        path, data, labels = h5file
        ds = PartialH5Dataset(path, ["data", "labels"], batch_size=100, initial_load=256)
        seen = []
        for xb, yb in ds:
            assert xb.shape == (100, 4)
            assert xb.split == 0
            seen.append(yb.numpy())
        seen = np.concatenate(seen)
        # chunk tails < batch are dropped (256 % 100 = 56 per chunk)
        assert len(seen) == 800
        assert len(np.unique(seen)) == len(seen)

    def test_single_dataset_name(self, h5file):
        path, data, _ = h5file
        ds = PartialH5Dataset(path, "data", batch_size=250, initial_load=500)
        batches = [b for b in ds]
        assert len(batches) == 4
        np.testing.assert_allclose(batches[0].numpy(), data[:250], rtol=1e-6)

    def test_len_and_mismatched_datasets(self, tmp_path):
        import h5py

        path = os.path.join(str(tmp_path), "bad.h5")
        with h5py.File(path, "w") as f:
            f.create_dataset("a", data=np.zeros((10, 2)))
            f.create_dataset("b", data=np.zeros((9,)))
        with pytest.raises(ValueError):
            PartialH5Dataset(path, ["a", "b"])

    def test_dataloader_delegates(self, h5file):
        path, _, _ = h5file
        ds = PartialH5Dataset(path, "data", batch_size=500, initial_load=500)
        loader = DataLoader(ds, batch_size=1)  # batch size owned by the stream
        assert len(loader) == 2  # defers to the stream's own batching
        assert len(list(loader)) == 2
        with pytest.raises(ValueError):
            DataLoader(ds, batch_size=1, shuffle=True)

    def test_abandoned_iterator_releases_thread(self, h5file):
        import threading
        import time

        path, _, _ = h5file
        before = threading.active_count()
        for _ in range(5):
            it = iter(PartialH5Dataset(path, "data", batch_size=100, initial_load=128))
            next(it)
            it.close()
        time.sleep(0.5)
        assert threading.active_count() <= before + 1


class TestMNIST:
    @pytest.fixture
    def mnist_root(self, tmp_path):
        """Write tiny synthetic IDX files in the standard layout."""
        root = str(tmp_path)
        raw = os.path.join(root, "MNIST", "raw")
        os.makedirs(raw)
        rng = np.random.default_rng(0)
        for prefix, n in (("train", 64), ("t10k", 32)):
            images = rng.integers(0, 256, size=(n, 28, 28), dtype=np.uint8)
            labels = rng.integers(0, 10, size=(n,), dtype=np.uint8)
            with open(os.path.join(raw, f"{prefix}-images-idx3-ubyte"), "wb") as f:
                f.write(struct.pack(">IIII", 0x00000803, n, 28, 28))
                f.write(images.tobytes())
            # gzip one of the files to exercise the .gz path
            lbl_payload = struct.pack(">II", 0x00000801, n) + labels.tobytes()
            with gzip.open(os.path.join(raw, f"{prefix}-labels-idx1-ubyte.gz"), "wb") as f:
                f.write(lbl_payload)
        return root

    def test_loads_and_splits(self, mnist_root):
        ds = MNISTDataset(mnist_root, train=True)
        assert len(ds) == 64
        assert ds.htdata.shape == (64, 28, 28)
        assert ds.htdata.split == 0
        assert ds.httargets.shape == (64,)
        assert float(ht.max(ds.htdata)) <= 1.0
        test = MNISTDataset(mnist_root, train=False)
        assert len(test) == 32
        assert test.test_set

    def test_transform_applied(self, mnist_root):
        tr = vision_transforms.Compose(
            [vision_transforms.Normalize(0.5, 0.5)]
        )
        ds = MNISTDataset(mnist_root, train=True, transform=tr)
        assert float(ht.min(ds.htdata)) < 0.0  # normalization shifted range

    def test_missing_files_raise(self, tmp_path):
        with pytest.raises(FileNotFoundError):
            MNISTDataset(str(tmp_path), train=True)


class TestVisionTransforms:
    def test_to_tensor_and_normalize(self):
        img = np.full((4, 4), 255, dtype=np.uint8)
        out = vision_transforms.ToTensor()(img)
        np.testing.assert_allclose(out, 1.0)
        norm = vision_transforms.Normalize(0.5, 0.5)(out)
        np.testing.assert_allclose(norm, 1.0)

    def test_unknown_transform_raises(self):
        with pytest.raises(AttributeError):
            vision_transforms.RandomCrop


class TestCommSplit:
    """MPI-faithful Comm.Split semantics (single-controller adaptation)."""

    def test_scalar_color_dups(self):
        import heat_tpu as ht

        comm = ht.get_comm()
        dup = comm.Split(0)
        assert dup.size == comm.size

    def test_vector_color_groups(self):
        import heat_tpu as ht

        comm = ht.get_comm()
        p = comm.size
        if p < 2:
            return
        colors = [i % 2 for i in range(p)]
        groups = comm.Split(colors)
        assert set(groups) == {0, 1}
        assert groups[0].size == (p + 1) // 2
        assert groups[1].size == p // 2
        # key-ordered membership (reverse order within group 0)
        keys = list(range(p, 0, -1))
        rev = comm.Split(colors, keys)
        assert [d for d in rev[0].devices] == list(reversed([d for d in groups[0].devices]))

    def test_negative_color_excluded(self):
        import heat_tpu as ht

        comm = ht.get_comm()
        p = comm.size
        colors = [-1] + [0] * (p - 1)
        groups = comm.Split(colors)
        assert groups[0].size == p - 1

    def test_bad_lengths_raise(self):
        import heat_tpu as ht
        import pytest as _pytest

        comm = ht.get_comm()
        with _pytest.raises(ValueError):
            comm.Split([0])
        if comm.size > 1:
            with _pytest.raises(ValueError):
                comm.Split([0] * comm.size, [0])


class TestCheckpoint:
    """Sharding-aware checkpoint/resume (TPU-native addition; the
    reference has no model checkpointing, SURVEY §5)."""

    def test_roundtrip_pytree(self, tmp_path):
        import numpy as np
        import heat_tpu as ht

        rng = np.random.default_rng(0)
        x = ht.array(rng.standard_normal((13, 4)).astype(np.float32), split=0)
        tree = {
            "model": {"w": x, "b": ht.zeros(4)},
            "step": 7,
            "lr": 0.01,
            "opt": [ht.array(rng.standard_normal(5).astype(np.float32)), 3],
        }
        p = str(tmp_path / "ck")
        ht.utils.save_checkpoint(p, tree)
        back = ht.utils.load_checkpoint(p)
        np.testing.assert_allclose(back["model"]["w"].numpy(), x.numpy())
        assert back["model"]["w"].split == 0
        assert back["model"]["b"].split is None
        assert back["step"] == 7
        np.testing.assert_allclose(back["opt"][0].numpy(), tree["opt"][0].numpy())

    def test_roundtrip_uneven_and_tuple(self, tmp_path):
        import numpy as np
        import heat_tpu as ht

        x = ht.arange(11, split=0, dtype=ht.float32)
        tree = {"t": (x, 2)}
        p = str(tmp_path / "ck2")
        ht.utils.save_checkpoint(p, tree)
        back = ht.utils.load_checkpoint(p)
        assert isinstance(back["t"], tuple)
        np.testing.assert_allclose(back["t"][0].numpy(), np.arange(11, dtype=np.float32))
        assert back["t"][1] == 2


class TestMonitor:
    """@monitor decorator + registry (reference: perun @monitor in
    benchmarks/cb/linalg.py:4-23; here a built-in equivalent)."""

    def test_monitor_records_and_reports(self):
        import heat_tpu as ht
        from heat_tpu.utils import monitor as mon

        mon.reset()

        @mon.monitor()
        def workload():
            return ht.sum(ht.arange(100, split=0))

        for _ in range(3):
            workload()
        table = mon.report()
        assert table["workload"]["calls"] == 3
        assert table["workload"]["total_s"] > 0
        assert table["workload"]["best_s"] <= table["workload"]["mean_s"] * 1.0001
        import json
        assert json.loads(mon.report(as_json=True))["workload"]["calls"] == 3
        mon.reset()
        assert mon.report() == {}
