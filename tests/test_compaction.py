"""Gather-free data-dependent-shape ops (VERDICT r2 missing #1): unique,
boolean-mask selection, nonzero. Oracle = numpy on the gathered result;
the structural claim (operand never all-gathered) is pinned by asserting
the per-shard count/compact programs contain NO collectives at all — the
only all-gathers in the pipeline are the candidate-prefix merges, whose
operands are capacity-sized (≤ output size) by construction."""

import numpy as np
import pytest

import heat_tpu as ht


class TestDistributedUnique:
    @pytest.mark.parametrize("split", [0, 1])
    def test_unique_uneven_with_duplicates(self, split):
        rng = np.random.default_rng(0)
        x = rng.integers(0, 23, size=(13, 5)).astype(np.float32)
        got = ht.unique(ht.array(x, split=split), sorted=True)
        assert got.split == 0
        np.testing.assert_array_equal(np.asarray(got.numpy()), np.unique(x))

    def test_unique_ints_and_bool(self):
        x = np.array([3, 1, 3, 7, 1, 0, 7, 7, 2], dtype=np.int64)
        got = ht.unique(ht.array(x, split=0))
        np.testing.assert_array_equal(np.asarray(got.numpy()), np.unique(x))
        b = np.array([True, False, True, True, False])
        gotb = ht.unique(ht.array(b, split=0))
        np.testing.assert_array_equal(np.asarray(gotb.numpy()), np.unique(b))

    def test_unique_nan_matches_numpy(self):
        x = np.array([1.0, np.nan, 2.0, np.nan, 1.0], dtype=np.float32)
        got = np.asarray(ht.unique(ht.array(x, split=0)).numpy())
        ref = np.unique(x)
        assert got.shape == ref.shape
        np.testing.assert_array_equal(got[~np.isnan(got)], ref[~np.isnan(ref)])
        assert np.isnan(got).sum() == np.isnan(ref).sum()

    def test_unique_return_inverse_reconstructs_distributed(self):
        rng = np.random.default_rng(1)
        x = rng.integers(0, 9, size=37).astype(np.float32)
        u, inv = ht.unique(ht.array(x, split=0), return_inverse=True)
        np.testing.assert_array_equal(
            np.asarray(u.numpy())[np.asarray(inv.numpy())], x
        )

    def test_unique_return_inverse_nan(self):
        # NaN queries must map to the (single, last) NaN slot like
        # np.unique, not to len(values) (ADVICE r3)
        x = np.array([2.0, np.nan, 1.0, np.nan, 2.0, 7.0], dtype=np.float32)
        u, inv = ht.unique(ht.array(x, split=0), return_inverse=True)
        ui, invi = np.asarray(u.numpy()), np.asarray(inv.numpy())
        assert invi.max() < ui.shape[0]
        recon = ui[invi]
        np.testing.assert_array_equal(np.isnan(recon), np.isnan(x))
        np.testing.assert_array_equal(recon[~np.isnan(x)], x[~np.isnan(x)])
        assert inv.split == 0  # inverse carries the input's distribution

    def test_single_value_array(self):
        x = np.full(17, 4.0, dtype=np.float32)
        got = ht.unique(ht.array(x, split=0))
        np.testing.assert_array_equal(np.asarray(got.numpy()), [4.0])


class TestBoolMaskGetitem:
    def test_elements_mask_uneven_1d(self):
        rng = np.random.default_rng(2)
        x = rng.standard_normal(37).astype(np.float32)
        hx = ht.array(x, split=0)
        got = hx[hx > 0]
        assert got.split == 0
        np.testing.assert_allclose(np.asarray(got.numpy()), x[x > 0])

    def test_elements_mask_2d_row_major_order(self):
        rng = np.random.default_rng(3)
        x = rng.standard_normal((11, 7)).astype(np.float32)
        hx = ht.array(x, split=0)
        mask = hx < 0.2
        got = hx[mask]
        np.testing.assert_allclose(np.asarray(got.numpy()), x[x < 0.2])

    def test_row_mask_selects_rows(self):
        rng = np.random.default_rng(4)
        x = rng.standard_normal((13, 4)).astype(np.float32)
        m = x[:, 0] > 0
        hx = ht.array(x, split=0)
        got = hx[ht.array(m, split=0)]
        assert got.split == 0 and got.shape == (int(m.sum()), 4)
        np.testing.assert_allclose(np.asarray(got.numpy()), x[m])

    def test_empty_and_full_selection(self):
        x = np.arange(10, dtype=np.float32)
        hx = ht.array(x, split=0)
        got_none = hx[hx > 99.0]
        assert got_none.shape == (0,)
        got_all = hx[hx > -1.0]
        np.testing.assert_allclose(np.asarray(got_all.numpy()), x)

    def test_split1_input_mask(self):
        rng = np.random.default_rng(5)
        x = rng.standard_normal((6, 9)).astype(np.float32)
        hx = ht.array(x, split=1)
        got = hx[hx > 0]
        np.testing.assert_allclose(np.asarray(got.numpy()), x[x > 0])


class TestNonzero:
    @pytest.mark.parametrize("split", [0, 1])
    def test_nonzero_2d(self, split):
        rng = np.random.default_rng(6)
        x = (rng.random((9, 5)) < 0.4).astype(np.float32) * rng.standard_normal((9, 5)).astype(np.float32)
        got = ht.nonzero(ht.array(x, split=split))
        assert got.split == 0
        np.testing.assert_array_equal(
            np.asarray(got.numpy()), np.stack(np.nonzero(x), axis=1)
        )

    def test_nonzero_1d_uneven_and_empty(self):
        x = np.array([0.0, 1.0, 0.0, 2.0, 0.0, 0.0, 3.0], dtype=np.float32)
        got = ht.nonzero(ht.array(x, split=0))
        np.testing.assert_array_equal(
            np.asarray(got.numpy()), np.stack(np.nonzero(x), axis=1)
        )
        z = ht.nonzero(ht.array(np.zeros(11, dtype=np.float32), split=0))
        assert z.shape == (0, 1)


class TestChunkedBalancedGather:
    def test_dense_selection_uses_bounded_rounds(self, monkeypatch):
        """Dense selections (cap ~ local extent) must not materialize the
        (p, cap) one-shot gather: shrink the budget so even this small
        input takes the chunked path, and check exactness against the
        one-shot result (ADVICE r3 medium)."""
        from heat_tpu.core import parallel

        rng = np.random.default_rng(3)
        x = rng.standard_normal(1037).astype(np.float32)
        mask = rng.random(1037) < 0.95  # dense: nearly everything selected
        hx = ht.array(x, split=0)
        hm = ht.array(mask, split=0)

        expected = x[mask]
        one_shot = hx[hm]
        np.testing.assert_array_equal(one_shot.numpy(), expected)

        monkeypatch.setattr(parallel, "_GATHER_BUDGET_BYTES", 256)
        chunked = hx[hm]
        assert chunked.split == 0
        np.testing.assert_array_equal(chunked.numpy(), expected)

        # nonzero rides the same gather
        nz = ht.nonzero(hm)
        np.testing.assert_array_equal(
            np.asarray(nz.numpy()).ravel(), np.nonzero(mask)[0]
        )


class TestGatherFreeStructure:
    """The per-shard count/compact programs must be pure local compute:
    no collective of any kind in their lowered HLO. (The downstream merge
    programs all-gather only capacity-sized candidate prefixes.)"""

    def _assert_no_collectives(self, lowered_text):
        for marker in ("all_gather", "all-gather", "all_reduce", "all-reduce",
                       "all_to_all", "all-to-all", "collective-permute"):
            assert marker not in lowered_text, f"found {marker} in per-shard program"

    def test_mask_compact_local_only(self):
        from heat_tpu.core import parallel

        comm = ht.get_comm()
        x = ht.random.randn(24, split=0)
        m = (x > 0)._phys
        p = comm.size
        prog = parallel._mask_compact_program(
            comm.mesh, comm.axis_name, (x._phys.shape[0] // p,), False, "float32"
        )
        self._assert_no_collectives(prog.lower(x._phys, m).as_text())

    def test_unique_compact_local_only(self):
        from heat_tpu.core import parallel

        comm = ht.get_comm()
        x = ht.random.randn(24, split=0)
        p = comm.size
        prog = parallel._local_unique_program(
            comm.mesh, comm.axis_name, (x._phys.shape[0] // p,), 24, "float32"
        )
        self._assert_no_collectives(prog.lower(x._phys).as_text())

    def test_nonzero_compact_local_only(self):
        from heat_tpu.core import parallel

        comm = ht.get_comm()
        x = ht.random.randn(24, split=0)
        p = comm.size
        prog = parallel._nonzero_compact_program(
            comm.mesh, comm.axis_name, (x._phys.shape[0] // p,), 24, "float32"
        )
        self._assert_no_collectives(prog.lower(x._phys).as_text())


class TestPallasSketchGate:
    """The fused sketch+norm kernel must gate itself off everywhere the
    Mosaic path can't run (CPU mesh, x64 mode, odd shapes) — the XLA
    fallback is the oracle, asserted on the TPU by the verify drive."""

    def test_gates(self):
        import jax
        import jax.numpy as jnp
        from heat_tpu.core.linalg._pallas_sketch import sketch_with_norm

        g = jnp.ones((25, 256), jnp.float32)
        a = jnp.ones((256, 128), jnp.float32)
        out = sketch_with_norm(g, a)
        if jax.default_backend() != "tpu" or jax.config.jax_enable_x64:
            assert out is None  # CPU mesh / x64: fallback path
        # shape gates hold everywhere
        assert sketch_with_norm(jnp.ones((40, 256), jnp.float32), a) is None  # l > pad
        assert sketch_with_norm(jnp.ones((25, 100), jnp.float32),
                                jnp.ones((100, 128), jnp.float32)) is None  # indivisible
        assert sketch_with_norm(g.astype(jnp.bfloat16), a.astype(jnp.bfloat16)) is None
