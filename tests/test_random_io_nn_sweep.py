"""Breadth sweep for the random, io and nn surfaces (the reference's
test_random.py / test_io.py / nn tests coverage shape)."""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import heat_tpu as ht
from heat_tpu import nn as htnn


class TestRandomSweep:
    @pytest.mark.parametrize("split", [None, 0])
    def test_uniform_moments(self, split):
        ht.random.seed(0)
        x = ht.random.rand(20_000, split=split)
        v = x.numpy()
        assert 0.0 <= v.min() and v.max() < 1.0
        assert abs(v.mean() - 0.5) < 0.02
        assert abs(v.var() - 1 / 12) < 0.01

    @pytest.mark.parametrize("split", [None, 0])
    def test_normal_moments(self, split):
        ht.random.seed(1)
        x = ht.random.randn(20_000, split=split)
        v = x.numpy()
        assert abs(v.mean()) < 0.05
        assert abs(v.std() - 1.0) < 0.05

    def test_randint_bounds_and_dtype(self):
        ht.random.seed(2)
        x = ht.random.randint(3, 17, (5_000,), split=0)
        v = x.numpy()
        assert v.min() >= 3 and v.max() < 17
        assert x.dtype == ht.int32

    def test_permutation_and_randperm(self):
        ht.random.seed(3)
        p = ht.random.randperm(97, comm=ht.get_comm())
        np.testing.assert_array_equal(np.sort(p.numpy()), np.arange(97))
        q = ht.random.permutation(ht.arange(23, split=0))
        np.testing.assert_array_equal(np.sort(q.numpy()), np.arange(23))

    def test_seed_reproducibility_across_splits(self):
        ht.random.seed(9)
        a = ht.random.rand(31, split=0).numpy()
        ht.random.seed(9)
        b = ht.random.rand(31, split=None).numpy()
        np.testing.assert_array_equal(a, b)

    def test_aliases(self):
        ht.random.seed(4)
        for fn in (ht.random.random_sample, ht.random.ranf, ht.random.sample):
            v = fn((8,))
            assert v.shape == (8,)


class TestIOSweep:
    def test_save_load_extension_dispatch(self, tmp_path):
        x = ht.arange(24, dtype=ht.float32, split=0).reshape((6, 4))
        p = str(tmp_path / "a.h5")
        ht.save(x, p, "data")
        y = ht.load(p, "data", split=0)
        np.testing.assert_array_equal(y.numpy(), x.numpy())

    def test_csv_header_lines_and_sep(self, tmp_path):
        p = str(tmp_path / "d.csv")
        with open(p, "w") as f:
            f.write("# header one\n# header two\n")
            for row in np.arange(12).reshape(4, 3):
                f.write(",".join(str(float(v)) for v in row) + "\n")
        x = ht.load_csv(p, header_lines=2, sep=",", split=0)
        np.testing.assert_allclose(x.numpy(), np.arange(12).reshape(4, 3), rtol=1e-6)

    def test_save_csv_roundtrip_sep(self, tmp_path):
        d = np.random.default_rng(0).random((5, 3)).astype(np.float32)
        p = str(tmp_path / "x.csv")
        ht.save_csv(ht.array(d, split=0), p, sep=";", decimals=6)
        y = ht.load_csv(p, sep=";", split=0)
        np.testing.assert_allclose(y.numpy(), d, atol=1e-5)

    def test_load_unknown_extension_raises(self, tmp_path):
        with pytest.raises(ValueError):
            ht.load(str(tmp_path / "x.xyz"), "d")


class TestNNSweep:
    def test_linear_matches_manual(self):
        lin = htnn.Linear(6, 3)
        params = lin.init(jax.random.key(0))
        x = jnp.asarray(np.random.default_rng(1).standard_normal((10, 6)).astype(np.float32))
        out = lin.apply(params, x)
        leaves = jax.tree.leaves(params)
        # y = x @ W (+ b): find the 2-d leaf as the weight
        wmat = next(l for l in leaves if l.ndim == 2)
        bvec = next((l for l in leaves if l.ndim == 1), None)
        ref = x @ wmat + (bvec if bvec is not None else 0)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5)

    def test_sequential_composes(self):
        model = htnn.Sequential(htnn.Linear(4, 8), htnn.ReLU(), htnn.Linear(8, 2))
        params = model.init(jax.random.key(0))
        x = jnp.ones((5, 4), dtype=jnp.float32)
        out = model.apply(params, x)
        assert out.shape == (5, 2)
        assert np.isfinite(np.asarray(out)).all()

    def test_functional_aliases(self):
        x = jnp.asarray(np.linspace(-2, 2, 9, dtype=np.float32))
        np.testing.assert_allclose(
            np.asarray(htnn.functional.relu(x)), np.maximum(np.asarray(x), 0)
        )
        np.testing.assert_allclose(
            np.asarray(htnn.functional.sigmoid(x)),
            1 / (1 + np.exp(-np.asarray(x))),
            rtol=1e-5,
        )
        s = np.asarray(htnn.functional.softmax(x))
        assert abs(s.sum() - 1.0) < 1e-5

    def test_dataparallel_forward_matches_single(self):
        model = htnn.Sequential(htnn.Linear(6, 4), htnn.ReLU(), htnn.Linear(4, 3))
        dp = htnn.DataParallel(model, key=0)
        d = np.random.default_rng(2).standard_normal((16, 6)).astype(np.float32)
        x_split = ht.array(d, split=0)
        x_repl = ht.array(d)
        np.testing.assert_allclose(
            dp(x_split).numpy(), dp(x_repl).numpy(), rtol=2e-5, atol=2e-6
        )
