"""Module tests for types promotion edges, printing, logical/relational
surfaces, bf16 numerics, the x64 policy, and basic-key setitem — the
breadth items VERDICT r1 flagged (weak #9 / item 10). Mirrors the
reference's per-module test layout (core/tests/test_types.py,
test_printing.py, test_logical.py, test_relational.py)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import heat_tpu as ht
from heat_tpu.core import types

from test_suites.basic_test import TestCase


class TestTypePromotion(TestCase):
    def test_promote_int_float_lattice(self):
        cases = [
            (ht.int8, ht.int16, ht.int16),
            (ht.int32, ht.int64, ht.int64),
            (ht.uint8, ht.int8, ht.int16),
            (ht.int64, ht.float32, ht.float32),
            (ht.float32, ht.float64, ht.float64),
            (ht.float16, ht.float32, ht.float32),
            (ht.bfloat16, ht.float32, ht.float32),
            (ht.bool, ht.int8, ht.int8),
            (ht.bool, ht.bool, ht.bool),
            (ht.float32, ht.complex64, ht.complex64),
            (ht.float64, ht.complex64, ht.complex128),
        ]
        for a, b, expected in cases:
            assert types.promote_types(a, b) == expected, (a, b)
            assert types.promote_types(b, a) == expected, (b, a)

    def test_result_type_scalars_stay_weak(self):
        x = ht.array(np.arange(4, dtype=np.int32), split=0)
        assert types.result_type(x, 1) == ht.int32
        assert (x + 1).dtype == ht.int32
        f = ht.array(np.arange(4, dtype=np.float32))
        assert (f + 1).dtype == ht.float32
        assert (f * True).dtype == ht.float32

    def test_canonicalization_and_instantiation(self):
        assert types.canonical_heat_type(np.float32) == ht.float32
        assert types.canonical_heat_type("float32") == ht.float32
        assert types.canonical_heat_type(jnp.dtype("int64")) == ht.int64
        # instantiating a heat type constructs an array-like scalar
        v = ht.float32(3)
        assert float(v) == 3.0

    def test_finfo_iinfo(self):
        assert types.finfo(ht.float32).bits == 32
        assert types.iinfo(ht.int16).max == 32767
        assert types.iinfo(ht.uint8).min == 0
        assert types.finfo(ht.bfloat16).bits == 16

    def test_can_cast(self):
        assert types.can_cast(ht.int8, ht.int32)
        assert not types.can_cast(ht.float64, ht.int32, casting="safe")
        assert types.can_cast(ht.float64, ht.float32, casting="same_kind")

    def test_issubdtype_helpers(self):
        assert types.heat_type_is_exact(ht.int32)
        assert not types.heat_type_is_exact(ht.float32)
        assert types.heat_type_is_inexact(ht.bfloat16)
        assert types.heat_type_is_complexfloating(ht.complex64)


class TestBF16Numerics(TestCase):
    def test_bf16_roundtrip_and_arith(self):
        x = np.linspace(-4, 4, 37, dtype=np.float32)
        a = ht.array(x, dtype=ht.bfloat16, split=0)
        assert a.dtype == ht.bfloat16
        # bf16 has ~3 decimal digits; compare loosely
        np.testing.assert_allclose(
            a.numpy().astype(np.float32), x, rtol=2e-2, atol=2e-2
        )
        s = a + a
        assert s.dtype == ht.bfloat16
        np.testing.assert_allclose(
            s.numpy().astype(np.float32), 2 * x, rtol=2e-2, atol=3e-2
        )

    def test_bf16_matmul_promotes_nothing(self):
        rng = np.random.default_rng(0)
        m = rng.standard_normal((8, 8)).astype(np.float32)
        a = ht.array(m, dtype=ht.bfloat16, split=0)
        out = ht.matmul(a, a)
        assert out.dtype == ht.bfloat16
        np.testing.assert_allclose(
            out.numpy().astype(np.float32), m @ m, rtol=0.1, atol=0.25
        )

    def test_bf16_reduction(self):
        a = ht.ones((64,), dtype=ht.bfloat16, split=0)
        assert float(ht.sum(a)) == 64.0


class TestX64Policy(TestCase):
    """f64 parity requires jax_enable_x64 (set at import,
    heat_tpu/__init__.py); these pin the observable contract."""

    def test_float64_preserved(self):
        x = ht.array(np.arange(5, dtype=np.float64), split=0)
        assert x.dtype == ht.float64
        assert (x * 2).dtype == ht.float64
        assert np.asarray(x.numpy()).dtype == np.float64

    def test_int64_preserved(self):
        x = ht.array(np.arange(5, dtype=np.int64), split=0)
        assert x.dtype == ht.int64
        assert (x + 1).dtype == ht.int64

    def test_default_float_is_f32(self):
        # the framework default stays float32 (TPU-native), x64 only by request
        assert ht.zeros((3,)).dtype == ht.float32
        assert ht.arange(3.0).dtype == ht.float32


class TestPrinting(TestCase):
    def test_repr_small(self):
        x = ht.arange(6, split=0)
        s = str(x)
        assert "DNDarray" in s
        assert "0" in s and "5" in s

    def test_repr_summarizes_large(self):
        # must summarize, not transfer the world (reference printing
        # threshold behavior, printing.py:150)
        x = ht.zeros((10_000, 100), split=0)
        s = str(x)
        assert "..." in s
        assert len(s) < 4000

    def test_set_printoptions_roundtrip(self):
        old = ht.get_printoptions()
        try:
            ht.set_printoptions(precision=2)
            x = ht.array(np.array([1.23456789]))
            assert "1.23" in str(x) and "1.2345" not in str(x)
        finally:
            ht.set_printoptions(**{k: v for k, v in old.items() if v is not None})

    def test_local_global_printing_toggle(self):
        ht.local_printing()
        try:
            x = ht.arange(8, split=0)
            s = str(x)
            assert s  # local repr renders without gathering
        finally:
            ht.global_printing()

    def test_print0(self, capsys=None):
        ht.print0("hello-from-rank0")  # must not raise


class TestLogicalRelational(TestCase):
    def setUp(self):
        np.random.seed(3)
        self.a = np.random.randn(4, 9).astype(np.float32)
        self.b = np.random.randn(4, 9).astype(np.float32)

    def test_relational_full_surface(self):
        for split in (None, 0, 1):
            x, y = ht.array(self.a, split=split), ht.array(self.b, split=split)
            for ht_op, np_op in [
                (ht.eq, np.equal), (ht.ne, np.not_equal),
                (ht.lt, np.less), (ht.le, np.less_equal),
                (ht.gt, np.greater), (ht.ge, np.greater_equal),
            ]:
                got = ht_op(x, y)
                assert got.dtype == ht.bool
                np.testing.assert_array_equal(got.numpy(), np_op(self.a, self.b))

    def test_logical_ops(self):
        m1 = self.a > 0
        m2 = self.b > 0
        for split in (None, 0):
            x, y = ht.array(m1, split=split), ht.array(m2, split=split)
            np.testing.assert_array_equal(ht.logical_and(x, y).numpy(), m1 & m2)
            np.testing.assert_array_equal(ht.logical_or(x, y).numpy(), m1 | m2)
            np.testing.assert_array_equal(ht.logical_xor(x, y).numpy(), m1 ^ m2)
            np.testing.assert_array_equal(ht.logical_not(x).numpy(), ~m1)

    def test_any_all_axis_and_uneven(self):
        m = np.zeros((13, 3), dtype=bool)
        m[4, 1] = True
        x = ht.array(m, split=0)
        assert bool(ht.any(x))
        assert not bool(ht.all(x))
        np.testing.assert_array_equal(ht.any(x, axis=0).numpy(), m.any(0))
        np.testing.assert_array_equal(ht.all(x, axis=1).numpy(), m.all(1))

    def test_isclose_allclose(self):
        x = ht.array(self.a, split=0)
        y = ht.array(self.a + 1e-9, split=0)
        assert ht.allclose(x, y)
        assert bool(ht.isclose(x, y).numpy().all())

    def test_isfinite_family(self):
        v = np.array([1.0, np.inf, -np.inf, np.nan], dtype=np.float32)
        x = ht.array(v, split=0)
        np.testing.assert_array_equal(ht.isfinite(x).numpy(), np.isfinite(v))
        np.testing.assert_array_equal(ht.isinf(x).numpy(), np.isinf(v))
        np.testing.assert_array_equal(ht.isnan(x).numpy(), np.isnan(v))
        np.testing.assert_array_equal(ht.isposinf(x).numpy(), np.isposinf(v))
        np.testing.assert_array_equal(ht.isneginf(x).numpy(), np.isneginf(v))


class TestBasicSetitem(TestCase):
    """Basic-key setitem scatters on the physical array (no unpad/repad);
    pad region must stay zero (VERDICT r1 missing #7)."""

    def test_int_slice_ellipsis_assignments(self):
        rng = np.random.default_rng(0)
        for n in (16, 13):
            x = rng.standard_normal((n, 4)).astype(np.float32)
            a = ht.array(x, split=0)
            ref = x.copy()
            a[0] = 9.0; ref[0] = 9.0
            a[-1] = 5.0; ref[-1] = 5.0
            a[2:5] = 1.5; ref[2:5] = 1.5
            a[:, 1] = 2.0; ref[:, 1] = 2.0
            a[...] = ref * 2; ref[...] = ref * 2
            a[3] = np.arange(4, dtype=np.float32); ref[3] = np.arange(4)
            np.testing.assert_allclose(a.numpy(), ref)
            phys = np.asarray(jax.device_get(a._phys))
            assert np.all(phys[n:] == 0)

    def test_out_of_bounds_raises(self):
        a = ht.arange(5, split=0)
        with pytest.raises(IndexError):
            a[7] = 1.0

    def test_advanced_assignment_fallback(self):
        rng = np.random.default_rng(1)
        x = rng.standard_normal(13).astype(np.float32)
        a = ht.array(x, split=0)
        ref = x.copy()
        a[np.array([1, 5])] = 7.0; ref[[1, 5]] = 7.0
        m = ref > 0
        a[ht.array(m, split=0)] = 0.25; ref[m] = 0.25
        np.testing.assert_allclose(a.numpy(), ref)


class TestSetitemReviewRegressions(TestCase):
    def test_negative_step_slices(self):
        a = ht.zeros(4)
        a[3::-1] = 7.0
        np.testing.assert_allclose(a.numpy(), [7.0] * 4)
        a = ht.zeros(4)
        a[::-1] = np.array([1.0, 2, 3, 4], dtype=np.float32)
        np.testing.assert_allclose(a.numpy(), [4.0, 3, 2, 1])
        c = ht.arange(13, split=0, dtype=ht.float32)
        c[::-1] = np.arange(13, dtype=np.float32)
        np.testing.assert_allclose(c.numpy(), np.arange(13)[::-1])
        phys = np.asarray(jax.device_get(c._phys))
        assert np.all(phys[13:] == 0)

    def test_bool_key_broadcasts(self):
        b = ht.zeros(4)
        b[True] = 5.0
        np.testing.assert_allclose(b.numpy(), [5.0] * 4)
        b = ht.zeros(4)
        b[False] = 5.0
        np.testing.assert_allclose(b.numpy(), [0.0] * 4)

    def test_checkpoint_reserved_keys_raise(self, tmp_path=None):
        with pytest.raises(ValueError):
            ht.utils.save_checkpoint("/tmp/reserved-ck", {"__tuple__": [1]})

    def test_below_range_negative_step_is_noop(self):
        a = ht.arange(13, split=0, dtype=ht.float32)
        a[-20::-1] = 99.0
        np.testing.assert_allclose(a.numpy(), np.arange(13))
        a[5:2] = 42.0
        np.testing.assert_allclose(a.numpy(), np.arange(13))

    def test_too_many_indices_message(self):
        a = ht.arange(5, split=0)
        with pytest.raises(IndexError, match="too many"):
            a[1, 2] = 0.0


class TestSanitizeInfinity(TestCase):
    def test_float_and_int_branches(self):
        from heat_tpu.core.sanitation import sanitize_infinity

        assert sanitize_infinity(ht.arange(3, dtype=ht.int32)) == 2**31 - 1
        assert sanitize_infinity(ht.arange(3, dtype=ht.int8)) == 127
        assert sanitize_infinity(ht.arange(3.0)) > 1e38
        assert sanitize_infinity(ht.arange(3.0, dtype=ht.float64)) > 1e300


class TestLloc(TestCase):
    def test_local_accessor_read_write(self):
        x = ht.arange(16, split=0, dtype=ht.float32)
        assert float(np.asarray(jax.device_get(x.lloc[3]))) == 3.0
        x.lloc[0] = 99.0
        assert float(x.numpy()[0]) == 99.0

    def test_lloc_logical_bounds_on_uneven_split(self):
        # tail/negative indices are LOGICAL: the physical pad is invisible
        x = ht.arange(10, split=0, dtype=ht.float32)
        assert float(np.asarray(jax.device_get(x.lloc[-1]))) == 9.0
        x.lloc[-1] = 5.0
        assert float(x.numpy()[9]) == 5.0
        phys = np.asarray(jax.device_get(x._phys))
        assert np.all(phys[10:] == 0)

    def test_lloc_bounds_discipline(self):
        x = ht.arange(10, split=0, dtype=ht.float32)
        with pytest.raises(IndexError):
            x.lloc[50]
        with pytest.raises(IndexError):
            x.lloc[50] = 7.0
        x.lloc[0:2] = ht.array(np.array([7.0, 8.0], np.float32))
        assert list(x.numpy()[:2]) == [7.0, 8.0]

    def test_lloc_mask_get_set_symmetric(self):
        y = ht.arange(10, split=0, dtype=ht.float32)
        m = y > 5
        got = np.asarray(jax.device_get(y.lloc[m]))
        np.testing.assert_array_equal(got, np.arange(6, 10))
        y.lloc[m] = 0.0
        assert float(y.numpy().sum()) == sum(range(6))


class TestScalarReshape(TestCase):
    def test_reshape_to_scalar(self):
        r = ht.array(np.array([5.0], np.float32), split=0).reshape(())
        assert float(r.numpy()) == 5.0
        assert r.split is None


class TestBF16Numerics:
    """bfloat16 end-to-end numerics on the mesh — the TPU-first dtype the
    reference only passes through to torch. Tolerances follow bf16's ~3
    decimal digits (8-bit mantissa)."""

    def test_matmul_bf16_vs_f32_oracle(self):
        rng = np.random.default_rng(7)
        an = rng.standard_normal((33, 17)).astype(np.float32)
        bn = rng.standard_normal((17, 21)).astype(np.float32)
        a = ht.array(an, dtype=ht.bfloat16, split=0)
        b = ht.array(bn, dtype=ht.bfloat16, split=0)
        out = ht.matmul(a, b)
        assert out.dtype is ht.bfloat16
        ref = an @ bn
        got = np.asarray(out.astype(ht.float32).numpy())
        # bf16 inputs quantize once (~2^-8 relative) before the MXU f32 accumulate
        np.testing.assert_allclose(got, ref, rtol=5e-2, atol=5e-2)

    def test_reductions_keep_bf16_dtype(self):
        # reference parity: torch.sum(bfloat16) stays bfloat16
        x = ht.ones(1000, dtype=ht.bfloat16, split=0)
        s = ht.sum(x)
        assert s.dtype is ht.bfloat16
        assert float(s) == 1000.0  # 1000 is exactly representable in bf16
        m = ht.mean(ht.arange(8, dtype=ht.bfloat16, split=0))
        assert abs(float(m) - 3.5) < 1e-2

    def test_elementwise_chain_bf16(self):
        rng = np.random.default_rng(8)
        xn = rng.standard_normal(129).astype(np.float32)
        x = ht.array(xn, dtype=ht.bfloat16, split=0)
        y = ht.exp(ht.sin(x) * 0.5)
        ref = np.exp(np.sin(xn.astype(jnp.bfloat16).astype(np.float32)) * 0.5)
        np.testing.assert_allclose(
            np.asarray(y.astype(ht.float32).numpy()), ref, rtol=2e-2, atol=2e-2
        )

    def test_ring_attention_bf16(self):
        rng = np.random.default_rng(9)
        S, D = 64, 8
        qn = rng.standard_normal((S, D)).astype(np.float32)
        q32 = ht.array(qn, split=0)
        qbf = ht.array(qn, dtype=ht.bfloat16, split=0)
        ref = np.asarray(ht.nn.ring_attention(q32, q32, q32, causal=True).numpy())
        out = ht.nn.ring_attention(qbf, qbf, qbf, causal=True)
        assert out.dtype is ht.bfloat16
        np.testing.assert_allclose(
            np.asarray(out.astype(ht.float32).numpy()), ref, rtol=5e-2, atol=5e-2
        )

    def test_bf16_io_roundtrip_via_f32(self, tmp_path):
        # HDF5 has no bf16: saves upcast to f32, loads re-quantize
        x = ht.array(np.linspace(-3, 3, 37, dtype=np.float32), dtype=ht.bfloat16, split=0)
        p = str(tmp_path / "bf.h5")
        ht.save_hdf5(x, p, "d")
        back = ht.load_hdf5(p, "d", dtype=ht.bfloat16, split=0)
        assert back.dtype is ht.bfloat16
        np.testing.assert_array_equal(
            np.asarray(back.astype(ht.float32).numpy()),
            np.asarray(x.astype(ht.float32).numpy()),
        )
