"""Linear algebra tests: matmul split rules, TSQR, hSVD, CG, Lanczos, SVD
(reference pattern: core/linalg/tests/ iterate split × shape)."""

import numpy as np

import heat_tpu as ht

from test_suites.basic_test import TestCase


class TestMatmul(TestCase):
    def test_matmul_all_splits(self):
        np.random.seed(1)
        a = np.random.randn(16, 12).astype(np.float32)
        b = np.random.randn(12, 10).astype(np.float32)
        expected = a @ b
        for sa in (None, 0, 1):
            for sb in (None, 0, 1):
                ha = ht.array(a, split=sa)
                hb = ht.array(b, split=sb)
                c = ht.matmul(ha, hb)
                np.testing.assert_allclose(c.numpy(), expected, rtol=1e-4)
        # reference split rules (basics.py:421-436)
        self.assertEqual(ht.matmul(ht.array(a, split=0), ht.array(b)).split, 0)
        self.assertEqual(ht.matmul(ht.array(a), ht.array(b, split=1)).split, 1)

    def test_matmul_uneven(self):
        a = np.random.randn(13, 7).astype(np.float32)
        b = np.random.randn(7, 5).astype(np.float32)
        c = ht.matmul(ht.array(a, split=0), ht.array(b, split=0))
        np.testing.assert_allclose(c.numpy(), a @ b, rtol=1e-4)

    def test_dot_outer(self):
        x = np.random.randn(20).astype(np.float32)
        y = np.random.randn(20).astype(np.float32)
        hx, hy = ht.array(x, split=0), ht.array(y, split=0)
        np.testing.assert_allclose(float(ht.dot(hx, hy)), x @ y, rtol=1e-4)
        np.testing.assert_allclose(ht.outer(hx, hy).numpy(), np.outer(x, y), rtol=1e-4)

    def test_inv_det_trace(self):
        m = np.random.randn(6, 6).astype(np.float64)
        m = m @ m.T + 6 * np.eye(6)
        for split in (None, 0, 1):
            hm = ht.array(m, split=split)
            np.testing.assert_allclose(ht.inv(hm).numpy(), np.linalg.inv(m), rtol=1e-6)
            np.testing.assert_allclose(float(ht.det(hm)), np.linalg.det(m), rtol=1e-6)
            np.testing.assert_allclose(float(ht.trace(hm)), np.trace(m), rtol=1e-6)

    def test_norms(self):
        a = np.random.randn(8, 6).astype(np.float32)
        for split in (None, 0, 1):
            x = ht.array(a, split=split)
            np.testing.assert_allclose(float(ht.norm(x)), np.linalg.norm(a), rtol=1e-5)
            np.testing.assert_allclose(
                ht.vector_norm(x, axis=0).numpy(), np.linalg.norm(a, axis=0), rtol=1e-5
            )
            np.testing.assert_allclose(
                float(ht.matrix_norm(x, ord="fro")), np.linalg.norm(a, "fro"), rtol=1e-5
            )

    def test_transpose_tri(self):
        a = np.random.randn(5, 7).astype(np.float32)
        for split in (None, 0, 1):
            x = ht.array(a, split=split)
            t = x.T
            np.testing.assert_allclose(t.numpy(), a.T)
            self.assertEqual(t.split, None if split is None else 1 - split)
            np.testing.assert_allclose(ht.tril(x).numpy(), np.tril(a))
            np.testing.assert_allclose(ht.triu(x, 1).numpy(), np.triu(a, 1))


class TestQR(TestCase):
    def _check_qr(self, a_np, split):
        x = ht.array(a_np, split=split)
        q, r = ht.linalg.qr(x)
        m, n = a_np.shape
        np.testing.assert_allclose(q.numpy() @ r.numpy(), a_np, atol=1e-4)
        np.testing.assert_allclose(
            q.numpy().T @ q.numpy(), np.eye(q.shape[1]), atol=1e-4
        )
        np.testing.assert_allclose(np.tril(r.numpy(), -1), 0, atol=1e-5)

    def test_qr_tall_split0(self):
        np.random.seed(2)
        self._check_qr(np.random.randn(64, 8).astype(np.float32), 0)
        self._check_qr(np.random.randn(50, 7).astype(np.float32), 0)  # uneven
        self._check_qr(np.random.randn(9, 3).astype(np.float32), 0)  # m < mesh·n

    def test_qr_split1_and_none(self):
        a = np.random.randn(20, 12).astype(np.float32)
        self._check_qr(a, 1)
        self._check_qr(a, None)

    def test_qr_no_q(self):
        a = np.random.randn(40, 6).astype(np.float32)
        q, r = ht.linalg.qr(ht.array(a, split=0), calc_q=False)
        self.assertIsNone(q)
        np.testing.assert_allclose(
            np.abs(r.numpy()), np.abs(np.linalg.qr(a, mode="r")), atol=1e-4
        )


class TestHSVD(TestCase):
    def _low_rank(self, m, n, rank):
        np.random.seed(3)
        u = np.linalg.qr(np.random.randn(m, rank))[0]
        v = np.linalg.qr(np.random.randn(n, rank))[0]
        s = np.linspace(10, 1, rank)
        return (u * s) @ v.T

    def test_hsvd_rank_split1(self):
        a = self._low_rank(40, 64, 5).astype(np.float32)
        x = ht.array(a, split=1)
        u, s, v, err = ht.linalg.hsvd_rank(x, 5, compute_sv=True)
        self.assertLessEqual(err, 1e-4)
        np.testing.assert_allclose(
            u.numpy() * s.numpy() @ v.numpy().T, a, atol=1e-3
        )
        # U orthonormal
        np.testing.assert_allclose(u.numpy().T @ u.numpy(), np.eye(5), atol=1e-4)
        np.testing.assert_allclose(
            s.numpy(), np.linalg.svd(a, compute_uv=False)[:5], rtol=1e-3
        )

    def test_hsvd_rank_split0(self):
        a = self._low_rank(64, 40, 4).astype(np.float32)
        x = ht.array(a, split=0)
        u, s, v, err = ht.linalg.hsvd_rank(x, 4, compute_sv=True)
        np.testing.assert_allclose(u.numpy() * s.numpy() @ v.numpy().T, a, atol=1e-3)

    def test_hsvd_rank_truncation_error(self):
        # full-rank matrix truncated to rank 3: error ≈ tail energy
        np.random.seed(4)
        a = np.random.randn(32, 24).astype(np.float32)
        x = ht.array(a, split=1)
        u, err = ht.linalg.hsvd_rank(x, 3)
        s_true = np.linalg.svd(a, compute_uv=False)
        expected_rel = np.sqrt(np.sum(s_true[3:] ** 2)) / np.linalg.norm(a)
        self.assertEqual(u.shape, (32, 3))
        # upper bound should hold and not be wildly pessimistic
        self.assertGreaterEqual(err * 1.05, expected_rel)
        self.assertLess(err, 5 * expected_rel + 0.1)

    def test_hsvd_rtol(self):
        a = self._low_rank(48, 32, 6).astype(np.float32)
        a = a + 1e-3 * np.random.randn(48, 32).astype(np.float32)
        x = ht.array(a, split=1)
        u, s, v, err = ht.linalg.hsvd_rtol(x, 0.05, compute_sv=True)
        self.assertLessEqual(err, 0.05 + 1e-6)
        recon = u.numpy() * s.numpy() @ v.numpy().T
        self.assertLessEqual(
            np.linalg.norm(recon - a) / np.linalg.norm(a), 0.05 + 1e-3
        )

    def test_hsvd_errors(self):
        x = ht.ones((4, 4), split=0)
        with self.assertRaises(ValueError):
            ht.linalg.hsvd_rank(x, 0)
        with self.assertRaises(ValueError):
            ht.linalg.hsvd_rtol(x, -1.0)
        with self.assertRaises(ValueError):
            ht.linalg.hsvd(x)


class TestOneViewHSVD(TestCase):
    """Single-pass (one-view) hSVD (r5, `hsvd_rank(..., single_pass=True)`):
    column and row sketches from one streaming read of A. The XLA
    formulation tested here is the oracle for the TPU dual-sketch kernel;
    quality is the documented trade — exact for rank ≤ budget, modestly
    looser than the 2-pass HMT route otherwise."""

    M, N = 512, 384  # large enough for the 4·ℓ ≤ min(m,n) eligibility gate

    def test_exact_rank_recovery_all_splits(self):
        rng = np.random.default_rng(0)
        a = (rng.standard_normal((self.M, 8)) @ rng.standard_normal((8, self.N))).astype(np.float32)
        for split in (None, 0, 1):
            x = ht.array(a, split=split)
            u, s, v, err = ht.linalg.hsvd_rank(x, 10, compute_sv=True, single_pass=True)
            rec = (u.numpy() * s.numpy()) @ v.numpy().T
            rel = np.linalg.norm(rec - a) / np.linalg.norm(a)
            self.assertLess(rel, 1e-3, f"split={split}")

    def test_factors_orthonormal(self):
        rng = np.random.default_rng(1)
        a = rng.standard_normal((self.M, self.N)).astype(np.float32)
        u, s, v, err = ht.linalg.hsvd_rank(
            ht.array(a, split=None), 10, compute_sv=True, single_pass=True
        )
        np.testing.assert_allclose(u.numpy().T @ u.numpy(), np.eye(10), atol=2e-4)
        np.testing.assert_allclose(v.numpy().T @ v.numpy(), np.eye(10), atol=2e-4)

    def test_decaying_spectrum_quality(self):
        # i^-1.5 spectrum: one-view must stay within 1.6x of the optimal
        # rank-10 error (2-pass holds ~1.11x; the gap is the documented
        # one-view constant)
        rng = np.random.default_rng(2)
        sv = np.arange(1, 257, dtype=np.float64) ** -1.5
        u0, _ = np.linalg.qr(rng.standard_normal((self.M, 256)))
        v0, _ = np.linalg.qr(rng.standard_normal((256, 256)))
        a = ((u0 * sv) @ v0.T).astype(np.float32)
        opt = np.sqrt(np.sum(sv[10:] ** 2))
        u, s, v, err = ht.linalg.hsvd_rank(
            ht.array(a, split=None), 10, compute_sv=True, single_pass=True
        )
        rec = (u.numpy().astype(np.float64) * s.numpy()) @ v.numpy().T.astype(np.float64)
        self.assertLess(np.linalg.norm(rec - a) / opt, 1.6)

    def test_distributed_one_view_engages_and_matches(self):
        # shards wide enough that the per-shard eligibility gate passes:
        # the level-0 kernel runs the one-view sketch, TSQR merges as usual
        from heat_tpu.core.linalg.svdtools import _one_view_params

        P = ht.get_comm().size
        n = 256 * P
        self.assertIsNotNone(_one_view_params(15, min(self.M, 256)))
        rng = np.random.default_rng(3)
        a = (rng.standard_normal((self.M, 12)) @ rng.standard_normal((12, n))).astype(np.float32)
        x = ht.array(a, split=1)
        u, s, v, err = ht.linalg.hsvd_rank(x, 12, compute_sv=True, single_pass=True)
        rec = (u.numpy() * s.numpy()) @ v.numpy().T
        self.assertLess(np.linalg.norm(rec - a) / np.linalg.norm(a), 1e-3)

    def test_error_estimate_honest_on_heavy_tail(self):
        # the held-out-rows estimator must TRACK the true residual on the
        # input class where a norm-minus-captured-energy estimate clamps
        # to a misleading zero (flat spectrum: captured energy inflates
        # past ||A||^2). Unbiased with q=10 rows: allow +-40%.
        rng = np.random.default_rng(5)
        b = rng.standard_normal((self.M, self.N)).astype(np.float32)
        u, s, v, err = ht.linalg.hsvd_rank(
            ht.array(b, split=None), 10, compute_sv=True, single_pass=True
        )
        rec = (u.numpy() * s.numpy()) @ v.numpy().T
        true_rel = np.linalg.norm(rec - b) / np.linalg.norm(b)
        self.assertGreater(float(err), 0.6 * true_rel)
        self.assertLess(float(err), 1.4 * true_rel)

    def test_error_estimate_small_on_exact_rank(self):
        rng = np.random.default_rng(6)
        a = (rng.standard_normal((self.M, 8)) @ rng.standard_normal((8, self.N))).astype(np.float32)
        _, err = ht.linalg.hsvd_rank(ht.array(a, split=None), 10, single_pass=True)
        self.assertLess(float(err), 1e-2)

    def test_small_matrix_falls_back_silently(self):
        # below the eligibility gate single_pass must degrade to the
        # 2-pass route, not fail
        a = np.random.default_rng(4).standard_normal((40, 64)).astype(np.float32)
        u1, e1 = ht.linalg.hsvd_rank(ht.array(a, split=1), 5, single_pass=True)
        u2, e2 = ht.linalg.hsvd_rank(ht.array(a, split=1), 5, single_pass=False)
        np.testing.assert_allclose(np.abs(u1.numpy()), np.abs(u2.numpy()), atol=1e-5)


class TestSVD(TestCase):
    def test_svd_tall_split0(self):
        np.random.seed(5)
        a = np.random.randn(64, 10).astype(np.float32)
        u, s, vh = ht.linalg.svd(ht.array(a, split=0))
        np.testing.assert_allclose((u.numpy() * s.numpy()) @ vh.numpy(), a, atol=1e-3)
        np.testing.assert_allclose(
            s.numpy(), np.linalg.svd(a, compute_uv=False), rtol=1e-4
        )
        np.testing.assert_allclose(u.numpy().T @ u.numpy(), np.eye(10), atol=1e-4)

    def test_svd_wide_split1(self):
        a = np.random.randn(10, 64).astype(np.float32)
        u, s, vh = ht.linalg.svd(ht.array(a, split=1))
        np.testing.assert_allclose((u.numpy() * s.numpy()) @ vh.numpy(), a, atol=1e-3)

    def test_svd_values_only(self):
        a = np.random.randn(30, 8).astype(np.float32)
        s = ht.linalg.svd(ht.array(a, split=0), compute_uv=False)
        np.testing.assert_allclose(s.numpy(), np.linalg.svd(a, compute_uv=False), rtol=1e-4)


class TestSolver(TestCase):
    def test_cg(self):
        np.random.seed(6)
        n = 16
        a = np.random.randn(n, n).astype(np.float32)
        a = a @ a.T + n * np.eye(n, dtype=np.float32)
        b = np.random.randn(n).astype(np.float32)
        x_expected = np.linalg.solve(a, b)
        for split in (None, 0):
            A = ht.array(a, split=split)
            B = ht.array(b)
            x0 = ht.zeros(n)
            x = ht.linalg.cg(A, B, x0)
            np.testing.assert_allclose(x.numpy(), x_expected, atol=1e-3)

    def test_hsvd_rtol_tight_rank_selection(self):
        # tight rtol must select rank from an EXACT spectrum: the sketch's
        # power pass weights directions by sigma^3, so a 1e-4*sigma_max
        # singular value is invisible to it in f32 (ADVICE r3); below
        # rtol=1e-3 the full-SVD path engages even with a rank budget
        rng = np.random.default_rng(5)
        m, n = 512, 128
        s_true = np.array([1.0, 0.5, 0.2, 1e-4, 5e-5, 2e-5])
        U, _ = np.linalg.qr(rng.standard_normal((m, 6)))
        V, _ = np.linalg.qr(rng.standard_normal((n, 6)))
        a = ((U * s_true) @ V.T).astype(np.float32)
        a_norm = float(np.linalg.norm(s_true))
        rtol = 6e-5  # oracle: keep sigma_4=1e-4, discard 5e-5/2e-5 tail
        for split in (None, 0):
            A = ht.array(a, split=split)
            u, sig, v, err = ht.linalg.hsvd_rtol(A, rtol, compute_sv=True, maxrank=8)
            got = np.asarray(sig.numpy())
            assert got.shape[0] == 4, f"split={split}: rank {got.shape[0]} != 4"
            np.testing.assert_allclose(got, s_true[:4], rtol=1e-2, atol=1e-6)
            assert float(err) <= rtol * 1.5
            # reconstruction honors the bound
            rec = (u.numpy() * got) @ v.numpy().T
            assert np.linalg.norm(rec - a) <= rtol * a_norm * 2

    def test_lanczos(self):
        np.random.seed(7)
        n = 12
        a = np.random.randn(n, n).astype(np.float64)
        a = (a + a.T) / 2
        A = ht.array(a, split=0, dtype=ht.float64)
        V, T = ht.linalg.lanczos(A, n)
        # V T V^T ≈ A for full Krylov space
        v, t = V.numpy(), T.numpy()
        np.testing.assert_allclose(v @ t @ v.T, a, atol=1e-6)


class TestTiling(TestCase):
    def test_split_tiles_geometry(self):
        x = ht.arange(64, split=0).reshape(8, 8)
        tiles = ht.tiling.SplitTiles(x)
        self.assertEqual(len(tiles.tile_dimensions), 2)
        self.assertEqual(int(np.sum(tiles.tile_dimensions[0])), 8)
        p = ht.get_comm().size
        self.assertEqual(tiles.tile_ends_g.shape, (2, p))
        self.assertEqual(int(tiles.tile_ends_g[0, -1]), 8)
        self.assertEqual(tiles.lshape_map.shape, (p, 2))

    def test_split_tiles_reads_cover_array(self):
        rng = np.random.default_rng(0)
        a = rng.standard_normal((13, 5)).astype(np.float32)  # uneven rows
        x = ht.array(a, split=0)
        tiles = ht.tiling.SplitTiles(x)
        p = ht.get_comm().size
        rebuilt = np.concatenate(
            [tiles[i] for i in range(p) if tiles[i].shape[0] > 0], axis=0
        )
        np.testing.assert_allclose(rebuilt, a)
        # tile-slice read
        np.testing.assert_allclose(tiles[0:p], a)

    def test_split_tiles_setitem_writes_through(self):
        a = np.zeros((12, 4), dtype=np.float32)
        x = ht.array(a, split=0)
        tiles = ht.tiling.SplitTiles(x)
        tiles[1] = 7.0
        starts = np.concatenate([[0], np.cumsum(tiles.tile_dimensions[0])])
        expect = a.copy()
        expect[int(starts[1]): int(starts[2])] = 7.0
        np.testing.assert_allclose(np.asarray(x.numpy()), expect)

    def test_square_diag_tiles_geometry(self):
        x = ht.zeros((16, 16), split=0)
        tiles = ht.tiling.SquareDiagTiles(x, tiles_per_proc=2)
        self.assertGreaterEqual(tiles.tile_rows, 8)
        rows, cols = tiles.get_tile_size((0, 0))
        self.assertGreater(rows, 0)
        rs, re, cs, ce = tiles.get_start_stop((0, 0))
        self.assertEqual((re - rs, ce - cs), (rows, cols))
        self.assertEqual(
            tiles.tile_map.shape, (tiles.tile_rows, tiles.tile_columns)
        )
        self.assertEqual(
            sum(tiles.tile_rows_per_process), tiles.tile_rows
        )
        self.assertLess(tiles.last_diagonal_process, ht.get_comm().size)

    def test_square_diag_tiles_get_set_local(self):
        rng = np.random.default_rng(1)
        a = rng.standard_normal((16, 16)).astype(np.float32)
        x = ht.array(a, split=0)
        tiles = ht.tiling.SquareDiagTiles(x, tiles_per_proc=1)
        # read: every tile matches its numpy region
        for i in range(tiles.tile_rows):
            for j in range(tiles.tile_columns):
                rs, re, cs, ce = tiles.get_start_stop((i, j))
                np.testing.assert_allclose(tiles[i, j], a[rs:re, cs:ce])
        # write-through: zero the (0, 1) tile
        tiles[0, 1] = 0.0
        rs, re, cs, ce = tiles.get_start_stop((0, 1))
        expect = a.copy()
        expect[rs:re, cs:ce] = 0.0
        np.testing.assert_allclose(np.asarray(x.numpy()), expect)
        # local accessor: device 1's first local tile is the global tile
        # offset by device 0's band
        if ht.get_comm().size > 1:
            gi, gj = tiles.local_to_global((0, 0), rank=1)
            np.testing.assert_allclose(tiles.local_get((0, 0), rank=1), tiles[gi, gj])
            tiles.local_set((0, 0), 3.5, rank=1)
            rs, re, cs, ce = tiles.get_start_stop((gi, gj))
            expect[rs:re, cs:ce] = 3.5
            np.testing.assert_allclose(np.asarray(x.numpy()), expect)

    def test_square_diag_tiles_match(self):
        x = ht.zeros((16, 12), split=0)
        q = ht.zeros((16, 16), split=0)
        a_tiles = ht.tiling.SquareDiagTiles(x, tiles_per_proc=2)
        q_tiles = ht.tiling.SquareDiagTiles(q, tiles_per_proc=1)
        q_tiles.match_tiles(a_tiles)
        # row boundaries adopted; column boundaries clipped to q's extent
        self.assertEqual(q_tiles.row_indices, a_tiles.row_indices)
        self.assertEqual(sum(q_tiles.tile_rows_per_process), q_tiles.tile_rows)


class TestComplexNativeLinalg(TestCase):
    """Native-mode complex linalg (ISSUE 5 satellite): on CPU/GPU worlds
    complex DNDarrays are native jax complex arrays and the
    factorizations just work — but nothing asserted it, so a regression
    would land silently. Pins qr/svd/hsvd_rank/lanczos on complex
    inputs against their defining identities. (On TPU these ops
    planar-refuse with an actionable TypeError — the MIGRATING.md
    "Complex platform policy" table; tests/test_complex_planar.py pins
    the refusals.)"""

    def _cplx(self, m, n, seed=3):
        rng = np.random.default_rng(seed)
        return (
            rng.standard_normal((m, n)) + 1j * rng.standard_normal((m, n))
        ).astype(np.complex64)

    def test_qr_complex(self):
        A = self._cplx(24, 12)
        for split in (None, 0):
            x = ht.array(A, split=split)
            self.assertFalse(x._is_planar)  # native on the CPU mesh
            q, r = ht.linalg.qr(x)
            qn, rn = q.numpy(), r.numpy()
            np.testing.assert_allclose(qn @ rn, A, atol=1e-4)
            # unitary Q: Q^H Q = I (the complex analog of orthogonality)
            np.testing.assert_allclose(
                qn.conj().T @ qn, np.eye(qn.shape[1]), atol=1e-4
            )
            # R upper triangular
            np.testing.assert_allclose(rn, np.triu(rn), atol=1e-5)

    def test_svd_complex(self):
        A = self._cplx(16, 10)
        for split in (None, 0):
            u, s, vh = ht.linalg.svd(ht.array(A, split=split))
            sn = s.numpy()
            # singular values real, non-negative, sorted
            self.assertTrue(np.all(sn >= -1e-6))
            self.assertTrue(np.all(np.diff(sn) <= 1e-5))
            np.testing.assert_allclose(
                sn, np.linalg.svd(A, compute_uv=False), atol=1e-3
            )

    def test_hsvd_rank_complex(self):
        # full-rank complex input, rank-3 truncation: the projection
        # residual must track the optimal truncation error (numpy SVD)
        A = self._cplx(32, 12, seed=9)
        x = ht.array(A, split=0)
        u, err = ht.linalg.hsvd_rank(x, 3)
        un = u.numpy()
        self.assertEqual(un.shape, (32, 3))
        resid = np.linalg.norm(A - un @ (un.conj().T @ A))
        s = np.linalg.svd(A, compute_uv=False)
        optimal = np.linalg.norm(s[3:])
        self.assertLessEqual(resid, 1.5 * optimal + 1e-3)

    def test_lanczos_complex_hermitian(self):
        A = self._cplx(20, 20, seed=7)
        H = (A @ A.conj().T).astype(np.complex64)  # hermitian PSD
        x = ht.array(H, split=0)
        V, T = ht.linalg.lanczos(x, 8)
        Vn, Tn = V.numpy(), T.numpy()
        # V^H V = I and V^H H V = T (the Lanczos relation on the Krylov basis)
        np.testing.assert_allclose(Vn.conj().T @ Vn, np.eye(8), atol=1e-3)
        np.testing.assert_allclose(Vn.conj().T @ H @ Vn, Tn, atol=1e-2)
        # the m=1 shortcut (code-review PR 5): T is the conjugated
        # Rayleigh quotient v0^H H v0 — real for hermitian H, and it
        # must not crash converting a complex inner product
        V1, T1 = ht.linalg.lanczos(x, 1)
        v1 = V1.numpy()[:, 0]
        np.testing.assert_allclose(
            T1.numpy()[0, 0], v1.conj() @ H @ v1, rtol=1e-3
        )

    def test_polar_complex(self):
        """ISSUE 19: the Newton–Schulz iteration's inner products are
        X^H X / U^H A — a missed conjugation (the PR 5 bug class) makes
        U non-unitary and H non-Hermitian on complex input. Pin the
        defining identities: A = U H, U^H U = I, H = H^H PSD."""
        A = self._cplx(48, 12, seed=11)
        for split in (None, 0):
            u, h = ht.linalg.polar(ht.array(A, split=split))
            un, hn = u.numpy(), h.numpy()
            np.testing.assert_allclose(un @ hn, A, atol=1e-4)
            np.testing.assert_allclose(
                un.conj().T @ un, np.eye(12), atol=1e-4
            )
            # H exactly Hermitian by construction (symmetrized return)
            np.testing.assert_allclose(hn, hn.conj().T, atol=0)
            self.assertTrue(np.all(np.linalg.eigvalsh(hn) >= -1e-3))

    def test_eigh_complex(self):
        """A = V diag(w) V^H with unitary V and REAL eigenvalues — the
        conjugate-transpose contract of the spectral divide-and-conquer
        compression Q^H (A Q)."""
        C = self._cplx(24, 24, seed=12)
        H = (C @ C.conj().T + 24 * np.eye(24)).astype(np.complex64)
        for split in (None, 0):
            w, v = ht.linalg.eigh(ht.array(H, split=split))
            wn, vn = w.numpy(), v.numpy()
            self.assertFalse(np.iscomplexobj(wn) and np.abs(wn.imag).max() > 0)
            np.testing.assert_allclose(
                vn @ np.diag(wn) @ vn.conj().T, H, atol=1e-2
            )
            np.testing.assert_allclose(
                vn.conj().T @ vn, np.eye(24), atol=1e-4
            )
            np.testing.assert_allclose(
                np.sort(np.real(wn)), np.linalg.eigvalsh(H), rtol=1e-4
            )

    def test_cholesky_complex(self):
        """A = L L^H with lower-triangular L — the trailing update
        subtracts L_panel (L_col)^H; a dropped conj breaks hermitian
        positive-definiteness of the remainder."""
        C = self._cplx(24, 24, seed=13)
        H = (C @ C.conj().T + 24 * np.eye(24)).astype(np.complex64)
        for split in (None, 0):
            l = ht.linalg.cholesky(ht.array(H, split=split))
            ln = l.numpy()
            np.testing.assert_allclose(ln @ ln.conj().T, H, atol=1e-2)
            np.testing.assert_allclose(ln, np.tril(ln), atol=1e-6)
            # solve rides the same conjugated triangular chain
            b = self._cplx(24, 3, seed=14)
            x = ht.linalg.solve(
                ht.array(H, split=split), ht.array(b, split=split),
                assume_a="pos",
            )
            np.testing.assert_allclose(H @ x.numpy(), b, atol=1e-2)


if __name__ == "__main__":
    import unittest

    unittest.main()
