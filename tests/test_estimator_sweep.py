"""Estimator breadth sweep against scikit-learn oracles across splits —
the reference validates its estimator layer the same way
(classification/tests, naive_bayes/tests, preprocessing/tests)."""

import numpy as np
import pytest

import heat_tpu as ht

from sklearn import naive_bayes as sknb
from sklearn import neighbors as sknn
from sklearn import preprocessing as skpp

_RNG = np.random.default_rng(3)
_X = _RNG.standard_normal((45, 6)).astype(np.float32)
_CENTERS = _RNG.standard_normal((3, 6)).astype(np.float32) * 4
_Y = _RNG.integers(0, 3, 45).astype(np.int32)
_XC = (_CENTERS[_Y] + _X).astype(np.float32)  # separable blobs
_XT = (_CENTERS[_RNG.integers(0, 3, 12)] + _RNG.standard_normal((12, 6))).astype(np.float32)


class TestGaussianNBSweep:
    @pytest.mark.parametrize("split", [None, 0])
    def test_fit_predict_matches_sklearn(self, split):
        ref = sknb.GaussianNB().fit(_XC, _Y)
        est = ht.naive_bayes.GaussianNB().fit(
            ht.array(_XC, split=split), ht.array(_Y, split=split)
        )
        np.testing.assert_array_equal(
            est.predict(ht.array(_XT, split=split)).numpy(), ref.predict(_XT)
        )
        np.testing.assert_allclose(
            np.asarray(est.theta_.numpy()), ref.theta_, rtol=1e-4, atol=1e-5
        )

    def test_partial_fit_equals_one_shot(self):
        est1 = ht.naive_bayes.GaussianNB().fit(ht.array(_XC, split=0), ht.array(_Y, split=0))
        est2 = ht.naive_bayes.GaussianNB()
        classes = ht.array(np.unique(_Y))
        est2.partial_fit(ht.array(_XC[:20], split=0), ht.array(_Y[:20], split=0), classes=classes)
        est2.partial_fit(ht.array(_XC[20:], split=0), ht.array(_Y[20:], split=0))
        np.testing.assert_allclose(
            np.asarray(est1.theta_.numpy()), np.asarray(est2.theta_.numpy()),
            rtol=1e-4, atol=1e-5,
        )


class TestKNNSweep:
    @pytest.mark.parametrize("split", [None, 0])
    @pytest.mark.parametrize("k", [1, 5])
    def test_predict_matches_sklearn(self, split, k):
        ref = sknn.KNeighborsClassifier(n_neighbors=k).fit(_XC, _Y)
        est = ht.classification.KNeighborsClassifier(n_neighbors=k)
        est.fit(ht.array(_XC, split=split), ht.array(_Y, split=split))
        got = est.predict(ht.array(_XT, split=split)).numpy().ravel()
        # blobs are well separated: labels must agree exactly
        np.testing.assert_array_equal(got, ref.predict(_XT))


class TestPreprocessingSweep:
    @pytest.mark.parametrize("split", [None, 0])
    def test_standard_scaler(self, split):
        ref = skpp.StandardScaler().fit_transform(_X)
        got = ht.preprocessing.StandardScaler().fit_transform(ht.array(_X, split=split))
        np.testing.assert_allclose(got.numpy(), ref, rtol=2e-4, atol=2e-4)

    @pytest.mark.parametrize("split", [None, 0])
    def test_minmax_scaler(self, split):
        ref = skpp.MinMaxScaler().fit_transform(_X)
        got = ht.preprocessing.MinMaxScaler().fit_transform(ht.array(_X, split=split))
        np.testing.assert_allclose(got.numpy(), ref, rtol=2e-4, atol=2e-4)

    @pytest.mark.parametrize("split", [None, 0])
    def test_maxabs_robust_normalizer(self, split):
        x = ht.array(_X, split=split)
        np.testing.assert_allclose(
            ht.preprocessing.MaxAbsScaler().fit_transform(x).numpy(),
            skpp.MaxAbsScaler().fit_transform(_X),
            rtol=2e-4, atol=2e-4,
        )
        np.testing.assert_allclose(
            ht.preprocessing.RobustScaler().fit_transform(x).numpy(),
            skpp.RobustScaler().fit_transform(_X),
            rtol=2e-3, atol=2e-3,
        )
        np.testing.assert_allclose(
            ht.preprocessing.Normalizer().fit_transform(x).numpy(),
            skpp.Normalizer().fit_transform(_X),
            rtol=2e-4, atol=2e-4,
        )

    def test_inverse_transform_roundtrip(self):
        sc = ht.preprocessing.StandardScaler()
        x = ht.array(_X, split=0)
        z = sc.fit_transform(x)
        back = sc.inverse_transform(z)
        np.testing.assert_allclose(back.numpy(), _X, rtol=1e-4, atol=1e-4)


class TestLassoSweep:
    @pytest.mark.parametrize("split", [None, 0])
    def test_sparse_recovery(self, split):
        rng = np.random.default_rng(0)
        n, m = 400, 12
        X = rng.standard_normal((n, m)).astype(np.float32)
        beta = np.zeros(m, np.float32)
        beta[[1, 4]] = [2.0, -3.0]
        y = X @ beta + 0.01 * rng.standard_normal(n).astype(np.float32)
        est = ht.regression.Lasso(lam=0.05, max_iter=200)
        est.fit(ht.array(X, split=split), ht.array(y, split=split))
        coef = np.asarray(est.coef_.numpy()).ravel()
        assert abs(coef[1] - 2.0) < 0.15
        assert abs(coef[4] + 3.0) < 0.15
        others = np.delete(coef, [1, 4])
        assert np.max(np.abs(others)) < 0.1


class TestGraphSpectralSweep:
    @pytest.mark.parametrize("split", [None, 0])
    def test_laplacian_simple_vs_scipy(self, split):
        from scipy.sparse import csgraph

        rng = np.random.default_rng(1)
        pts = rng.standard_normal((17, 3)).astype(np.float32)
        # fully-connected similarity graph; simple L = D - W
        lap = ht.graph.Laplacian(
            lambda x: ht.spatial.rbf(x, sigma=1.0), definition="simple",
            mode="fully_connected",
        )
        L = lap.construct(ht.array(pts, split=split))
        from scipy.spatial.distance import cdist as scd

        W = np.exp(-(scd(pts, pts) ** 2) / 2.0).astype(np.float64)
        np.fill_diagonal(W, 0.0)
        ref = csgraph.laplacian(W, normed=False)
        np.testing.assert_allclose(np.asarray(L.numpy(), np.float64), ref, rtol=2e-3, atol=2e-3)

    def test_spectral_separates_blobs(self):
        rng = np.random.default_rng(0)
        a = rng.standard_normal((24, 2)).astype(np.float32) * 0.2
        b = rng.standard_normal((24, 2)).astype(np.float32) * 0.2 + 5.0
        pts = np.concatenate([a, b])
        est = ht.cluster.Spectral(n_clusters=2, gamma=1.0, n_lanczos=20)
        est.fit(ht.array(pts, split=0))
        labels = est.labels_.numpy().ravel()
        # the two blobs must land in different clusters
        assert len(set(labels[:24])) == 1
        assert len(set(labels[24:])) == 1
        assert labels[0] != labels[-1]
