"""docs/TUTORIAL.md is executable documentation: every ```python block
runs top-to-bottom in one namespace on the 8-device CPU mesh (the
tutorial's own stated contract; analog of the reference's doctested
tutorials under doc/source/)."""

import os
import re

from test_suites.basic_test import TestCase

TUTORIAL = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                        "docs", "TUTORIAL.md")


class TestTutorial(TestCase):
    def test_tutorial_blocks_run(self):
        with open(TUTORIAL) as f:
            text = f.read()
        blocks = re.findall(r"```python\n(.*?)```", text, flags=re.S)
        assert len(blocks) >= 6, f"tutorial lost its code blocks ({len(blocks)})"
        ns: dict = {}
        for i, block in enumerate(blocks):
            try:
                exec(compile(block, f"TUTORIAL.md[block {i}]", "exec"), ns)
            except Exception as e:  # pragma: no cover - failure reporting
                raise AssertionError(
                    f"tutorial block {i} failed: {e}\n--- block ---\n{block}"
                ) from e
