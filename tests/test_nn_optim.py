"""Deep-learning layer tests: DataParallel + DataParallelOptimizer + DASO.

The analog of the reference's examples/nn/mnist.py training loop (BASELINE
config #5) exercised on the virtual 8-device mesh: a synthetic separable
classification task must train to high accuracy, the DP step's loss must
match a hand-rolled single-device replica step, and DASO must converge with
staggered global syncs.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import heat_tpu as ht
from heat_tpu import nn as htnn
from heat_tpu import optim as htoptim


def _toy_problem(n=512, d=16, classes=4, seed=0):
    rng = np.random.default_rng(seed)
    w = rng.standard_normal((d, classes)).astype(np.float32)
    x = rng.standard_normal((n, d)).astype(np.float32)
    y = np.argmax(x @ w + 0.1 * rng.standard_normal((n, classes)).astype(np.float32), axis=1)
    return x, y.astype(np.int32)


def _mlp(d=16, classes=4):
    return htnn.Sequential(
        htnn.Linear(d, 32),
        htnn.ReLU(),
        htnn.Linear(32, classes),
    )


class TestDataParallel:
    def test_forward_shapes_and_split(self):
        model = htnn.Sequential(htnn.Linear(8, 3), htnn.Tanh())
        dp = htnn.DataParallel(model, key=0)
        x = ht.random.randn(40, 8, split=0)
        out = dp(x)
        assert out.shape == (40, 3)
        assert out.split == 0
        # forward matches the functional apply on the logical array
        ref = model.apply(dp.params, x.larray)
        np.testing.assert_allclose(out.numpy(), np.asarray(ref), rtol=1e-5, atol=1e-5)

    def test_training_converges(self):
        x_np, y_np = _toy_problem()
        x = ht.array(x_np, split=0)
        y = ht.array(y_np, split=0)
        dp = htnn.DataParallel(_mlp(), key=1)
        opt = htoptim.DataParallelOptimizer(htoptim.Adam(lr=0.01), dp)
        losses = [float(opt.step(x, y)) for _ in range(60)]
        assert losses[-1] < 0.25 * losses[0], losses[::10]
        preds = np.argmax(dp(x).numpy(), axis=1)
        assert (preds == y_np).mean() > 0.9

    def test_dp_matches_single_device_replica(self):
        """Grad-allreduce semantics: the sharded-batch step must produce the
        same parameters as an unsharded replica computing the global-mean
        loss (the invariant the reference's Allreduce hooks maintain,
        data_parallel.py:219-237)."""
        x_np, y_np = _toy_problem(n=64, seed=3)
        model = _mlp()
        dp = htnn.DataParallel(model, key=5)
        # deep-copy: the fused step donates the live param buffers
        params0 = jax.tree.map(lambda a: jnp.array(a, copy=True), dp.params)
        opt = htoptim.DataParallelOptimizer(htoptim.SGD(lr=0.1), dp)
        loss_dist = float(opt.step(ht.array(x_np, split=0), ht.array(y_np, split=0)))

        # oracle: same init, plain single-array step
        import optax
        tx = optax.sgd(0.1)
        st = tx.init(params0)
        ce = htnn.CrossEntropyLoss()

        def lf(p):
            return ce.raw(model.apply(p, jnp.asarray(x_np)), jnp.asarray(y_np))

        loss_ref, g = jax.value_and_grad(lf)(params0)
        upd, _ = tx.update(g, st, params0)
        ref_params = optax.apply_updates(params0, upd)

        assert abs(loss_dist - float(loss_ref)) < 1e-5
        for a, b in zip(jax.tree.leaves(dp.params), jax.tree.leaves(ref_params)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6)

    def test_uneven_batch_masked(self):
        """Padded batch rows must not contribute to loss or gradients."""
        x_np, y_np = _toy_problem(n=100, seed=4)  # 100 over 8 devices: pad to 104
        dp = htnn.DataParallel(_mlp(), key=2)
        opt = htoptim.DataParallelOptimizer(htoptim.SGD(lr=0.05), dp)
        loss = float(opt.step(ht.array(x_np, split=0), ht.array(y_np, split=0)))

        ce = htnn.CrossEntropyLoss()
        dp2 = htnn.DataParallel(_mlp(), key=2)
        ref = float(ce.raw(dp2.module.apply(dp2.params, jnp.asarray(x_np)), jnp.asarray(y_np)))
        assert abs(loss - ref) < 1e-5

    def test_loss_callable_on_dndarrays(self):
        x_np, y_np = _toy_problem(n=32, seed=6)
        dp = htnn.DataParallel(_mlp(), key=0)
        out = dp(ht.array(x_np, split=0))
        loss = htnn.CrossEntropyLoss()(out, ht.array(y_np, split=0))
        ref = htnn.CrossEntropyLoss().raw(dp(jnp.asarray(x_np)), jnp.asarray(y_np))
        assert abs(float(loss) - float(ref)) < 1e-5


class TestDASO:
    @pytest.fixture(autouse=True)
    def _needs_even_mesh(self):
        # DASO's two-level ("node", "local") mesh factorization requires
        # divisibility — same constraint as the reference's node groups
        if ht.get_comm().size % 2 != 0:
            pytest.skip("DASO n_nodes=2 needs an even mesh")

    def test_daso_converges_and_syncs(self):
        x_np, y_np = _toy_problem(n=512, seed=7)
        x = ht.array(x_np, split=0)
        y = ht.array(y_np, split=0)
        dp = htnn.DataParallel(_mlp(), key=1)
        daso = htoptim.DASO(htoptim.Adam(lr=0.01), dp, n_nodes=2, global_skip=4)
        losses = [float(daso.step(x, y)) for _ in range(60)]
        assert losses[-1] < 0.3 * losses[0], losses[::10]
        # eval through the wrapped model must see trained weights WITHOUT an
        # explicit sync (the reference mutates the torch model in place)
        preds = np.argmax(dp(x).numpy(), axis=1)
        assert (preds == y_np).mean() > 0.85
        # node copies agree right after a forced sync
        daso.sync_params()
        stacked = jax.tree.leaves(daso.params)[0]
        np.testing.assert_allclose(np.asarray(stacked[0]), np.asarray(stacked[1]), rtol=1e-6)

    def test_daso_global_sync_equalizes_nodes(self):
        x_np, y_np = _toy_problem(n=256, seed=8)
        x = ht.array(x_np, split=0)
        y = ht.array(y_np, split=0)
        dp = htnn.DataParallel(_mlp(), key=3)
        daso = htoptim.DASO(htoptim.SGD(lr=0.05), dp, n_nodes=2, global_skip=3, compression=False)
        for i in range(1, 7):
            daso.step(x, y)
            leaf = np.asarray(jax.tree.leaves(daso.params)[0])
            same = np.allclose(leaf[0], leaf[1], rtol=1e-6, atol=1e-7)
            assert same == (i % 3 == 0), f"iter {i}: node agreement {same}"

    def test_daso_state_dict_and_load(self):
        """Checkpoints during DASO training must capture trained weights,
        and loading must redirect subsequent forwards."""
        x_np, y_np = _toy_problem(n=256, seed=12)
        x, y = ht.array(x_np, split=0), ht.array(y_np, split=0)
        dp = htnn.DataParallel(_mlp(), key=9)
        init_leaf = np.asarray(jax.tree.leaves(dp.params)[0]).copy()
        daso = htoptim.DASO(htoptim.SGD(lr=0.1), dp, n_nodes=2, global_skip=2)
        for _ in range(5):
            daso.step(x, y)
        ckpt = dp.state_dict()
        trained_leaf = np.asarray(jax.tree.leaves(ckpt)[0])
        assert not np.allclose(trained_leaf, init_leaf), "state_dict returned init weights"
        out_before = dp(x).numpy()
        for _ in range(5):
            daso.step(x, y)
        dp.load_state_dict(ckpt)
        np.testing.assert_allclose(dp(x).numpy(), out_before, rtol=1e-5, atol=1e-6)

    def test_daso_custom_loss_raw_contract(self):
        """A loss implementing only the documented raw() API must work."""
        class L2Loss:
            def raw(self, output, target, weight=None):
                per = jnp.sum((output - jax.nn.one_hot(target, output.shape[-1])) ** 2, axis=-1)
                if weight is not None:
                    return jnp.sum(per * weight) / jnp.maximum(jnp.sum(weight), 1.0)
                return jnp.mean(per)

        x_np, y_np = _toy_problem(n=128, seed=13)
        dp = htnn.DataParallel(_mlp(), key=4)
        daso = htoptim.DASO(htoptim.SGD(lr=0.05), dp, n_nodes=2, loss=L2Loss())
        l0 = float(daso.step(ht.array(x_np, split=0), ht.array(y_np, split=0)))
        l1 = float(daso.step(ht.array(x_np, split=0), ht.array(y_np, split=0)))
        assert np.isfinite(l0) and np.isfinite(l1)

    def test_daso_lr_scheduler(self):
        dp = htnn.DataParallel(_mlp(), key=0)
        daso = htoptim.DASO(htoptim.SGD(lr=0.2), dp, n_nodes=2)
        sched = htoptim.lr_scheduler.ExponentialLR(daso, gamma=0.5)
        assert abs(daso.lr - 0.2) < 1e-8
        sched.step()
        assert abs(daso.lr - 0.1) < 1e-8

    def test_epoch_loss_logic_matches_reference_policy(self):
        """The schedule must take the reference's decisions verbatim on a
        scripted loss sequence (reference dp_optimizer.py:354-470):
        warmup zeros → post-warmup (4,1,1) → plateaus collapse the skips
        by the reduction factor → bottoming out at gs=1 widens back to
        max_gs → cooldown zeros."""
        dp = htnn.DataParallel(_mlp(), key=0)
        daso = htoptim.DASO(
            htoptim.SGD(lr=0.01), dp, n_nodes=2,
            total_epochs=20, warmup_epochs=2, cooldown_epochs=2,
            stability_level=0.05, max_global_skips=8,
        )
        # hand-simulated reference trace: (loss, gs, ls, btw) AFTER the call
        flat = 0.8  # < 5% change → counts as a bad epoch
        trace = [
            (1.0, 0, 0, 0),   # warmup epoch 0
            (0.9, 0, 0, 0),   # warmup epoch 1
            (flat, 4, 1, 1),  # end of warmup: (4,1,1); best=0.8, improving
            (flat, 4, 1, 1),  # bad 1
            (flat, 4, 1, 1),  # bad 2 (patience)
            (flat, 2, 1, 1),  # bad 3 > patience → plateau: gs 4→2, clamps
            (flat, 2, 1, 1),  # counter reset after detection: bad 1
            (flat, 2, 1, 1),  # bad 2
            (flat, 1, 1, 1),  # plateau → gs 2→1
            (flat, 1, 1, 1),
            (flat, 1, 1, 1),
            (flat, 8, 2, 2),  # plateau at gs=1 → widen to max_gs
            (0.2, 8, 2, 2),   # real improvement: counter resets, no change
            (flat, 8, 2, 2),  # bad 1 (vs best 0.2)
            (flat, 8, 2, 2),  # bad 2
            (flat, 4, 1, 1),  # plateau → gs 8→4, ls 2→1, btw 2→1
            (flat, 4, 1, 1),
            (flat, 4, 1, 1),
            (flat, 0, 0, 0),  # epoch 18 ≥ total-cooldown → cooldown zeros
            (flat, 0, 0, 0),  # epoch 19
        ]
        for i, (loss, gs, ls, btw) in enumerate(trace):
            daso.epoch_loss_logic(loss)
            assert (daso.global_skip, daso.local_skip, daso.batches_to_wait) == (
                gs, ls, btw
            ), f"epoch {i}: got {(daso.global_skip, daso.local_skip, daso.batches_to_wait)}"

    def test_daso_converges_through_schedule(self):
        """End-to-end: training drives the schedule through warmup and
        adaptation while the loss still decreases."""
        x_np, y_np = _toy_problem(n=256, seed=11)
        x = ht.array(x_np, split=0)
        y = ht.array(y_np, split=0)
        dp = htnn.DataParallel(_mlp(), key=3)
        daso = htoptim.DASO(htoptim.SGD(lr=0.05), dp, n_nodes=2,
                            total_epochs=8, warmup_epochs=1, cooldown_epochs=1)
        epoch_losses = []
        for _ in range(8):
            losses = [float(daso.step(x, y)) for _ in range(4)]
            epoch_losses.append(losses[-1])
            daso.epoch_loss_logic(epoch_losses[-1])
        assert daso.epoch == 8
        assert epoch_losses[-1] < epoch_losses[0], epoch_losses
        # cooldown reached: full sync restored
        assert daso.global_skip == 0


class TestSchedulersAndUtils:
    def test_step_lr(self):
        dp = htnn.DataParallel(_mlp(), key=0)
        opt = htoptim.DataParallelOptimizer(htoptim.SGD(lr=0.1), dp)
        sched = htoptim.lr_scheduler.StepLR(opt, step_size=2, gamma=0.1)
        sched.step()
        assert abs(opt.lr - 0.1) < 1e-8
        sched.step()
        assert abs(opt.lr - 0.01) < 1e-8
        # the updated lr actually drives the next step
        x_np, y_np = _toy_problem(n=32, seed=1)
        before = [np.asarray(l).copy() for l in jax.tree.leaves(dp.params)]
        opt.step(ht.array(x_np, split=0), ht.array(y_np, split=0))
        after = jax.tree.leaves(dp.params)
        deltas = [np.abs(np.asarray(a) - b).max() for a, b in zip(after, before)]
        assert max(deltas) < 0.05  # tiny lr → tiny update

    def test_plateau_detector(self):
        det = htoptim.DetectMetricPlateau(patience=2)
        assert not det.test_if_improving(1.0)
        assert not det.test_if_improving(0.5)
        assert not det.test_if_improving(0.5)
        assert not det.test_if_improving(0.5)
        assert det.test_if_improving(0.5)  # patience exceeded
        state = det.get_state()
        det2 = htoptim.DetectMetricPlateau()
        det2.set_state(state)
        assert det2.best == det.best

    def test_nn_flax_fallback(self):
        import flax.linen as linen
        assert htnn.Conv is linen.Conv

    def test_optim_optax_fallback(self):
        import optax
        assert htoptim.cosine_decay_schedule is optax.cosine_decay_schedule


class TestRingAttention:
    """Sequence-parallel exact attention (nn.attention) — the TPU-native
    long-context primitive (no reference analog; SURVEY §5 names the ring
    mechanism of distance.py:262-359 as its building block)."""

    @staticmethod
    def _dense(q, k, v, causal, scale):
        s = np.einsum("...qd,...kd->...qk", q, k) * scale
        if causal:
            S1, S2 = s.shape[-2:]
            s = np.where(np.tril(np.ones((S1, S2), bool)), s, -1e30)
        p = np.exp(s - s.max(-1, keepdims=True))
        return np.einsum("...qk,...kd->...qd", p / p.sum(-1, keepdims=True), v)

    @pytest.mark.parametrize("S", [64, 61, 11])
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_dense(self, S, causal):
        rng = np.random.default_rng(S)
        qn, kn, vn = (rng.standard_normal((S, 8)).astype(np.float32) for _ in range(3))
        q, k, v = (ht.array(x, split=0) for x in (qn, kn, vn))
        out = ht.nn.ring_attention(q, k, v, causal=causal)
        assert out.split == 0
        ref = self._dense(qn, kn, vn, causal, 1 / np.sqrt(8))
        np.testing.assert_allclose(out.numpy(), ref, rtol=2e-4, atol=2e-5)
        phys = np.asarray(jax.device_get(out._phys))
        assert np.all(phys[S:] == 0)

    def test_batched_heads(self):
        rng = np.random.default_rng(0)
        B, H, S, D = 2, 3, 33, 8
        qn, kn, vn = (rng.standard_normal((B, H, S, D)).astype(np.float32) for _ in range(3))
        q, k, v = (ht.array(x, split=2) for x in (qn, kn, vn))
        out = ht.nn.ring_attention(q, k, v, causal=True)
        ref = self._dense(qn, kn, vn, True, 1 / np.sqrt(D))
        np.testing.assert_allclose(out.numpy(), ref, rtol=2e-4, atol=2e-5)

    def test_replicated_and_self(self):
        rng = np.random.default_rng(1)
        xn = rng.standard_normal((17, 8)).astype(np.float32)
        x = ht.array(xn)
        out = ht.nn.ring_self_attention(x)
        ref = self._dense(xn, xn, xn, False, 1 / np.sqrt(8))
        np.testing.assert_allclose(out.numpy(), ref, rtol=2e-4, atol=2e-5)

    def test_differentiable(self):
        import jax.numpy as jnp
        from heat_tpu.nn.attention import _ring_attention_program

        comm = ht.get_comm()
        prog = _ring_attention_program(
            comm.mesh, comm.axis_name, 2, 0, 64, 64, False, float(1 / np.sqrt(8)), "float32"
        )
        qj = comm.shard(jnp.asarray(np.random.default_rng(2).standard_normal((64, 8)).astype(np.float32)), 0)
        g = jax.grad(lambda a: prog(a, a, a).sum())(qj)
        assert np.isfinite(np.asarray(jax.device_get(g))).all()

    @pytest.mark.parametrize("S,chunk", [(8 * 8, 3), (8 * 8 - 5, 4), (8 * 8, 16)])
    def test_inner_chunking_matches_unchunked(self, S, chunk):
        # the per-step K/V tiling (bounded live memory at scale) must be
        # numerically invisible, incl. non-dividing chunks and uneven
        # global sequence lengths, and stay differentiable
        import jax.numpy as jnp
        from heat_tpu.nn.attention import _ring_attention_program

        comm = ht.get_comm()
        D = 8
        scale = float(1 / np.sqrt(D))
        rng = np.random.default_rng(S + chunk)
        qn, kn, vn = (rng.standard_normal((S, D)).astype(np.float32) for _ in range(3))
        args = tuple(comm.shard(jnp.asarray(a), 0) for a in (qn, kn, vn))
        S_pad = args[0].shape[0]
        prog_c = _ring_attention_program(
            comm.mesh, comm.axis_name, 2, 0, S, S, True, scale, "float32", chunk
        )
        prog_full = _ring_attention_program(
            comm.mesh, comm.axis_name, 2, 0, S, S, True, scale, "float32", S_pad
        )
        out_c = np.asarray(jax.device_get(prog_c(*args)))[:S]
        out_f = np.asarray(jax.device_get(prog_full(*args)))[:S]
        np.testing.assert_allclose(out_c, out_f, rtol=1e-5, atol=1e-6)
        # the backward through the inner scan + dynamic_slice transpose
        # must MATCH the unchunked gradients (not merely be finite)
        def loss(prog):
            return lambda q, k, v: (prog(q, k, v) ** 2).sum()
        g_c = jax.grad(loss(prog_c), argnums=(0, 1, 2))(*args)
        g_f = jax.grad(loss(prog_full), argnums=(0, 1, 2))(*args)
        for gc, gf, name in zip(g_c, g_f, "qkv"):
            np.testing.assert_allclose(
                np.asarray(jax.device_get(gc)), np.asarray(jax.device_get(gf)),
                rtol=1e-4, atol=1e-5, err_msg=f"d{name} mismatch",
            )

    def test_gradient_matches_dense_oracle(self):
        # the ring program's grad (through scan + ppermute transpose
        # rules) must equal the dense attention gradient, not merely be
        # finite — this pins training-through-ring-attention numerics
        import jax.numpy as jnp
        from heat_tpu.nn.attention import _ring_attention_program

        comm = ht.get_comm()
        S, D = 8 * comm.size, 8
        scale = float(1 / np.sqrt(D))
        rng = np.random.default_rng(7)
        qn, kn, vn = (rng.standard_normal((S, D)).astype(np.float32) for _ in range(3))
        prog = _ring_attention_program(
            comm.mesh, comm.axis_name, 2, 0, S, S, True, scale, "float32"
        )

        def dense(q, k, v):
            s = (q @ k.T) * scale
            s = jnp.where(jnp.tril(jnp.ones((S, S), bool)), s, jnp.finfo(jnp.float32).min)
            p = jax.nn.softmax(s, axis=-1)
            return p @ v

        tgt = jnp.asarray(rng.standard_normal((S, D)).astype(np.float32))
        args = tuple(comm.shard(jnp.asarray(a), 0) for a in (qn, kn, vn))
        g_ring = jax.grad(lambda q, k, v: jnp.sum((prog(q, k, v) - tgt) ** 2), argnums=(0, 1, 2))(*args)
        g_dense = jax.grad(
            lambda q, k, v: jnp.sum((dense(q, k, v) - tgt) ** 2), argnums=(0, 1, 2)
        )(jnp.asarray(qn), jnp.asarray(kn), jnp.asarray(vn))
        for gr, gd, name in zip(g_ring, g_dense, "qkv"):
            np.testing.assert_allclose(
                np.asarray(jax.device_get(gr)), np.asarray(gd),
                rtol=2e-3, atol=2e-4, err_msg=f"d{name} mismatch",
            )

    def test_wrong_split_raises(self):
        x = ht.array(np.zeros((4, 8), dtype=np.float32), split=1)
        with pytest.raises(ValueError):
            ht.nn.ring_attention(x, x, x)

    def test_value_head_dim_differs(self):
        # Dv != Dq is legal attention; must work on the DISTRIBUTED ring
        rng = np.random.default_rng(3)
        S = 33
        qn = rng.standard_normal((S, 4)).astype(np.float32)
        kn = rng.standard_normal((S, 4)).astype(np.float32)
        vn = rng.standard_normal((S, 6)).astype(np.float32)
        out = ht.nn.ring_attention(*(ht.array(x, split=0) for x in (qn, kn, vn)))
        assert out.shape == (S, 6)
        ref = self._dense(qn, kn, vn, False, 1 / np.sqrt(4))
        np.testing.assert_allclose(out.numpy(), ref, rtol=2e-4, atol=2e-5)


class TestRingKernelAttention:
    """Kernel-backed ring attention (VERDICT r4 #1): each ring step runs
    the splash/flash Pallas kernel in save-residuals form and the per-step
    (out, lse) combine must be EXACT against the blocked-XLA ring oracle.
    CPU meshes run the kernels in Mosaic interpret mode."""

    B, H, S, D = 1, 2, 1024, 64

    def _mk(self, dtype=np.float32, seed=0):
        rng = np.random.default_rng(seed)
        return tuple(
            rng.standard_normal((self.B, self.H, self.S, self.D)).astype(dtype)
            for _ in range(3)
        )

    @pytest.mark.slow
    @pytest.mark.parametrize("causal", [False, True])
    def test_kernel_ring_matches_blocked_oracle_p8(self, causal):
        import heat_tpu.nn.attention as att

        comm = ht.get_comm()
        scale = float(1 / np.sqrt(self.D))
        qn, kn, vn = self._mk()
        q, k, v = (ht.array(x, split=2) for x in (qn, kn, vn))
        kprog = att._ring_attention_kernel_program(
            comm.mesh, comm.axis_name, self.S, self.S, self.B, self.H,
            self.D, causal, scale, "float32", True,
        )
        assert kprog is not None
        out_k = np.asarray(jax.device_get(kprog(q._phys, k._phys, v._phys)))
        prog = att._ring_attention_program(
            comm.mesh, comm.axis_name, 4, 2, self.S, self.S, causal,
            scale, "float32",
        )
        out_b = np.asarray(jax.device_get(prog(q._phys, k._phys, v._phys)))
        np.testing.assert_allclose(out_k, out_b, rtol=2e-5, atol=2e-6)

    @pytest.mark.slow
    def test_public_dispatch_routes_to_kernel_and_matches_dense(self, monkeypatch):
        import heat_tpu.nn.attention as att

        monkeypatch.setattr(att, "_RING_KERNEL_INTERPRET", True)
        calls = []
        orig = att._ring_attention_kernel_program

        def spy(*a, **kw):
            r = orig(*a, **kw)
            calls.append(r is not None)
            return r

        monkeypatch.setattr(att, "_ring_attention_kernel_program", spy)
        qn, kn, vn = self._mk(seed=1)
        q, k, v = (ht.array(x, split=2) for x in (qn, kn, vn))
        out = ht.nn.ring_attention(q, k, v, causal=True)
        assert calls == [True], "kernel ring program was not dispatched"
        assert out.split == 2
        ref = TestRingAttention._dense(qn, kn, vn, True, 1 / np.sqrt(self.D))
        np.testing.assert_allclose(out.numpy(), ref, rtol=2e-4, atol=2e-5)

    @pytest.mark.slow
    def test_kernel_ring_p1_wrapper_is_exact(self):
        """Size-1 ring: the wrapper (scan of one step + switch) around the
        kernel must be numerically invisible — the real-chip bench pins
        its cost; this pins its numerics."""
        import heat_tpu.nn.attention as att
        from jax.sharding import Mesh

        mesh1 = Mesh(np.asarray(jax.devices()[:1]), ("d",))
        scale = float(1 / np.sqrt(self.D))
        qn, kn, vn = self._mk(seed=2)
        kprog = att._ring_attention_kernel_program(
            mesh1, "d", self.S, self.S, self.B, self.H, self.D, True,
            scale, "float32", True,
        )
        assert kprog is not None
        out_k = np.asarray(jax.device_get(kprog(*map(jnp.asarray, (qn, kn, vn)))))
        ref = TestRingAttention._dense(qn, kn, vn, True, scale)
        np.testing.assert_allclose(out_k, ref, rtol=2e-4, atol=2e-5)

    @pytest.mark.slow
    def test_kernel_ring_bf16(self):
        import heat_tpu.nn.attention as att

        comm = ht.get_comm()
        scale = float(1 / np.sqrt(self.D))
        qn, kn, vn = self._mk(seed=3)
        args = tuple(
            ht.array(x, split=2).astype(ht.bfloat16)._phys for x in (qn, kn, vn)
        )
        kprog = att._ring_attention_kernel_program(
            comm.mesh, comm.axis_name, self.S, self.S, self.B, self.H,
            self.D, True, scale, "bfloat16", True,
        )
        assert kprog is not None
        out_k = np.asarray(jax.device_get(kprog(*args))).astype(np.float32)
        ref = TestRingAttention._dense(qn, kn, vn, True, scale)
        # bf16 storage + bf16 kernel matmuls: ~8-bit mantissa tolerance
        np.testing.assert_allclose(out_k, ref, rtol=0.06, atol=0.06)

    def test_kernel_ring_hlo_ppermute_structure(self):
        """The kernel ring is UNROLLED over the static ring length:
        exactly 2(p-1) collective-permutes — K and V per hop, and the
        final wasted rotation elided — never an all-gather. Same total
        ICI bytes as the blocked ring's 2-permute scan, minus one hop.
        S is derived from the mesh size so the odd-mesh CI leg exercises
        it too."""
        import heat_tpu.nn.attention as att

        comm = ht.get_comm()
        S = 128 * comm.size  # 128-row shards: smallest splash block
        scale = float(1 / np.sqrt(self.D))
        kprog = att._ring_attention_kernel_program(
            comm.mesh, comm.axis_name, S, S, self.B, self.H,
            self.D, True, scale, "float32", True,
        )
        if kprog is None:
            # capability gate, not a regression: older splash kernels
            # demand head_dim % 128 == 0 and refuse this D=64 signature
            # (dispatch then falls back to the blocked XLA ring). Probe
            # the kernel directly so a real program-build break on a
            # capable runtime still fails loudly.
            import jax.numpy as jnp

            fns = att._build_splash_mha(
                self.H, 128, 128, False, scale, 128, 128, True, True
            )
            shp = jax.ShapeDtypeStruct((self.B, self.H, 128, self.D), jnp.float32)
            try:
                jax.eval_shape(fns, shp, shp, shp)
            except NotImplementedError as e:
                pytest.skip(f"runtime splash kernel cannot serve D={self.D}: {e}")
        assert kprog is not None
        txt = kprog.as_text()
        n_pp = txt.count(" collective-permute(") + txt.count("collective-permute-start(")
        want = 2 * (comm.size - 1)
        assert n_pp == want, f"kernel ring ppermute count {n_pp} != {want}"
        assert " all-gather(" not in txt and "all-gather-start(" not in txt

    @pytest.mark.slow
    @pytest.mark.parametrize("causal", [False, True])
    def test_scan_body_matches_blocked_oracle_p8(self, causal, monkeypatch):
        """The scan-with-carry ring body — the composition real-TPU f32
        (flash) dispatch runs, which the unrolled-by-default CPU suite
        would otherwise never compile — must match the blocked oracle
        too (code-review r5)."""
        import heat_tpu.nn.attention as att

        monkeypatch.setattr(att, "_RING_KERNEL_FORCE_SCAN", True)
        att._ring_attention_kernel_callable.cache_clear()
        att._ring_attention_kernel_program.cache_clear()
        try:
            comm = ht.get_comm()
            scale = float(1 / np.sqrt(self.D))
            qn, kn, vn = self._mk(seed=4)
            q, k, v = (ht.array(x, split=2) for x in (qn, kn, vn))
            kprog = att._ring_attention_kernel_program(
                comm.mesh, comm.axis_name, self.S, self.S, self.B, self.H,
                self.D, causal, scale, "float32", True,
            )
            assert kprog is not None
            out_k = np.asarray(jax.device_get(kprog(q._phys, k._phys, v._phys)))
            prog = att._ring_attention_program(
                comm.mesh, comm.axis_name, 4, 2, self.S, self.S, causal,
                scale, "float32",
            )
            out_b = np.asarray(jax.device_get(prog(q._phys, k._phys, v._phys)))
            np.testing.assert_allclose(out_k, out_b, rtol=2e-5, atol=2e-6)
        finally:
            att._ring_attention_kernel_callable.cache_clear()
            att._ring_attention_kernel_program.cache_clear()

    def test_ineligible_signatures_fall_back(self):
        import heat_tpu.nn.attention as att

        comm = ht.get_comm()
        # non-divisible global sequence → pad rows the kernels cannot mask
        assert (
            att._ring_attention_kernel_program(
                comm.mesh, comm.axis_name, 1001, 1001, 1, 2, 64, False,
                0.125, "float32", True,
            )
            is None
        )
        # causal with mismatched q/kv lengths has no diagonal kernel
        assert (
            att._ring_attention_kernel_program(
                comm.mesh, comm.axis_name, 1024, 2048, 1, 2, 64, True,
                0.125, "float32", True,
            )
            is None
        )
        # tracers (user jit/grad) must never take the kernel path, even
        # when the platform gate is open
        import unittest.mock as mock

        hit = []

        def probe(x):
            with mock.patch.object(att, "_RING_KERNEL_INTERPRET", True):
                hit.append(att._ring_kernel_eligible(x, x, x, 4, 2, jnp.float32))
            return x

        jax.make_jaxpr(probe)(jnp.zeros((1, 2, 64, 64), jnp.float32))
        assert hit == [False]


class TestPallasAttentionGating:
    """The Mosaic flash kernel is a TPU-only fast path: on any other
    backend the gate must return None (blocked program serves), and a
    per-signature compile failure must not disable other signatures."""

    def test_gate_off_on_non_tpu_backend(self):
        import jax
        import jax.numpy as jnp
        from heat_tpu.nn import attention as att

        if jax.default_backend() == "tpu":
            pytest.skip("gate is open on a real TPU backend")
        x = jnp.zeros((1, 1, 512, 64), jnp.float32)
        assert att._pallas_attention(x, x, x, False, 0.125) is None
        # gating must not have flipped the import-unavailable flag
        assert not att._PALLAS_ATTENTION_UNAVAILABLE

    def test_shape_gate_backend_independent(self):
        import jax.numpy as jnp
        from heat_tpu.nn.attention import _pallas_attention_fits

        good = (1, 1, 512, 64)
        assert _pallas_attention_fits(good, good, good, jnp.float32)
        assert _pallas_attention_fits(good, good, good, jnp.bfloat16)
        # 3-D input, odd seq, odd head dim, f64, cross-attention lengths,
        # mismatched value head dim: all rejected before any compile
        assert not _pallas_attention_fits((8, 512, 64), (8, 512, 64), (8, 512, 64), jnp.float32)
        assert not _pallas_attention_fits((1, 1, 500, 64), (1, 1, 500, 64), (1, 1, 500, 64), jnp.float32)
        assert not _pallas_attention_fits((1, 1, 512, 60), (1, 1, 512, 60), (1, 1, 512, 60), jnp.float32)
        assert not _pallas_attention_fits(good, good, good, jnp.float64)
        assert not _pallas_attention_fits(good, (1, 1, 1024, 64), (1, 1, 1024, 64), jnp.float32)
        assert not _pallas_attention_fits(good, good, (1, 1, 512, 128), jnp.float32)


class TestSDPAAlias:
    """torch-parity F.scaled_dot_product_attention over ring/blocked
    attention (reference functional is a torch passthrough)."""

    def test_matches_oracle_both_routes(self):
        from heat_tpu.nn import functional as F

        rng = np.random.default_rng(0)
        S, D = 33, 8
        qn, kn, vn = (rng.standard_normal((S, D)).astype(np.float32) for _ in range(3))
        s_ = qn @ kn.T / np.sqrt(D)
        s_ = np.where(np.tril(np.ones((S, S), bool)), s_, -1e30)
        p_ = np.exp(s_ - s_.max(-1, keepdims=True)); p_ /= p_.sum(-1, keepdims=True)
        ref = p_ @ vn
        out = F.scaled_dot_product_attention(
            ht.array(qn, split=0), ht.array(kn, split=0), ht.array(vn, split=0),
            is_causal=True,
        )
        np.testing.assert_allclose(out.numpy(), ref, rtol=2e-4, atol=2e-5)
        out2 = F.scaled_dot_product_attention(
            jnp.asarray(qn), jnp.asarray(kn), jnp.asarray(vn), is_causal=True
        )
        np.testing.assert_allclose(np.asarray(out2), ref, rtol=2e-4, atol=2e-5)
        with pytest.raises(NotImplementedError):
            F.scaled_dot_product_attention(jnp.asarray(qn), jnp.asarray(kn), jnp.asarray(vn), attn_mask=1)


class TestConvLayers:
    """CNN layer parity vs torch-CPU oracles — the reference's flagship
    example is a Conv2d/Dropout2d/max_pool2d net (examples/nn/mnist.py:26)
    served there by the torch passthrough."""

    def _torch(self):
        torch = pytest.importorskip("torch")
        return torch

    def test_conv2d_matches_torch(self):
        torch = self._torch()
        import jax
        import jax.numpy as jnp

        rng = np.random.default_rng(0)
        x = rng.standard_normal((2, 3, 12, 12)).astype(np.float32)
        for stride, padding in [(1, 0), (2, 1), (1, (2, 1))]:
            m = htnn.Conv2d(3, 5, 3, stride=stride, padding=padding)
            params = m.init(jax.random.PRNGKey(0))
            tconv = torch.nn.Conv2d(3, 5, 3, stride=stride, padding=padding)
            with torch.no_grad():
                tconv.weight.copy_(torch.from_numpy(np.asarray(params["weight"])))
                tconv.bias.copy_(torch.from_numpy(np.asarray(params["bias"])))
                ref = tconv(torch.from_numpy(x)).numpy()
            got = np.asarray(m.apply(params, jnp.asarray(x)))
            np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-5)

    def test_conv2d_same_padding_matches_torch(self):
        torch = self._torch()
        import torch.nn.functional as tF
        import jax.numpy as jnp

        rng = np.random.default_rng(2)
        x = rng.standard_normal((1, 1, 4, 4)).astype(np.float32)
        # even kernels: torch pads the odd element on the HIGH side
        for k in [2, 3, (2, 3)]:
            w_shape = (1, 1) + (k if isinstance(k, tuple) else (k, k))
            w = rng.standard_normal(w_shape).astype(np.float32)
            ref = tF.conv2d(torch.from_numpy(x), torch.from_numpy(w), padding="same").numpy()
            m = htnn.Conv2d(1, 1, k, padding="same", bias=False)
            got = np.asarray(m.apply({"weight": jnp.asarray(w)}, jnp.asarray(x)))
            np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)
        # torch parity: strided 'same' is rejected
        with pytest.raises(ValueError):
            htnn.Conv2d(1, 1, 3, stride=2, padding="same")

    def test_maxpool_integer_dtype(self):
        import jax.numpy as jnp

        x = jnp.arange(16, dtype=jnp.int32).reshape(1, 1, 4, 4)
        out = np.asarray(htnn.MaxPool2d(2).apply({}, x))
        np.testing.assert_array_equal(out[0, 0], [[5, 7], [13, 15]])

    def test_pools_match_torch(self):
        torch = self._torch()
        import jax.numpy as jnp

        rng = np.random.default_rng(1)
        x = rng.standard_normal((2, 4, 10, 10)).astype(np.float32)
        for k, s in [(2, None), (3, 2), ((2, 3), (1, 2))]:
            got = np.asarray(htnn.MaxPool2d(k, s).apply({}, jnp.asarray(x)))
            ref = torch.nn.functional.max_pool2d(
                torch.from_numpy(x), k, stride=s
            ).numpy()
            np.testing.assert_allclose(got, ref)
            got = np.asarray(htnn.AvgPool2d(k, s).apply({}, jnp.asarray(x)))
            ref = torch.nn.functional.avg_pool2d(
                torch.from_numpy(x), k, stride=s
            ).numpy()
            # atol: reduce_window may sum the window in a different order
            # than torch — near-zero outputs can differ by an ULP or two
            np.testing.assert_allclose(got, ref, rtol=1e-6, atol=1e-7)

    def test_dropout2d_channelwise(self):
        import jax
        import jax.numpy as jnp

        x = jnp.ones((4, 6, 5, 5), jnp.float32)
        out = np.asarray(
            htnn.Dropout2d(0.5).apply({}, x, train=True, key=jax.random.PRNGKey(3))
        )
        # each (sample, channel) map is either all-zero or all-scaled
        per_map = out.reshape(4, 6, -1)
        for m in per_map.reshape(24, -1):
            assert np.all(m == 0.0) or np.all(m == 2.0)
        # eval mode: identity
        np.testing.assert_array_equal(
            np.asarray(htnn.Dropout2d(0.5).apply({}, x, train=False)), np.asarray(x)
        )

    def test_cnn_trains_under_data_parallel(self):
        """The reference CNN shape (conv-conv-pool-fc) must train through
        DataParallel + DataParallelOptimizer on the mesh."""
        import jax

        rng = np.random.default_rng(4)
        n = 64
        y_np = rng.integers(0, 2, size=n).astype(np.int32)
        # class-dependent mean patch makes the task learnable
        x_np = (
            rng.standard_normal((n, 1, 8, 8)) + y_np[:, None, None, None] * 2.0
        ).astype(np.float32)
        net = htnn.Sequential(
            htnn.Conv2d(1, 4, 3),
            htnn.ReLU(),
            htnn.MaxPool2d(2),
            htnn.Flatten(),
            htnn.Linear(4 * 3 * 3, 2),
        )
        dp = htnn.DataParallel(net, key=5)
        opt = htoptim.DataParallelOptimizer(htoptim.Adam(lr=0.01), dp)
        x = ht.array(x_np, split=0)
        y = ht.array(y_np, split=0)
        losses = [float(opt.step(x, y)) for _ in range(30)]
        assert losses[-1] < 0.5 * losses[0], losses[::10]
        preds = np.argmax(np.asarray(dp(x).numpy()), axis=1)
        assert (preds == y_np).mean() > 0.9


class TestNormAndEmbedding:
    def test_layernorm_matches_torch(self):
        torch = pytest.importorskip("torch")
        rng = np.random.default_rng(0)
        x = rng.standard_normal((3, 5, 8)).astype(np.float32)
        m = htnn.LayerNorm(8)
        params = m.init(jax.random.PRNGKey(0))
        tln = torch.nn.LayerNorm(8)
        ref = tln(torch.from_numpy(x)).detach().numpy()
        got = np.asarray(m.apply(params, jnp.asarray(x)))
        np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)
        # multi-dim normalized_shape, no affine
        m2 = htnn.LayerNorm((5, 8), elementwise_affine=False)
        tln2 = torch.nn.LayerNorm((5, 8), elementwise_affine=False)
        np.testing.assert_allclose(
            np.asarray(m2.apply({}, jnp.asarray(x))),
            tln2(torch.from_numpy(x)).detach().numpy(),
            rtol=1e-5, atol=1e-6,
        )

    def test_layernorm_shape_mismatch_raises(self):
        m = htnn.LayerNorm(8)
        params = m.init(jax.random.PRNGKey(0))
        with pytest.raises(ValueError):
            m.apply(params, jnp.zeros((3, 5, 1), jnp.float32))
        with pytest.raises(ValueError):
            htnn.LayerNorm((5, 8), elementwise_affine=False).apply({}, jnp.zeros((3, 4, 8)))

    def test_embedding_lookup(self):
        m = htnn.Embedding(10, 4)
        params = m.init(jax.random.PRNGKey(1))
        idx = jnp.asarray([0, 3, 9, 3])
        out = np.asarray(m.apply(params, idx))
        np.testing.assert_array_equal(out[1], out[3])
        np.testing.assert_array_equal(out, np.asarray(params["weight"])[np.asarray(idx)])

    def test_tiny_transformer_block_with_ring_attention(self):
        """Embedding + LayerNorm + ring attention + Linear — the
        long-context building blocks compose on the mesh."""
        S, D = 64, 8
        rng = np.random.default_rng(2)
        tokens = rng.integers(0, 16, size=S).astype(np.int32)
        emb = htnn.Embedding(16, D)
        ln = htnn.LayerNorm(D)
        proj = htnn.Linear(D, D)
        key = jax.random.PRNGKey(3)
        k1, k2, k3 = jax.random.split(key, 3)
        pe, pl, pp = emb.init(k1), ln.init(k2), proj.init(k3)
        h = ln.apply(pl, emb.apply(pe, jnp.asarray(tokens)))
        hd = ht.array(np.asarray(h), split=0)
        att = ht.nn.ring_attention(hd, hd, hd, causal=True)
        out = proj.apply(pp, att.larray)
        assert out.shape == (S, D)
        assert np.isfinite(np.asarray(out)).all()


class TestTorchParityEdges:
    def test_embedding_raises_out_of_range(self):
        m = htnn.Embedding(4, 2)
        params = m.init(jax.random.PRNGKey(0))
        with pytest.raises(IndexError):
            m.apply(params, jnp.asarray([3, 7]))
        with pytest.raises(IndexError):
            m.apply(params, jnp.asarray([-1]))
        # traced calls keep gather-clamp semantics (documented)
        out = jax.jit(lambda i: m.apply(params, i))(jnp.asarray([0, 3]))
        assert out.shape == (2, 2)

    def test_dropout_p1_zeroes(self):
        x = jnp.ones((3, 3), jnp.float32)
        out = htnn.Dropout(1.0).apply({}, x, train=True, key=jax.random.PRNGKey(0))
        np.testing.assert_array_equal(np.asarray(out), 0.0)
        # eval mode: identity even at p=1 (torch parity)
        np.testing.assert_array_equal(
            np.asarray(htnn.Dropout(1.0).apply({}, x, train=False)), np.asarray(x)
        )


class TestMultiheadAttention:
    def test_torch_oracle_self_attention(self):
        torch = pytest.importorskip("torch")

        torch.manual_seed(0)
        B, S, E, H = 2, 12, 16, 4
        x = np.random.default_rng(0).standard_normal((B, S, E)).astype(np.float32)

        t_mha = torch.nn.MultiheadAttention(E, H, bias=True, batch_first=True)
        with torch.no_grad():
            ref, _ = t_mha(torch.tensor(x), torch.tensor(x), torch.tensor(x),
                           need_weights=False)

        mha = ht.nn.MultiheadAttention(E, H, bias=True)
        params = {
            "in_proj": jnp.asarray(t_mha.in_proj_weight.detach().numpy().T),
            "in_bias": jnp.asarray(t_mha.in_proj_bias.detach().numpy()),
            "out_proj": jnp.asarray(t_mha.out_proj.weight.detach().numpy().T),
            "out_bias": jnp.asarray(t_mha.out_proj.bias.detach().numpy()),
        }
        out = mha.apply(params, jnp.asarray(x))
        np.testing.assert_allclose(np.asarray(out), ref.numpy(), rtol=2e-4, atol=2e-5)

    def test_causal_and_unbatched(self):
        torch = pytest.importorskip("torch")

        torch.manual_seed(1)
        S, E, H = 9, 8, 2
        x = np.random.default_rng(1).standard_normal((S, E)).astype(np.float32)
        t_mha = torch.nn.MultiheadAttention(E, H, bias=True, batch_first=True)
        mask = torch.triu(torch.ones(S, S, dtype=torch.bool), diagonal=1)
        with torch.no_grad():
            ref, _ = t_mha(torch.tensor(x[None]), torch.tensor(x[None]),
                           torch.tensor(x[None]), attn_mask=mask, need_weights=False)
        mha = ht.nn.MultiheadAttention(E, H, bias=True, causal=True)
        params = {
            "in_proj": jnp.asarray(t_mha.in_proj_weight.detach().numpy().T),
            "in_bias": jnp.asarray(t_mha.in_proj_bias.detach().numpy()),
            "out_proj": jnp.asarray(t_mha.out_proj.weight.detach().numpy().T),
            "out_bias": jnp.asarray(t_mha.out_proj.bias.detach().numpy()),
        }
        out = mha.apply(params, jnp.asarray(x))  # unbatched (S, E)
        assert out.shape == (S, E)
        np.testing.assert_allclose(np.asarray(out), ref.numpy()[0], rtol=2e-4, atol=2e-5)

    def test_trains_in_sequential(self):
        # end-to-end: a tiny transformer-ish stack learns under DataParallel
        rng = np.random.default_rng(2)
        n, s, e = 256, 8, 16
        x = ht.array(rng.standard_normal((n, s * e)).astype(np.float32), split=0)
        y = (ht.sum(x, axis=1) > 0).astype(ht.int32)

        class Reshape(ht.nn.Module):
            def apply(self, params, a, *, train=False, key=None):
                return a.reshape(a.shape[0], s, e)

        class Pool(ht.nn.Module):
            def apply(self, params, a, *, train=False, key=None):
                return a.mean(axis=1)

        model = ht.nn.Sequential(
            Reshape(), ht.nn.MultiheadAttention(e, 4, causal=True), Pool(),
            ht.nn.Linear(e, 2),
        )
        dp = ht.nn.DataParallel(model)
        opt = ht.optim.DataParallelOptimizer(ht.optim.SGD(lr=0.1), dp)
        first = last = None
        for _ in range(15):
            loss = float(opt.step(x, y))
            first = loss if first is None else first
            last = loss
        assert np.isfinite(last) and last < first

    def test_validation(self):
        with pytest.raises(ValueError):
            ht.nn.MultiheadAttention(10, 3)

    def test_grad_finite(self):
        mha = ht.nn.MultiheadAttention(8, 2, causal=True)
        params = mha.init(jax.random.key(0))
        x = jnp.asarray(np.random.default_rng(3).standard_normal((2, 6, 8)).astype(np.float32))
        g = jax.grad(lambda p: jnp.sum(mha.apply(p, x) ** 2))(params)
        for leaf in jax.tree.leaves(g):
            assert np.isfinite(np.asarray(leaf)).all()
