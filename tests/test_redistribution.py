"""Redistribution planner subsystem (``ht.redistribution``): golden
plans, degenerate specs, executor equivalence, and the plan-census ==
compiled-HLO contract.

Everything here is CPU-mesh tier-1: plans are pure Python (no device
work at all), the census checks lower compile-only, and the equivalence
sweeps run on the virtual 8-device mesh from conftest.py. The golden
matrix (``planner.golden_specs``) is pinned three ways:

1. strategy + step count + collective census per spec (this file),
2. byte-identical serialization run-to-run (``scripts/redist_plans.py``
   diffed twice in ci.sh — plans key the executor's program cache),
3. compiled-HLO collective counts == the plan's census for every spec
   that lowers to a planner program (the acceptance criterion).
"""

import numpy as np
import pytest

import jax

import heat_tpu as ht

from heat_tpu.core import _padding
from heat_tpu.core.communication import MeshCommunication
from heat_tpu.observability.hlo import _count_ops
from heat_tpu.redistribution import RedistSpec, executor, planner
from heat_tpu.redistribution.schedule import Schedule, Step

from test_suites.basic_test import TestCase, env_pin

P = len(jax.devices())

# the default planner budget, passed explicitly so an ambient
# HEAT_TPU_REDIST_BUDGET_MB cannot skew the golden pins
BUDGET = planner.DEFAULT_BUDGET_MB << 20

# the ambient two-tier topology (ISSUE 8): None on the default flat
# CPU mesh, (S, C) under the forced HEAT_TPU_TOPOLOGY=2x4 CI leg. The
# golden STRATEGY pins below are the flat contract and pass
# topology="flat" explicitly; the census==HLO and executor-equivalence
# tests run AMBIENT, so the forced leg exercises the tiered programs
# end to end against their own plans.
AMBIENT_TOPO = planner.resolve_topology(P)

# name -> (strategy, n_steps, collective census) under the default budget.
# n_steps pins the CODEC-FREE step structure: under a forced
# HEAT_TPU_WIRE_QUANT gate the admissible plans additionally carry
# quantize/dequantize step pairs (ISSUE 7), which never change the
# strategy, the collective census, or the lap structure — the pin test
# subtracts them and separately asserts they are absent with the gate
# off.
GOLDEN_PINS = {
    "noop_same_split": ("noop", 0, {}),
    "resplit_0_to_1_p8": ("all-to-all", 1, {"all-to-all": 1}),
    "resplit_1_to_0_p8": ("all-to-all", 1, {"all-to-all": 1}),
    "resplit_0_to_1_int32_p4": ("all-to-all", 1, {"all-to-all": 1}),
    "resplit_uneven_p8": ("all-to-all", 2, {"all-to-all": 1}),
    "resplit_3d_1_to_2_p8": ("all-to-all", 1, {"all-to-all": 1}),
    "replicate_p8": ("replicate", 1, {"all-gather": 1}),
    "slice_from_replicated_p8": ("slice", 1, {}),
    "mesh1_resplit": ("local", 0, {}),
    # big exchanges chunk to the OVERLAP_GRAIN (ISSUE 6) so the executor
    # has laps to double-buffer — the lap structure (and census) is
    # identical overlap-on and overlap-off
    "resplit_chunked_2gb_p8": ("chunked-all-to-all", 9, {"all-to-all": 4}),
    "resplit_ring_8gb_p8": ("ring", 7, {"collective-permute": 7}),
    # narrow minor dims (40->80 over p=8: 5- and 10-lane shards): the
    # lane-fill cost term picks the packed pivot
    "reshape_pivot_p8": ("packed-pivot", 6, {"all-to-all": 2}),
    "reshape_split0_local_p8": ("local-reshape", 1, {}),
    "reshape_gather_fallback_p8": ("gather-reshape", 3, {"all-gather": 1}),
    # the 1 GB ROADMAP spec: packed on the narrow OUT side (25->32 cols,
    # 4-lane shards); same all-to-all census as the direct pivot. 5 in-
    # laps (125 MB over the 32 MiB overlap grain, divisor-rounded) and 4
    # out-laps (160 MB)
    "reshape_split1_1gb_p8": ("packed-pivot", 23, {"all-to-all": 9}),
    # its reverse: packed on the narrow IN side
    "reshape_packed_rev_p8": ("packed-pivot", 22, {"all-to-all": 9}),
    # lane-friendly companion (512/256-lane shards): packing gains
    # nothing, the DIRECT pivot stays; 4 overlap laps per side
    "reshape_lane_1gb_p8": ("split0-pivot", 19, {"all-to-all": 8}),
    # the ISSUE 8 mesh-16 pair: flat pins here, tiered (2x8) pins in
    # tests/test_topology.py
    "resplit_1gb_p16": ("all-to-all", 2, {"all-to-all": 1}),
    "reshape_split1_1gb_p16": ("packed-pivot", 10, {"all-to-all": 3}),
}


def _golden():
    return planner.golden_specs()


def _planner_program(comm, spec, budget, pipelined=False):
    """The jitted program the executor would run for ``spec``, or None
    for the direct-placement strategies (noop/local/slice/replicate).
    ``pipelined`` selects the ISSUE-6 software-pipelined issue order of
    the chunk loops (same collectives; tests pin both forms). The wire
    codec AND topology follow the ambient gates through the plan,
    exactly like execute() — so the forced CI legs compile the
    encoded-payload and hierarchical program forms here too."""
    sched = planner.plan(spec, budget)
    strategy = sched.strategy
    wire = sched.quant["mode"] if sched.quant else None
    topo = sched.topo_key
    if strategy in ("noop", "local", "slice", "replicate"):
        return None
    if strategy in ("all-to-all", "chunked-all-to-all", "ring"):
        return executor._move_program(comm, spec, budget, pipelined, wire, topo)
    if strategy == "hierarchical-a2a" and not spec.is_reshape:
        return executor._move_program(comm, spec, budget, pipelined, wire, topo)
    if strategy == "split0-pivot" or (
        strategy == "hierarchical-a2a"
        and not any(s.kind in ("pack", "unpack") for s in sched.steps)
    ):
        return executor._pivot_program(comm, spec, budget, pipelined, wire, topo)
    if strategy in ("packed-pivot", "hierarchical-a2a"):
        impl_in, impl_out = executor._relayout_impls(spec, sched)
        return executor._packed_pivot_program(
            comm, spec, budget, impl_in, impl_out, pipelined, wire, topo
        )
    if strategy == "gather-reshape":
        return executor._gather_reshape_program(comm, spec, budget, topo)
    return executor._local_reshape_program(comm, spec, budget)


class TestGoldenPlans(TestCase):
    def test_matrix_covers_the_pins(self):
        self.assertEqual({n for n, _ in _golden()}, set(GOLDEN_PINS))

    def test_strategy_step_count_and_census_pinned(self):
        # pinned at topology="flat": the flat contract must hold
        # verbatim regardless of the ambient HEAT_TPU_TOPOLOGY (the 2x4
        # leg's tiered strategies are pinned in tests/test_topology.py)
        for name, spec in _golden():
            strategy, n_steps, census = GOLDEN_PINS[name]
            sched = planner.plan(spec, BUDGET, topology="flat")
            self.assertEqual(sched.strategy, strategy, name)
            # codec steps (forced HEAT_TPU_WIRE_QUANT legs) ride in
            # pairs around collectives without changing the pinned
            # structure; with the gate off there are none
            quant_steps = sum(
                1 for s in sched.steps if s.kind in ("quantize", "dequantize")
            )
            self.assertEqual(sched.n_steps - quant_steps, n_steps, name)
            self.assertEqual(sched.collective_counts(), census, name)
            if planner.wire_quant_gate() is None:
                self.assertEqual(quant_steps, 0, name)
                self.assertIsNone(sched.quant, name)

    def test_every_plan_fits_the_budget(self):
        for name, spec in _golden():
            sched = planner.plan(spec, BUDGET)
            self.assertTrue(sched.within_budget, f"{name}: {sched!r}")
            self.assertLessEqual(sched.peak_bytes, BUDGET, name)

    def test_plans_byte_identical_run_to_run(self):
        """Plans key the executor's program cache, so planning the same
        spec twice — including across a cache wipe — must serialize to
        the identical bytes (the ci.sh determinism leg does this across
        processes)."""
        first = {n: planner.plan(s, BUDGET).canonical_json() for n, s in _golden()}
        planner.clear_plan_cache()
        for name, spec in _golden():
            self.assertEqual(planner.plan(spec, BUDGET).canonical_json(), first[name])

    def test_1gb_split1_reshape_acceptance(self):
        """The acceptance spec: the 1 GB split-1 reshape plans to a
        bounded-footprint pivot whose per-step peak never exceeds the
        configured budget — not the old full all-gather."""
        (spec,) = [s for n, s in _golden() if n == "reshape_split1_1gb_p8"]
        self.assertEqual(spec.logical_bytes, 10**9)
        sched = planner.plan(spec, planner.budget_bytes(), topology="flat")
        self.assertEqual(sched.strategy, "packed-pivot")
        for step in sched.steps:
            self.assertLessEqual(step.peak_bytes, planner.budget_bytes())
        self.assertEqual(sched.collective_counts().get("all-gather", 0), 0)

    def test_tighter_budget_rechunks(self):
        """Tightening the budget must re-chunk, not blow the budget: the
        2 GiB resplit pipelines into more laps and the peak drops. (The
        default plan already runs 4 overlap-grain laps, so the budget
        must drop past that point before it binds — BUDGET//4 forces 8.)"""
        (spec,) = [s for n, s in _golden() if n == "resplit_chunked_2gb_p8"]
        base = planner.plan(spec, BUDGET, topology="flat")
        tight = planner.plan(spec, BUDGET // 4, topology="flat")
        self.assertLessEqual(tight.peak_bytes, BUDGET // 4)
        # the tighter plan pipelines more collectives (chunk laps, or the
        # p-1 ppermute hops of the minimal-footprint ring)
        self.assertGreater(tight.n_collectives, base.n_collectives)

    def test_plan_cache_and_telemetry(self):
        from heat_tpu.observability import telemetry

        planner.clear_plan_cache()
        telemetry.reset()
        telemetry.enable()
        try:
            spec = RedistSpec.normalize((64, 48), "float32", 0, 1, 8)
            planner.plan(spec, BUDGET)
            planner.plan(spec, BUDGET)
            snap = telemetry.snapshot()
            self.assertEqual(snap["counters"]["redist.plan_cache.miss"], 1)
            self.assertEqual(snap["counters"]["redist.plan_cache.hit"], 1)
            self.assertGreater(snap["counters"]["redist.planned_bytes"], 0)
            self.assertGreater(snap["counters"]["redist.steps"], 0)
        finally:
            telemetry.disable()
            telemetry.reset()


class TestScheduleIR(TestCase):
    def test_unknown_step_kind_rejected(self):
        with self.assertRaises(ValueError):
            Step("teleport")

    def test_census_counts_collectives_only(self):
        spec = RedistSpec.normalize((8, 8), "float32", 0, 1, 8)
        sched = Schedule(
            spec,
            "all-to-all",
            [Step("pad"), Step("all_to_all", bytes_moved=4), Step("slice")],
            BUDGET,
        )
        self.assertEqual(sched.collective_counts(), {"all-to-all": 1})
        self.assertEqual(sched.n_collectives, 1)
        self.assertEqual(sched.bytes_moved, 4)

    def test_plan_id_is_the_serialization_hash_even_over_budget(self):
        """An infeasible budget annotates the chosen plan's notes — and
        the plan_id must still be the sha1 of the canonical
        serialization (consumers correlate serialized plans by
        recomputing it)."""
        import hashlib

        sched = planner.plan(RedistSpec.normalize((64, 48), "float32", 0, 1, 8), 8)
        self.assertIn("over budget", sched.notes)
        self.assertEqual(
            sched.plan_id,
            hashlib.sha1(sched.canonical_json(with_plan_id=False).encode()).hexdigest()[
                :12
            ],
        )

    def test_plan_id_tracks_content(self):
        spec = RedistSpec.normalize((8, 8), "float32", 0, 1, 8)
        a = Schedule(spec, "all-to-all", [Step("all_to_all", bytes_moved=4)], BUDGET)
        b = Schedule(spec, "all-to-all", [Step("all_to_all", bytes_moved=4)], BUDGET)
        c = Schedule(spec, "all-to-all", [Step("all_to_all", bytes_moved=8)], BUDGET)
        self.assertEqual(a.plan_id, b.plan_id)
        self.assertNotEqual(a.plan_id, c.plan_id)


class TestSpecNormalization(TestCase):
    def test_negative_axes_modded(self):
        spec = RedistSpec.normalize((4, 6), "float32", -1, -2, 8)
        self.assertEqual((spec.src_split, spec.dst_split), (1, 0))

    def test_reshape_size_mismatch_rejected(self):
        with self.assertRaises(ValueError):
            RedistSpec.normalize((4, 6), "float32", 0, 0, 8, reshape_to=(5, 5))

    def test_same_movement_same_spec(self):
        a = RedistSpec.normalize((64, 48), np.float32, 0, 1, 8)
        b = RedistSpec.normalize([64, 48], "float32", -2, -1, 8)
        self.assertEqual(a, b)
        self.assertEqual(hash(a), hash(b))


class TestDegenerateSpecs(TestCase):
    def test_same_split_is_noop(self):
        spec = RedistSpec.normalize((64, 48), "float32", 1, 1, 8)
        sched = planner.plan(spec, BUDGET)
        self.assertEqual((sched.strategy, sched.n_steps), ("noop", 0))

    def test_mesh1_is_local(self):
        spec = RedistSpec.normalize((64, 48), "float32", 0, 1, 1)
        sched = planner.plan(spec, BUDGET)
        self.assertEqual(sched.strategy, "local")
        self.assertEqual(sched.collective_counts(), {})

    def test_replicated_to_split_never_communicates(self):
        spec = RedistSpec.normalize((64, 48), "float32", None, 1, 8)
        sched = planner.plan(spec, BUDGET)
        self.assertEqual(sched.strategy, "slice")
        self.assertEqual(sched.collective_counts(), {})

    def test_replicate_is_exactly_one_all_gather(self):
        spec = RedistSpec.normalize((64, 48), "float32", 0, None, 8)
        sched = planner.plan(spec, BUDGET)
        self.assertEqual(sched.strategy, "replicate")
        self.assertEqual(sched.collective_counts(), {"all-gather": 1})

    def test_uneven_shards_pad_locally_not_collectively(self):
        """_padding discipline: the uneven spec adds local pad/slice
        steps around the SAME single all-to-all — pad never rides a
        collective."""
        even = planner.plan(RedistSpec.normalize((64, 48), "float32", 0, 1, 8), BUDGET)
        uneven = planner.plan(RedistSpec.normalize((63, 48), "float32", 0, 1, 8), BUDGET)
        self.assertEqual(uneven.collective_counts(), even.collective_counts())
        self.assertGreater(uneven.n_steps, even.n_steps)
        self.assertTrue(any(s.kind == "slice" for s in uneven.steps))


class TestExplain(TestCase):
    def test_explain_resplit(self):
        x = ht.zeros((64, 48), split=0)
        sched = planner.explain(x, 1)
        self.assertIsInstance(sched, Schedule)
        self.assertEqual(sched.spec.src_split, 0)
        self.assertEqual(sched.spec.dst_split, 1)
        if P >= 2:
            self.assertEqual(sched.strategy, "all-to-all")

    def test_explain_is_the_public_api(self):
        x = ht.zeros((64, 48), split=0)
        self.assertEqual(
            ht.redistribution.explain(x, 1).plan_id, planner.explain(x, 1).plan_id
        )

    def test_explain_reshape_defaults_new_split_like_reshape(self):
        x = ht.zeros((64, 48), split=1)
        sched = planner.explain(x, reshape=(32, 96))
        self.assertEqual(sched.spec.reshape_to, (32, 96))
        self.assertEqual(sched.spec.dst_split, 1)
        inferred = planner.explain(x, reshape=(64 * 48,))
        self.assertEqual(inferred.spec.dst_split, 0)

    def test_explain_reshape_minus_one(self):
        x = ht.zeros((64, 48), split=0)
        sched = planner.explain(x, reshape=(-1, 96))
        self.assertEqual(sched.spec.reshape_to, (32, 96))

    def test_explain_rejects_non_dndarray(self):
        with self.assertRaises(TypeError):
            planner.explain(np.zeros((4, 4)), 1)


@pytest.mark.skipif(P < 2, reason="needs a real mesh")
class TestExecutorEquivalence(TestCase):
    """The planned schedules must produce bit-identical arrays to the
    oracle (and therefore to the legacy direct-placement resplit)."""

    def test_resplit_sweep(self):
        shapes = [(64, 48), (63, 41), (16, 24, 40), (40,), (7, 5)]
        for shape in shapes:
            oracle = np.arange(int(np.prod(shape)), dtype=np.float32).reshape(shape)
            splits = [None] + list(range(len(shape)))
            for src in splits:
                for dst in splits:
                    x = ht.array(oracle, split=src)
                    self.assert_array_equal(x.resplit(dst), oracle)

    def test_resplit_int_dtype(self):
        oracle = np.arange(64 * 48, dtype=np.int32).reshape(64, 48)
        x = ht.array(oracle, split=0)
        self.assert_array_equal(x.resplit(1), oracle)

    def test_resplit_matches_legacy_path(self):
        """Planner output == the legacy unpad->repad placement, shard
        for shard (assert_array_equal checks per-device shards)."""
        oracle = np.arange(63 * 48, dtype=np.float32).reshape(63, 48)
        x = ht.array(oracle, split=0)
        planned = executor.resplit_phys(self.comm, x._phys, (63, 48), 0, 1)
        legacy = executor._reshard_direct(self.comm, x._phys, (63, 48), 0, 1)
        np.testing.assert_array_equal(np.asarray(planned), np.asarray(legacy))

    def test_reshape_sweep(self):
        cases = [
            ((64, 48), (32, 96), 1),
            ((64, 48), (96, 32), 0),
            ((1024, 40), (512, 80), 1),
            ((64, 48), (64 * 48,), 0),
            ((1000, 26), (26, 1000), 1),  # gather-reshape fallback
        ]
        for in_shape, out_shape, new_split in cases:
            for src in [None] + list(range(len(in_shape))):
                oracle = np.arange(int(np.prod(in_shape)), dtype=np.float32).reshape(
                    in_shape
                )
                x = ht.array(oracle, split=src)
                got = ht.reshape(x, out_shape, new_split=new_split)
                self.assertEqual(got.split, new_split)
                self.assert_array_equal(got, oracle.reshape(out_shape))

    def test_chunked_and_ring_numerics(self):
        """Tiny explicit budgets force the chunked pipeline and the
        ppermute ring; both must reproduce the oracle exactly."""
        oracle = np.arange(64 * 48, dtype=np.float32).reshape(64, 48)
        x = ht.array(oracle, split=0)
        spec = RedistSpec.normalize((64, 48), "float32", 0, 1, P)
        seen = set()
        for budget in (384, 1024, 2048):
            sched = planner.plan(spec, budget)
            seen.add(sched.strategy)
            y = executor.execute(self.comm, x._phys, spec, sched)
            got = np.asarray(_padding.unpad(y, (64, 48), 1))
            np.testing.assert_array_equal(got, oracle)
        if P == 8:
            self.assertIn("ring", seen)
            self.assertIn("chunked-all-to-all", seen)

    def test_zero_size_and_scalarish(self):
        z = ht.zeros((0, 4), split=0)
        self.assertEqual(z.resplit(1).split, 1)
        one = ht.zeros((1, 1), split=0)
        self.assert_array_equal(one.resplit(1), np.zeros((1, 1), np.float32))

    def test_escape_hatch_restores_legacy(self):
        """HEAT_TPU_REDIST_PLANNER=0 must bypass the planner and still
        produce correct results."""
        oracle = np.arange(64 * 48, dtype=np.float32).reshape(64, 48)
        with env_pin("HEAT_TPU_REDIST_PLANNER", "0"):
            self.assertFalse(planner.planner_enabled())
            x = ht.array(oracle, split=0)
            self.assert_array_equal(x.resplit(1), oracle)
            self.assert_array_equal(
                ht.reshape(x, (32, 96), new_split=1), oracle.reshape(32, 96)
            )
            # explain refuses: the plan it would show is not what runs
            with self.assertRaises(RuntimeError):
                planner.explain(x, 1)
        self.assertTrue(planner.planner_enabled())


@pytest.mark.skipif(P < 8, reason="golden census pins assume the 8-device mesh")
class TestCompiledCensusMatchesPlan(TestCase):
    """Acceptance criterion: for every golden spec that lowers to a
    planner program, the compiled HLO's collective counts equal the
    plan's census EXACTLY — compile-only, nothing executes (the 4 GB /
    32 GB / 1 GB specs never allocate)."""

    def _comm_for(self, mesh_size):
        if mesh_size == self.comm.size:
            return self.comm
        if mesh_size <= len(jax.devices()):
            return MeshCommunication(jax.devices()[:mesh_size])
        return None

    def test_census(self):
        checked = 0
        for name, spec in _golden():
            comm = self._comm_for(spec.mesh_size)
            if comm is None:
                continue
            prog = _planner_program(comm, spec, BUDGET)
            if prog is None:
                continue
            sched = planner.plan(spec, BUDGET)
            phys = _padding.phys_shape(spec.gshape, spec.src_split, spec.mesh_size)
            arg = jax.ShapeDtypeStruct(
                phys,
                np.dtype(spec.dtype),
                sharding=comm.sharding(len(phys), spec.src_split),
            )
            text = prog.lower(arg).compile().as_text()
            counts = {k: v for k, v in _count_ops(text).items() if v}
            self.assertEqual(counts, sched.collective_counts(), name)
            checked += 1
        # the matrix must actually exercise the program-backed strategies
        self.assertGreaterEqual(checked, 9)

    def test_executed_resplit_census_matches_plan(self):
        """End-to-end: the census of the PUBLIC resplit call equals the
        plan explain() returns for the same array."""
        x = ht.zeros((320 * P, 2 * P), split=0)
        sched = ht.redistribution.explain(x, 1)
        rep = ht.observability.collective_counts(lambda v: v.resplit(1), x)
        for op, n in sched.collective_counts().items():
            self.assertEqual(rep.counts[op], n)
        self.assertEqual(rep.total, sched.n_collectives)


class TestShardlintIntegration(TestCase):
    def test_executor_registered_as_planner_module(self):
        """boundaries.PLANNER_MODULES declares the one module whose
        collectives are cost-modeled movement by contract; the HLO
        marker parser recognizes the executor's named_scope stamp."""
        from heat_tpu.analysis import boundaries

        self.assertIn("redistribution/executor.py", boundaries.PLANNER_MODULES)
        self.assertEqual(
            boundaries.planned_reshard_plan_id(
                'metadata={op_name="jit(fn)/redist_plan_0123456789ab/all_to_all"}'
            ),
            "0123456789ab",
        )
        self.assertIsNone(boundaries.planned_reshard_plan_id("%all-to-all.1 = ..."))

    @pytest.mark.skipif(P < 2, reason="needs a real mesh")
    def test_explicit_replicate_reports_as_info(self):
        """resplit(None) is the planner's explicit replicate strategy;
        its full all-gather must carry the plan stamp and report as
        SL102 info, not an error-severity replicated materialization."""
        x = ht.zeros((4096, 2048), split=0)  # 32 MB: over every threshold
        sched = ht.redistribution.explain(x, None)
        self.assertEqual(sched.strategy, "replicate")
        rep = ht.analysis.check(lambda v: v.resplit(None) * 2.0, x)
        sl102 = [f for f in rep.findings if f.rule == "SL102"]
        self.assertTrue(sl102)
        for f in sl102:
            self.assertEqual(f.severity, "info")
            self.assertIn(sched.plan_id, f.message)
        self.assertTrue(rep.ok)

    @pytest.mark.skipif(P < 2, reason="needs a real mesh")
    def test_planner_reshards_report_as_info(self):
        """SL101 on a planner-issued all-to-all downgrades to info with
        the plan id attached — the subsystem's own schedules are not
        implicit reshards."""
        x = ht.zeros((4096, 2048), split=0)  # 32 MB: over every threshold
        sched = ht.redistribution.explain(x, 1)
        rep = ht.analysis.check(lambda v: v.resplit(1), x)
        sl101 = [f for f in rep.findings if f.rule == "SL101"]
        self.assertTrue(sl101)
        for f in sl101:
            self.assertEqual(f.severity, "info")
            self.assertIn(sched.plan_id, f.message)
        self.assertTrue(rep.ok)


if __name__ == "__main__":
    import unittest

    unittest.main()
