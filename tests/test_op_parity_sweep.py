"""Systematic parity sweep: every elementwise op on the NumPy surface is
compared against its numpy oracle across splits (None/0/1), uneven
extents, and representative dtypes — the breadth the reference gets from
its per-module test files (core/tests/test_arithmetics.py etc.) in one
generated matrix."""

import numpy as np
import pytest

import heat_tpu as ht

_RNG = np.random.default_rng(0)
_POS = np.abs(_RNG.standard_normal((5, 9)).astype(np.float32)) + 0.5
_ANY = _RNG.standard_normal((5, 9)).astype(np.float32)
_UNIT = np.clip(_ANY / 3.0, -0.99, 0.99)
_INT = _RNG.integers(1, 9, size=(5, 9)).astype(np.int32)
_BOOL = _ANY > 0

# (ht name, numpy oracle, input domain)
_UNARY = [
    ("abs", np.abs, _ANY),
    ("ceil", np.ceil, _ANY),
    ("floor", np.floor, _ANY),
    ("trunc", np.trunc, _ANY),
    ("round", np.round, _ANY),
    ("exp", np.exp, _ANY),
    ("expm1", np.expm1, _ANY),
    ("exp2", np.exp2, _ANY),
    ("log", np.log, _POS),
    ("log2", np.log2, _POS),
    ("log10", np.log10, _POS),
    ("log1p", np.log1p, _POS),
    ("sqrt", np.sqrt, _POS),
    ("sin", np.sin, _ANY),
    ("cos", np.cos, _ANY),
    ("tan", np.tan, _UNIT),
    ("arcsin", np.arcsin, _UNIT),
    ("arccos", np.arccos, _UNIT),
    ("arctan", np.arctan, _ANY),
    ("sinh", np.sinh, _UNIT),
    ("cosh", np.cosh, _UNIT),
    ("tanh", np.tanh, _ANY),
    ("arcsinh", np.arcsinh, _ANY),
    ("arctanh", np.arctanh, _UNIT),
    ("negative", np.negative, _ANY),
    ("positive", np.positive, _ANY),
    ("sign", np.sign, _ANY),
    ("square", np.square, _ANY),
    ("rad2deg", np.rad2deg, _ANY),
    ("deg2rad", np.deg2rad, _ANY),
]

_BINARY = [
    ("add", np.add, _ANY, _POS),
    ("sub", np.subtract, _ANY, _POS),
    ("mul", np.multiply, _ANY, _POS),
    ("div", np.divide, _ANY, _POS),
    ("floordiv", np.floor_divide, _ANY, _POS),
    ("mod", np.mod, _POS, _POS),
    ("fmod", np.fmod, _POS, _POS),
    ("pow", np.power, _POS, _UNIT),
    ("hypot", np.hypot, _ANY, _POS),
    ("copysign", np.copysign, _POS, _ANY),
    ("maximum", np.maximum, _ANY, _POS),
    ("minimum", np.minimum, _ANY, _POS),
    ("arctan2", np.arctan2, _ANY, _POS),
]

_BINARY_INT = [
    ("bitwise_and", np.bitwise_and),
    ("bitwise_or", np.bitwise_or),
    ("bitwise_xor", np.bitwise_xor),
    ("gcd", np.gcd),
    ("lcm", np.lcm),
    ("left_shift", np.left_shift),
    ("right_shift", np.right_shift),
]


@pytest.mark.parametrize("name,oracle,data", _UNARY, ids=[u[0] for u in _UNARY])
@pytest.mark.parametrize("split", [None, 0, 1])
def test_unary_parity(name, oracle, data, split):
    fn = getattr(ht, name)
    # uneven extent on the split axis: exercises the pad-inside-jit path
    x = ht.array(data, split=split)
    got = fn(x)
    np.testing.assert_allclose(
        got.numpy(), oracle(data), rtol=3e-5, atol=3e-6, err_msg=name
    )
    assert got.split == split
    assert got.gshape == data.shape


@pytest.mark.parametrize("name,oracle,a,b", _BINARY, ids=[b[0] for b in _BINARY])
@pytest.mark.parametrize("split", [None, 0, 1])
def test_binary_parity(name, oracle, a, b, split):
    fn = getattr(ht, name)
    x, y = ht.array(a, split=split), ht.array(b, split=split)
    got = fn(x, y)
    np.testing.assert_allclose(
        got.numpy(), oracle(a, b), rtol=3e-5, atol=3e-6, err_msg=name
    )


@pytest.mark.parametrize("name,oracle", _BINARY_INT, ids=[b[0] for b in _BINARY_INT])
@pytest.mark.parametrize("split", [None, 0])
def test_binary_int_parity(name, oracle, split):
    fn = getattr(ht, name)
    a = _INT
    b = (_INT % 5 + 1).astype(np.int32)
    x, y = ht.array(a, split=split), ht.array(b, split=split)
    np.testing.assert_array_equal(fn(x, y).numpy(), oracle(a, b), err_msg=name)


@pytest.mark.parametrize("split", [None, 0])
def test_unary_bool_and_int_promotion(split):
    # exact dtypes promote to float for transcendental ops (reference rule)
    x = ht.array(_INT, split=split)
    got = ht.exp(x)
    assert got.dtype in (ht.float32, ht.float64)
    np.testing.assert_allclose(got.numpy(), np.exp(_INT.astype(np.float32)), rtol=1e-4)
    b = ht.array(_BOOL, split=split)
    np.testing.assert_array_equal(ht.logical_not(b).numpy(), ~_BOOL)


@pytest.mark.parametrize("split", [None, 0, 1])
def test_scalar_operand_matrix(split):
    x = ht.array(_ANY, split=split)
    np.testing.assert_allclose((x + 2).numpy(), _ANY + 2, rtol=1e-6)
    np.testing.assert_allclose((2 + x).numpy(), 2 + _ANY, rtol=1e-6)
    np.testing.assert_allclose((x * 0.5).numpy(), _ANY * 0.5, rtol=1e-6)
    np.testing.assert_allclose((1.0 / (ht.array(_POS, split=split))).numpy(), 1.0 / _POS, rtol=1e-5)
    np.testing.assert_allclose((x ** 2).numpy(), _ANY ** 2, rtol=1e-5)


@pytest.mark.parametrize("name,oracle", [
    ("cumsum", np.cumsum), ("cumprod", np.cumprod),
])
@pytest.mark.parametrize("split", [None, 0, 1])
@pytest.mark.parametrize("axis", [0, 1])
def test_cum_parity(name, oracle, split, axis):
    data = _UNIT  # bounded values keep cumprod stable
    x = ht.array(data, split=split)
    got = getattr(ht, name)(x, axis)
    np.testing.assert_allclose(
        got.numpy(), oracle(data, axis=axis), rtol=2e-4, atol=2e-5, err_msg=name
    )


@pytest.mark.parametrize("name,oracle,kwargs", [
    ("sum", np.sum, {}),
    ("prod", np.prod, {}),
    ("max", np.max, {}),
    ("min", np.min, {}),
    ("mean", np.mean, {}),
])
@pytest.mark.parametrize("split", [None, 0, 1])
@pytest.mark.parametrize("axis", [None, 0, 1])
def test_reduce_parity(name, oracle, kwargs, split, axis):
    data = _UNIT + 1.1  # positive, away from 0: prod-stable
    x = ht.array(data, split=split)
    got = getattr(ht, name)(x, axis=axis)
    ref = oracle(data, axis=axis)
    np.testing.assert_allclose(
        np.asarray(got.numpy()), ref, rtol=3e-4, atol=3e-5, err_msg=f"{name} axis={axis}"
    )


@pytest.mark.parametrize("split", [None, 0, 1])
@pytest.mark.parametrize("n", [1, 2, 3])
@pytest.mark.parametrize("axis", [0, 1, -1])
def test_diff_parity(split, n, axis):
    """diff across splits/orders/axes — the split path shards the result,
    and the recorded gshape must be the LOGICAL diff shape (regression:
    the padded physical extent leaked into .numpy())."""
    a = np.arange(24, dtype=np.float32).reshape(4, 6) ** 1.5
    got = ht.diff(ht.array(a, split=split), n=n, axis=axis)
    ref = np.diff(a, n=n, axis=axis)
    assert got.shape == ref.shape
    np.testing.assert_allclose(np.asarray(got.numpy()), ref, rtol=1e-5)
