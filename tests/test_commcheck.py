"""Pass 5 (commcheck) — SPMD collective-congruence & progress verifier
(ISSUE 14).

Contracts pinned here:

- Every SL5xx golden bad fixture trips at its DECLARED severity (SL501
  error, SL502 error, SL503 error-on-cycle / warning-on-independent,
  SL504 warning), and every clean twin comes back clean — the fix each
  finding names really is the fix.
- The IR rules are folded into ``ht.analysis.check`` (one report proves
  congruence AND the SL1xx movement rules), and the shared
  ``analysis/_groups.py`` parser keeps SL107's cross-tier verdict and
  SL502's congruence verdict reading the same HLO line identically.
- The shipped collective contracts — TSQR (barrier AND forced-ring
  forms), hSVD level-0, the collective-matmul ring, the kcluster
  serving endpoint, the driver training step — are commcheck-clean at
  zero errors, and the whole ``heat_tpu/`` tree is SL504-clean.
- The ``progress`` invariant: every golden-matrix plan (all topologies,
  quant on and off) and every staged golden plan replays to completion,
  while a hand-mutated plan fails ``verify_plan`` with
  ``invariant="progress"`` and the violating step named.
- Seeded mutations (the ci.sh proof): drop one pair from a
  ring_all_gather schedule -> SL502; make a cond predicate
  device-dependent -> SL501; remove the executor's / the endpoint's
  epoch-fence call -> SL504.
- The ``capture_epoch``/``check_epoch`` object-level fence: no-op until
  the elastic runtime stamps a world, typed ``WorldChangedError`` on a
  stale token, inert under ``HEAT_TPU_RESILIENCE=0``.

Everything here runs on the tier-1 CPU mesh at 8 AND 5 devices — the
group fixtures that need an even mesh carry their own skips.
"""

import copy
import importlib
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import heat_tpu as ht

import analysis_fixtures as fx

from heat_tpu.analysis import findings
from heat_tpu.analysis.planverify import (
    PlanVerificationError,
    check_progress,
    verify_plan,
)
from heat_tpu.kernels import cmatmul
from heat_tpu.redistribution import planner
from heat_tpu.resilience import checkpoint as ck
from heat_tpu.resilience import elastic

from test_suites.basic_test import TestCase, env_pin

# the module is shadowed by the function in the package namespace
commcheck_mod = importlib.import_module("heat_tpu.analysis.commcheck")
commcheck = commcheck_mod.commcheck

P = len(jax.devices())
ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BUDGET = planner.DEFAULT_BUDGET_MB << 20


def _read(rel):
    with open(os.path.join(ROOT, rel), encoding="utf-8") as f:
        return f.read()


def _x(rows=64, cols=8):
    return ht.array(
        np.arange(rows * cols, dtype=np.float32).reshape(rows, cols) + 1.0,
        split=0,
    )


# ------------------------------------------------------------------ #
# golden bad fixtures: each rule trips at its declared severity      #
# ------------------------------------------------------------------ #
class TestGoldenBadFixtures(TestCase):
    @pytest.mark.skipif(P < 2, reason="needs a real mesh")
    def test_divergent_cond_trips_sl501_error(self):
        rep = commcheck(fx.divergent_cond_collective_program, _x())
        hits = [f for f in rep.findings if f.rule == "SL501"]
        self.assertTrue(hits, [repr(f) for f in rep.findings])
        self.assertTrue(all(f.severity == "error" for f in hits))
        self.assertFalse(rep.ok)

    @pytest.mark.skipif(P < 2, reason="needs a real mesh")
    def test_uniform_cond_twin_is_clean(self):
        """The fix SL501 names — psum the local condition — is clean."""
        rep = commcheck(fx.uniform_cond_collective_program, _x())
        self.assertEqual(rep.rule_ids, [])

    @pytest.mark.skipif(P < 2, reason="needs a real mesh")
    def test_divergent_while_trips_sl501_error(self):
        rep = commcheck(fx.divergent_while_collective_program, _x())
        hits = [f for f in rep.findings if f.rule == "SL501"]
        self.assertTrue(hits)
        self.assertIn("while", hits[0].message)

    @pytest.mark.skipif(P < 2, reason="needs a real mesh")
    def test_open_ring_trips_sl502_error(self):
        rep = commcheck(fx.open_ring_program, _x())
        hits = [f for f in rep.findings if f.rule == "SL502"]
        self.assertTrue(hits, [repr(f) for f in rep.findings])
        self.assertTrue(all(f.severity == "error" for f in hits))
        self.assertIn("hang", hits[0].message)

    @pytest.mark.skipif(P < 2, reason="needs a real mesh")
    def test_closed_ring_twin_has_no_sl502(self):
        """The complete +1 ring (the SL101 fixture) is CONGRUENT — pass
        5 has no complaint even where pass 1 flags the movement."""
        rep = commcheck(fx.ppermute_ring_program, _x())
        self.assertNotIn("SL502", rep.rule_ids)

    @pytest.mark.skipif(P < 2, reason="needs a real mesh")
    def test_opposite_order_branches_trip_sl503_error(self):
        rep = commcheck(fx.opposite_order_collectives_program, _x())
        hits = [f for f in rep.findings if f.rule == "SL503"]
        self.assertTrue(hits, [repr(f) for f in rep.findings])
        self.assertTrue(all(f.severity == "error" for f in hits))
        self.assertIn("OPPOSITE", hits[0].message)
        # the divergence that arms the cycle is itself reported
        self.assertIn("SL501", rep.rule_ids)

    @pytest.mark.skipif(
        P < 4 or P % 2, reason="group fixtures need an even mesh >= 4"
    )
    def test_overlapping_groups_trip_sl503_warning(self):
        rep = commcheck(fx.overlapping_groups_program, _x())
        hits = [f for f in rep.findings if f.rule == "SL503"]
        self.assertTrue(hits, [repr(f) for f in rep.findings])
        self.assertTrue(all(f.severity == "warning" for f in hits))

    @pytest.mark.skipif(
        P < 4 or P % 2, reason="group fixtures need an even mesh >= 4"
    )
    def test_aligned_groups_twin_is_clean(self):
        rep = commcheck(fx.aligned_groups_program, _x())
        self.assertEqual(rep.rule_ids, [])

    @pytest.mark.skipif(P < 2, reason="needs a real mesh")
    def test_ir_rules_fold_into_check(self):
        """One ``ht.analysis.check`` report proves congruence AND the
        SL1xx movement rules — the pass-5 folding contract."""
        rep = ht.analysis.check(fx.divergent_cond_collective_program, _x())
        self.assertIn("SL501", rep.rule_ids)

    def test_unfenced_dispatch_src_trips_sl504_warning(self):
        found = commcheck_mod.lint_source(
            fx.UNFENCED_DISPATCH_SRC, "heat_tpu/serving/dispatcher.py"
        )
        hits = [f for f in found if f.rule == "SL504"]
        self.assertTrue(hits)
        self.assertTrue(all(f.severity == "warning" for f in hits))
        # both the public entry and the worker root are flagged
        self.assertGreaterEqual(len(hits), 2)

    def test_fenced_dispatch_twin_is_clean(self):
        found = commcheck_mod.lint_source(
            fx.FENCED_DISPATCH_SRC, "heat_tpu/serving/dispatcher.py"
        )
        self.assertEqual(found, [])

    def test_sl504_is_scoped_to_dispatch_modules(self):
        """The same unfenced source OUTSIDE the dispatch layer is not in
        scope — a public library op is not a dispatch entry."""
        found = commcheck_mod.lint_source(
            fx.UNFENCED_DISPATCH_SRC, "heat_tpu/core/_operations.py"
        )
        self.assertEqual(found, [])

    def test_fenced_dispatch_module_population_pinned(self):
        self.assertEqual(
            commcheck_mod.FENCED_DISPATCH_MODULES,
            ("redistribution/executor.py", "serving/dispatcher.py"),
        )

    def test_sl5xx_rules_are_cataloged(self):
        for rule in ("SL501", "SL502", "SL503", "SL504"):
            self.assertIn(rule, findings.RULES)


# ------------------------------------------------------------------ #
# the shared group parser: one verdict for SL107 and SL502           #
# ------------------------------------------------------------------ #
class TestSharedGroupParser(TestCase):
    def test_ircheck_uses_the_shared_parser(self):
        from heat_tpu.analysis import _groups, ircheck

        self.assertIs(ircheck._parse_groups, _groups.parse_groups)

    def test_iota_form_one_verdict(self):
        from heat_tpu.analysis import _groups

        line = "all-to-all(p0), replica_groups=[2,4]<=[8], dimensions={0}"
        want = [[0, 1, 2, 3], [4, 5, 6, 7]]
        self.assertEqual(_groups.parse_groups(line), want)
        self.assertEqual(_groups.parse_replica_groups(line), want)
        self.assertIsNone(_groups.partition_defect(want, 8))

    def test_explicit_form_and_defects(self):
        from heat_tpu.analysis import _groups

        line = "all-gather(p0), replica_groups={{0,1},{2,3}}"
        groups = _groups.parse_replica_groups(line)
        self.assertEqual(groups, [[0, 1], [2, 3]])
        # congruent over 4 devices, non-covering over 8
        self.assertIsNone(_groups.partition_defect(groups, 4))
        self.assertIn("no group", _groups.partition_defect(groups, 8))
        self.assertIn(
            "more than one", _groups.partition_defect([[0, 1], [1, 2]], 4)
        )

    def test_pair_defects(self):
        from heat_tpu.analysis import _groups

        # a complete ring is congruent; partner swaps are congruent
        ring = [(s, (s + 1) % 4) for s in range(4)]
        self.assertIsNone(_groups.permutation_defect(ring, 4))
        self.assertIsNone(_groups.permutation_defect([(0, 1), (1, 0)], 4))
        # the hang shapes
        self.assertIn(
            "duplicate source", _groups.permutation_defect([(0, 1), (0, 2)], 4)
        )
        self.assertIn(
            "duplicate target", _groups.permutation_defect([(0, 2), (1, 2)], 4)
        )
        self.assertIn(
            "outside", _groups.permutation_defect([(0, 9)], 4)
        )
        self.assertIn(
            "never", _groups.permutation_defect([(0, 1), (1, 2)], 4)
        )


# ------------------------------------------------------------------ #
# clean pins: the shipped collective contracts                       #
# ------------------------------------------------------------------ #
class TestCleanPins(TestCase):
    @pytest.mark.skipif(P < 2, reason="needs a real mesh")
    def test_tsqr_commcheck_clean(self):
        a = ht.random.randn(16 * P, 2 * P, split=0)
        rep = commcheck(lambda v: ht.linalg.qr(v), a)
        self.assertEqual(rep.errors, [])

    @pytest.mark.skipif(P < 2, reason="needs a real mesh")
    def test_tsqr_forced_ring_commcheck_clean(self):
        """The ring TSQR builds its permutation through
        ``grouped_ring_perm`` — complete by construction, and pass 5
        proves it on the compiled module."""
        a = ht.random.randn(16 * P, 2 * P, split=0)
        with env_pin(planner.OVERLAP_ENV, "1"):
            rep = commcheck(lambda v: ht.linalg.qr(v), a)
        self.assertEqual(rep.errors, [])

    @pytest.mark.skipif(P < 2, reason="needs a real mesh")
    def test_hsvd_level0_commcheck_clean(self):
        from heat_tpu.core.linalg.svdtools import _local_svd_fn

        comm = ht.get_comm()
        phys = comm.shard(jnp.ones((16, 4 * P), jnp.float32), 1)
        fn = _local_svd_fn(
            comm.mesh, comm.axis_name, 16, phys.shape[1] // P, 3, "float32", 5
        )
        rep = commcheck(fn, phys)
        self.assertEqual(rep.errors, [])
        self.assertEqual(rep.context["collective_counts"], {})

    @pytest.mark.skipif(P < 2, reason="needs a real mesh")
    def test_ring_cmatmul_commcheck_clean(self):
        a = ht.ones((512, 64 * P), split=1)
        b = ht.ones((64 * P, 512), split=0)
        with env_pin(planner.OVERLAP_ENV, "1"):
            rep = commcheck(lambda u, v: ht.matmul(u, v), a, b)
        self.assertEqual(rep.errors, [])

    def test_kcluster_endpoint_commcheck_clean(self):
        from heat_tpu.cluster import _kcluster

        centers = jnp.linspace(0.0, 1.0, 5 * 12, dtype=jnp.float32).reshape(5, 12)
        spec = _kcluster.serving_spec("euclidean", centers)
        prog = spec["build"]()
        batch = jnp.zeros((8, 12), jnp.float32)
        rep = commcheck(prog, batch, *spec["args"])
        self.assertEqual(rep.errors, [])

    @pytest.mark.skipif(P < 2, reason="needs a real mesh")
    def test_training_step_commcheck_clean(self):
        import __graft_entry__ as graft

        fn, args = graft.training_step_program(P)
        rep = commcheck(fn, *args)
        self.assertEqual(rep.errors, [])
        self.assertEqual(rep.context["pass"], "commcheck")

    def test_tree_is_sl504_clean(self):
        rep = commcheck_mod.lint_paths([os.path.join(ROOT, "heat_tpu")], root=ROOT)
        self.assertEqual([str(f) for f in rep.findings], [])


# ------------------------------------------------------------------ #
# the progress invariant (pass 5's dynamic half)                     #
# ------------------------------------------------------------------ #
class TestProgressInvariant(TestCase):
    def test_all_golden_plans_progress_clean(self):
        n = 0
        for topo in ("flat", "2x4", "2x8"):
            for q in ("0", "int8"):
                for name, spec in planner.golden_specs():
                    sched = planner.plan(spec, BUDGET, quant=q, topology=topo)
                    res = verify_plan(sched, topology=topo)
                    self.assertTrue(res["ok"], f"{name}@{topo} quant={q}")
                    self.assertIn("progress", res["checks"])
                    self.assertEqual(check_progress(sched), [], f"{name}@{topo}")
                    n += 1
        self.assertEqual(n, 3 * 2 * len(planner.golden_specs()))

    def test_staged_golden_plans_progress_clean(self):
        from heat_tpu.redistribution import staging

        for name, sched in staging.golden_staged_plans():
            res = verify_plan(sched)
            self.assertTrue(res["ok"], name)
            self.assertIn("progress", res["checks"])
            self.assertEqual(check_progress(sched), [], name)

    def _chunked(self, topo="flat"):
        spec = dict(planner.golden_specs())["resplit_chunked_2gb_p8"]
        sched = planner.plan(spec, BUDGET, quant="0", topology=topo)
        return json.loads(sched.canonical_json())

    def test_mutation_reordered_laps_fail_progress(self):
        """Swap the chunk tags of the first two overlap laps: bytes,
        kinds, counts all conserve — only the replay sees that the
        depth-2 double buffer would consume an unissued lap."""
        m = self._chunked()
        a2a = [k for k, st in enumerate(m["steps"]) if st["kind"] == "all_to_all"]
        self.assertGreaterEqual(len(a2a), 2)
        i, j = a2a[0], a2a[1]
        m["steps"][i]["chunk"], m["steps"][j]["chunk"] = (
            m["steps"][j]["chunk"],
            m["steps"][i]["chunk"],
        )
        with self.assertRaises(PlanVerificationError) as cm:
            verify_plan(m)
        self.assertEqual(cm.exception.invariant, "progress", str(cm.exception))
        self.assertIn("unissued lap", str(cm.exception))
        self.assertIn("pipe0", str(cm.exception))
        # the non-raising mode and the standalone entry agree
        res = verify_plan(m, raise_on_violation=False)
        self.assertIn("progress", [v["invariant"] for v in res["violations"]])
        found = check_progress(m)
        self.assertTrue(found)
        self.assertTrue(all(f.rule == "SL503" for f in found))

    def test_mutation_split_hierarchical_pair_fails_progress(self):
        """Retag one dcn half to a different chunk than its ici pivot:
        the inter-slice exchange would consume a lap the intra-slice
        half never issued."""
        m = self._chunked(topo="2x4")
        self.assertEqual(m["strategy"], "hierarchical-a2a")
        dcn = [k for k, st in enumerate(m["steps"]) if st.get("tier") == "dcn"]
        self.assertTrue(dcn)
        m["steps"][dcn[0]]["chunk"] = 7
        with self.assertRaises(PlanVerificationError) as cm:
            verify_plan(m, topology="2x4")
        self.assertEqual(cm.exception.invariant, "progress", str(cm.exception))

    def test_mutation_open_ring_named_by_standalone_replay(self):
        """Drop one hop from the ring plan: ``verify_plan`` fails at
        composition (exactly p-1 hops), and the standalone replay names
        the progress defect — defense in depth for plans that never
        came from this planner (the MPMD stage-graph case)."""
        spec = dict(planner.golden_specs())["resplit_ring_8gb_p8"]
        sched = planner.plan(spec, BUDGET, quant="0", topology="flat")
        m = json.loads(sched.canonical_json())
        hops = [k for k, st in enumerate(m["steps"]) if st["kind"] == "ppermute"]
        del m["steps"][hops[-1]]
        found = check_progress(m)
        self.assertTrue(found)
        self.assertTrue(any("ring does not close" in f.message for f in found))
        self.assertTrue(any("p-1" in f.message for f in found))
        with self.assertRaises(PlanVerificationError):
            verify_plan(m)

    def test_mutation_broken_topology_product_fails(self):
        """A topology annotation that does not factor the mesh can never
        partition it — both tier-labels and the replay refuse it."""
        m = self._chunked(topo="2x4")
        m["topology"]["n_slices"] = 3
        found = check_progress(m)
        self.assertTrue(any("partition" in f.message for f in found))
        res = verify_plan(m, raise_on_violation=False, topology=None)
        self.assertFalse(res["ok"])

    def test_check_progress_findings_name_the_plan(self):
        m = self._chunked()
        a2a = [k for k, st in enumerate(m["steps"]) if st["kind"] == "all_to_all"]
        m["steps"][a2a[0]]["chunk"], m["steps"][a2a[1]]["chunk"] = (
            m["steps"][a2a[1]]["chunk"],
            m["steps"][a2a[0]]["chunk"],
        )
        for f in check_progress(m):
            self.assertEqual(f.severity, "error")
            self.assertIn(str(m["plan_id"]), f.message)

    def test_congruence_hooks_never_touch_serialization(self):
        """The Schedule-side hooks are read-only: calling them leaves
        the canonical bytes (and so the plan_id) unchanged."""
        spec = dict(planner.golden_specs())["resplit_chunked_2gb_p8"]
        sched = planner.plan(spec, BUDGET, quant="0", topology="flat")
        before = sched.canonical_json()
        structure = sched.collective_group_structure()
        laps = sched.overlap_lap_chunks("pipe0")
        self.assertTrue(structure)
        self.assertEqual(laps, sorted(laps))
        self.assertEqual(sched.canonical_json(), before)

    def test_group_structure_partitions_the_mesh(self):
        """Every reported subgroup shape multiplies back to mesh_size —
        the partition property the replay re-proves on dumps."""
        for topo in ("flat", "2x4"):
            for name, spec in planner.golden_specs():
                sched = planner.plan(spec, BUDGET, quant="0", topology=topo)
                for g in sched.collective_group_structure():
                    self.assertEqual(
                        g["n_groups"] * g["group_size"],
                        sched.spec.mesh_size,
                        f"{name}@{topo}: {g}",
                    )


# ------------------------------------------------------------------ #
# seeded mutations (the ci.sh proof)                                 #
# ------------------------------------------------------------------ #
class TestSeededMutations(TestCase):
    """Remove ONE congruence invariant, the verifier trips. Each
    mutation asserts its anchor still exists, so source drift fails
    loudly instead of silently weakening the proof."""

    @pytest.mark.skipif(P < 2, reason="needs a real mesh")
    def test_mutation_dropped_ring_pair_trips_sl502(self):
        """Invariant: ring_all_gather rides the COMPLETE +1 ring from
        grouped_ring_perm. Mutation: drop the wraparound pair — the
        congruence scan sees a device that receives without sending."""
        from jax.sharding import PartitionSpec as PS

        from heat_tpu.core._jax_compat import shard_map

        comm = self.comm
        full = cmatmul.grouped_ring_perm(1, P)
        self.assertEqual(len(full), P)

        def program(perm):
            def body(xl):
                i = jax.lax.axis_index(comm.axis_name)
                return cmatmul.ring_all_gather(xl, comm.axis_name, P, i, perm)

            return shard_map(
                body,
                mesh=comm.mesh,
                in_specs=(PS(comm.axis_name, None),),
                out_specs=PS(None, None, None),
                check_vma=False,
            )

        phys = comm.shard(jnp.ones((4 * P, 4), jnp.float32), 0)
        clean = commcheck(program(full), phys)
        self.assertNotIn("SL502", [f.rule for f in clean.errors])
        mutated = commcheck(program(full[:-1]), phys)
        hits = [f for f in mutated.findings if f.rule == "SL502"]
        self.assertTrue(hits, [repr(f) for f in mutated.findings])
        self.assertTrue(all(f.severity == "error" for f in hits))

    @pytest.mark.skipif(P < 2, reason="needs a real mesh")
    def test_mutation_device_dependent_predicate_trips_sl501(self):
        """Invariant: a collective-launching cond rides a full-axis
        reduced predicate. Mutation: predicate becomes the LOCAL
        condition — one token, and the lattice sees the divergence."""
        from jax import lax
        from jax.sharding import PartitionSpec as PS

        from heat_tpu.core._jax_compat import shard_map

        comm = self.comm

        def program(mutated):
            def body(xl):
                local = (xl.sum() > 0.0).astype(jnp.float32)
                pred = local if mutated else lax.psum(local, comm.axis_name)
                return lax.cond(
                    pred > 0.0,
                    lambda v: lax.psum(v, comm.axis_name),
                    lambda v: v,
                    xl,
                )

            return shard_map(
                body,
                mesh=comm.mesh,
                in_specs=(PS(comm.axis_name, None),),
                out_specs=PS(comm.axis_name, None),
                check_vma=False,
            )

        phys = comm.shard(jnp.ones((4 * P, 4), jnp.float32), 0)
        self.assertEqual(commcheck(program(False), phys).rule_ids, [])
        rep = commcheck(program(True), phys)
        self.assertIn("SL501", [f.rule for f in rep.errors])

    def test_mutation_unfenced_executor_trips_sl504(self):
        """Invariant: the executor's entry carries the PR 13 epoch
        fence. Mutation: delete the check_world call."""
        src = _read("heat_tpu/redistribution/executor.py")
        anchor = "    _elastic.check_world(comm)\n"
        self.assertIn(anchor, src)
        clean = commcheck_mod.lint_source(src, "heat_tpu/redistribution/executor.py")
        self.assertEqual([f for f in clean if f.rule == "SL504"], [])
        mutated = src.replace(anchor, "")
        found = commcheck_mod.lint_source(
            mutated, "heat_tpu/redistribution/executor.py"
        )
        hits = [f for f in found if f.rule == "SL504"]
        self.assertTrue(hits, [repr(f) for f in found])
        self.assertIn("execute", hits[0].message)

    def test_mutation_unfenced_endpoint_trips_sl504(self):
        """Invariant: Endpoint.run fences on its world token. Mutation:
        delete the check_epoch call."""
        src = _read("heat_tpu/serving/dispatcher.py")
        anchor = "        _elastic.check_epoch(self._world_token"
        self.assertIn(anchor, src)
        clean = commcheck_mod.lint_source(src, "heat_tpu/serving/dispatcher.py")
        self.assertEqual([f for f in clean if f.rule == "SL504"], [])
        lines = [
            ln for ln in src.splitlines(keepends=True)
            if not ln.startswith(anchor)
        ]
        mutated = "".join(lines)
        self.assertNotEqual(mutated, src)
        found = commcheck_mod.lint_source(mutated, "heat_tpu/serving/dispatcher.py")
        hits = [f for f in found if f.rule == "SL504"]
        self.assertTrue(hits, [repr(f) for f in found])
        self.assertTrue(any("run" in f.message for f in hits))


# ------------------------------------------------------------------ #
# the object-level epoch fence (capture_epoch / check_epoch)         #
# ------------------------------------------------------------------ #
class TestEpochFence(TestCase):
    def test_noop_until_a_world_is_stamped(self):
        elastic._clear_stamps()
        token = elastic.capture_epoch()
        elastic.check_epoch(token)  # fresh: no-op
        elastic.check_epoch(None)  # unfenced holder: no-op
        elastic.check_epoch(token - 1)  # stale but fence disarmed: no-op

    def test_stale_token_raises_typed_and_hatch_inerts(self):
        class _Dummy:
            pass

        stale = _Dummy()
        try:
            elastic.stamp(stale)  # arm the fence
            token = elastic.capture_epoch() - 1  # a holder built pre-resize
            with env_pin(ck.RESILIENCE_ENV, "0"):
                elastic.check_epoch(token)  # escape hatch: never raises
            with env_pin(ck.RESILIENCE_ENV, "auto"):
                with self.assertRaises(elastic.WorldChangedError) as cm:
                    elastic.check_epoch(token, what="test endpoint")
                self.assertIn("test endpoint", str(cm.exception))
                elastic.check_epoch(elastic.capture_epoch())  # fresh: no-op
        finally:
            elastic._clear_stamps()


# ------------------------------------------------------------------ #
# the CLI face (scripts/lint.py --pass commcheck | all)              #
# ------------------------------------------------------------------ #
class TestLintCLI(TestCase):
    def test_pass_commcheck_clean_tree_exits_zero(self):
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        r = subprocess.run(
            [
                sys.executable,
                os.path.join(ROOT, "scripts", "lint.py"),
                os.path.join(ROOT, "heat_tpu"),
                "--pass",
                "commcheck",
            ],
            capture_output=True,
            text=True,
            env=env,
        )
        self.assertEqual(r.returncode, 0, r.stdout + r.stderr)
        self.assertIn("[commcheck]", r.stdout)

    def test_pass_all_runs_three_passes_in_one_process(self):
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        r = subprocess.run(
            [
                sys.executable,
                os.path.join(ROOT, "scripts", "lint.py"),
                os.path.join(ROOT, "heat_tpu"),
                "--pass",
                "all",
            ],
            capture_output=True,
            text=True,
            env=env,
        )
        self.assertEqual(r.returncode, 0, r.stdout + r.stderr)
        for tag in ("[srclint]", "[effectcheck]", "[commcheck]"):
            self.assertIn(tag, r.stdout)


# ------------------------------------------------------------------ #
# scripts/verify_plans.py sweeps the progress invariant              #
# ------------------------------------------------------------------ #
class TestVerifyPlansSweep(TestCase):
    @pytest.mark.slow
    def test_sweep_passes_and_mutated_dump_names_progress(self):
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        dump = subprocess.run(
            [sys.executable, os.path.join(ROOT, "scripts", "redist_plans.py")],
            capture_output=True,
            text=True,
            env=env,
        )
        self.assertEqual(dump.returncode, 0, dump.stderr)
        ok = subprocess.run(
            [sys.executable, os.path.join(ROOT, "scripts", "verify_plans.py")],
            input=dump.stdout,
            capture_output=True,
            text=True,
            env=env,
        )
        self.assertEqual(ok.returncode, 0, ok.stdout + ok.stderr)
        # hand-mutate one chunked plan's lap order: the sweep fails
        # naming the progress invariant and the violating group
        mutated_lines = []
        hit = False
        for line in dump.stdout.splitlines():
            name, _, payload = line.partition("\t")
            if not hit and payload:
                d = json.loads(payload)
                a2a = [
                    k
                    for k, st in enumerate(d.get("steps") or [])
                    if st.get("kind") == "all_to_all"
                    and st.get("chunk") is not None
                    and st.get("overlap") is not None
                ]
                if len(a2a) >= 2 and d.get("overlap"):
                    i, j = a2a[0], a2a[1]
                    d["steps"][i]["chunk"], d["steps"][j]["chunk"] = (
                        d["steps"][j]["chunk"],
                        d["steps"][i]["chunk"],
                    )
                    line = name + "\t" + json.dumps(d, sort_keys=True)
                    hit = True
            mutated_lines.append(line)
        self.assertTrue(hit, "no chunked overlap plan in the dump to mutate")
        bad = subprocess.run(
            [sys.executable, os.path.join(ROOT, "scripts", "verify_plans.py")],
            input="\n".join(mutated_lines) + "\n",
            capture_output=True,
            text=True,
            env=env,
        )
        self.assertEqual(bad.returncode, 1, bad.stdout + bad.stderr)
        self.assertIn("progress", bad.stdout)
