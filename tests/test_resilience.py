"""ISSUE 13: the elastic, fault-tolerant runtime (``heat_tpu.resilience``).

Contracts pinned here:

- **Checkpoint envelope** — slab-streamed save/load round-trips numpy /
  jax (replicated AND split-0-sharded) / DNDarray / scalar / RNG-tuple
  state bit-exactly; per-entry sha256 catches truncation as
  ``CheckpointCorrupt`` and ``restore_latest`` falls back to the
  committed predecessor; ``.tmp-*`` write orphans are invisible; host
  memory stays O(slab), ASSERTED off the envelope's recorded
  ``max_slab_bytes``; the meta stamps the PR 12 gate roster + topology.
- **Resume contract** — ``KMeans.fit(HostArray, ckpt=)`` commits the
  window cursor and resumes bit-identically to an uninterrupted
  same-seed run: same world, a crashed-and-restarted process, or a
  RESIZED world (the restored arrays re-shard onto the survivors).
- **RNG satellite** — seed/stream state is explicit model state: two
  same-seed models draw IDENTICAL inits, the ctor never touches the
  global stream, and checkpoint-restored twins draw identically (the
  PR 11 footgun closed).
- **World re-resolution** — epoch bump + eviction sweep over the
  plan/program/jit caches; a stamped stale-epoch communicator entering
  the redistribution executor raises the typed ``WorldChangedError``.
- **Serving failover** — ``Dispatcher.drain(reason="resize")`` fences
  the in-flight batch (its futures RESOLVE), sheds the queue typed,
  rejects submits during the drain, and ``resume``/``drain_and_rewarm``
  serve again with a rebuilt endpoint.
- **Chaos harness** — same seed + same declarations = byte-identical
  injection schedules; poison recovery is bit-identical.
- **SL406** — the swallowed-worker-exception rule fires on the golden
  fixture, passes every surfacing idiom, and the shipped dispatcher /
  partial-dataset workers are pinned clean (with a seeded-bug mutation
  proof on the dispatcher's own handler).
- **Escape hatch** — ``HEAT_TPU_RESILIENCE=0``: no checkpoints, no
  fences, plain fit paths.
"""

import os
import shutil
import tempfile
import threading

import numpy as np

import jax
import jax.numpy as jnp

import heat_tpu as ht

import analysis_fixtures as fx  # noqa: F401  (fixture import parity with test_effectcheck)

from heat_tpu.analysis import effectcheck, findings
from heat_tpu.core import communication as comm_mod, gates, tiers
from heat_tpu.core import random as ht_random
from heat_tpu.redistribution import planner, staging
from heat_tpu.resilience import chaos, checkpoint as ck, elastic
from heat_tpu.serving.admission import ServingOverloaded
from heat_tpu.serving.dispatcher import Dispatcher, Endpoint

from test_suites.basic_test import TestCase, env_pin

P = len(jax.devices())
ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _bits(dnd) -> np.ndarray:
    return np.asarray(dnd.numpy()).view(np.uint32)


def _host(n=40960, d=16, seed=0) -> staging.HostArray:
    rng = np.random.default_rng(seed)
    pts = rng.standard_normal((n, d)).astype(np.float32)
    pts[: n // 4] += 4.0
    return staging.HostArray(pts)


def _restore_full_world():
    comm_mod.use_comm(comm_mod.MPI_WORLD)
    elastic._clear_stamps()


# ------------------------------------------------------------------ #
# gates + lattice edge                                               #
# ------------------------------------------------------------------ #
class TestResilienceGates(TestCase):
    def test_gates_declared(self):
        spec = gates.GATES["HEAT_TPU_RESILIENCE"]
        self.assertEqual(spec.values, ("0", "1", "auto"))
        self.assertTrue(spec.affects_programs)
        self.assertIn("aot", spec.scopes)
        dir_spec = gates.GATES["HEAT_TPU_CKPT_DIR"]
        self.assertFalse(dir_spec.affects_programs)
        self.assertEqual(dir_spec.kind, "path")
        roster = gates.program_gate_roster()
        self.assertIn("HEAT_TPU_RESILIENCE", roster)
        self.assertNotIn("HEAT_TPU_CKPT_DIR", roster)

    def test_mode_resolution(self):
        with env_pin(ck.RESILIENCE_ENV, None):
            self.assertEqual(ck.resilience_mode(), "auto")
            self.assertFalse(ck.resilience_enabled())
            self.assertTrue(ck.resilience_enabled(explicit=True))
        for raw in ("0", "off", "no"):
            with env_pin(ck.RESILIENCE_ENV, raw):
                self.assertEqual(ck.resilience_mode(), "0")
                self.assertFalse(ck.resilience_enabled(explicit=True))
        for raw in ("1", "force", "on"):
            with env_pin(ck.RESILIENCE_ENV, raw):
                self.assertEqual(ck.resilience_mode(), "1")
                self.assertTrue(ck.resilience_enabled())

    def test_ckpt_dir_resolution(self):
        with env_pin(ck.CKPT_DIR_ENV, "/tmp/ht-ckpt-test"):
            self.assertEqual(ck.ckpt_dir(), "/tmp/ht-ckpt-test")
        self.assertEqual(ck.ckpt_dir("/explicit"), "/explicit")

    def test_disk_edge_priced(self):
        self.assertEqual(tiers.bandwidth("disk"), tiers.DISK_BPS)
        self.assertEqual(tiers.edge_between("host", "disk"), "disk")
        self.assertGreaterEqual(tiers.penalty("disk"), 1)
        self.assertIn("disk", tiers.describe())
        # the durable-commit price sits BELOW the pcie staging edge —
        # a checkpoint is never modeled faster than the host hop
        self.assertLess(tiers.DISK_BPS, tiers.PCIE_BPS)


# ------------------------------------------------------------------ #
# checkpoint envelope                                                #
# ------------------------------------------------------------------ #
class TestCheckpointEnvelope(TestCase):
    def test_round_trip_all_kinds(self):
        with tempfile.TemporaryDirectory() as d:
            x = ht.ones((64, 8), split=0 if P > 1 else None) * 3.5
            carry = comm_mod.get_comm().shard(
                jnp.arange(P * 6, dtype=jnp.float32).reshape(P, 6), 0
            )
            state = {
                "dnd": x,
                "np": np.arange(24, dtype=np.float64).reshape(4, 6),
                "jax_repl": jnp.full((3, 3), 2.25, jnp.float32),
                "jax_sharded": carry,
                "rng": ("Threefry", 7, 13, 0, 0.0),
                "cursor": 5,
                "note": "resume",
            }
            ck.save(state, tag="rt", step=3, directory=d)
            step, got, meta = ck.restore_latest(d, tag="rt")
            self.assertEqual(step, 3)
            np.testing.assert_array_equal(got["dnd"].numpy(), x.numpy())
            self.assertEqual(got["dnd"].split, x.split)
            np.testing.assert_array_equal(got["np"], state["np"])
            np.testing.assert_array_equal(
                np.asarray(got["jax_repl"]), np.asarray(state["jax_repl"])
            )
            np.testing.assert_array_equal(
                np.asarray(jax.device_get(got["jax_sharded"])),
                np.asarray(jax.device_get(carry)),
            )
            if P > 1:
                self.assertFalse(got["jax_sharded"].sharding.is_fully_replicated)
            self.assertEqual(got["rng"], state["rng"])
            self.assertEqual(got["cursor"], 5)
            self.assertEqual(got["note"], "resume")

    def test_stamps(self):
        with tempfile.TemporaryDirectory() as d:
            ck.save({"a": np.zeros(4, np.float32)}, tag="s", step=1, directory=d)
            _, _, meta = ck.restore_latest(d, tag="s")
            stamps = meta["stamps"]
            self.assertEqual(stamps["gate_roster"], gates.program_gate_roster())
            self.assertEqual(stamps["world_size"], comm_mod.get_comm().size)
            self.assertEqual(stamps["topology"], str(comm_mod.get_comm().topology))
            self.assertEqual(meta["format"], ck.FORMAT)

    def test_truncation_detected_and_fallback(self):
        with tempfile.TemporaryDirectory() as d:
            state = {"a": np.arange(4096, dtype=np.float32)}
            ck.save(state, tag="t", step=1, directory=d)
            ck.save(state, tag="t", step=2, directory=d)
            path2 = ck.step_path(d, "t", 2)
            with open(os.path.join(path2, "a.bin"), "r+b") as f:
                f.truncate(100)
            with self.assertRaises(ck.CheckpointCorrupt):
                ck.load(path2)
            step, _, _ = ck.restore_latest(d, tag="t")
            self.assertEqual(step, 1)  # corruption costs recency, not correctness

    def test_bitflip_detected(self):
        with tempfile.TemporaryDirectory() as d:
            ck.save({"a": np.zeros(1024, np.float32)}, tag="b", step=1, directory=d)
            fp = os.path.join(ck.step_path(d, "b", 1), "a.bin")
            with open(fp, "r+b") as f:
                f.seek(512)
                f.write(b"\x01")
            with self.assertRaises(ck.CheckpointCorrupt):
                ck.load(ck.step_path(d, "b", 1))

    def test_tmp_orphans_invisible(self):
        with tempfile.TemporaryDirectory() as d:
            ck.save({"a": np.zeros(4, np.float32)}, tag="o", step=1, directory=d)
            orphan = ck.step_path(d, "o", 2) + ".tmp-999"
            os.makedirs(orphan)
            with open(os.path.join(orphan, "meta.json"), "w") as f:
                f.write("{}")  # a torn write that never committed
            self.assertEqual(ck.list_steps(d, "o"), [1])
            self.assertEqual(ck.latest_step(d, "o"), 1)

    def test_meta_tamper_detected(self):
        """Review regression: the meta carries the resume-critical
        cursor — a parseable-but-flipped meta.json (window_index digit
        flip) must fail verification, not resume from a wrong cursor."""
        with tempfile.TemporaryDirectory() as d:
            ck.save(
                {"a": np.zeros(8, np.float32), "window_index": 3},
                tag="m", step=1, directory=d,
            )
            mp = os.path.join(ck.step_path(d, "m", 1), "meta.json")
            with open(mp) as f:
                tampered = f.read().replace('"window_index": 3', '"window_index": 7')
            with open(mp, "w") as f:
                f.write(tampered)
            with self.assertRaises(ck.CheckpointCorrupt):
                ck.load(ck.step_path(d, "m", 1))
            self.assertIsNone(ck.restore_latest(d, tag="m"))

    def test_prune_keeps_newest(self):
        with tempfile.TemporaryDirectory() as d:
            for s in (1, 2, 3, 4):
                ck.save({"a": np.zeros(4, np.float32)}, tag="p", step=s, directory=d)
            dropped = ck.prune(d, "p", keep=2)
            self.assertEqual(dropped, [1, 2])
            self.assertEqual(ck.list_steps(d, "p"), [3, 4])

    def test_host_memory_o_slab_asserted(self):
        """The acceptance pin: host staging during save is bounded at
        O(slab), read off the envelope's RECORDED high-water mark — an
        unsharded 256 MiB entry stages at most SLAB_BYTES at once, and
        a split-0 DNDarray at most one device block."""
        with tempfile.TemporaryDirectory() as d:
            big = np.zeros((256 << 20) // 4, dtype=np.float32)  # 256 MiB
            path = ck.save({"big": big}, tag="slab", step=1, directory=d)
            meta = ck._read_meta(path)
            self.assertEqual(meta["total_bytes"], big.nbytes)
            self.assertLessEqual(meta["max_slab_bytes"], ck.SLAB_BYTES)
            self.assertLess(meta["max_slab_bytes"], big.nbytes // 2)
        with tempfile.TemporaryDirectory() as d:
            rows = 512 * max(P, 1)
            x = ht.ones((rows, 64), split=0 if P > 1 else None)
            path = ck.save({"x": x}, tag="slab", step=1, directory=d)
            meta = ck._read_meta(path)
            block = (x._phys.shape[0] // max(P, 1)) * 64 * 4 if P > 1 else x.numpy().nbytes
            self.assertLessEqual(meta["max_slab_bytes"], max(block, ck.SLAB_BYTES))

    def test_write_floor_vs_disk_edge(self):
        """Supporting evidence for the bench floor (``ckpt_write_2gb``
        pins >= 0.5x at 2.1 GB): a 256 MiB durable commit must not fall
        below a LOOSE 0.2x of the lattice's disk edge even on a noisy
        CI box — the pipelined writer is disk-bound, not hash-bound."""
        import time

        with tempfile.TemporaryDirectory() as d:
            data = np.random.default_rng(0).standard_normal((64 << 20) // 8)
            data = data.astype(np.float32)  # 32 MiB x 8 = 256 MiB? no: keep simple
            data = np.tile(data, 8)  # 256 MiB
            t0 = time.perf_counter()
            ck.save({"data": data}, tag="bw", step=1, directory=d)
            dt = time.perf_counter() - t0
            gbps = data.nbytes / dt / 1e9
            self.assertGreaterEqual(
                gbps, 0.2 * tiers.bandwidth("disk") / 1e9,
                f"durable commit at {gbps:.3f} GB/s",
            )

    def test_failed_save_leaks_no_writer_threads(self):
        """Review regression: a mid-entry save failure aborts the
        writer — no parked hasher, no 20 Hz flusher, no open fd left
        behind per retry."""
        if P == 1:
            self.skipTest("split-1 needs a multi-device mesh")
        import threading as _threading
        import time as _time

        with tempfile.TemporaryDirectory() as d:
            before = _threading.active_count()
            for _ in range(3):
                with self.assertRaises(NotImplementedError):
                    ck.save(
                        {"ok": np.zeros(8, np.float32), "x": ht.ones((32, 32), split=1)},
                        tag="leak", step=1, directory=d,
                    )
            _time.sleep(0.1)
            self.assertLessEqual(_threading.active_count(), before)
            self.assertEqual(ck.list_steps(d, "leak"), [])  # nothing committed

    def test_flush_error_fails_the_commit(self):
        """Review regression: a writeback error observed by the early
        flusher must fail the commit — close() re-raises it instead of
        letting its own (error-cleared) fsync falsely succeed."""
        with tempfile.TemporaryDirectory() as d:
            w = ck._SlabWriter(os.path.join(d, "e.bin"))
            w.write(np.zeros(16, np.float32))
            w._flush_error = OSError("injected EIO")
            with self.assertRaises(OSError):
                w.close()

    def test_replicated_jax_staging_recorded_honestly(self):
        """Review regression: a replicated jax entry stages WHOLE on
        the host — max_slab_bytes must record that true footprint, not
        just the 64 MiB write chunks."""
        with tempfile.TemporaryDirectory() as d:
            big = jnp.zeros((1 << 20,), jnp.float32)  # 4 MiB replicated
            path = ck.save({"p": big}, tag="honest", step=1, directory=d)
            meta = ck._read_meta(path)
            self.assertGreaterEqual(meta["max_slab_bytes"], big.nbytes)

    def test_split1_dnd_rejected(self):
        if P == 1:
            self.skipTest("split-1 needs a multi-device mesh")
        with tempfile.TemporaryDirectory() as d:
            x = ht.ones((32, 32), split=1)
            with self.assertRaises(NotImplementedError):
                ck.save({"x": x}, tag="s1", step=1, directory=d)


# ------------------------------------------------------------------ #
# the RNG satellite                                                  #
# ------------------------------------------------------------------ #
class TestExplicitRngState(TestCase):
    def _data(self):
        rng = np.random.default_rng(5)
        return ht.array(rng.standard_normal((256, 8)).astype(np.float32), split=None)

    def test_same_seed_models_draw_identical_inits(self):
        """The PR 11 footgun closed: two same-seed models created then
        fitted IN SEQUENCE draw identical inits (each owns a private
        (seed, 0) stream; the old global-stream contract made the
        second model draw from wherever the first left the counter)."""
        data = self._data()
        for init in ("random", "kmeans++"):
            a = ht.cluster.KMeans(n_clusters=4, init=init, max_iter=5, random_state=9)
            b = ht.cluster.KMeans(n_clusters=4, init=init, max_iter=5, random_state=9)
            a.fit(data)
            b.fit(data)
            np.testing.assert_array_equal(
                _bits(a.cluster_centers_), _bits(b.cluster_centers_), init
            )

    def test_ctor_and_fit_leave_global_stream_untouched(self):
        before = ht_random.get_state()
        km = ht.cluster.KMeans(n_clusters=3, init="random", max_iter=3, random_state=4)
        km.fit(self._data())
        self.assertEqual(ht_random.get_state(), before)
        self.assertEqual(km.rng_state[1], 4)  # seed
        self.assertGreater(km.rng_state[2], 0)  # init ADVANCED the model stream

    def test_unseeded_model_keeps_legacy_global_stream(self):
        ht_random.seed(123)
        before = ht_random.get_state()
        km = ht.cluster.KMeans(n_clusters=3, init="random", max_iter=3)
        self.assertIsNone(km.rng_state)
        km.fit(self._data())
        self.assertNotEqual(ht_random.get_state(), before)

    def test_restored_twins_draw_identical(self):
        """The satellite's acceptance sentence: two models restored
        from the SAME checkpoint carry the same stream state and draw
        identical subsequent inits."""
        data = self._data()
        km = ht.cluster.KMeans(n_clusters=4, init="random", max_iter=5, random_state=9)
        km.fit(data)
        with tempfile.TemporaryDirectory() as d:
            ck.save(
                {"rng_state": km.rng_state, "centers": km.cluster_centers_},
                tag="twins", step=1, directory=d,
            )
            _, state, _ = ck.restore_latest(d, tag="twins")
            twins = []
            for _ in range(2):
                m = ht.cluster.KMeans(n_clusters=4, init="random", max_iter=5)
                m.rng_state = state["rng_state"]
                m.fit(data)  # draws its init from the restored stream
                twins.append(_bits(m.cluster_centers_))
            np.testing.assert_array_equal(twins[0], twins[1])
            self.assertEqual(state["rng_state"], km.rng_state)


# ------------------------------------------------------------------ #
# streaming resume                                                   #
# ------------------------------------------------------------------ #
class TestStreamingResume(TestCase):
    def _ref(self, host, seed=11):
        km = ht.cluster.KMeans(n_clusters=4, init="random", random_state=seed)
        km.fit(host)
        return _bits(km.cluster_centers_)

    def test_checkpointed_fit_bit_identical_to_plain(self):
        # explicit gate anchor: these tests REQUIRE the runtime engaged,
        # so the HEAT_TPU_RESILIENCE=0 escape-hatch CI leg still passes
        with env_pin(staging.SLAB_ENV, "1"), env_pin(ck.RESILIENCE_ENV, "auto"):
            host = _host()
            ref = self._ref(host)
            with tempfile.TemporaryDirectory() as d:
                cfg = ck.CheckpointConfig(directory=d, tag="km", every=2)
                km = ht.cluster.KMeans(n_clusters=4, init="random", random_state=11)
                km.fit(host, ckpt=cfg)
                np.testing.assert_array_equal(ref, _bits(km.cluster_centers_))
                self.assertTrue(ck.list_steps(d, "km"))

    def test_crash_resume_bit_identical(self):
        """Kill the run after an early checkpoint (simulated: drop the
        later envelopes), resume in a FRESH model, and reproduce the
        uninterrupted bits — including the streaming counts."""
        with env_pin(staging.SLAB_ENV, "1"), env_pin(ck.RESILIENCE_ENV, "auto"):
            host = _host()
            ref = self._ref(host)
            with tempfile.TemporaryDirectory() as d:
                cfg = ck.CheckpointConfig(directory=d, tag="crash", every=1, keep=99)
                km = ht.cluster.KMeans(n_clusters=4, init="random", random_state=11)
                km.fit(host, ckpt=cfg)
                full_counts = np.asarray(jax.device_get(km._partial_counts))
                steps = ck.list_steps(d, "crash")
                self.assertGreaterEqual(len(steps), 3)
                for s in steps[1:]:
                    shutil.rmtree(ck.step_path(d, "crash", s))
                fresh = ht.cluster.KMeans(n_clusters=4, init="random", random_state=11)
                fresh.fit(host, ckpt=cfg)
                np.testing.assert_array_equal(ref, _bits(fresh.cluster_centers_))
                np.testing.assert_array_equal(
                    full_counts, np.asarray(jax.device_get(fresh._partial_counts))
                )

    def test_resume_on_resized_world_bit_identical(self):
        """The elastic acceptance at this mesh: restore re-shards onto
        a SHRUNK world and the resumed windows reproduce the original
        world's bits exactly."""
        if P < 2:
            self.skipTest("needs a multi-device mesh to shrink")
        with env_pin(staging.SLAB_ENV, "1"), env_pin(ck.RESILIENCE_ENV, "auto"):
            host = _host()
            ref = self._ref(host)
            try:
                with tempfile.TemporaryDirectory() as d:
                    cfg = ck.CheckpointConfig(directory=d, tag="rs", every=1, keep=99)
                    km = ht.cluster.KMeans(
                        n_clusters=4, init="random", random_state=11
                    )
                    km.fit(host, ckpt=cfg)
                    steps = ck.list_steps(d, "rs")
                    for s in steps[2:]:
                        shutil.rmtree(ck.step_path(d, "rs", s))
                    elastic.resolve_world(comm_mod.MPI_WORLD.devices[: P // 2 + 1])
                    elastic.invalidate_caches("test-resize")
                    fresh = ht.cluster.KMeans(
                        n_clusters=4, init="random", random_state=11
                    )
                    fresh.fit(host, ckpt=cfg)
                    self.assertEqual(
                        fresh.cluster_centers_.comm.size, P // 2 + 1
                    )
                    np.testing.assert_array_equal(ref, _bits(fresh.cluster_centers_))
            finally:
                _restore_full_world()

    def test_fit_ckpt_rejects_unstreamable_inputs(self):
        cfg = ck.CheckpointConfig(directory=tempfile.gettempdir(), tag="x")
        with env_pin(ck.RESILIENCE_ENV, "auto"):
            with self.assertRaises(ValueError):
                ht.cluster.KMeans(n_clusters=2).fit(
                    ht.ones((32, 4), split=None), ckpt=cfg
                )
            with env_pin(staging.OOC_ENV, "0"):
                with self.assertRaises(ValueError):
                    ht.cluster.KMeans(n_clusters=2).fit(
                        staging.HostArray(np.ones((64, 4), np.float32)), ckpt=cfg
                    )
        # ... but under the =0 escape hatch ckpt= is inert EVERYWHERE
        # (review regression): both shapes run the plain pre-resilience
        # fit instead of raising
        with env_pin(ck.RESILIENCE_ENV, "0"):
            km = ht.cluster.KMeans(n_clusters=2, random_state=1).fit(
                ht.ones((32, 4), split=None), ckpt=cfg
            )
            self.assertIsNotNone(km.cluster_centers_)
            with env_pin(staging.OOC_ENV, "0"):
                km = ht.cluster.KMeans(n_clusters=2, random_state=1).fit(
                    staging.HostArray(np.ones((64, 4), np.float32)), ckpt=cfg
                )
                self.assertIsNotNone(km.cluster_centers_)

    def test_escape_hatch_ignores_ckpt(self):
        """HEAT_TPU_RESILIENCE=0: the exact pre-resilience stream — no
        checkpoint is ever written, and elastic_fit is plain fit."""
        with env_pin(ck.RESILIENCE_ENV, "0"), env_pin(staging.SLAB_ENV, "1"):
            host = _host(n=8192)
            with tempfile.TemporaryDirectory() as d:
                cfg = ck.CheckpointConfig(directory=d, tag="off", every=1)
                km = ht.cluster.KMeans(n_clusters=4, init="random", random_state=3)
                elastic.elastic_fit(km, host, ckpt=cfg)
                self.assertEqual(ck.list_steps(d, "off"), [])
                plain = ht.cluster.KMeans(n_clusters=4, init="random", random_state=3)
                plain.fit(host)
                np.testing.assert_array_equal(
                    _bits(km.cluster_centers_), _bits(plain.cluster_centers_)
                )


    def test_escape_hatch_leaves_hooks_inert(self):
        """Review regression: under HEAT_TPU_RESILIENCE=0 the watcher/
        chaos hooks are inert too — a declared slice kill neither fires
        nor costs the per-window validation sync."""
        with env_pin(ck.RESILIENCE_ENV, "0"), env_pin(staging.SLAB_ENV, "1"):
            host = _host(n=8192)
            watcher = elastic.SimulatedWorldWatcher(
                topology="2x4" if P == 8 else None
            ).kill_slice_at(1, 0)
            km = ht.cluster.KMeans(n_clusters=4, init="random", random_state=3)
            km.fit(host, _watcher=watcher)  # must NOT raise
            self.assertEqual(watcher.events, [])
            self.assertEqual(comm_mod.get_comm().size, P)

    def test_failure_before_first_commit_still_bit_reproducible(self):
        """Review regression: a poison at window 0 (BEFORE any commit)
        rewinds the model's private RNG stream, so the retry re-inits
        identically and the recovered fit still matches the
        uninterrupted run bit-for-bit."""
        with env_pin(staging.SLAB_ENV, "1"), env_pin(ck.RESILIENCE_ENV, "auto"):
            host = _host()
            ref = self._ref(host)
            with tempfile.TemporaryDirectory() as d:
                cfg = ck.CheckpointConfig(directory=d, tag="early", every=3)
                monkey = chaos.ChaosMonkey(seed=2).poison_collective(step=0)
                km = ht.cluster.KMeans(n_clusters=4, init="random", random_state=11)
                elastic.elastic_fit(km, host, ckpt=cfg, chaos=monkey)
                np.testing.assert_array_equal(ref, _bits(km.cluster_centers_))

    def test_resume_refuses_foreign_operand(self):
        """Review regression: a same-tag resume against a DIFFERENT
        dataset fails typed instead of adopting the old cursor."""
        with env_pin(staging.SLAB_ENV, "1"), env_pin(ck.RESILIENCE_ENV, "auto"):
            with tempfile.TemporaryDirectory() as d:
                cfg = ck.CheckpointConfig(directory=d, tag="op", every=1)
                km = ht.cluster.KMeans(n_clusters=4, init="random", random_state=1)
                km.fit(_host(), ckpt=cfg)
                other = staging.HostArray(
                    np.ones((8192, 16), np.float32)
                )
                km2 = ht.cluster.KMeans(n_clusters=4, init="random", random_state=1)
                with self.assertRaises(ValueError) as cm:
                    km2.fit(other, ckpt=cfg)
                self.assertIn("fresh tag", str(cm.exception))


# ------------------------------------------------------------------ #
# world re-resolution                                                #
# ------------------------------------------------------------------ #
class TestElasticWorld(TestCase):
    def test_world_changed_error_typed(self):
        e = elastic.WorldChangedError("slice-lost", old_size=8, new_size=4, epoch=2)
        self.assertEqual(e.reason, "slice-lost")
        self.assertEqual((e.old_size, e.new_size, e.epoch), (8, 4, 2))
        self.assertIn("8 -> 4", str(e))

    def test_simulated_watcher_slice_major(self):
        if P != 8:
            self.skipTest("slice-major layout pinned at the 8-device mesh")
        w = elastic.SimulatedWorldWatcher(topology="2x4")
        w.kill_slice_at(3, slice_index=0)
        self.assertIsNone(w.poll(2))
        evt = w.poll(3)
        self.assertEqual(evt.kind, "slice-lost")
        # slice 0 owns mesh positions [0, 4): the SURVIVORS are 4..7
        all_devs = comm_mod.MPI_WORLD.devices
        self.assertEqual(evt.devices, all_devs[4:])
        self.assertEqual(w.devices(), all_devs[4:])
        self.assertEqual(evt.detail["old_size"], 8)
        # successive events report the PREVIOUS world's size, not the
        # original one (review regression)
        w.resize_at(5, 2)
        evt2 = w.poll(5)
        self.assertEqual(evt2.detail["old_size"], 4)
        self.assertIsNone(w.poll(3))  # fires once

    def test_invalidate_bumps_epoch_and_sweeps(self):
        spec_name, spec = next(iter(planner.golden_specs()))
        planner.plan(spec)

        @ht.jit
        def prog(a):
            return a + 1.0

        prog(ht.ones((8,)))
        before = elastic.world_epoch()
        counts = elastic.invalidate_caches("test")
        self.assertEqual(elastic.world_epoch(), before + 1)
        self.assertGreaterEqual(counts["plans"], 1)
        self.assertGreaterEqual(counts["jit_entries"], 1)
        self.assertEqual(len(prog._ht_jit_cache), 0)

    def test_stale_epoch_comm_raises_in_executor(self):
        if P < 2:
            self.skipTest("needs a distributed resplit")
        stale = comm_mod.MeshCommunication(comm_mod.MPI_WORLD.devices)
        try:
            with env_pin(ck.RESILIENCE_ENV, "auto"):
                elastic.stamp(stale)
                elastic.invalidate_caches("test-stale")
                x = ht.ones((64, 4), split=0, comm=stale)
                with self.assertRaises(elastic.WorldChangedError):
                    x.resplit(1)
        finally:
            elastic._clear_stamps()
        # fence disarmed: the same movement executes normally again
        y = ht.ones((64, 4), split=0).resplit(1)
        self.assertEqual(y.split, 1)

    def test_check_world_is_noop_by_default_and_under_escape_hatch(self):
        elastic._clear_stamps()
        elastic.check_world(comm_mod.get_comm())  # fence disarmed: no-op
        # a STALE comm object (not the installed default) trips the fence
        stale = comm_mod.MeshCommunication(comm_mod.MPI_WORLD.devices)
        try:
            elastic.stamp(stale)
            elastic.invalidate_caches("test-hatch")
            with env_pin(ck.RESILIENCE_ENV, "0"):
                elastic.check_world(stale)  # escape hatch: never raises
            with env_pin(ck.RESILIENCE_ENV, "auto"):
                with self.assertRaises(elastic.WorldChangedError):
                    elastic.check_world(stale)
        finally:
            elastic._clear_stamps()

    def test_elastic_fit_recovers_from_slice_kill(self):
        if P < 2:
            self.skipTest("needs a multi-device mesh to shrink")
        with env_pin(staging.SLAB_ENV, "1"), env_pin(ck.RESILIENCE_ENV, "auto"):
            host = _host()
            km_ref = ht.cluster.KMeans(n_clusters=4, init="random", random_state=11)
            km_ref.fit(host)
            ref = _bits(km_ref.cluster_centers_)
            try:
                with tempfile.TemporaryDirectory() as d:
                    cfg = ck.CheckpointConfig(directory=d, tag="el", every=1)
                    monkey = chaos.ChaosMonkey(seed=3).kill_slice(step=2)
                    watcher = monkey.watcher(
                        topology="2x4" if P == 8 else None
                    )
                    km = ht.cluster.KMeans(
                        n_clusters=4, init="random", random_state=11
                    )
                    elastic.elastic_fit(
                        km, host, ckpt=cfg, watcher=watcher, chaos=monkey
                    )
                    self.assertLess(comm_mod.get_comm().size, P)
                    np.testing.assert_array_equal(ref, _bits(km.cluster_centers_))
            finally:
                _restore_full_world()


    def test_recovery_order_leaves_current_world_live(self):
        """Review regression: resolve_world() THEN invalidate_caches()
        (the shipped recovery order) must leave the installed world
        UN-fenced — the current communicator rides the epoch bump
        forward; only dead worlds' comms trip the fence."""
        if P < 2:
            self.skipTest("needs a distributed resplit")
        try:
            with env_pin(ck.RESILIENCE_ENV, "auto"):
                comm = elastic.resolve_world(comm_mod.MPI_WORLD.devices)
                elastic.invalidate_caches("test-order")
                elastic.check_world(comm)  # must NOT raise
                x = ht.ones((64, 4), split=0).resplit(1)  # executor entry
                self.assertEqual(x.split, 1)
                # and the inverse order too
                elastic.invalidate_caches("test-order-2")
                comm2 = elastic.resolve_world(comm_mod.MPI_WORLD.devices)
                elastic.check_world(comm2)
        finally:
            _restore_full_world()


# ------------------------------------------------------------------ #
# serving failover                                                   #
# ------------------------------------------------------------------ #
class TestDispatcherDrain(TestCase):
    def _blocked_dispatcher(self):
        gate, entered = threading.Event(), threading.Event()

        def blocking_place(batch):
            entered.set()
            gate.wait(30)
            return jnp.asarray(batch)

        ep = Endpoint(
            {8: jax.jit(lambda b: b * 2.0)}, (4,), np.float32, place=blocking_place
        )
        d = Dispatcher(ep, max_queue=32, poll_s=0.005).start()
        return d, gate, entered

    def test_drain_fences_inflight_and_sheds_typed(self):
        d, gate, entered = self._blocked_dispatcher()
        try:
            inflight = d.submit(np.ones((2, 4), np.float32))
            self.assertTrue(entered.wait(10))
            queued = [d.submit(np.ones((1, 4), np.float32)) for _ in range(6)]
            out = []
            t = threading.Thread(
                target=lambda: out.append(d.drain(reason="resize", timeout=30))
            )
            t.start()
            gate.set()
            t.join(35)
            self.assertEqual(out, [True])
            # the in-flight batch COMPLETED — its future resolves
            np.testing.assert_allclose(np.asarray(inflight.result(1)), 2.0)
            reasons = set()
            for f in queued:
                with self.assertRaises(ServingOverloaded) as cm:
                    f.result(1)
                reasons.add(cm.exception.reason)
            self.assertEqual(reasons, {"resize"})
            self.assertGreaterEqual(d.stats()["shed"], 6)
            # submits during the drain fail fast with the drain reason
            with self.assertRaises(ServingOverloaded) as cm:
                d.submit(np.ones((1, 4), np.float32))
            self.assertEqual(cm.exception.reason, "resize")
        finally:
            gate.set()
            d.stop()

    def test_resume_serves_with_new_endpoint(self):
        d, gate, entered = self._blocked_dispatcher()
        try:
            gate.set()
            self.assertTrue(d.drain(reason="resize", timeout=10))
            ep2 = Endpoint({8: jax.jit(lambda b: b * 5.0)}, (4,), np.float32)
            d.resume(endpoint=ep2)
            out = d.call(np.ones((2, 4), np.float32), timeout=10)
            np.testing.assert_allclose(np.asarray(out), 5.0)
        finally:
            d.stop()

    def test_drain_and_rewarm_helper(self):
        d, gate, entered = self._blocked_dispatcher()
        try:
            gate.set()
            ep2 = elastic.drain_and_rewarm(
                d,
                lambda: Endpoint({8: jax.jit(lambda b: b * 7.0)}, (4,), np.float32),
                reason="resize",
            )
            self.assertIs(d.endpoint, ep2)
            out = d.call(np.ones((1, 4), np.float32), timeout=10)
            np.testing.assert_allclose(np.asarray(out), 7.0)
        finally:
            d.stop()

    def test_drain_and_rewarm_timeout_raises(self):
        """Review regression: a drain that cannot confirm must raise —
        swapping the endpoint under a live worker is never safe."""
        d, gate, entered = self._blocked_dispatcher()
        try:
            d.submit(np.ones((1, 4), np.float32))
            self.assertTrue(entered.wait(10))  # worker wedged in the batch
            with self.assertRaises(TimeoutError):
                elastic.drain_and_rewarm(
                    d, lambda: None, reason="resize", timeout=0.2
                )
        finally:
            gate.set()
            d.stop()

    def test_drain_not_running_sweeps(self):
        ep = Endpoint({4: jax.jit(lambda b: b)}, (2,), np.float32)
        d = Dispatcher(ep, max_queue=4)
        self.assertTrue(d.drain(reason="resize", timeout=1))

    def test_stop_reason_stays_shutdown(self):
        d, gate, entered = self._blocked_dispatcher()
        gate.set()
        d.call(np.ones((1, 4), np.float32), timeout=10)
        d.stop()
        with self.assertRaises(RuntimeError):
            d.submit(np.ones((1, 4), np.float32))


# ------------------------------------------------------------------ #
# chaos determinism                                                  #
# ------------------------------------------------------------------ #
class TestChaosMonkey(TestCase):
    def test_same_seed_same_schedule(self):
        def build():
            m = (
                chaos.ChaosMonkey(seed=42)
                .kill_slice(step=5)
                .poison_collective(step=9)
                .truncate_checkpoint(step=12)
            )
            m.watcher(topology="2x4" if P == 8 else None)  # resolves the slice draw
            return m

        a, b = build(), build()
        self.assertEqual(a.schedule(), b.schedule())
        self.assertEqual(a.log, b.log)

    def test_poison_recovery_bit_identical(self):
        with env_pin(staging.SLAB_ENV, "1"), env_pin(ck.RESILIENCE_ENV, "auto"):
            host = _host()
            km_ref = ht.cluster.KMeans(n_clusters=4, init="random", random_state=11)
            km_ref.fit(host)
            ref = _bits(km_ref.cluster_centers_)
            with tempfile.TemporaryDirectory() as d:
                cfg = ck.CheckpointConfig(directory=d, tag="po", every=2)
                monkey = chaos.ChaosMonkey(seed=5).poison_collective(step=3)
                km = ht.cluster.KMeans(n_clusters=4, init="random", random_state=11)
                elastic.elastic_fit(km, host, ckpt=cfg, chaos=monkey)
                np.testing.assert_array_equal(ref, _bits(km.cluster_centers_))
                self.assertEqual(
                    [e["kind"] for e in monkey.log], ["poison-collective"]
                )

    def test_truncation_mutilates_largest_entry(self):
        with tempfile.TemporaryDirectory() as d:
            path = ck.save(
                {"big": np.zeros(4096, np.float32), "small": np.zeros(2, np.float32)},
                tag="tr", step=7, directory=d,
            )
            monkey = chaos.ChaosMonkey(seed=1).truncate_checkpoint(step=7)
            monkey.after_checkpoint(path, 7)
            self.assertEqual(monkey.log[0]["entry"], "big.bin")
            with self.assertRaises(ck.CheckpointCorrupt):
                ck.load(path)


# ------------------------------------------------------------------ #
# SL406 — the swallowed-worker-exception rule                        #
# ------------------------------------------------------------------ #
class TestSL406(TestCase):
    def test_fixture_trips_and_twins_pass(self):
        found = effectcheck.lint_source(fx.SWALLOWED_WORKER_EXC_SRC, "heat_tpu/x.py")
        self.assertEqual({f.rule for f in found}, {"SL406"})
        self.assertEqual(len(found), 2)
        self.assertTrue(all(f.severity == "error" for f in found))
        blob = " ".join(f.message for f in found)
        self.assertIn("SwallowingWorker", blob)
        # log-and-continue is the FLAGSHIP swallow: passing the caught
        # object to a logger is formatting, not delivery
        self.assertIn("LoggingSwallowWorker", blob)

    def test_suppression_pragma(self):
        patched = fx.SWALLOWED_WORKER_EXC_SRC.replace(
            "            except Exception:\n"
            "                continue",
            "            except Exception:  # shardlint: ignore[SL406] -- test\n"
            "                continue",
        ).replace(
            "            except Exception as e:",
            "            except Exception as e:  # shardlint: ignore[SL406] -- test",
        )
        self.assertNotEqual(patched, fx.SWALLOWED_WORKER_EXC_SRC)
        self.assertEqual(effectcheck.lint_source(patched, "heat_tpu/x.py"), [])

    def test_shipped_workers_clean(self):
        for rel in (
            "heat_tpu/serving/dispatcher.py",
            "heat_tpu/utils/data/partial_dataset.py",
            "heat_tpu/resilience/checkpoint.py",
        ):
            with open(os.path.join(ROOT, rel), encoding="utf-8") as f:
                src = f.read()
            found = [f for f in effectcheck.lint_source(src, rel) if f.rule == "SL406"]
            self.assertEqual(found, [], (rel, [repr(f) for f in found]))

    def test_mutation_swallowing_dispatch_handler_trips(self):
        """Seeded-bug proof: neuter the dispatcher's batch-failure
        handler (the drain path's contract — every owned future fails
        typed) and SL406 must trip at error."""
        with open(os.path.join(ROOT, "heat_tpu/serving/dispatcher.py"), encoding="utf-8") as f:
            src = f.read()
        anchor = (
            "        except Exception as e:  # program build/placement failure: fail the batch, not the loop\n"
            "            for r in reqs:\n"
            "                if not r.future.done():\n"
            "                    r.future.set_exception(e)\n"
            "            _tracing.end_span(batch_sp, status=\"error\")\n"
            "            return None\n"
        )
        self.assertIn(anchor, src)
        mutated = src.replace(
            anchor,
            "        except Exception:\n            return None\n",
        )
        found = [
            f
            for f in effectcheck.lint_source(mutated, "heat_tpu/serving/dispatcher.py")
            if f.rule == "SL406"
        ]
        self.assertTrue(found, "neutered handler not caught")
        self.assertTrue(all(f.severity == "error" for f in found))

    def test_rule_catalogued(self):
        self.assertIn("SL406", findings.RULES)


# ------------------------------------------------------------------ #
# DataParallelOptimizer checkpoint                                   #
# ------------------------------------------------------------------ #
class TestOptimizerCheckpoint(TestCase):
    def _toy(self, n=256, d=16, classes=4, seed=0):
        rng = np.random.default_rng(seed)
        w = rng.standard_normal((d, classes)).astype(np.float32)
        x = rng.standard_normal((n, d)).astype(np.float32)
        y = np.argmax(x @ w, axis=1).astype(np.int32)
        return ht.array(x, split=0), ht.array(y, split=0)

    def _mlp(self):
        import heat_tpu.nn as htnn

        return htnn.Sequential(htnn.Linear(16, 32), htnn.ReLU(), htnn.Linear(32, 4))

    def _fresh(self, wire_quant=None):
        import heat_tpu.nn as htnn
        import heat_tpu.optim as htoptim

        dp = htnn.DataParallel(self._mlp(), key=2)
        opt = htoptim.DataParallelOptimizer(
            htoptim.Adam(lr=0.01), dp, wire_quant=wire_quant
        )
        return dp, opt

    def test_resume_bit_identical(self):
        X, Y = self._toy()
        dp_ref, opt_ref = self._fresh()
        for _ in range(6):
            opt_ref.step(X, Y)
        with tempfile.TemporaryDirectory() as d:
            dp_a, opt_a = self._fresh()
            for i in range(3):
                opt_a.step(X, Y)
            ck.save(opt_a.checkpoint_state(), tag="dpo", step=3, directory=d)
            dp_b, opt_b = self._fresh()
            step, state, _ = ck.restore_latest(d, tag="dpo")
            opt_b.load_checkpoint_state(state)
            self.assertEqual(opt_b._iter, 3)
            for _ in range(step, 6):
                opt_b.step(X, Y)
            for a, b in zip(jax.tree.leaves(dp_ref.params), jax.tree.leaves(dp_b.params)):
                np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_ef_carry_round_trip_and_codec_guard(self):
        if P < 2:
            self.skipTest("quantized DP needs a distributed mesh")
        X, Y = self._toy()
        dp, opt = self._fresh(wire_quant="int8")
        for _ in range(2):
            opt.step(X, Y)
        with tempfile.TemporaryDirectory() as d:
            ck.save(opt.checkpoint_state(), tag="q", step=2, directory=d)
            _, state, _ = ck.restore_latest(d, tag="q")
            dp2, opt2 = self._fresh(wire_quant="int8")
            opt2.load_checkpoint_state(state)
            np.testing.assert_array_equal(
                np.asarray(jax.device_get(opt._ef_carry)),
                np.asarray(jax.device_get(opt2._ef_carry)),
            )
            # codec mismatch is refused — the carry is codec-specific —
            # and the refusal leaves the optimizer UNMUTATED (review
            # regression: validation precedes mutation)
            dp3, opt3 = self._fresh(wire_quant=None)
            before = [np.asarray(l) for l in jax.tree.leaves(dp3.params)]
            before_iter = opt3._iter
            with self.assertRaises(ValueError):
                opt3.load_checkpoint_state(state)
            self.assertEqual(opt3._iter, before_iter)
            for a, b in zip(before, jax.tree.leaves(dp3.params)):
                np.testing.assert_array_equal(a, np.asarray(b))

    def test_ef_carry_fold_preserves_total_residual(self):
        """World-resize restore folds carry rows as r -> r % p_new with
        the TOTAL outstanding residual (what error feedback re-injects)
        preserved exactly."""
        if P < 2:
            self.skipTest("needs a multi-device mesh")
        X, Y = self._toy()
        dp, opt = self._fresh(wire_quant="int8")
        for _ in range(2):
            opt.step(X, Y)
        carry = np.asarray(jax.device_get(opt._ef_carry))
        with tempfile.TemporaryDirectory() as d:
            ck.save(opt.checkpoint_state(), tag="fold", step=1, directory=d)
            sub = comm_mod.MeshCommunication(comm_mod.MPI_WORLD.devices[: P // 2 + 1])
            try:
                comm_mod.use_comm(sub)
                _, state, _ = ck.restore_latest(d, tag="fold")
                dp2, opt2 = self._fresh(wire_quant="int8")
                opt2.load_checkpoint_state(state)
                folded = np.asarray(jax.device_get(opt2._ef_carry))
                self.assertEqual(folded.shape[0], sub.size)
                # fold-then-sum reassociates the f32 additions vs the
                # direct 8-row sum: bit equality is not the contract
                # here (same-size restores ARE bit-pinned above), the
                # preserved TOTAL is
                np.testing.assert_allclose(
                    folded.sum(axis=0), carry.sum(axis=0), rtol=1e-5, atol=1e-7
                )
            finally:
                _restore_full_world()


if __name__ == "__main__":
    import unittest

    unittest.main()
