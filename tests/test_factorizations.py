"""Matmul-native dense factorization suite (ISSUE 19).

The contract, pinned four ways:

1. **Correctness** — polar/eigh/cholesky/lu/solve/svd against their
   defining identities and the numpy/jnp oracles, across splits,
   ragged orders (pad blocks engaged), and complex dtypes.
2. **Movement** — the collective census of each solver's compiled
   program equals its registered plan exactly: ppermute-ring chains
   only, no all-gather of any operand. (The census must trace the FULL
   factor tuple — tracing one factor lets XLA dead-code-eliminate the
   rings that only feed the others.)
3. **Bit-identity** — ``HEAT_TPU_REDIST_OVERLAP=0`` (sequential
   oracle) and ``=1`` (pipelined rings) produce byte-identical factors
   for every solver: the rings only place, select, or accumulate in
   one fixed order, so the knob can only change issue order.
4. **Plans** — ``golden_factorization_plans()`` is deterministic and
   its plan_ids stable, riding the same determinism leg as the
   redistribution plans (scripts/redist_plans.py).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import heat_tpu as ht

from heat_tpu.core.linalg import basics
from heat_tpu.core.linalg import factorizations as F
from heat_tpu.core.linalg.svd import FullMatricesNotSupported
from heat_tpu.redistribution import planner
from heat_tpu.redistribution.staging import HostArray

from test_suites.basic_test import TestCase, env_pin

P = len(jax.devices())

needs_mesh = pytest.mark.skipif(P < 2, reason="needs a real mesh")


def _overlap(mode):
    return env_pin(planner.OVERLAP_ENV, mode)


def _clear_programs():
    """The ring programs cache on (mesh, ..., pipelined); clearing on a
    mode flip forces a rebuild so the env gate is re-read."""
    F._polar_program.cache_clear()
    F._blocked_factor_program.cache_clear()
    F._blocked_solve_program.cache_clear()
    basics._cmatmul_program.cache_clear()


def _spd(n, dtype=np.float32, seed=0, complex_=False):
    rng = np.random.default_rng(seed)
    a = rng.standard_normal((n, n))
    if complex_:
        a = a + 1j * rng.standard_normal((n, n))
    h = a @ a.conj().T / n + np.eye(n) * 2
    return h.astype(dtype)


def _randn(m, n, dtype=np.float32, seed=0):
    rng = np.random.default_rng(seed)
    return rng.standard_normal((m, n)).astype(dtype)


def _wellcond(n, seed=0, diag=3.0):
    """General square matrix with condition number O(1): scaled noise
    (sigma_max ~ 2) around a shifted diagonal. An unscaled randn + c*eye
    draw can land an eigenvalue near zero (cond 1e5 at some seeds) and
    turn a residual check into a conditioning lottery."""
    rng = np.random.default_rng(seed)
    a = rng.standard_normal((n, n)) / np.sqrt(n)
    return (a + np.eye(n) * diag).astype(np.float32)


class TestPolar(TestCase):
    def test_polar_identities_split_sweep(self):
        an = _randn(192, 40, seed=1)
        for split in (None, 0, 1):
            u, h = ht.linalg.polar(ht.array(an, split=split))
            un, hn = np.asarray(u.larray), np.asarray(h.larray)
            np.testing.assert_allclose(un @ hn, an, atol=1e-4)
            np.testing.assert_allclose(un.T @ un, np.eye(40), atol=1e-4)
            # exactly symmetric by construction (symmetrized return)
            np.testing.assert_array_equal(hn, hn.T)
            self.assertEqual(u.split, 0 if split is not None else None)
            self.assertIsNone(h.split)

    def test_polar_ragged_and_tiny(self):
        # m not divisible by p (pad rows), and n < p (devices with
        # all-pad shards): the diag(A, I) pad seeding must keep both
        # exact
        for (m, n) in ((67, 13), (37, 5)):
            an = _randn(m, n, seed=2)
            u, h = ht.linalg.polar(ht.array(an, split=0))
            np.testing.assert_allclose(
                np.asarray(u.larray) @ np.asarray(h.larray), an, atol=1e-4
            )

    def test_polar_left(self):
        an = _randn(24, 96, seed=3)
        u, h = ht.linalg.polar(ht.array(an, split=1), side="left")
        un, hn = np.asarray(u.larray), np.asarray(h.larray)
        np.testing.assert_allclose(hn @ un, an, atol=1e-4)
        np.testing.assert_allclose(un @ un.T, np.eye(24), atol=1e-4)

    def test_polar_validation(self):
        a = ht.array(_randn(8, 16), split=None)
        with self.assertRaises(ValueError):
            ht.linalg.polar(a)  # m < n needs side="left"
        with self.assertRaises(ValueError):
            ht.linalg.polar(a, side="middle")


class TestCholeskyLuDet(TestCase):
    def test_cholesky_matches_oracle(self):
        hn = _spd(96, seed=4)
        for split in (None, 0, 1):
            l = ht.linalg.cholesky(ht.array(hn, split=split))
            ln = np.asarray(l.larray)
            np.testing.assert_allclose(ln @ ln.T, hn, atol=1e-4)
            np.testing.assert_allclose(ln, np.tril(ln), atol=0)

    def test_cholesky_ragged(self):
        hn = _spd(37, seed=5)  # pad blocks engaged on the 8-mesh
        l = ht.linalg.cholesky(ht.array(hn, split=0))
        ln = np.asarray(l.larray)
        np.testing.assert_allclose(ln @ ln.T, hn, atol=1e-4)

    def test_lu_reconstruction(self):
        an = _wellcond(96, seed=6)
        perm, l, u = ht.linalg.lu(ht.array(an, split=0))
        pn = np.asarray(perm.larray)
        ln, un = np.asarray(l.larray), np.asarray(u.larray)
        np.testing.assert_allclose(ln @ un, an[pn], atol=1e-4)
        np.testing.assert_allclose(ln, np.tril(ln), atol=0)
        np.testing.assert_allclose(np.diag(ln), np.ones(96), atol=0)
        np.testing.assert_allclose(un, np.triu(un), atol=0)
        self.assertEqual(sorted(pn.tolist()), list(range(96)))

    @needs_mesh
    def test_det_blocked_path_sign_and_value(self):
        n = max(520, F._EIGH_RESPLIT_MIN_N + 8)
        an = (
            _randn(n, n, seed=7) * 0.002 + np.eye(n, dtype=np.float32) * 1.001
        )
        an[0] *= -1  # odd permutation-free sign flip
        ref = np.linalg.det(an.astype(np.float64))
        for split in (0, 1):
            got = float(np.asarray(ht.linalg.det(ht.array(an, split=split)).larray))
            self.assertLess(abs(got - ref) / abs(ref), 1e-4)

    @needs_mesh
    def test_inv_blocked_path(self):
        n = 520
        an = (_randn(n, n, seed=8) * 0.1 + np.eye(n, dtype=np.float32) * 3)
        ref = np.linalg.inv(an)
        for split in (0, 1):
            iv = ht.linalg.inv(ht.array(an, split=split))
            self.assertEqual(iv.split, split)
            np.testing.assert_allclose(np.asarray(iv.larray), ref, atol=1e-4)


class TestSolve(TestCase):
    def test_solve_gen_and_pos(self):
        n = 96
        an = _wellcond(n, seed=9)
        hn = _spd(n, seed=10)
        bn = _randn(n, 7, seed=11)
        for split in (None, 0, 1):
            a = ht.array(an, split=split)
            b = ht.array(bn, split=0 if split is not None else None)
            x = ht.linalg.solve(a, b)
            np.testing.assert_allclose(an @ np.asarray(x.larray), bn, atol=1e-3)
            xp = ht.linalg.solve(ht.array(hn, split=split), b, assume_a="pos")
            np.testing.assert_allclose(hn @ np.asarray(xp.larray), bn, atol=1e-3)

    def test_solve_vector_rhs(self):
        n = 64
        hn = _spd(n, seed=12)
        bn = _randn(n, 1, seed=13)[:, 0]
        x = ht.linalg.solve(
            ht.array(hn, split=0), ht.array(bn, split=0), assume_a="pos"
        )
        self.assertEqual(x.ndim, 1)
        np.testing.assert_allclose(hn @ np.asarray(x.larray), bn, atol=1e-3)

    def test_solve_validation(self):
        a = ht.array(_spd(16), split=None)
        b = ht.array(_randn(12, 2), split=None)
        with self.assertRaises(ValueError):
            ht.linalg.solve(a, b)  # shape mismatch
        with self.assertRaises(ValueError):
            ht.linalg.solve(a, ht.array(_randn(16, 2), split=None), assume_a="sym")

    def test_solve_host_rhs_streams(self):
        """HostArray RHS: factor once, stream column windows through
        the staged double-buffer, HostArray result (PR 11 composition)."""
        n = 64
        hn = _spd(n, seed=14)
        bn = _randn(n, 96, seed=15)
        x = ht.linalg.solve(
            ht.array(hn, split=0), HostArray(bn), assume_a="pos"
        )
        self.assertIsInstance(x, HostArray)
        out = x.window(0, 0, n)
        np.testing.assert_allclose(hn @ out, bn, atol=1e-3)


class TestEigh(TestCase):
    def test_eigh_matches_oracle(self):
        hn = _spd(96, seed=16) * 3
        ref = np.linalg.eigvalsh(hn)
        for split in (None, 0):
            w, v = ht.linalg.eigh(ht.array(hn, split=split))
            wn, vn = np.asarray(w.larray), np.asarray(v.larray)
            np.testing.assert_allclose(np.sort(wn), ref, rtol=1e-3, atol=1e-4)
            np.testing.assert_allclose(
                vn @ np.diag(wn) @ vn.T, hn, atol=1e-3
            )
            np.testing.assert_allclose(vn.T @ vn, np.eye(96), atol=1e-4)

    def test_eigh_uplo_triangle_only(self):
        hn = _spd(48, seed=17)
        lower = np.tril(hn) + np.triu(_randn(48, 48, seed=18), 1)  # junk upper
        w, _ = ht.linalg.eigh(ht.array(lower, split=0), UPLO="L")
        np.testing.assert_allclose(
            np.sort(np.asarray(w.larray)), np.linalg.eigvalsh(hn),
            rtol=1e-3, atol=1e-4,
        )
        with self.assertRaises(ValueError):
            ht.linalg.eigh(ht.array(hn, split=0), UPLO="X")

    @needs_mesh
    def test_eigh_distributed_recursion(self):
        """Force the divide-and-conquer to RECURSE distributed (not
        fall back to the local eigh of the sub-blocks) by lowering the
        resplit threshold below the branch sizes."""
        hn = _spd(64, seed=19) * 2
        old = F._EIGH_RESPLIT_MIN_N
        F._EIGH_RESPLIT_MIN_N = 8
        try:
            w, v = ht.linalg.eigh(ht.array(hn, split=0))
        finally:
            F._EIGH_RESPLIT_MIN_N = old
        wn, vn = np.asarray(w.larray), np.asarray(v.larray)
        np.testing.assert_allclose(
            np.sort(wn), np.linalg.eigvalsh(hn), rtol=1e-3, atol=1e-4
        )
        np.testing.assert_allclose(vn @ np.diag(wn) @ vn.T, hn, atol=1e-3)


class TestFullSVD(TestCase):
    def test_matches_jnp_svd_one_device(self):
        """The documented-tolerance acceptance pin: a split-0 operand's
        reduced factors match jnp.linalg.svd on the 1-device (local)
        path and both distributed methods to rtol 1e-4."""
        an = _randn(128, 24, seed=20)
        ref_u, ref_s, ref_vh = np.linalg.svd(an, full_matrices=False)
        for kwargs in (
            {"split": None},
            {"split": 0, "method": "qr"},
            {"split": 0, "method": "polar"},
        ):
            split = kwargs.pop("split")
            u, s, vh = ht.linalg.svd(ht.array(an, split=split), **kwargs)
            un, sn, vhn = (
                np.asarray(u.larray), np.asarray(s.larray), np.asarray(vh.larray)
            )
            np.testing.assert_allclose(sn, ref_s, rtol=1e-4, atol=1e-4)
            np.testing.assert_allclose(
                un @ np.diag(sn) @ vhn, an, atol=1e-4
            )
            # factors match the oracle up to per-column phase
            np.testing.assert_allclose(
                np.abs(np.diag(ref_vh @ vhn.conj().T)), np.ones(24), atol=1e-3
            )

    def test_values_only_never_forms_uv(self):
        an = _randn(128, 24, seed=21)
        ref = np.linalg.svd(an, compute_uv=False)
        for method in ("qr", "polar"):
            s = ht.linalg.svd(
                ht.array(an, split=0), compute_uv=False, method=method
            )
            np.testing.assert_allclose(np.asarray(s.larray), ref, rtol=1e-3)
        # full_matrices is irrelevant (and must not raise) without U/V
        s = ht.linalg.svd(
            ht.array(an, split=0), full_matrices=True, compute_uv=False
        )
        np.testing.assert_allclose(np.asarray(s.larray), ref, rtol=1e-3)

    def test_full_matrices_typed_error(self):
        a = ht.array(_randn(32, 8, seed=22), split=0)
        with self.assertRaises(FullMatricesNotSupported) as ctx:
            ht.linalg.svd(a, full_matrices=True)
        msg = str(ctx.exception)
        self.assertIn("hsvd_rank", msg)
        self.assertIn("eigh", msg)
        self.assertTrue(issubclass(FullMatricesNotSupported, NotImplementedError))

    def test_wide_operand(self):
        an = _randn(24, 96, seed=23)
        u, s, vh = ht.linalg.svd(ht.array(an, split=1))
        np.testing.assert_allclose(
            np.asarray(u.larray) @ np.diag(np.asarray(s.larray))
            @ np.asarray(vh.larray),
            an, atol=1e-4,
        )

    def test_host_values_only_gram(self):
        an = _randn(512, 24, seed=24)
        s = ht.linalg.svd(HostArray(an), compute_uv=False)
        ref = np.linalg.svd(an, compute_uv=False)
        np.testing.assert_allclose(
            np.asarray(s.larray), ref, rtol=1e-3, atol=1e-4
        )

    @needs_mesh
    def test_polar_path_census_no_all_gather(self):
        """The acceptance pin: the polar-composition SVD's distributed
        census has ZERO all-gathers — the operand (and everything else)
        moves only on collective-permute rings."""
        a = ht.array(_randn(128, 24, seed=25), split=0)
        rep = ht.observability.collective_counts(
            lambda x: tuple(ht.linalg.svd(x, method="polar")), a
        )
        self.assertEqual(rep.counts["all-gather"], 0)
        self.assertEqual(rep.counts["all-reduce"], 0)
        self.assertEqual(rep.counts["all-to-all"], 0)
        self.assertGreater(rep.counts["collective-permute"], 0)


@needs_mesh
class TestCensusMatchesPlan(TestCase):
    """Collective census of each solver's compiled program == the
    registered plan, exactly. The census traces the FULL factor tuple:
    tracing a single factor lets XLA DCE the rings feeding the others
    (polar's H ring vanishes from a U-only trace)."""

    def _plan_counts(self, kind, gshape):
        return F._factorization_plan(
            kind, gshape, "float32", P, planner.budget_bytes()
        ).collective_counts()

    def test_polar_census(self):
        a = ht.array(_randn(256, 64, seed=26), split=0)
        rep = ht.observability.collective_counts(
            lambda x: tuple(ht.linalg.polar(x)), a
        )
        self.assertEqual(
            {k: v for k, v in rep.counts.items() if v},
            self._plan_counts("polar", (256, 64)),
        )

    def test_cholesky_census(self):
        a = ht.array(_spd(96, seed=27), split=0)
        rep = ht.observability.collective_counts(ht.linalg.cholesky, a)
        self.assertEqual(
            {k: v for k, v in rep.counts.items() if v},
            self._plan_counts("cholesky", (96, 96)),
        )

    def test_lu_census(self):
        a = ht.array(_wellcond(96, seed=28), split=0)
        rep = ht.observability.collective_counts(
            lambda x: tuple(ht.linalg.lu(x)), a
        )
        self.assertEqual(
            {k: v for k, v in rep.counts.items() if v},
            self._plan_counts("lu", (96, 96)),
        )

    def test_solve_census_is_factor_plus_substitution(self):
        n, nrhs = 96, 8
        hn = _spd(n, seed=29)
        b = ht.array(_randn(n, nrhs, seed=30), split=0)
        rep = ht.observability.collective_counts(
            lambda u, v: ht.linalg.solve(u, v, assume_a="pos"),
            ht.array(hn, split=0), b,
        )
        chol = self._plan_counts("cholesky", (n, n))
        sub = self._plan_counts("solve-chol", (n, nrhs))
        want = {k: chol.get(k, 0) + sub.get(k, 0) for k in set(chol) | set(sub)}
        self.assertEqual({k: v for k, v in rep.counts.items() if v}, want)


@needs_mesh
class TestBitIdentity(TestCase):
    """Sequential (OVERLAP=0) vs pipelined (OVERLAP=1) ring forms are
    byte-identical for every solver — the rings only place, select, or
    accumulate in ONE fixed order, so the knob can only change issue
    order, never an addition order."""

    def _both_modes(self, fn):
        out = []
        for mode in ("0", "1"):
            with _overlap(mode):
                _clear_programs()
                out.append([np.asarray(x) for x in fn()])
        _clear_programs()
        for a, b in zip(*out):
            np.testing.assert_array_equal(a, b)

    def test_polar_bit_identical(self):
        an = _randn(192, 40, seed=31)

        def run():
            u, h = ht.linalg.polar(ht.array(an, split=0))
            return [u.larray, h.larray]

        self._both_modes(run)

    def test_cholesky_lu_bit_identical(self):
        hn = _spd(96, seed=32)
        an = _wellcond(96, seed=33)

        def run():
            l = ht.linalg.cholesky(ht.array(hn, split=0))
            perm, ll, uu = ht.linalg.lu(ht.array(an, split=0))
            return [l.larray, perm.larray, ll.larray, uu.larray]

        self._both_modes(run)

    def test_solve_eigh_bit_identical(self):
        hn = _spd(64, seed=34) * 2
        bn = _randn(64, 5, seed=35)

        def run():
            x = ht.linalg.solve(
                ht.array(hn, split=0), ht.array(bn, split=0), assume_a="pos"
            )
            w, v = ht.linalg.eigh(ht.array(hn, split=0))
            return [x.larray, w.larray, v.larray]

        self._both_modes(run)


class TestGoldenPlans(TestCase):
    def test_plans_deterministic(self):
        first = F.golden_factorization_plans()
        second = F.golden_factorization_plans()
        self.assertEqual(len(first), 5)
        names = [n for n, _ in first]
        self.assertEqual(len(set(names)), 5)
        for (n1, s1), (n2, s2) in zip(first, second):
            self.assertEqual(n1, n2)
            self.assertEqual(s1.plan_id, s2.plan_id)
            self.assertEqual(s1.collective_counts(), s2.collective_counts())
            # every plan is ppermute-only movement
            self.assertEqual(
                set(s1.collective_counts()), {"collective-permute"}
            )


class TestSolveEndpoint(TestCase):
    def test_chol_endpoint_serves_batches(self):
        from heat_tpu.serving.dispatcher import Dispatcher

        n = 24
        hn = _spd(n, seed=36)
        l = ht.linalg.cholesky(ht.array(hn, split=None))
        ep = F.solve_endpoint(l, buckets=(4, 16), name="chol-solve")
        rng = np.random.default_rng(37)
        batch = rng.standard_normal((3, n)).astype(np.float32)
        with Dispatcher(ep, poll_s=0.001) as d:
            out = np.asarray(d.submit(batch).result(timeout=60))
        for i in range(3):
            np.testing.assert_allclose(hn @ out[i], batch[i], atol=1e-3)

    def test_lu_endpoint_serves_batches(self):
        from heat_tpu.serving.dispatcher import Dispatcher

        n = 24
        an = _wellcond(n, seed=38, diag=5.0)
        fac = ht.linalg.lu(ht.array(an, split=None))
        ep = F.solve_endpoint(fac, buckets=(4,), name="lu-solve")
        rng = np.random.default_rng(39)
        batch = rng.standard_normal((2, n)).astype(np.float32)
        with Dispatcher(ep, poll_s=0.001) as d:
            out = np.asarray(d.submit(batch).result(timeout=60))
        for i in range(2):
            np.testing.assert_allclose(an @ out[i], batch[i], atol=1e-3)


if __name__ == "__main__":
    import unittest

    unittest.main()
