"""Scale-safety tests: per-shard random generation and hyperslab HDF5 I/O.

The guarantees the reference engineers by hand (per-rank counter slices,
random.py:55-198; per-rank hyperslab reads, io.py:57) must hold natively:
draws are value-identical at any sharding, no device materializes the
global array, and HDF5 round-trips touch only per-device slabs."""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import heat_tpu as ht
from heat_tpu.core import _padding


class TestShardedRandom:
    def test_stream_independent_of_sharding(self):
        """Same (seed, counter) must produce the same global values split
        or replicated — the reference's rank-count independence."""
        ht.random.seed(42)
        split0 = ht.random.rand(101, 7, split=0)
        ht.random.seed(42)
        repl = ht.random.rand(101, 7, split=None)
        np.testing.assert_array_equal(split0.numpy(), repl.numpy())
        ht.random.seed(42)
        split1 = ht.random.rand(101, 7, split=1)
        np.testing.assert_array_equal(split1.numpy(), repl.numpy())

    def test_matches_raw_jax_stream(self):
        """The sharded draw equals the plain jax.random draw for the same
        derived key (partitionable Threefry value-stability)."""
        ht.random.seed(7)
        x = ht.random.rand(64, 8, split=0)
        # the framework folds BOTH 32-bit counter words into the key so the
        # stream only cycles after 2**64 elements (heat_tpu/core/random.py
        # _next_key); counter starts at 0, so both folds are of 0 here
        key = jax.random.fold_in(jax.random.PRNGKey(7), 0)
        key = jax.random.fold_in(key, 0)
        ref = jax.random.uniform(key, (64, 8), dtype=jnp.float32)
        np.testing.assert_array_equal(x.numpy(), np.asarray(ref))

    def test_each_device_holds_only_its_shard(self):
        p = ht.get_comm().size
        x = ht.random.randn(100 * p, 4, split=0)
        shard_shapes = {tuple(s.data.shape) for s in x._phys.addressable_shards}
        assert shard_shapes == {(100, 4)}

    def test_pad_region_zero(self):
        p = ht.get_comm().size
        x = ht.random.randn(13, 3, split=0)  # pads 13 up to a mesh multiple
        phys = np.asarray(jax.device_get(x._phys))
        assert phys.shape[0] == -(-13 // p) * p
        np.testing.assert_array_equal(phys[13:], 0.0)
        np.testing.assert_array_equal(x.numpy(), phys[:13])

    def test_randint_and_normal_sharded(self):
        ht.random.seed(3)
        r = ht.random.randint(0, 10, (40, 5), split=0)
        assert r.numpy().min() >= 0 and r.numpy().max() < 10
        n = ht.random.normal(2.0, 0.5, (4000,), split=0)
        assert abs(float(ht.mean(n)) - 2.0) < 0.1
        # pad region of a nonzero-mean draw must still be zero
        n2 = ht.random.normal(5.0, 0.1, (13,), split=0)
        phys = np.asarray(jax.device_get(n2._phys))
        np.testing.assert_array_equal(phys[13:], 0.0)

    def test_normal_with_array_moments(self):
        mean = ht.full((32,), 3.0, split=0)
        n = ht.random.normal(mean, 0.01, (32,), split=0)
        assert abs(float(ht.mean(n)) - 3.0) < 0.1

    def test_counter_no_wrap_at_2_31(self):
        """The stream must not repeat after 2**31 drawn elements."""
        ht.random.seed(1)
        a = ht.random.rand(8)
        ht.random.set_state(("Threefry", 1, 2**31, 0, 0.0))
        b = ht.random.rand(8)
        ht.random.set_state(("Threefry", 1, 2**33, 0, 0.0))
        c = ht.random.rand(8)
        assert not np.array_equal(a.numpy(), b.numpy())
        assert not np.array_equal(a.numpy(), c.numpy())
        assert not np.array_equal(b.numpy(), c.numpy())

    def test_counter_advances(self):
        ht.random.seed(0)
        a = ht.random.rand(16, split=0)
        b = ht.random.rand(16, split=0)
        assert not np.array_equal(a.numpy(), b.numpy())
        assert ht.random.get_state()[2] == 32


@pytest.mark.skipif(not ht.io.supports_hdf5(), reason="h5py not available")
class TestHDF5Hyperslab:
    def _round_trip(self, tmp_path, shape, split, dtype=ht.float32):
        path = os.path.join(str(tmp_path), "t.h5")
        ht.random.seed(11)
        x = ht.random.rand(*shape, split=split, dtype=dtype) if dtype != ht.int32 else None
        ht.save(x, path, "data")
        y = ht.load(path, "data", dtype=dtype, split=split)
        np.testing.assert_allclose(y.numpy(), x.numpy(), rtol=1e-6)
        assert y.split == split
        return path, x

    def test_round_trip_split0_uneven(self, tmp_path):
        self._round_trip(tmp_path, (101, 5), 0)

    def test_round_trip_split1(self, tmp_path):
        self._round_trip(tmp_path, (6, 37), 1)

    def test_round_trip_replicated(self, tmp_path):
        self._round_trip(tmp_path, (9, 4), None)

    def test_sharded_load_places_slabs(self, tmp_path):
        path = os.path.join(str(tmp_path), "t.h5")
        data = np.arange(160, dtype=np.float32).reshape(32, 5)
        import h5py

        with h5py.File(path, "w") as f:
            f.create_dataset("d", data=data)
        x = ht.load(path, "d", split=0)
        np.testing.assert_array_equal(x.numpy(), data)
        # every device holds exactly its block-row slab (zero-padded tail)
        block = -(-32 // ht.get_comm().size)
        for s in x._phys.addressable_shards:
            r0 = s.index[0].start or 0
            expect = np.zeros((block, 5), np.float32)
            valid = max(0, min(32 - r0, block))
            expect[:valid] = data[r0 : r0 + valid]
            np.testing.assert_array_equal(np.asarray(s.data), expect)

    def test_save_writes_per_shard_slabs(self, tmp_path):
        """The file contents must equal the logical array even though no
        global gather happened (write path is shard-wise)."""
        path = os.path.join(str(tmp_path), "t.h5")
        x = ht.arange(87, dtype=ht.float32, split=0).reshape((29, 3))
        ht.save(x, path, "data")
        import h5py

        with h5py.File(path, "r") as f:
            np.testing.assert_array_equal(f["data"][...], x.numpy())

    def test_load_fraction(self, tmp_path):
        path = os.path.join(str(tmp_path), "t.h5")
        import h5py

        data = np.random.default_rng(0).random((40, 3)).astype(np.float32)
        with h5py.File(path, "w") as f:
            f.create_dataset("d", data=data)
        x = ht.load(path, "d", split=0, load_fraction=0.5)
        assert x.shape == (20, 3)
        np.testing.assert_allclose(x.numpy(), data[:20], rtol=1e-6)

    def test_bfloat16_round_trip(self, tmp_path):
        path = os.path.join(str(tmp_path), "t.h5")
        x = ht.full((16, 4), 1.5, dtype=ht.bfloat16, split=0)
        ht.save(x, path, "data")
        y = ht.load(path, "data", dtype=ht.bfloat16, split=0)
        np.testing.assert_array_equal(y.numpy(), x.numpy())


# ---------------------------------------------------------------------- #
# per-device byte invariants (VERDICT r2 weak #5)                        #
# ---------------------------------------------------------------------- #
from heat_tpu.core.sanitation import assert_evenly_sharded as _assert_evenly_sharded


def _resident_bytes_per_device():
    per = {}
    for a in jax.live_arrays():
        for s in a.addressable_shards:
            per[s.device] = per.get(s.device, 0) + s.data.nbytes
    return per


class TestPerDeviceBytes:
    def test_factory_random_io_sort_reshape_stay_sharded(self, tmp_path):
        p = ht.get_comm().size
        n = 64 * p

        x = ht.arange(n * 4, dtype=ht.float32, split=0)
        _assert_evenly_sharded(x, "arange")
        _assert_evenly_sharded(ht.zeros((n, 8), split=0), "zeros")
        _assert_evenly_sharded(ht.random.randn(n, 8, split=0), "randn")

        r = ht.reshape(x, (n, 4), new_split=1)
        _assert_evenly_sharded(r, "reshape")

        sv, si = ht.sort(ht.random.randn(n, split=0))
        _assert_evenly_sharded(sv, "sort values")
        _assert_evenly_sharded(si, "sort indices")

        path = os.path.join(str(tmp_path), "sharded.h5")
        big = ht.random.randn(n, 16, split=0)
        ht.save(big, path, "d")
        back = ht.load(path, "d", split=0)
        _assert_evenly_sharded(back, "h5 load")

        # gather-free compaction results are evenly sharded too
        sel = big[big > 0]
        if sel.shape[0] >= p:
            _assert_evenly_sharded(sel, "bool-mask select")

    def test_creation_adds_only_one_shard_per_device(self):
        """Creating a split array must grow each device's RESIDENT bytes
        by ~gshape/p, not by the global size — pins 'no device
        materializes the global array' as a live-buffer invariant."""
        import gc

        comm = ht.get_comm()
        p = comm.size
        gc.collect()
        before = _resident_bytes_per_device()
        x = ht.random.randn(512 * p, 32, split=0)  # 64 KiB/device at p=8
        gc.collect()
        after = _resident_bytes_per_device()
        per_dev = x._phys.nbytes // p
        for dev in after:
            delta = after[dev] - before.get(dev, 0)
            assert delta <= per_dev * 1.5 + 4096, (
                f"device {dev} grew by {delta} bytes for a {per_dev}-byte shard"
            )
        del x
