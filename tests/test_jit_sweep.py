"""ht.jit trace-safety sweep: a representative slice of every public-op
category must produce IDENTICAL results (values, split, dtype) traced
as eager — this pins the fused-program contract across the surface
(shape-static ops trace; data-dependent-shape ops raise the documented
error, covered in test_jit.py)."""

import numpy as np
import pytest

import heat_tpu as ht


def _mk(split):
    rng = np.random.default_rng(0)
    return ht.array(rng.standard_normal((13, 6)).astype(np.float32) + 2.0, split=split)


UNARY = [
    ("exp", lambda x: ht.exp(x)),
    ("log", lambda x: ht.log(ht.abs(x) + 1.0)),
    ("sqrt-abs", lambda x: ht.sqrt(ht.abs(x))),
    ("sin-cos", lambda x: ht.sin(x) + ht.cos(x)),
    ("tanh", lambda x: ht.tanh(x)),
    ("clip", lambda x: ht.clip(x, -1.0, 1.0)),
    ("round", lambda x: ht.round(x)),
    ("floor-ceil", lambda x: ht.floor(x) + ht.ceil(x)),
    ("sign", lambda x: ht.sign(x)),
    ("square", lambda x: ht.square(x)),
]

BINARY = [
    ("add-mul", lambda x: x + x * 2.0),
    ("div-sub", lambda x: (x - 1.0) / (ht.abs(x) + 1.0)),
    ("pow", lambda x: ht.abs(x) ** 1.5),
    ("minimum-maximum", lambda x: ht.minimum(x, ht.maximum(-x, x * 0.5))),
    ("where", lambda x: ht.where(x > 2.0, x, -x)),
    ("relational", lambda x: (x > 2.0).astype(ht.float32) + (x <= 2.0).astype(ht.float32)),
    ("logical", lambda x: (ht.logical_and(x > 0, x < 4)).astype(ht.float32)),
]

REDUCTIONS = [
    ("sum-axis", lambda x: ht.sum(x, axis=0)),
    ("sum-all", lambda x: ht.sum(x)),
    ("mean-keepdims", lambda x: ht.mean(x, axis=1, keepdims=True)),
    ("std-var", lambda x: ht.std(x, axis=0) + ht.var(x, axis=0)),
    ("min-max", lambda x: ht.min(x, axis=0) + ht.max(x, axis=0)),
    ("argmax", lambda x: ht.argmax(x, axis=1)),
    ("prod", lambda x: ht.prod(ht.clip(x, 0.5, 1.5), axis=0)),
    ("median", lambda x: ht.median(x, axis=0)),
    ("percentile", lambda x: ht.percentile(x, 30.0, axis=0)),
    ("norm", lambda x: ht.linalg.norm(x)),
    ("cumsum", lambda x: ht.cumsum(x, axis=0)),
]

MANIPULATIONS = [
    ("reshape", lambda x: ht.reshape(x, (6, 13))),
    ("transpose", lambda x: ht.transpose(x)),
    ("flatten", lambda x: ht.flatten(x)),
    ("concat-self", lambda x: ht.concatenate([x, x], axis=0)),
    ("stack", lambda x: ht.stack([x, x], axis=0)),
    ("expand-squeeze", lambda x: ht.squeeze(ht.expand_dims(x, 0), 0)),
    ("flip", lambda x: ht.flip(x, 0)),
    ("roll", lambda x: ht.roll(x, 3, 0)),
    ("split-slice", lambda x: x[2:9, 1:4]),
    ("sort", lambda x: ht.sort(x, axis=0)[0]),
    ("topk", lambda x: ht.topk(x.flatten(), 5)[0]),
    ("resplit", lambda x: x.resplit(1) + 0.0),
    ("pad", lambda x: ht.pad(x, ((1, 1), (0, 0)))),
    ("diag-of-gram", lambda x: ht.diag(ht.matmul(ht.transpose(x), x))),
    ("tril", lambda x: ht.tril(ht.matmul(x, ht.transpose(x)))),
]

LINALG = [
    ("matmul", lambda x: ht.matmul(x, ht.transpose(x))),
    ("vecdot-col", lambda x: ht.matmul(ht.transpose(x), x)),
    ("qr-q", lambda x: ht.linalg.qr(x.resplit(0))[0]),
    ("dot-1d", lambda x: ht.dot(x[:, 0], x[:, 1])),
    ("outer", lambda x: ht.outer(x[:, 0], x[:, 2])),
]

ALL_CASES = (
    [("unary-" + n, f) for n, f in UNARY]
    + [("binary-" + n, f) for n, f in BINARY]
    + [("reduce-" + n, f) for n, f in REDUCTIONS]
    + [("manip-" + n, f) for n, f in MANIPULATIONS]
    + [("linalg-" + n, f) for n, f in LINALG]
)


@pytest.mark.parametrize("name,fn", ALL_CASES, ids=[n for n, _ in ALL_CASES])
def test_traced_matches_eager(name, fn):
    for split in (0, None):
        x = _mk(split)
        eager = fn(x)
        traced = ht.jit(fn)(x)
        assert traced.shape == eager.shape, f"{name} split={split}: shape"
        assert traced.split == eager.split, f"{name} split={split}: split"
        assert traced.dtype == eager.dtype, f"{name} split={split}: dtype"
        np.testing.assert_allclose(
            traced.numpy(), eager.numpy(), rtol=1e-5, atol=1e-5,
            err_msg=f"{name} split={split}",
        )
