"""Lane-packing relayout engine (``heat_tpu.kernels.relayout`` + the
``packed-pivot`` redistribution strategy).

Three contracts pinned here:

1. the pack/unpack primitives are pure permutation + zero-pad — the XLA
   formulation and the Pallas tiled-copy kernel (interpret mode on CPU)
   are BIT-IDENTICAL, and unpack inverts pack exactly;
2. the planner's lane-fill cost term picks ``packed-pivot`` exactly for
   narrow-minor-dim reshape pivots and keeps the direct pivot for
   lane-friendly ones, with the SAME collective census either way;
3. the executed packed programs reproduce the oracle bit-for-bit under
   every ``HEAT_TPU_RELAYOUT_KERNEL`` setting (kernel-on == kernel-off
   == direct), with the compiled HLO census equal to the plan's.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import heat_tpu as ht

from heat_tpu.kernels import relayout
from heat_tpu.redistribution import RedistSpec, executor, planner

from test_suites.basic_test import TestCase, env_pin

P = len(jax.devices())
BUDGET = planner.DEFAULT_BUDGET_MB << 20


# the shared env save/set/restore helper (test_suites.basic_test)
_env = env_pin


def _pack_oracle(x, rows, c_in, c_out, p):
    """Independent numpy formulation of the pack layout."""
    xb = np.zeros((rows, c_out), dtype=np.asarray(x).dtype)
    xb[:, :c_in] = np.asarray(x).reshape(rows, c_in)
    cpp = c_out // p
    return xb.reshape(rows, p, cpp).transpose(1, 0, 2).reshape(p, rows * cpp)


class TestPrimitives(TestCase):
    CASES = [
        # (rows, c_in, c_out, p)
        (12, 25, 32, 8),
        (8, 5, 8, 8),
        (40, 40, 40, 8),     # no widen: group only
        (16, 3, 4, 4),
        (7, 13, 15, 5),      # odd everything
        (1, 25, 32, 8),      # single row
    ]

    def test_xla_matches_numpy_oracle(self):
        for rows, c_in, c_out, p in self.CASES:
            x = jnp.arange(rows * c_in, dtype=jnp.float32) + 1.0
            got = relayout.pack_rows(x, rows, c_in, c_out, p, impl="xla")
            np.testing.assert_array_equal(
                np.asarray(got), _pack_oracle(x, rows, c_in, c_out, p)
            )

    def test_pallas_bit_identical_to_xla(self):
        for rows, c_in, c_out, p in self.CASES:
            for dt in (jnp.float32, jnp.int32):
                x = jnp.arange(rows * c_in, dtype=dt)
                a = relayout.pack_rows(x, rows, c_in, c_out, p, impl="xla")
                b = relayout.pack_rows(x, rows, c_in, c_out, p, impl="pallas")
                np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
                ua = relayout.unpack_rows(a, rows, c_out, c_in, p, impl="xla")
                ub = relayout.unpack_rows(a, rows, c_out, c_in, p, impl="pallas")
                np.testing.assert_array_equal(np.asarray(ua), np.asarray(ub))

    def test_unpack_inverts_pack(self):
        for rows, c_in, c_out, p in self.CASES:
            x = jnp.arange(rows * c_in, dtype=jnp.float32) * 0.5
            for impl in ("xla", "pallas"):
                packed = relayout.pack_rows(x, rows, c_in, c_out, p, impl=impl)
                back = relayout.unpack_rows(packed, rows, c_out, c_in, p, impl=impl)
                np.testing.assert_array_equal(np.asarray(back), np.asarray(x))

    def test_special_float_bits_round_trip(self):
        # a relayout must move BITS, never canonicalize values
        vals = np.array(
            [0.0, -0.0, np.inf, -np.inf, np.nan, -np.nan, 1e-45, -1e-45],
            dtype=np.float32,
        )
        x = jnp.asarray(np.resize(vals, 4 * 6))
        for impl in ("xla", "pallas"):
            packed = relayout.pack_rows(x, 4, 6, 8, 8, impl=impl)
            back = relayout.unpack_rows(packed, 4, 8, 6, 8, impl=impl)
            np.testing.assert_array_equal(
                np.asarray(back).view(np.uint32), np.asarray(x).view(np.uint32)
            )

    def test_invalid_shapes_rejected(self):
        x = jnp.zeros((12 * 25,), jnp.float32)
        with self.assertRaises(ValueError):
            relayout.pack_rows(x, 12, 25, 30, 8)  # p does not divide c_out
        with self.assertRaises(ValueError):
            relayout.pack_rows(x, 12, 25, 16, 8)  # c_out < c_in
        with self.assertRaises(ValueError):
            relayout.unpack_rows(jnp.zeros((8, 48), jnp.float32), 12, 32, 33, 8)  # widen on unpack

    def test_lane_fill(self):
        self.assertEqual(relayout.lane_fill(128), 1.0)
        self.assertEqual(relayout.lane_fill(512), 1.0)
        self.assertAlmostEqual(relayout.lane_fill(25), 25 / 128)
        self.assertAlmostEqual(relayout.lane_fill(4), 4 / 128)
        self.assertAlmostEqual(relayout.lane_fill(130), 130 / 256)
        self.assertEqual(relayout.lane_fill(0), 1.0)


class TestDispatch(TestCase):
    def test_escape_hatch_forces_xla(self):
        with _env("HEAT_TPU_RELAYOUT_KERNEL", "0"):
            self.assertEqual(relayout.kernel_mode(), "0")
            self.assertEqual(relayout.decide("pack", 8, 25, 32, 8, "float32"), "xla")

    def test_forced_mode_serves_pallas(self):
        with _env("HEAT_TPU_RELAYOUT_KERNEL", "1"):
            self.assertEqual(relayout.decide("pack", 8, 25, 32, 8, "float32"), "pallas")

    def test_auto_off_tpu_is_xla(self):
        with _env("HEAT_TPU_RELAYOUT_KERNEL", None):
            if jax.default_backend() != "tpu":
                self.assertEqual(relayout.decide("pack", 8, 25, 32, 8, "float32"), "xla")

    def test_forced_mode_unserviceable_falls_back(self):
        from heat_tpu.observability import telemetry

        telemetry.reset()
        telemetry.enable()
        try:
            with _env("HEAT_TPU_RELAYOUT_KERNEL", "1"):
                # c_out beyond the VMEM block budget: kernel refuses
                big = relayout._BLOCK_ELEMS * 2
                impl = relayout.decide("pack", 4, big // 2, big, 2, "float32")
                self.assertEqual(impl, "xla")
                snap = telemetry.snapshot()["counters"]
                self.assertGreaterEqual(snap.get("relayout.kernel.fallback", 0), 1)
        finally:
            telemetry.disable()
            telemetry.reset()

    def test_kernel_hit_telemetry(self):
        from heat_tpu.observability import telemetry

        telemetry.reset()
        telemetry.enable()
        try:
            x = jnp.arange(8 * 25, dtype=jnp.float32)
            relayout.pack_rows(x, 8, 25, 32, 8, impl="pallas")
            snap = telemetry.snapshot()["counters"]
            self.assertGreaterEqual(snap.get("relayout.kernel.hit", 0), 1)
        finally:
            telemetry.disable()
            telemetry.reset()


class TestPackedPlans(TestCase):
    NARROW = RedistSpec.normalize(
        (1000, 250000), "float32", 1, 1, 8, reshape_to=(10_000_000, 25)
    )
    WIDE = RedistSpec.normalize(
        (65536, 4096), "float32", 1, 1, 8, reshape_to=(131072, 2048)
    )

    def test_packed_sides(self):
        self.assertEqual(planner._packed_sides(self.NARROW), (False, True))
        self.assertEqual(planner._packed_sides(self.WIDE), (False, False))
        rev = RedistSpec.normalize(
            (10_000_000, 25), "float32", 1, 1, 8, reshape_to=(1000, 250000)
        )
        self.assertEqual(planner._packed_sides(rev), (True, False))

    def test_lane_fill_term_picks_packed_for_narrow_only(self):
        self.assertEqual(planner.plan(self.NARROW, BUDGET).strategy, "packed-pivot")
        self.assertEqual(planner.plan(self.WIDE, BUDGET).strategy, "split0-pivot")

    def test_packed_census_equals_direct_census(self):
        """Packing changes layouts, never movement: the packed plan's
        collective census equals the direct pivot's for the same spec."""
        packed = planner.plan(self.NARROW, BUDGET)
        direct = planner._pivot_schedule(self.NARROW, BUDGET)
        self.assertEqual(packed.collective_counts(), direct.collective_counts())

    def test_packed_cost_beats_direct_exactly_on_narrow(self):
        packed = planner._packed_pivot_schedule(self.NARROW, BUDGET)
        direct = planner._pivot_schedule(self.NARROW, BUDGET)
        self.assertLess(planner._cost(packed), planner._cost(direct))

    def test_pack_unpack_steps_carry_bytes(self):
        sched = planner.plan(self.NARROW, BUDGET)
        kinds = [s.kind for s in sched.steps]
        self.assertIn("pack", kinds)
        self.assertIn("unpack", kinds)
        for st in sched.steps:
            if st.kind in ("pack", "unpack"):
                self.assertGreater(st.bytes_copied, 0)
                self.assertGreater(st.peak_bytes, 0)
        # the one HEAVILY lane-amplified write is the LAST step (the dst
        # materialization); every other step streams (near-)full lanes
        self.assertEqual(sched.steps[-1].kind, "unpack")
        self.assertLess(sched.steps[-1].lane_fill, 0.5)
        amplified = [s for s in sched.steps if s.lane_fill < 0.5]
        self.assertEqual(len(amplified), 1)

    def test_tighter_budget_rechunks_packed(self):
        # the default plan already runs overlap-grain laps (ISSUE 6), so
        # the budget must tighten past the grain before it adds chunks
        base = planner.plan(self.NARROW, BUDGET)
        tight = planner.plan(self.NARROW, BUDGET // 8)
        self.assertLessEqual(
            max(s.peak_bytes for s in tight.steps if s.is_collective), BUDGET // 8
        )
        self.assertGreater(
            tight.collective_counts()["all-to-all"],
            base.collective_counts()["all-to-all"],
        )

    def test_packed_within_budget(self):
        sched = planner.plan(self.NARROW, BUDGET)
        self.assertTrue(sched.within_budget)


@pytest.mark.skipif(P < 2, reason="needs a real mesh")
class TestPackedExecutor(TestCase):
    """Numerics + census of the executed packed programs. Shapes sized
    so the packed sides engage on the test mesh (narrow cols over P)."""

    def _cases(self):
        """(in_shape, out_shape) pairs big enough that the lane-fill
        cost term beats the per-collective ALPHA — the planner routes
        them packed on the 8-device mesh (some degrade to the direct
        pivot on 2/4-device meshes; correctness must hold either way)."""
        return [
            ((4096, 24), (2048, 48)),      # packed both sides
            ((4096, 25), (10240, 10)),     # uneven cols: widen + narrow pads
            ((2048, 48), (4096, 24)),      # reverse
            ((8192, 6), (6144, 8)),        # very narrow both sides
            ((4096, 200), (102400, 8)),    # wide in, narrow out
        ]

    def test_packed_reshape_matches_oracle(self):
        for in_shape, out_shape in self._cases():
            if in_shape[0] % P or out_shape[0] % P:
                continue
            oracle = np.arange(int(np.prod(in_shape)), dtype=np.float32).reshape(in_shape)
            x = ht.array(oracle, split=1)
            got = ht.reshape(x, out_shape, new_split=1)
            self.assertEqual(got.split, 1)
            self.assert_array_equal(got, oracle.reshape(out_shape))

    def test_kernel_on_off_bit_identical(self):
        """HEAT_TPU_RELAYOUT_KERNEL=1 (Pallas tiled copy, interpret on
        CPU) and =0 (XLA formulation) must produce bit-identical
        physical arrays on every program-backed spec."""
        for in_shape, out_shape in self._cases():
            if in_shape[0] % P or out_shape[0] % P:
                continue
            oracle = np.arange(int(np.prod(in_shape)), dtype=np.float32).reshape(in_shape)
            x = ht.array(oracle, split=1)
            spec = RedistSpec.normalize(
                in_shape, "float32", 1, 1, P, reshape_to=out_shape
            )
            results = {}
            for mode in ("0", "1"):
                with _env("HEAT_TPU_RELAYOUT_KERNEL", mode):
                    results[mode] = np.asarray(
                        executor.execute(self.comm, x._phys, spec)
                    )
            np.testing.assert_array_equal(results["0"], results["1"])

    def test_packed_census_matches_compiled_hlo(self):
        """Executed census == plan census for a packed spec, end to end
        through the public reshape."""
        in_shape, out_shape = (4096, 24), (2048, 48)
        if in_shape[0] % P or out_shape[0] % P:
            pytest.skip("mesh does not divide the packed test shape")
        x = ht.zeros(in_shape, split=1)
        sched = ht.redistribution.explain(x, reshape=out_shape, new_split=1)
        self.assertEqual(sched.strategy, "packed-pivot")
        rep = ht.observability.collective_counts(
            lambda v: ht.reshape(v, out_shape, new_split=1), x
        )
        for op, n in sched.collective_counts().items():
            self.assertEqual(rep.counts[op], n, op)
        self.assertEqual(rep.total, sched.n_collectives)
        self.assertEqual(rep.counts["all-gather"], 0)

    def test_relayout_strategy_telemetry(self):
        from heat_tpu.observability import telemetry

        if (4096 % P) or (2048 % P):
            pytest.skip("mesh does not divide the packed test shape")
        telemetry.reset()
        telemetry.enable()
        try:
            x = ht.zeros((4096, 24), split=1)
            ht.reshape(x, (2048, 48), new_split=1)
            snap = telemetry.snapshot()["counters"]
            self.assertGreaterEqual(snap.get("redist.relayout.packed", 0), 1)
            w = ht.zeros((4096, 256 * P), split=1)
            ht.reshape(w, (2048, 512 * P), new_split=1)
            snap = telemetry.snapshot()["counters"]
            self.assertGreaterEqual(snap.get("redist.relayout.direct", 0), 1)
        finally:
            telemetry.disable()
            telemetry.reset()

    def test_packed_program_shardlint_info_downgrade(self):
        """PR-3 contract carried over unchanged: the packed program runs
        under jax.named_scope("redist_plan_<id>"), so shardlint reports
        its collectives at info severity with the plan id attached."""
        if (4096 % P) or (2048 % P):
            pytest.skip("mesh does not divide the packed test shape")
        x = ht.zeros((4096, 24), split=1)
        sched = ht.redistribution.explain(x, reshape=(2048, 48), new_split=1)
        self.assertEqual(sched.strategy, "packed-pivot")
        rep = ht.analysis.check(
            lambda v: ht.reshape(v, (2048, 48), new_split=1), x
        )
        sl101 = [f for f in rep.findings if f.rule == "SL101"]
        for f in sl101:
            self.assertEqual(f.severity, "info")
            self.assertIn(sched.plan_id, f.message)
        self.assertTrue(rep.ok)

    def test_planner_escape_hatch_still_exact(self):
        """HEAT_TPU_REDIST_PLANNER=0 (legacy monolithic path) agrees
        with the packed plan's result — the hatch's contract."""
        oracle = np.arange(4096 * 24, dtype=np.float32).reshape(4096, 24)
        x = ht.array(oracle, split=1)
        planned = ht.reshape(x, (2048, 48), new_split=1)
        with _env("HEAT_TPU_REDIST_PLANNER", "0"):
            legacy = ht.reshape(x, (2048, 48), new_split=1)
        self.assert_array_equal(planned, oracle.reshape(2048, 48))
        np.testing.assert_array_equal(
            np.asarray(planned._phys), np.asarray(legacy._phys)
        )


if __name__ == "__main__":
    import unittest

    unittest.main()
