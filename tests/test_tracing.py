"""Span tracing, flight recorder & attribution (ISSUE 15).

The contract pinned here, four ways:

1. **Census == plan structure** — with tracing on and the program cache
   cleared, one chunked-a2a, one ring, and one staged execution each
   record exactly the span census their Schedule describes (issue/
   consume pairs == collective laps; stage_in/compute windows == the
   staging annotation's ``n_windows``), and a dispatcher run records
   one ``serving.batch`` span per batch it reports in ``stats()``.
2. **Byte identity at every gate value** — ``HEAT_TPU_TRACE`` is
   registered ``affects_programs=False``: plan canonical serializations,
   plan_ids, the AOT gate fingerprint, and the envelope gate roster are
   identical under ``0``/``1``/unset (the golden-dump sha pin in
   test_effectcheck plus the ci.sh parity leg diff the full dumps).
3. **Zero overhead at ``=0``** — the hard-off escape hatch keeps every
   probe a single module-bool read: no span is recorded, the context
   manager yields ``None``, and ``telemetry.enable()`` does NOT drag
   tracing on (an explicit ``0`` beats ``auto``-follow).
4. **Thread safety** — concurrent recorders commit every span exactly
   once with unique ids and per-thread parentage, and the module passes
   the racecheck/gatecheck analyzer clean (SL402–SL406).

Satellites ride along: Chrome-trace export validity + structural
determinism, the flight recorder's bound/tail/always-on contract,
``events.dropped`` overwrite accounting + span correlation,
``timer_table`` p99, and the Prometheus text exposition.
"""

import json
import os
import threading

import numpy as np
import pytest

import jax

import heat_tpu as ht

from heat_tpu.core import gates
from heat_tpu.observability import events, telemetry, tracing
from heat_tpu.redistribution import RedistSpec, executor, planner, staging

from test_suites.basic_test import TestCase, env_pin

import importlib

attribution = importlib.import_module("heat_tpu.observability.attribution")

P = len(jax.devices())
ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class TracingCase(TestCase):
    """Every test runs with a clean span buffer and restores the
    ambient off state (the suite's telemetry convention)."""

    def setUp(self):
        tracing.enable()
        tracing.clear()

    def tearDown(self):
        tracing.disable()
        tracing.clear()


# --------------------------------------------------------------------- #
# 1. span primitives                                                    #
# --------------------------------------------------------------------- #
class TestSpanPrimitives(TracingCase):
    def test_span_nesting_and_attrs(self):
        with tracing.span("outer", a=1) as so:
            with tracing.span("inner", b=2) as si:
                self.assertEqual(si.parent, so.id)
                self.assertEqual(tracing.current_span_id(), si.id)
        rows = tracing.spans()
        self.assertEqual([r["name"] for r in rows], ["inner", "outer"])
        inner, outer = rows
        self.assertEqual(inner["attrs"], {"b": 2})
        self.assertEqual(outer["attrs"], {"a": 1})
        self.assertEqual(inner["parent"], outer["id"])
        self.assertIsNotNone(outer["dur_s"])
        self.assertIsNone(tracing.current_span_id())

    def test_ambient_context_inherited(self):
        with tracing.context(plan_id="p1", tier="ici"):
            with tracing.span("work", tier="dcn"):
                pass
        (row,) = tracing.spans()
        # ambient attrs merge under the span's own (span wins)
        self.assertEqual(row["attrs"], {"plan_id": "p1", "tier": "dcn"})

    def test_detached_span_stays_off_the_stack(self):
        sp = tracing.start_span("batch", detached=True)
        self.assertIsNone(tracing.current_span_id())
        with tracing.span("phase", parent_id=sp.id):
            pass
        tracing.end_span(sp, status="ok")
        rows = {r["name"]: r for r in tracing.spans()}
        self.assertEqual(rows["phase"]["parent"], sp.id)
        self.assertEqual(rows["batch"]["attrs"]["status"], "ok")

    def test_add_span_retroactive(self):
        import time

        t0 = time.perf_counter()
        t1 = t0 + 0.25
        tracing.add_span("lifecycle", t0, t1, rows=3)
        (row,) = tracing.spans()
        self.assertAlmostEqual(row["dur_s"], 0.25, places=6)
        self.assertEqual(row["attrs"]["rows"], 3)

    def test_ring_bound_and_dropped(self):
        cap = tracing.capacity()
        self.assertEqual(tracing.dropped(), 0)
        for i in range(cap + 7):
            tracing.add_span("s", 0.0, 1e-9, i=i)
        self.assertEqual(len(tracing.spans()), cap)
        self.assertEqual(tracing.dropped(), 7)
        tracing.clear()
        self.assertEqual(tracing.dropped(), 0)


# --------------------------------------------------------------------- #
# 2. census == plan structure (the acceptance pins)                     #
# --------------------------------------------------------------------- #
def _lap_census(sched):
    """issue/consume span counts recorded for one traced execution of
    ``sched``, keyed by span name (plan_id-filtered)."""
    counts = {}
    for r in tracing.spans():
        attrs = r["attrs"]
        if attrs.get("plan_id") == sched.plan_id and attrs.get("traced"):
            counts[r["name"]] = counts.get(r["name"], 0) + 1
    return counts


@pytest.mark.skipif(P < 2, reason="needs a real mesh")
class TestCensusMatchesPlan(TracingCase):
    def _execute_traced(self, spec, budget):
        sched = planner.plan(spec, budget)
        oracle = np.arange(spec.size, dtype=spec.dtype).reshape(spec.gshape)
        x = ht.array(oracle, split=spec.src_split)
        executor.clear_program_cache()  # fresh trace: lap probes re-fire
        tracing.clear()
        executor.execute(self.comm, x._phys, spec, sched)
        return sched

    def test_chunked_and_ring_census(self):
        """For every multi-lap plan the tiny-budget sweep produces, the
        issue/consume span pairs recorded at trace time equal the plan's
        own collective count — the census IS the plan structure. The
        sweep covers chunked-all-to-all and (at the 8-dev mesh) the
        ppermute ring."""
        spec = RedistSpec.normalize((64, 48), "float32", 0, 1, P)
        strategies = set()
        for budget in (384, 1024, 2048):
            sched = self._execute_traced(spec, budget)
            strategies.add(sched.strategy)
            laps = sum(sched.collective_counts().values())
            census = _lap_census(sched)
            self.assertEqual(census.get("redist.issue", 0), laps, sched.strategy)
            self.assertEqual(census.get("redist.consume", 0), laps, sched.strategy)
            # the execute wrapper span carries the plan id + strategy
            execs = [
                r for r in tracing.spans()
                if r["name"] == "redist.execute"
                and r["attrs"].get("plan_id") == sched.plan_id
            ]
            self.assertEqual(len(execs), 1)
            self.assertEqual(execs[0]["attrs"]["strategy"], sched.strategy)
        if P == 8:  # the sweep is 8-dev-shaped: both gated forms appear
            self.assertIn("ring", strategies)
            self.assertIn("chunked-all-to-all", strategies)

    def test_census_cached_program_records_once(self):
        """Lap spans fire at TRACE time: re-executing a cached program
        adds an execute span but no new lap spans — the census counts
        compiles, not runs."""
        spec = RedistSpec.normalize((64, 48), "float32", 0, 1, P)
        sched = self._execute_traced(spec, 1024)
        first = _lap_census(sched)
        self.assertGreater(first.get("redist.issue", 0), 0)
        oracle = np.arange(spec.size, dtype=np.float32).reshape(spec.gshape)
        x = ht.array(oracle, split=0)
        executor.execute(self.comm, x._phys, spec, sched)
        self.assertEqual(_lap_census(sched), first)

    def test_staged_window_census(self):
        """One staged stream records exactly one stage_in + one compute
        span per window, plan_id-tagged, and the attribution join sees
        real (non-traced) wall time on the pcie leg."""
        data = np.arange(4096 * 64, dtype=np.float32).reshape(4096, 64)
        host = staging.HostArray(data)
        slab = 256 << 10
        sched = staging.plan_staged_passes(
            host.shape, host.dtype, [{"tag": "sketch", "axis": 0}], slab=slab
        )
        wins = staging.window_extents(host.shape, host.dtype.itemsize, 0, slab)
        tracing.clear()
        seen = []
        staging.stream_windows(
            host, 0, wins, lambda k, arr, w: seen.append(int(k)),
            plan_id=sched.plan_id,
        )
        n = sched.staging["passes"][0]["n_windows"]
        self.assertEqual(len(wins), n)
        by_name = {}
        for r in tracing.spans():
            if r["attrs"].get("plan_id") == sched.plan_id:
                by_name[r["name"]] = by_name.get(r["name"], 0) + 1
        self.assertEqual(by_name.get("staging.stage_in", 0), n)
        self.assertEqual(by_name.get("staging.compute", 0), n)
        stage_in = [
            r for r in tracing.spans() if r["name"] == "staging.stage_in"
        ]
        self.assertTrue(all(r["attrs"]["tier"] == "pcie" for r in stage_in))
        self.assertTrue(all(not r["attrs"].get("traced") for r in stage_in))
        self.assertTrue(all(r["attrs"]["bytes"] > 0 for r in stage_in))

    def test_dispatcher_batch_census(self):
        """serving.batch spans == the dispatcher's own batch tally, with
        the full submit→queue→dispatch→fence→resolve lifecycle around
        them and one serving.request span per request."""
        from heat_tpu import serving as srv

        ep = srv.Endpoint(
            {8: jax.jit(lambda b: b * 2.0)}, (4,), np.float32, name="census"
        )
        with srv.Dispatcher(ep, max_queue=32, poll_s=0.001) as disp:
            futs = [disp.submit(np.ones((2, 4), np.float32)) for _ in range(6)]
            for f in futs:
                f.result(timeout=60)
            stats = disp.stats()
        by_name = {}
        for r in tracing.spans():
            by_name[r["name"]] = by_name.get(r["name"], 0) + 1
        self.assertEqual(by_name.get("serving.batch", 0), stats["batches"])
        self.assertEqual(by_name.get("serving.submit", 0), stats["requests"])
        self.assertEqual(by_name.get("serving.request", 0), stats["requests"])
        self.assertEqual(by_name.get("serving.queue", 0), stats["requests"])
        for phase in ("serving.dispatch", "serving.fence", "serving.resolve"):
            self.assertEqual(by_name.get(phase, 0), stats["batches"], phase)
        # phase spans parent to their batch span
        batches = {
            r["id"] for r in tracing.spans() if r["name"] == "serving.batch"
        }
        for r in tracing.spans():
            if r["name"] in ("serving.dispatch", "serving.fence", "serving.resolve"):
                self.assertIn(r["parent"], batches)


# --------------------------------------------------------------------- #
# 3. byte identity + zero overhead at =0 (the escape hatch)             #
# --------------------------------------------------------------------- #
class TestGateByteIdentity(TestCase):
    def test_gate_registered_not_program_affecting(self):
        spec = gates.GATES["HEAT_TPU_TRACE"]
        self.assertFalse(spec.affects_programs)
        self.assertNotIn(
            "HEAT_TPU_TRACE", gates.program_gate_roster().split(",")
        )

    def test_plans_and_aot_stamps_identical_both_ways(self):
        """plan canonical bytes, plan_id, the AOT gate fingerprint, and
        the envelope gate roster must not move at any gate value (the
        ci.sh parity leg diffs the full golden dumps on top)."""
        from heat_tpu.serving import aot_cache

        spec = RedistSpec.normalize((1000, 250000), "float32", 0, 1, 8)
        got = {}
        for mode in ("0", "1", None):
            with env_pin(tracing.TRACE_ENV, mode):
                sched = planner.plan(spec, 256 << 20, topology="flat")
                got[mode] = (
                    sched.plan_id,
                    sched.canonical_json(),
                    gates.aot_fingerprint(),
                    aot_cache._envelope_stamps()["gate_roster"],
                )
        self.assertEqual(got["0"], got["1"])
        self.assertEqual(got["0"], got[None])

    def test_zero_records_nothing_and_beats_telemetry_follow(self):
        was_tel = telemetry.enabled()
        tracing.clear()
        try:
            with env_pin(tracing.TRACE_ENV, "0"):
                tracing.disable()
                # auto-follow must NOT engage under an explicit 0
                telemetry.enable()
                self.assertFalse(tracing.enabled())
                self.assertIsNone(tracing.start_span("x"))
                tracing.end_span(None)  # no-op by contract
                with tracing.span("y") as sp:
                    self.assertIsNone(sp)
                tracing.add_span("z", 0.0, 1.0)
                self.assertEqual(tracing.spans(), [])
        finally:
            telemetry.disable() if not was_tel else telemetry.enable()
            tracing.disable()
            tracing.clear()

    def test_auto_follows_telemetry_switch(self):
        was_tel = telemetry.enabled()
        try:
            with env_pin(tracing.TRACE_ENV, None):
                tracing.disable()
                telemetry.enable()
                self.assertTrue(tracing.enabled())
                telemetry.disable()
                self.assertFalse(tracing.enabled())
        finally:
            telemetry.disable() if not was_tel else telemetry.enable()
            tracing.disable()
            tracing.clear()

    @pytest.mark.skipif(P < 2, reason="needs a real mesh")
    def test_execution_off_records_nothing(self):
        tracing.disable()
        tracing.clear()
        spec = RedistSpec.normalize((64, 48), "float32", 0, 1, P)
        sched = planner.plan(spec, 1024)
        oracle = np.arange(64 * 48, dtype=np.float32).reshape(64, 48)
        x = ht.array(oracle, split=0)
        executor.clear_program_cache()
        executor.execute(self.comm, x._phys, spec, sched)
        self.assertEqual(tracing.spans(), [])


# --------------------------------------------------------------------- #
# 4. thread safety + analyzer cleanliness                               #
# --------------------------------------------------------------------- #
class TestThreadedRecorders(TracingCase):
    def test_concurrent_recorders_commit_every_span_once(self):
        N, M = 8, 200  # well under capacity: nothing may drop
        errs = []

        def worker(t):
            try:
                for i in range(M):
                    with tracing.span(f"w{t}", i=i):
                        with tracing.span(f"w{t}.inner"):
                            pass
            except Exception as e:  # pragma: no cover
                errs.append(e)

        threads = [threading.Thread(target=worker, args=(t,)) for t in range(N)]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        self.assertEqual(errs, [])
        rows = tracing.spans()
        self.assertEqual(len(rows), N * M * 2)
        self.assertEqual(tracing.dropped(), 0)
        ids = [r["id"] for r in rows]
        self.assertEqual(len(ids), len(set(ids)))
        # per-thread parentage: every inner span's parent is a span of
        # the SAME logical worker (stacks are thread-local)
        by_id = {r["id"]: r for r in rows}
        for r in rows:
            if r["name"].endswith(".inner"):
                parent = by_id[r["parent"]]
                self.assertEqual(parent["name"] + ".inner", r["name"])
                self.assertEqual(parent["thread"], r["thread"])

    def test_tracing_module_is_analyzer_clean(self):
        """SL402–SL406 over the tracer and the attribution join: the
        lock/ring/TLS discipline documented in the module must hold up
        to the racecheck pass, not just the docstring."""
        from heat_tpu.analysis import effectcheck

        for rel in (
            "heat_tpu/observability/tracing.py",
            "heat_tpu/observability/attribution.py",
        ):
            with open(os.path.join(ROOT, rel), encoding="utf-8") as f:
                src = f.read()
            found = effectcheck.lint_source(src, rel)
            self.assertEqual([repr(f) for f in found], [], rel)


# --------------------------------------------------------------------- #
# 5. Chrome-trace export                                                #
# --------------------------------------------------------------------- #
class TestExportTrace(TracingCase):
    def _rows(self):
        with tracing.context(plan_id="pX"):
            with tracing.span("redist.execute", step="execute"):
                with tracing.span("staging.stage_in", tier="pcie", window=0):
                    pass
        with tracing.span("serving.batch", endpoint="e"):
            pass
        return tracing.spans()

    def test_export_valid_and_structurally_deterministic(self):
        rows = self._rows()
        import tempfile

        with tempfile.TemporaryDirectory() as d:
            p1, p2 = os.path.join(d, "a.json"), os.path.join(d, "b.json")
            n1 = ht.observability.export_trace(p1, span_rows=rows)
            n2 = ht.observability.export_trace(p2, span_rows=rows)
            with open(p1, "rb") as f:
                b1 = f.read()
            with open(p2, "rb") as f:
                b2 = f.read()
            self.assertEqual(b1, b2)  # same rows -> byte-identical docs
            doc = json.loads(b1)
        self.assertEqual(n1, n2)
        evs = doc["traceEvents"]
        self.assertEqual(len(evs), n1)
        phases = {e["ph"] for e in evs}
        self.assertEqual(phases, {"M", "X", "b", "e"})
        # every complete event is well-formed
        for e in evs:
            if e["ph"] == "X":
                self.assertIn("ts", e)
                self.assertIn("dur", e)
                self.assertGreaterEqual(e["dur"], 0)
                self.assertEqual(e["cat"], e["name"].split(".", 1)[0])
                self.assertIn("span_id", e["args"])
        # plan-correlated spans emit balanced async begin/end pairs
        # under one id per plan
        begins = [e for e in evs if e["ph"] == "b"]
        ends = [e for e in evs if e["ph"] == "e"]
        self.assertEqual(len(begins), 2)  # execute + stage_in carry pX
        self.assertEqual(len(ends), len(begins))
        self.assertEqual({e["id"] for e in begins}, {"pX"})
        self.assertTrue(all(e["cat"] == "plan" for e in begins + ends))
        # thread tracks are labeled
        metas = [e for e in evs if e["ph"] == "M"]
        self.assertTrue(all(e["name"] == "thread_name" for e in metas))
        self.assertEqual(doc["otherData"]["spans"], len(rows))

    def test_unfinished_spans_are_skipped(self):
        sp = tracing.start_span("never.closed", detached=True)
        self.assertIsNotNone(sp)
        rows = tracing.spans() + [
            {"id": 999, "parent": None, "name": "open", "thread": 1,
             "t0_s": 0.0, "dur_s": None, "attrs": {}}
        ]
        import tempfile

        with tempfile.TemporaryDirectory() as d:
            path = os.path.join(d, "t.json")
            ht.observability.export_trace(path, span_rows=rows)
            with open(path) as f:
                doc = json.load(f)
        self.assertEqual(
            [e for e in doc["traceEvents"] if e["ph"] == "X"], []
        )


# --------------------------------------------------------------------- #
# 6. flight recorder                                                    #
# --------------------------------------------------------------------- #
class TestFlightRecorder(TestCase):
    def setUp(self):
        tracing.flight_clear()

    def tearDown(self):
        tracing.flight_clear()

    def test_always_on_and_bounded(self):
        # independent of the trace gate: records land with tracing OFF
        tracing.disable()
        cap = tracing.flight_capacity()
        for i in range(cap + 10):
            tracing.flight_record("test.kind", "w", i)
        tail = tracing.flight_tail(cap + 100)
        self.assertEqual(len(tail), cap)
        self.assertEqual(tail[-1]["value"], cap + 9)
        # oldest-first, monotonic seq, fixed fields
        seqs = [r["seq"] for r in tail]
        self.assertEqual(seqs, sorted(seqs))
        self.assertEqual(
            set(tail[0]), {"seq", "t_s", "thread", "kind", "what", "value"}
        )
        self.assertEqual(len(tracing.flight_tail(8)), 8)

    def test_world_changed_error_carries_tail(self):
        from heat_tpu.resilience import elastic

        tracing.flight_record("test.before", "breadcrumb", 42)
        err = elastic.WorldChangedError("test-reason", old_size=8, new_size=4)
        kinds = [r["kind"] for r in err.flight_tail]
        self.assertIn("test.before", kinds)
        self.assertIn("world.changed", kinds)  # the error records itself
        self.assertEqual(err.flight_tail[-1]["what"], "test-reason")

    def test_dispatcher_shed_carries_tail(self):
        from heat_tpu import serving as srv
        from heat_tpu.serving.admission import ServingOverloaded

        from concurrent.futures import Future

        ep = srv.Endpoint(
            {8: jax.jit(lambda b: b)}, (4,), np.float32, name="shedtail"
        )
        disp = srv.Dispatcher(ep, max_queue=8, poll_s=0.001)
        tracing.flight_record("test.breadcrumb", "before-shed", 7)
        # a queued request swept by the shed path (never started: the
        # queue is drained directly, the worker is not involved)
        req = type("R", (), {"future": Future(), "rows": 1})()
        disp._q.put_nowait(req)
        shed = disp._fail_queued("failover")
        self.assertEqual(shed, 1)
        exc = req.future.exception()
        self.assertIsInstance(exc, ServingOverloaded)
        # the typed error carries the tail, breadcrumb included
        kinds = [r["kind"] for r in exc.flight_tail]
        self.assertIn("serving.shed", kinds)
        self.assertIn("test.breadcrumb", kinds)


# --------------------------------------------------------------------- #
# 7. events: overwrite accounting + span correlation                    #
# --------------------------------------------------------------------- #
class TestEventsRingAccounting(TestCase):
    def setUp(self):
        events.clear()

    def tearDown(self):
        events.clear()
        tracing.disable()
        tracing.clear()

    def test_overwrites_counted_and_surfaced(self):
        cap = events.capacity()
        self.assertEqual(events.dropped(), 0)
        for i in range(cap + 12):
            events.emit("test.flood", i=i)
        self.assertEqual(events.dropped(), 12)
        self.assertEqual(len(events.snapshot()), cap)
        meta = events.meta()
        self.assertEqual(meta, {"capacity": cap, "buffered": cap, "dropped": 12})
        # the ring health rides every telemetry snapshot
        self.assertEqual(telemetry.snapshot()["events"], meta)
        events.clear()
        self.assertEqual(events.dropped(), 0)

    def test_events_correlate_to_active_span(self):
        tracing.enable()
        tracing.clear()
        with tracing.span("correlated") as sp:
            events.emit("test.inside")
        events.emit("test.outside")
        inside, outside = events.snapshot()[-2:]
        self.assertEqual(inside["span"], sp.id)
        self.assertNotIn("span", outside)


# --------------------------------------------------------------------- #
# 8. p99 + Prometheus exposition                                        #
# --------------------------------------------------------------------- #
class TestTelemetryExposition(TestCase):
    def setUp(self):
        telemetry.reset()
        telemetry.enable()

    def tearDown(self):
        telemetry.disable()
        telemetry.reset()
        tracing.disable()
        tracing.clear()

    def test_timer_table_p99(self):
        for v in range(1, 101):
            telemetry.observe("test.lat", v / 1000.0)
        table = telemetry.report()["timers"]["test.lat"]
        self.assertEqual(table["calls"], 100)
        self.assertIn("p99_s", table)
        self.assertGreaterEqual(table["p99_s"], table["p95_s"])
        self.assertGreaterEqual(table["p95_s"], table["p50_s"])
        self.assertAlmostEqual(table["p99_s"], 0.099, places=3)

    def test_dispatcher_stats_p99(self):
        from heat_tpu import serving as srv

        ep = srv.Endpoint(
            {8: jax.jit(lambda b: b)}, (4,), np.float32, name="p99"
        )
        with srv.Dispatcher(ep, max_queue=8, poll_s=0.001) as disp:
            disp.call(np.ones((2, 4), np.float32), timeout=60)
            stats = disp.stats()
        for k in ("p50_s", "p95_s", "p99_s"):
            self.assertIn(k, stats)
            self.assertGreater(stats[k], 0.0)

    def test_prometheus_text_format(self):
        telemetry.inc("test.prom.count", 3)
        for v in (0.01, 0.02, 0.03):
            telemetry.observe("test.prom.lat", v)
        text = ht.observability.prometheus_text()
        lines = text.splitlines()
        self.assertIn("# TYPE heat_tpu_test_prom_count_total counter", lines)
        self.assertIn("heat_tpu_test_prom_count_total 3", lines)
        self.assertIn("# TYPE heat_tpu_test_prom_lat_seconds summary", lines)
        for q in ("0.5", "0.95", "0.99"):
            self.assertTrue(
                any(
                    l.startswith(f'heat_tpu_test_prom_lat_seconds{{quantile="{q}"}} ')
                    for l in lines
                ),
                q,
            )
        self.assertTrue(any(l.startswith("heat_tpu_test_prom_lat_seconds_sum ") for l in lines))
        self.assertIn("heat_tpu_test_prom_lat_seconds_count 3", lines)
        self.assertIn("# TYPE heat_tpu_events_dropped_total counter", lines)
        # exposition-format shape: every non-comment line is
        # "name{labels} value" with a parseable float value
        for l in lines:
            if not l or l.startswith("#"):
                continue
            name_part, _, value = l.rpartition(" ")
            self.assertTrue(name_part)
            float(value)  # must parse

    def test_prometheus_live_dispatcher_gauges(self):
        from heat_tpu import serving as srv

        ep = srv.Endpoint(
            {8: jax.jit(lambda b: b)}, (4,), np.float32, name="promgauge"
        )
        with srv.Dispatcher(ep, max_queue=8, poll_s=0.001, name="promgauge") as disp:
            disp.call(np.ones((1, 4), np.float32), timeout=60)
            text = ht.observability.prometheus_text()
            self.assertIn(
                'heat_tpu_serving_requests{dispatcher="promgauge"} 1', text
            )
            self.assertIn(
                'heat_tpu_serving_latency_seconds{dispatcher="promgauge",quantile="0.99"}',
                text,
            )
        # stopped dispatchers drop off the exposition
        text = ht.observability.prometheus_text()
        self.assertNotIn('dispatcher="promgauge"', text)


# --------------------------------------------------------------------- #
# 9. attribution: the model-vs-measured join                            #
# --------------------------------------------------------------------- #
class TestAttribution(TracingCase):
    def _synthetic_rows(self, sched, stage_s=0.002):
        """Hand-built span rows shaped like one traced+fenced run."""
        rows = []
        sid = iter(range(1, 100))
        for k in range(3):
            rows.append({
                "id": next(sid), "parent": None, "name": "redist.issue",
                "thread": 1, "t0_s": 0.0, "dur_s": 0.0001,
                "attrs": {"plan_id": sched.plan_id, "traced": True,
                          "step": "all_to_all", "tier": "ici", "lap": k},
            })
        rows.append({
            "id": next(sid), "parent": None, "name": "staging.stage_in",
            "thread": 1, "t0_s": 0.0, "dur_s": stage_s,
            "attrs": {"plan_id": sched.plan_id, "step": "stage_in",
                      "tier": "pcie", "window": 0, "bytes": 1 << 20},
        })
        rows.append({
            "id": next(sid), "parent": None, "name": "bench.execute",
            "thread": 1, "t0_s": 0.0, "dur_s": 0.5,
            "attrs": {"plan_id": sched.plan_id, "step": "execute",
                      "fenced": True},
        })
        # another plan's span must not leak into the join
        rows.append({
            "id": next(sid), "parent": None, "name": "redist.issue",
            "thread": 1, "t0_s": 0.0, "dur_s": 0.1,
            "attrs": {"plan_id": "other", "traced": True},
        })
        return rows

    def test_join_reports_census_and_model_error(self):
        spec = RedistSpec.normalize((1000, 250000), "float32", 0, 1, 8)
        sched = planner.plan(spec, 256 << 20, topology="flat")
        rep = ht.observability.attribution(
            sched, span_rows=self._synthetic_rows(sched)
        )
        self.assertEqual(rep["plan_id"], sched.plan_id)
        self.assertEqual(rep["census"], {"redist.issue:ici": 3})
        legs = {(l["step"], l["tier"]): l for l in rep["legs"]}
        execute = legs[("execute", None)]
        self.assertEqual(execute["measured_s"], 0.5)
        self.assertEqual(execute["model_s"], rep["model"]["wall_s"])
        self.assertAlmostEqual(
            execute["model_error"],
            round(0.5 / rep["model"]["wall_s"] - 1.0, 4), places=4,
        )
        stage = legs[("stage_in", "pcie")]
        self.assertEqual(stage["calls"], 1)
        # no pcie leg in a flat in-HBM plan's model: measured-only —
        # attribution never invents a bound it cannot defend
        self.assertNotIn("model_error", stage)
        # the modeled wall reflects the overlap critical path
        self.assertLess(rep["model"]["wall_s"], rep["model"]["total_s"])

    def test_lookup_by_plan_id_and_unknown_raises(self):
        spec = RedistSpec.normalize((64, 48), "float32", 0, 1, 8)
        sched = planner.plan(spec, 256 << 20)
        attribution.register_plan(sched)
        rep = ht.observability.attribution(sched.plan_id, span_rows=[])
        self.assertEqual(rep["plan_id"], sched.plan_id)
        with self.assertRaises(KeyError):
            ht.observability.attribution("no-such-plan", span_rows=[])

    def test_staged_plan_uses_critical_path_model(self):
        sched = staging.golden_staged_plans()[0][1]
        rep = ht.observability.attribution(sched, span_rows=[])
        self.assertEqual(
            rep["model"]["wall_s"],
            round(float(sched.staging["model"]["critical_path_s"]), 9),
        )
        self.assertIn("staging", rep["model"])

    def test_serving_breakdown_percentiles(self):
        rows = [
            {"id": i, "parent": None, "name": "serving.request", "thread": 1,
             "t0_s": 0.0, "dur_s": i / 1000.0, "attrs": {}}
            for i in range(1, 21)
        ]
        rows.append({"id": 99, "parent": None, "name": "redist.execute",
                     "thread": 1, "t0_s": 0.0, "dur_s": 1.0, "attrs": {}})
        out = attribution.serving_breakdown(span_rows=rows)
        self.assertEqual(list(out), ["serving.request"])
        ent = out["serving.request"]
        self.assertEqual(ent["calls"], 20)
        self.assertAlmostEqual(ent["total_s"], sum(r / 1000 for r in range(1, 21)))
        self.assertGreaterEqual(ent["p99_s"], ent["p95_s"])
        self.assertGreaterEqual(ent["p95_s"], ent["p50_s"])


if __name__ == "__main__":
    import unittest

    unittest.main()
