"""heat_tpu.graph — PageRank + spectral embedding on the sparse engine
(ISSUE 18 workloads; the reference's graph package stops at the
Laplacian, these EXCEED parity).

Pins:

1. ``pagerank`` converges to the dense NumPy power-iteration oracle on
   random digraphs, handles dangling nodes, respects tol/max_iter, and
   accepts every adjacency form (DBCSR / DCSR / DNDarray / scipy);
2. ``pagerank_stream`` — the HostArray edge stream riding the PR 11
   staging windows — agrees with the in-HBM fixpoint on the same graph
   (weighted multiplicity included);
3. ``spectral_embedding`` feeds the DBCSR operator to Lanczos: the
   Fiedler coordinate separates a planted two-clique graph, the Ritz
   values approximate the Laplacian's bottom spectrum, and the
   embedding distributes like the operand.
"""

import numpy as np
import pytest
import scipy.sparse as sp

import jax

import heat_tpu as ht
from heat_tpu.graph import PageRankResult, pagerank, pagerank_stream, spectral_embedding
from heat_tpu.redistribution import staging

P = len(jax.devices())


def _random_digraph(n=60, avg_deg=5, seed=0):
    rng = np.random.default_rng(seed)
    e = n * avg_deg
    src = rng.integers(0, n, e)
    dst = rng.integers(0, n, e)
    keep = src != dst
    src, dst = src[keep], dst[keep]
    A = sp.csr_matrix(
        (np.ones(src.size, np.float32), (src, dst)), shape=(n, n)
    )
    A.sum_duplicates()
    return A, np.stack([src, dst], axis=1).astype(np.int32)


def _oracle_pagerank(A, alpha=0.85, tol=1e-10, max_iter=500):
    """Dense NumPy power iteration with uniform dangling teleport."""
    n = A.shape[0]
    A = A.toarray().astype(np.float64)
    outdeg = A.sum(axis=1)
    dangling = outdeg == 0
    M = np.divide(
        A, outdeg[:, None], out=np.zeros_like(A), where=~dangling[:, None]
    ).T
    r = np.full(n, 1.0 / n)
    for _ in range(max_iter):
        r_new = alpha * (M @ r + r[dangling].sum() / n) + (1 - alpha) / n
        if np.abs(r_new - r).sum() < tol:
            r = r_new
            break
        r = r_new
    return (r / r.sum()).astype(np.float32)


class TestPageRank:
    def test_matches_dense_oracle(self):
        A, _ = _random_digraph(seed=1)
        res = pagerank(A, tol=1e-10)
        assert isinstance(res, PageRankResult)
        assert res.converged
        np.testing.assert_allclose(
            res.ranks.numpy(), _oracle_pagerank(A), atol=1e-6
        )
        np.testing.assert_allclose(float(ht.sum(res.ranks).numpy()), 1.0, rtol=1e-6)

    def test_dangling_nodes(self):
        """Sinks teleport their mass uniformly — ranks stay a
        distribution and match the oracle."""
        n = 40
        A, _ = _random_digraph(n=n, seed=2)
        A = A.tolil()
        A[n - 3 :, :] = 0  # three dangling sinks
        A = A.tocsr()
        A.eliminate_zeros()
        res = pagerank(A, tol=1e-10)
        assert res.converged
        np.testing.assert_allclose(
            res.ranks.numpy(), _oracle_pagerank(A), atol=1e-6
        )

    def test_adjacency_forms_agree(self):
        A, _ = _random_digraph(seed=3)
        r_scipy = pagerank(A, tol=1e-10).ranks.numpy()
        r_dbcsr = pagerank(ht.sparse.sparse_dbcsr_matrix(A, split=0), tol=1e-10).ranks.numpy()
        r_dcsr = pagerank(ht.sparse.sparse_csr_matrix(A, split=0), tol=1e-10).ranks.numpy()
        r_dense = pagerank(ht.array(A.toarray(), split=0), tol=1e-10).ranks.numpy()
        np.testing.assert_allclose(r_dbcsr, r_scipy, atol=1e-7)
        np.testing.assert_allclose(r_dcsr, r_scipy, atol=1e-7)
        np.testing.assert_allclose(r_dense, r_scipy, atol=1e-7)

    def test_ranks_distribute_with_split(self):
        A, _ = _random_digraph(n=16 * max(P, 1), seed=4)
        res = pagerank(A, split=0)
        assert res.ranks.split == 0
        res_r = pagerank(A, split=None)
        assert res_r.ranks.split is None
        np.testing.assert_allclose(res.ranks.numpy(), res_r.ranks.numpy(), atol=1e-7)

    def test_max_iter_and_tol(self):
        A, _ = _random_digraph(seed=5)
        res = pagerank(A, tol=1e-14, max_iter=2)
        assert res.iterations == 2 and not res.converged
        assert res.delta > 1e-14
        with pytest.raises(ValueError):
            pagerank(A, alpha=1.5)
        with pytest.raises(ValueError):
            pagerank(sp.csr_matrix((3, 4), dtype=np.float32))


class TestPageRankStream:
    def test_stream_matches_in_hbm(self):
        """The HostArray edge stream and the brick-engine fixpoint agree
        on the same graph — including duplicate edges (multiplicity)."""
        A, edges = _random_digraph(n=50, seed=6)  # edges carry duplicates
        dup_csr = sp.csr_matrix(
            (np.ones(edges.shape[0], np.float32), (edges[:, 0], edges[:, 1])),
            shape=A.shape,
        )
        dup_csr.sum_duplicates()
        ref = pagerank(dup_csr, tol=1e-10)
        res = pagerank_stream(edges, n=50, tol=1e-10)
        assert res.converged
        np.testing.assert_allclose(res.ranks.numpy(), ref.ranks.numpy(), atol=1e-6)

    def test_hostarray_and_small_slab(self):
        """An explicit HostArray with a slab forcing MANY windows per
        pass still converges to the oracle — the streamed fixpoint does
        not depend on window geometry."""
        _, edges = _random_digraph(n=30, seed=7)
        host = staging.HostArray(edges)
        res = pagerank_stream(host, n=30, tol=1e-10, slab=1 << 10)
        dup_csr = sp.csr_matrix(
            (np.ones(edges.shape[0], np.float32), (edges[:, 0], edges[:, 1])),
            shape=(30, 30),
        )
        np.testing.assert_allclose(
            res.ranks.numpy(), _oracle_pagerank(dup_csr), atol=1e-6
        )

    def test_edge_shape_validation(self):
        with pytest.raises(ValueError):
            pagerank_stream(np.zeros((5, 3), np.int32), n=5)
        with pytest.raises(ValueError):
            pagerank_stream(np.zeros((5, 2), np.int32), n=5, alpha=0.0)


def _two_cliques(n_half=12, seed=8):
    """Two dense cliques joined by a single bridge edge."""
    n = 2 * n_half
    A = np.zeros((n, n), np.float32)
    A[:n_half, :n_half] = 1.0
    A[n_half:, n_half:] = 1.0
    np.fill_diagonal(A, 0.0)
    A[0, n_half] = A[n_half, 0] = 1.0
    return sp.csr_matrix(A)


class TestSpectralEmbedding:
    def test_fiedler_separates_two_cliques(self):
        A = _two_cliques()
        evals, emb = spectral_embedding(A, k=2)
        assert evals.shape == (2,) and emb.gshape == (24, 2)
        # lambda_0 ~ 0 (connected graph), lambda_1 small (one bridge)
        assert abs(evals[0]) < 1e-5
        assert 0 < evals[1] < 0.5
        fiedler = emb.numpy()[:, 1]
        signs = np.sign(fiedler)
        assert (signs[:12] == signs[0]).all()
        assert (signs[12:] == -signs[0]).all()

    def test_matches_dense_eigendecomposition(self):
        A = _two_cliques(n_half=10, seed=9)
        n = A.shape[0]
        evals, _ = spectral_embedding(A, k=3, m=n)  # full subspace: exact
        deg = np.asarray(A.sum(axis=1)).ravel()
        L = np.eye(n) - (A.toarray() / np.sqrt(deg)[:, None]) / np.sqrt(deg)[None, :]
        ref = np.linalg.eigvalsh(L)[:3]
        np.testing.assert_allclose(evals, ref, atol=1e-4)

    def test_unnormalized_laplacian(self):
        A = _two_cliques(n_half=8)
        n = A.shape[0]
        evals, _ = spectral_embedding(A, k=2, m=n, normalized=False)
        deg = np.asarray(A.sum(axis=1)).ravel()
        L = np.diag(deg) - A.toarray()
        ref = np.linalg.eigvalsh(L)[:2]
        np.testing.assert_allclose(evals, ref, atol=1e-3)

    def test_distributed_operand(self):
        A = _two_cliques(n_half=8 * max(P // 2, 1))
        S = ht.sparse.sparse_dbcsr_matrix(A, split=0)
        evals, emb = spectral_embedding(S, k=2)
        assert emb.split == 0
        evals_r, emb_r = spectral_embedding(
            ht.sparse.sparse_dbcsr_matrix(A, split=None), k=2
        )
        np.testing.assert_allclose(evals, evals_r, atol=1e-5)
        np.testing.assert_allclose(
            np.abs(emb.numpy()), np.abs(emb_r.numpy()), atol=1e-4
        )

    def test_validation(self):
        A = _two_cliques()
        with pytest.raises(ValueError):
            spectral_embedding(A, k=0)
        with pytest.raises(ValueError):
            spectral_embedding(A, k=2, m=1)
        with pytest.raises(ValueError):
            spectral_embedding(sp.csr_matrix((3, 5), dtype=np.float32), k=1)
