"""ML estimator tests: clustering, classification, regression,
preprocessing, spatial distances, graph Laplacian (reference pattern:
per-subpackage tests/ with synthetic data)."""

import numpy as np

import jax.numpy as jnp

import heat_tpu as ht

from test_suites.basic_test import TestCase


class TestSpatial(TestCase):
    def setUp(self):
        np.random.seed(11)
        self.x = np.random.randn(20, 4).astype(np.float32)
        self.y = np.random.randn(12, 4).astype(np.float32)

    def test_cdist(self):
        from scipy.spatial.distance import cdist as scipy_cdist

        expected = scipy_cdist(self.x, self.y)
        for split in (None, 0):
            X = ht.array(self.x, split=split)
            Y = ht.array(self.y)
            for quad in (False, True):
                got = ht.spatial.cdist(X, Y, quadratic_expansion=quad)
                np.testing.assert_allclose(got.numpy(), expected, rtol=1e-3, atol=1e-4)
        # X ≡ Y symmetry path
        X = ht.array(self.x, split=0)
        d = ht.spatial.cdist(X)
        np.testing.assert_allclose(d.numpy(), scipy_cdist(self.x, self.x), rtol=1e-3, atol=1e-4)

    def test_manhattan_rbf(self):
        from scipy.spatial.distance import cdist as scipy_cdist

        X = ht.array(self.x, split=0)
        Y = ht.array(self.y)
        np.testing.assert_allclose(
            ht.spatial.manhattan(X, Y).numpy(),
            scipy_cdist(self.x, self.y, metric="cityblock"),
            rtol=1e-4,
        )
        sigma = 2.0
        d2 = scipy_cdist(self.x, self.y) ** 2
        np.testing.assert_allclose(
            ht.spatial.rbf(X, Y, sigma=sigma).numpy(),
            np.exp(-d2 / (2 * sigma * sigma)),
            rtol=1e-3,
            atol=1e-5,
        )


class TestClustering(TestCase):
    def _blobs(self):
        return ht.utils.data.create_spherical_dataset(
            num_samples_cluster=64, radius=0.5, offset=6.0, random_state=5
        )

    def test_kmeans(self):
        data = self._blobs()
        km = ht.cluster.KMeans(n_clusters=4, init="kmeans++", max_iter=100, random_state=3)
        km.fit(data)
        self.assertEqual(km.cluster_centers_.shape, (4, 3))
        labels = km.labels_.numpy()
        self.assertEqual(labels.shape, (256,))
        # every ground-truth block maps to a single cluster
        for b in range(4):
            blk = labels[b * 64 : (b + 1) * 64]
            self.assertEqual(len(np.unique(blk)), 1)
        # blocks map to distinct clusters
        self.assertEqual(len(np.unique([labels[b * 64] for b in range(4)])), 4)
        self.assertIsNotNone(km.inertia_)
        # predict on the same data reproduces labels
        np.testing.assert_array_equal(km.predict(data).numpy(), labels)

    def test_kmeans_random_init_and_dndarray_init(self):
        data = self._blobs()
        km = ht.cluster.KMeans(n_clusters=4, init="random", max_iter=50, random_state=1)
        km.fit(data)
        self.assertEqual(km.cluster_centers_.shape, (4, 3))
        init = km.cluster_centers_
        km2 = ht.cluster.KMeans(n_clusters=4, init=init, max_iter=10)
        km2.fit(data)
        self.assertEqual(km2.cluster_centers_.shape, (4, 3))
        with self.assertRaises(ValueError):
            ht.cluster.KMeans(n_clusters=4, init="bogus").fit(data)

    def test_kmedians_kmedoids(self):
        data = self._blobs()
        for cls in (ht.cluster.KMedians, ht.cluster.KMedoids):
            est = cls(n_clusters=4, init="kmeans++", random_state=7)
            est.fit(data)
            labels = est.labels_.numpy()
            for b in range(4):
                blk = labels[b * 64 : (b + 1) * 64]
                self.assertEqual(len(np.unique(blk)), 1, f"{cls.__name__} split cluster")
        # medoids are actual data points
        est = ht.cluster.KMedoids(n_clusters=4, random_state=7).fit(data)
        dat = data.numpy()
        for c in est.cluster_centers_.numpy():
            self.assertTrue(np.any(np.all(np.isclose(dat, c, atol=1e-5), axis=1)))

    def test_spectral(self):
        data = self._blobs()
        sp = ht.cluster.Spectral(
            n_clusters=4, gamma=0.1, metric="rbf", n_lanczos=40, assign_labels="kmeans"
        )
        sp.fit(data)
        labels = sp.labels_.numpy()
        self.assertEqual(labels.shape, (256,))
        # spectral on well-separated blobs: blocks are pure
        purity = np.mean(
            [np.max(np.bincount(labels[b * 64 : (b + 1) * 64])) / 64 for b in range(4)]
        )
        self.assertGreater(purity, 0.9)


class TestClassification(TestCase):
    def test_knn(self):
        np.random.seed(13)
        train = np.concatenate(
            [np.random.randn(30, 2) + 4, np.random.randn(30, 2) - 4]
        ).astype(np.float32)
        labels = np.concatenate([np.zeros(30), np.ones(30)]).astype(np.int32)
        test = np.array([[4.0, 4.0], [-4.0, -4.0], [5.0, 3.0]], dtype=np.float32)
        knn = ht.classification.KNeighborsClassifier(n_neighbors=5)
        knn.fit(ht.array(train, split=0), ht.array(labels, split=0))
        pred = knn.predict(ht.array(test))
        np.testing.assert_array_equal(pred.numpy(), [0, 1, 0])


class TestGaussianNB(TestCase):
    def test_fit_predict_vs_sklearn_math(self):
        np.random.seed(17)
        x0 = np.random.randn(50, 3) + np.array([3, 0, 0])
        x1 = np.random.randn(50, 3) + np.array([-3, 0, 0])
        X = np.concatenate([x0, x1]).astype(np.float32)
        y = np.concatenate([np.zeros(50), np.ones(50)]).astype(np.int32)
        nb = ht.naive_bayes.GaussianNB()
        nb.fit(ht.array(X, split=0), ht.array(y, split=0))
        pred = nb.predict(ht.array(X, split=0))
        acc = (pred.numpy() == y).mean()
        self.assertGreater(acc, 0.95)
        probs = nb.predict_proba(ht.array(X[:5]))
        np.testing.assert_allclose(probs.numpy().sum(axis=1), 1.0, rtol=1e-5)
        # partial_fit merge equals one-shot fit
        nb2 = ht.naive_bayes.GaussianNB()
        nb2.partial_fit(ht.array(X[:40], split=0), ht.array(y[:40]), classes=ht.array([0, 1]))
        nb2.partial_fit(ht.array(X[40:], split=0), ht.array(y[40:]))
        np.testing.assert_allclose(nb2.theta_.numpy(), nb.theta_.numpy(), rtol=1e-4)
        np.testing.assert_allclose(nb2.var_.numpy(), nb.var_.numpy(), rtol=1e-3)


class TestLasso(TestCase):
    def test_fit_recovers_sparse_coefficients(self):
        np.random.seed(19)
        n, f = 200, 8
        X = np.random.randn(n, f).astype(np.float32)
        beta = np.array([2.0, 0, 0, -3.0, 0, 0, 1.5, 0], dtype=np.float32)
        y = X @ beta + 0.01 * np.random.randn(n).astype(np.float32)
        lasso = ht.regression.Lasso(lam=0.2, max_iter=200, tol=1e-8)
        lasso.fit(ht.array(X, split=0), ht.array(y, split=0))
        coef = lasso.coef_.numpy().ravel()
        # support recovery
        self.assertTrue(np.all(np.abs(coef[[1, 2, 4, 5, 7]]) < 0.1))
        self.assertTrue(np.all(np.abs(coef[[0, 3, 6]]) > 0.5))
        # coefficient values match sklearn's coordinate descent (same
        # mean-scale objective): spot-check against known shrinkage
        from sklearn.linear_model import Lasso as SkLasso

        sk = SkLasso(alpha=0.2).fit(X, y)
        np.testing.assert_allclose(coef, sk.coef_, atol=1e-2)
        pred = lasso.predict(ht.array(X, split=0))
        self.assertLess(lasso.rmse(ht.array(y), pred), 1.0)


class TestPreprocessing(TestCase):
    def setUp(self):
        np.random.seed(23)
        self.x = (np.random.randn(40, 5) * np.array([1, 10, 0.1, 5, 2]) + 7).astype(np.float32)

    def test_standard_scaler(self):
        for split in (None, 0):
            X = ht.array(self.x, split=split)
            sc = ht.preprocessing.StandardScaler()
            out = sc.fit_transform(X)
            np.testing.assert_allclose(out.numpy().mean(axis=0), 0.0, atol=1e-5)
            np.testing.assert_allclose(out.numpy().std(axis=0), 1.0, atol=1e-4)
            back = sc.inverse_transform(out)
            np.testing.assert_allclose(back.numpy(), self.x, rtol=1e-4)

    def test_minmax_scaler(self):
        X = ht.array(self.x, split=0)
        sc = ht.preprocessing.MinMaxScaler(feature_range=(0.0, 1.0))
        out = sc.fit_transform(X)
        np.testing.assert_allclose(out.numpy().min(axis=0), 0.0, atol=1e-6)
        np.testing.assert_allclose(out.numpy().max(axis=0), 1.0, atol=1e-6)
        back = sc.inverse_transform(out)
        np.testing.assert_allclose(back.numpy(), self.x, rtol=1e-4)

    def test_normalizer(self):
        X = ht.array(self.x, split=0)
        out = ht.preprocessing.Normalizer(norm="l2").fit_transform(X)
        np.testing.assert_allclose(np.linalg.norm(out.numpy(), axis=1), 1.0, rtol=1e-5)

    def test_maxabs_robust(self):
        X = ht.array(self.x, split=0)
        out = ht.preprocessing.MaxAbsScaler().fit_transform(X)
        self.assertLessEqual(np.abs(out.numpy()).max(), 1.0 + 1e-6)
        rs = ht.preprocessing.RobustScaler()
        out = rs.fit_transform(X)
        np.testing.assert_allclose(np.median(out.numpy(), axis=0), 0.0, atol=1e-5)


class TestGraph(TestCase):
    def test_laplacian(self):
        np.random.seed(29)
        x = np.random.randn(16, 3).astype(np.float32)
        X = ht.array(x, split=0)
        lap = ht.graph.Laplacian(
            lambda a: ht.spatial.rbf(a, sigma=1.0, quadratic_expansion=True), definition="norm_sym"
        )
        L = lap.construct(X)
        l_np = L.numpy()
        # symmetric, unit diagonal, eigenvalues in [0, 2]
        np.testing.assert_allclose(l_np, l_np.T, atol=1e-5)
        np.testing.assert_allclose(np.diag(l_np), 1.0, atol=1e-5)
        w = np.linalg.eigvalsh(l_np)
        self.assertGreater(w.min(), -1e-5)
        self.assertLess(w.max(), 2 + 1e-5)

    def test_simple_laplacian_rowsum_zero(self):
        x = np.random.randn(10, 3).astype(np.float32)
        X = ht.array(x, split=0)
        lap = ht.graph.Laplacian(
            lambda a: ht.spatial.rbf(a, sigma=1.0), definition="simple"
        )
        L = lap.construct(X)
        np.testing.assert_allclose(L.numpy().sum(axis=1), 0.0, atol=1e-4)


class TestBaseEstimator(TestCase):
    def test_params_roundtrip(self):
        km = ht.cluster.KMeans(n_clusters=3, max_iter=10)
        params = km.get_params()
        self.assertEqual(params["n_clusters"], 3)
        km.set_params(n_clusters=5)
        self.assertEqual(km.n_clusters, 5)
        with self.assertRaises(ValueError):
            km.set_params(bogus=1)
        self.assertTrue(ht.is_estimator(km))
        self.assertTrue(ht.is_clusterer(km))
        self.assertFalse(ht.is_classifier(km))
        knn = ht.classification.KNeighborsClassifier()
        self.assertTrue(ht.is_classifier(knn))
        self.assertTrue(ht.is_transformer(ht.preprocessing.StandardScaler()))
        self.assertTrue(ht.is_regressor(ht.regression.Lasso()))
        self.assertIn("KMeans", repr(km))


if __name__ == "__main__":
    import unittest

    unittest.main()


class TestPallasFusedAssign(TestCase):
    """Fused distance+argmin Pallas kernel (interpreter mode on CPU) vs the
    jnp Lloyd formulation — same sums/counts/inertia."""

    def test_matches_jnp_step(self):
        import jax.numpy as jnp
        from heat_tpu.cluster import _pallas

        rng = np.random.default_rng(0)
        for n, d, k in ((1000, 64, 8), (1003, 16, 4), (64, 8, 3)):
            x = jnp.asarray(rng.standard_normal((n, d)).astype(np.float32))
            c = x[:k]
            prog = _pallas.fused_assign_program(n, d, k, "float32", interpret=True)
            sums, counts, inertia = prog(x, c)
            # oracle
            d2 = np.maximum(
                (np.asarray(x) ** 2).sum(1)[:, None]
                + (np.asarray(c) ** 2).sum(1)[None, :]
                - 2 * np.asarray(x) @ np.asarray(c).T,
                0.0,
            )
            labels = d2.argmin(1)
            oh = np.eye(k, dtype=np.float32)[labels]
            np.testing.assert_allclose(np.asarray(sums), oh.T @ np.asarray(x), rtol=2e-4, atol=2e-4)
            np.testing.assert_allclose(np.asarray(counts), oh.sum(0), rtol=1e-6)
            np.testing.assert_allclose(float(inertia), d2.min(1).sum(), rtol=2e-4)

    def test_lloyd_step_pallas_flag(self):
        import jax.numpy as jnp
        from heat_tpu.cluster.kmeans import _lloyd_step
        from heat_tpu.cluster import _pallas

        rng = np.random.default_rng(1)
        n, d, k = 500, 8, 4
        x = jnp.asarray(rng.standard_normal((n, d)).astype(np.float32))
        c0 = x[:k]
        ref_step = _lloyd_step(k, (n, d), "float32", use_pallas=False)
        ref = ref_step(x, c0)
        # interpret-mode pallas variant: patch availability then compare
        prog = _pallas.fused_assign_program(n, d, k, "float32", interpret=True)
        sums, counts, inertia = prog(x, c0)
        new_centers = np.where(
            np.asarray(counts)[:, None] > 0,
            np.asarray(sums) / np.maximum(np.asarray(counts)[:, None], 1),
            np.asarray(c0),
        )
        np.testing.assert_allclose(np.asarray(ref[0]), new_centers, rtol=2e-4, atol=2e-4)
        np.testing.assert_allclose(float(ref[2]), float(inertia), rtol=2e-4)


class TestSparseEncoders(TestCase):
    """ISSUE 18: transforms that EMIT sparse outputs — one-hot and
    TF-IDF return DCSR matrices, register as serving ``transform``
    endpoints, and stream host-resident inputs with stage_out
    writeback."""

    def _codes(self, n=30, seed=40):
        rng = np.random.default_rng(seed)
        return np.stack(
            [rng.integers(0, 4, n), rng.integers(10, 13, n), rng.integers(-2, 1, n)],
            axis=1,
        ).astype(np.int32)

    def test_onehot_sparse_output_matches_dense_oracle(self):
        codes = self._codes()
        enc = ht.preprocessing.OneHotEncoder().fit(codes)
        out = enc.transform(codes)
        self.assertIsInstance(out, ht.sparse.DCSR_matrix)
        self.assertEqual(out.shape, (30, enc.n_features_out_))
        self.assertEqual(out.nnz, 30 * 3)  # exactly one 1.0 per (row, feature)
        dense = out.todense().numpy()
        # invert: each feature block's argmax recovers the code
        for f, cats in enumerate(enc.categories_):
            lo = int(enc._offsets[f])
            block = dense[:, lo : lo + len(cats)]
            np.testing.assert_array_equal(cats[block.argmax(1)], codes[:, f])
            np.testing.assert_allclose(block.sum(1), 1.0)

    def test_onehot_unknown_category_encodes_zero_block(self):
        codes = self._codes()
        enc = ht.preprocessing.OneHotEncoder().fit(codes)
        probe = codes[:2].copy()
        probe[0, 1] = 99  # unseen at fit time
        dense = enc.transform(probe).todense().numpy()
        lo = int(enc._offsets[1])
        hi = int(enc._offsets[2])
        np.testing.assert_array_equal(dense[0, lo:hi], 0.0)
        self.assertAlmostEqual(float(dense[1].sum()), 3.0)

    def test_onehot_serving_program_and_endpoint(self):
        codes = self._codes()
        enc = ht.preprocessing.OneHotEncoder().fit(codes)
        spec = enc.serving_program()
        run = spec["build"]()
        batch = jnp.asarray(codes[:8])
        got = np.asarray(run(batch, *spec["args"]))
        ref = enc.transform(codes[:8]).todense().numpy()
        np.testing.assert_array_equal(got, ref)
        # and the public endpoint constructor accepts the transformer
        ep = ht.serving.transform_endpoint(enc, buckets=(8,))
        self.assertEqual(ep.name, "onehot-transform")

    def test_onehot_stream_transform_writeback(self):
        codes = self._codes(n=200, seed=41)
        enc = ht.preprocessing.OneHotEncoder().fit(codes)
        streamed = enc.stream_transform(codes, slab=1 << 10)  # many windows
        ref = enc.transform(codes).todense().numpy()
        np.testing.assert_array_equal(streamed, ref)

    def _counts(self, n=25, v=12, seed=42):
        rng = np.random.default_rng(seed)
        counts = rng.poisson(0.6, (n, v)).astype(np.float32)
        counts[0] = 0  # an empty document: norm must not divide by zero
        return counts

    def test_tfidf_matches_sklearn_formula(self):
        counts = self._counts()
        tf = ht.preprocessing.TfidfTransformer().fit(counts)
        out = tf.transform(counts)
        self.assertIsInstance(out, ht.sparse.DCSR_matrix)
        n, v = counts.shape
        df = (counts > 0).sum(0)
        idf = np.log((1.0 + n) / (1.0 + df)) + 1.0
        ref = counts * idf[None, :]
        norms = np.linalg.norm(ref, axis=1, keepdims=True)
        ref = np.divide(ref, norms, out=np.zeros_like(ref), where=norms > 0)
        np.testing.assert_allclose(out.todense().numpy(), ref, rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(tf.idf_, idf.astype(np.float32), rtol=1e-6)

    def test_tfidf_preserves_sparsity_pattern(self):
        counts = self._counts(seed=43)
        out = ht.preprocessing.TfidfTransformer().fit(counts).transform(counts)
        self.assertEqual(out.nnz, int((counts != 0).sum()))

    def test_tfidf_serving_and_stream_agree(self):
        counts = self._counts(n=150, seed=44)
        tf = ht.preprocessing.TfidfTransformer().fit(counts)
        ref = tf.transform(counts).todense().numpy()
        spec = tf.serving_program()
        run = spec["build"]()
        got = np.asarray(run(jnp.asarray(counts), *spec["args"]))
        np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)
        streamed = tf.stream_transform(counts, slab=1 << 11)
        np.testing.assert_allclose(streamed, ref, rtol=1e-5, atol=1e-6)
        ep = ht.serving.transform_endpoint(tf, buckets=(8,))
        self.assertEqual(ep.name, "tfidf-transform")

    def test_fit_validation(self):
        enc = ht.preprocessing.OneHotEncoder()
        with self.assertRaises(TypeError):
            enc.fit(np.zeros((4, 2), np.float32))  # float codes rejected
        with self.assertRaises(RuntimeError):
            enc.transform(self._codes())
        enc.fit(self._codes())
        with self.assertRaises(ValueError):
            enc.transform(self._codes()[:, :2])
        tf = ht.preprocessing.TfidfTransformer()
        with self.assertRaises(RuntimeError):
            tf.transform(self._counts())
        with self.assertRaises(ValueError):
            ht.preprocessing.TfidfTransformer(norm="l1")
