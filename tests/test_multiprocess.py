"""True multi-process execution — the analog of the reference's
``mpirun -n N`` CI runs with REAL separate processes (not just a virtual
device mesh): 2 controller processes x 2 CPU devices each, wired with
``init_distributed`` (jax.distributed over Gloo). Exercises the lazy
import contract (import heat_tpu BEFORE initialize), per-host hyperslab
HDF5 ingest, cross-process allgather in ``numpy()``, shard_map
collectives (sort), sharded matmul, and a DP training step, all spanning
both processes. See tests/mp_worker.py for the worker program."""

import os
import socket
import subprocess
import sys

import numpy as np
import pytest

WORKER = os.path.join(os.path.dirname(os.path.abspath(__file__)), "mp_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def test_two_process_world(tmp_path):
    h5py = pytest.importorskip("h5py")
    h5 = str(tmp_path / "mh.h5")
    with h5py.File(h5, "w") as f:
        f.create_dataset("d", data=np.arange(13 * 3, dtype=np.float32).reshape(13, 3))

    port = str(_free_port())
    env = {k: v for k, v in os.environ.items() if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    procs = [
        subprocess.Popen(
            [sys.executable, WORKER, str(i), "2", port, h5],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env,
        )
        for i in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=300)
            outs.append(out.decode())
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {i} failed:\n{out[-3000:]}"
        assert f"[p{i}] MULTIHOST_OK" in out
