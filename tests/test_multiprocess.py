"""True multi-process execution — the analog of the reference's
``mpirun -n N`` CI runs with REAL separate processes (not just a virtual
device mesh), wired with ``init_distributed`` (jax.distributed over
Gloo). Two world shapes (VERDICT r2 weak #7):

* 2 processes x 2 CPU devices (multi-device hosts)
* 4 processes x 1 CPU device (the mpirun -n 4 shape)

The worker (tests/mp_worker.py) exercises the lazy import contract,
per-host hyperslab HDF5 ingest + single-writer saves, byte-range CSV
ingest, cross-process allgather in ``numpy()``, the shard_map sort
network and percentile, ring attention, a KMeans fit, gather-free
unique/mask/nonzero, and DP + DASO training steps, all spanning
processes."""

import os
import socket
import subprocess
import sys

import numpy as np
import pytest

WORKER = os.path.join(os.path.dirname(os.path.abspath(__file__)), "mp_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def _run_world(tmp_path, nprocs: int, local_devices: int, timeout: int = 420):
    h5py = pytest.importorskip("h5py")
    h5 = str(tmp_path / "mh.h5")
    with h5py.File(h5, "w") as f:
        f.create_dataset("d", data=np.arange(13 * 3, dtype=np.float32).reshape(13, 3))

    port = str(_free_port())
    env = {k: v for k, v in os.environ.items() if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    procs = [
        subprocess.Popen(
            [sys.executable, WORKER, str(i), str(nprocs), port, h5,
             str(tmp_path), str(local_devices)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env,
        )
        for i in range(nprocs)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=timeout)
            outs.append(out.decode())
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for i, (p, out) in enumerate(zip(procs, outs)):
        if p.returncode != 0 and "Multiprocess computations aren't implemented" in out:
            # capability gate, not a code failure: jaxlib 0.4.x cannot run
            # multi-process worlds on the CPU backend (newer runtimes can)
            pytest.skip("runtime's CPU backend lacks multiprocess support")
        assert p.returncode == 0, f"proc {i} failed:\n{out[-3000:]}"
        assert f"[p{i}] MULTIHOST_OK" in out


def test_two_process_world(tmp_path):
    _run_world(tmp_path, nprocs=2, local_devices=2)


def test_four_process_world(tmp_path):
    _run_world(tmp_path, nprocs=4, local_devices=1)
