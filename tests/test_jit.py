"""Tests for ``ht.jit`` — the fused-program surface (no reference analog;
the reference is torch-eager throughout, bench.py ``op_chain`` measures
the dispatch gap this closes)."""

import numpy as np
import pytest

import heat_tpu as ht

from test_suites.basic_test import TestCase


class TestHtJit(TestCase):
    def test_elementwise_chain_matches_eager(self):
        x = ht.random.randn(257, 3, split=0)  # odd length exercises padding

        def chain(y):
            return ht.exp(ht.sin(y) * 2.0 + y)

        fused = ht.jit(chain)
        out = fused(x)
        ref = chain(x)
        self.assertEqual(out.split, ref.split)
        self.assertEqual(out.shape, ref.shape)
        np.testing.assert_allclose(out.numpy(), ref.numpy(), rtol=1e-5, atol=1e-5)

    def test_matmul_reduction_sharded(self):
        x = ht.random.randn(64, 8, split=0)

        @ht.jit
        def gram_rows(y):
            g = ht.matmul(y, ht.transpose(y))
            return ht.sum(g, axis=1)

        out = gram_rows(x)
        ref = ht.sum(ht.matmul(x, ht.transpose(x)), axis=1)
        self.assertEqual(out.split, ref.split)
        np.testing.assert_allclose(out.numpy(), ref.numpy(), rtol=1e-4, atol=1e-4)

    def test_resplit_inside(self):
        x = ht.random.randn(32, 16, split=0)
        fused = ht.jit(lambda y: ht.mean(y.resplit(1), axis=0))
        ref = ht.mean(x.resplit(1), axis=0)
        np.testing.assert_allclose(fused(x).numpy(), ref.numpy(), rtol=1e-5, atol=1e-5)

    def test_pytree_in_out(self):
        a = ht.arange(12, split=0).astype(ht.float32)
        b = ht.ones((12,), split=0)

        @ht.jit
        def f(pair, scale):
            s = pair["a"] + pair["b"] * scale
            return {"sum": s, "total": ht.sum(s)}

        out = f({"a": a, "b": b}, 3.0)
        np.testing.assert_allclose(
            out["sum"].numpy(), np.arange(12, dtype=np.float32) + 3.0
        )
        self.assertAlmostEqual(float(out["total"]), float(np.sum(np.arange(12) + 3.0)), places=3)

    def test_single_program_and_cache(self):
        calls = [0]

        def chain(y):
            calls[0] += 1
            return ht.sqrt(ht.abs(y)) + 1.0

        fused = ht.jit(chain)
        x = ht.random.randn(64, split=0)
        fused(x)
        fused(x + 1.0)  # same signature: no retrace
        self.assertEqual(calls[0], 1)
        self.assertEqual(len(fused._ht_jit_cache), 1)
        fused(ht.random.randn(32, split=0))  # new shape: retrace
        self.assertEqual(calls[0], 2)
        self.assertEqual(len(fused._ht_jit_cache), 2)

    def test_static_scalar_keys_cache(self):
        fused = ht.jit(lambda y, p: y**p)
        x = ht.full((8,), 2.0, split=0)
        np.testing.assert_allclose(fused(x, 2).numpy(), np.full(8, 4.0))
        np.testing.assert_allclose(fused(x, 3).numpy(), np.full(8, 8.0))
        self.assertEqual(len(fused._ht_jit_cache), 2)

    def test_resplit_physical_sharding_under_jit(self):
        # jax.device_put on a Tracer is not a binding constraint (the
        # sharding is silently dropped); communication.place must lower to
        # with_sharding_constraint under trace so split metadata and the
        # physical layout stay in sync
        x = ht.random.randn(64, 8, split=0)
        out = ht.jit(lambda y: y.resplit(1))(x)
        self.assertEqual(out.split, 1)
        eager = x.resplit(1)
        self.assertEqual(
            {s.data.shape for s in out._phys.addressable_shards},
            {s.data.shape for s in eager._phys.addressable_shards},
        )

    def test_data_dependent_op_raises_helpfully(self):
        x = ht.array([1.0, 0.0, 2.0, 0.0], split=0)
        fused = ht.jit(lambda y: ht.nonzero(y))
        with pytest.raises(TypeError, match="ht.jit"):
            fused(x)

    def test_estimator_predict_under_jit(self):
        # estimators compose with ht.jit: a fitted model's predict traces
        # into one program (labels keep their split and values)
        rng = np.random.default_rng(4)
        x = ht.array(rng.standard_normal((96, 3)).astype(np.float32), split=0)
        km = ht.cluster.KMeans(n_clusters=3, init="kmeans++", random_state=0).fit(x)
        fused_predict = ht.jit(km.predict)
        out = fused_predict(x)
        ref = km.predict(x)
        self.assertEqual(out.split, ref.split)
        np.testing.assert_array_equal(out.numpy(), ref.numpy())

    def test_preprocessing_pipeline_under_jit(self):
        rng = np.random.default_rng(5)
        x = ht.array(rng.standard_normal((64, 6)).astype(np.float32), split=0)

        @ht.jit
        def pipeline(a):
            sc = ht.preprocessing.StandardScaler(copy=False)
            z = sc.fit_transform(a)
            rb = ht.preprocessing.RobustScaler(copy=False)
            return rb.fit_transform(z)

        ref = ht.preprocessing.RobustScaler(copy=False).fit_transform(
            ht.preprocessing.StandardScaler(copy=False).fit_transform(x)
        )
        np.testing.assert_allclose(
            pipeline(x).numpy(), ref.numpy(), rtol=1e-4, atol=1e-5
        )

    def test_mixed_dtypes_and_int_output(self):
        x = ht.random.randn(40, split=0)

        @ht.jit
        def f(y):
            return ht.argmax(y), y * 2.0

        idx, doubled = f(x)
        self.assertEqual(int(idx), int(np.argmax(x.numpy())))
        np.testing.assert_allclose(doubled.numpy(), x.numpy() * 2.0, rtol=1e-6)

    # ---- donation + closure guard (VERDICT r4 #7 / ADVICE r4) ---- #
    def test_donation_frees_input_buffer(self):
        f = ht.jit(lambda y: y * 2.0 + 1.0, donate_argnums=(0,))
        x = ht.arange(1000, dtype=ht.float32, split=0)
        phys = x._phys
        out = f(x)
        # the donated input buffer must actually be reused/deleted —
        # the live-buffer criterion from the r4 limitation note
        self.assertTrue(phys.is_deleted())
        np.testing.assert_allclose(out.numpy(), np.arange(1000) * 2.0 + 1.0)
        # cache-hit path donates too
        x2 = ht.arange(1000, dtype=ht.float32, split=0)
        p2 = x2._phys
        f(x2)
        self.assertTrue(p2.is_deleted())

    def test_donation_is_positionally_selective(self):
        g = ht.jit(lambda a, b: a + b, donate_argnums=(1,))
        a = ht.arange(100, dtype=ht.float32)
        b = ht.arange(100, dtype=ht.float32)
        pa, pb = a._phys, b._phys
        out = g(a, b)
        self.assertFalse(pa.is_deleted())
        self.assertTrue(pb.is_deleted())
        np.testing.assert_allclose(out.numpy(), np.arange(100) * 2.0)

    def test_donation_rejects_bad_positions_and_argnames(self):
        with self.assertRaises(TypeError):
            ht.jit(lambda y: y, donate_argnames=("y",))
        f = ht.jit(lambda y: y * 1.0, donate_argnums=(3,))
        with self.assertRaises(ValueError):
            f(ht.arange(4, dtype=ht.float32))

    def test_closure_capture_warns(self):
        import warnings

        cap = ht.arange(8, dtype=ht.float32)
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            ht.jit(lambda z: z + cap)(ht.arange(8, dtype=ht.float32))
        self.assertTrue(
            any("closes over DNDarray" in str(x.message) for x in w)
        )

    def test_no_capture_no_warning(self):
        import warnings

        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            ht.jit(lambda z: ht.exp(z))(ht.arange(8, dtype=ht.float32))
        self.assertFalse(any("closes over" in str(x.message) for x in w))

    def test_container_closure_capture_warns(self):
        import warnings

        def outer():
            bag = {"w": ht.arange(6, dtype=ht.float32)}
            return ht.jit(lambda z: z + bag["w"])

        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            outer()(ht.arange(6, dtype=ht.float32))
        self.assertTrue(any("closes over DNDarray" in str(x.message) for x in w))

    def test_attribute_name_no_false_positive(self):
        import warnings

        # module global named like an attribute the fn uses: co_names
        # would flag it; the LOAD_GLOBAL scan must not
        globals()["T"] = ht.arange(4, dtype=ht.float32)
        try:
            with warnings.catch_warnings(record=True) as w:
                warnings.simplefilter("always")
                f = ht.jit(lambda x: ht.transpose(ht.reshape(x, (2, 2))).T)
                f(ht.arange(4, dtype=ht.float32))
            self.assertFalse(any("closes over" in str(x.message) for x in w))
        finally:
            del globals()["T"]

    def test_dndarray_default_argument_warns(self):
        import warnings

        w_default = ht.arange(4, dtype=ht.float32)

        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")

            @ht.jit
            def step(x, wgt=w_default):
                return x * wgt

            step(ht.arange(4, dtype=ht.float32))
        self.assertTrue(any("closes over DNDarray" in str(x.message) for x in w))
