"""Tests for ``ht.jit`` — the fused-program surface (no reference analog;
the reference is torch-eager throughout, bench.py ``op_chain`` measures
the dispatch gap this closes)."""

import numpy as np
import pytest

import heat_tpu as ht

from test_suites.basic_test import TestCase


class TestHtJit(TestCase):
    def test_elementwise_chain_matches_eager(self):
        x = ht.random.randn(257, 3, split=0)  # odd length exercises padding

        def chain(y):
            return ht.exp(ht.sin(y) * 2.0 + y)

        fused = ht.jit(chain)
        out = fused(x)
        ref = chain(x)
        self.assertEqual(out.split, ref.split)
        self.assertEqual(out.shape, ref.shape)
        np.testing.assert_allclose(out.numpy(), ref.numpy(), rtol=1e-5, atol=1e-5)

    def test_matmul_reduction_sharded(self):
        x = ht.random.randn(64, 8, split=0)

        @ht.jit
        def gram_rows(y):
            g = ht.matmul(y, ht.transpose(y))
            return ht.sum(g, axis=1)

        out = gram_rows(x)
        ref = ht.sum(ht.matmul(x, ht.transpose(x)), axis=1)
        self.assertEqual(out.split, ref.split)
        np.testing.assert_allclose(out.numpy(), ref.numpy(), rtol=1e-4, atol=1e-4)

    def test_resplit_inside(self):
        x = ht.random.randn(32, 16, split=0)
        fused = ht.jit(lambda y: ht.mean(y.resplit(1), axis=0))
        ref = ht.mean(x.resplit(1), axis=0)
        np.testing.assert_allclose(fused(x).numpy(), ref.numpy(), rtol=1e-5, atol=1e-5)

    def test_pytree_in_out(self):
        a = ht.arange(12, split=0).astype(ht.float32)
        b = ht.ones((12,), split=0)

        @ht.jit
        def f(pair, scale):
            s = pair["a"] + pair["b"] * scale
            return {"sum": s, "total": ht.sum(s)}

        out = f({"a": a, "b": b}, 3.0)
        np.testing.assert_allclose(
            out["sum"].numpy(), np.arange(12, dtype=np.float32) + 3.0
        )
        self.assertAlmostEqual(float(out["total"]), float(np.sum(np.arange(12) + 3.0)), places=3)

    def test_single_program_and_cache(self):
        calls = [0]

        def chain(y):
            calls[0] += 1
            return ht.sqrt(ht.abs(y)) + 1.0

        fused = ht.jit(chain)
        x = ht.random.randn(64, split=0)
        fused(x)
        fused(x + 1.0)  # same signature: no retrace
        self.assertEqual(calls[0], 1)
        self.assertEqual(len(fused._ht_jit_cache), 1)
        fused(ht.random.randn(32, split=0))  # new shape: retrace
        self.assertEqual(calls[0], 2)
        self.assertEqual(len(fused._ht_jit_cache), 2)

    def test_static_scalar_keys_cache(self):
        fused = ht.jit(lambda y, p: y**p)
        x = ht.full((8,), 2.0, split=0)
        np.testing.assert_allclose(fused(x, 2).numpy(), np.full(8, 4.0))
        np.testing.assert_allclose(fused(x, 3).numpy(), np.full(8, 8.0))
        self.assertEqual(len(fused._ht_jit_cache), 2)

    def test_resplit_physical_sharding_under_jit(self):
        # jax.device_put on a Tracer is not a binding constraint (the
        # sharding is silently dropped); communication.place must lower to
        # with_sharding_constraint under trace so split metadata and the
        # physical layout stay in sync
        x = ht.random.randn(64, 8, split=0)
        out = ht.jit(lambda y: y.resplit(1))(x)
        self.assertEqual(out.split, 1)
        eager = x.resplit(1)
        self.assertEqual(
            {s.data.shape for s in out._phys.addressable_shards},
            {s.data.shape for s in eager._phys.addressable_shards},
        )

    def test_data_dependent_op_raises_helpfully(self):
        x = ht.array([1.0, 0.0, 2.0, 0.0], split=0)
        fused = ht.jit(lambda y: ht.nonzero(y))
        with pytest.raises(TypeError, match="ht.jit"):
            fused(x)

    def test_estimator_predict_under_jit(self):
        # estimators compose with ht.jit: a fitted model's predict traces
        # into one program (labels keep their split and values)
        rng = np.random.default_rng(4)
        x = ht.array(rng.standard_normal((96, 3)).astype(np.float32), split=0)
        km = ht.cluster.KMeans(n_clusters=3, init="kmeans++", random_state=0).fit(x)
        fused_predict = ht.jit(km.predict)
        out = fused_predict(x)
        ref = km.predict(x)
        self.assertEqual(out.split, ref.split)
        np.testing.assert_array_equal(out.numpy(), ref.numpy())

    def test_preprocessing_pipeline_under_jit(self):
        rng = np.random.default_rng(5)
        x = ht.array(rng.standard_normal((64, 6)).astype(np.float32), split=0)

        @ht.jit
        def pipeline(a):
            sc = ht.preprocessing.StandardScaler(copy=False)
            z = sc.fit_transform(a)
            rb = ht.preprocessing.RobustScaler(copy=False)
            return rb.fit_transform(z)

        ref = ht.preprocessing.RobustScaler(copy=False).fit_transform(
            ht.preprocessing.StandardScaler(copy=False).fit_transform(x)
        )
        np.testing.assert_allclose(
            pipeline(x).numpy(), ref.numpy(), rtol=1e-4, atol=1e-5
        )

    def test_mixed_dtypes_and_int_output(self):
        x = ht.random.randn(40, split=0)

        @ht.jit
        def f(y):
            return ht.argmax(y), y * 2.0

        idx, doubled = f(x)
        self.assertEqual(int(idx), int(np.argmax(x.numpy())))
        np.testing.assert_allclose(doubled.numpy(), x.numpy() * 2.0, rtol=1e-6)
