"""Planar complex surface (VERDICT r4 #3, round-5 close): on backends
without native complex support (the bench TPU), complex DNDarrays run in
PLANAR form — split real/imaginary f32 planes computed by ordinary XLA
programs (``heat_tpu/core/complex_planar.py``). The mode is forced here
on the CPU suite via ``ht.use_complex("planar")`` — the exact state a
TPU world boots into (``devices.complex_mode()`` resolves backend
'tpu' → "planar") — and every result is checked against numpy's native
complex arithmetic as the oracle. Ops outside the documented planar
surface must raise the actionable policy TypeError, never compute
silently wrong results (``larray``/``_phys`` refuse planar arrays).

Reference parity: /root/reference/heat/core/complex_math.py:1-110 (the
angle/conj/conjugate/imag/real surface) plus the factory, arithmetic,
reduction and export paths a complex workload touches.
"""

import numpy as np
import pytest

import heat_tpu as ht
from heat_tpu.core import devices


@pytest.fixture(autouse=True)
def planar_mode():
    ht.use_complex("planar")
    try:
        yield
    finally:
        devices._complex_choice = None  # back to platform resolution


Z1 = np.array([1 + 2j, 3 - 4j, -5 + 0.5j, 0.25 - 0.75j, -1 - 1j, 2 + 0j], np.complex64)
Z2 = np.array([2 - 1j, 1 + 1j, 0.5 + 0.5j, -3 + 2j, 0.1 - 0.2j, 1 + 3j], np.complex64)


def _mk(z, split=None):
    return ht.array(z, split=split)


# --------------------------------------------------------------------- #
# creation / export                                                     #
# --------------------------------------------------------------------- #
class TestCreation:
    def test_array_roundtrip(self):
        x = _mk(Z1)
        assert x._is_planar
        assert x.dtype == ht.complex64
        assert x.shape == Z1.shape
        np.testing.assert_allclose(x.numpy(), Z1)

    def test_python_complex_list_infers(self):
        x = ht.array([1 + 2j, 3 - 4j])
        assert x._is_planar and x.dtype == ht.complex64
        np.testing.assert_allclose(x.numpy(), np.array([1 + 2j, 3 - 4j], np.complex64))

    def test_complex128_degrades_to_complex64(self):
        x = ht.array(Z1.astype(np.complex128), dtype=ht.complex128)
        assert x.dtype == ht.complex64  # planes are f32 (doc'd degrade)
        np.testing.assert_allclose(x.numpy(), Z1)

    @pytest.mark.parametrize("split", [None, 0])
    def test_factories(self, split):
        assert ht.zeros((10,), dtype=ht.complex64, split=split).numpy().dtype == np.complex64
        np.testing.assert_allclose(
            ht.ones((10,), dtype=ht.complex64, split=split).numpy(), np.ones(10, np.complex64)
        )
        np.testing.assert_allclose(
            ht.full((10,), 1 - 2j, split=split).numpy(), np.full(10, 1 - 2j, np.complex64)
        )
        e = ht.empty((10,), dtype=ht.complex64, split=split)
        assert e.numpy().shape == (10,) and e.numpy().dtype == np.complex64

    def test_eye_arange_linspace(self):
        np.testing.assert_allclose(
            ht.eye(4, dtype=ht.complex64).numpy(), np.eye(4, dtype=np.complex64)
        )
        np.testing.assert_allclose(
            ht.arange(5, dtype=ht.complex64).numpy(), np.arange(5, dtype=np.complex64)
        )
        np.testing.assert_allclose(
            ht.linspace(0.0, 1.0, 5, dtype=ht.complex64).numpy(),
            np.linspace(0, 1, 5, dtype=np.complex64),
        )

    def test_like_factories(self):
        x = _mk(Z1)
        z = ht.zeros_like(x)
        assert z._is_planar and z.shape == x.shape
        np.testing.assert_allclose(z.numpy(), np.zeros_like(Z1))

    def test_array_from_planar_dndarray(self):
        x = _mk(Z1)
        y = ht.array(x)
        assert y._is_planar
        np.testing.assert_allclose(y.numpy(), Z1)

    def test_printing_and_scalar_export(self):
        x = _mk(Z1)
        s = str(x)
        assert "complex64" in s and "DNDarray" in s
        one = ht.array(np.complex64(2 + 3j))
        assert complex(one) == 2 + 3j
        assert one.item() == 2 + 3j
        assert _mk(Z1).tolist() == [complex(v) for v in Z1]

    def test_split_layout_uneven(self):
        # 6 elements over the 8-device mesh: pad region exercised
        x = _mk(Z1, split=0)
        assert x.split == 0 and x._is_planar
        np.testing.assert_allclose(x.numpy(), Z1)


# --------------------------------------------------------------------- #
# complex_math surface (the reference module)                           #
# --------------------------------------------------------------------- #
class TestComplexMath:
    @pytest.mark.parametrize("split", [None, 0])
    def test_angle(self, split):
        x = _mk(Z1, split)
        np.testing.assert_allclose(ht.angle(x).numpy(), np.angle(Z1), rtol=1e-6)
        np.testing.assert_allclose(
            ht.angle(x, deg=True).numpy(), np.angle(Z1, deg=True), rtol=1e-5
        )

    @pytest.mark.parametrize("split", [None, 0])
    def test_conj_real_imag(self, split):
        x = _mk(Z1, split)
        np.testing.assert_allclose(ht.conj(x).numpy(), np.conj(Z1))
        np.testing.assert_allclose(ht.conjugate(x).numpy(), np.conj(Z1))
        np.testing.assert_allclose(x.conj().numpy(), np.conj(Z1))
        r, i = x.real, x.imag
        assert r.dtype == ht.float32 and i.dtype == ht.float32
        np.testing.assert_allclose(r.numpy(), Z1.real)
        np.testing.assert_allclose(i.numpy(), Z1.imag)
        np.testing.assert_allclose(ht.real(x).numpy(), Z1.real)
        np.testing.assert_allclose(ht.imag(x).numpy(), Z1.imag)


# --------------------------------------------------------------------- #
# arithmetic                                                            #
# --------------------------------------------------------------------- #
class TestArithmetic:
    @pytest.mark.parametrize("split", [None, 0])
    def test_binary_oracle(self, split):
        x, y = _mk(Z1, split), _mk(Z2, split)
        np.testing.assert_allclose((x + y).numpy(), Z1 + Z2, rtol=1e-6)
        np.testing.assert_allclose((x - y).numpy(), Z1 - Z2, rtol=1e-6)
        np.testing.assert_allclose((x * y).numpy(), Z1 * Z2, rtol=1e-5)
        np.testing.assert_allclose((x / y).numpy(), Z1 / Z2, rtol=1e-5)

    def test_complex_with_real_operand(self):
        x = _mk(Z1)
        r = ht.arange(6, dtype=ht.float32)
        np.testing.assert_allclose((x * r).numpy(), Z1 * np.arange(6), rtol=1e-6)
        np.testing.assert_allclose((r + x).numpy(), np.arange(6) + Z1, rtol=1e-6)

    def test_scalar_operands(self):
        x = _mk(Z1)
        np.testing.assert_allclose((x * (2 + 1j)).numpy(), Z1 * (2 + 1j), rtol=1e-5)
        np.testing.assert_allclose((x + 3).numpy(), Z1 + 3, rtol=1e-6)
        np.testing.assert_allclose((x / 2.0).numpy(), Z1 / 2.0, rtol=1e-6)

    def test_real_array_times_complex_scalar_promotes(self):
        # the promotion-point hook: real DNDarray x python complex scalar
        r = ht.arange(4, dtype=ht.float32)
        z = r * (1 + 2j)
        assert z._is_planar and z.dtype == ht.complex64
        np.testing.assert_allclose(z.numpy(), np.arange(4) * (1 + 2j))

    def test_neg_and_unary_plus(self):
        x = _mk(Z1)
        np.testing.assert_allclose((-x).numpy(), -Z1)

    def test_comparisons(self):
        x, y = _mk(Z1), _mk(Z2)
        assert (x == x).numpy().all() and not (x == y).numpy().any()
        assert (x != y).numpy().all()
        assert (x == x).dtype == ht.bool

    def test_isclose_allclose(self):
        x = _mk(Z1)
        y = _mk(Z1 + np.complex64(1e-7 + 1e-7j))
        assert ht.allclose(x, y, atol=1e-5)
        assert not ht.allclose(x, _mk(Z2))
        np.testing.assert_array_equal(
            ht.isclose(x, y, atol=1e-5).numpy(), np.isclose(Z1, Z1 + 1e-7 + 1e-7j, atol=1e-5)
        )

    def test_broadcasting(self):
        a2 = np.stack([Z1, Z2])  # (2, 6)
        x = ht.array(a2)
        row = ht.array(Z2)  # (6,)
        np.testing.assert_allclose((x * row).numpy(), a2 * Z2, rtol=1e-5)

    def test_mismatched_splits_realign(self):
        # code-review r5: splits landing on different output axes must
        # redistribute (as the real __binary_op does), not refuse
        a2 = np.stack([Z1, Z2])
        r = ht.array(a2, split=0) * ht.array(Z2, split=0)
        np.testing.assert_allclose(r.numpy(), a2 * Z2, rtol=1e-5)
        o = ht.outer(ht.array(Z1, split=0), ht.array(Z2, split=0))
        np.testing.assert_allclose(o.numpy(), np.outer(Z1, Z2), rtol=1e-5)

    def test_native_complex_operand_keeps_imag(self):
        # code-review r5: a native complex array created before the mode
        # switch must not lose its imaginary plane in planar dispatch
        devices._complex_choice = True
        xn = ht.array(Z1)
        assert not xn._is_planar
        ht.use_complex("planar")
        prod = xn * ht.array(Z2)
        np.testing.assert_allclose(prod.numpy(), Z1 * Z2, rtol=1e-5)


# --------------------------------------------------------------------- #
# transcendental / predicates                                           #
# --------------------------------------------------------------------- #
class TestUnary:
    @pytest.mark.parametrize(
        "hfn,nfn,tol",
        [
            (ht.abs, np.abs, 1e-6),
            (ht.exp, np.exp, 1e-5),
            (ht.sqrt, np.sqrt, 1e-5),
            (ht.log, np.log, 1e-5),
            (ht.log2, np.log2, 1e-5),
            (ht.log10, np.log10, 1e-5),
            (ht.square, np.square, 1e-5),
            (ht.sin, np.sin, 1e-5),
            (ht.cos, np.cos, 1e-5),
            (ht.tan, np.tan, 1e-4),
            (ht.sinh, np.sinh, 1e-5),
            (ht.cosh, np.cosh, 1e-5),
            (ht.tanh, np.tanh, 1e-5),
        ],
    )
    def test_unary_oracle(self, hfn, nfn, tol):
        z = Z1[Z1 != 0]  # log/sqrt branch points excluded
        x = _mk(z)
        np.testing.assert_allclose(hfn(x).numpy(), nfn(z), rtol=tol, atol=tol)

    def test_abs_is_real(self):
        assert ht.abs(_mk(Z1)).dtype == ht.float32

    def test_predicates(self):
        z = np.array([1 + 2j, np.nan + 0j, 1j * np.nan, np.inf + 1j, 1 + 0j], np.complex64)
        x = _mk(z)
        np.testing.assert_array_equal(ht.isnan(x).numpy(), np.isnan(z))
        np.testing.assert_array_equal(ht.isinf(x).numpy(), np.isinf(z))
        np.testing.assert_array_equal(ht.isfinite(x).numpy(), np.isfinite(z))

    def test_reciprocal(self):
        z = Z1[Z1 != 0]
        np.testing.assert_allclose((1.0 / _mk(z)).numpy(), 1.0 / z, rtol=1e-5)


# --------------------------------------------------------------------- #
# reductions / cumsum                                                   #
# --------------------------------------------------------------------- #
class TestReductions:
    @pytest.mark.parametrize("split", [None, 0, 1])
    def test_sum_mean_2d(self, split):
        a2 = np.stack([Z1, Z2, Z1 * 2])  # (3, 6)
        x = ht.array(a2, split=split)
        np.testing.assert_allclose(ht.sum(x).numpy(), a2.sum(), rtol=1e-5)
        np.testing.assert_allclose(ht.sum(x, axis=0).numpy(), a2.sum(0), rtol=1e-5)
        np.testing.assert_allclose(ht.sum(x, axis=1).numpy(), a2.sum(1), rtol=1e-5)
        np.testing.assert_allclose(
            ht.sum(x, axis=1, keepdims=True).numpy(), a2.sum(1, keepdims=True), rtol=1e-5
        )
        np.testing.assert_allclose(ht.mean(x).numpy(), a2.mean(), rtol=1e-5)
        np.testing.assert_allclose(ht.mean(x, axis=0).numpy(), a2.mean(0), rtol=1e-5)

    def test_sum_uneven_split_pad_safe(self):
        z = (np.arange(10) + 1j * np.arange(10)[::-1]).astype(np.complex64)
        x = ht.array(z, split=0)  # 10 over 8 devices: pad rows live
        np.testing.assert_allclose(ht.sum(x).numpy(), z.sum(), rtol=1e-5)
        np.testing.assert_allclose(ht.mean(x).numpy(), z.mean(), rtol=1e-5)

    @pytest.mark.parametrize("split", [None, 0, 1])
    def test_prod(self, split):
        # unit-modulus-ish values keep the product inside f32 range;
        # split=1 over 8 devices pads the REDUCED axis (neutral refill)
        rng = np.random.default_rng(2)
        z = (
            np.exp(1j * rng.uniform(0, 2 * np.pi, (3, 10)))
            * rng.uniform(0.9, 1.1, (3, 10))
        ).astype(np.complex64)
        x = ht.array(z, split=split)
        np.testing.assert_allclose(ht.prod(x).numpy(), z.prod(), rtol=1e-4)
        np.testing.assert_allclose(ht.prod(x, axis=1).numpy(), z.prod(1), rtol=1e-4)
        np.testing.assert_allclose(
            ht.prod(x, axis=0, keepdims=True).numpy(), z.prod(0, keepdims=True), rtol=1e-4
        )

    def test_prod_empty_is_identity(self):
        # empty product = 1 (numpy; code-review r5)
        assert ht.prod(ht.array(np.zeros((0,), np.complex64))).numpy() == 1
        np.testing.assert_array_equal(
            ht.prod(ht.array(np.zeros((3, 0), np.complex64)), axis=1).numpy(),
            np.ones(3, np.complex64),
        )

    def test_nansum(self):
        z = np.array([1 + 1j, np.nan + 2j, 3 - 1j], np.complex64)
        np.testing.assert_allclose(ht.nansum(_mk(z)).numpy(), np.nansum(z), rtol=1e-5)

    @pytest.mark.parametrize("split", [None, 0])
    def test_var_std(self, split):
        a2 = np.stack([Z1, Z2, Z1 * (1 - 1j)])
        x = ht.array(a2, split=split)
        np.testing.assert_allclose(ht.var(x).numpy(), np.var(a2), rtol=1e-4)
        np.testing.assert_allclose(ht.var(x, axis=0).numpy(), np.var(a2, axis=0), rtol=1e-4)
        np.testing.assert_allclose(
            ht.var(x, axis=1, ddof=1, keepdims=True).numpy(),
            np.var(a2, axis=1, ddof=1, keepdims=True),
            rtol=1e-4,
        )
        s = ht.std(x)
        assert s.dtype == ht.float32  # complex variance is REAL
        np.testing.assert_allclose(s.numpy(), np.std(a2), rtol=1e-4)

    def test_pow(self):
        z = Z1[Z1 != 0]
        x = ht.array(z)
        np.testing.assert_allclose((x**2).numpy(), z**2, rtol=1e-4)
        np.testing.assert_allclose((x**-1).numpy(), z ** (-1.0), rtol=1e-4)
        np.testing.assert_allclose((x**0.5).numpy(), z**0.5, rtol=1e-4)
        np.testing.assert_allclose((x ** (1 + 1j)).numpy(), z ** (1 + 1j), rtol=1e-3)
        zb = np.array([0 + 0j, 3 + 1j], np.complex64)
        b = ht.array(zb)
        with np.errstate(all="ignore"):
            assert (b**0).numpy()[0] == 1 and (b**2).numpy()[0] == 0
            assert np.isnan((b ** (1j)).numpy()[0])

    def test_pow_integer_exact_and_nonfinite(self):
        # code-review r5: integral exponents run exact repeated complex
        # multiplication (not exp/log), and x**0 == 1 for EVERY base
        with np.errstate(all="ignore"):
            z = np.array([np.nan + 0j, np.inf + 0j, -1 - 1j, 2 + 3j], np.complex64)
            x = ht.array(z)
            np.testing.assert_array_equal((x**0).numpy(), np.ones(4, np.complex64))
            sq = (x**2).numpy()
            assert sq[2] == (-1 - 1j) ** 2  # exact, no exp/log roundoff
            assert sq[1] == np.complex64(np.inf) ** 2 or (
                np.isinf(sq[1].real) and np.isnan(sq[1].imag)
            )
            np.testing.assert_allclose((x**-3).numpy()[2:], z[2:] ** (-3.0), rtol=1e-5)
            # 0 ** b zeroes for ANY b with positive real part (npy_cpow;
            # code-review r5 — the imag part of b is free)
            zero = ht.array(np.array([0j], np.complex64))
            assert (zero ** (2 + 1j)).numpy()[0] == 0

    def test_numpy_roundtrip_nonfinite(self):
        # host assembly must be componentwise (re + 1j*im corrupts
        # (inf, nan) pairs — code-review r5)
        z = np.array([np.inf + 1j, 1 - 1j * np.inf, np.nan + 2j], np.complex64)
        np.testing.assert_array_equal(ht.array(z).numpy(), z)

    def test_cumsum(self):
        x = _mk(Z1)
        np.testing.assert_allclose(ht.cumsum(x, 0).numpy(), np.cumsum(Z1), rtol=1e-5)
        xs = ht.array(Z1, split=0)
        np.testing.assert_allclose(ht.cumsum(xs, 0).numpy(), np.cumsum(Z1), rtol=1e-5)


# --------------------------------------------------------------------- #
# structural / casts                                                    #
# --------------------------------------------------------------------- #
class TestStructural:
    def test_basic_getitem(self):
        a2 = np.stack([Z1, Z2])
        x = ht.array(a2)
        np.testing.assert_allclose(x[0].numpy(), a2[0])
        np.testing.assert_allclose(x[1, 2:5].numpy(), a2[1, 2:5])
        np.testing.assert_allclose(x[:, ::2].numpy(), a2[:, ::2])
        assert x[0, 0].item() == complex(a2[0, 0])

    def test_getitem_on_split(self):
        x = ht.array(Z1, split=0)
        np.testing.assert_allclose(x[1:4].numpy(), Z1[1:4])

    def test_getitem_preserves_split(self):
        # code-review r5: slicing a split planar array must stay sharded
        # (replicating would all-gather), int keys drop the split
        big = np.tile(Z1, 4)
        xs = ht.array(big, split=0)
        sl = xs[2:20]
        assert sl.split == 0
        np.testing.assert_allclose(sl.numpy(), big[2:20])
        m = ht.array(np.outer(big, Z2), split=0)
        assert m[1:, ::2].split == 0
        assert m[0].split is None

    @pytest.mark.parametrize("split", [None, 0])
    def test_plane_passenger_ops(self, split):
        z = np.outer(np.arange(6) + 1j, np.arange(4) - 2j).astype(np.complex64)
        x = ht.array(z, split=split)
        for name, r, oracle in [
            ("reshape", ht.reshape(x, (4, 6)), z.reshape(4, 6)),
            ("ravel", ht.ravel(x), z.ravel()),
            ("transpose", x.T, z.T),
            ("expand_dims", ht.expand_dims(x, 0), z[None]),
            ("concatenate", ht.concatenate([x, x], axis=0), np.concatenate([z, z], 0)),
            ("stack", ht.stack([x, x], axis=-1), np.stack([z, z], axis=-1)),
            ("flip", ht.flip(x), z[::-1, ::-1]),
            ("roll", ht.roll(x, 3), np.roll(z, 3)),
            # negative axis must resolve against the LOGICAL rank, not the
            # plane view (code-review r5 finding)
            ("roll_neg_axis", ht.roll(x, 1, axis=-1), np.roll(z, 1, axis=-1)),
            ("rot90", ht.rot90(x), np.rot90(z)),
            ("swapaxes", ht.swapaxes(x, 0, 1), np.swapaxes(z, 0, 1)),
            ("copy", ht.copy(x), z),
        ]:
            assert r._is_planar, name
            np.testing.assert_allclose(r.numpy(), oracle, err_msg=name)

    def test_concat_promotes_real_operand(self):
        z = np.outer(np.arange(6) + 1j, np.arange(4) - 2j).astype(np.complex64)
        x = ht.array(z)
        r = ht.concatenate([x, ht.array(z.real)], axis=1)
        assert r._is_planar
        np.testing.assert_allclose(r.numpy(), np.concatenate([z, z.real.astype(np.complex64)], 1))

    def test_squeeze(self):
        z = np.outer(np.arange(6) + 1j, np.arange(4) - 2j).astype(np.complex64)
        r = ht.squeeze(ht.array(z[None]))
        assert r._is_planar
        np.testing.assert_allclose(r.numpy(), z)

    @pytest.mark.parametrize("pair", [(0, None), (None, 0), (0, 1)])
    def test_resplit(self, pair):
        src, dst = pair
        z = np.outer(np.arange(10) + 1j, np.arange(4) - 2j).astype(np.complex64)
        x = ht.array(z, split=src)
        y = x.resplit(dst)
        assert y._is_planar and y.split == dst
        np.testing.assert_allclose(y.numpy(), z)

    def test_astype_roundtrip(self):
        x = _mk(Z1)
        f = x.astype(ht.float32)
        assert not f._is_planar and f.dtype == ht.float32
        np.testing.assert_allclose(f.numpy(), Z1.real)
        c = ht.arange(4, dtype=ht.float32).astype(ht.complex64)
        assert c._is_planar
        np.testing.assert_allclose(c.numpy(), np.arange(4).astype(np.complex64))
        same = x.astype(ht.complex64)
        assert same._is_planar
        np.testing.assert_allclose(same.numpy(), Z1)

    def test_astype_inplace(self):
        x = _mk(Z1)
        x.astype(ht.float32, copy=False)
        assert not x._is_planar and x.dtype == ht.float32
        y = ht.arange(4, dtype=ht.float32)
        y.astype(ht.complex64, copy=False)
        assert y._is_planar and y.dtype == ht.complex64


# --------------------------------------------------------------------- #
# linear algebra: Gauss 3-real-matmul decomposition                     #
# --------------------------------------------------------------------- #
class TestLinalg:
    A = (np.arange(24).reshape(6, 4) / 7 + 1j * np.arange(24)[::-1].reshape(6, 4) / 11).astype(
        np.complex64
    )
    B = (np.arange(20).reshape(4, 5) / 5 - 1j * np.arange(20).reshape(4, 5) / 13).astype(
        np.complex64
    )

    @pytest.mark.parametrize("splits", [(None, None), (0, None), (None, 1), (0, 1)])
    def test_matmul_oracle(self, splits):
        sa, sb = splits
        a = ht.array(self.A, split=sa)
        b = ht.array(self.B, split=sb)
        r = ht.matmul(a, b, precision="highest")
        assert r._is_planar
        np.testing.assert_allclose(r.numpy(), self.A @ self.B, rtol=1e-4, atol=1e-4)

    def test_matmul_operator_and_mixed_real(self):
        a = ht.array(self.A)
        np.testing.assert_allclose(
            (a @ ht.array(self.B)).numpy(), self.A @ self.B, rtol=3e-2, atol=3e-2
        )
        r = ht.matmul(a, ht.array(self.B.real), precision="highest")
        assert r._is_planar
        np.testing.assert_allclose(r.numpy(), self.A @ self.B.real, rtol=1e-4, atol=1e-4)

    def test_matmul_vector_operands(self):
        # code-review r5: 2-D split @ 1-D used to compute out split -1,
        # which the plane view resolves to the PLANE axis
        v = (np.arange(4) - 2j).astype(np.complex64)
        a = ht.array(self.A, split=0)
        r = ht.matmul(a, ht.array(v), precision="highest")
        np.testing.assert_allclose(r.numpy(), self.A @ v, rtol=1e-4)
        r2 = ht.matmul(ht.array(v), ht.array(self.A.T.copy(), split=1), precision="highest")
        np.testing.assert_allclose(r2.numpy(), v @ self.A.T, rtol=1e-4)

    def test_default_precision_is_highest(self):
        """VERDICT r5 live defect (the judge's 64x8 @ 8x16 repro): the
        Gauss decomposition recovers Im(C) by cancellation (P3-P1-P2),
        so default-precision bf16 MXU products turn the imaginary part
        into noise on TPU. Planar matmul must DEFAULT to
        precision="highest" — the default call must match the explicit
        highest-precision call and sit within 2e-3 relative error of the
        numpy oracle."""
        rng = np.random.default_rng(5)
        a = (rng.standard_normal((64, 8)) + 1j * rng.standard_normal((64, 8))).astype(
            np.complex64
        )
        b = (rng.standard_normal((8, 16)) + 1j * rng.standard_normal((8, 16))).astype(
            np.complex64
        )
        for sa, sb in [(None, None), (0, None), (None, 1)]:
            ha, hb = ht.array(a, split=sa), ht.array(b, split=sb)
            default = ht.matmul(ha, hb).numpy()
            oracle = a @ b
            rel = np.abs(default - oracle) / np.maximum(np.abs(oracle), 1e-6)
            assert rel.max() <= 2e-3, f"rel error {rel.max()} (splits {sa},{sb})"
            explicit = ht.matmul(ha, hb, precision="highest").numpy()
            np.testing.assert_array_equal(default, explicit)
        # the operator and 2-D dot route through the same default
        np.testing.assert_allclose(
            (ht.array(a) @ ht.array(b)).numpy(), a @ b, rtol=2e-3, atol=2e-3
        )
        np.testing.assert_allclose(
            ht.dot(ht.array(a), ht.array(b)).numpy(), a @ b, rtol=2e-3, atol=2e-3
        )

    def test_dot_vdot_vecdot_outer(self):
        v = self.A[:, 0]
        w = np.conj(self.A[:, 1])
        hv, hw = ht.array(v), ht.array(w)
        np.testing.assert_allclose(ht.dot(hv, hw).numpy(), np.dot(v, w), rtol=1e-4)
        # vdot conjugates the FIRST operand (dot does not)
        np.testing.assert_allclose(ht.vdot(hv, hw).numpy(), np.vdot(v, w), rtol=1e-4)
        np.testing.assert_allclose(
            ht.vecdot(ht.array(self.A), ht.array(self.A)).numpy(),
            (np.conj(self.A) * self.A).sum(-1),
            rtol=1e-4,
        )
        np.testing.assert_allclose(ht.outer(hv, hw).numpy(), np.outer(v, w), rtol=1e-4)


# --------------------------------------------------------------------- #
# refusals: outside the surface -> actionable error, never wrong math   #
# --------------------------------------------------------------------- #
class TestRefusals:
    def _check(self, fn):
        with pytest.raises((TypeError, NotImplementedError)) as exc:
            fn()
        assert "complex" in str(exc.value) or "planar" in str(exc.value)

    def test_unsupported_ops_raise_actionably(self):
        x = _mk(Z1)
        self._check(lambda: ht.sort(x))
        self._check(lambda: ht.linalg.inv(ht.array(np.outer(Z1, Z2)[:4, :4] + np.eye(4))))
        self._check(lambda: ht.maximum(x, x))
        self._check(lambda: ht.floor(x))

    def test_ordering_comparisons_raise(self):
        x = _mk(Z1)
        self._check(lambda: x < x)
        self._check(lambda: x >= x)

    def test_advanced_indexing_raises(self):
        x = _mk(Z1)
        self._check(lambda: x[ht.array(np.array([True] * 6))])
        self._check(lambda: x[np.array([0, 2])])

    def test_setitem_raises(self):
        x = _mk(Z1)
        with pytest.raises(TypeError):
            x[0] = 1 + 1j

    def test_larray_refused(self):
        x = _mk(Z1)
        with pytest.raises(TypeError):
            x.larray
        with pytest.raises(TypeError):
            x._phys

    def test_message_is_actionable(self):
        with pytest.raises(TypeError) as exc:
            ht.sort(_mk(Z1))
        msg = str(exc.value)
        assert "planar" in msg and "MIGRATING" in msg


# --------------------------------------------------------------------- #
# policy selection                                                      #
# --------------------------------------------------------------------- #
class TestPolicy:
    def test_refuse_mode_still_fails_fast(self):
        ht.use_complex(False)
        with pytest.raises(TypeError) as exc:
            ht.array(Z1)
        assert "use_complex('planar')" in str(exc.value).replace('"', "'")

    def test_native_mode_on_cpu(self):
        ht.use_complex(True)
        x = ht.array(Z1[:3])
        assert not x._is_planar
        np.testing.assert_allclose(ht.conj(x).numpy(), np.conj(Z1[:3]))

    def test_mode_query(self):
        assert devices.complex_mode() == "planar"
        assert not ht.use_complex()  # planar != native
        with pytest.raises(ValueError):
            ht.use_complex("bogus")

    def test_int_flags_normalize(self):
        # 1/0 must behave like True/False (code-review r5 finding)
        ht.use_complex(1)
        assert devices.complex_mode() == "native"
        ht.use_complex(0)
        assert devices.complex_mode() == "refuse"
