"""Manipulations parity tests vs NumPy across splits (reference:
core/tests/test_manipulations.py pattern: iterate splits × shapes)."""

import numpy as np

import heat_tpu as ht

from test_suites.basic_test import TestCase


class TestManipulations(TestCase):
    def setUp(self):
        np.random.seed(0)
        self.d2 = np.random.randn(6, 8).astype(np.float32)
        self.d3 = np.random.randn(4, 6, 5).astype(np.float32)

    def test_concatenate_stack(self):
        d = self.d2
        for sa in (None, 0, 1):
            for sb in (None, 0):
                a = ht.array(d, split=sa)
                b = ht.array(d, split=sb)
                self.assert_array_equal(ht.concatenate([a, b], axis=0), np.concatenate([d, d], 0))
                self.assert_array_equal(ht.concatenate([a, b], axis=1), np.concatenate([d, d], 1))
        a = ht.array(d, split=0)
        st = ht.stack([a, a], axis=0)
        np.testing.assert_allclose(st.numpy(), np.stack([d, d], 0))
        self.assertEqual(st.split, 1)
        self.assert_array_equal(ht.vstack([a, a]), np.vstack([d, d]))
        self.assert_array_equal(ht.hstack([a, a]), np.hstack([d, d]))

    def test_reshape(self):
        d = self.d2
        for split in (None, 0, 1):
            x = ht.array(d, split=split)
            self.assert_array_equal(x.reshape(8, 6), d.reshape(8, 6))
            self.assert_array_equal(x.reshape(-1), d.reshape(-1))
            self.assert_array_equal(x.reshape(2, 2, 12), d.reshape(2, 2, 12))
        # new_split kwarg (reference manipulations.py:1994)
        x = ht.array(d, split=0)
        y = ht.reshape(x, (8, 6), new_split=1)
        self.assertEqual(y.split, 1)
        np.testing.assert_allclose(y.numpy(), d.reshape(8, 6))
        with self.assertRaises(ValueError):
            x.reshape(5, 5)

    def test_sort(self):
        d = self.d2
        for split in (None, 0, 1):
            x = ht.array(d, split=split)
            for axis in (0, 1, -1):
                v, i = ht.sort(x, axis=axis)
                np.testing.assert_allclose(v.numpy(), np.sort(d, axis=axis), rtol=1e-6)
                np.testing.assert_array_equal(i.numpy(), np.argsort(d, axis=axis, kind="stable"))
            v, _ = ht.sort(x, axis=0, descending=True)
            np.testing.assert_allclose(v.numpy(), -np.sort(-d, axis=0), rtol=1e-6)

    def test_unique(self):
        v = np.array([3, 1, 2, 1, 3, 5, 2], dtype=np.int32)
        x = ht.array(v, split=0)
        got = ht.unique(x, sorted=True)
        np.testing.assert_array_equal(got.numpy(), np.unique(v))
        got, inv = ht.unique(x, return_inverse=True)
        np.testing.assert_array_equal(got.numpy()[inv.numpy()], v)

    def test_topk(self):
        d = self.d2
        for split in (None, 0):
            x = ht.array(d, split=split)
            v, i = ht.topk(x, 3, dim=1)
            np.testing.assert_allclose(v.numpy(), -np.sort(-d, axis=1)[:, :3], rtol=1e-6)
            v, i = ht.topk(x, 2, dim=0, largest=False)
            np.testing.assert_allclose(v.numpy(), np.sort(d, axis=0)[:2], rtol=1e-6)

    def test_squeeze_expand(self):
        d = self.d2[:, None, :]
        for split in (None, 0, 2):
            x = ht.array(d, split=split)
            sq = ht.squeeze(x, 1)
            np.testing.assert_allclose(sq.numpy(), d.squeeze(1))
            self.assertEqual(sq.split, None if split is None else (0 if split == 0 else 1))
        x = ht.array(self.d2, split=1)
        ex = ht.expand_dims(x, 0)
        self.assertEqual(ex.split, 2)
        np.testing.assert_allclose(ex.numpy(), self.d2[None])

    def test_pad_roll_flip(self):
        d = self.d2
        for split in (None, 0, 1):
            x = ht.array(d, split=split)
            self.assert_array_equal(
                ht.pad(x, [(1, 2), (0, 1)], constant_values=7.0),
                np.pad(d, [(1, 2), (0, 1)], constant_values=7.0),
            )
            self.assert_array_equal(ht.roll(x, 3, axis=0), np.roll(d, 3, 0))
            self.assert_array_equal(ht.roll(x, (1, -2), axis=(0, 1)), np.roll(d, (1, -2), (0, 1)))
            self.assert_array_equal(ht.flip(x, 1), np.flip(d, 1))
            self.assert_array_equal(ht.fliplr(x), np.fliplr(d))
            self.assert_array_equal(ht.flipud(x), np.flipud(d))

    def test_split_fns(self):
        d = self.d2
        x = ht.array(d, split=0)
        parts = ht.split(x, 2, axis=0)
        self.assertEqual(len(parts), 2)
        np.testing.assert_allclose(parts[0].numpy(), d[:3])
        parts = ht.vsplit(x, [2, 4])
        np.testing.assert_allclose(parts[1].numpy(), d[2:4])
        parts = ht.hsplit(x, 4)
        np.testing.assert_allclose(parts[3].numpy(), d[:, 6:])

    def test_moveaxis_swap_rot(self):
        d = self.d3
        for split in (None, 0, 1, 2):
            x = ht.array(d, split=split)
            self.assert_array_equal(ht.moveaxis(x, 0, 2), np.moveaxis(d, 0, 2))
            self.assert_array_equal(ht.swapaxes(x, 0, 1), np.swapaxes(d, 0, 1))
        x = ht.array(self.d2, split=0)
        self.assert_array_equal(ht.rot90(x), np.rot90(self.d2))

    def test_diag(self):
        v = np.arange(5, dtype=np.float32)
        x = ht.array(v, split=0)
        self.assert_array_equal(ht.diag(x), np.diag(v))
        m = ht.array(self.d2, split=0)
        self.assert_array_equal(ht.diag(m), np.diag(self.d2))
        self.assert_array_equal(ht.diagonal(m, offset=1), np.diagonal(self.d2, offset=1))

    def test_broadcast_tile_repeat(self):
        v = np.arange(6, dtype=np.float32)
        x = ht.array(v, split=0)
        self.assert_array_equal(ht.broadcast_to(x, (4, 6)), np.broadcast_to(v, (4, 6)))
        self.assert_array_equal(ht.tile(x, (2, 3)), np.tile(v, (2, 3)))
        self.assert_array_equal(ht.repeat(x, 3), np.repeat(v, 3))


if __name__ == "__main__":
    import unittest

    unittest.main()
