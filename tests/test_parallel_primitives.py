"""Tests for the explicit SPMD primitives (halo exchange, ring pipeline)
and their consumers (convolve, cdist ring path, get_halo).

Reference behaviors mirrored: DNDarray.get_halo (dndarray.py:386-454),
signal.convolve halo pattern (signal.py:125-127), spatial ring schedule
(distance.py:208-477).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import heat_tpu as ht
from heat_tpu.core import parallel


P = len(jax.devices())


class TestHaloExchange:
    def test_basic_1d(self):
        comm = ht.get_comm()
        n = 4 * P
        phys = comm.shard(jnp.arange(n, dtype=jnp.float32), 0)
        out = parallel.halo_exchange(phys, comm.mesh, comm.axis_name, 0, 1, 1)
        out = np.asarray(jax.device_get(out))
        block = n // P
        ext = block + 2
        for r in range(P):
            seg = out[r * ext : (r + 1) * ext]
            # prev halo
            if r == 0:
                assert seg[0] == 0.0
            else:
                assert seg[0] == r * block - 1
            np.testing.assert_array_equal(seg[1:-1], np.arange(r * block, (r + 1) * block))
            if r == P - 1:
                assert seg[-1] == 0.0
            else:
                assert seg[-1] == (r + 1) * block

    def test_2d_split0_width2(self):
        comm = ht.get_comm()
        rows = 3 * P
        a = jnp.arange(rows * 4, dtype=jnp.float32).reshape(rows, 4)
        phys = comm.shard(a, 0)
        out = np.asarray(jax.device_get(
            parallel.halo_exchange(phys, comm.mesh, comm.axis_name, 0, 2, 2)
        ))
        block, ext = 3, 7
        an = np.asarray(a)
        for r in range(1, P - 1):
            seg = out[r * ext : (r + 1) * ext]
            np.testing.assert_array_equal(seg[:2], an[r * block - 2 : r * block])
            np.testing.assert_array_equal(seg[2:5], an[r * block : (r + 1) * block])
            np.testing.assert_array_equal(seg[5:], an[(r + 1) * block : (r + 1) * block + 2])

    def test_halo_too_large_raises(self):
        comm = ht.get_comm()
        phys = comm.shard(jnp.arange(2 * P, dtype=jnp.float32), 0)
        with pytest.raises(ValueError):
            parallel.halo_exchange(phys, comm.mesh, comm.axis_name, 0, 3, 3)

    def test_prev_only(self):
        comm = ht.get_comm()
        n = 2 * P
        phys = comm.shard(jnp.arange(n, dtype=jnp.float32), 0)
        out = np.asarray(jax.device_get(
            parallel.halo_exchange(phys, comm.mesh, comm.axis_name, 0, 1, 0)
        ))
        assert out.shape[0] == 3 * P
        for r in range(1, P):
            assert out[r * 3] == r * 2 - 1


class TestGetHalo:
    def test_get_halo_views(self):
        x = ht.arange(4 * P, split=0)
        x.get_halo(1)
        hp, hn = x.halo_prev, x.halo_next
        assert hp[0] is None and hn[-1] is None
        block = 4 * P // P
        for r in range(1, P):
            assert int(np.asarray(hp[r])[0]) == r * block - 1
        for r in range(P - 1):
            assert int(np.asarray(hn[r])[0]) == (r + 1) * block

    def test_array_with_halos_shape(self):
        x = ht.arange(4 * P, split=0)
        x.get_halo(2)
        awh = x.array_with_halos
        assert awh.shape[0] == (4 + 4) * P  # block 4 + 2 + 2 per shard

    def test_zero_halo_is_identity(self):
        x = ht.arange(4 * P, split=0)
        x.get_halo(0)
        assert x.array_with_halos.shape == x._phys.shape


class TestDistributedConvolve:
    @pytest.mark.parametrize("mode", ["full", "same", "valid"])
    @pytest.mark.parametrize("n,k", [(64, 3), (61, 5), (40, 7), (17, 3)])
    def test_matches_numpy(self, mode, n, k):
        if mode == "same" and k % 2 == 0:
            pytest.skip("even kernel invalid for same")
        rng = np.random.default_rng(n * 100 + k)
        a_np = rng.normal(size=n).astype(np.float32)
        v_np = rng.normal(size=k).astype(np.float32)
        a = ht.array(a_np, split=0)
        v = ht.array(v_np)
        out = ht.convolve(a, v, mode=mode)
        ref = np.convolve(a_np, v_np, mode=mode)
        assert out.split == 0
        np.testing.assert_allclose(out.numpy(), ref, rtol=1e-4, atol=1e-4)

    def test_int_kernel_exact(self):
        a = ht.arange(5 * P, split=0)
        v = ht.array(np.array([1, 2, 1]))
        out = ht.convolve(a, v, mode="full")
        ref = np.convolve(np.arange(5 * P), [1, 2, 1], mode="full")
        np.testing.assert_array_equal(out.numpy(), ref)

    def test_kernel_larger_than_block_falls_back(self):
        # k-1 > block: the shard_map stencil can't run; global path must
        # still give the right answer
        n = 2 * P
        a_np = np.arange(n, dtype=np.float32)
        v_np = np.ones(n - 1, dtype=np.float32)
        out = ht.convolve(ht.array(a_np, split=0), ht.array(v_np), mode="full")
        np.testing.assert_allclose(out.numpy(), np.convolve(a_np, v_np, "full"), rtol=1e-5)

    def test_replicated_unchanged(self):
        a_np = np.arange(20, dtype=np.float32)
        out = ht.convolve(ht.array(a_np), ht.array(np.ones(3, np.float32)), mode="same")
        np.testing.assert_allclose(out.numpy(), np.convolve(a_np, np.ones(3), "same"), rtol=1e-5)
        assert out.split is None


class TestRingPairwise:
    def _ref_cdist(self, x, y):
        from scipy.spatial.distance import cdist as scdist

        return scdist(x, y)

    @pytest.mark.parametrize("nx,ny", [(4 * P, 4 * P), (3 * P + 1, 2 * P + 3)])
    def test_ring_cdist_xy(self, nx, ny):
        rng = np.random.default_rng(7)
        x_np = rng.normal(size=(nx, 5)).astype(np.float32)
        y_np = rng.normal(size=(ny, 5)).astype(np.float32)
        X = ht.array(x_np, split=0)
        Y = ht.array(y_np, split=0)
        d_ring = ht.spatial.cdist(X, Y, ring=True)
        d_gspmd = ht.spatial.cdist(X, Y)
        assert d_ring.split == 0
        np.testing.assert_allclose(d_ring.numpy(), d_gspmd.numpy(), rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(d_ring.numpy(), self._ref_cdist(x_np, y_np), rtol=1e-3, atol=1e-3)

    def test_ring_symmetric_half(self):
        rng = np.random.default_rng(3)
        x_np = rng.normal(size=(3 * P + 2, 4)).astype(np.float32)
        X = ht.array(x_np, split=0)
        d = ht.spatial.cdist(X, ring=True)
        np.testing.assert_allclose(d.numpy(), self._ref_cdist(x_np, x_np), rtol=1e-3, atol=1e-3)

    def test_ring_quadratic_expansion(self):
        rng = np.random.default_rng(11)
        x_np = rng.normal(size=(2 * P, 6)).astype(np.float32)
        X = ht.array(x_np, split=0)
        d = ht.spatial.cdist(X, quadratic_expansion=True, ring=True)
        # atol: the expansion's catastrophic cancellation at d≈0 leaves a
        # sqrt(eps)·‖x‖ residue (~1.4e-3 here) whose exact size depends
        # on the backend's dot accumulation order
        np.testing.assert_allclose(d.numpy(), self._ref_cdist(x_np, x_np), rtol=1e-3, atol=3e-3)

    def test_ring_manhattan(self):
        from scipy.spatial.distance import cdist as scdist

        rng = np.random.default_rng(5)
        x_np = rng.normal(size=(2 * P + 1, 3)).astype(np.float32)
        y_np = rng.normal(size=(P + 2, 3)).astype(np.float32)
        d = ht.spatial.manhattan(ht.array(x_np, split=0), ht.array(y_np, split=0), ring=True)
        np.testing.assert_allclose(
            d.numpy(), scdist(x_np, y_np, metric="cityblock"), rtol=1e-3, atol=1e-3
        )

    def test_ring_rbf(self):
        rng = np.random.default_rng(9)
        x_np = rng.normal(size=(2 * P, 3)).astype(np.float32)
        X = ht.array(x_np, split=0)
        r = ht.spatial.rbf(X, sigma=2.0, ring=True)
        d2 = self._ref_cdist(x_np, x_np) ** 2
        np.testing.assert_allclose(r.numpy(), np.exp(-d2 / 8.0), rtol=1e-3, atol=1e-3)
        # pad region of the physical array must stay zero (exp(0)=1 trap)
        phys = np.asarray(jax.device_get(r._phys))
        n = x_np.shape[0]
        if phys.shape[0] > n:
            np.testing.assert_array_equal(phys[n:], 0.0)

    def test_ring_replicated_falls_back(self):
        rng = np.random.default_rng(1)
        x_np = rng.normal(size=(10, 3)).astype(np.float32)
        X = ht.array(x_np)  # replicated, no ring possible
        d = ht.spatial.cdist(X, ring=True)
        np.testing.assert_allclose(d.numpy(), self._ref_cdist(x_np, x_np), rtol=1e-3, atol=1e-3)


class TestDistributedSort:
    """Gather-free split-axis sort (core.parallel.distributed_sort) — the
    explicit-SPMD replacement for the reference's sample-sort + Alltoallv
    (manipulations.py:2428)."""

    @pytest.mark.parametrize("n", [8 * P, 8 * P - 3, P, 5])
    def test_matches_numpy_1d(self, n):
        rng = np.random.default_rng(n)
        x = rng.standard_normal(n).astype(np.float32)
        a = ht.array(x, split=0)
        v, i = ht.sort(a)
        np.testing.assert_allclose(v.numpy(), np.sort(x), rtol=1e-6)
        np.testing.assert_allclose(x[i.numpy()], np.sort(x), rtol=1e-6)
        vd, _ = ht.sort(a, descending=True)
        np.testing.assert_allclose(vd.numpy(), np.sort(x)[::-1], rtol=1e-6)

    def test_2d_split_axis_lanes(self):
        rng = np.random.default_rng(0)
        x = rng.integers(-9, 9, size=(4 * P + 1, 5)).astype(np.int32)
        a = ht.array(x, split=0)
        v, i = ht.sort(a, axis=0)
        np.testing.assert_array_equal(v.numpy(), np.sort(x, axis=0))
        np.testing.assert_array_equal(
            np.take_along_axis(x, i.numpy(), axis=0), np.sort(x, axis=0)
        )

    def test_duplicates_exact_multiset(self):
        # ties at shard boundaries: the composite (value, position) key must
        # neither drop nor duplicate elements
        x = np.tile(np.arange(3, dtype=np.float32), 5 * P)
        a = ht.array(x, split=0)
        v, _ = ht.sort(a)
        np.testing.assert_array_equal(v.numpy(), np.sort(x))

    def test_nan_and_inf_ordering(self):
        x = np.array([3.0, np.nan, -np.inf, 1.0, np.inf, np.nan, 0.0, -1.0, 2.0, 5.0, -2.0],
                     dtype=np.float64)
        a = ht.array(x, split=0)
        v, _ = ht.sort(a)
        ref = np.sort(x)
        np.testing.assert_array_equal(np.isnan(v.numpy()), np.isnan(ref))
        np.testing.assert_allclose(v.numpy()[~np.isnan(ref)], ref[~np.isnan(ref)])

    def test_pad_invariant_restored(self):
        n = 8 * P - 3
        x = np.random.default_rng(7).standard_normal(n).astype(np.float32) + 100.0
        v, i = ht.sort(ht.array(x, split=0))
        phys = np.asarray(jax.device_get(v._phys))
        np.testing.assert_array_equal(phys[n:], 0.0)

    @pytest.mark.skipif(P < 2, reason="needs a real mesh")
    def test_no_allgather_in_hlo(self):
        # VERDICT r1 item 8 done-criterion: the compiled program must move
        # data by collective-permute, never by full gather
        from heat_tpu.core.parallel import _oddeven_sort_program

        comm = ht.get_comm()
        prog = _oddeven_sort_program(comm.mesh, comm.axis_name, 1, 0, "int32")
        phys = comm.shard(jnp.arange(8.0 * P, dtype=jnp.float32), 0)
        txt = prog.lower(phys).compile().as_text()
        assert "all-gather" not in txt
        assert "all-to-all" not in txt
        assert "collective-permute" in txt


class TestColumnsort:
    """Leighton columnsort (VERDICT r4 #2) — the O(1)-collective-round
    large-shard sort. Parity must hold on exactly the inputs where a
    sample sort's splitter scheme degenerates (pre-sorted, constant,
    few-unique), and the compiled program must show a p-independent
    collective structure: 2 all-to-alls per operand, 2 half-shard
    permutes, no rounds growing with p."""

    @pytest.mark.parametrize(
        "kind", ["random", "sorted", "reverse", "const", "fewuniq"]
    )
    def test_matches_numpy_stable_argsort(self, kind):
        from heat_tpu.core import parallel as par

        n = 4 * P * P * P  # B=4P² ≥ 2(P-1)² and P|B at every mesh size
        assert par._columnsort_applicable(P, n // P) or P <= 2
        seeds = {"random": 0, "sorted": 1, "reverse": 2, "const": 3, "fewuniq": 4}
        rng = np.random.default_rng(seeds[kind])
        if kind == "random":
            x = rng.standard_normal(n).astype(np.float32)
        elif kind == "sorted":
            x = np.sort(rng.standard_normal(n).astype(np.float32))
        elif kind == "reverse":
            x = np.sort(rng.standard_normal(n).astype(np.float32))[::-1].copy()
        elif kind == "const":
            x = np.zeros(n, np.float32)
        else:
            x = rng.integers(0, 5, n).astype(np.float32)
        v, i = ht.sort(ht.array(x, split=0))
        np.testing.assert_array_equal(v.numpy(), np.sort(x, kind="stable"))
        np.testing.assert_array_equal(i.numpy(), np.argsort(x, kind="stable"))

    def test_uneven_extent_pads_sink(self):
        n = 4 * P * P * P - 3  # phys pads to B=4P²; sentinels must stay at tail
        x = np.random.default_rng(5).standard_normal(n).astype(np.float32)
        v, i = ht.sort(ht.array(x, split=0))
        np.testing.assert_array_equal(v.numpy(), np.sort(x, kind="stable"))
        np.testing.assert_array_equal(i.numpy(), np.argsort(x, kind="stable"))

    def test_2d_lanes_large(self):
        x = np.random.default_rng(6).standard_normal((4 * P * P * P, 4)).astype(np.float32)
        v, i = ht.sort(ht.array(x, split=0), axis=0)
        np.testing.assert_array_equal(v.numpy(), np.sort(x, axis=0, kind="stable"))
        np.testing.assert_array_equal(i.numpy(), np.argsort(x, axis=0, kind="stable"))

    def test_small_shards_fall_back_to_oddeven(self):
        from heat_tpu.core import parallel as par

        # below Leighton's bound columnsort is invalid; the gate must
        # route around it (and the result must still be right)
        assert not par._columnsort_applicable(P, 8)
        n = 8 * P
        x = np.random.default_rng(7).standard_normal(n).astype(np.float32)
        v, _ = ht.sort(ht.array(x, split=0))
        np.testing.assert_array_equal(v.numpy(), np.sort(x, kind="stable"))

    @pytest.mark.skipif(P <= 2, reason="columnsort gates to p > 2")
    def test_hlo_constant_collective_rounds(self):
        # VERDICT r4 #2 done-criterion: O(1) all-to-all rounds instead of
        # p permute rounds; no gather
        from heat_tpu.core.parallel import _columnsort_program

        comm = ht.get_comm()
        for idx_dtype, per_op in (("int32", 2), (None, 1)):
            prog = _columnsort_program(comm.mesh, comm.axis_name, 1, 0, idx_dtype)
            phys = comm.shard(jnp.arange(4.0 * P * P * P, dtype=jnp.float32), 0)
            txt = prog.lower(phys).compile().as_text()
            n_a2a = txt.count(" all-to-all(") + txt.count("all-to-all-start(")
            n_pp = txt.count(" collective-permute(") + txt.count(
                "collective-permute-start("
            )
            assert n_a2a == 2 * per_op, f"{idx_dtype}: {n_a2a} all-to-alls"
            assert n_pp == 2 * per_op, f"{idx_dtype}: {n_pp} ppermutes"
            assert "all-gather" not in txt
            assert "all-reduce(" not in txt


class TestDistributedPercentile:
    @pytest.mark.parametrize("n", [8 * P, 8 * P - 5])
    @pytest.mark.parametrize("method", ["linear", "lower", "higher", "midpoint", "nearest"])
    def test_methods_match_numpy(self, n, method):
        rng = np.random.default_rng(n)
        x = rng.standard_normal(n).astype(np.float64)
        a = ht.array(x, split=0)
        got = ht.percentile(a, 30.0, axis=0, interpolation=method).numpy()
        np.testing.assert_allclose(got, np.percentile(x, 30.0, method=method), rtol=1e-6)

    def test_vector_q_and_keepdims(self):
        rng = np.random.default_rng(3)
        x = rng.standard_normal((5 * P + 1, 6))
        a = ht.array(x, split=0)
        np.testing.assert_allclose(
            ht.percentile(a, [25.0, 75.0], axis=0).numpy(),
            np.percentile(x, [25, 75], axis=0),
            rtol=1e-6,
        )
        assert ht.percentile(a, 25.0, axis=0, keepdims=True).numpy().shape == (1, 6)
        np.testing.assert_allclose(
            ht.median(a, axis=0).numpy(), np.median(x, axis=0), rtol=1e-6
        )


class TestReviewRegressions:
    """Regression tests for round-2 review findings."""

    def test_convolve_same_even_kernel_after_swap(self):
        # operand swap can make the kernel even though even kernels were
        # rejected pre-swap; the distributed path must match numpy 'same'
        small = np.arange(1, 5, dtype=np.float32)
        big = np.arange(4 * P + 1, dtype=np.float32)
        got = ht.convolve(ht.array(small), ht.array(big, split=0), mode="same")
        np.testing.assert_allclose(got.numpy(), np.convolve(small, big, mode="same"), rtol=1e-5)

    def test_percentile_nan_propagates(self):
        x = np.array([1.0, np.nan, 3.0, 2.0] * (2 * P))
        a = ht.array(x, split=0)
        assert np.isnan(float(ht.percentile(a, 50.0, axis=0)))
        got = ht.percentile(a, [25.0, 75.0], axis=0).numpy()
        assert np.all(np.isnan(got))

    def test_percentile_keepdims_axis_none(self):
        a = ht.array(np.arange(16.0), split=0)
        assert ht.percentile(a, 30.0, keepdims=True).numpy().shape == (1,)

    def test_halo_cache_invalidated_on_rebind(self):
        x = ht.arange(2 * P, split=0, dtype=ht.float32)
        x.get_halo(1)
        x.larray = np.arange(100.0, 100.0 + 2 * P).astype(np.float32)
        fresh = np.asarray(jax.device_get(x.array_with_halos))
        assert fresh.max() >= 100.0

    def test_percentile_q_out_of_range_raises(self):
        a = ht.array(np.arange(16.0), split=0)
        with pytest.raises(ValueError):
            ht.percentile(a, -5.0, axis=0)
        with pytest.raises(ValueError):
            ht.percentile(a, 150.0, axis=0)
        b = ht.array(np.arange(16.0))  # replicated path: same contract
        with pytest.raises(ValueError):
            ht.percentile(b, -5.0, axis=0)

    def test_values_only_sort_matches(self):
        from heat_tpu.core import manipulations

        rng = np.random.default_rng(11)
        x = rng.standard_normal(8 * P - 3).astype(np.float32)
        a = ht.array(x, split=0)
        sv = manipulations._sorted_values(a, 0)
        np.testing.assert_allclose(sv.numpy(), np.sort(x), rtol=1e-6)
        # ties must partition exactly (rank-order concat + stable sort)
        x = np.tile(np.arange(3, dtype=np.float32), 5 * P)
        sv = manipulations._sorted_values(ht.array(x, split=0), 0)
        np.testing.assert_array_equal(sv.numpy(), np.sort(x))

    def test_binary_mismatched_split_broadcast(self):
        # operand split maps to a non-dominant output axis and cannot be
        # resplit: must feed the logical view, not the padded physical
        a = ht.array(np.ones((6, 5), dtype=np.float32), split=0)
        b = ht.array(np.arange(5.0, dtype=np.float32), split=0)
        np.testing.assert_allclose((a + b).numpy(), np.ones((6, 5)) + np.arange(5.0))

    def test_binary_extent1_split_operand(self):
        c = ht.array(np.array([3.0], dtype=np.float32), split=0)
        r = c + 1.0
        assert r.numpy().shape == (1,)
        assert float(r.numpy()[0]) == 4.0


class TestCollectiveStructure:
    """Pin the ICI traffic shape of the flagship distributed ops (VERDICT
    r3 next-step 6): the analytic cost model in docs/PERF.md claims TSQR
    moves exactly one p*K^2 R-factor all-gather, ring attention moves two
    collective-permutes (K and V) per program, and the hSVD level-0 block
    SVD moves nothing. These assertions make the model checkable."""

    @pytest.mark.skipif(P < 2, reason="needs a real mesh")
    def test_ring_attention_two_ppermutes_no_gather(self):
        from heat_tpu.nn.attention import _ring_attention_program

        comm = ht.get_comm()
        S, D = 16 * P, 16
        prog = _ring_attention_program(
            comm.mesh, comm.axis_name, 4, 2, S, S, True, D ** -0.5, "float32"
        )
        q = comm.shard(jnp.ones((1, 2, S, D), jnp.float32), 2)
        txt = prog.lower(q, q, q).compile().as_text()

        def count(op):
            return txt.count(f" {op}(") + txt.count(f"{op}-start(")

        assert count("collective-permute") == 2  # K and V ring rotations
        assert count("all-gather") == 0          # K/V are never gathered
        assert count("all-to-all") == 0
        assert count("all-reduce") == 0          # softmax stats stay local

    @pytest.mark.skipif(P < 2, reason="needs a real mesh")
    def test_hsvd_level0_no_collectives(self):
        from heat_tpu.core.linalg.svdtools import _local_svd_fn

        comm = ht.get_comm()
        m, n = 64, 16 * P
        phys = comm.shard(jnp.ones((m, n), jnp.float32), 1)
        fn = _local_svd_fn(
            comm.mesh, comm.axis_name, m, phys.shape[1] // P, 10, "float32", None
        )
        txt = fn.lower(phys).compile().as_text()
        for op in ("all-gather", "all-reduce", "collective-permute", "all-to-all"):
            assert txt.count(f" {op}(") + txt.count(f"{op}-start(") == 0, op

    @pytest.mark.skipif(P < 2, reason="needs a real mesh")
    @pytest.mark.skipif(
        __import__("heat_tpu.core.linalg.qr", fromlist=["_tsqr_group_size"])._tsqr_group_size(P) > 1 and P >= 16,
        reason="composite p >= 16 takes the two-level tree (test_tsqr_two_level)",
    )
    def test_tsqr_single_rfactor_allgather(self):
        import re

        from heat_tpu.core.linalg.qr import _tsqr_fn

        comm = ht.get_comm()
        m, K = 32 * P, 3 * P  # stacked-factor geometry (K = p*r, small)
        phys = comm.shard(jnp.ones((m, K), jnp.float32), 0)
        fn = _tsqr_fn(comm.mesh, comm.axis_name, phys.shape[0] // P, K, "float32", True)
        txt = fn.lower(phys).compile().as_text()
        ag_lines = [
            l for l in txt.splitlines() if " all-gather(" in l or "all-gather-start(" in l
        ]
        assert len(ag_lines) == 1  # exactly the R-factor merge
        shape = re.search(r"f32\[([\d,]+)\]", ag_lines[0]).group(1)
        elems = int(np.prod([int(s) for s in shape.split(",")]))
        assert elems == P * K * K  # p*K^2 floats over ICI — never the operand
        assert txt.count(" all-to-all(") == 0
