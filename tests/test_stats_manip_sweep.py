"""Breadth sweep for statistics and manipulations: every op against its
numpy/scipy oracle across splits and uneven extents (the reference's
test_statistics.py / test_manipulations.py coverage shape)."""

import numpy as np
import pytest
from scipy import stats as sps

import heat_tpu as ht

_RNG = np.random.default_rng(7)
_D = _RNG.standard_normal((13, 6)).astype(np.float32)  # uneven rows on 8 devs
_V = _RNG.standard_normal(45).astype(np.float32)

_SPLITS_2D = [None, 0, 1]


class TestStatisticsSweep:
    @pytest.mark.parametrize("split", _SPLITS_2D)
    @pytest.mark.parametrize("axis", [None, 0, 1])
    def test_var_std_ddof(self, split, axis):
        x = ht.array(_D, split=split)
        for ddof in (0, 1):
            np.testing.assert_allclose(
                np.asarray(ht.var(x, axis=axis, ddof=ddof).numpy()),
                _D.var(axis=axis, ddof=ddof),
                rtol=2e-4, atol=2e-5,
            )
            np.testing.assert_allclose(
                np.asarray(ht.std(x, axis=axis, ddof=ddof).numpy()),
                _D.std(axis=axis, ddof=ddof),
                rtol=2e-4, atol=2e-5,
            )

    @pytest.mark.parametrize("split", [None, 0])
    def test_skew_kurtosis_vs_scipy(self, split):
        v = ht.array(_V, split=split)
        np.testing.assert_allclose(
            float(ht.skew(v, unbiased=False)), sps.skew(_V, bias=True), rtol=1e-3
        )
        np.testing.assert_allclose(
            float(ht.kurtosis(v, unbiased=False)),
            sps.kurtosis(_V, fisher=True, bias=True),
            rtol=1e-3, atol=1e-3,
        )

    @pytest.mark.parametrize("split", _SPLITS_2D)
    def test_cov(self, split):
        x = ht.array(_D, split=split)
        np.testing.assert_allclose(
            ht.cov(x).numpy(), np.cov(_D), rtol=1e-4, atol=1e-5
        )

    @pytest.mark.parametrize("split", [None, 0])
    def test_histogram_bincount(self, split):
        v = ht.array(_V, split=split)
        hist, edges = ht.histogram(v, bins=7)
        ref_h, ref_e = np.histogram(_V, bins=7)
        np.testing.assert_array_equal(np.asarray(hist.numpy()), ref_h)
        np.testing.assert_allclose(np.asarray(edges.numpy()), ref_e, rtol=1e-5)
        iv = np.abs((_V * 3).astype(np.int32))
        np.testing.assert_array_equal(
            ht.bincount(ht.array(iv, split=split)).numpy(), np.bincount(iv)
        )

    @pytest.mark.parametrize("split", [None, 0])
    def test_digitize_bucketize(self, split):
        v = ht.array(_V, split=split)
        bins = np.array([-1.0, 0.0, 1.0], dtype=np.float32)
        np.testing.assert_array_equal(
            ht.digitize(v, bins).numpy(), np.digitize(_V, bins)
        )

    @pytest.mark.parametrize("split", _SPLITS_2D)
    @pytest.mark.parametrize("axis", [None, 0, 1])
    def test_argmax_argmin(self, split, axis):
        x = ht.array(_D, split=split)
        np.testing.assert_array_equal(
            np.asarray(ht.argmax(x, axis=axis).numpy()), _D.argmax(axis=axis)
        )
        np.testing.assert_array_equal(
            np.asarray(ht.argmin(x, axis=axis).numpy()), _D.argmin(axis=axis)
        )

    @pytest.mark.parametrize("split", [None, 0])
    def test_maximum_minimum_elementwise(self, split):
        a = ht.array(_D, split=split)
        b = ht.array(_D[::-1].copy(), split=split)
        np.testing.assert_array_equal(
            ht.maximum(a, b).numpy(), np.maximum(_D, _D[::-1])
        )
        np.testing.assert_array_equal(
            ht.minimum(a, b).numpy(), np.minimum(_D, _D[::-1])
        )


class TestManipulationsSweep:
    @pytest.mark.parametrize("split", _SPLITS_2D)
    def test_roll(self, split):
        x = ht.array(_D, split=split)
        for shift, axis in ((3, 0), (-2, 1), (5, None)):
            np.testing.assert_array_equal(
                ht.roll(x, shift, axis=axis).numpy(), np.roll(_D, shift, axis=axis)
            )

    @pytest.mark.parametrize("split", _SPLITS_2D)
    def test_pad(self, split):
        x = ht.array(_D, split=split)
        np.testing.assert_array_equal(
            ht.pad(x, ((1, 2), (0, 3))).numpy(), np.pad(_D, ((1, 2), (0, 3)))
        )

    @pytest.mark.parametrize("split", [None, 0])
    def test_unique_sorted(self, split):
        v = np.tile(np.arange(5, dtype=np.float32), 9)
        x = ht.array(v, split=split)
        got = ht.unique(x, sorted=True)
        # order matters: sorted=True must return the ascending uniques
        np.testing.assert_array_equal(got.numpy(), np.unique(v))

    @pytest.mark.parametrize("split", _SPLITS_2D)
    def test_moveaxis_swapaxes_rot90(self, split):
        x = ht.array(_D, split=split)
        np.testing.assert_array_equal(
            ht.moveaxis(x, 0, 1).numpy(), np.moveaxis(_D, 0, 1)
        )
        np.testing.assert_array_equal(
            ht.swapaxes(x, 0, 1).numpy(), np.swapaxes(_D, 0, 1)
        )
        np.testing.assert_array_equal(ht.rot90(x).numpy(), np.rot90(_D))

    @pytest.mark.parametrize("split", _SPLITS_2D)
    def test_stack_family(self, split):
        x = ht.array(_D, split=split)
        np.testing.assert_array_equal(
            ht.stack([x, x], axis=0).numpy(), np.stack([_D, _D], axis=0)
        )
        np.testing.assert_array_equal(ht.vstack([x, x]).numpy(), np.vstack([_D, _D]))
        np.testing.assert_array_equal(ht.hstack([x, x]).numpy(), np.hstack([_D, _D]))
        np.testing.assert_array_equal(
            ht.column_stack([x, x]).numpy(), np.column_stack([_D, _D])
        )

    @pytest.mark.parametrize("split", _SPLITS_2D)
    def test_tile_repeat(self, split):
        x = ht.array(_D, split=split)
        np.testing.assert_array_equal(ht.tile(x, (2, 2)).numpy(), np.tile(_D, (2, 2)))
        np.testing.assert_array_equal(ht.repeat(x, 2).numpy(), np.repeat(_D, 2))

    @pytest.mark.parametrize("split", _SPLITS_2D)
    @pytest.mark.parametrize("new_split", [None, 0, 1])
    def test_reshape_split_matrix(self, split, new_split):
        x = ht.array(_D[:12], split=split)  # 12x6 → 8x9
        got = ht.reshape(x, (8, 9), new_split=new_split)
        np.testing.assert_array_equal(got.numpy(), _D[:12].reshape(8, 9))
        if new_split is not None:
            assert got.split == new_split

    @pytest.mark.parametrize("split", [None, 0])
    def test_flatten_ravel(self, split):
        x = ht.array(_D, split=split)
        np.testing.assert_array_equal(ht.flatten(x).numpy(), _D.ravel())
        np.testing.assert_array_equal(ht.ravel(x).numpy(), _D.ravel())

    @pytest.mark.parametrize("split", [None, 0, 1])
    def test_concatenate_mixed_splits(self, split):
        x = ht.array(_D, split=split)
        y = ht.array(_D, split=0)
        np.testing.assert_array_equal(
            ht.concatenate([x, y], axis=0).numpy(), np.concatenate([_D, _D], 0)
        )


class TestReshapeEdges:
    def test_empty_array_reshape(self):
        x = ht.array(np.empty((0, 4), np.float32), split=0)
        r = x.reshape(0, 2, 2)
        assert r.shape == (0, 2, 2)
        assert r.numpy().shape == (0, 2, 2)

    @pytest.mark.parametrize("target,ns", [((4, 0), 0), ((0, 8), 1), ((2, 0, 2), 0)])
    def test_empty_reshape_any_split(self, target, ns):
        x = ht.array(np.empty((0, 4), np.float32), split=0)
        r = ht.reshape(x, target, new_split=ns)
        assert r.shape == target
