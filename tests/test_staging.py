"""One memory-tier cost lattice + out-of-core staging (ISSUE 11).

The contract pinned here, five ways:

1. **Lattice** (``ht.core.tiers``) — the refactor re-derived, not
   re-tuned: every constant the former call sites used comes back
   identical (ICI 200e9 / DCN 25e9 / ``penalty("dcn")`` == the old
   ``DCN_PENALTY`` == 8; ``capacity("hbm")`` IS memcheck's SL301
   budget, same env, same parsing), ``transfer_time`` reproduces the
   old ``tier_time_model`` arithmetic, and planning is byte-identical
   under every ``HEAT_TPU_OOC`` value (the gate touches execution,
   never plans).
2. **Staged plans** — the ``host-staging`` golden matrix verifies clean
   (Schedule and JSON forms), windows are grain-aligned multiples of
   (512, 512) except the global tail, every pass conserves the operand
   exactly, the depth-2 slab occupancy accounting is the
   window+prefetch recompute, ``prove_fits`` holds the liveness peak
   under ``tiers.capacity("hbm")`` — and each mutation class is caught
   by ``verify_plan`` with its invariant named.
3. **Bit-identity** — staged ``hsvd_rank`` (2-pass AND 1-pass) over a
   host-resident operand spanning MANY windows returns factors AND
   error estimate bit-identical to the in-HBM path on a fitting twin
   (the fixed-grain tiled streams construction), including on the
   5-device odd mesh; an operand ≥ 2× a (simulated, env-pinned) HBM
   capacity stages and still matches the twin bitwise; the
   ``HEAT_TPU_OOC=0`` escape hatch materializes and matches bitwise;
   forced ``=1`` on device operands matches the gate-off run bitwise.
4. **Streaming KMeans** — ``partial_fit`` reproduces the running-mean
   oracle exactly, a ``fit(HostArray)`` epoch equals the manual
   window-by-window ``partial_fit`` sequence bit-for-bit, and the
   escape hatch runs exact Lloyd.
5. **Gather-free unique(axis=)** (the VERDICT-backlog satellite) — the
   sorted-split rows formulation matches the numpy oracle (values,
   inverse, axis≠0, bool/int dtypes, NaN-row collapse under the
   framework's flat-unique tie semantics) and its census is pinned:
   the per-shard program launches ZERO collectives and the merge
   gathers only the candidate prefixes.
"""

import json

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import heat_tpu as ht

import importlib

from heat_tpu.analysis.planverify import PlanVerificationError, verify_plan
from heat_tpu.core import tiers

# the module is shadowed by the identically-named function in the
# package namespace (same gotcha as core.jit)
memcheck = importlib.import_module("heat_tpu.analysis.memcheck")
from heat_tpu.core.linalg import svdtools
from heat_tpu.redistribution import planner, staging
from heat_tpu.redistribution.schedule import Schedule, Step
from heat_tpu.redistribution.spec import RedistSpec

from test_suites.basic_test import TestCase, env_pin

P = len(jax.devices())


def _rand(shape, seed=0, dtype=np.float32):
    rng = np.random.default_rng(seed)
    lowrank = rng.standard_normal((shape[0], 12)) @ rng.standard_normal((12, shape[1]))
    return (lowrank + 0.01 * rng.standard_normal(shape)).astype(dtype)


def _bits(a):
    return np.asarray(a.larray if hasattr(a, "larray") else a)


# --------------------------------------------------------------------- #
# 1. the lattice                                                        #
# --------------------------------------------------------------------- #
class TestTierLattice(TestCase):
    def test_constants_identical_to_pre_lattice_call_sites(self):
        from heat_tpu.core import communication as comm

        self.assertEqual(tiers.ICI_BPS, 200e9)
        self.assertEqual(tiers.DCN_BPS, 25e9)
        self.assertEqual(comm.ICI_BPS, tiers.ICI_BPS)
        self.assertEqual(comm.DCN_BPS, tiers.DCN_BPS)
        self.assertEqual(comm.DCN_PENALTY, 8)
        self.assertEqual(tiers.penalty("dcn"), comm.DCN_PENALTY)
        self.assertEqual(tiers.penalty("ici"), 1)
        self.assertEqual(tiers.penalty("pcie"), int(200e9 / 16e9))

    def test_capacity_is_the_sl301_budget(self):
        self.assertEqual(tiers.capacity("hbm"), memcheck.hbm_budget_bytes())
        self.assertEqual(tiers.DEFAULT_HBM_BYTES, memcheck.DEFAULT_HBM_BYTES)
        self.assertEqual(tiers.HBM_ENV, memcheck.HBM_ENV)
        with env_pin(tiers.HBM_ENV, str(123 << 20)):
            self.assertEqual(tiers.capacity("hbm"), 123 << 20)
            self.assertEqual(memcheck.hbm_budget_bytes(), 123 << 20)
        with env_pin(tiers.HBM_ENV, "not-a-number"):
            # the exact fallback semantics hbm_budget_bytes always had
            self.assertEqual(tiers.capacity("hbm"), tiers.DEFAULT_HBM_BYTES)

    def test_wire_tiers_hold_no_operands(self):
        with self.assertRaises(ValueError):
            tiers.capacity("ici")
        with self.assertRaises(ValueError):
            tiers.bandwidth("hbm2")

    def test_transfer_time_and_edges(self):
        self.assertEqual(tiers.transfer_time(200e9, "ici"), 1.0)
        self.assertEqual(tiers.transfer_time(16e9, "pcie"), 1.0)
        self.assertEqual(tiers.edge_between("hbm", "host"), "pcie")
        self.assertEqual(tiers.edge_between("vmem", "hbm"), "hbm")
        self.assertIsNone(tiers.edge_between("vmem", "host"))
        self.assertIn("pcie", tiers.describe())

    def test_tier_time_model_arithmetic_unchanged(self):
        # the old hand-rolled arithmetic: bytes/ICI_BPS + bytes/DCN_BPS
        spec = RedistSpec.normalize((1024, 1024), "float32", 0, 1, 8)
        sched = planner.plan(spec, 256 << 20, quant="0", topology="2x4")
        tm = planner.tier_time_model(sched)
        tb = sched.tier_bytes()
        self.assertEqual(tm["ici_s"], tb["ici"] / 200e9)
        self.assertEqual(tm["dcn_s"], tb["dcn"] / 25e9)
        self.assertEqual(tm["total_s"], tm["ici_s"] + tm["dcn_s"])
        self.assertNotIn("pcie_s", tm)

    def test_planning_is_ooc_gate_independent(self):
        spec = RedistSpec.normalize((1000, 250000), "float32", 1, 1, 8,
                                    reshape_to=(10_000_000, 25))
        ref = None
        for mode in (None, "0", "1", "auto"):
            with env_pin(staging.OOC_ENV, mode):
                planner.clear_plan_cache()
                js = planner.plan(spec, 256 << 20, quant="0", topology="flat").canonical_json()
            ref = ref or js
            self.assertEqual(js, ref)
        planner.clear_plan_cache()

    def test_staged_plan_model_rides_the_lattice(self):
        sched = staging.plan_staged_passes(
            (65536, 81920), "float32",
            [{"tag": "sketch", "axis": 1}, {"tag": "project", "axis": 0}],
            slab=staging.DEFAULT_SLAB_MB << 20, out_bytes=128 << 20,
        )
        model = sched.staging["model"]
        pcie_bytes = sched.tier_bytes()["pcie"]
        self.assertEqual(pcie_bytes, 2 * sched.spec.logical_bytes)
        self.assertEqual(model["pcie_s"], round(pcie_bytes / tiers.PCIE_BPS, 9))
        tm = planner.tier_time_model(sched)
        self.assertEqual(tm["pcie_bytes"], pcie_bytes)
        # PCIe-bound by construction: >= half the critical path is wire
        self.assertGreaterEqual(model["pcie_s"] / model["critical_path_s"], 0.5)
        self.assertAlmostEqual(model["bound_gbps"], tiers.PCIE_BPS / 1e9, delta=0.5)


# --------------------------------------------------------------------- #
# 2. staged plans: golden matrix, geometry, verifier mutations          #
# --------------------------------------------------------------------- #
class TestStagedPlans(TestCase):
    def test_golden_staged_plans_verify_clean_both_forms(self):
        for name, sched in staging.golden_staged_plans():
            self.assertEqual(sched.strategy, "host-staging")
            res = verify_plan(sched)
            self.assertTrue(res["ok"], (name, res))
            res_js = verify_plan(sched.canonical_json())
            self.assertTrue(res_js["ok"], name)
            self.assertIn("staging", res["checks"])
            # no collectives: staging never changes the HLO census
            self.assertEqual(sched.collective_counts(), {})
            staging.prove_fits(sched)

    def test_windows_grain_aligned_and_conserving(self):
        shape = (65536, 8192)
        wins = staging.window_extents(shape, 4, 0, 256 << 20)
        self.assertGreater(len(wins), 1)
        for (a, b) in wins[:-1]:
            self.assertEqual((b - a) % staging.GRAIN[0], 0)
        self.assertEqual(wins[0][0], 0)
        self.assertEqual(wins[-1][1], shape[0])
        for (a, b), (a2, _) in zip(wins, wins[1:]):
            self.assertEqual(b, a2)
        # tail window: ragged allowed, everything else grain-sized
        wins_t = staging.window_extents((1000, 700), 4, 1, 1 << 20)
        self.assertEqual(wins_t[-1][1], 700)

    def test_liveness_is_the_fit_oracle(self):
        sched = staging.golden_staged_plans()[1][1]
        self.assertEqual(
            sched.liveness_peak_bytes,
            sched.staging["resident_bytes"] + sched.peak_bytes,
        )
        # slab occupancy: two windows in flight at depth 2
        self.assertLessEqual(sched.peak_bytes, sched.staging["slab_bytes"])
        with env_pin(tiers.HBM_ENV, str(1 << 20)):
            with self.assertRaises(MemoryError):
                staging.prove_fits(sched)
        with env_pin(tiers.HOST_ENV, str(1 << 20)):
            with self.assertRaises(MemoryError):
                staging.prove_fits(sched)

    def _mutate(self, fn):
        d = json.loads(staging.golden_staged_plans()[4][1].canonical_json())
        fn(d)
        with self.assertRaises(PlanVerificationError) as ctx:
            verify_plan(d)
        return ctx.exception.invariant

    def test_mutation_classes_caught_with_invariant_named(self):
        # 1. stage_out issued BEFORE its stage_in (totals unchanged, so
        #    only the pairing walk can catch the reorder)
        def swap(d):
            d["steps"][0], d["steps"][1] = d["steps"][1], d["steps"][0]
        self.assertEqual(self._mutate(swap), "staging")
        # 1b. dropped stage_out: the recorded totals catch it first
        self.assertEqual(self._mutate(lambda d: d["steps"].pop(1)), "accounting")
        # 2. stage_in bytes tampered (accounting recompute catches first)
        def grow(d):
            d["steps"][0]["bytes_moved"] += 4096
        self.assertEqual(self._mutate(grow), "accounting")
        # 3. consistent tampering (recorded totals updated too): window
        #    conservation is the staging invariant's catch
        def grow_consistent(d):
            d["steps"][0]["bytes_moved"] += 4096
            d["bytes_moved"] += 4096
        self.assertIn(self._mutate(grow_consistent), ("staging", "conservation"))
        # 4. slab occupancy wrong (recorded aggregates fixed up so only
        #    the depth-2 window+prefetch recompute can catch it)
        def occ(d):
            for st in d["steps"]:
                st["peak_bytes"] += 1
            d["peak_bytes"] += 1
            d["within_budget"] = d["peak_bytes"] <= d["budget_bytes"]
        self.assertEqual(self._mutate(occ), "staging")
        # 5. annotation window count wrong
        def wincount(d):
            d["staging"]["n_windows"] += 1
            d["staging"]["passes"][0]["n_windows"] += 1
        self.assertEqual(self._mutate(wincount), "staging")
        # 6. lattice model tampered
        def model(d):
            d["staging"]["model"]["pcie_s"] *= 2
        self.assertEqual(self._mutate(model), "staging")
        # 7. stage step without its pcie tier
        def untier(d):
            d["steps"][0].pop("tier")
        self.assertEqual(self._mutate(untier), "step-kinds")
        # 8. pcie tier on a non-staging step
        def tier_local(d):
            d["steps"][0]["kind"] = "slice"
            d["steps"][0]["bytes_moved"] = 0
        self.assertEqual(self._mutate(tier_local), "step-kinds")
        # 9. annotation dropped entirely (the composition template
        #    requires it before the staging walk ever runs)
        def drop_ann(d):
            d.pop("staging")
        self.assertEqual(self._mutate(drop_ann), "composition")
        # 10. strategy lies about itself
        def relabel(d):
            d["strategy"] = "all-to-all"
        self.assertEqual(self._mutate(relabel), "composition")
        # 11. resident working set blown past capacity("hbm")
        def resident(d):
            d["staging"]["resident_bytes"] = 64 << 30
        self.assertEqual(self._mutate(resident), "staging")
        # 12. plan body edited but plan_id kept (everything else fixed up
        #     consistently is impractical by hand — the id seals the rest)
        def notes(d):
            d["notes"] = "edited"
        self.assertEqual(self._mutate(notes), "plan-id")

    def test_schedule_serialization_staging_key_conditional(self):
        spec = RedistSpec.normalize((64, 48), "float32", 0, 1, 8)
        plain = planner.plan(spec, 256 << 20, quant="0", topology="flat")
        self.assertNotIn('"staging"', plain.canonical_json())
        self.assertNotIn("pcie", plain.canonical_json())
        staged = staging.golden_staged_plans()[0][1]
        self.assertIn('"staging"', staged.canonical_json())

    def test_stage_step_vocabulary(self):
        with self.assertRaises(ValueError):
            Step("stage_in", bytes_moved=4, peak_bytes=4)  # tier required
        with self.assertRaises(ValueError):
            Step("slice", tier="pcie")  # reserved for staging
        st = Step("stage_out", bytes_moved=4, peak_bytes=8, tier="pcie")
        self.assertFalse(st.is_collective)


# --------------------------------------------------------------------- #
# 3. staged hsvd: bit-identity with the in-HBM path                     #
# --------------------------------------------------------------------- #
class TestStagedHsvdBitIdentity(TestCase):
    def _compare(self, data, rank, single_pass):
        A = ht.array(data, split=None)
        with env_pin(staging.OOC_ENV, None):
            ref = ht.linalg.hsvd_rank(A, rank, compute_sv=True, single_pass=single_pass)
        with env_pin(staging.OOC_ENV, "auto"):
            with env_pin(staging.SLAB_ENV, "4"):  # tiny slab: MANY windows
                host = staging.HostArray(data)
                got = ht.linalg.hsvd_rank(
                    host, rank, compute_sv=True, single_pass=single_pass
                )
        for name, r, g in zip("UsVe", ref, got):
            np.testing.assert_array_equal(
                _bits(r), _bits(g),
                err_msg=f"{name} (rank={rank}, single_pass={single_pass})",
            )

    def test_two_pass_bitwise(self):
        self._compare(_rand((1600, 2200), seed=1), 8, False)

    def test_two_pass_bitwise_tall(self):
        self._compare(_rand((2200, 900), seed=2), 6, False)

    def test_one_pass_bitwise(self):
        self._compare(_rand((1600, 2200), seed=3), 8, True)

    def test_operand_2x_hbm_capacity_stages_and_matches(self):
        # the acceptance scenario: a host-resident operand >= 2x the
        # (simulated) per-chip HBM stages through windows and matches
        # the in-HBM fitting twin bit-identically
        data = _rand((4096, 4096), seed=4)  # 64 MiB
        A = ht.array(data, split=None)
        with env_pin(staging.OOC_ENV, None):
            ref = ht.linalg.hsvd_rank(A, 8, compute_sv=True)
        with env_pin(staging.OOC_ENV, "auto"), env_pin(tiers.HBM_ENV, str(32 << 20)):
            host = staging.HostArray(data)
            self.assertGreaterEqual(host.nbytes, 2 * tiers.capacity("hbm"))
            sched = staging.plan_staged_passes(
                host.shape, host.dtype,
                [{"tag": "sketch", "axis": 1}, {"tag": "project", "axis": 0}],
            )
            staging.prove_fits(sched)  # the window schedule fits 32 MiB
            self.assertGreater(sched.staging["n_windows"], 4)
            got = ht.linalg.hsvd_rank(host, 8, compute_sv=True)
        for r, g in zip(ref, got):
            np.testing.assert_array_equal(_bits(r), _bits(g))

    def test_forced_gate_on_device_operand_bitwise(self):
        data = _rand((1100, 800), seed=5)
        A = ht.array(data, split=None)
        with env_pin(staging.OOC_ENV, None):
            ref = ht.linalg.hsvd_rank(A, 7, compute_sv=True)
        with env_pin(staging.OOC_ENV, "1"):
            got = ht.linalg.hsvd_rank(A, 7, compute_sv=True)
        for r, g in zip(ref, got):
            np.testing.assert_array_equal(_bits(r), _bits(g))

    def test_escape_hatch_materializes_bitwise(self):
        data = _rand((900, 1200), seed=6)
        A = ht.array(data, split=None)
        ref = ht.linalg.hsvd_rank(A, 7)
        with env_pin(staging.OOC_ENV, "0"):
            got = ht.linalg.hsvd_rank(staging.HostArray(data), 7)
        np.testing.assert_array_equal(_bits(ref[0]), _bits(got[0]))
        np.testing.assert_array_equal(_bits(ref[1]), _bits(got[1]))

    def test_escape_hatch_refuses_oversized(self):
        with env_pin(staging.OOC_ENV, "0"):
            with env_pin(tiers.HBM_ENV, str(1 << 20)):
                with self.assertRaises(MemoryError):
                    ht.linalg.hsvd_rank(staging.HostArray(_rand((1024, 1024))), 6)

    def test_small_inadmissible_budget_falls_back(self):
        # 4*l > min(m, n): the sketch is inadmissible — full-SVD path
        # through materialization, same as a device array
        data = _rand((64, 48), seed=7)
        ref = ht.linalg.hsvd_rank(ht.array(data, split=None), 40)
        got = ht.linalg.hsvd_rank(staging.HostArray(data), 40)
        np.testing.assert_array_equal(_bits(ref[0]), _bits(got[0]))

    def test_distributed_split_paths_untouched_by_gate(self):
        # the level-0 shard_map path serves split operands under every
        # gate value — forced staging routes only the single-device
        # orientation
        data = _rand((256, 64 * P), seed=8)
        A = ht.array(data, split=1)
        ref = ht.linalg.hsvd_rank(A, 5)
        with env_pin(staging.OOC_ENV, "1"):
            got = ht.linalg.hsvd_rank(A, 5)
        np.testing.assert_array_equal(_bits(ref[0]), _bits(got[0]))

    def test_pass_tile_grain_matches_staging_grain(self):
        # the bit-identity construction: window extents are multiples of
        # the SAME grain the in-HBM tiled streams walk
        self.assertEqual(svdtools._PASS_TILE, staging.GRAIN[0])
        self.assertEqual(svdtools._PASS_TILE, staging.GRAIN[1])
        self.assertEqual(staging.GRAIN[0] % 8, 0)
        self.assertEqual(staging.GRAIN[1] % 128, 0)

    def test_hdf5_host_array(self):
        if not ht.supports_hdf5():
            self.skipTest("h5py not available")
        import os
        import tempfile

        import h5py

        data = _rand((800, 640), seed=9)
        with tempfile.TemporaryDirectory() as td:
            path = os.path.join(td, "a.h5")
            with h5py.File(path, "w") as f:
                f.create_dataset("data", data=data)
            host = staging.HostArray.from_hdf5(path, "data")
            self.assertEqual(host.shape, (800, 640))
            got = ht.linalg.hsvd_rank(host, 6)
            ref = ht.linalg.hsvd_rank(ht.array(data, split=None), 6)
            np.testing.assert_array_equal(_bits(ref[0]), _bits(got[0]))


# --------------------------------------------------------------------- #
# 4. streaming KMeans                                                   #
# --------------------------------------------------------------------- #
class TestStreamingKMeans(TestCase):
    def _blobs(self, n=2400, d=16, k=4, seed=11):
        rng = np.random.default_rng(seed)
        pts = np.concatenate(
            [rng.standard_normal((n // k, d)).astype(np.float32) + 8 * i for i in range(k)]
        )
        rng.shuffle(pts)
        return pts

    def test_partial_fit_matches_running_mean_oracle(self):
        pts = self._blobs()
        k = 4
        batches = np.split(pts, 4)
        # explicit init centers: the oracle and the model start from the
        # same state without sharing the global PRNG stream
        init_c = pts[:: len(pts) // k][:k].copy()
        km = ht.cluster.KMeans(n_clusters=k, init=ht.array(init_c, split=None))
        cc = init_c.astype(np.float64)
        counts = np.zeros(k, dtype=np.float64)
        for b in batches:
            d2 = ((b[:, None, :].astype(np.float64) - cc[None]) ** 2).sum(-1)
            lbl = d2.argmin(1)
            sums = np.zeros_like(cc)
            np.add.at(sums, lbl, b.astype(np.float64))
            bc = np.bincount(lbl, minlength=k).astype(np.float64)
            new_counts = counts + bc
            cc = np.where(
                (new_counts > 0)[:, None],
                (cc * counts[:, None] + sums) / np.maximum(new_counts, 1)[:, None],
                cc,
            )
            counts = new_counts
            km.partial_fit(ht.array(b, split=None))
            np.testing.assert_allclose(
                _bits(km.cluster_centers_), cc.astype(np.float32), rtol=2e-5, atol=2e-5
            )

    def test_stream_fit_equals_manual_windows(self):
        pts = self._blobs(n=4096, d=512, k=4, seed=12)  # wide rows: many windows
        with env_pin(staging.OOC_ENV, "auto"), env_pin(staging.SLAB_ENV, "4"):
            wins = staging.window_extents(pts.shape, 4, 0, staging.slab_bytes())
            self.assertGreater(len(wins), 1)
            km_s = ht.cluster.KMeans(n_clusters=4, init="random", random_state=7)
            km_s.fit(staging.HostArray(pts))
            km_o = ht.cluster.KMeans(n_clusters=4, init="random", random_state=7)
            for a, b in wins:
                km_o.partial_fit(ht.array(pts[a:b], split=None))
        np.testing.assert_array_equal(_bits(km_s.cluster_centers_), _bits(km_o.cluster_centers_))

    def test_escape_hatch_runs_exact_lloyd(self):
        pts = self._blobs(seed=13)
        with env_pin(staging.OOC_ENV, "0"):
            km_e = ht.cluster.KMeans(n_clusters=4, init="random", random_state=5)
            km_e.fit(staging.HostArray(pts))
        km_l = ht.cluster.KMeans(n_clusters=4, init="random", random_state=5)
        km_l.fit(ht.array(pts, split=None))
        np.testing.assert_array_equal(_bits(km_e.cluster_centers_), _bits(km_l.cluster_centers_))
        self.assertIsNotNone(km_e.labels_)

    def test_partial_fit_distributed_batches(self):
        pts = self._blobs(n=8 * 64, d=8, seed=14)
        init_c = ht.array(pts[:4].copy(), split=None)
        km_r = ht.cluster.KMeans(n_clusters=4, init=init_c)
        km_d = ht.cluster.KMeans(n_clusters=4, init=init_c)
        for b in np.split(pts, 4):
            km_r.partial_fit(ht.array(b, split=None))
            km_d.partial_fit(ht.array(b, split=0))
        np.testing.assert_allclose(
            _bits(km_r.cluster_centers_), _bits(km_d.cluster_centers_), rtol=1e-5, atol=1e-5
        )

    def test_staged_kmeans_plan_verifies(self):
        sched = staging.plan_staged_passes(
            (8_388_608, 64), "float32", [{"tag": "partial-fit", "axis": 0}],
            slab=staging.DEFAULT_SLAB_MB << 20, out_bytes=1 << 20,
        )
        self.assertTrue(verify_plan(sched)["ok"])
        self.assertEqual(sched.tier_bytes()["pcie"], 8_388_608 * 64 * 4)


# --------------------------------------------------------------------- #
# 5. gather-free unique(axis=)                                          #
# --------------------------------------------------------------------- #
class TestUniqueAxisGatherFree(TestCase):
    def test_axis0_parity_f32(self):
        rng = np.random.default_rng(21)
        rows = rng.integers(0, 4, size=(64, 3)).astype(np.float32)
        a = ht.array(rows, split=0)
        got = ht.unique(a, axis=0)
        self.assert_array_equal(got, np.unique(rows, axis=0))

    def test_axis0_return_inverse(self):
        rng = np.random.default_rng(22)
        rows = rng.integers(-2, 3, size=(48, 4)).astype(np.int32)
        a = ht.array(rows, split=0)
        got, inv = ht.unique(a, axis=0, return_inverse=True)
        ref_u, ref_inv = np.unique(rows, axis=0, return_inverse=True)
        self.assert_array_equal(got, ref_u)
        np.testing.assert_array_equal(np.asarray(inv.numpy()), ref_inv.reshape(-1))
        # the inverse reconstructs the input
        np.testing.assert_array_equal(np.asarray(got.numpy())[np.asarray(inv.numpy())], rows)

    def test_axis1_parity(self):
        rng = np.random.default_rng(23)
        cols = rng.integers(0, 3, size=(5, 40)).astype(np.int64)
        a = ht.array(cols, split=1)
        got = ht.unique(a, axis=1)
        self.assert_array_equal(got, np.unique(cols, axis=1))

    def test_bool_and_nan_rows(self):
        rng = np.random.default_rng(24)
        b = rng.integers(0, 2, size=(32, 2)).astype(bool)
        self.assert_array_equal(
            ht.unique(ht.array(b, split=0), axis=0), np.unique(b, axis=0)
        )
        nan_rows = np.array([[1.0, np.nan]] * 8 + [[1.0, 2.0]] * 8, dtype=np.float32)
        got = np.asarray(ht.unique(ht.array(nan_rows, split=0), axis=0).numpy())
        # framework tie semantics (the flat unique's): NaN payloads
        # collapse to ONE canonical-NaN row (jnp.unique behavior; numpy's
        # axis mode keeps bitwise-equal NaN rows distinct — documented)
        self.assertEqual(got.shape, (2, 2))
        self.assertTrue(np.isnan(got[1, 1]))

    def test_census_no_operand_gather(self):
        if P < 2:
            self.skipTest("needs a distributed mesh")
        from heat_tpu.core import parallel as par
        from heat_tpu.kernels import sort as ksort

        rng = np.random.default_rng(25)
        rows = rng.integers(0, 5, size=(128 * P, 4)).astype(np.float32)
        a = ht.array(rows, split=0)
        u = ksort.to_sortable(a._phys.reshape(a._phys.shape[0], 4))
        blk = (u.shape[0] // P, 4)
        local = par._local_unique_rows_program(
            a.comm.mesh, a.comm.axis_name, blk, rows.shape[0], "uint32"
        )
        rep = ht.observability.collective_counts(local, u)
        # the per-shard compaction launches NO collective at all
        self.assertTrue(all(v == 0 for v in rep.counts.values()), rep.counts)
        cand, counts = local(u)
        cap = 8
        merge = par._unique_rows_merge_program(
            a.comm.mesh, a.comm.axis_name, P, cap, "uint32"
        )
        rep_m = ht.observability.collective_counts(merge, cand, counts)
        # the merge gathers ONLY the candidate prefixes (+ the count
        # vector) — never the operand
        self.assertEqual(rep_m.counts.get("all-gather", 0), 2)
        self.assertEqual(rep_m.counts.get("all-to-all", 0), 0)
        self.assertEqual(rep_m.counts.get("collective-permute", 0), 0)
        # end-to-end parity on the same operand
        self.assert_array_equal(ht.unique(a, axis=0), np.unique(rows, axis=0))

    def test_wide_slices_fall_back(self):
        rng = np.random.default_rng(26)
        wide = rng.integers(0, 2, size=(16, 300)).astype(np.float32)
        a = ht.array(wide, split=0)
        self.assert_array_equal(ht.unique(a, axis=0), np.unique(wide, axis=0))

    def test_1d_axis0_is_flat(self):
        rng = np.random.default_rng(27)
        v = rng.integers(0, 6, size=(64,)).astype(np.float32)
        a = ht.array(v, split=0)
        self.assert_array_equal(ht.unique(a, axis=0), np.unique(v))


# --------------------------------------------------------------------- #
# 6. the bench models                                                   #
# --------------------------------------------------------------------- #
class TestStagingBenchModels(TestCase):
    def test_hsvd_20gb_analytic_row_floor(self):
        # the analytic 20 GB scenario: PCIe-bound, stage_bw_frac ~1.0 —
        # the floor the TPU round must clear is 0.5
        sched = staging.plan_staged_passes(
            (65536, 81920), "float32",
            [{"tag": "sketch", "axis": 1}, {"tag": "project", "axis": 0}],
            slab=staging.DEFAULT_SLAB_MB << 20, out_bytes=128 << 20,
        )
        self.assertGreater(sched.staging["host_bytes"], tiers.capacity("hbm"))
        model = sched.staging["model"]
        self.assertGreaterEqual(model["pcie_s"] / model["critical_path_s"], 0.5)
        self.assertGreaterEqual(model["model_speedup"], 1.0)
        staging.prove_fits(sched)

    def test_telemetry_counts_windows(self):
        ht.telemetry.enable()
        try:
            ht.telemetry.reset()
            data = _rand((800, 1100), seed=31)
            with env_pin(staging.OOC_ENV, "auto"), env_pin(staging.SLAB_ENV, "4"):
                ht.linalg.hsvd_rank(staging.HostArray(data), 6)
            snap = ht.telemetry.snapshot()
            self.assertGreater(snap["counters"].get("redist.staging.windows", 0), 1)
            self.assertGreater(
                snap["counters"].get("redist.staging.bytes_in", 0), data.nbytes
            )
        finally:
            ht.telemetry.disable()


if __name__ == "__main__":
    import unittest

    unittest.main()
