"""Pass 6 (numcheck) — precision-flow & tolerance-budget verifier
(ISSUE 17).

Contracts pinned here:

- Every SL6xx golden bad fixture trips at its DECLARED severity (SL601
  warning below the 65536 extent / error at or past it, SL602 error at
  default MXU precision / info when HIGHEST-stamped or
  pragma-acknowledged, SL603 error on both carry arms, SL604 warning
  under the x64-off policy), and every clean twin comes back clean —
  the fix each finding names really is the fix.
- The IR rules (SL601-SL603) are folded into ``ht.analysis.check``;
  SL604 stays standalone-only (a source rule the jaxpr cannot witness),
  and the shared ``analysis/_dtypes.py`` vocabulary keeps SL104's
  widening verdict and SL601's low-precision verdict deciding casts in
  exactly one place.
- The ``HEAT_TPU_NUMCHECK_ACC_DIM`` gate moves the SL601 threshold
  (env and ``acc_dim=`` forms agree) without entering any program cache
  key, and the ``# numcheck: ignore[...]`` pragma downgrades without
  silencing.
- The shipped numeric contracts — TSQR, hSVD level-0, the collective
  matmul ring, ``quantized_allreduce_sum``, the kcluster serving
  endpoint, the driver training step — are numcheck-clean at zero
  errors, and the whole ``heat_tpu/`` tree passes the planar
  precision-policy source arm.
- Seeded mutations (the ci.sh proof): delete the PR 5 planar
  ``precision="highest"`` default -> SL602 error; strip the gram
  builders' ``preferred_element_type=jnp.float32`` -> SL601; narrow an
  EF carry to bf16 -> SL603.
- The ``tolerance`` invariant: every golden-matrix plan (all
  topologies, quant on and off) and every staged golden plan composes
  to exactly its ``quant.tol`` annotation, while >= 6 hand-mutated
  plans fail ``verify_plan`` with ``invariant="tolerance"`` and the
  defective step named (the tier-flip form lands as an SL605 finding
  from the standalone ``check_tolerance``).

Everything here runs on the tier-1 CPU mesh at 8 AND 5 devices — the
collective pins that need a real mesh carry their own skips.
"""

import copy
import importlib
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import heat_tpu as ht

import analysis_fixtures as fx

from heat_tpu.analysis import _dtypes, ircheck
from heat_tpu.analysis.planverify import (
    PlanVerificationError,
    check_tolerance,
    verify_plan,
)
from heat_tpu.kernels import quant
from heat_tpu.redistribution import planner

from test_suites.basic_test import TestCase, env_pin

# the module is shadowed by the function in the package namespace
numcheck_mod = importlib.import_module("heat_tpu.analysis.numcheck")
numcheck = numcheck_mod.numcheck

P = len(jax.devices())
ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BUDGET = planner.DEFAULT_BUDGET_MB << 20

PLANAR_REL = "heat_tpu/core/complex_planar.py"


def _read(rel):
    with open(os.path.join(ROOT, rel), encoding="utf-8") as f:
        return f.read()


def _gauss_args(n=64):
    k = jnp.linspace(0.0, 1.0, n * n, dtype=jnp.float32).reshape(n, n)
    return k, k + 1.0, k + 2.0, k + 3.0


# ------------------------------------------------------------------ #
# golden bad fixtures: each rule trips at its declared severity      #
# ------------------------------------------------------------------ #
class TestGoldenBadFixtures(TestCase):
    def test_low_precision_gram_trips_sl601_warning(self):
        x = jnp.zeros((2048, 64), jnp.bfloat16)
        rep = numcheck(fx.low_precision_gram_program, x)
        hits = [f for f in rep.findings if f.rule == "SL601"]
        self.assertTrue(hits, [repr(f) for f in rep.findings])
        # extent 2048 is past the 1024 gate but below the error floor
        self.assertTrue(all(f.severity == "warning" for f in hits))
        self.assertTrue(rep.ok)  # warnings report, never gate
        clean = numcheck(fx.f32_accum_gram_program, x)
        self.assertEqual([f for f in clean.findings if f.rule == "SL601"], [])

    def test_raw_bf16_reduce_trips_sl601_error(self):
        x = jnp.zeros((70000,), jnp.bfloat16)
        rep = numcheck(fx.low_precision_reduce_program, x)
        hits = [f for f in rep.findings if f.rule == "SL601"]
        self.assertTrue(hits, [repr(f) for f in rep.findings])
        # extent 70000 >= 65536: every bf16 partial saturates 8 mantissa
        # bits long before the sum closes — error, gates
        self.assertTrue(all(f.severity == "error" for f in hits))
        self.assertFalse(rep.ok)
        # jnp.sum auto-upcasts internally: the clean twin IS the idiom
        clean = numcheck(fx.upcast_reduce_program, x)
        self.assertEqual([f for f in clean.findings if f.rule == "SL601"], [])

    def test_gauss_default_precision_trips_sl602_error(self):
        rep = numcheck(fx.gauss_default_precision_program, *_gauss_args())
        hits = [f for f in rep.findings if f.rule == "SL602"]
        # both cancelling outputs (p1-p2 and p3-p1-p2) are findings
        self.assertGreaterEqual(len(hits), 2, [repr(f) for f in rep.findings])
        self.assertTrue(all(f.severity == "error" for f in hits))
        self.assertFalse(rep.ok)

    def test_gauss_highest_precision_downgrades_to_info(self):
        rep = numcheck(fx.gauss_highest_precision_program, *_gauss_args())
        hits = [f for f in rep.findings if f.rule == "SL602"]
        self.assertTrue(hits)
        self.assertTrue(all(f.severity == "info" for f in hits))
        self.assertTrue(rep.ok)

    def test_bf16_scan_carry_trips_sl603_error(self):
        x = jnp.linspace(0.0, 1.0, 16 * 8, dtype=jnp.float32).reshape(16, 8)
        rep = numcheck(fx.bf16_carry_scan_program, x)
        hits = [f for f in rep.findings if f.rule == "SL603"]
        self.assertTrue(hits, [repr(f) for f in rep.findings])
        self.assertTrue(all(f.severity == "error" for f in hits))
        clean = numcheck(fx.f32_carry_scan_program, x)
        self.assertEqual([f for f in clean.findings if f.rule == "SL603"], [])

    def test_bf16_ef_carry_trips_sl603_error(self):
        carry = jnp.zeros((128,), jnp.float32)
        grad = jnp.ones((128,), jnp.float32)
        rep = numcheck(fx.bf16_ef_carry_program, carry, grad)
        hits = [f for f in rep.findings if f.rule == "SL603"]
        self.assertTrue(hits, [repr(f) for f in rep.findings])
        self.assertTrue(all(f.severity == "error" for f in hits))
        clean = numcheck(fx.f32_ef_carry_program, carry, grad)
        self.assertEqual([f for f in clean.findings if f.rule == "SL603"], [])

    def test_f64_request_trips_sl604_under_x64_off_policy(self):
        x = jnp.ones((32,), jnp.float32)
        rep = numcheck(fx.f64_request_program, x, x64=False)
        hits = [f for f in rep.findings if f.rule == "SL604"]
        self.assertTrue(hits, [repr(f) for f in rep.findings])
        self.assertTrue(all(f.severity == "warning" for f in hits))
        self.assertTrue(hits[0].path.endswith("analysis_fixtures.py"))
        self.assertTrue(hits[0].line)
        # with x64 honored there is nothing to degrade
        on = numcheck(fx.f64_request_program, x, x64=True)
        self.assertEqual([f for f in on.findings if f.rule == "SL604"], [])
        clean = numcheck(fx.f32_request_program, x, x64=False)
        self.assertEqual([f for f in clean.findings if f.rule == "SL604"], [])


# ------------------------------------------------------------------ #
# the acc-dim gate and the acknowledgement pragma                    #
# ------------------------------------------------------------------ #
class TestThresholdAndPragma(TestCase):
    def test_acc_dim_param_moves_the_sl601_threshold(self):
        x = jnp.zeros((512, 64), jnp.bfloat16)  # extent 512 < 1024
        self.assertEqual(
            [f.rule for f in numcheck(fx.low_precision_gram_program, x).findings
             if f.rule == "SL601"],
            [],
        )
        rep = numcheck(fx.low_precision_gram_program, x, acc_dim=256)
        self.assertIn("SL601", [f.rule for f in rep.findings])
        self.assertEqual(rep.context["acc_dim"], 256)

    def test_acc_dim_gate_moves_the_sl601_threshold(self):
        x = jnp.zeros((512, 64), jnp.bfloat16)
        with env_pin("HEAT_TPU_NUMCHECK_ACC_DIM", "256"):
            rep = numcheck(fx.low_precision_gram_program, x)
        self.assertIn("SL601", [f.rule for f in rep.findings])
        self.assertEqual(rep.context["acc_dim"], 256)

    def test_acc_dim_gate_never_enters_program_keys(self):
        """affects_programs=False: the threshold tunes a REPORT, not a
        program — flipping it must leave every cache roster alone."""
        from heat_tpu.core import gates

        spec = gates.GATES["HEAT_TPU_NUMCHECK_ACC_DIM"]
        self.assertFalse(spec.affects_programs)
        self.assertEqual(len(spec.scopes), 0)

    def test_pragma_downgrades_sl602_to_info(self):
        self.assertEqual(
            numcheck_mod.fn_pragmas(fx.gauss_pragma_acknowledged_program),
            frozenset({"SL602"}),
        )
        rep = numcheck(fx.gauss_pragma_acknowledged_program, *_gauss_args())
        hits = [f for f in rep.findings if f.rule == "SL602"]
        self.assertTrue(hits)  # acknowledged, not silenced
        self.assertTrue(all(f.severity == "info" for f in hits))
        self.assertTrue(rep.ok)


# ------------------------------------------------------------------ #
# the check() fold and the shared dtype vocabulary                   #
# ------------------------------------------------------------------ #
class TestCheckFold(TestCase):
    def test_check_folds_sl602(self):
        rep = ht.analysis.check(fx.gauss_default_precision_program, *_gauss_args())
        self.assertIn("SL602", [f.rule for f in rep.findings])

    def test_check_folds_sl601(self):
        x = jnp.zeros((2048, 64), jnp.bfloat16)
        rep = ht.analysis.check(fx.low_precision_gram_program, x)
        self.assertIn("SL601", [f.rule for f in rep.findings])

    def test_check_does_not_fold_sl604(self):
        """SL604 is standalone-only: a SOURCE rule the jaxpr cannot
        witness — folding it would re-flag every sanctioned widening
        SL104 already prices."""
        x = jnp.ones((32,), jnp.float32)
        rep = ht.analysis.check(fx.f64_request_program, x)
        self.assertNotIn("SL604", [f.rule for f in rep.findings])

    def test_jit_wrapper_carries_numcheck_hook(self):
        @ht.jit
        def program(a, b):
            return jnp.matmul(a, b)

        rep = program.numcheck(
            jnp.zeros((2048, 64), jnp.bfloat16).T,
            jnp.zeros((2048, 64), jnp.bfloat16),
        )
        self.assertIn("SL601", [f.rule for f in rep.findings])
        self.assertEqual(rep.context["pass"], "numcheck")

    def test_dtype_vocabulary_is_shared(self):
        """SL104 (ircheck) and SL601-SL603 (numcheck) read the SAME
        ``_dtypes.py`` classifiers — the two passes can never disagree
        on what a cast costs."""
        self.assertIs(ircheck._effective_itemsize, _dtypes.effective_itemsize)
        self.assertIs(ircheck._lossy_narrowing, _dtypes.lossy_narrowing)
        self.assertIs(ircheck._promotion_ceiling, _dtypes.promotion_ceiling)
        self.assertIs(ircheck._widens_past, _dtypes.widens_past)
        self.assertIs(numcheck_mod._dtypes, _dtypes)
        self.assertTrue(_dtypes.is_low_precision(jnp.bfloat16))
        self.assertTrue(_dtypes.is_low_precision(jnp.float16))
        self.assertFalse(_dtypes.is_low_precision(jnp.float32))
        # lossy_narrowing is SL104's float->int8 arm; the bf16 cast
        # shape belongs to SL603's low-precision walk instead
        self.assertTrue(_dtypes.lossy_narrowing(jnp.float32, jnp.int8))
        self.assertFalse(_dtypes.lossy_narrowing(jnp.float32, jnp.bfloat16))


# ------------------------------------------------------------------ #
# shipped numeric contracts stay clean                               #
# ------------------------------------------------------------------ #
class TestCleanPins(TestCase):
    @pytest.mark.skipif(P < 2, reason="needs a real mesh")
    def test_tsqr_numcheck_clean(self):
        a = ht.random.randn(16 * P, 2 * P, split=0)
        rep = numcheck(lambda v: ht.linalg.qr(v), a)
        self.assertEqual(rep.errors, [])

    @pytest.mark.skipif(P < 2, reason="needs a real mesh")
    def test_hsvd_level0_numcheck_clean(self):
        from heat_tpu.core.linalg.svdtools import _local_svd_fn

        comm = ht.get_comm()
        phys = comm.shard(jnp.ones((16, 4 * P), jnp.float32), 1)
        fn = _local_svd_fn(
            comm.mesh, comm.axis_name, 16, phys.shape[1] // P, 3, "float32", 5
        )
        rep = numcheck(fn, phys)
        self.assertEqual(rep.errors, [])

    @pytest.mark.skipif(P < 2, reason="needs a real mesh")
    def test_ring_cmatmul_numcheck_clean(self):
        a = ht.ones((512, 64 * P), split=1)
        b = ht.ones((64 * P, 512), split=0)
        with env_pin(planner.OVERLAP_ENV, "1"):
            rep = numcheck(lambda u, v: ht.matmul(u, v), a, b)
        self.assertEqual(rep.errors, [])

    @pytest.mark.skipif(P < 2, reason="needs a real mesh")
    def test_quantized_allreduce_numcheck_clean(self):
        """The int8 wire codec accumulates FULL-WIDTH (decode-then-sum,
        f32 EF residual) — the shape SL601/SL603 exist to protect."""
        from jax.sharding import PartitionSpec as PS

        from heat_tpu.core._jax_compat import shard_map

        comm = self.comm

        def body(hl):
            out, resid = quant.quantized_allreduce_sum(
                hl[0], comm.axis_name, P, "int8"
            )
            return out[None], resid[None]

        f = shard_map(
            body,
            mesh=comm.mesh,
            in_specs=(PS(comm.axis_name, None),),
            out_specs=(PS(comm.axis_name, None), PS(comm.axis_name, None)),
            check_vma=False,
        )
        phys = comm.shard(jnp.ones((P, 5000), jnp.float32), 0)
        rep = numcheck(f, phys)
        self.assertEqual(rep.errors, [])

    def test_kcluster_endpoint_numcheck_clean(self):
        from heat_tpu.cluster import _kcluster

        centers = jnp.linspace(0.0, 1.0, 5 * 12, dtype=jnp.float32).reshape(5, 12)
        spec = _kcluster.serving_spec("euclidean", centers)
        prog = spec["build"]()
        batch = jnp.zeros((8, 12), jnp.float32)
        rep = numcheck(prog, batch, *spec["args"])
        self.assertEqual(rep.errors, [])

    @pytest.mark.skipif(P < 2, reason="needs a real mesh")
    def test_training_step_numcheck_clean(self):
        import __graft_entry__ as graft

        fn, args = graft.training_step_program(P)
        rep = numcheck(fn, *args)
        self.assertEqual(rep.errors, [])
        self.assertEqual(rep.context["pass"], "numcheck")

    def test_tree_passes_the_planar_policy_arm(self):
        rep = numcheck_mod.lint_paths([os.path.join(ROOT, "heat_tpu")], root=ROOT)
        self.assertEqual([str(f) for f in rep.findings], [])
        self.assertEqual(rep.context["pass"], "numcheck")


# ------------------------------------------------------------------ #
# seeded mutations (the ci.sh proof)                                 #
# ------------------------------------------------------------------ #
class TestSeededMutations(TestCase):
    """Remove ONE precision invariant, the verifier trips. Each
    mutation asserts its anchor still exists, so source drift fails
    loudly instead of silently weakening the proof."""

    def test_mutation_dropped_planar_highest_default_trips_sl602(self):
        """Invariant: the PR 5 planar fix — every Gauss-form op in
        core/complex_planar.py defaults its MXU precision to HIGHEST.
        Mutation: delete the default — the 13% on-chip defect comes
        back, and the policy arm catches it at PR time."""
        src = _read(PLANAR_REL)
        needle = '    if precision is None:\n        precision = "highest"\n'
        self.assertIn(needle, src)
        clean = numcheck_mod.lint_source(src, PLANAR_REL)
        self.assertEqual([f for f in clean if f.severity == "error"], [])
        mutated = src.replace(needle, "")
        found = numcheck_mod.lint_source(mutated, PLANAR_REL)
        hits = [f for f in found if f.rule == "SL602"]
        self.assertTrue(hits, [repr(f) for f in found])
        self.assertTrue(all(f.severity == "error" for f in hits))
        self.assertTrue(all(f.path == PLANAR_REL for f in hits))

    def test_mutation_policy_table_tracks_the_module(self):
        """Every op the policy table prices exists in the planar module
        — a renamed op would silently drop out of enforcement, so the
        drift is itself an error."""
        import ast

        policy = numcheck_mod.PLANAR_PRECISION_POLICY
        self.assertEqual(policy["matmul"], "highest")
        self.assertEqual(policy["dot"], "highest")
        tree = ast.parse(_read(PLANAR_REL))
        defs = {
            n.name for n in ast.walk(tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        }
        for op in policy:
            self.assertIn(op, defs, f"policy op {op!r} not in {PLANAR_REL}")

    def test_mutation_stripped_gram_accumulator_trips_sl601(self):
        """Invariant: the kcluster gram builders accumulate wide
        (``preferred_element_type=jnp.float32``, cluster/_pallas.py).
        Mutation: strip the argument on a bf16 gram — the accumulator
        collapses to bf16 and SL601 fires."""
        src = _read("heat_tpu/cluster/_pallas.py")
        self.assertGreaterEqual(
            src.count("preferred_element_type=jnp.float32"), 2
        )
        x = jnp.zeros((2048, 64), jnp.bfloat16)
        kept = numcheck_mod.scan_jaxpr_precision(
            jax.make_jaxpr(fx.f32_accum_gram_program)(x)
        )
        self.assertEqual([f.rule for f in kept if f.rule == "SL601"], [])
        stripped = numcheck_mod.scan_jaxpr_precision(
            jax.make_jaxpr(fx.low_precision_gram_program)(x)
        )
        self.assertIn("SL601", [f.rule for f in stripped])

    def test_mutation_narrowed_ef_carry_trips_sl603(self):
        """Invariant: optim/dp_optimizer.py holds its error-feedback
        carry in f32 (the residual IS the low-order bits). Mutation:
        return the carry narrowed to bf16 — pass 6 sees the
        cross-program cast."""
        carry = jnp.zeros((128,), jnp.float32)
        grad = jnp.ones((128,), jnp.float32)
        kept = numcheck_mod.scan_jaxpr_precision(
            jax.make_jaxpr(fx.f32_ef_carry_program)(carry, grad)
        )
        self.assertEqual([f.rule for f in kept if f.rule == "SL603"], [])
        narrowed = numcheck_mod.scan_jaxpr_precision(
            jax.make_jaxpr(fx.bf16_ef_carry_program)(carry, grad)
        )
        hits = [f for f in narrowed if f.rule == "SL603"]
        self.assertTrue(hits)
        self.assertTrue(all(f.severity == "error" for f in hits))


# ------------------------------------------------------------------ #
# the tolerance invariant (pass 6's dynamic half)                    #
# ------------------------------------------------------------------ #
class TestToleranceInvariant(TestCase):
    def test_all_golden_plans_tolerance_clean(self):
        n = 0
        for topo in ("flat", "2x4", "2x8"):
            for q in ("0", "int8"):
                for name, spec in planner.golden_specs():
                    sched = planner.plan(spec, BUDGET, quant=q, topology=topo)
                    res = verify_plan(sched, topology=topo)
                    self.assertTrue(res["ok"], f"{name}@{topo} quant={q}")
                    self.assertIn("tolerance", res["checks"])
                    self.assertEqual(check_tolerance(sched), [], f"{name}@{topo}")
                    n += 1
        self.assertEqual(n, 3 * 2 * len(planner.golden_specs()))

    def test_staged_golden_plans_tolerance_clean(self):
        from heat_tpu.redistribution import staging

        for name, sched in staging.golden_staged_plans():
            res = verify_plan(sched)
            self.assertTrue(res["ok"], name)
            self.assertIn("tolerance", res["checks"])
            self.assertEqual(check_tolerance(sched), [], name)

    def test_composed_bound_equals_the_codec_tolerance(self):
        """The arithmetic contract behind the invariant: a quantized
        plan's declared tol IS the codec's pinned per-crossing bound,
        and the step-level recomputation reproduces it."""
        spec = dict(planner.golden_specs())["resplit_chunked_2gb_p8"]
        sched = planner.plan(spec, BUDGET, quant="int8", topology="flat")
        self.assertEqual(sched.quant_tolerance, quant.tolerance("int8"))
        tols = sched.step_tolerances()
        self.assertEqual(len(tols), len(sched.steps))
        q_idx = [k for k, st in enumerate(sched.steps) if st.kind == "quantize"]
        self.assertTrue(q_idx)
        for k, t in enumerate(tols):
            expect = quant.tolerance("int8") if k in q_idx else 0.0
            self.assertEqual(t, expect, f"step {k}")
        # disjoint chunks: the end-to-end bound is the max leg, and
        # every leg composes to exactly one crossing
        self.assertEqual(
            quant.compose_tolerance([tols[q_idx[0]]]), sched.quant_tolerance
        )
        self.assertEqual(quant.compose_tolerance([]), 0.0)
        self.assertEqual(quant.compose_tolerance([0.25, 0.25]), 0.5)
        self.assertEqual(planner.quant_tolerance(None), 0.0)
        self.assertEqual(planner.quant_tolerance("int8"), quant.tolerance("int8"))

    def test_exact_bit_plans_declare_zero(self):
        spec = dict(planner.golden_specs())["resplit_chunked_2gb_p8"]
        sched = planner.plan(spec, BUDGET, quant="0", topology="flat")
        self.assertEqual(sched.quant_tolerance, 0.0)
        self.assertEqual(sched.step_tolerances(), [0.0] * len(sched.steps))

    def test_tolerance_hooks_never_touch_serialization(self):
        """The Schedule-side hooks are read-only: calling them leaves
        the canonical bytes (and so the plan_id) unchanged."""
        spec = dict(planner.golden_specs())["resplit_chunked_2gb_p8"]
        sched = planner.plan(spec, BUDGET, quant="int8", topology="flat")
        before = sched.canonical_json()
        self.assertGreater(sched.quant_tolerance, 0.0)
        self.assertTrue(any(t > 0.0 for t in sched.step_tolerances()))
        self.assertEqual(sched.canonical_json(), before)

    # -- the seeded tolerance mutations (>= 6 name the step) -------- #
    def _qplan(self, topo="flat", quant_mode="int8"):
        spec = dict(planner.golden_specs())["resplit_chunked_2gb_p8"]
        sched = planner.plan(spec, BUDGET, quant=quant_mode, topology=topo)
        return json.loads(sched.canonical_json())

    def _expect_tolerance(self, m, step_named=True, topo=None):
        with self.assertRaises(PlanVerificationError) as cm:
            verify_plan(m, topology=topo)
        self.assertEqual(cm.exception.invariant, "tolerance", str(cm.exception))
        if step_named:
            self.assertIn("step [", str(cm.exception))
        # the non-raising mode and the standalone entry agree
        res = verify_plan(m, topology=topo, raise_on_violation=False)
        self.assertIn("tolerance", [v["invariant"] for v in res["violations"]])
        found = check_tolerance(m)
        self.assertTrue(found)
        self.assertTrue(all(f.rule == "SL605" for f in found))
        return cm.exception

    def test_mutation_doubled_tol_annotation_fails_tolerance(self):
        """Loosen the declared budget 2x: the recomposition says the
        steps only spend the codec's pinned bound."""
        m = self._qplan()
        m["quant"]["tol"] = m["quant"]["tol"] * 2
        self._expect_tolerance(m, step_named=False)

    def test_mutation_zeroed_tol_annotation_fails_tolerance(self):
        """Claim exact-bit on a quantized plan: the quantize steps
        provably spend tolerance the annotation denies."""
        m = self._qplan()
        m["quant"]["tol"] = 0.0
        self._expect_tolerance(m, step_named=False)

    def test_mutation_encode_mode_swap_names_the_step(self):
        """Retag one encode step bf16 in an int8 plan: the per-step
        contract (mode pins the detail prefix) breaks at that step."""
        m = self._qplan()
        qs = [k for k, st in enumerate(m["steps"]) if st["kind"] == "quantize"]
        st = m["steps"][qs[0]]
        st["detail"] = st["detail"].replace("int8-encode", "bf16-encode", 1)
        e = self._expect_tolerance(m)
        self.assertIn(f"step [{qs[0]}] (quantize)", str(e))

    def test_mutation_requantized_chunk_names_the_step(self):
        """Point the second encode at the FIRST chunk's leg: that leg
        would cross the wire encoded twice — the composition doubles
        past the declared budget."""
        m = self._qplan()
        qs = [k for k, st in enumerate(m["steps"]) if st["kind"] == "quantize"]
        self.assertGreaterEqual(len(qs), 2)
        m["steps"][qs[1]]["chunk"] = m["steps"][qs[0]]["chunk"]
        e = self._expect_tolerance(m)
        self.assertIn(f"step [{qs[1]}] (quantize)", str(e))

    def test_mutation_stripped_wire_marker_names_the_step(self):
        """Strip the ``[int8 wire]`` suffix from a sandwiched
        collective: the encode/decode pair brackets a step that no
        longer claims the encoded payload."""
        m = self._qplan()
        k = next(
            k for k, st in enumerate(m["steps"])
            if st["kind"] == "all_to_all"
            and st.get("detail", "").endswith(" [int8 wire]")
        )
        st = m["steps"][k]
        st["detail"] = st["detail"][: -len(" [int8 wire]")]
        e = self._expect_tolerance(m)
        self.assertIn(f"step [{k}] (all_to_all)", str(e))

    def test_mutation_forged_wire_marker_names_the_step(self):
        """Forge an ``[int8 wire]`` claim on an EXACT-BIT plan: a
        collective spends tolerance no quant annotation budgets."""
        m = self._qplan(quant_mode="0")
        self.assertIsNone(m.get("quant"))
        k = next(
            k for k, st in enumerate(m["steps"]) if st["kind"] == "all_to_all"
        )
        m["steps"][k]["detail"] = m["steps"][k]["detail"] + " [int8 wire]"
        e = self._expect_tolerance(m)
        self.assertIn(f"step [{k}] (all_to_all)", str(e))

    def test_mutation_corrupted_decode_names_the_step(self):
        """Corrupt the decode detail after an encode: the sandwich
        closes on a step that no longer proves the full-width
        reconstruction."""
        m = self._qplan()
        k = next(
            k for k, st in enumerate(m["steps"]) if st["kind"] == "dequantize"
        )
        m["steps"][k]["detail"] = "corrupt " + m["steps"][k]["detail"]
        e = self._expect_tolerance(m)
        self.assertIn(f"step [{k}] (dequantize)", str(e))

    def test_mutation_tier_flip_lands_as_sl605(self):
        """Relabel a codec-carrying dcn hop as ici in a hierarchical
        plan: ``verify_plan`` trips the earlier ``tier-labels``
        invariant by design (alternation breaks first), so the
        standalone ``check_tolerance`` proves the tolerance-side
        verdict — SL605, the step named."""
        m = self._qplan(topo="2x4")
        self.assertEqual(m["strategy"], "hierarchical-a2a")
        k = next(
            k for k, st in enumerate(m["steps"])
            if st.get("tier") == "dcn"
            and k > 0
            and m["steps"][k - 1]["kind"] == "quantize"
        )
        m["steps"][k]["tier"] = "ici"
        found = check_tolerance(m)
        self.assertTrue(found)
        self.assertTrue(all(f.rule == "SL605" for f in found))
        self.assertTrue(all(f.severity == "error" for f in found))
        self.assertIn(f"step [{k}]", str(found[0]))

    def test_check_tolerance_names_the_plan(self):
        m = self._qplan()
        m["quant"]["tol"] = 0.0
        found = check_tolerance(m)
        self.assertTrue(found)
        self.assertIn(m["plan_id"], str(found[0]))


# ------------------------------------------------------------------ #
# lint.py CLI: pass 6 rides the single CI lint entry                 #
# ------------------------------------------------------------------ #
class TestLintCLI(TestCase):
    def test_pass_numcheck_clean_tree_exits_zero(self):
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        r = subprocess.run(
            [
                sys.executable,
                os.path.join(ROOT, "scripts", "lint.py"),
                os.path.join(ROOT, "heat_tpu"),
                "--pass",
                "numcheck",
            ],
            capture_output=True,
            text=True,
            env=env,
        )
        self.assertEqual(r.returncode, 0, r.stdout + r.stderr)
        self.assertIn("[numcheck]", r.stdout)

    def test_pass_all_runs_four_passes_in_one_process(self):
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        r = subprocess.run(
            [
                sys.executable,
                os.path.join(ROOT, "scripts", "lint.py"),
                os.path.join(ROOT, "heat_tpu"),
                "--pass",
                "all",
            ],
            capture_output=True,
            text=True,
            env=env,
        )
        self.assertEqual(r.returncode, 0, r.stdout + r.stderr)
        for tag in ("[srclint]", "[effectcheck]", "[commcheck]", "[numcheck]"):
            self.assertIn(tag, r.stdout)


# ------------------------------------------------------------------ #
# scripts/verify_plans.py sweeps the tolerance invariant             #
# ------------------------------------------------------------------ #
class TestVerifyPlansSweep(TestCase):
    @pytest.mark.slow
    def test_sweep_passes_and_mutated_dump_names_tolerance(self):
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        dump = subprocess.run(
            [sys.executable, os.path.join(ROOT, "scripts", "redist_plans.py")],
            capture_output=True,
            text=True,
            env=env,
        )
        self.assertEqual(dump.returncode, 0, dump.stderr)
        ok = subprocess.run(
            [sys.executable, os.path.join(ROOT, "scripts", "verify_plans.py")],
            input=dump.stdout,
            capture_output=True,
            text=True,
            env=env,
        )
        self.assertEqual(ok.returncode, 0, ok.stdout + ok.stderr)
        # hand-mutate one quantized plan's tol annotation: the sweep
        # fails naming the tolerance invariant
        lines = dump.stdout.splitlines()
        mutated = []
        hit = False
        for line in lines:
            name, _, payload = line.partition("\t")
            if payload and not hit:
                d = json.loads(payload)
                if d.get("quant"):
                    d["quant"]["tol"] = float(d["quant"]["tol"]) * 2
                    payload = json.dumps(
                        d, sort_keys=True, separators=(",", ":")
                    )
                    hit = True
            mutated.append(f"{name}\t{payload}" if payload else line)
        self.assertTrue(hit, "no quantized plan in the dump")
        bad = subprocess.run(
            [sys.executable, os.path.join(ROOT, "scripts", "verify_plans.py")],
            input="\n".join(mutated) + "\n",
            capture_output=True,
            text=True,
            env=env,
        )
        self.assertEqual(bad.returncode, 1, bad.stdout + bad.stderr)
        self.assertIn("tolerance", bad.stdout)


if __name__ == "__main__":
    import unittest

    unittest.main()
