"""heat_tpu.kernels.spmm + sparse.DBCSR_matrix — the TPU-native sparse
engine (ISSUE 18 tentpole).

Five pins:

1. DBCSR round-trips: scipy/DCSR/dense -> (8,128) bricks -> back, with
   honest nnz / nbricks / occupancy metadata at every mesh size
   (including brick rows straddling device boundaries);
2. brick SpMM / SDDMM match the scipy oracle at both splits, for vector
   and matrix operands, with f32 accumulation for bf16 data;
3. kernel-on (Pallas, interpret on CPU) is BIT-IDENTICAL to kernel-off
   (the XLA oracle) — the accumulation stays in the same segment-sum,
   so the paths may not differ even in the last ulp;
4. the distributed programs are shard_map LOCAL: the collective census
   is zero for SpMM and SDDMM, and a SPLIT dense operand reshards
   through the redistribution planner BEFORE the local program;
5. the ``HEAT_TPU_SPMM_KERNEL`` escape hatch and the
   ``sparse.kernel.{hit,fallback}`` telemetry counters behave.
"""

import numpy as np
import pytest
import scipy.sparse as sp

import jax
import jax.numpy as jnp

import heat_tpu as ht
from heat_tpu.kernels import spmm as kspmm
from heat_tpu.sparse import BRICK_SHAPE, DBCSR_matrix, sparse_dbcsr_matrix, to_dbcsr

P = len(jax.devices())


@pytest.fixture
def kernel_mode(monkeypatch):
    def _set(mode):
        monkeypatch.setenv("HEAT_TPU_SPMM_KERNEL", mode)

    return _set


def _rand_csr(m, n, nnz, seed=0, dtype=np.float32):
    rng = np.random.default_rng(seed)
    rows = rng.integers(0, m, nnz)
    cols = rng.integers(0, n, nnz)
    csr = sp.csr_matrix(
        (rng.standard_normal(nnz).astype(dtype), (rows, cols)), shape=(m, n)
    )
    csr.sum_duplicates()
    return csr


class TestDBCSRFormat:
    def test_brick_shape_constant(self):
        assert BRICK_SHAPE == (8, 128)

    @pytest.mark.parametrize("split", [0, None])
    def test_from_scipy_round_trip(self, split):
        csr = _rand_csr(100, 300, 400, seed=1)
        A = sparse_dbcsr_matrix(csr, split=split)
        assert isinstance(A, DBCSR_matrix)
        assert A.shape == (100, 300)
        assert A.split == split
        assert A.nnz == csr.nnz
        # bricks tile the padded grid: ceil(100/8) x ceil(300/128)
        assert A.mb == 13 and A.nb == 3
        assert 0 < A.nbricks <= A.mb * A.nb
        assert 0.0 < A.occupancy <= 1.0
        np.testing.assert_allclose(A.todense().numpy(), csr.toarray())

    def test_to_dcsr_and_back(self):
        csr = _rand_csr(64, 256, 500, seed=2)
        A = sparse_dbcsr_matrix(csr, split=0)
        D = A.to_dcsr()
        assert D.nnz == csr.nnz
        np.testing.assert_allclose(np.asarray(D.data), csr.data, rtol=1e-6)
        # and DCSR -> DBCSR keeps the distribution
        A2 = to_dbcsr(D)
        assert A2.split == 0
        assert A2.nnz == csr.nnz
        np.testing.assert_allclose(A2.todense().numpy(), csr.toarray())

    def test_from_dense_dndarray(self):
        rng = np.random.default_rng(3)
        dense = (rng.random((40, 150)) < 0.05) * rng.standard_normal((40, 150))
        dense = dense.astype(np.float32)
        x = ht.array(dense, split=0)
        A = to_dbcsr(x)
        assert A.split == 0
        assert A.nnz == int(np.count_nonzero(dense))
        np.testing.assert_allclose(A.todense().numpy(), dense)

    def test_even_slabs_and_boundary_masks(self):
        """Physical slabs are mesh-even; straddle bricks are stored by
        both neighbors with disjoint row masks (no double counting)."""
        m = 8 * P + 4  # brick rows straddle device boundaries for odd P
        csr = _rand_csr(m, 256, 6 * m, seed=4)
        A = sparse_dbcsr_matrix(csr, split=0)
        bdata, bcol, brow, bmask = A._phys_components
        assert bdata.shape[0] == P * A.slab_bricks
        assert bmask.shape == (P * A.slab_bricks, 8)
        # ownership masks partition each brick row set: summing the
        # per-device mask over duplicates of a (brow) brick covers each
        # dense row at most once
        mask = np.asarray(jax.device_get(bmask))
        rows = np.asarray(jax.device_get(brow))
        cols = np.asarray(jax.device_get(bcol))
        cover = {}
        B = A.slab_bricks
        for r, (g0, g1, nreal) in enumerate(A._slab_meta):
            for t in range(r * B, r * B + nreal):
                key = (rows[t], cols[t])
                seen = cover.setdefault(key, np.zeros(8, bool))
                assert not (seen & mask[t]).any(), "row owned twice"
                seen |= mask[t]
        np.testing.assert_allclose(A.todense().numpy(), csr.toarray())

    def test_component_nbytes_prices_bricks_not_dense(self):
        csr = _rand_csr(512, 1024, 200, seed=5)
        A = sparse_dbcsr_matrix(csr, split=0)
        dense_bytes = 512 * 1024 * 4
        assert 0 < A.component_nbytes < dense_bytes

    def test_astype(self):
        csr = _rand_csr(32, 128, 60, seed=6)
        A = sparse_dbcsr_matrix(csr, split=0).astype(ht.bfloat16)
        assert A.dtype == ht.bfloat16
        np.testing.assert_allclose(
            A.todense().numpy().astype(np.float32), csr.toarray(), atol=0.02
        )

    def test_invalid_split(self):
        with pytest.raises(ValueError):
            sparse_dbcsr_matrix(_rand_csr(8, 128, 4), split=1)


class TestBrickSpMM:
    @pytest.mark.parametrize("split", [0, None])
    @pytest.mark.parametrize("k", [None, 1, 3, 16])
    def test_matches_scipy(self, split, k):
        csr = _rand_csr(90, 260, 700, seed=7)
        A = sparse_dbcsr_matrix(csr, split=split)
        rng = np.random.default_rng(8)
        shape = (260,) if k is None else (260, k)
        x = rng.standard_normal(shape).astype(np.float32)
        y = A @ x
        np.testing.assert_allclose(y.numpy(), csr @ x, rtol=1e-5, atol=1e-5)
        assert y.split == split
        assert y.gshape == ((90,) if k is None else (90, k))

    def test_empty_rows_and_all_zero_bricks(self):
        dense = np.zeros((40, 200), np.float32)
        dense[7, 130] = 3.0  # single brick, most rows empty
        A = sparse_dbcsr_matrix(sp.csr_matrix(dense), split=0)
        x = np.ones(200, np.float32)
        np.testing.assert_allclose((A @ x).numpy(), dense @ x)

    def test_bf16_accumulates_in_f32(self):
        csr = _rand_csr(64, 256, 2000, seed=9)
        A = sparse_dbcsr_matrix(csr, split=0).astype(ht.bfloat16)
        x = np.random.default_rng(10).standard_normal((256, 4)).astype(np.float32)
        y = A @ x
        ref = csr.toarray().astype(np.float32) @ x
        np.testing.assert_allclose(
            y.numpy().astype(np.float32), ref, rtol=5e-2, atol=5e-2
        )

    @pytest.mark.skipif(P < 2, reason="needs a real mesh")
    def test_split_dense_operand_reshards_by_plan(self):
        """A split-0 dense operand is legal: it rides the redistribution
        planner to replicated BEFORE the local program."""
        csr = _rand_csr(80, 256, 600, seed=11)
        A = sparse_dbcsr_matrix(csr, split=0)
        xnp = np.random.default_rng(12).standard_normal((256, 3)).astype(np.float32)
        y = A @ ht.array(xnp, split=0)
        np.testing.assert_allclose(y.numpy(), csr @ xnp, rtol=1e-5, atol=1e-5)

    def test_decide_paths_and_telemetry(self, kernel_mode):
        ht.telemetry.enable()
        try:
            ht.telemetry.reset()
            kernel_mode("1")
            assert kspmm.decide("spmm", 4, 2, "float32") == "pallas"
            kernel_mode("0")
            assert kspmm.decide("spmm", 4, 2, "float32") == "xla"
            kernel_mode("auto")  # off-TPU: the oracle wins without timing
            assert kspmm.decide("spmm", 4, 2, "float32") == "xla"
            counters = ht.telemetry.snapshot()["counters"]
            assert counters.get("sparse.kernel.hit", 0) >= 1
            assert counters.get("sparse.kernel.fallback", 0) >= 2
        finally:
            ht.telemetry.disable()
            ht.telemetry.reset()

    @pytest.mark.parametrize("k", [None, 1, 2, 5])
    def test_kernel_on_equals_off_bitwise(self, kernel_mode, k):
        """The acceptance pin: HEAT_TPU_SPMM_KERNEL=1 (Pallas, interpret
        on CPU) produces byte-identical results to =0 (XLA oracle) —
        including k=1, which pads to the matmul codepath to dodge the
        matvec reduction-order divergence."""
        csr = _rand_csr(100, 300, 900, seed=13)
        A = sparse_dbcsr_matrix(csr, split=0 if P > 1 else None)
        shape = (300,) if k is None else (300, k)
        x = np.random.default_rng(14).standard_normal(shape).astype(np.float32)
        kernel_mode("0")
        y0 = (A @ x).numpy()
        kernel_mode("1")
        y1 = (A @ x).numpy()
        np.testing.assert_array_equal(y0.view(np.uint32), y1.view(np.uint32))


class TestSDDMM:
    def _setup(self, split, seed=15, dtype=np.float32):
        csr = _rand_csr(70, 260, 500, seed=seed, dtype=dtype)
        S = sparse_dbcsr_matrix(csr, split=split)
        rng = np.random.default_rng(seed + 1)
        u = rng.standard_normal((70, 6)).astype(dtype)
        v = rng.standard_normal((260, 6)).astype(dtype)
        return csr, S, u, v

    @pytest.mark.parametrize("split", [0, None])
    def test_matches_dense_oracle(self, split):
        csr, S, u, v = self._setup(split)
        C = ht.sparse.sddmm(S, u, v)
        assert isinstance(C, DBCSR_matrix)
        assert C.nnz == S.nnz and C.nbricks == S.nbricks
        # only the stored PATTERN of S carries values; compare on it
        ref = csr.toarray() * 0
        mask = csr.toarray() != 0
        ref[mask] = (csr.toarray() * (u @ v.T))[mask]
        got = C.todense().numpy() * mask  # pattern-restricted comparison
        np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-4)

    def test_kernel_on_equals_off_bitwise(self, kernel_mode):
        csr, S, u, v = self._setup(0 if P > 1 else None, seed=17)
        kernel_mode("0")
        c0 = np.asarray(jax.device_get(ht.sparse.sddmm(S, u, v)._phys_components[0]))
        kernel_mode("1")
        c1 = np.asarray(jax.device_get(ht.sparse.sddmm(S, u, v)._phys_components[0]))
        np.testing.assert_array_equal(c0.view(np.uint32), c1.view(np.uint32))

    def test_d1_pads_to_matmul_codepath(self, kernel_mode):
        csr, S, _, _ = self._setup(0 if P > 1 else None, seed=19)
        rng = np.random.default_rng(20)
        u = rng.standard_normal((70, 1)).astype(np.float32)
        v = rng.standard_normal((260, 1)).astype(np.float32)
        kernel_mode("0")
        c0 = np.asarray(jax.device_get(ht.sparse.sddmm(S, u, v)._phys_components[0]))
        kernel_mode("1")
        c1 = np.asarray(jax.device_get(ht.sparse.sddmm(S, u, v)._phys_components[0]))
        np.testing.assert_array_equal(c0.view(np.uint32), c1.view(np.uint32))

    def test_shape_validation(self):
        _, S, u, v = self._setup(None)
        with pytest.raises(ValueError):
            ht.sparse.sddmm(S, u[:10], v)
        with pytest.raises(ValueError):
            ht.sparse.sddmm(S, u, v[:, :3])
        with pytest.raises(TypeError):
            ht.sparse.sddmm(np.zeros((3, 3)), u, v)


@pytest.mark.skipif(P < 2, reason="needs a real mesh")
class TestDistributedCensusPin:
    """ISSUE 18 acceptance: the distributed brick programs are LOCAL —
    zero collectives in the compiled SpMM and SDDMM, on both paths."""

    def _spmm_census(self, mode, monkeypatch):
        monkeypatch.setenv("HEAT_TPU_SPMM_KERNEL", mode)
        csr = _rand_csr(16 * P, 512, 64 * P, seed=21)
        A = sparse_dbcsr_matrix(csr, split=0)
        bdata, bcol, brow, bmask = A._phys_components
        x2d = jnp.asarray(
            np.random.default_rng(22).standard_normal((512, 4)).astype(np.float32)
        )
        path = kspmm.decide("spmm", A.slab_bricks, 4, "float32")
        prog = kspmm.spmm_bcsr_program(
            A.comm, A.shape[0], A.nb, A.slab_bricks, 0, 2, "float32", path
        )
        return ht.observability.collective_counts(prog, bdata, bcol, brow, bmask, x2d)

    @pytest.mark.parametrize("mode", ["0", "1"])
    def test_spmm_zero_collectives(self, mode, monkeypatch):
        rep = self._spmm_census(mode, monkeypatch)
        assert all(v == 0 for v in rep.counts.values()), rep.counts

    @pytest.mark.parametrize("mode", ["0", "1"])
    def test_sddmm_zero_collectives(self, mode, monkeypatch):
        monkeypatch.setenv("HEAT_TPU_SPMM_KERNEL", mode)
        csr = _rand_csr(16 * P, 512, 64 * P, seed=23)
        S = sparse_dbcsr_matrix(csr, split=0)
        sdata, bcol, brow, _ = S._phys_components
        rng = np.random.default_rng(24)
        u = jnp.asarray(rng.standard_normal((S.shape[0], 4)).astype(np.float32))
        v = jnp.asarray(rng.standard_normal((S.shape[1], 4)).astype(np.float32))
        path = kspmm.decide("sddmm", S.slab_bricks, 4, "float32")
        prog = kspmm.sddmm_bcsr_program(
            S.comm, S.mb, S.nb, S.slab_bricks, 0, "float32", path
        )
        rep = ht.observability.collective_counts(prog, sdata, bcol, brow, u, v)
        assert all(v == 0 for v in rep.counts.values()), rep.counts

    def test_spmv_result_matches_oracle_distributed(self, monkeypatch):
        """Executed distributed result (not just the census) stays on
        the scipy oracle at the mesh size CI runs (8 and 5)."""
        monkeypatch.setenv("HEAT_TPU_SPMM_KERNEL", "1")
        csr = _rand_csr(16 * P + 3, 384, 900, seed=25)
        A = sparse_dbcsr_matrix(csr, split=0)
        x = np.random.default_rng(26).standard_normal(384).astype(np.float32)
        np.testing.assert_allclose(
            (A @ x).numpy(), csr @ x, rtol=1e-5, atol=1e-5
        )
