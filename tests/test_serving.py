"""Serving runtime tests (ISSUE 9): persistent AOT program cache,
async micro-batching dispatcher, admission control, and the satellites
(telemetry-registry concurrency, SL106 serving budget, escape hatch).

Contracts pinned here:

- AOT round trip is BIT-identical to a fresh compile (including output
  DNDarray metadata: shape/split/dtype) and survives donation.
- Corruption and version mismatch fall back to recompile — never an
  error — and are counted.
- An AOT-served request compiles 0 programs (cache-hit census: a hit,
  no ``ht.jit.trace`` event, no ``ht.jit.compile`` timer).
- Bucket-padded dispatcher numerics equal the unbatched predict.
- Donation-aware double buffering returns correct results under
  concurrent mixed-size clients.
- The bounded queue rejects with the typed ``ServingOverloaded``;
  deadline-expired requests are shed with the same type.
- Telemetry carries per-request p50/p95 latency + queue-depth samples.
- ``HEAT_TPU_SERVING_AOT=0`` (hooks uninstalled) leaves the miss-path
  program forms byte-identical to the gate-on ones — the escape hatch.
"""

import glob
import os
import pickle
import tempfile
import threading
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import heat_tpu as ht

# the module, not the public `jit` function shadowing it in the core
# package namespace
import importlib
htjit = importlib.import_module("heat_tpu.core.jit")

from heat_tpu.serving import aot_cache
from heat_tpu.serving.admission import AdmissionControl, ServingOverloaded
from heat_tpu.serving.dispatcher import Dispatcher, Endpoint, estimator_endpoint, program_endpoint

from test_suites.basic_test import TestCase

P = jax.device_count()


class ServingCase(TestCase):
    """Every test anchors the serving gate explicitly and restores the
    ambient resolution on exit, so the suite passes identically under
    the tier-1 default (hooks off) and the forced HEAT_TPU_SERVING_AOT=1
    CI leg."""

    def setUp(self):
        super().setUp()
        self._tmp = tempfile.TemporaryDirectory()
        self.store = aot_cache.configure(self._tmp.name, enable=True)

    def tearDown(self):
        aot_cache.configure(enable=False)
        aot_cache._auto_configure()  # restore the ambient gate resolution
        self._tmp.cleanup()
        super().tearDown()


def _pipeline(x, y):
    g = ht.matmul(x, ht.transpose(y))
    return {"norms": ht.sqrt(ht.sum(g * g, axis=1)), "mean": ht.mean(g)}


def _times2(a):
    return a * 2


def _minus1(a):
    return a - 1


def _split_arr(rows, cols, seed=0):
    rng = np.random.default_rng(seed)
    return ht.array(rng.normal(size=(rows, cols)).astype(np.float32), split=0)


# ---------------------------------------------------------------------- #
# AOT store + ht.jit hooks                                               #
# ---------------------------------------------------------------------- #
class TestAOTRoundTrip(ServingCase):
    def test_round_trip_bit_identical_and_metadata(self):
        """A fresh wrapper (simulating a fresh process against a warm
        store) serves the SAME bits and the same DNDarray metadata."""
        x, y = _split_arr(64, 16, 1), _split_arr(48, 16, 2)
        r1 = ht.jit(_pipeline)(x, y)
        self.assertEqual(self.store.stats["store"], 1)
        r2 = ht.jit(_pipeline)(x, y)  # new wrapper: ht-level miss, AOT hit
        self.assertEqual(self.store.stats["hit"], 1)
        for key in ("norms", "mean"):
            np.testing.assert_array_equal(
                np.asarray(r1[key]._phys), np.asarray(r2[key]._phys)
            )
            self.assertEqual(r1[key].shape, r2[key].shape)
            self.assertEqual(r1[key].split, r2[key].split)
            self.assertEqual(r1[key].dtype, r2[key].dtype)

    def test_round_trip_with_donation(self):
        def double(a):
            return a + a

        r1 = ht.jit(double, donate_argnums=0)(_split_arr(32, 8, 3))
        self.assertEqual(self.store.stats["store"], 1)
        r2 = ht.jit(double, donate_argnums=0)(_split_arr(32, 8, 3))
        self.assertEqual(self.store.stats["hit"], 1)
        np.testing.assert_array_equal(np.asarray(r1._phys), np.asarray(r2._phys))

    def test_corruption_falls_back_to_recompile(self):
        x = _split_arr(32, 8, 4)
        ht.jit(_times2)(x)
        (path,) = glob.glob(os.path.join(self.store.root, "*.aot"))
        with open(path, "wb") as f:
            f.write(b"not a pickle")
        r = ht.jit(_times2)(x)  # must not raise
        np.testing.assert_array_equal(r.numpy(), x.numpy() * 2)
        self.assertEqual(self.store.stats["corrupt"], 1)
        self.assertEqual(self.store.stats["store"], 2)  # evicted + re-exported
        with open(path, "rb") as f:  # same key, now a valid envelope again
            self.assertIn("exported", pickle.load(f))

    def test_version_mismatch_falls_back_to_recompile(self):
        x = _split_arr(32, 8, 5)
        ht.jit(_minus1)(x)
        (path,) = glob.glob(os.path.join(self.store.root, "*.aot"))
        with open(path, "rb") as f:
            rec = pickle.load(f)
        rec["meta"]["jax"] = "0.0.0-stale"
        with open(path, "wb") as f:
            pickle.dump(rec, f)
        # the persistent KEY includes the version stamps too, so a fresh
        # wrapper derives the same key; the envelope check catches the
        # tampered/stale meta and recompiles
        r = ht.jit(_minus1)(x)
        np.testing.assert_array_equal(r.numpy(), x.numpy() - 1)
        self.assertEqual(self.store.stats["version_mismatch"], 1)

    def test_cache_hit_census_zero_compiles(self):
        """An AOT-served request compiles 0 programs: serving.aot.hit
        fires, ht.jit.trace/compile never do."""
        x = _split_arr(64, 16, 6)
        ht.jit(_pipeline)(x, x)  # populate the store
        ht.telemetry.enable()
        ht.telemetry.reset()
        try:
            ht.jit(_pipeline)(x, x)  # fresh wrapper: served from the store
            snap = ht.telemetry.snapshot()
            events = ht.observability.events.snapshot()
        finally:
            ht.telemetry.disable()
            ht.telemetry.reset()
        self.assertEqual(snap["counters"].get("serving.aot.hit"), 1)
        self.assertNotIn("ht.jit.compile", snap["timers"])
        self.assertIn("serving.aot.first_dispatch", snap["timers"])
        self.assertFalse([e for e in events if e["event"] == "ht.jit.trace"])

    def test_unstable_static_bypasses(self):
        """A static arg with no stable serialization (arbitrary object)
        bypasses the persistent cache instead of risking a collision."""

        class Cfg:  # repr carries an address
            pass

        def f(a, cfg):
            return a * 2

        ht.jit(f)(_split_arr(16, 4, 7), Cfg())
        self.assertEqual(self.store.stats["store"], 0)
        self.assertGreaterEqual(self.store.stats["bypass"], 1)

    def test_escape_hatch_program_forms_identical(self):
        """HEAT_TPU_SERVING_AOT=0 restores the exact pre-PR wrapper: the
        gate-on MISS path builds the same jax.jit(inner) program — the
        lowered text is byte-identical to the hooks-off build."""
        x = _split_arr(32, 8, 8)

        def f(a):
            return ht.sum(a * a)

        w_on = ht.jit(f)
        w_on(x)  # miss path under the active hooks
        ((jit_on, _),) = w_on._ht_jit_cache.values()
        aot_cache.configure(enable=False)
        self.assertIsNone(htjit.aot_hooks())
        w_off = ht.jit(f)
        w_off(x)
        ((jit_off, _),) = w_off._ht_jit_cache.values()
        self.assertEqual(
            jit_on.lower(x._phys).as_text(), jit_off.lower(x._phys).as_text()
        )

    def test_ensure_program_round_trip(self):
        def build():
            return jax.jit(lambda b: b * 3.0)

        sds = jax.ShapeDtypeStruct((8, 4), np.float32)
        p1, s1 = aot_cache.ensure_program(("t", 1), build, (sds,))
        self.assertEqual(s1, "store")
        p2, s2 = aot_cache.ensure_program(("t", 1), build, (sds,))
        self.assertEqual(s2, "hit")
        arr = jnp.arange(32, dtype=jnp.float32).reshape(8, 4)
        np.testing.assert_array_equal(np.asarray(p1(arr)), np.asarray(p2(arr)))

    def test_warmup_declared_set(self):
        cold = ht.serving.warmup()
        self.assertTrue(cold)
        for rec in cold.values():
            for status in rec["variants"].values():
                self.assertIn(status, ("store", "hit"))
        with self.assertRaises(ValueError):  # typos error, never skip silently
            ht.serving.warmup(["kcluster_predit"])


# ---------------------------------------------------------------------- #
# dispatcher                                                             #
# ---------------------------------------------------------------------- #
def _fit_kmeans(n=192, d=12, k=5, seed=11):
    x = _split_arr(n, d, seed)
    return ht.cluster.KMeans(n_clusters=k, init="random", random_state=3).fit(x)


class TestDispatcher(ServingCase):
    def test_bucket_padding_numerics_match_unbatched(self):
        """Padded/coalesced serving labels == the unbatched eager
        predict — bit-identical by shared-program construction."""
        km = _fit_kmeans()
        ep = estimator_endpoint(km, buckets=(8, 32))
        rng = np.random.default_rng(21)
        q = rng.normal(size=(29, 12)).astype(np.float32)
        direct = km.predict(ht.array(q, split=0)).numpy()
        with Dispatcher(ep, max_queue=64) as d:
            sizes = [1, 5, 7, 3, 8, 5]  # 29 rows over mixed request sizes
            futs, off = [], 0
            for s in sizes:
                futs.append(d.submit(q[off:off + s]))
                off += s
            got = np.concatenate([np.asarray(f.result(timeout=60)) for f in futs])
        np.testing.assert_array_equal(got, direct)

    def test_concurrent_mixed_shape_clients(self):
        km = _fit_kmeans()
        ep = estimator_endpoint(km, buckets=(8, 32))
        rng = np.random.default_rng(5)
        q = rng.normal(size=(64, 12)).astype(np.float32)
        direct = km.predict(ht.array(q, split=0)).numpy()
        results = {}

        def client(i, lo, hi):
            with_lat = d.submit(q[lo:hi]).result(timeout=60)
            results[i] = np.asarray(with_lat)

        with Dispatcher(ep, max_queue=64) as d:
            spans = [(0, 7), (7, 15), (15, 16), (16, 28), (28, 36), (36, 64)]
            threads = [
                threading.Thread(target=client, args=(i, lo, hi))
                for i, (lo, hi) in enumerate(spans)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(60)
            stats = d.stats()
        for i, (lo, hi) in enumerate(spans):
            np.testing.assert_array_equal(results[i], direct[lo:hi])
        self.assertEqual(stats["requests"], len(spans))
        self.assertEqual(stats["rows"], 64)
        self.assertGreaterEqual(stats["batches"], 1)

    def test_donation_double_buffering_correct(self):
        """A donating endpoint (input slab reuse) under a stream of
        back-to-back batches: depth-2 staging must never hand the
        program a buffer it already consumed."""

        def build():
            return jax.jit(lambda b: b * 2.0 + 1.0)

        ep = program_endpoint(
            build, (6,), np.float32, buckets=(4, 16), key=("donate-test",),
            donate=True,
        )
        with Dispatcher(ep, max_queue=128, poll_s=0.001) as d:
            futs = []
            rng = np.random.default_rng(9)
            payloads = [rng.normal(size=(3, 6)).astype(np.float32) for _ in range(40)]
            for p in payloads:
                futs.append(d.submit(p))
            for p, f in zip(payloads, futs):
                np.testing.assert_allclose(
                    np.asarray(f.result(timeout=60)), p * 2.0 + 1.0, rtol=1e-6
                )

    def test_bounded_queue_rejects_with_typed_overload(self):
        gate = threading.Event()

        def build():
            return jax.jit(lambda b: b + 1.0)

        def blocking_place(batch):
            gate.wait(timeout=30)
            return jnp.asarray(batch)

        ep = Endpoint(
            {4: build()}, (2,), np.float32, place=blocking_place, name="stall"
        )
        d = Dispatcher(ep, max_queue=2, poll_s=0.001)
        d.start()
        try:
            first = d.submit(np.zeros((1, 2), np.float32))  # stalls in place()
            time.sleep(0.05)  # let the worker pick it up
            a = d.submit(np.zeros((1, 2), np.float32))
            b = d.submit(np.zeros((1, 2), np.float32))
            with self.assertRaises(ServingOverloaded) as ctx:
                d.submit(np.zeros((1, 2), np.float32))
            self.assertEqual(ctx.exception.reason, "queue-full")
            self.assertEqual(ctx.exception.limit, 2)
            gate.set()
            for f in (first, a, b):
                f.result(timeout=60)
        finally:
            gate.set()
            d.stop()

    def test_deadline_shedding(self):
        gate = threading.Event()

        def blocking_place(batch):
            gate.wait(timeout=30)
            return jnp.asarray(batch)

        ep = Endpoint(
            {4: jax.jit(lambda b: b + 1.0)}, (2,), np.float32,
            place=blocking_place, name="shed",
        )
        d = Dispatcher(ep, max_queue=8, poll_s=0.001)
        d.start()
        try:
            first = d.submit(np.zeros((1, 2), np.float32))  # stalls the worker
            time.sleep(0.05)
            doomed = d.submit(np.zeros((1, 2), np.float32), deadline_s=0.01)
            time.sleep(0.05)  # deadline passes while queued
            gate.set()
            first.result(timeout=60)
            with self.assertRaises(ServingOverloaded) as ctx:
                doomed.result(timeout=60)
            self.assertEqual(ctx.exception.reason, "deadline")
            self.assertGreaterEqual(d.stats()["shed"], 1)
        finally:
            gate.set()
            d.stop()

    def test_telemetry_fields(self):
        km = _fit_kmeans()
        ep = estimator_endpoint(km, buckets=(8,))
        ht.telemetry.enable()
        ht.telemetry.reset()
        try:
            with Dispatcher(ep, max_queue=16) as d:
                for i in range(4):
                    d.call(np.zeros((3, 12), np.float32), timeout=60)
            snap = ht.telemetry.snapshot()
        finally:
            ht.telemetry.disable()
            ht.telemetry.reset()
        self.assertEqual(snap["counters"].get("serving.requests"), 4)
        self.assertGreaterEqual(snap["counters"].get("serving.batches", 0), 1)
        lat = snap["timers"]["serving.request.latency"]
        self.assertEqual(lat["calls"], 4)
        self.assertGreaterEqual(lat["p95_s"], lat["p50_s"])
        self.assertIn("serving.queue.depth", snap["timers"])

    def test_stop_without_drain_fails_leftovers(self):
        gate = threading.Event()

        def blocking_place(batch):
            gate.wait(timeout=30)
            return jnp.asarray(batch)

        ep = Endpoint(
            {4: jax.jit(lambda b: b + 1.0)}, (2,), np.float32,
            place=blocking_place, name="stopper",
        )
        d = Dispatcher(ep, max_queue=8, poll_s=0.001)
        d.start()
        stuck = d.submit(np.zeros((1, 2), np.float32))
        time.sleep(0.05)
        queued = d.submit(np.zeros((1, 2), np.float32))
        stopper = threading.Thread(target=d.stop, kwargs={"drain": False})
        stopper.start()
        gate.set()
        stopper.join(60)
        stuck.result(timeout=60)  # in flight: completes
        with self.assertRaises(ServingOverloaded) as ctx:
            queued.result(timeout=60)  # undrained leftover: typed failure
        # shutdown, NOT "queue-full": a load balancer must fail over,
        # not back off as if the replica were overloaded
        self.assertEqual(ctx.exception.reason, "shutdown")

    def test_cancelled_future_does_not_kill_worker(self):
        """A client cancel()ing its queued future must not poison the
        resolve loop for the other requests in the batch."""
        gate = threading.Event()

        def blocking_place(batch):
            gate.wait(timeout=30)
            return jnp.asarray(batch)

        ep = Endpoint(
            {4: jax.jit(lambda b: b + 1.0)}, (2,), np.float32,
            place=blocking_place, name="cancel",
        )
        with Dispatcher(ep, max_queue=8, poll_s=0.001) as d:
            stall = d.submit(np.zeros((1, 2), np.float32))
            time.sleep(0.05)
            doomed = d.submit(np.zeros((1, 2), np.float32))
            survivor = d.submit(np.ones((1, 2), np.float32))
            doomed.cancel()
            gate.set()
            stall.result(timeout=60)
            np.testing.assert_allclose(np.asarray(survivor.result(timeout=60)), 2.0)
            # the worker survived the cancelled future: it still serves
            r = d.call(np.full((1, 2), 4.0, np.float32), timeout=60)
            np.testing.assert_allclose(np.asarray(r), 5.0)

    def test_request_validation(self):
        ep = Endpoint({4: jax.jit(lambda b: b)}, (2,), np.float32)
        with Dispatcher(ep) as d:
            with self.assertRaises(ValueError):
                d.submit(np.zeros((5, 2), np.float32))  # > largest bucket
            with self.assertRaises(ValueError):
                d.submit(np.zeros((1, 3), np.float32))  # wrong feature shape
            r = d.call(np.zeros(2, np.float32), timeout=60)  # single sample
            self.assertEqual(np.asarray(r).shape, (1, 2))
        with self.assertRaises(RuntimeError):
            d.submit(np.zeros((1, 2), np.float32))  # stopped dispatcher


class TestKNNServing(ServingCase):
    def test_knn_endpoint_matches_predict(self):
        rng = np.random.default_rng(13)
        xt = ht.array(rng.normal(size=(40, 6)).astype(np.float32), split=0)
        yt = ht.array((rng.integers(0, 3, size=40)).astype(np.int32), split=0)
        knn = ht.classification.KNeighborsClassifier(n_neighbors=3).fit(xt, yt)
        q = rng.normal(size=(11, 6)).astype(np.float32)
        direct = knn.predict(ht.array(q, split=0)).numpy()
        ep = estimator_endpoint(knn, buckets=(16,))
        with Dispatcher(ep) as d:
            got = np.asarray(d.call(q, timeout=60))
        np.testing.assert_array_equal(got, direct)


# ---------------------------------------------------------------------- #
# SL106 serving budget (shardlint)                                       #
# ---------------------------------------------------------------------- #
class TestServingShardlint(TestCase):
    def test_serving_tree_is_srclint_clean(self):
        """The dispatcher's per-request hot path carries zero undeclared
        device_get (SL201 over heat_tpu/serving/) — the enforcement of
        the SL106 per-request budget at the source level."""
        from heat_tpu.analysis.srclint import lint_paths

        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        rep = lint_paths([os.path.join(root, "heat_tpu", "serving")], root=root)
        self.assertEqual([str(f) for f in rep.errors], [])

    def test_endpoint_program_is_sl106_clean(self):
        """ht.analysis.check over the serving predict program: the
        dispatch→result path contains no host sync."""
        km = _fit_kmeans()
        spec = km.serving_program()
        prog = spec["build"]()
        batch = jnp.zeros((8, 12), jnp.float32)
        rep = ht.analysis.check(prog, batch, *spec["args"])
        self.assertEqual([str(f) for f in rep.by_rule("SL106")], [])
        self.assertTrue(rep.ok)


# ---------------------------------------------------------------------- #
# telemetry registry concurrency (satellite fix)                         #
# ---------------------------------------------------------------------- #
class TestTelemetryConcurrentRecorders(TestCase):
    def test_sharded_registry_exact_under_threads(self):
        """Dispatcher-style concurrency: N recorder threads + a reader
        polling snapshots. Counter and call totals must be EXACT (the
        pre-fix failure mode under a hypothetical unlocked registry is
        lost updates), percentiles sane, and no exception raised."""
        from heat_tpu.observability.telemetry import Registry

        reg = Registry()
        n_threads, n_iter = 8, 4000
        stop = threading.Event()
        errors = []

        def reader():
            while not stop.is_set():
                try:
                    reg.snapshot()
                except Exception as e:  # pragma: no cover
                    errors.append(e)

        def recorder(i):
            for j in range(n_iter):
                reg.inc("serving.requests")
                reg.observe("serving.request.latency", (j % 100) / 1000.0)

        rt = threading.Thread(target=reader)
        rt.start()
        threads = [threading.Thread(target=recorder, args=(i,)) for i in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(60)
        stop.set()
        rt.join(10)
        self.assertEqual(errors, [])
        snap = reg.snapshot()
        self.assertEqual(snap["counters"]["serving.requests"], n_threads * n_iter)
        lat = snap["timers"]["serving.request.latency"]
        self.assertEqual(lat["calls"], n_threads * n_iter)
        self.assertGreaterEqual(lat["p95_s"], lat["p50_s"])
        self.assertLessEqual(lat["max_s"], 0.099 + 1e-9)
        reg.clear()
        self.assertEqual(reg.snapshot()["counters"], {})

    def test_dead_thread_shards_fold_into_retired(self):
        """Thread churn must not leak shards: totals stay exact after
        the recording threads die, and the shard list stays bounded by
        LIVE threads (dead shards fold into the retired accumulator
        when new threads register)."""
        from heat_tpu.observability.telemetry import Registry

        reg = Registry()
        waves, per_wave = 6, 4

        def recorder():
            reg.inc("churn")
            reg.observe("churn.lat", 0.001)

        for _ in range(waves):
            threads = [threading.Thread(target=recorder) for _ in range(per_wave)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(30)
        reg.inc("churn")  # a new live thread registers -> prune runs
        snap = reg.snapshot()
        self.assertEqual(snap["counters"]["churn"], waves * per_wave + 1)
        self.assertEqual(snap["timers"]["churn.lat"]["calls"], waves * per_wave)
        with reg._lock:
            live_shards = len(reg._shards)
        self.assertLessEqual(live_shards, 2)  # this thread (+ at most one straggler)

    def test_module_registry_merges_across_threads(self):
        ht.telemetry.enable()
        ht.telemetry.reset()
        try:
            def w():
                for _ in range(100):
                    ht.telemetry.inc("x")

            threads = [threading.Thread(target=w) for _ in range(4)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(30)
            self.assertEqual(ht.telemetry.snapshot()["counters"]["x"], 400)
        finally:
            ht.telemetry.disable()
            ht.telemetry.reset()


# ---------------------------------------------------------------------- #
# admission control units                                                #
# ---------------------------------------------------------------------- #
class TestAdmission(TestCase):
    def test_policy(self):
        ac = AdmissionControl(max_queue=3, default_deadline_s=1.0)
        self.assertEqual(ac.deadline_for(10.0, None), 11.0)
        self.assertEqual(ac.deadline_for(10.0, 0.5), 10.5)
        self.assertIsNone(AdmissionControl(max_queue=1).deadline_for(10.0, None))
        self.assertFalse(ac.expired(None))
        self.assertTrue(ac.expired(time.monotonic() - 1.0))
        exc = ac.reject(3)
        self.assertEqual((exc.reason, exc.queue_depth, exc.limit), ("queue-full", 3, 3))
        shed = ac.shed(12.0, 1)
        self.assertEqual(shed.reason, "deadline")
        with self.assertRaises(ValueError):
            AdmissionControl(max_queue=0)

    def test_memory_policy_units(self):
        """ISSUE 10: the hbm-estimate admission arm. No declared
        estimate never rejects (the pre-memcheck code path); an
        estimate over the budget rejects typed with the budget as the
        limit and the estimate attached."""
        ac = AdmissionControl(max_queue=3, hbm_limit_bytes=1 << 20)
        self.assertFalse(ac.over_memory(None))
        self.assertFalse(ac.over_memory(1 << 20))  # at the limit: fits
        self.assertTrue(ac.over_memory((1 << 20) + 1))
        exc = ac.reject_memory(5 << 20)
        self.assertEqual(exc.reason, "hbm-estimate")
        self.assertEqual(exc.limit, 1 << 20)
        self.assertEqual(exc.static_peak_bytes, 5 << 20)
        self.assertIn("hbm-estimate", str(exc))
        # default limit resolves HEAT_TPU_HBM_BYTES (16 GiB unset):
        # sane programs always fit
        self.assertFalse(AdmissionControl().over_memory(1 << 30))

    def test_dispatcher_rejects_over_budget_endpoint(self):
        """An endpoint that declares a static peak over the admission
        budget is rejected at submit — typed, before any dispatch can
        OOM; the same endpoint with no declared estimate serves."""
        ep = Endpoint(
            {4: lambda b: b * 2.0}, (3,), np.float32,
            static_peak_bytes=2 << 20,
        )
        ac = AdmissionControl(max_queue=4, hbm_limit_bytes=1 << 20)
        with Dispatcher(ep, admission=ac) as d:
            with self.assertRaises(ServingOverloaded) as cm:
                d.submit(np.zeros((2, 3), np.float32))
            self.assertEqual(cm.exception.reason, "hbm-estimate")
            self.assertEqual(cm.exception.static_peak_bytes, 2 << 20)
            self.assertGreaterEqual(d.stats()["rejected"], 1)
        ep_fits = Endpoint({4: lambda b: b * 2.0}, (3,), np.float32)
        with Dispatcher(ep_fits, admission=AdmissionControl(
                max_queue=4, hbm_limit_bytes=1 << 20)) as d:
            out = np.asarray(d.call(np.ones((2, 3), np.float32), timeout=30))
        np.testing.assert_allclose(out, 2.0)
