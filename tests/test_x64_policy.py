"""Platform x64 policy (VERDICT r2 weak #6 / next #8): 64-bit dtypes are
a per-platform policy, not an import-time global. CPU/GPU worlds enable
JAX's x64 mode at first backend use (full float64/int64 reference
parity — the rest of the suite runs in that mode); TPU worlds keep x64
off and DEGRADE 64-bit dtype requests to 32-bit, with array metadata and
device buffers degrading together. ``ht.use_x64`` overrides explicitly.

The degraded mode is platform-independent logic, so it is exercised here
in a SUBPROCESS on CPU with x64 forced off — the same state a TPU world
boots into."""

import os
import subprocess
import sys

import numpy as np

import heat_tpu as ht

_WORKER = r"""
import jax
jax.config.update('jax_platforms', 'cpu')
import numpy as np
import heat_tpu as ht

ht.use_x64(False)  # the TPU-world boot state, forced on CPU

# factories: 64-bit requests degrade — metadata AND buffer agree
x = ht.arange(7, dtype=ht.int64, split=0)
assert x.dtype is ht.int32 and x._phys.dtype == 'int32', (x.dtype, x._phys.dtype)
f = ht.full((3, 2), 1.5, dtype=ht.float64, split=0)
assert f.dtype is ht.float32 and f._phys.dtype == 'float32'

# numpy f64 ingestion degrades consistently
a = ht.array(np.arange(6, dtype=np.float64).reshape(3, 2), split=0)
assert a.dtype is ht.float32 and a._phys.dtype == 'float32'

# ops on degraded arrays stay 32-bit and numerically correct
s = ht.sum(a)
assert float(s) == 15.0
m = ht.matmul(a, ht.array(np.ones((2, 2), np.float64)))
assert m.dtype is ht.float32
np.testing.assert_allclose(np.asarray(m.numpy()), np.arange(6).reshape(3, 2) @ np.ones((2, 2)))

# index-producing ops (int64 by reference convention) degrade cleanly
sv, si = ht.sort(ht.array(np.array([3.0, 1.0, 2.0], np.float32), split=0))
assert si._phys.dtype == 'int32', si._phys.dtype
nz = ht.nonzero(ht.array(np.array([0.0, 1.0, 2.0], np.float32), split=0))
assert nz._phys.dtype == 'int32'
np.testing.assert_array_equal(np.asarray(nz.numpy()), [[1], [2]])

# linalg paths trace without any x64 escape hatch
u, err = ht.linalg.hsvd_rank(ht.array(np.random.default_rng(0).standard_normal((64, 16)).astype(np.float32), split=0), 3)
assert np.isfinite(np.asarray(u.numpy())).all()

print('X64_OFF_MODE_OK')
"""


def test_x64_off_mode_subprocess():
    env = {k: v for k, v in os.environ.items() if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    env["JAX_PLATFORMS"] = "cpu"
    out = subprocess.run(
        [sys.executable, "-c", _WORKER], capture_output=True, text=True, env=env,
        timeout=300,
    )
    assert out.returncode == 0, out.stdout[-2000:] + out.stderr[-2000:]
    assert "X64_OFF_MODE_OK" in out.stdout


def test_use_x64_round_trip():
    """The suite runs with x64 on (CPU policy); flipping off and back must
    change factory behavior immediately and restore full parity."""
    ht.ones((1,))  # first backend use decides the platform policy
    assert ht.use_x64() is True  # CPU world default
    try:
        ht.use_x64(False)
        assert ht.ones((2,), dtype=ht.float64).dtype is ht.float32
    finally:
        ht.use_x64(True)
    assert ht.ones((2,), dtype=ht.float64).dtype is ht.float64
    assert ht.arange(3, dtype=ht.int64).dtype is ht.int64
