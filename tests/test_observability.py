"""Observability subsystem: telemetry registry semantics (enable/disable,
nesting, thread safety), the core-layer instrumentation hooks, the
``collective_counts`` HLO inspector (promoting the MULTICHIP dryrun's
collective pins into tier-1), and the ``utils.monitor`` compat shim."""

import json
import os
import threading

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import heat_tpu as ht

from heat_tpu.observability import events, telemetry

from test_suites.basic_test import TestCase

P = len(jax.devices())


class TelemetryCase(TestCase):
    """Every test leaves the global switch off and the registry empty."""

    def setUp(self):
        telemetry.disable()
        telemetry.reset()

    def tearDown(self):
        telemetry.disable()
        telemetry.reset()


class TestTelemetryRegistry(TelemetryCase):
    def test_disabled_is_noop(self):
        self.assertFalse(telemetry.enabled())
        telemetry.inc("x")
        telemetry.observe("t", 0.5)
        with telemetry.record("blk"):
            pass
        snap = telemetry.snapshot()
        self.assertEqual(snap["counters"], {})
        self.assertEqual(snap["timers"], {})
        self.assertEqual(events.snapshot(), [])

    def test_enable_disable_counters(self):
        telemetry.enable()
        self.assertTrue(telemetry.enabled())
        telemetry.inc("c")
        telemetry.inc("c", 4)
        telemetry.disable()
        telemetry.inc("c")  # dropped
        self.assertEqual(telemetry.snapshot()["counters"]["c"], 5)

    def test_timer_stats_and_percentiles(self):
        telemetry.enable()
        for ms in range(1, 101):  # 1..100 ms
            telemetry.observe("t", ms / 1000.0)
        stats = telemetry.snapshot()["timers"]["t"]
        self.assertEqual(stats["calls"], 100)
        self.assertAlmostEqual(stats["best_s"], 0.001)
        self.assertAlmostEqual(stats["max_s"], 0.100)
        self.assertAlmostEqual(stats["mean_s"], 0.0505)
        self.assertAlmostEqual(stats["p50_s"], 0.051, delta=0.002)
        self.assertAlmostEqual(stats["p95_s"], 0.095, delta=0.002)

    def test_record_nesting_joins_names(self):
        telemetry.enable()
        with telemetry.record("outer", tag="a"):
            with telemetry.record("inner"):
                pass
        timers = telemetry.snapshot()["timers"]
        self.assertIn("outer", timers)
        self.assertIn("outer/inner", timers)
        names = [e["name"] for e in events.snapshot() if e["event"] == "record"]
        self.assertEqual(names, ["outer/inner", "outer"])  # inner closes first

    def test_thread_safety_smoke(self):
        telemetry.enable()

        def worker():
            for _ in range(1000):
                telemetry.inc("threads.c")
                telemetry.observe("threads.t", 1e-6)

        threads = [threading.Thread(target=worker) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        snap = telemetry.snapshot()
        self.assertEqual(snap["counters"]["threads.c"], 8000)
        self.assertEqual(snap["timers"]["threads.t"]["calls"], 8000)

    def test_export_jsonl(self):
        import tempfile

        telemetry.enable()
        telemetry.inc("e.c", 3)
        telemetry.observe("e.t", 0.25)
        with telemetry.record("e.blk"):
            pass
        with tempfile.TemporaryDirectory() as td:
            path = os.path.join(td, "telemetry.jsonl")
            n = telemetry.export_jsonl(path)
            with open(path) as f:
                lines = [json.loads(line) for line in f]
        self.assertEqual(len(lines), n)
        kinds = {line["kind"] for line in lines}
        self.assertEqual(kinds, {"counter", "timer", "event"})
        counter = next(l for l in lines if l["kind"] == "counter" and l["name"] == "e.c")
        self.assertEqual(counter["value"], 3)
        timer = next(l for l in lines if l["kind"] == "timer" and l["name"] == "e.t")
        self.assertIn("p95_s", timer)

    def test_env_var_activation_parse(self):
        for val in ("1", "true", "ON", "yes"):
            self.assertTrue(telemetry._env_truthy(val))
        for val in (None, "", "0", "false", "off"):
            self.assertFalse(telemetry._env_truthy(val))

    def test_event_buffer_is_bounded(self):
        telemetry.enable()
        for i in range(events.capacity() + 50):
            events.emit("flood", i=i)
        buffered = events.snapshot()
        self.assertEqual(len(buffered), events.capacity())
        self.assertEqual(buffered[-1]["i"], events.capacity() + 49)

    def test_report_json_roundtrip(self):
        telemetry.enable()
        telemetry.inc("r.c")
        parsed = json.loads(telemetry.report(as_json=True))
        self.assertEqual(parsed["counters"]["r.c"], 1)


class TestInstrumentationHooks(TelemetryCase):
    def test_op_cache_hit_miss_counters(self):
        telemetry.enable()
        # unusual shape so the binary program cache cannot already hold it
        a = ht.arange(9973, split=0).astype(ht.float32)
        _ = a + 2.0
        c = telemetry.snapshot()["counters"]
        base_miss = c.get("op.binary.miss", 0)
        self.assertGreaterEqual(base_miss, 1)
        _ = a + 3.0  # same (op, shape, dtype, split): must hit
        c = telemetry.snapshot()["counters"]
        self.assertGreaterEqual(c.get("op.binary.hit", 0), 1)
        self.assertEqual(c.get("op.binary.miss", 0), base_miss)
        # the miss recorded build + first-execution (compile) timers
        timers = telemetry.snapshot()["timers"]
        self.assertIn("op.binary.build", timers)
        self.assertIn("op.binary.compile", timers)

    def test_reshard_event_and_bytes(self):
        telemetry.enable()
        data = np.arange(60, dtype=np.float32).reshape(10, 6)
        x = ht.array(data, split=0)
        y = x.resplit(1)
        self.assert_array_equal(y, data)
        snap = telemetry.snapshot()["counters"]
        self.assertGreaterEqual(snap.get("dndarray.resplit.calls", 0), 1)
        self.assertGreaterEqual(snap.get("comm.reshard.calls", 0), 1)
        self.assertGreaterEqual(snap.get("comm.reshard.bytes", 0), data.nbytes)
        ev = [e for e in events.snapshot() if e["event"] == "comm.reshard"]
        self.assertTrue(ev)
        self.assertEqual(ev[-1]["old_split"], 0)
        self.assertEqual(ev[-1]["new_split"], 1)
        self.assertEqual(ev[-1]["bytes_moved"], data.nbytes)
        rev = [e for e in events.snapshot() if e["event"] == "dndarray.resplit"]
        self.assertEqual(rev[-1]["in_place"], False)

    def test_htjit_cache_counters_and_compile_timer(self):
        telemetry.enable()
        fused = ht.jit(lambda v: ht.exp(ht.sin(v) * 2.0 + v))
        x = ht.arange(1009, split=0).astype(ht.float32)
        fused(x)
        fused(x)
        c = telemetry.snapshot()["counters"]
        self.assertEqual(c.get("ht.jit.cache.miss", 0), 1)
        self.assertEqual(c.get("ht.jit.cache.hit", 0), 1)
        self.assertIn("ht.jit.compile", telemetry.snapshot()["timers"])

    def test_monitor_compat_shim(self):
        from heat_tpu.utils import monitor as mon

        mon.reset()

        @mon.monitor()
        def workload():
            return ht.sum(ht.ones((8,), split=0))

        for _ in range(3):
            workload()
        table = mon.report()
        self.assertEqual(table["workload"]["calls"], 3)
        for key in ("total_s", "best_s", "mean_s", "p50_s", "p95_s"):
            self.assertIn(key, table["workload"])
        self.assertGreaterEqual(table["workload"]["p95_s"], table["workload"]["p50_s"])
        self.assertEqual(json.loads(mon.report(as_json=True))["workload"]["calls"], 3)
        mon.reset()
        self.assertEqual(mon.report(), {})
        # with the global switch on, @monitor mirrors into the registry
        telemetry.enable()
        workload()
        self.assertIn("monitor.workload", telemetry.snapshot()["timers"])


class TestCollectiveCounts(TelemetryCase):
    """The public form of the dryrun/HLO collective pins: TSQR moves
    exactly ONE all-gather (p < 16 flat schedule), the hSVD level-0 block
    sketch moves NOTHING (every ICI byte of the merge is that
    all-gather). docs/PERF.md's cost model cites these counts."""

    @pytest.mark.skipif(P < 2, reason="needs a real mesh")
    def test_tsqr_exactly_one_allgather(self):
        a = ht.random.randn(16 * P, 2 * P, split=0)
        rep = ht.observability.collective_counts(lambda x: ht.linalg.qr(x), a)
        self.assertEqual(rep.counts["all-gather"], 1)
        self.assertEqual(rep.total, 1)  # and nothing else
        # the gathered buffer is the (p, K, K) R stack: p * K^2 * 4 bytes
        K = 2 * P
        self.assertEqual(rep.bytes_by_op["all-gather"], P * K * K * 4)
        self.assertEqual(rep.all_gather, 1)  # attribute sugar

    @pytest.mark.skipif(P < 2, reason="needs a real mesh")
    def test_hsvd_level0_zero_collectives(self):
        from heat_tpu.core.linalg.svdtools import _local_svd_fn

        comm = ht.get_comm()
        m = 16
        phys = comm.shard(jnp.ones((m, 4 * P), jnp.float32), 1)
        fn = _local_svd_fn(comm.mesh, comm.axis_name, m, phys.shape[1] // P, 3, "float32", 5)
        rep = ht.observability.collective_counts(fn, phys)  # .lower fast path
        self.assertEqual(rep.total, 0)
        self.assertEqual(rep.total_bytes, 0)

    @pytest.mark.skipif(P < 2, reason="needs a real mesh")
    def test_sum_single_allreduce(self):
        x = ht.arange(8 * P + 3, split=0).astype(ht.float32)
        rep = ht.observability.collective_counts(lambda v: ht.sum(v), x)
        self.assertEqual(rep.counts["all-reduce"], 1)
        self.assertEqual(rep.total, 1)

    def test_no_collectives_on_replicated_elementwise(self):
        x = ht.ones((4, 4), split=None)
        rep = ht.observability.collective_counts(lambda v: ht.exp(v), x)
        self.assertEqual(rep.total, 0)

    def test_report_dict_shape(self):
        x = ht.ones((6,), split=0)
        rep = ht.observability.collective_counts(lambda v: ht.sum(v), x)
        d = rep.as_dict()
        for key in ("counts", "total", "bytes_by_op", "total_bytes", "flops", "bytes_accessed"):
            self.assertIn(key, d)
        self.assertTrue(repr(rep).startswith("CollectiveReport("))
        with self.assertRaises(AttributeError):
            rep.not_a_collective

    @pytest.mark.skipif(P < 2, reason="needs a real mesh")
    def test_resplit_0_to_1_single_alltoall(self):
        """split->split relayout is ONE all-to-all moving exactly the
        local shard (logical bytes / p per device) — the reshard-bytes
        floor any repartition rework must hold."""
        x = ht.random.randn(320 * P, 2 * P, split=0)
        rep = ht.observability.collective_counts(lambda v: v.resplit(1), x)
        self.assertEqual(rep.counts["all-to-all"], 1)
        self.assertEqual(rep.total, 1)
        logical = 320 * P * 2 * P * 4
        self.assertEqual(rep.bytes_by_op["all-to-all"] * P, logical)

    @pytest.mark.skipif(P < 2, reason="needs a real mesh")
    def test_resplit_to_replicated_single_allgather(self):
        """split->None consumed downstream is ONE all-gather of the full
        logical array (a bare resplit(None) program gets its constraint
        elided by XLA — the consumer keeps it honest)."""
        x = ht.random.randn(320 * P, 2 * P, split=0)
        rep = ht.observability.collective_counts(lambda v: ht.exp(v.resplit(None)), x)
        self.assertEqual(rep.counts["all-gather"], 1)
        self.assertEqual(rep.total, 1)
        self.assertEqual(rep.bytes_by_op["all-gather"], 320 * P * 2 * P * 4)

    @pytest.mark.skipif(P < 2, reason="needs a real mesh")
    def test_reshape_split1_planned_schedule(self):
        """ROADMAP `reshape`: the split=1 repartition is planner-routed
        (ht.redistribution split-0 pivot — minor-dim packing) and must
        compile to exactly the plan's collective census: all-to-all in,
        LOCAL full-width reshape, all-to-all out, ZERO all-gathers. The
        pre-planner baseline (one all-gather of the FULL operand, pinned
        here until PR 3) stays as a strict `>` regression bound on the
        per-device bytes the schedule ships."""
        x = ht.random.randn(1 << 14, 40, split=1)  # 40 lanes: 8- and 5-mesh divisible
        plan = ht.redistribution.explain(x, reshape=(1 << 13, 80), new_split=1)
        rep = ht.observability.collective_counts(
            lambda v: ht.reshape(v, (1 << 13, 80), new_split=1), x
        )
        # executed HLO census == plan census, exactly, on ANY mesh
        census = plan.collective_counts()
        for op in ("all-gather", "all-to-all", "collective-permute"):
            self.assertEqual(rep.counts[op], census.get(op, 0), op)
        self.assertEqual(rep.total, plan.n_collectives)
        old_baseline_bytes = (1 << 14) * 40 * 4
        if (1 << 14) % P or (1 << 13) % P:
            # indivisible leading extents (the 5-device leg): the pivot is
            # ruled out and the planner EXPLICITLY degrades to the old
            # gather — same census as the pre-planner baseline
            self.assertEqual(plan.strategy, "gather-reshape")
        else:
            # 40->80 columns over p=8 are 5-/10-lane shards: the
            # lane-fill cost term engages the packed pivot (PR 5)
            self.assertEqual(plan.strategy, "packed-pivot")
            self.assertEqual(rep.counts["all-gather"], 0)
            # regression bound: the old monolithic gather assembled every
            # logical byte on every device — the planned schedule must
            # ship strictly less per device (2/p-ish for the pivot)
            self.assertGreater(old_baseline_bytes, rep.bytes_by_op["all-to-all"])
            self.assertGreater(old_baseline_bytes, plan.bytes_moved)

    def test_compile_only_no_execution(self):
        # inspection must not execute the program: an fn with a host-side
        # side effect traced once is acceptable, but device buffers of the
        # input must be left untouched (compile-only contract)
        calls = []

        def fn(v):
            calls.append(1)  # trace-time only
            return v * 2.0

        x = ht.ones((5,), split=0)
        ht.observability.collective_counts(fn, x)
        self.assertEqual(len(calls), 1)


if __name__ == "__main__":
    import unittest

    unittest.main()
