"""shardlint (``heat_tpu.analysis``): golden-finding tests.

The deliberately-bad fixture programs must trigger the IR rules
(implicit reshard, replicated materialization, gather-fed reduction,
dtype widening, missed donation, host sync); the shipped contracts —
TSQR, hSVD level-0, ring attention, sharded reductions — must come back
with zero error-severity findings; and the source lint must pass the
shipped tree while catching seeded violations. This is the machine
-enforced form of the collective pins in ``tests/test_observability.py``
and the MULTICHIP dryrun.
"""

import json
import os
import subprocess
import sys
import textwrap

import pytest

import jax
import jax.numpy as jnp

import heat_tpu as ht

import analysis_fixtures as fx

from heat_tpu.analysis import boundaries, findings, srclint

from test_suites.basic_test import TestCase

P = len(jax.devices())
ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _big_split0():
    # large enough that the per-device all-to-all shard clears the 1 MiB
    # default threshold on the 5- and 8-device CI meshes, and divisible
    # by both mesh sizes (2^16 * 5 rows) so no pad rows blur the
    # aval-alias match or sit between the gather and its reduce consumer
    return ht.random.randn(327680, 16, split=0)


class TestIRCheckBadFixture(TestCase):
    """The acceptance contract: one deliberately-bad program, >= 3
    distinct rule ids (implicit reshard, missed donation, host sync)."""

    @pytest.mark.skipif(P < 2, reason="needs a real mesh")
    def test_bad_program_reports_the_golden_rules(self):
        rep = ht.analysis.check(fx.bad_program, _big_split0())
        self.assertFalse(rep.ok)
        ids = set(rep.rule_ids)
        self.assertIn("SL101", ids)  # implicit reshard (all-to-all)
        self.assertIn("SL102", ids)  # replicated materialization
        self.assertIn("SL105", ids)  # missed donation
        self.assertIn("SL106", ids)  # host sync (untaken debug arm)
        self.assertGreaterEqual(len(ids), 3)
        # findings carry byte estimates and severities
        gather = rep.by_rule("SL102")[0]
        self.assertEqual(gather.severity, "error")
        self.assertGreaterEqual(gather.nbytes, (1 << 18) * 16 * 4)
        self.assertTrue(all(f.rule in findings.RULES for f in rep))

    @pytest.mark.skipif(P < 2, reason="needs a real mesh")
    def test_gather_fed_reduction(self):
        rep = ht.analysis.check(fx.gather_reduce_program, _big_split0())
        ids = set(rep.rule_ids)
        self.assertIn("SL102", ids)
        self.assertIn("SL103", ids)
        # the sharded twin is the fix — and it is clean
        clean = ht.analysis.check(lambda v: ht.sum(v), _big_split0())
        self.assertEqual(clean.rule_ids, [])

    def test_dtype_widening(self):
        rep = ht.analysis.check(fx.widening_program, ht.random.randn(4096, split=0))
        self.assertEqual(rep.rule_ids, ["SL104"])
        self.assertTrue(rep.ok)  # warning severity: reports, does not gate

    @pytest.mark.skipif(P < 2, reason="needs a real mesh")
    def test_unscaled_int8_narrowing_trips_sl104_at_error(self):
        """ISSUE 7 golden bad-fixture: a hand-rolled UNSCALED
        astype(int8) feeding a psum is the gradient-compression
        accident the narrowing arm exists for — error severity, gates.
        Only wire_codec-stamped converts (heat_tpu.kernels.quant)
        downgrade to info; that pin lives in tests/test_quant.py."""
        rep = ht.analysis.check(fx.int8_wire_program, ht.random.randn(64, 48, split=0))
        sl104 = [f for f in rep.findings if f.rule == "SL104"]
        self.assertTrue(sl104)
        self.assertTrue(any(f.severity == "error" for f in sl104))
        self.assertIn("kernels.quant", sl104[0].message)
        self.assertFalse(rep.ok)

    @pytest.mark.skipif(P < 2, reason="needs a real mesh")
    def test_int8_narrowing_inside_nested_jit_still_trips(self):
        """The backward walk crosses call boundaries: an unscaled
        astype(int8) hiding inside a nested jit wrapper whose OUTPUT
        feeds the collective must trip the same error — the producer
        map steps from the pjit eqn onto its sub-jaxpr's outvars."""
        import jax.numpy as jnp
        from jax import lax
        from jax.sharding import PartitionSpec as PS

        from heat_tpu.core._jax_compat import shard_map

        enc = jax.jit(lambda g: g.astype(jnp.int8))  # shardlint: ignore[SL202] -- fixture

        x = ht.random.randn(64, 48, split=0)
        comm = x.comm

        def nested(v):
            phys = v._phys

            def body(xl):
                return lax.psum(enc(xl), comm.axis_name).astype(jnp.float32)

            spec = PS(*(comm.axis_name if k == 0 else None for k in range(phys.ndim)))
            return shard_map(
                body, mesh=comm.mesh, in_specs=(spec,),
                out_specs=PS(*(None,) * phys.ndim), check_vma=False,
            )(phys)

        rep = ht.analysis.check(nested, x, scan_source=False)
        sl104 = [f for f in rep.findings if f.rule == "SL104"]
        self.assertTrue(any(f.severity == "error" for f in sl104))

        # the inverse guard: a SIBLING int8 output of the same jit
        # wrapper, NOT on the collective's dataflow path, must not trip
        # (call outvars map 1:1 onto sub-jaxpr outvars — only the
        # index-matched one continues the walk)
        two = jax.jit(  # shardlint: ignore[SL202] -- fixture
            lambda g: (g.astype(jnp.int8), g * 2.0)
        )

        def sibling(v):
            phys = v._phys

            def body(xl):
                q, f = two(xl)
                return lax.psum(f, comm.axis_name) + q.astype(jnp.float32).sum()

            spec = PS(*(comm.axis_name if k == 0 else None for k in range(phys.ndim)))
            return shard_map(
                body, mesh=comm.mesh, in_specs=(spec,),
                out_specs=PS(*(None,) * phys.ndim), check_vma=False,
            )(phys)

        clean = ht.analysis.check(sibling, x, scan_source=False)
        self.assertFalse(
            any(f.rule == "SL104" and f.severity == "error" for f in clean.findings)
        )

    @pytest.mark.skipif(P < 2, reason="needs a real mesh")
    def test_donation_bookkeeping_suppresses_sl105(self):
        x = _big_split0()
        undonated = ht.analysis.check(ht.jit(fx.donated_program), x)
        self.assertIn("SL105", undonated.rule_ids)
        donated = ht.analysis.check(ht.jit(fx.donated_program, donate_argnums=0), x)
        self.assertNotIn("SL105", donated.rule_ids)

    @pytest.mark.skipif(P < 2, reason="needs a real mesh")
    def test_unstamped_ppermute_loop_trips_sl101(self):
        """ISSUE 6 golden bad-fixture: a hand-rolled ppermute relayout
        loop with no plan stamp still trips SL101 at full severity —
        the planner's own pipelined ring programs downgrade to info
        (tests/test_overlap.py), the UNstamped chain must not."""
        rep = ht.analysis.check(fx.ppermute_ring_program, _big_split0())
        hops = [f for f in rep.by_rule("SL101") if f.op == "collective-permute"]
        self.assertTrue(hops)
        for f in hops:
            self.assertIn(f.severity, ("warning", "error"))
            self.assertGreaterEqual(f.nbytes, 1 << 20)

    @pytest.mark.skipif(P < 2, reason="needs a real mesh")
    def test_library_ring_schedules_report_as_info(self):
        """The library's OWN documented ring schedules (the distributed
        sort networks' block rotations here) are not hand-rolled
        accidents: their collective-permute hops report at info, keyed
        on the instruction's source_file (boundaries.RING_SCHEDULE_MODULES)."""
        x = ht.random.randn(P * (1 << 20), split=0)  # MB-class hops
        rep = ht.analysis.check(lambda v: ht.sort(v)[0], x)
        hops = [f for f in rep.findings if f.op == "collective-permute"]
        self.assertTrue(hops)
        for f in hops:
            self.assertEqual(f.severity, "info")
            self.assertIn("ring schedule", f.message)

    def test_trace_abort_reports_host_sync_not_raise(self):
        def syncing(v):
            s = ht.sum(v)
            return v * float(s)  # concretizes under trace

        rep = ht.analysis.check(syncing, ht.arange(64, split=0).astype(ht.float32))
        self.assertIn("SL106", rep.rule_ids)
        self.assertFalse(rep.ok)

    def test_serving_sync_handler_trips_sl106(self):
        """ISSUE 9 golden bad fixture: a BLOCKING host sync inside a
        serving request handler — the dispatch→result hot path budget
        is zero undeclared device_get, and the check aborts at the
        concretizing read with SL106 at error severity."""
        rep = ht.analysis.check(fx.serving_sync_handler, ht.random.randn(32, 8, split=0))
        self.assertFalse(rep.ok)
        sl106 = rep.by_rule("SL106")
        self.assertTrue(sl106)
        self.assertTrue(any(f.severity == "error" for f in sl106))

    def test_report_dict_shape(self):
        rep = ht.analysis.check(fx.widening_program, ht.random.randn(256, split=0))
        d = rep.as_dict()
        for key in ("ok", "rule_ids", "findings", "context"):
            self.assertIn(key, d)
        self.assertEqual(d["findings"][0]["rule"], "SL104")
        json.dumps(d)  # JSON-ready
        self.assertTrue(repr(rep).startswith("AnalysisReport("))


class TestIRCheckCleanContracts(TestCase):
    """TSQR / hSVD level-0 / ring attention — the pinned collective
    contracts — must report ZERO error-severity findings: the analyzer
    turns the hand-written pins into a machine-enforced contract."""

    @pytest.mark.skipif(P < 2, reason="needs a real mesh")
    def test_tsqr_clean(self):
        a = ht.random.randn(16 * P, 2 * P, split=0)
        rep = ht.analysis.check(lambda x: ht.linalg.qr(x), a)
        self.assertEqual(rep.errors, [])

    @pytest.mark.skipif(P < 2, reason="needs a real mesh")
    def test_hsvd_level0_clean(self):
        from heat_tpu.core.linalg.svdtools import _local_svd_fn

        comm = ht.get_comm()
        phys = comm.shard(jnp.ones((16, 4 * P), jnp.float32), 1)
        fn = _local_svd_fn(comm.mesh, comm.axis_name, 16, phys.shape[1] // P, 3, "float32", 5)
        rep = ht.analysis.check(fn, phys)  # .lower fast path
        self.assertEqual(rep.errors, [])
        self.assertEqual(rep.context["collective_counts"], {})

    @pytest.mark.skipif(P < 2, reason="needs a real mesh")
    def test_ring_attention_clean(self):
        S, D = 8 * P, 8
        q = ht.random.randn(2, S, D, split=1)
        rep = ht.analysis.check(
            lambda a, b, c: ht.nn.ring_attention(a, b, c, causal=True), q, q, q
        )
        self.assertEqual(rep.errors, [])

    @pytest.mark.skipif(P < 2, reason="needs a real mesh")
    def test_training_step_clean(self):
        import __graft_entry__ as graft

        fn, args = graft.training_step_program(P)
        rep = ht.analysis.check(fn, *args)
        self.assertEqual(rep.errors, [])


class TestFactorizationLint(TestCase):
    """ISSUE 19: the gather-then-``jnp.linalg.inv`` anti-pattern (the
    path ``ht.linalg.inv`` ran before the blocked ring-LU) trips
    SL102/SL106 as a golden bad fixture, and the blocked ``solve`` that
    replaced it is pinned memcheck-clean and SL-clean."""

    @pytest.mark.skipif(P < 2, reason="needs a real mesh")
    def test_gather_inv_fixture_trips_sl102_sl106(self):
        x = ht.random.randn(2560, 2560, split=0)
        rep = ht.analysis.check(fx.gather_inv_program, x)
        ids = set(rep.rule_ids)
        self.assertIn("SL102", ids)  # whole-operand replicated gather
        self.assertIn("SL106", ids)  # host read in the debug arm
        gather = rep.by_rule("SL102")[0]
        self.assertEqual(gather.severity, "error")
        self.assertGreaterEqual(gather.nbytes, 2560 * 2560 * 4)

    @pytest.mark.skipif(P < 2, reason="needs a real mesh")
    def test_blocked_solve_sl_clean(self):
        n = 128 * P
        a = ht.random.randn(n, n, split=0) * 0.01 + ht.eye((n, n), split=0) * 4
        b = ht.random.randn(n, 16, split=0)
        rep = ht.analysis.check(
            lambda u, v: ht.linalg.solve(u, v, assume_a="pos"), a, b
        )
        self.assertEqual(rep.errors, [])
        # the plan-stamped panel rings report at info only
        hops = [f for f in rep.findings if f.op == "collective-permute"]
        for f in hops:
            self.assertEqual(f.severity, "info")

    @pytest.mark.skipif(P < 2, reason="needs a real mesh")
    def test_blocked_solve_memcheck_clean(self):
        n = 128 * P
        a = ht.random.randn(n, n, split=0) * 0.01 + ht.eye((n, n), split=0) * 4
        b = ht.random.randn(n, 16, split=0)
        rep = ht.analysis.memcheck(
            lambda u, v: ht.linalg.solve(u, v, assume_a="pos"), a, b
        )
        self.assertEqual(rep.errors, [])
        self.assertGreater(rep.context["static_peak_bytes"], 0)


class TestMemCheckGoldenFixtures(TestCase):
    """ISSUE 10 (pass 3, memcheck): each SL3xx golden bad fixture trips
    at its pinned severity, and the shipped contracts — TSQR, hSVD
    level-0, the serving endpoint program, the training step — come
    back clean under the default budget."""

    @pytest.mark.skipif(P < 2, reason="needs a real mesh")
    def test_over_budget_program_trips_sl301_under_forced_budget(self):
        x = ht.random.randn(1 << 16, 16, split=0)  # 4 MiB operand
        rep = ht.analysis.memcheck(fx.over_budget_program, x, hbm_bytes=1 << 20)
        self.assertFalse(rep.ok)
        sl301 = rep.by_rule("SL301")
        self.assertTrue(sl301)
        self.assertEqual(sl301[0].severity, "error")
        self.assertGreater(sl301[0].nbytes, 1 << 20)
        # ... and the same program under the default 16 GiB budget is clean
        clean = ht.analysis.memcheck(fx.over_budget_program, x)
        self.assertNotIn("SL301", clean.rule_ids)
        self.assertEqual(
            clean.context["hbm_budget_bytes"],
            ht.analysis.hbm_budget_bytes(),
        )

    @pytest.mark.skipif(P < 2, reason="needs a real mesh")
    def test_dropped_donation_trips_sl302(self):
        """Donation declared via ht.jit bookkeeping but unusable (no
        output aliases the donated aval) — the executable drops it, and
        only the input_output_aliases check can see that. The honored
        twin (full-size output) stays clean: the alias map carries the
        donated parameter."""
        x = ht.random.randn(64, 4096, split=0)
        dropped = ht.analysis.memcheck(
            ht.jit(fx.dropped_donation_program, donate_argnums=0), x
        )
        self.assertFalse(dropped.ok)
        sl302 = dropped.by_rule("SL302")
        self.assertTrue(sl302)
        self.assertEqual(sl302[0].severity, "error")
        self.assertIn("input_output_aliases", sl302[0].message)
        honored = ht.analysis.memcheck(ht.jit(fx.donated_program, donate_argnums=0), x)
        self.assertNotIn("SL302", honored.rule_ids)
        self.assertIn(0, honored.context.get("aliased_params", []))

    @pytest.mark.skipif(P < 2, reason="needs a real mesh")
    def test_explicit_donation_on_jitted_fn_is_checked(self):
        """The already-jitted (.lower fast path) form honors an EXPLICIT
        donate_argnums: the donated compile is what gets alias-checked,
        so a dropped donation reports SL302 there too — not just on the
        ht.jit wrap path."""
        import jax as _jax

        dropped = _jax.jit(lambda a: a[:16] * 1.0)  # shardlint: ignore[SL202] -- fixture
        x = jnp.ones((64, 4096), jnp.float32)
        rep = ht.analysis.memcheck(dropped, x, donate_argnums=(0,))
        self.assertIn("SL302", rep.rule_ids)
        honored = _jax.jit(lambda a: a * 1.0)  # shardlint: ignore[SL202] -- fixture
        clean = ht.analysis.memcheck(honored, x, donate_argnums=(0,))
        self.assertNotIn("SL302", clean.rule_ids)
        self.assertIn(0, clean.context.get("aliased_params", []))

    @pytest.mark.skipif(P < 2, reason="needs a real mesh")
    def test_shard_map_passthrough_keeps_caller_replication_fact(self):
        """A shard_map whose output PASSES an input through must not
        rewrite the caller value's replication fact in place (the body
        invar aliases the caller's buffer record): a replicated value
        flowing through a sharded-out passthrough stays SL303-eligible
        for ITS OWN live range."""
        import importlib

        import jax as _jax
        from jax.sharding import PartitionSpec as PS

        from heat_tpu.core._jax_compat import shard_map

        mc = importlib.import_module("heat_tpu.analysis.memcheck")
        comm = ht.get_comm()
        f = lambda a: shard_map(
            lambda b: b, mesh=comm.mesh, in_specs=(PS(None, None),),
            out_specs=PS(comm.axis_name, None), check_vma=False,
        )(a)
        closed = _jax.make_jaxpr(f)(jnp.ones((8, 16), jnp.float32))
        interp = mc._Interp(comm.size)
        in_fact = mc._Fact(8 * 16 * 4, True)
        interp.run(closed.jaxpr, [in_fact], local_avals=False)
        self.assertTrue(in_fact.replicated)

    @pytest.mark.skipif(P < 2, reason="needs a real mesh")
    def test_replicated_liverange_trips_sl303(self):
        x = ht.random.randn(1 << 18, 8, split=0)  # 8 MiB replicated copy
        rep = ht.analysis.memcheck(fx.replicated_liverange_program, x)
        sl303 = rep.by_rule("SL303")
        self.assertTrue(sl303)
        self.assertEqual(sl303[0].severity, "warning")
        self.assertTrue(rep.ok)  # warning severity: reports, does not gate
        self.assertGreaterEqual(sl303[0].nbytes, 1 << 20)
        # the sharded twin (no replicated materialization) is clean
        clean = ht.analysis.memcheck(lambda v: v.resplit(1).resplit(0), x)
        self.assertNotIn("SL303", clean.rule_ids)

    @pytest.mark.skipif(P < 2, reason="needs a real mesh")
    def test_shipped_contracts_memcheck_clean(self):
        a = ht.random.randn(16 * P, 2 * P, split=0)
        self.assertEqual(ht.analysis.memcheck(lambda v: ht.linalg.qr(v), a).rule_ids, [])
        from heat_tpu.core.linalg.svdtools import _local_svd_fn

        comm = ht.get_comm()
        phys = comm.shard(jnp.ones((16, 4 * P), jnp.float32), 1)
        fn = _local_svd_fn(comm.mesh, comm.axis_name, 16, phys.shape[1] // P, 3, "float32", 5)
        self.assertEqual(ht.analysis.memcheck(fn, phys).rule_ids, [])

    @pytest.mark.skipif(P < 2, reason="needs a real mesh")
    def test_training_step_memcheck_clean(self):
        import __graft_entry__ as graft

        fn, args = graft.training_step_program(P)
        rep = ht.analysis.memcheck(fn, *args)
        self.assertEqual(rep.rule_ids, [])
        self.assertGreater(rep.context["static_peak_bytes"], 0)

    def test_serving_endpoint_program_memcheck_clean(self):
        from heat_tpu.cluster import _kcluster

        centers = jnp.linspace(0.0, 1.0, 5 * 12, dtype=jnp.float32).reshape(5, 12)
        spec = _kcluster.serving_spec("euclidean", centers)
        prog = spec["build"]()
        batch = jnp.zeros((8, 12), jnp.float32)
        rep = ht.analysis.memcheck(prog, batch, *spec["args"])
        self.assertEqual(rep.rule_ids, [])

    def test_sl3xx_rules_are_cataloged(self):
        for rule in ("SL301", "SL302", "SL303"):
            self.assertIn(rule, findings.RULES)


class TestSrcLint(TestCase):
    def test_shipped_tree_is_clean(self):
        rep = srclint.lint_paths([os.path.join(ROOT, "heat_tpu")], root=ROOT)
        self.assertEqual([str(f) for f in rep.errors], [])

    def test_seeded_bare_jit_fails(self):
        src = textwrap.dedent(
            """
            import jax

            def public_op(x):
                return jax.jit(lambda v: v * 2)(x)
            """
        )
        found = srclint.lint_source(src, "core/somemodule.py")
        self.assertEqual([f.rule for f in found], ["SL202"])
        self.assertEqual(found[0].severity, "error")

    def test_seeded_undeclared_device_get_fails(self):
        src = textwrap.dedent(
            """
            import jax

            def mean_to_host(x):
                return float(jax.device_get(x).mean())
            """
        )
        found = srclint.lint_source(src, "core/somemodule.py")
        self.assertEqual([f.rule for f in found], ["SL201"])

    def test_new_sync_in_core_statistics_must_be_declared(self):
        # the percentile-q declaration covers percentile ONLY: the same
        # call in any other function of the same file still gates
        src = "import jax\ndef median_fast(x):\n    return jax.device_get(x)\n"
        found = srclint.lint_source(src, "heat_tpu/core/statistics.py")
        self.assertIn("SL201", [f.rule for f in found])
        declared = "import jax\ndef percentile(x):\n    return jax.device_get(x)\n"
        found = srclint.lint_source(declared, "heat_tpu/core/statistics.py")
        self.assertNotIn("SL201", [f.rule for f in found])

    def test_pragma_suppresses_with_reason(self):
        src = (
            "import jax\n"
            "def f(x):\n"
            "    return jax.device_get(x)  # shardlint: ignore[SL201] -- test\n"
        )
        self.assertEqual(srclint.lint_source(src, "core/m.py"), [])

    def test_from_jax_import_jit_flagged(self):
        found = srclint.lint_source("from jax import jit\n", "core/m.py")
        self.assertEqual([f.rule for f in found], ["SL202"])

    def test_private_builder_jit_allowed(self):
        src = "import jax\ndef _my_program(shape):\n    return jax.jit(lambda v: v)\n"
        self.assertEqual(srclint.lint_source(src, "core/m.py"), [])

    def test_unsanitized_public_op_warns(self):
        src = "def frobnicate(x):\n    return x + 1\n"
        found = srclint.lint_source(src, "heat_tpu/core/arithmetics.py")
        self.assertEqual([f.rule for f in found], ["SL203"])
        self.assertEqual(found[0].severity, "warning")
        routed = "from .sanitation import sanitize_in\ndef frobnicate(x):\n    sanitize_in(x)\n    return x + 1\n"
        self.assertEqual(srclint.lint_source(routed, "heat_tpu/core/arithmetics.py"), [])


class TestLintCLI(TestCase):
    """scripts/lint.py: exit 0 on the shipped tree, nonzero on a seeded
    violation — the exact contract ci.sh leans on."""

    def test_cli_exit_codes(self):
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        ok = subprocess.run(
            [sys.executable, os.path.join(ROOT, "scripts", "lint.py"),
             os.path.join(ROOT, "heat_tpu")],
            capture_output=True, text=True, env=env,
        )
        self.assertEqual(ok.returncode, 0, ok.stdout + ok.stderr)
        import tempfile

        with tempfile.TemporaryDirectory() as td:
            bad = os.path.join(td, "seeded.py")
            with open(bad, "w") as f:
                f.write("import jax\ndef op(x):\n    return jax.jit(lambda v: v)(jax.device_get(x))\n")
            r = subprocess.run(
                [sys.executable, os.path.join(ROOT, "scripts", "lint.py"), bad],
                capture_output=True, text=True, env=env,
            )
            self.assertEqual(r.returncode, 1, r.stdout + r.stderr)
            self.assertIn("SL201", r.stdout)
            self.assertIn("SL202", r.stdout)

    def test_sarif_format_exit_codes(self):
        """ISSUE 10 satellite: `--format sarif` emits one SARIF 2.1.0
        document with one run per pass and rule ids = SLxxx, while the
        exit-code contract is unchanged — 0 on the clean tree, 1 on a
        seeded violation (the gate is the findings, not the format)."""
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        ok = subprocess.run(
            [sys.executable, os.path.join(ROOT, "scripts", "lint.py"),
             "--format", "sarif", os.path.join(ROOT, "heat_tpu")],
            capture_output=True, text=True, env=env,
        )
        self.assertEqual(ok.returncode, 0, ok.stdout + ok.stderr)
        doc = json.loads(ok.stdout)
        self.assertEqual(doc["version"], "2.1.0")
        # one run per pass — the default `--pass all` is the single CI
        # lint entry (ISSUE 14; ISSUE 17 adds pass 6): passes 2, 4, 5
        # AND 6 in one process, one SARIF document with one run per pass
        self.assertEqual(
            [run["tool"]["driver"]["name"] for run in doc["runs"]],
            [
                "shardlint/srclint",
                "shardlint/effectcheck",
                "shardlint/commcheck",
                "shardlint/numcheck",
            ],
        )
        import tempfile

        with tempfile.TemporaryDirectory() as td:
            bad = os.path.join(td, "seeded.py")
            with open(bad, "w") as f:
                f.write("import jax\ndef op(x):\n    return jax.jit(lambda v: v)(jax.device_get(x))\n")
            r = subprocess.run(
                [sys.executable, os.path.join(ROOT, "scripts", "lint.py"),
                 "--format", "sarif", bad],
                capture_output=True, text=True, env=env,
            )
            self.assertEqual(r.returncode, 1, r.stdout + r.stderr)
            doc = json.loads(r.stdout)
            results = doc["runs"][0]["results"]
            rules = {res["ruleId"] for res in results}
            self.assertIn("SL201", rules)
            self.assertIn("SL202", rules)
            self.assertTrue(all(res["level"] in ("error", "warning", "note") for res in results))
            # findings anchor on file:line for CI annotation
            loc = results[0]["locations"][0]["physicalLocation"]
            self.assertTrue(loc["artifactLocation"]["uri"].endswith("seeded.py"))
            self.assertGreaterEqual(loc["region"]["startLine"], 1)
            # declared rules carry the catalog text
            driver = doc["runs"][0]["tool"]["driver"]
            self.assertTrue(
                all(rule["id"] in findings.RULES for rule in driver["rules"])
            )


class TestBoundaries(TestCase):
    def test_percentile_is_the_only_core_whitelisted_sync(self):
        """The named host-boundary whitelist holds exactly ONE core/
        entry: the percentile q round-trip. Any new sync in a core
        compute path must add a named declaration here — this test is
        the tripwire that makes the diff visible."""
        core_entries = [
            name
            for name, (path, _qn, _reason) in boundaries.HOST_BOUNDARIES.items()
            if path.startswith("core/")
        ]
        self.assertEqual(core_entries, ["percentile-q"])
        # and the declaration matches the real site
        path, qualname, reason = boundaries.HOST_BOUNDARIES["percentile-q"]
        self.assertEqual((path, qualname), ("core/statistics.py", "percentile"))
        self.assertTrue(reason)

    def test_is_declared_sync_categories(self):
        ok, cat = boundaries.is_declared_sync("heat_tpu/core/statistics.py", "percentile")
        self.assertEqual((ok, cat), (True, "percentile-q"))
        ok, cat = boundaries.is_declared_sync("heat_tpu/core/io.py", "anything")
        self.assertTrue(ok)
        self.assertTrue(cat.startswith("host-module:"))
        ok, cat = boundaries.is_declared_sync(
            "heat_tpu/core/linalg/svdtools.py", "_hsvd_impl.inner_helper"
        )
        self.assertTrue(ok)  # a boundary owns its nested helpers
        self.assertTrue(cat.startswith("data-dependent:"))
        ok, _ = boundaries.is_declared_sync("heat_tpu/core/statistics.py", "median")
        self.assertFalse(ok)

    def test_every_declaration_points_at_real_code(self):
        """Declarations must not go stale: each declared (file, function)
        still exists in the tree."""
        import ast

        decls = (
            [(p, q) for (p, q) in boundaries.HOST_FUNCS]
            + [(p, q) for (p, q) in boundaries.DATA_DEPENDENT_BOUNDARIES]
            + [(p, q) for (p, q, _r) in boundaries.HOST_BOUNDARIES.values()]
        )
        for path, qualname in decls:
            full = os.path.join(ROOT, "heat_tpu", path)
            self.assertTrue(os.path.exists(full), f"stale declaration path: {path}")
            tree = ast.parse(open(full).read())
            names = set()

            def collect(node, stack):
                for child in ast.iter_child_nodes(node):
                    if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                        names.add(".".join(stack + [child.name]))
                        collect(child, stack + [child.name])
                    else:
                        collect(child, stack)

            collect(tree, [])
            self.assertIn(qualname, names, f"stale declaration: {path}:{qualname}")
        for mod in boundaries.HOST_MODULES:
            self.assertTrue(os.path.exists(os.path.join(ROOT, "heat_tpu", mod)))


class TestBenchCompareNewRows(TestCase):
    def _mod(self):
        import importlib.util

        spec = importlib.util.spec_from_file_location(
            "bench_compare", os.path.join(ROOT, "scripts", "bench_compare.py")
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    def test_new_and_missing_rows_never_gate(self):
        bc = self._mod()
        current = {"detail": {"old": {"gbps": 10.0}, "brand_new": {"gbps": 5.0}}}
        baseline = {"key_rows": {"old": {"gbps": 10.0}, "dropped": {"gbps": 3.0}}}
        res = bc.compare(current, baseline, 0.10)
        self.assertEqual(res["verdict"], "ok")
        self.assertEqual(res["new_rows"], ["brand_new"])
        self.assertEqual(res["missing_rows"], ["dropped"])
        self.assertEqual(res["regressions"], [])

    def test_regression_still_gates_alongside_new_rows(self):
        bc = self._mod()
        current = {"detail": {"old": {"gbps": 5.0}, "brand_new": {"gbps": 5.0}}}
        baseline = {"key_rows": {"old": {"gbps": 10.0}}}
        res = bc.compare(current, baseline, 0.10)
        self.assertEqual(res["verdict"], "regressed")
        self.assertEqual(res["new_rows"], ["brand_new"])

    def test_measurement_suspect_rows_waived_but_counted(self):
        """ISSUE 17 satellite: a regression on a row either side flags
        ``measurement_suspect`` never gates (the r5 attention-MFU
        0.68->0.58 slip was exactly this shape) — but it stays in the
        record, marked waived and counted in the summary."""
        bc = self._mod()
        current = {
            "detail": {
                "attn": {"mfu": 0.58, "measurement_suspect": True},
                "solid": {"gbps": 10.0},
            }
        }
        baseline = {"key_rows": {"attn": {"mfu": 0.68}, "solid": {"gbps": 10.0}}}
        res = bc.compare(current, baseline, 0.10)
        self.assertEqual(res["verdict"], "ok")
        self.assertEqual(res["waived"], 1)
        self.assertEqual(len(res["regressions"]), 1)
        self.assertEqual(res["regressions"][0]["row"], "attn")
        self.assertEqual(res["regressions"][0]["waived"], "measurement_suspect")
        # the suspect flag on the BASELINE side waives too
        res2 = bc.compare(
            {"detail": {"attn": {"mfu": 0.58}}},
            {"key_rows": {"attn": {"mfu": 0.68, "measurement_suspect": True}}},
            0.10,
        )
        self.assertEqual(res2["verdict"], "ok")
        self.assertEqual(res2["waived"], 1)
        # an unflagged regression of the same size still gates
        res3 = bc.compare(
            {"detail": {"attn": {"mfu": 0.58}}},
            {"key_rows": {"attn": {"mfu": 0.68}}},
            0.10,
        )
        self.assertEqual(res3["verdict"], "regressed")
        self.assertEqual(res3["waived"], 0)


class TestSparseEngineFixtures(TestCase):
    """ISSUE 18: the sparse-engine golden fixtures — the gather-per-row
    SpMV anti-pattern trips SL101/SL103, and the engine's kernel SpMM
    and PageRank step programs pin LINT-CLEAN across ircheck, memcheck
    and numcheck."""

    def _sparse_split0(self, n=327680):
        import numpy as np
        import scipy.sparse as sp

        rng = np.random.default_rng(0x18)
        m, nnz = 4096, 400000
        rows = rng.integers(0, m, nnz)
        cols = rng.integers(0, n, nnz)
        csr = sp.csr_matrix(
            (rng.random(nnz).astype(np.float32), (rows, cols)), shape=(m, n)
        )
        csr.sum_duplicates()
        return csr

    @pytest.mark.skipif(P < 2, reason="needs a real mesh")
    def test_gather_per_row_spmv_trips_sl101_sl103(self):
        # narrow dense operand: the nnz gathers must dominate the
        # largest input for SL102 to reach error severity (gating)
        csr = self._sparse_split0(n=32768)
        A = ht.sparse.sparse_csr_matrix(csr, split=0)
        x = ht.random.randn(csr.shape[1], 16, split=0)
        comm, m = A.comm, A.shape[0]
        # components passed as TRACED args — closure capture would
        # constant-fold them replicated and hide the gathers
        rep = ht.analysis.check(
            lambda r, i, d, v: fx.gather_per_row_spmv_program(comm, m, r, i, d, v),
            A._rows, *A._phys_components[1:], x._phys,
            min_bytes=1 << 17,
        )
        ids = set(rep.rule_ids)
        self.assertIn("SL101", ids)  # bare constraint -> implicit all-to-all
        self.assertIn("SL103", ids)  # gathered values feed a reduction
        self.assertIn("SL102", ids)  # the gather itself materializes
        self.assertFalse(rep.ok)

    @pytest.mark.skipif(P < 2, reason="needs a real mesh")
    def test_kernel_spmm_path_is_lint_clean(self):
        """The engine's distributed SpMM local program: no implicit
        reshards (the dense operand arrives replicated BY PLAN), no
        collectives at all, honest memory facts, f32-accumulating."""
        import numpy as np

        from heat_tpu.kernels import spmm as kspmm

        csr = self._sparse_split0()
        A = ht.sparse.sparse_dbcsr_matrix(csr, split=0)
        bdata, bcol, brow, bmask = A._phys_components
        x = np.ones((csr.shape[1], 4), np.float32)
        prog = kspmm.spmm_bcsr_program(
            A.comm, A.shape[0], A.nb, A.slab_bricks, 0, 2, "float32", "xla"
        )
        rep = ht.analysis.check(prog, bdata, bcol, brow, bmask, x)
        self.assertEqual([f for f in rep.findings if f.severity == "error"], [])
        self.assertEqual(
            [f for f in rep.findings if f.rule in ("SL101", "SL102", "SL103")],
            [],
        )
        mem = ht.analysis.memcheck(prog, bdata, bcol, brow, bmask, x)
        self.assertTrue(mem.ok)
        num = ht.analysis.numcheck(prog, bdata, bcol, brow, bmask, x)
        self.assertTrue(num.ok)

    @pytest.mark.skipif(P < 2, reason="needs a real mesh")
    def test_pagerank_step_program_is_lint_clean(self):
        import numpy as np

        csr = self._sparse_split0().T.tocsr()  # (n, m): square not needed
        csr = csr[: csr.shape[1], :].tocsr()
        A = ht.sparse.sparse_dbcsr_matrix(csr, split=0)
        bdata, bcol, brow, bmask = A._phys_components
        step = fx.make_pagerank_step(
            A.comm, A.shape[0], A.nb, A.slab_bricks, alpha=0.85
        )
        r = np.full(csr.shape[1], 1.0 / csr.shape[1], np.float32)
        tel = np.float32(0.15 / csr.shape[1])
        rep = ht.analysis.check(step, bdata, bcol, brow, bmask, r, tel)
        self.assertEqual([f for f in rep.findings if f.severity == "error"], [])
        mem = ht.analysis.memcheck(step, bdata, bcol, brow, bmask, r, tel)
        self.assertTrue(mem.ok)
        num = ht.analysis.numcheck(step, bdata, bcol, brow, bmask, r, tel)
        self.assertTrue(num.ok)


if __name__ == "__main__":
    import unittest

    unittest.main()
