"""Complex platform policy, REFUSE mode (VERDICT r4 #3): complex dtypes
are native on cpu/gpu; TPU plugin backends — whose XLA backend has no
complex implementation and (measured on the bench chip) is left
permanently failing by a single enqueued complex op — default to the
PLANAR representation (tests/test_complex_planar.py). ``ht.use_complex(
False)`` opts into the round-4 fail-fast behavior instead: an actionable
TypeError naming the policy, raised before anything reaches the device,
from every creation path. Reference parity note: complex_math.py:1-110
runs on every torch device class; the planar surface (and this opt-in
refusal) is the documented deviation (docs/MIGRATING.md, 'Complex
platform policy').

The refusal mode is platform-independent logic, forced here on the CPU
suite via ``ht.use_complex(False)``."""

import numpy as np
import pytest

import heat_tpu as ht


@pytest.fixture
def tpu_complex_policy():
    """Force the TPU-world complex refusal, restore the CPU default."""
    ht.use_complex(False)
    try:
        yield
    finally:
        from heat_tpu.core import devices

        devices._complex_choice = None  # back to platform resolution


CREATORS = {
    "array_np": lambda: ht.array(np.array([1 + 2j, 3 + 4j], np.complex64)),
    "array_infer": lambda: ht.array([1 + 2j, 3 + 4j]),
    "array_jax_cast": lambda: ht.array(np.ones(3, np.float32), dtype=ht.complex64),
    "astype": lambda: ht.arange(4, dtype=ht.float32).astype(ht.complex64),
    "full_fill": lambda: ht.full((3,), 1j, dtype=ht.complex64),
    "zeros": lambda: ht.zeros((3,), dtype=ht.complex64),
    "complex128": lambda: ht.ones((2,), dtype=ht.complex128),
    "scalar_ctor": lambda: ht.complex64(1 + 1j),
    # promotion path: real array x complex python scalar promotes to
    # complex64 INSIDE __binary_op — must refuse at the promotion point,
    # before the complex program is enqueued (code-review r5 finding)
    "binary_promotion": lambda: ht.arange(4, dtype=ht.float32) * (1 + 2j),
}


@pytest.mark.parametrize("site", sorted(CREATORS))
def test_refusal_at_every_creation_site(tpu_complex_policy, site):
    with pytest.raises(TypeError) as exc:
        CREATORS[site]()
    msg = str(exc.value)
    # actionable: names the dtype family, the reason, and the way out
    assert "complex" in msg
    assert "UNIMPLEMENTED" in msg or "backend" in msg
    assert "MIGRATING" in msg


def test_real_dtypes_unaffected(tpu_complex_policy):
    x = ht.arange(6, dtype=ht.float32, split=0)
    assert float(x.sum()) == 15.0
    assert x.astype(ht.bfloat16).dtype is ht.bfloat16


def test_cpu_default_allows_complex():
    """The suite's CPU world must keep full reference complex parity."""
    assert ht.supports_complex() is True
    z = ht.array(np.array([1 + 2j, -3 + 4j], np.complex64), split=0)
    np.testing.assert_allclose(ht.angle(z).numpy(), np.angle([1 + 2j, -3 + 4j]), rtol=1e-6)
    np.testing.assert_allclose(ht.conj(z).numpy(), np.conj([1 + 2j, -3 + 4j]))


def test_use_complex_round_trip():
    assert ht.use_complex(False) is False
    try:
        with pytest.raises(TypeError):
            ht.zeros((2,), dtype=ht.complex64)
        assert ht.use_complex(True) is True
        assert ht.zeros((2,), dtype=ht.complex64).dtype is ht.complex64
    finally:
        from heat_tpu.core import devices

        devices._complex_choice = None
