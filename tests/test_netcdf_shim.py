"""netCDF I/O paths under a minimal in-memory netCDF4 stand-in.

netCDF4 is an optional dependency the reference also gates on
(/root/reference/heat/core/io.py supports_netcdf); this image does not
ship it, which would leave load_netcdf/save_netcdf untested. The shim
implements the small API surface io.py uses (Dataset, createDimension,
createVariable, variable get/setitem) over numpy so the slab-read
assembly, per-shard writes, and append-along-dimension flow run for real
on the 8-device mesh.
"""

import importlib
import sys
import types as pytypes

import numpy as np
import pytest

import heat_tpu as ht


class _FakeVar:
    def __init__(self, store, name, dtype, dims, ds):
        self._ds = ds
        self.name = name
        self.dtype = np.dtype(dtype)
        self.dims = dims
        self._store = store

    @property
    def shape(self):
        return tuple(self._store[self.name].shape)

    def __getitem__(self, sl):
        return self._store[self.name][sl]

    def __setitem__(self, sl, value):
        arr = self._store[self.name]
        value = np.asarray(value, dtype=arr.dtype)
        # grow unlimited leading dims the way netCDF4 does on out-of-range writes
        idx = sl if isinstance(sl, tuple) else (sl,)
        grown = list(arr.shape)
        for d, s in enumerate(idx):
            if isinstance(s, slice) and s.stop is not None and self._ds.dimensions[self.dims[d]] is None:
                grown[d] = max(grown[d], s.stop)
        if tuple(grown) != arr.shape:
            bigger = np.zeros(grown, dtype=arr.dtype)
            bigger[tuple(slice(0, s) for s in arr.shape)] = arr
            arr = bigger
            self._store[self.name] = arr
        arr[sl] = value


class _FakeDataset:
    _files = {}  # path -> (dimensions, variables-store, var-meta)

    def __init__(self, path, mode="r"):
        if mode == "w" or path not in self._files:
            if mode in ("r", "r+"):
                # real netCDF4 raises for read/update modes on missing paths
                raise FileNotFoundError(path)
            self._files[path] = ({}, {}, {})
        self.dimensions, self._store, self._meta = self._files[path]
        self.variables = {
            name: _FakeVar(self._store, name, self._store[name].dtype, dims, self)
            for name, dims in self._meta.items()
        }

    def createDimension(self, name, size):
        self.dimensions[name] = size

    def createVariable(self, name, dtype, dims, **kwargs):
        shape = tuple(0 if self.dimensions[d] is None else self.dimensions[d] for d in dims)
        self._store[name] = np.zeros(shape, dtype=dtype)
        self._meta[name] = tuple(dims)
        var = _FakeVar(self._store, name, dtype, tuple(dims), self)
        self.variables[name] = var
        return var

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


@pytest.fixture()
def nc_io(monkeypatch):
    fake = pytypes.ModuleType("netCDF4")
    fake.Dataset = _FakeDataset
    monkeypatch.setitem(sys.modules, "netCDF4", fake)
    import heat_tpu.core.io as hio

    importlib.reload(hio)
    assert hio.supports_netcdf()
    yield hio
    _FakeDataset._files.clear()
    monkeypatch.delitem(sys.modules, "netCDF4")
    importlib.reload(hio)


class TestNetCDF:
    def test_roundtrip_split(self, nc_io, tmp_path):
        p = str(tmp_path / "t.nc")
        x = ht.array(np.arange(103 * 3, dtype=np.float32).reshape(103, 3), split=0)
        nc_io.save_netcdf(x, p, "data")
        back = nc_io.load_netcdf(p, "data", dtype=ht.float32, split=0)
        assert back.split == 0
        np.testing.assert_array_equal(np.asarray(back.numpy()), np.asarray(x.numpy()))

    def test_roundtrip_replicated_and_split1(self, nc_io, tmp_path):
        p = str(tmp_path / "t.nc")
        xn = np.arange(24, dtype=np.float32).reshape(4, 6)
        nc_io.save_netcdf(ht.array(xn, split=1), p, "d")
        for split in (None, 1):
            back = nc_io.load_netcdf(p, "d", dtype=ht.float32, split=split)
            assert back.split == split
            np.testing.assert_array_equal(np.asarray(back.numpy()), xn)

    def test_append_along_unlimited_dim(self, nc_io, tmp_path):
        # the reference's time-series append pattern (io.py:366)
        p = str(tmp_path / "t.nc")
        step0 = ht.array(np.full((1, 5), 0.0, dtype=np.float32), split=1)
        nc_io.save_netcdf(step0, p, "ts", mode="w", dimension_names=["t", "x"], is_unlimited=True)
        for t in range(1, 4):
            step = ht.array(np.full((1, 5), float(t), dtype=np.float32), split=1)
            nc_io.save_netcdf(
                step, p, "ts", mode="r+", dimension_names=["t", "x"],
                file_slices=slice(t, t + 1),
            )
        back = nc_io.load_netcdf(p, "ts", dtype=ht.float32, split=None)
        np.testing.assert_array_equal(
            np.asarray(back.numpy()), np.repeat(np.arange(4, dtype=np.float32)[:, None], 5, 1)
        )

    def test_save_bad_mode_raises(self, nc_io, tmp_path):
        x = ht.arange(4)
        with pytest.raises(ValueError):
            nc_io.save_netcdf(x, str(tmp_path / "t.nc"), "d", mode="x")

    def test_extension_dispatch(self, nc_io, tmp_path):
        p = str(tmp_path / "t.nc")
        x = ht.arange(11, dtype=ht.float32, split=0)
        nc_io.save(x, p, "d")
        back = nc_io.load(p, "d", dtype=ht.float32, split=0)
        np.testing.assert_array_equal(np.asarray(back.numpy()), np.arange(11, dtype=np.float32))
