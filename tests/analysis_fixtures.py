"""Deliberately-bad programs for the ``ht.analysis`` golden-finding
tests. Each function violates one or more shardlint IR rules ON PURPOSE
— tier-1 asserts ``ht.analysis.check`` reports them (and that the
shipped TSQR/hSVD/ring-attention programs stay clean). Keep the
violations obvious and commented; these are the analyzer's oracle.
"""

import jax

import heat_tpu as ht


def bad_program(x, debug=False):
    """Three violations in one program:

    - SL101: a bare sharding constraint pins the operand to the OTHER
      split mid-expression — an implicit GSPMD all-to-all no plan
      issued. (The public ``resplit`` no longer models this: it routes
      through ``ht.redistribution`` whose programs stamp their plan id
      into the HLO and downgrade to info — the accident this rule
      exists for is exactly the UNstamped relayout.)
    - SL102: a replicated constraint materializes a copy of the whole
      array (an all-gather of every byte);
    - SL105: the replicated output has the same aval as the argument but
      the buffer is not donated;
    - SL106: the debug arm reads the device value on the host — never
      taken at trace time, only the source scan can see it.
    """
    import jax.numpy as jnp
    from jax import lax

    phys = x._phys
    y = jnp.exp(lax.with_sharding_constraint(phys, x.comm.sharding(phys.ndim, 1)))
    z = lax.with_sharding_constraint(phys, x.comm.sharding(phys.ndim, None))
    if debug:
        host = jax.device_get(z)  # shardlint: ignore[SL201] -- fixture
        print(float(host.sum()))
    return y, z


def widening_program(x):
    """SL104: promotes the f32 operand to f64 mid-program (an accidental
    64-bit astype — no input justifies the widening)."""
    return ht.sum(x.astype(ht.float64) * 2.0)


def gather_reduce_program(x):
    """SL103 (and SL102): gathers the whole operand replicated, then
    reduces it — the textbook case where reduce-scatter (or a local
    reduce + tiny all-reduce, what ``ht.sum`` on the SHARDED array
    compiles to) moves O(1/p) of the bytes."""
    return ht.sum(x.resplit(None))


def donated_program(x):
    """Clean twin of ``bad_program``'s SL105 arm: same aliasable output,
    but the wrapper donates the argument."""
    return ht.exp(x)


def int8_wire_program(x):
    """SL104 (narrowing arm): a hand-rolled UNSCALED ``astype(int8)``
    feeding a psum — the gradient-compression accident: values outside
    [-128, 127] truncate and the int8 reduction wraps. The sanctioned
    narrowing is the STAMPED block-quantized wire codec
    (``heat_tpu.kernels.quant``: per-tile scales, reserved special
    codes, ``wire_codec_<mode>`` named scope) — only codec-stamped
    converts downgrade to info; this one trips at error severity."""
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from heat_tpu.core._jax_compat import shard_map

    comm = x.comm
    phys = x._phys

    def body(xl):
        # no scale, no specials, straight into the collective
        return lax.psum(xl.astype(jnp.int8), comm.axis_name).astype(jnp.float32)

    spec = P(*(comm.axis_name if k == 0 else None for k in range(phys.ndim)))
    out = P(*(None,) * phys.ndim)
    return shard_map(
        body, mesh=comm.mesh, in_specs=(spec,), out_specs=out, check_vma=False
    )(phys)


def flat_dcn_a2a_program(x):
    """SL107 (cross-tier collective not decomposed): a hand-rolled FLAT
    all-to-all whose replica group spans every device — at a two-tier
    topology its whole payload completes at DCN speed (~8x ICI). The
    sanctioned form is the planner's ``hierarchical-a2a`` (intra-slice
    pivot + inter-slice exchange of pre-packed per-slice rows), whose
    stamped programs downgrade to info; this unstamped flat exchange
    trips the rule at warn/error when ``check(..., topology="SxC")``
    (or ``HEAT_TPU_TOPOLOGY``) declares a tiered mesh — and is
    perfectly clean at a flat topology, which is why SL101 alone never
    catches it."""
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from heat_tpu.core._jax_compat import shard_map

    comm = x.comm
    phys = x._phys

    def body(xl):
        return lax.all_to_all(xl, comm.axis_name, 0, 0, tiled=True)

    spec = P(*(comm.axis_name if k == 0 else None for k in range(phys.ndim)))
    return shard_map(
        body, mesh=comm.mesh, in_specs=(spec,), out_specs=spec, check_vma=False
    )(phys)


def ppermute_ring_program(x):
    """SL101: a hand-rolled ppermute relayout loop with NO plan stamp —
    every hop ships the whole local shard around the ring (an all-gather
    in disguise, (p-1)x the bytes of a planned exchange). The planner's
    own ring/pipelined programs run under ``redist_plan_<id>`` /
    ``cmatmul_ring_<tag>`` named scopes and downgrade to info; the
    accident SL101 exists for is exactly this UNstamped chain."""
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from heat_tpu.core._jax_compat import shard_map

    comm = x.comm
    p = comm.size
    phys = x._phys

    def body(xl):
        acc = xl
        for d in range(1, p):
            acc = lax.ppermute(
                acc, comm.axis_name, [(s, (s + 1) % p) for s in range(p)]
            )
        return acc

    spec = P(*(comm.axis_name if k == 0 else None for k in range(phys.ndim)))
    return shard_map(
        body, mesh=comm.mesh, in_specs=(spec,), out_specs=spec, check_vma=False
    )(phys)


def over_budget_program(x):
    """SL301 (ISSUE 10): holds three full-size intermediates live
    simultaneously — the liveness peak is ~4x the operand's shard, so
    under a tiny forced budget (``memcheck(..., hbm_bytes=...)`` or
    ``HEAT_TPU_HBM_BYTES``) the static estimate overcommits HBM and the
    check reports SL301 at error severity BEFORE any dispatch OOMs.
    Under the default 16 GiB budget the same program is clean — the
    rule prices programs against the deployment target, it does not
    punish intermediates per se."""
    a = ht.exp(x)
    b = ht.sqrt(ht.abs(x) + 1.0)
    c = a * b
    return a + b + c  # a, b, c all live at the final combine


def dropped_donation_program(x):
    """SL302 (ISSUE 10): the caller DONATES ``x`` (the test wraps this
    in ``ht.jit(..., donate_argnums=0)``), but the only output is half
    the rows — no output matches the donated aval, XLA cannot alias the
    buffer, and the donation is silently dropped: the compiled module
    carries no ``input_output_alias`` entry for the parameter while the
    caller believes the HBM was reclaimed. SL105's bookkeeping alone
    cannot see this (donation WAS declared); only the executable-level
    check can."""
    return ht.exp(x)[: x.shape[0] // 2]


def replicated_liverange_program(x):
    """SL303 (ISSUE 10): materializes a REPLICATED copy of the whole
    operand (``resplit(None)`` — every device holds all the bytes) and
    then keeps it live across a two-collective resplit round trip
    before finally consuming it. The planner's peak accounting budgets
    each exchange's transients, but the replicated value's residency
    rides across the whole chain unseen — exactly the live-range
    materialization memcheck's liveness analysis exists to surface."""
    g = x.resplit(None)              # replicated materialization, held ...
    y = x.resplit(1).resplit(0)      # ... across two collective steps
    return g * 1.0 + y


# --------------------------------------------------------------------- #
# pass 4 (ISSUE 12): gatecheck + racecheck golden bad fixtures           #
# --------------------------------------------------------------------- #
_donating_double = None


def use_after_donate_program(x):
    """SL401: the inner program DONATES its operand (ht.jit
    donate_argnums — resolved through the shared analysis/_donation.py
    resolver), and the caller then reads the donated array again. The
    donating program may already have overwritten the buffer in place;
    on hardware the second read returns garbage nondeterministically,
    which is exactly why the rule is static (jaxpr dataflow: the
    donated invar is dead past the pjit equation that donates it)."""
    global _donating_double
    if _donating_double is None:
        _donating_double = ht.jit(lambda a: a * 2.0, donate_argnums=0)
    y = _donating_double(x)
    return y + x  # x's buffer was donated one line up


def donate_then_done_program(x):
    """Clean twin of ``use_after_donate_program``: same donating inner
    call, but the donated operand is never touched again."""
    global _donating_double
    if _donating_double is None:
        _donating_double = ht.jit(lambda a: a * 2.0, donate_argnums=0)
    return _donating_double(x) + 1.0


#: SL402 (lru arm): a cached program builder that resolves the overlap
#: gate INSIDE its body — the cache key (the parameters) no longer
#: carries the gate, so a HEAT_TPU_REDIST_OVERLAP flip keeps serving
#: the program compiled under the old value. The fix the finding names:
#: resolve at the caller, pass `pipelined` as a parameter (exactly what
#: redistribution/executor.py does).
STALE_KEY_BUILDER_SRC = '''
import functools

from heat_tpu.redistribution.planner import overlap_mode


@functools.lru_cache(maxsize=512)
def _move_program(comm, spec, budget):
    pipelined = overlap_mode() != "0"   # ambient read under the cache
    return (comm, spec, budget, pipelined)
'''

#: SL402 (dict arm): a plan cache whose key tuple DROPS the resolved
#: topology — the planner's own `key = (spec, b, qmode, topo)` with one
#: component deleted, the exact omission class the PR 9/10 hardening
#: lists kept catching by review.
STALE_DICT_KEY_SRC = '''
_plan_cache = {}


def wire_quant_gate():
    return None


def resolve_topology(n):
    return None


def plan(spec, budget):
    qmode = wire_quant_gate()
    topo = resolve_topology(8)
    key = (spec, budget, qmode or "0")   # topo missing from the key
    cached = _plan_cache.get(key)
    if cached is not None:
        return cached
    _plan_cache[key] = spec
    return spec
'''

#: SL403: raw HEAT_TPU_* reads bypassing the registry — a literal get,
#: the hand-rolled fingerprint enumeration, and a containment probe.
RAW_GATE_READ_SRC = '''
import os


def read_gate():
    return os.environ.get("HEAT_TPU_REDIST_OVERLAP", "auto")


def fingerprint():
    return sorted(k for k in os.environ.keys() if k.startswith("HEAT_TPU_"))


def probe():
    return "HEAT_TPU_OOC" in os.environ
'''

#: SL404: the dispatcher's shape with the counts lock MISSING on the
#: client path — the worker mutates under the lock, stats() reads bare.
UNGUARDED_ATTR_SRC = '''
import threading


class BadDispatcher:
    def __init__(self):
        self._counts_lock = threading.Lock()
        self._counts = {"batches": 0}
        self._thread = None

    def start(self):
        self._thread = threading.Thread(target=self._worker, daemon=True)
        self._thread.start()

    def _worker(self):
        with self._counts_lock:
            self._counts["batches"] += 1

    def stats(self):
        return dict(self._counts)   # client read, no lock
'''

#: SL405: three broken depth-2 skeletons — the inverted loop (consume
#: lap k before issuing lap k+1), the unfenced read (consuming the lap
#: it JUST issued), and the dropped final lap — plus the correct
#: rotation (`good_laps`, the executor's `_run_laps` shape) as the
#: clean pin.
PIPELINE_PROTOCOL_SRC = '''
def inverted_laps(indices, issue, consume, state):
    idx = list(indices)
    prev = issue(idx[0])
    for i in range(1, len(idx)):
        state = consume(state, prev, idx[i - 1])   # consume BEFORE issue
        prev = issue(idx[i])
    return consume(state, prev, idx[-1])


def unfenced_laps(indices, issue, consume, state):
    idx = list(indices)
    prev = issue(idx[0])
    for i in range(1, len(idx)):
        nxt = issue(idx[i])
        state = consume(state, nxt, idx[i])        # consumes the in-flight lap
        prev = nxt
    return consume(state, prev, idx[-1])


def dropped_lap(indices, issue, consume, state):
    idx = list(indices)
    prev = issue(idx[0])
    for i in range(1, len(idx)):
        nxt = issue(idx[i])
        state = consume(state, prev, idx[i - 1])
        prev = nxt
    return state                                    # final prefetch dropped


def good_laps(indices, issue, consume, state):
    idx = list(indices)
    prev = issue(idx[0])
    for i in range(1, len(idx)):
        nxt = issue(idx[i])
        state = consume(state, prev, idx[i - 1])
        prev = nxt
    return consume(state, prev, idx[-1])
'''

#: SL406 (ISSUE 13): the silent-swallow worker — a threaded request
#: loop whose `except Exception` neither re-raises, resolves a future,
#: nor forwards the caught object: the client's future never resolves
#: and the failure becomes a hang. The clean twins show each accepted
#: surfacing shape (typed future failure; forwarding the object into a
#: queue; delegating to an intra-class helper that fails futures).
SWALLOWED_WORKER_EXC_SRC = '''
import threading


class SwallowingWorker:
    def __init__(self):
        self._q = []
        self._thread = None

    def start(self):
        self._thread = threading.Thread(target=self._worker, daemon=True)
        self._thread.start()

    def _worker(self):
        while True:
            req = self._q.pop()
            try:
                req.run()
            except Exception:
                continue                      # swallowed: future never resolves


class ResolvingWorker:
    def __init__(self):
        self._q = []
        self._thread = None

    def start(self):
        self._thread = threading.Thread(target=self._worker, daemon=True)
        self._thread.start()

    def _worker(self):
        while True:
            req = self._q.pop()
            try:
                req.future.set_result(req.run())
            except Exception as e:
                req.future.set_exception(e)   # surfaced typed


class ForwardingWorker:
    def __init__(self):
        self._q = []
        self._out = []
        self._thread = None

    def start(self):
        self._thread = threading.Thread(target=self._worker, daemon=True)
        self._thread.start()

    def _worker(self):
        try:
            for req in self._q:
                self._out.append(req.run())
        except Exception as exc:
            self._out.append(("error", exc))  # forwarded to the consumer


class DelegatingWorker:
    def __init__(self):
        self._q = []
        self._thread = None

    def start(self):
        self._thread = threading.Thread(target=self._worker, daemon=True)
        self._thread.start()

    def _fail_all(self):
        for req in self._q:
            req.future.set_exception(RuntimeError("failed over"))

    def _worker(self):
        try:
            for req in self._q:
                req.run()
        except Exception:
            self._fail_all()                  # intra-class resolver helper


class LoggingSwallowWorker:
    def __init__(self, logger):
        self._q = []
        self._log = logger
        self._thread = None

    def start(self):
        self._thread = threading.Thread(target=self._worker, daemon=True)
        self._thread.start()

    def _worker(self):
        while True:
            req = self._q.pop()
            try:
                req.run()
            except Exception as e:
                self._log.warning("worker died: %s", e)  # log-and-continue: STILL a swallow
'''


# --------------------------------------------------------------------- #
# pass 5 (ISSUE 14): commcheck golden bad fixtures                       #
# --------------------------------------------------------------------- #
def divergent_cond_collective_program(x):
    """SL501: a ``lax.cond`` whose TRUE branch launches a full-axis psum
    is predicated on ``axis_index`` — the device-identity source, never
    replicated. Half the mesh enters the branch and issues the
    collective, the other half skips it: on TPU the psum never matches
    and the mesh hangs silently. The replication lattice proves the
    predicate varying and trips at error."""
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from heat_tpu.core._jax_compat import shard_map

    comm = x.comm
    phys = x._phys

    def body(xl):
        i = lax.axis_index(comm.axis_name)
        return lax.cond(
            i < comm.size // 2,
            lambda v: lax.psum(v, comm.axis_name),
            lambda v: v * 2.0,
            xl,
        )

    spec = P(*(comm.axis_name if k == 0 else None for k in range(phys.ndim)))
    return shard_map(
        body, mesh=comm.mesh, in_specs=(spec,), out_specs=spec, check_vma=False
    )(phys)


def uniform_cond_collective_program(x):
    """Clean twin of ``divergent_cond_collective_program`` — the fix the
    SL501 message names: the predicate is a FULL-AXIS psum of the local
    condition, so every device computes the same boolean and the
    branches stay congruent."""
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from heat_tpu.core._jax_compat import shard_map

    comm = x.comm
    phys = x._phys

    def body(xl):
        agree = lax.psum((xl.sum() > 0.0).astype(jnp.float32), comm.axis_name)
        return lax.cond(
            agree > 0.0,
            lambda v: lax.psum(v, comm.axis_name),
            lambda v: v * 2.0,
            xl,
        )

    spec = P(*(comm.axis_name if k == 0 else None for k in range(phys.ndim)))
    return shard_map(
        body, mesh=comm.mesh, in_specs=(spec,), out_specs=spec, check_vma=False
    )(phys)


def divergent_while_collective_program(x):
    """SL501 (while arm): the loop's continuation predicate reads the
    LOCAL shard (each device's values differ), so devices exit on
    different iterations — and the psum in the body stops matching on
    the first iteration some device has already left."""
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from heat_tpu.core._jax_compat import shard_map

    comm = x.comm
    phys = x._phys

    def body(xl):
        def cond_fn(c):
            return c[0] < c[1][0, 0]  # local-shard value: per-device trip count

        def body_fn(c):
            return c[0] + 1.0, lax.psum(c[1], comm.axis_name)

        _, out = lax.while_loop(cond_fn, body_fn, (jnp.float32(0.0), xl))
        return out

    spec = P(*(comm.axis_name if k == 0 else None for k in range(phys.ndim)))
    return shard_map(
        body, mesh=comm.mesh, in_specs=(spec,), out_specs=spec, check_vma=False
    )(phys)


def open_ring_program(x):
    """SL502: a hand-rolled ppermute whose pairs DROP the wraparound
    edge — ``(s, s+1)`` for ``s < p-1`` only. Device 0 sends but never
    receives, device p-1 receives but never sends: the ring never
    closes and the unmatched device waits forever. The congruence scan
    reads the compiled ``source_target_pairs`` and trips at error; the
    fix it names is ``kernels.cmatmul.grouped_ring_perm`` (the one
    place the complete +1 ring is built)."""
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from heat_tpu.core._jax_compat import shard_map

    comm = x.comm
    p = comm.size
    phys = x._phys

    def body(xl):
        return lax.ppermute(
            xl, comm.axis_name, [(s, s + 1) for s in range(p - 1)]
        )

    spec = P(*(comm.axis_name if k == 0 else None for k in range(phys.ndim)))
    return shard_map(
        body, mesh=comm.mesh, in_specs=(spec,), out_specs=spec, check_vma=False
    )(phys)


def opposite_order_collectives_program(x):
    """SL503 (cycle arm, error): a DIVERGENT cond whose two branches
    issue the same two full-axis collectives in OPPOSITE orders — psum
    then pmax on one side, pmax then psum on the other. Devices taking
    different branches each wait for the collective the other has not
    issued yet: a cross-group dependency cycle in the channel graph
    (also trips SL501 — the divergence is what arms the cycle)."""
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from heat_tpu.core._jax_compat import shard_map

    comm = x.comm
    phys = x._phys

    def body(xl):
        i = lax.axis_index(comm.axis_name)

        def lo(v):
            return lax.pmax(lax.psum(v, comm.axis_name), comm.axis_name)

        def hi(v):
            return lax.psum(lax.pmax(v, comm.axis_name), comm.axis_name)

        return lax.cond(i < comm.size // 2, lo, hi, xl)

    spec = P(*(comm.axis_name if k == 0 else None for k in range(phys.ndim)))
    return shard_map(
        body, mesh=comm.mesh, in_specs=(spec,), out_specs=spec, check_vma=False
    )(phys)


def overlapping_groups_program(x):
    """SL503 (independent arm, warning): two INDEPENDENT grouped psums
    whose group partitions partially overlap — halves vs neighbor pairs
    — with no dataflow ordering between them. Participants shared by
    unequal groups may observe the two collectives in different issue
    orders (the compiler is free to schedule them per-participant).
    Requires an even mesh of >= 4 devices."""
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from heat_tpu.core._jax_compat import shard_map

    comm = x.comm
    p = comm.size
    phys = x._phys
    halves = [list(range(p // 2)), list(range(p // 2, p))]
    pairs = [[2 * k, 2 * k + 1] for k in range(p // 2)]

    def body(xl):
        a = lax.psum(xl, comm.axis_name, axis_index_groups=halves)
        b = lax.psum(xl * 2.0, comm.axis_name, axis_index_groups=pairs)
        return a + b

    spec = P(*(comm.axis_name if k == 0 else None for k in range(phys.ndim)))
    return shard_map(
        body, mesh=comm.mesh, in_specs=(spec,), out_specs=spec, check_vma=False
    )(phys)


def aligned_groups_program(x):
    """Clean twin of ``overlapping_groups_program`` — the fix the SL503
    message names: both psums ride the SAME partition, so every
    participant agrees on the group structure and order cannot
    diverge."""
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from heat_tpu.core._jax_compat import shard_map

    comm = x.comm
    p = comm.size
    phys = x._phys
    halves = [list(range(p // 2)), list(range(p // 2, p))]

    def body(xl):
        a = lax.psum(xl, comm.axis_name, axis_index_groups=halves)
        b = lax.psum(xl * 2.0, comm.axis_name, axis_index_groups=halves)
        return a + b

    spec = P(*(comm.axis_name if k == 0 else None for k in range(phys.ndim)))
    return shard_map(
        body, mesh=comm.mesh, in_specs=(spec,), out_specs=spec, check_vma=False
    )(phys)


#: SL504: a dispatcher-shaped module whose public entry issues the
#: bucket program with NO epoch fence reachable on its intra-module
#: closure — work dispatched across a world re-resolution hangs on
#: devices that are gone instead of failing typed. The clean twin below
#: shows the sanctioned shape (``elastic.check_epoch`` on entry — the
#: serving Endpoint's own idiom since ISSUE 14).
UNFENCED_DISPATCH_SRC = '''
import threading


class BareEndpoint:
    def __init__(self, programs):
        self.programs = programs
        self._thread = None

    def start(self):
        self._thread = threading.Thread(target=self._worker, daemon=True)
        self._thread.start()

    def run(self, batch, bucket):
        return self.programs[bucket](batch)   # no fence on the entry path

    def _worker(self):
        self.run(None, 0)
'''

#: the fenced twin: one ``check_epoch`` call on the entry makes the
#: whole intra-module closure fenced (same reachability SL402 uses).
FENCED_DISPATCH_SRC = '''
from heat_tpu.resilience.elastic import check_epoch


class FencedEndpoint:
    def __init__(self, programs):
        self.programs = programs
        self._token = None

    def run(self, batch, bucket):
        check_epoch(self._token, what="fixture endpoint")
        return self.programs[bucket](batch)
'''


def serving_sync_handler(x):
    """SL106 (ISSUE 9): a serving request handler that reads device
    VALUES on the host mid-request — a debug/logging sync buried in the
    dispatch→result hot path. One such read serializes the dispatcher's
    whole pipeline behind a host round trip (every queued request
    behind it eats the latency), which is exactly why the serving
    budget is ZERO undeclared ``device_get`` between dispatch and
    result; the dispatcher's own fence is ``block_until_ready``
    (completion, no transfer). ``ht.analysis.check`` aborts the trace
    at the concretizing read and reports SL106; the source scan flags
    the line even when the branch is untaken."""
    import jax

    y = x * 2.0
    if getattr(serving_sync_handler, "_debug", True):
        peek = jax.device_get(y._phys)  # shardlint: ignore[SL201] -- fixture
        print("serving batch mean:", peek.mean())
    return y + 1.0


# --------------------------------------------------------------------- #
# pass 6 (ISSUE 17): numcheck golden bad fixtures                        #
# --------------------------------------------------------------------- #
# Pure-jax programs over jnp arrays (numcheck's calling contract admits
# them like check's): the wrong-number class is a property of the traced
# jaxpr's dtypes, not of the DNDarray layer. Each bad fixture has a
# clean twin one fix away — the fix the finding message names.
def low_precision_gram_program(x):
    """SL601: a bf16 gram matrix accumulated IN bf16 — the contraction
    runs over the full feature extent (>= the acc-dim threshold) and
    every MXU pass rounds the partial sum to 8 mantissa bits. The fix
    is ONE argument: ``preferred_element_type=jnp.float32`` (see
    cluster/_pallas.py's gram builders — accumulate wide, store
    narrow)."""
    import jax.numpy as jnp

    return jnp.matmul(x.T, x)  # bf16 @ bf16 -> bf16 accumulator


def f32_accum_gram_program(x):
    """Clean twin of ``low_precision_gram_program``: same bf16 operands,
    same contraction — the accumulator is f32 via
    ``preferred_element_type`` (the sanctioned form SL601's message
    names)."""
    import jax.numpy as jnp

    return jnp.matmul(x.T, x, preferred_element_type=jnp.float32)


def low_precision_reduce_program(x):
    """SL601 (reduce arm, error extent): a raw bf16 reduce_sum over the
    whole axis — ``jnp.sum`` would auto-upcast (and is therefore
    clean), so the bad form binds the primitive the way a custom
    kernel's reference or a transpose rule would."""
    import jax

    return jax.lax.reduce_sum_p.bind(x, axes=(0,))


def upcast_reduce_program(x):
    """Clean twin of ``low_precision_reduce_program``: upcast before the
    sum, narrow after — also exactly what ``jnp.sum(x)`` emits for
    bf16 input."""
    import jax.numpy as jnp

    return jnp.sum(x, axis=0).astype(x.dtype)


def gauss_default_precision_program(ar, ai, br, bi):
    """SL602: the planar-complex Gauss 3-multiply form at DEFAULT MXU
    precision — ``p3 - p1 - p2`` recovers the imaginary part by
    cancellation of products sharing operands, and default (bf16)
    passes turn that into up to 13% relative error on chip (the PR 5
    live defect, re-created)."""
    import jax.numpy as jnp

    p1 = jnp.matmul(ar, br)
    p2 = jnp.matmul(ai, bi)
    p3 = jnp.matmul(ar + ai, br + bi)
    return p1 - p2, p3 - p1 - p2


def gauss_highest_precision_program(ar, ai, br, bi):
    """Clean twin of ``gauss_default_precision_program``: the same form
    with every dot stamped ``Precision.HIGHEST`` — exact f32 MXU
    products, the sanctioned planar lowering (numcheck reports it at
    info, never gating)."""
    import jax
    import jax.numpy as jnp

    hp = jax.lax.Precision.HIGHEST
    p1 = jnp.matmul(ar, br, precision=hp)
    p2 = jnp.matmul(ai, bi, precision=hp)
    p3 = jnp.matmul(ar + ai, br + bi, precision=hp)
    return p1 - p2, p3 - p1 - p2


def gauss_pragma_acknowledged_program(ar, ai, br, bi):
    """Pragma twin of ``gauss_default_precision_program``: the same
    cancellation-prone form, acknowledged IN SOURCE — the pragma names
    the rule and the reason, and numcheck downgrades SL602 to info
    (recorded, never gating)."""
    # numcheck: ignore[SL602] -- validated against the f64 reference path
    import jax.numpy as jnp

    p1 = jnp.matmul(ar, br)
    p2 = jnp.matmul(ai, bi)
    p3 = jnp.matmul(ar + ai, br + bi)
    return p1 - p2, p3 - p1 - p2


def bf16_carry_scan_program(x):
    """SL603 (carry arm): a running mean whose loop carry is CAST to
    bf16 before the scan — every lap re-rounds the accumulated state
    to 8 mantissa bits (the KMeans bf16-counts bug, re-created as the
    scan shape)."""
    import jax
    import jax.numpy as jnp

    def body(mean, row):
        return 0.9 * mean + 0.1 * row.astype(mean.dtype), ()

    mean0 = x[0].astype(jnp.bfloat16)  # f32 state narrowed INTO the loop
    mean, _ = jax.lax.scan(body, mean0, x)
    return mean


def f32_carry_scan_program(x):
    """Clean twin of ``bf16_carry_scan_program``: the carry stays f32;
    only the per-row payload may ride narrow."""
    import jax
    import jax.numpy as jnp

    def body(mean, row):
        return 0.9 * mean + 0.1 * row.astype(jnp.float32), ()

    mean0 = x[0].astype(jnp.float32)
    mean, _ = jax.lax.scan(body, mean0, x)
    return mean


def bf16_ef_carry_program(carry, grad):
    """SL603 (cross-program arm): a DP-style error-feedback step that
    returns its residual carry DOWN-CAST to bf16 — the carry rides the
    ``ht.jit`` boundary back in next step, and the residual it stores
    IS the low-order bits the cast throws away (the contract
    optim/dp_optimizer.py keeps by holding its EF carry in f32)."""
    import jax.numpy as jnp

    h = grad + carry                      # compensate
    update = jnp.round(h * 8.0) / 8.0     # coarse quantized apply
    residual = h - update
    return update, residual.astype(jnp.bfloat16)  # carry dies here


def f32_ef_carry_program(carry, grad):
    """Clean twin of ``bf16_ef_carry_program``: the residual carry
    returns in full f32 width."""
    import jax.numpy as jnp

    h = grad + carry
    update = jnp.round(h * 8.0) / 8.0
    return update, h - update


def f64_request_program(x):
    """SL604: requests f64 mid-program. Under the x64-disabled platform
    policy (core/devices.py — TPU runs x64 off) the astype silently
    degrades to f32 at trace time: the jaxpr shows float32 everywhere
    and only the source scan can see the unmet request."""
    import jax.numpy as jnp

    return jnp.cumsum(x.astype(jnp.float64))


def f32_request_program(x):
    """Clean twin of ``f64_request_program``: requests the f32 the
    platform actually provides — the narrowing is visible in the
    source."""
    import jax.numpy as jnp

    return jnp.cumsum(x.astype(jnp.float32))


# --------------------------------------------------------------------- #
# ISSUE 18: sparse-engine fixtures                                      #
# --------------------------------------------------------------------- #
def gather_per_row_spmv_program(comm, m, rows, indices, data, x):
    """ISSUE 18 golden bad-fixture: gather-the-world SpMV.

    The anti-pattern the brick engine exists to avoid — three
    violations:

    - SL101: the dense operand relays to the OTHER split through a bare
      sharding constraint (an implicit all-to-all no redistribution plan
      stamped; the engine routes this through ``comm.reshard_phys``);
    - SL102: the nnz-sharded stored values materialize replicated (an
      all-gather of every stored element — the engine's shard_map local
      program needs only the device's own brick slab);
    - SL103: the gathered values then feed a full dense reduction (the
      per-multiply normalization), where a local reduce + small
      all-reduce moves O(1/p) of the bytes.

    The sparse components arrive as TRACED arguments (the caller must
    not close over them: a closure-captured component is inlined as a
    replicated constant, and the gathers this fixture exists to pin
    vanish from the compiled program).
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    # SL101: bare constraint pins the dense operand to split 1
    xs = lax.with_sharding_constraint(x, comm.sharding(x.ndim, 1))
    # SL102: every stored element gathered to every device
    data_r = lax.with_sharding_constraint(data, comm.sharding(1, None))
    idx_r = lax.with_sharding_constraint(indices, comm.sharding(1, None))
    rows_r = lax.with_sharding_constraint(rows, comm.sharding(1, None))
    contrib = data_r[:, None] * jnp.take(xs, idx_r, axis=0)
    y = jax.ops.segment_sum(contrib, rows_r, num_segments=m)
    # SL103: the replicated gather feeds a full reduction
    return y / jnp.sum(data_r)


def make_pagerank_step(comm, m, nb, B, alpha=0.85):
    """The device program of one PageRank sweep — the engine SpMV plus
    the damping/teleport affine map. Pinned LINT-CLEAN (ircheck +
    memcheck + numcheck) by tests/test_analysis.py: the fixpoint loop's
    entire device side must stay collective-free on the local program
    and free of implicit reshards."""
    import jax
    import jax.numpy as jnp

    from heat_tpu.kernels import spmm as kspmm

    spmv = kspmm.spmm_bcsr_program(comm, m, nb, B, 0, 1, "float32", "xla")

    def step(bdata, bcol, brow, bmask, r, teleport):
        y = spmv(bdata, bcol, brow, bmask, r[:, None])
        return y * jnp.float32(alpha) + teleport

    return step


# --------------------------------------------------------------------- #
# ISSUE 19: dense-factorization fixtures                                #
# --------------------------------------------------------------------- #
def gather_inv_program(x, check_cond=False):
    """ISSUE 19 golden bad-fixture: the pre-factorization inverse path
    writ explicit — gather the whole sharded matrix replicated and hand
    the copy to XLA's one-device LU inverse.

    - SL102: the replicated constraint materializes every byte of the
      operand on every device (an all-gather of the full matrix — the
      blocked ring-LU of ``ht.linalg.inv``/``solve`` moves only
      block-panel ppermutes, its clean twin pinned alongside);
    - SL106: the debug arm reads the conditioning estimate back on the
      host — never taken at trace time, only the source scan sees it.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    phys = x._phys
    # SL102: whole-operand replicated materialization
    rep = lax.with_sharding_constraint(phys, x.comm.sharding(phys.ndim, None))
    out = jnp.linalg.inv(rep)
    if check_cond:
        host = jax.device_get(out)  # shardlint: ignore[SL201] -- fixture
        print(float(abs(host).max()))  # SL106: host concretization
    return out
