"""End-to-end smoke tests: the reference's scripts/heat_test.py workload
(``ht.arange(N, split=0).sum()`` — SURVEY.md §3.1) plus basic factory/op/
distribution sanity across splits on the 8-device CPU mesh."""

import numpy as np

import heat_tpu as ht

from test_suites.basic_test import TestCase


class TestSmoke(TestCase):
    def test_mesh(self):
        import jax

        self.assertEqual(self.comm.size, len(jax.devices()))

    def test_arange_sum(self):
        # reference smoke test: scripts/heat_test.py
        a = ht.arange(2 * 3 * 4, split=0)
        self.assertEqual(a.split, 0)
        self.assertEqual(a.shape, (24,))
        s = a.sum()
        self.assertIsInstance(s, ht.DNDarray)
        self.assertEqual(s.split, None)
        self.assertEqual(int(s), 276)

    def test_arange_parity(self):
        self.assert_array_equal(ht.arange(10, split=0), np.arange(10, dtype=np.int32))
        self.assert_array_equal(ht.arange(1, 7, 2), np.arange(1, 7, 2, dtype=np.int32))
        self.assert_array_equal(
            ht.arange(0.0, 1.0, 0.1, split=0), np.arange(0.0, 1.0, 0.1, dtype=np.float32)
        )

    def test_factories_parity(self):
        for split in (None, 0, 1):
            self.assert_array_equal(ht.zeros((7, 5), split=split), np.zeros((7, 5), np.float32))
            self.assert_array_equal(ht.ones((7, 5), split=split), np.ones((7, 5), np.float32))
            self.assert_array_equal(
                ht.full((7, 5), 3.5, split=split), np.full((7, 5), 3.5, np.float32)
            )
        self.assert_array_equal(ht.eye(4, split=0), np.eye(4, dtype=np.float32))
        self.assert_array_equal(
            ht.linspace(0, 1, 11, split=0), np.linspace(0, 1, 11).astype(np.float32)
        )

    def test_array_from_data(self):
        data = np.random.randn(9, 4).astype(np.float32)
        for split in (None, 0, 1):
            x = ht.array(data, split=split)
            self.assertEqual(x.split, split)
            self.assert_array_equal(x, data)
        # dtype inference from python scalars stays canonical (float32/int32)
        self.assertEqual(ht.array([1.0, 2.0]).dtype, ht.float32)
        self.assertEqual(ht.array([1, 2]).dtype, ht.int32)
        self.assertEqual(ht.array(True).dtype, ht.bool)

    def test_binary_ops_mixed_splits(self):
        a_np = np.random.randn(8, 6).astype(np.float32)
        b_np = np.random.randn(8, 6).astype(np.float32)
        for sa in (None, 0, 1):
            for sb in (None, 0, 1):
                a = ht.array(a_np, split=sa)
                b = ht.array(b_np, split=sb)
                c = a + b
                np.testing.assert_allclose(c.numpy(), a_np + b_np, rtol=1e-6)
        # scalar ops preserve dtype
        x = ht.ones((4,), dtype=ht.float32, split=0)
        self.assertEqual((x + 1).dtype, ht.float32)
        self.assertEqual((x * 2.0).dtype, ht.float32)

    def test_reductions(self):
        # seeded LOCAL generator: an unseeded draw occasionally sums to
        # ~0, where a pure relative tolerance on the f32 global sum flakes
        # on accumulation order (and the global np stream must not mutate)
        data = np.random.default_rng(5).standard_normal((6, 8, 4)).astype(np.float32)
        for split in (None, 0, 1, 2):
            x = ht.array(data, split=split)
            self.assert_array_equal(x.sum(axis=0), data.sum(axis=0))
            self.assert_array_equal(x.sum(axis=1), data.sum(axis=1))
            self.assert_array_equal(x.sum(axis=(0, 2)), data.sum(axis=(0, 2)))
            np.testing.assert_allclose(float(x.sum()), data.sum(), rtol=1e-4, atol=1e-4)
            self.assert_array_equal(
                x.sum(axis=1, keepdims=True), data.sum(axis=1, keepdims=True)
            )

    def test_resplit(self):
        data = np.arange(24, dtype=np.float32).reshape(6, 4)
        x = ht.array(data, split=0)
        y = x.resplit(1)
        self.assertEqual(y.split, 1)
        self.assert_array_equal(y, data)
        x.resplit_(None)
        self.assertEqual(x.split, None)
        self.assert_array_equal(x, data)
        x.resplit_(1)
        self.assertEqual(x.split, 1)
        self.assert_array_equal(x, data)

    def test_lshape_map(self):
        p = self.comm.size
        x = ht.zeros((10, 4), split=0)
        lmap = x.lshape_map
        self.assertEqual(lmap.shape, (p, 2))
        self.assertEqual(lmap[:, 0].sum(), 10)
        # ceil-division convention: first shard holds the full block
        self.assertEqual(lmap[0, 0], min(10, -(-10 // p)))

    def test_getitem_setitem(self):
        data = np.arange(48, dtype=np.float32).reshape(8, 6)
        for split in (None, 0, 1):
            x = ht.array(data, split=split)
            np.testing.assert_allclose(x[2].numpy(), data[2])
            np.testing.assert_allclose(x[1:5].numpy(), data[1:5])
            np.testing.assert_allclose(x[:, 2:4].numpy(), data[:, 2:4])
            np.testing.assert_allclose(x[3, 4].numpy(), data[3, 4])
            y = ht.array(data, split=split)
            y[0] = 0.0
            expected = data.copy()
            expected[0] = 0.0
            np.testing.assert_allclose(y.numpy(), expected)

    def test_advanced_setitem_parity(self):
        """Integer-array and bool-mask assignment across splits — the
        fast paths scatter on the physical array (pad rows untouched),
        the rest falls back; all must match numpy semantics, and the
        zero-pad invariant must survive."""
        data = np.arange(44, dtype=np.float32).reshape(11, 4)
        idx = np.array([0, 3, 10, -1, 5])
        for split in (None, 0, 1):
            # integer-array scatter, scalar value
            y = ht.array(data, split=split)
            y[idx] = -1.0
            expected = data.copy(); expected[idx] = -1.0
            np.testing.assert_allclose(np.asarray(y.numpy()), expected)
            # integer-array scatter, row values
            y = ht.array(data, split=split)
            rows = np.full((5, 4), 9.0, dtype=np.float32)
            y[idx] = rows
            expected = data.copy(); expected[idx] = rows
            np.testing.assert_allclose(np.asarray(y.numpy()), expected)
            # bool-mask scatter (DNDarray mask), scalar value
            y = ht.array(data, split=split)
            mask = y > 30.0
            y[mask] = 0.0
            expected = data.copy(); expected[expected > 30.0] = 0.0
            np.testing.assert_allclose(np.asarray(y.numpy()), expected)
            # pad rows must still be zero after the in-place scatters
            import jax

            phys = np.asarray(jax.device_get(y._phys))
            if split is not None and phys.shape[split] > y.shape[split]:
                tail = [slice(None)] * y.ndim
                tail[split] = slice(y.shape[split], None)
                assert np.all(phys[tuple(tail)] == 0)

    def test_setitem_out_of_range_indices_dropped(self):
        """Out-of-range integer indices are dropped (old advanced-path
        behavior) and must NEVER land in the physical pad region — the
        zero-pad invariant feeds pad-safe kernels like TSQR."""
        import jax

        data = np.arange(22, dtype=np.float32).reshape(11, 2)
        y = ht.array(data, split=0)
        y[np.array([11])] = 99.0   # past the end
        y[np.array([-12])] = 55.0  # double-wrap hazard
        y[np.array([12], dtype=np.int8)] = 44.0   # narrow dtype sentinel overflow hazard
        np.testing.assert_allclose(np.asarray(y.numpy()), data)
        # unsigned keys must ASSIGN (not silently drop): -n0 would promote
        # into the unsigned domain without the signed widening
        y2 = ht.array(data, split=0)
        y2[np.array([1, 2], dtype=np.uint32)] = -5.0
        expected = data.copy(); expected[[1, 2]] = -5.0
        np.testing.assert_allclose(np.asarray(y2.numpy()), expected)
        phys = np.asarray(jax.device_get(y._phys))
        if phys.shape[0] > 11:
            assert np.all(phys[11:] == 0)
        r = ht.linalg.qr(y).R
        ref_r = np.linalg.qr(data)[1]
        np.testing.assert_allclose(
            np.abs(np.asarray(r.numpy())), np.abs(ref_r), rtol=1e-4
        )

    def test_item_and_scalar_conversion(self):
        x = ht.array([[5.0]], split=0)
        self.assertEqual(x.item(), 5.0)
        self.assertEqual(float(x), 5.0)
        self.assertEqual(int(x), 5)

    def test_astype(self):
        x = ht.arange(10, split=0)
        y = x.astype(ht.float64)
        self.assertEqual(y.dtype, ht.float64)
        self.assert_array_equal(y, np.arange(10, dtype=np.float64))

    def test_promotion(self):
        self.assertEqual(ht.promote_types(ht.int32, ht.float32), ht.float32)
        self.assertEqual(ht.promote_types(ht.int64, ht.float32), ht.float32)
        self.assertEqual(ht.promote_types(ht.uint8, ht.int8), ht.int16)
        self.assertEqual(ht.promote_types(ht.bfloat16, ht.float32), ht.float32)
        x = ht.ones((3,), dtype=ht.int32)
        y = ht.ones((3,), dtype=ht.float32)
        self.assertEqual((x + y).dtype, ht.float32)

    def test_elementwise_parity(self):
        self.assert_func_equal((5, 5), ht.exp, np.exp, data_types=(np.float32,))
        self.assert_func_equal((5, 5), ht.sin, np.sin, data_types=(np.float32,))
        self.assert_func_equal((5, 5), ht.sqrt, np.abs, data_types=())  # no-op guard
        data = np.random.rand(5, 5).astype(np.float32) + 0.1
        for split in (None, 0, 1):
            x = ht.array(data, split=split)
            self.assert_array_equal(ht.sqrt(x), np.sqrt(data))
            self.assert_array_equal(ht.log(x), np.log(data))

    def test_trig_int_promotes(self):
        x = ht.arange(5, split=0)
        y = ht.sin(x)
        self.assertEqual(y.dtype, ht.float32)

    def test_repr(self):
        x = ht.arange(5, split=0)
        s = str(x)
        self.assertIn("DNDarray", s)
        self.assertIn("dtype=ht.int32", s)
        self.assertIn("split=0", s)

    def test_bfloat16_extension(self):
        x = ht.ones((4, 4), dtype=ht.bfloat16, split=0)
        self.assertEqual(x.dtype, ht.bfloat16)
        self.assertEqual(x.nbytes, 32)
        y = x @ x
        np.testing.assert_allclose(y.numpy(), np.full((4, 4), 4.0), rtol=1e-2)


if __name__ == "__main__":
    import unittest

    unittest.main()
