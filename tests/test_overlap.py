"""Compute/communication overlap (ISSUE 6): software-pipelined
redistribution schedules and the collective-matmul linalg forms.

The contract pinned here, three ways:

1. **Model** — the Schedule IR's ``overlap`` annotation prices a
   pipelined stage pair at ``max(wire, copy)`` instead of the sum; the
   planner-chosen plans for the two 1 GB acceptance rows
   (``resplit_1gb``, ``reshape_split1_1gb``) model ≥ 1.3× effective
   GB/s vs their sequential form (``model_speedup`` — the bench
   ``critical_path_model`` field), and the annotation folds into the
   canonical serialization / ``plan_id``.
2. **Movement** — overlap-on == overlap-off is *bit-identical* with an
   *identical collective census* across the golden spec matrix: the
   pipelined program form is the same collectives in a prefetch-issue
   order writing the same disjoint regions. Compile-only census checks
   cover the multi-GB specs; the executable ones run both ways.
3. **Linalg** — TSQR's collective-matmul merge (the R-factor all-gather
   decomposed into a ppermute ring consumed block-by-block) is
   bit-identical to the barrier form and byte-equivalent on the wire
   (p-1 hops × the R block = the all-gather payload); the hSVD path
   inherits both through ``_merge_svd``; the split matmul's
   reduce-scatter/gather ring is sequential-vs-pipelined bit-identical
   and env-level exact on integer data.

``HEAT_TPU_REDIST_OVERLAP=0`` is the escape hatch (sequential oracle);
``=1`` forces pipelining — both legs run in ci.sh.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import heat_tpu as ht

from heat_tpu.core import _padding
from heat_tpu.core.communication import MeshCommunication
from heat_tpu.kernels import cmatmul
from heat_tpu.observability.hlo import _count_ops
from heat_tpu.redistribution import RedistSpec, executor, planner
from heat_tpu.redistribution.schedule import Schedule, Step

from test_suites.basic_test import TestCase, env_pin

P = len(jax.devices())
BUDGET = planner.DEFAULT_BUDGET_MB << 20


def _OverlapEnv(mode):
    """Pin HEAT_TPU_REDIST_OVERLAP for a block (shared env_pin helper)."""
    return env_pin(planner.OVERLAP_ENV, mode)


class TestOverlapAnnotation(TestCase):
    """Pure-Python model pins — no mesh, any device count."""

    def test_acceptance_rows_model_at_least_1_3x(self):
        """The acceptance criterion: planner-chosen overlapped plans for
        the resplit_1gb and reshape_split1_1gb bench rows model >= 1.3x
        effective GB/s vs the sequential plan. Pinned at topology="flat"
        (the tiered max(ici, dcn, copy) models are pinned in
        tests/test_topology.py)."""
        resplit = planner.plan(
            RedistSpec.normalize((1000, 250000), "float32", 0, 1, 8), BUDGET,
            topology="flat",
        )
        reshape = planner.plan(
            RedistSpec.normalize(
                (1000, 250000), "float32", 1, 1, 8, reshape_to=(10_000_000, 25)
            ),
            BUDGET,
            topology="flat",
        )
        for sched in (resplit, reshape):
            self.assertIsNotNone(sched.overlap, sched)
            self.assertEqual(sched.overlap_depth, 2)
            self.assertGreaterEqual(sched.overlap["model_speedup"], 1.3, sched)
            self.assertLess(
                sched.overlap["critical_path_bytes"],
                sched.overlap["sequential_bytes"],
            )
            self.assertLess(sched.critical_path_bytes, sched.sequential_model_bytes)

    def test_max_vs_sum_arithmetic(self):
        """Each group's critical path is w + (laps-1)*max(w, c) + c —
        first wire and last copy exposed, everything else pipelined."""
        sched = planner.plan(
            RedistSpec.normalize((1000, 250000), "float32", 0, 1, 8), BUDGET,
            topology="flat",
        )
        for g in sched.overlap["groups"]:
            w = g["wire_bytes"] // g["laps"]
            c = g["copy_bytes"] // g["laps"]
            self.assertEqual(
                g["critical_path_bytes"], w + (g["laps"] - 1) * max(w, c) + c
            )
            self.assertEqual(
                g["sequential_bytes"], g["wire_bytes"] + g["copy_bytes"]
            )

    def test_prime_extent_does_not_explode_lap_count(self):
        """Overlap-motivated chunking is best-effort: a pipelinable-size
        move whose chunk extent is PRIME has no small divisor, and the
        lap rule must fall back to the budget-only count (here one
        collective) instead of divisor-rounding to a million-step
        schedule (the regression: plan() built ~4M steps and sha1'd a
        multi-hundred-MB serialization)."""
        prime = 2097143  # prime, ~2M
        spec = RedistSpec.normalize((8 * prime, 16), "float32", 0, 1, 8)
        sched = planner.plan(spec, BUDGET)
        self.assertLessEqual(sched.n_steps, 8)
        self.assertLessEqual(
            sched.collective_counts().get("all-to-all", 0)
            + sched.collective_counts().get("collective-permute", 0),
            8,
        )

    def test_small_moves_stay_sequential(self):
        """Below the overlap grain nothing chunks: single-collective
        plans carry no annotation and their pinned censuses hold."""
        sched = planner.plan(RedistSpec.normalize((64, 48), "float32", 0, 1, 8), BUDGET)
        self.assertIsNone(sched.overlap)
        self.assertEqual(sched.overlap_depth, 1)
        self.assertEqual(sched.critical_path_bytes, sched.sequential_model_bytes)

    def test_ring_plans_annotate(self):
        """The ppermute ring pipelines too: hop d+1 flies while hop d's
        block scatters — (p-1) equal stage pairs, 2(p-1)/p modeled."""
        sched = planner.plan(
            RedistSpec.normalize((131072, 16384), "float32", 0, 1, 8), BUDGET,
            topology="flat",
        )
        self.assertEqual(sched.strategy, "ring")
        self.assertIsNotNone(sched.overlap)
        self.assertAlmostEqual(sched.overlap["model_speedup"], 2 * 7 / 8, places=3)

    def test_annotation_folds_into_plan_id(self):
        spec = RedistSpec.normalize((64, 48), "float32", 0, 1, 8)
        steps = [Step("all_to_all", bytes_moved=4)]
        plain = Schedule(spec, "all-to-all", steps, BUDGET)
        annotated = Schedule(
            spec, "all-to-all", steps, BUDGET,
            overlap=planner._overlap_annotation(
                [planner._overlap_group("pipe0", 2, 100, 100)]
            ),
        )
        self.assertNotEqual(plain.plan_id, annotated.plan_id)
        self.assertIn('"overlap":', annotated.canonical_json())

    def test_lap_steps_carry_the_pipe_tag(self):
        sched = planner.plan(
            RedistSpec.normalize((32768, 16384), "float32", 0, 1, 8), BUDGET
        )
        lap_tags = {s.overlap for s in sched.steps if s.chunk is not None}
        self.assertEqual(lap_tags, {"pipe0"})

    def test_explain_renders_overlap(self):
        """Satellite: ht.redistribution.explain() renders the overlap
        annotation and the modeled critical-path time per step."""
        x = ht.zeros((1000, 250000), split=0)
        sched = ht.redistribution.explain(x, 1, topology="flat")
        text = sched.describe()
        self.assertIn("overlap: depth=2", text)
        self.assertIn("model_speedup=", text)
        self.assertIn("pipe=pipe0", text)
        self.assertIn("model=max(wire", text)
        self.assertIn("overlap=depth2", repr(sched))
        # sequential plans say so
        small = ht.redistribution.explain(
            ht.zeros((64, 48), split=0), 1, topology="flat"
        )
        self.assertIn("overlap: none", small.describe())

    def test_overlap_mode_parsing(self):
        cases = {"0": "0", "off": "0", "1": "1", "force": "1", "auto": "auto", "": "auto"}
        for raw, want in cases.items():
            with _OverlapEnv(raw if raw else None):
                self.assertEqual(planner.overlap_mode(), want, raw)

    def test_plans_are_gate_independent(self):
        """The gate switches the executor's issue order, never the plan:
        identical serialization (and census) under =0 / =1 / auto."""
        spec = RedistSpec.normalize((32768, 16384), "float32", 0, 1, 8)
        dumps = []
        for mode in ("0", "1", None):
            with _OverlapEnv(mode):
                planner.clear_plan_cache()
                dumps.append(planner.plan(spec, BUDGET).canonical_json())
        self.assertEqual(dumps[0], dumps[1])
        self.assertEqual(dumps[1], dumps[2])


@pytest.mark.skipif(P < 2, reason="needs a real mesh")
class TestPipelinedExecutorParity(TestCase):
    """Acceptance: overlap-on == overlap-off bit-identical numerics and
    identical collective census across the golden spec matrix."""

    def _comm_for(self, mesh_size):
        if mesh_size == self.comm.size:
            return self.comm
        if mesh_size <= len(jax.devices()):
            return MeshCommunication(jax.devices()[:mesh_size])
        return None

    def test_golden_matrix_census_identical_both_forms(self):
        """Compile-only, covers the multi-GB specs: for every golden
        spec that lowers to a planner program, the sequential and the
        pipelined program both compile to exactly the plan's census."""
        checked = 0
        for name, spec in planner.golden_specs():
            comm = self._comm_for(spec.mesh_size)
            if comm is None:
                continue
            sched = planner.plan(spec, BUDGET)
            phys = _padding.phys_shape(spec.gshape, spec.src_split, spec.mesh_size)
            arg = jax.ShapeDtypeStruct(
                phys,
                np.dtype(spec.dtype),
                sharding=comm.sharding(len(phys), spec.src_split),
            )
            from test_redistribution import _planner_program

            for pipelined in (False, True):
                prog = _planner_program(comm, spec, BUDGET, pipelined)
                if prog is None:
                    break
                text = prog.lower(arg).compile().as_text()
                counts = {k: v for k, v in _count_ops(text).items() if v}
                self.assertEqual(counts, sched.collective_counts(), (name, pipelined))
            else:
                checked += 1
        if P >= 8:  # the golden matrix assumes the 8-device mesh
            self.assertGreaterEqual(checked, 9)

    def test_golden_matrix_bit_identical_where_executable(self):
        """Execute every golden spec small enough to allocate, under
        =0 and =1, and require byte-identical physical results (and the
        oracle layout)."""
        ran = 0
        for name, spec in planner.golden_specs():
            if spec.logical_bytes > (1 << 22) or spec.is_reshape:
                continue
            # ht.array places on the default comm: run the specs shaped
            # for THIS mesh (the compile-only census test covers the rest)
            if spec.mesh_size != self.comm.size or spec.src_split is None:
                continue
            comm = self.comm
            oracle = np.arange(spec.size, dtype=spec.dtype).reshape(spec.gshape)
            x = ht.array(oracle, split=spec.src_split)
            outs = {}
            for mode in ("0", "1"):
                with _OverlapEnv(mode):
                    outs[mode] = np.asarray(
                        executor.execute(comm, x._phys, spec)
                    )
            np.testing.assert_array_equal(outs["0"], outs["1"], err_msg=name)
            if spec.dst_split is not None:
                logical = np.asarray(
                    _padding.unpad(jnp.asarray(outs["1"]), spec.gshape, spec.dst_split)
                )
                np.testing.assert_array_equal(logical, oracle, err_msg=name)
            ran += 1
        if self.comm.size == 8:  # the golden matrix is p=8-shaped
            self.assertGreaterEqual(ran, 4)

    def test_chunked_and_ring_pipelines_bit_identical(self):
        """Tiny explicit budgets force multi-lap chunked pipelines and
        the ppermute ring; the pipelined issue order must reproduce the
        sequential program exactly."""
        oracle = np.arange(64 * 48, dtype=np.float32).reshape(64, 48)
        x = ht.array(oracle, split=0)
        spec = RedistSpec.normalize((64, 48), "float32", 0, 1, P)
        for budget in (384, 1024, 2048):
            sched = planner.plan(spec, budget)
            outs = {}
            for mode in ("0", "1"):
                with _OverlapEnv(mode):
                    y = executor.execute(self.comm, x._phys, spec, sched)
                    outs[mode] = np.asarray(y)
                    np.testing.assert_array_equal(
                        np.asarray(_padding.unpad(y, (64, 48), 1)), oracle
                    )
            np.testing.assert_array_equal(outs["0"], outs["1"], err_msg=str(budget))

    def test_reshape_public_api_parity(self):
        """The public reshape repartition end to end under both modes
        (packed pivot at p=8, gather fallback elsewhere) — identical."""
        oracle = np.arange((1 << 12) * 40, dtype=np.float32).reshape(1 << 12, 40)
        outs = {}
        for mode in ("0", "1"):
            with _OverlapEnv(mode):
                x = ht.array(oracle, split=1)
                got = ht.reshape(x, (1 << 11, 80), new_split=1)
                outs[mode] = got.numpy()
                np.testing.assert_array_equal(outs[mode], oracle.reshape(1 << 11, 80))
        np.testing.assert_array_equal(outs["0"], outs["1"])

    def test_escape_hatch_forces_sequential(self):
        sched = planner.plan(
            RedistSpec.normalize((32768, 16384), "float32", 0, 1, 8), BUDGET
        )
        with _OverlapEnv("0"):
            self.assertFalse(executor._overlap_active(sched))
        with _OverlapEnv("1"):
            self.assertTrue(executor._overlap_active(sched))
        with _OverlapEnv(None):  # auto: follow the plan's annotation
            self.assertTrue(executor._overlap_active(sched))
            small = planner.plan(
                RedistSpec.normalize((64, 48), "float32", 0, 1, 8), BUDGET
            )
            self.assertFalse(executor._overlap_active(small))

    def test_overlap_telemetry(self):
        from heat_tpu.observability import telemetry

        telemetry.reset()
        telemetry.enable()
        try:
            # a chunked (tag-carrying) plan via a tiny explicit budget:
            # only plans with pipelinable laps may count as pipelined
            oracle = np.arange(64 * 48, dtype=np.float32).reshape(64, 48)
            x = ht.array(oracle, split=0)
            spec = RedistSpec.normalize((64, 48), "float32", 0, 1, P)
            sched = planner.plan(spec, 1024)
            self.assertTrue(any(s.overlap for s in sched.steps))
            with _OverlapEnv("1"):
                executor.execute(self.comm, x._phys, spec, sched)
            with _OverlapEnv("0"):
                executor.execute(self.comm, x._phys, spec, sched)
            # a single-collective plan has nothing to pipeline: it must
            # count sequential even under the forced gate
            with _OverlapEnv("1"):
                x.resplit(1)
            snap = telemetry.snapshot()["counters"]
            self.assertEqual(snap.get("redist.overlap.pipelined", 0), 1)
            self.assertEqual(snap.get("redist.overlap.sequential", 0), 2)
        finally:
            telemetry.disable()
            telemetry.reset()


@pytest.mark.skipif(P < 2, reason="needs a real mesh")
class TestCollectiveMatmulTSQR(TestCase):
    """The TSQR merge in collective-matmul form: ring-gather the R
    factors, consume each block as it lands — bit-identical Q/R, wire
    bytes equivalent to the one all-gather."""

    def test_qr_bit_identical_ring_vs_gather(self):
        rng = np.random.default_rng(0)
        a = rng.standard_normal((16 * P, 2 * P)).astype(np.float32)
        res = {}
        for mode in ("0", "1"):
            with _OverlapEnv(mode):
                q, r = ht.linalg.qr(ht.array(a, split=0))
                res[mode] = (q.numpy(), r.numpy())
        np.testing.assert_array_equal(res["0"][0], res["1"][0])
        np.testing.assert_array_equal(res["0"][1], res["1"][1])
        np.testing.assert_allclose(res["1"][0] @ res["1"][1], a, atol=1e-4)

    def test_ring_census_is_one_allgather_equivalent(self):
        """Forced overlap: each merge-level all-gather becomes exactly
        size-1 collective-permutes carrying the SAME total payload (the
        gather's (size-1)/size crossing bytes). At the default flat CPU
        topology the tree is single-level below 16 devices (one gather,
        P-1 hops); under a forced tiered HEAT_TPU_TOPOLOGY the tree
        groups slice-major (ISSUE 8) and the expectations follow
        ``qr._tsqr_grouping``."""
        # linalg's __init__ star-shadows the qr submodule with the qr
        # function — resolve the module itself for the grouping helper
        from heat_tpu.core.linalg.qr import _tsqr_grouping
        from heat_tpu.redistribution import planner as _planner

        a = ht.random.randn(16 * P, 2 * P, split=0)
        K = 2 * P
        topo = _planner.resolve_topology(P)
        s = _tsqr_grouping(P, topo)
        if s > 1:
            G = P // s
            hops, gathers = (s - 1) + (G - 1), 2
        else:
            hops, gathers = P - 1, 1
        with _OverlapEnv("1"):
            rep = ht.observability.collective_counts(lambda x: ht.linalg.qr(x), a)
        self.assertEqual(rep.counts["collective-permute"], hops)
        self.assertEqual(rep.counts.get("all-gather", 0), 0)
        self.assertEqual(rep.total, hops)
        if s == 1:
            # p-1 hops x one (K, K) R block = the gather's crossing bytes
            self.assertEqual(
                rep.bytes_by_op["collective-permute"], (P - 1) * K * K * 4
            )
        # the default (auto, CPU) keeps the pinned barrier form
        with _OverlapEnv(None):
            rep0 = ht.observability.collective_counts(lambda x: ht.linalg.qr(x), a)
        self.assertEqual(rep0.counts["all-gather"], gathers)
        if s == 1:
            self.assertEqual(rep0.bytes_by_op["all-gather"], P * K * K * 4)

    def test_hsvd_inherits_the_ring_merge_bit_identically(self):
        """The hSVD path feeds through the same TSQR merge: overlap-on
        == overlap-off exactly, and level 0 stays at zero collectives."""
        rng = np.random.default_rng(1)
        lr = (
            rng.standard_normal((P * 24, 6)) @ rng.standard_normal((6, 16 * P))
        ).astype(np.float32)
        outs = {}
        for mode in ("0", "1"):
            with _OverlapEnv(mode):
                u, s, v, err = ht.linalg.hsvd_rank(
                    ht.array(lr, split=0), 8, compute_sv=True
                )
                outs[mode] = (u.numpy(), s.numpy(), v.numpy())
        for z0, z1 in zip(outs["0"], outs["1"]):
            np.testing.assert_array_equal(z0, z1)
        # hSVD level 0 moves nothing, ring or not (pinned elsewhere too)
        from heat_tpu.core.linalg.svdtools import _local_svd_fn

        comm = self.comm
        m = 16
        phys = comm.shard(jnp.ones((m, 4 * P), jnp.float32), 1)
        with _OverlapEnv("1"):
            fn = _local_svd_fn(
                comm.mesh, comm.axis_name, m, phys.shape[1] // P, 3, "float32", 5
            )
            rep = ht.observability.collective_counts(fn, phys)
        self.assertEqual(rep.total, 0)


@pytest.mark.skipif(P < 2, reason="needs a real mesh")
class TestCollectiveMatmulSplit(TestCase):
    """The contraction-split matmul in collective-matmul form: a
    reduce-scatter ppermute ring whose per-hop partial block matmul
    rides under the wire, then a ring gather of the reduced chunks."""

    def test_matmul_correct_and_split_rules_hold(self):
        rng = np.random.default_rng(2)
        a = rng.standard_normal((30, 10 * P)).astype(np.float32)
        b = rng.standard_normal((10 * P, 20)).astype(np.float32)
        with _OverlapEnv("1"):
            c = ht.matmul(ht.array(a, split=1), ht.array(b, split=0))
        self.assertIsNone(c.split)  # full-reduction case stays replicated
        np.testing.assert_allclose(c.numpy(), a @ b, rtol=2e-4, atol=2e-5)

    def test_matmul_exact_on_integer_data_on_vs_off(self):
        """Integer-valued f32 operands make every accumulation order
        exact, so the ring form must agree bit-for-bit with the GSPMD
        barrier schedule the escape hatch restores."""
        rng = np.random.default_rng(3)
        a = rng.integers(-8, 8, (3 * P, 5 * P)).astype(np.float32)
        b = rng.integers(-8, 8, (5 * P, 2 * P)).astype(np.float32)
        outs = {}
        for mode in ("0", "1"):
            with _OverlapEnv(mode):
                outs[mode] = ht.matmul(
                    ht.array(a, split=1), ht.array(b, split=0)
                ).numpy()
        np.testing.assert_array_equal(outs["0"], outs["1"])
        np.testing.assert_array_equal(outs["1"], a @ b)

    def test_ring_sequential_vs_pipelined_bit_identical(self):
        """Program-level oracle: the barriered sequential ring and the
        prefetch-issue pipelined ring are the same adds in the same
        order — bit-identical on ARBITRARY data."""
        from heat_tpu.core._jax_compat import shard_map
        from jax.sharding import PartitionSpec as PS

        rng = np.random.default_rng(4)
        a = rng.standard_normal((24, 5 * P)).astype(np.float32)
        b = rng.standard_normal((5 * P, 16)).astype(np.float32)
        comm = self.comm
        outs = []
        for pipe in (False, True):
            f = shard_map(
                lambda u, v, pipe=pipe: cmatmul.ring_matmul_reduce(
                    u, v, comm.axis_name, P, pipelined=pipe
                ),
                mesh=comm.mesh,
                in_specs=(PS(None, comm.axis_name), PS(comm.axis_name, None)),
                out_specs=PS(None, None),
                check_vma=False,
            )
            outs.append(
                np.asarray(
                    f(comm.shard(jnp.asarray(a), 1), comm.shard(jnp.asarray(b), 0))
                )
            )
        np.testing.assert_array_equal(outs[0], outs[1])

    def test_ring_gather_matches_all_gather_exactly(self):
        """ring_all_gather assembles the all-gather's stack layout for
        any data — the property that makes every consumer bit-identical."""
        from heat_tpu.core._jax_compat import shard_map
        from jax.sharding import PartitionSpec as PS

        rng = np.random.default_rng(5)
        x = rng.standard_normal((P * 3, 4)).astype(np.float32)
        comm = self.comm
        perm = [(s, (s + 1) % P) for s in range(P)]

        def ring(xl):
            i = jax.lax.axis_index(comm.axis_name)
            return cmatmul.ring_all_gather(xl, comm.axis_name, P, i, perm)

        def gather(xl):
            return jax.lax.all_gather(xl, comm.axis_name)

        outs = []
        for body in (ring, gather):
            f = shard_map(
                body, mesh=comm.mesh, in_specs=(PS(comm.axis_name, None),),
                out_specs=PS(None, None, None), check_vma=False,
            )
            outs.append(np.asarray(f(comm.shard(jnp.asarray(x), 0))))
        np.testing.assert_array_equal(outs[0], outs[1])

    def test_census_two_rings(self):
        """Forced overlap: reduce-scatter ring + gather ring = exactly
        2(p-1) collective-permutes, no all-reduce barrier."""
        a = ht.ones((2 * P, 3 * P), split=1)
        b = ht.ones((3 * P, 2 * P), split=0)
        with _OverlapEnv("1"):
            rep = ht.observability.collective_counts(
                lambda u, v: ht.matmul(u, v), a, b
            )
        self.assertEqual(rep.counts["collective-permute"], 2 * (P - 1))
        self.assertEqual(rep.counts.get("all-reduce", 0), 0)


@pytest.mark.skipif(P < 2, reason="needs a real mesh")
class TestShardlintOverlap(TestCase):
    """Satellite: pipelined ppermute chains inside planner-stamped
    programs keep the SL101 info-downgrade; the collective-matmul rings
    are stamped the same way."""

    @pytest.mark.skipif(P != 8, reason="ring-vs-chunked budget geometry is 8-mesh-shaped")
    def test_planner_ring_reports_as_info(self):
        """A ring-strategy resplit's ppermute chain is planner-stamped
        movement: SL101 reports it at info with the plan id attached."""
        # sized so the ring wins under a 1 MiB budget: L = 32 MB / p per
        # device, ring peak 2L/p fits where chunking would need >= p laps,
        # and each ppermute hop ships L/p >= the check's min_bytes.
        # Pinned at a flat topology — the ring-vs-hierarchical cost race
        # at a tiered one is test_topology.py's business.
        x = ht.zeros((2048 * P, 512), split=0)
        try:
            with env_pin("HEAT_TPU_TOPOLOGY", "flat"), env_pin(
                "HEAT_TPU_REDIST_BUDGET_MB", "1"
            ):
                planner.clear_plan_cache()
                sched = ht.redistribution.explain(x, 1)
                self.assertEqual(sched.strategy, "ring")
                with _OverlapEnv("1"):
                    rep = ht.analysis.check(
                        lambda v: v.resplit(1), x, min_bytes=1 << 17
                    )
                hops = [f for f in rep.findings if f.op == "collective-permute"]
                self.assertTrue(hops)
                for f in hops:
                    self.assertEqual(f.severity, "info")
                    self.assertIn(sched.plan_id, f.message)
                self.assertTrue(rep.ok)
        finally:
            planner.clear_plan_cache()

    def test_cmatmul_ring_reports_as_info(self):
        a = ht.ones((512, 64 * P), split=1)
        b = ht.ones((64 * P, 512), split=0)
        with _OverlapEnv("1"):
            rep = ht.analysis.check(
                lambda u, v: ht.matmul(u, v), a, b, min_bytes=1 << 16
            )
        hops = [f for f in rep.findings if f.op == "collective-permute"]
        self.assertTrue(hops)
        for f in hops:
            self.assertEqual(f.severity, "info")
            self.assertIn("cmatmul", f.message)
        self.assertTrue(rep.ok)

    def test_cmatmul_module_is_registered(self):
        from heat_tpu.analysis import boundaries

        self.assertIn("kernels/cmatmul.py", boundaries.PLANNER_MODULES)
        self.assertEqual(
            boundaries.planned_reshard_plan_id(
                'metadata={op_name="jit(fn)/cmatmul_ring_tsqr/ppermute"}'
            ),
            "cmatmul:tsqr",
        )


if __name__ == "__main__":
    import unittest

    unittest.main()
