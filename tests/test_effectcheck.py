"""Pass 4 (ISSUE 12): the gate registry + gatecheck/racecheck analyzer.

Contracts pinned here:

- **Registry round-trip** — every ``HEAT_TPU_*`` gate read anywhere in
  ``heat_tpu/`` is declared in ``core/gates.py`` (a raw ``os.environ``
  grep over the tree finds ZERO gate reads outside the registry — the
  same verdict rule SL403 reaches), declarations are well-formed, and
  no declaration is dead.
- **Cache-key byte identity** — with all gates at defaults, the
  registry-derived keys reproduce the PR 11 artifacts bit-for-bit: the
  golden plan_ids (pinned hex), the empty AOT gate fingerprint, and
  key-for-key equality between ``gates.aot_fingerprint()`` and the PR 9
  hand-rolled prefix scan it replaced, at every gate combination tried.
- **AOT roster invalidation** — registering a NEW program-affecting
  gate invalidates stored envelopes as ``version_mismatch`` (never a
  stale hit).
- **Golden bad fixtures** fire each SL401–SL405 rule; the shipped
  dispatcher/aot_cache/telemetry/executor/staging modules and the
  golden plan forms (flat/2x4/2x8, quant on+off, staged) come back
  SL4xx-clean.
- **Seeded-bug mutations** (the ci.sh leg): removing one gate from a
  program-cache key trips SL402; removing one lock acquisition from a
  guarded dispatcher path trips SL404 — each at error severity, with
  the invariant named.
- **Threading stress** — the SL404-clean dispatcher/telemetry paths
  stay exact-total under concurrent clients.
"""

import os
import re
import threading

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import heat_tpu as ht

import analysis_fixtures as fx

from heat_tpu.analysis import effectcheck, findings
from heat_tpu.core import gates
from heat_tpu.redistribution import planner, staging
from heat_tpu.serving import aot_cache
from heat_tpu.serving.dispatcher import Dispatcher, Endpoint

from test_suites.basic_test import TestCase, env_pin

P = len(jax.devices())
ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
HT = os.path.join(ROOT, "heat_tpu")


def _read(rel):
    with open(os.path.join(ROOT, rel), encoding="utf-8") as f:
        return f.read()


def _tree_sources():
    for root, dirs, files in os.walk(HT):
        dirs[:] = [d for d in dirs if d != "__pycache__"]
        for f in sorted(files):
            if f.endswith(".py"):
                fp = os.path.join(root, f)
                rel = os.path.relpath(fp, ROOT).replace(os.sep, "/")
                with open(fp, encoding="utf-8") as fh:
                    yield rel, fh.read()


# ------------------------------------------------------------------ #
# registry round-trip                                                #
# ------------------------------------------------------------------ #
class TestGateRegistry(TestCase):
    def test_every_gate_token_in_tree_is_declared(self):
        """Every concrete HEAT_TPU_* token in the library source is a
        declared gate (or a proper prefix of one, e.g. the old
        exclusion-list spellings in comments)."""
        token = re.compile(r"HEAT_TPU_[A-Z0-9_]*[A-Z0-9]")
        declared = set(gates.GATES)
        undeclared = {}
        for rel, src in _tree_sources():
            for name in set(token.findall(src)):
                ok = name in declared or any(
                    g.startswith(name) for g in declared
                )
                if not ok:
                    undeclared.setdefault(name, rel)
        self.assertEqual(
            undeclared, {},
            f"HEAT_TPU_* names read/mentioned but not declared in "
            f"core/gates.py: {undeclared}",
        )

    def test_no_dead_declarations(self):
        """Every declared gate is actually read somewhere in the tree."""
        blob = "\n".join(src for _, src in _tree_sources())
        for name in gates.GATES:
            self.assertIn(name, blob, f"{name} declared but never referenced")

    def test_raw_read_grep_matches_sl403_verdict(self):
        """The satellite's cross-check: a raw grep for ``os.environ``
        over ``heat_tpu/`` finds gate reads ONLY in core/gates.py, and
        the SL403 sweep reaches the same verdict (zero findings)."""
        import ast

        def uses_environ(src):
            for node in ast.walk(ast.parse(src)):
                if (
                    isinstance(node, ast.Attribute)
                    and node.attr in ("environ", "getenv")
                    and isinstance(node.value, ast.Name)
                    and node.value.id == "os"
                ):
                    return True
            return False

        raw = [
            rel
            for rel, src in _tree_sources()
            if not rel.endswith("core/gates.py") and uses_environ(src)
        ]
        self.assertEqual(raw, [], f"raw os.environ access outside the registry: {raw}")
        report = effectcheck.lint_paths([HT], root=ROOT)
        self.assertEqual([f for f in report if f.rule == "SL403"], [])

    def test_get_rejects_undeclared_names(self):
        with self.assertRaises(KeyError):
            gates.get("HEAT_TPU_NOT_A_GATE")
        with self.assertRaises(KeyError):
            gates.is_set("HEAT_TPU_NOT_A_GATE")

    def test_get_mirrors_environ_semantics(self):
        with env_pin("HEAT_TPU_REDIST_OVERLAP", None):
            self.assertIsNone(gates.get("HEAT_TPU_REDIST_OVERLAP"))
            self.assertEqual(gates.get("HEAT_TPU_REDIST_OVERLAP", "auto"), "auto")
            self.assertFalse(gates.is_set("HEAT_TPU_REDIST_OVERLAP"))
        with env_pin("HEAT_TPU_REDIST_OVERLAP", "0"):
            self.assertEqual(gates.get("HEAT_TPU_REDIST_OVERLAP", "auto"), "0")
            self.assertTrue(gates.is_set("HEAT_TPU_REDIST_OVERLAP"))

    def test_scope_and_roster_derivations(self):
        affecting = {s.name for s in gates.affecting_programs()}
        # the serving/telemetry/tracing switches change no program
        # bytes, and neither does the checkpoint store path (ISSUE 13)
        # or the numcheck analyzer threshold (ISSUE 17 — read-only
        # report tuning); the resilience runtime switch IS roster
        # material (its registration version-bumps pre-resilience AOT
        # envelopes)
        self.assertNotIn("HEAT_TPU_SERVING_AOT", affecting)
        self.assertNotIn("HEAT_TPU_SERVING_CACHE", affecting)
        self.assertNotIn("HEAT_TPU_TELEMETRY", affecting)
        self.assertNotIn("HEAT_TPU_CKPT_DIR", affecting)
        self.assertNotIn("HEAT_TPU_TRACE", affecting)
        self.assertNotIn("HEAT_TPU_NUMCHECK_ACC_DIM", affecting)
        self.assertIn("HEAT_TPU_RESILIENCE", affecting)
        self.assertEqual(len(affecting), len(gates.GATES) - 6)
        self.assertEqual(
            gates.program_gate_roster(), ",".join(sorted(affecting))
        )
        # the lattice profile changes plan pricing AND (via the roster
        # bump) AOT envelope identity — affecting, plan+aot scoped
        self.assertIn("HEAT_TPU_LATTICE_PROFILE", affecting)
        # plan-scope gates are exactly the components of the planner key
        plan_scope = {s.name for s in gates.scope_gates("plan")}
        self.assertEqual(
            plan_scope,
            {
                "HEAT_TPU_REDIST_BUDGET_MB", "HEAT_TPU_WIRE_QUANT",
                "HEAT_TPU_TOPOLOGY", "HEAT_TPU_OOC", "HEAT_TPU_OOC_SLAB_MB",
                "HEAT_TPU_HBM_BYTES", "HEAT_TPU_LATTICE_PROFILE",
            },
        )
        with self.assertRaises(ValueError):
            gates.scope_gates("nonsense")

    def test_executor_program_keys_derive_from_registry(self):
        """The executor's cached-builder signatures carry one declared
        ``key_params`` name for every program-scope gate — the
        'cache keys derive from the registry' pin, enforced in depth by
        rule SL402."""
        import inspect

        from heat_tpu.redistribution import executor

        for builder in (
            executor._move_program, executor._pivot_program,
            executor._packed_pivot_program,
        ):
            params = set(inspect.signature(builder.__wrapped__).parameters)
            for spec in gates.scope_gates("program"):
                if spec.name in ("HEAT_TPU_SORT_KERNEL", "HEAT_TPU_RELAYOUT_KERNEL",
                                 "HEAT_TPU_SPMM_KERNEL",
                                 "HEAT_TPU_REDIST_PLANNER"):
                    continue  # keyed one level down (impl strings / route)
                self.assertTrue(
                    params & set(spec.key_params),
                    f"{builder.__wrapped__.__name__} carries no key param "
                    f"for {spec.name} (declared: {spec.key_params})",
                )
        packed = set(
            inspect.signature(executor._packed_pivot_program.__wrapped__).parameters
        )
        self.assertTrue(
            packed & set(gates.GATES["HEAT_TPU_RELAYOUT_KERNEL"].key_params)
        )


# ------------------------------------------------------------------ #
# cache-key byte identity (the PR 11 artifacts)                      #
# ------------------------------------------------------------------ #
#: golden plan_ids captured at PR 11 HEAD (all gates at defaults) —
#: the registry refactor must reproduce every one bit-for-bit.
_PR11_PLAN_IDS = {
    "noop_same_split": "a73577b2e204",
    "resplit_0_to_1_p8": "3fa7e27aefe5",
    "resplit_1_to_0_p8": "9dcceb241644",
    "resplit_0_to_1_int32_p4": "7da388bc1f4e",
    "resplit_uneven_p8": "785b5c64ef22",
    "resplit_3d_1_to_2_p8": "a4312eca02cb",
    "replicate_p8": "ba5015838a00",
    "slice_from_replicated_p8": "fd958543fa59",
    "mesh1_resplit": "ea8f4a542d36",
    "resplit_chunked_2gb_p8": "ac7c3d3bd0e2",
    "resplit_ring_8gb_p8": "9a9f6522afa0",
    "reshape_pivot_p8": "7e55bd63cf2f",
    "reshape_split0_local_p8": "06af6969c5a1",
    "reshape_gather_fallback_p8": "7187d492c0d5",
    "reshape_split1_1gb_p8": "e25264d7562c",
    "reshape_packed_rev_p8": "1424eb21252e",
    "reshape_lane_1gb_p8": "4f79dda1bad3",
    "resplit_1gb_p16": "6c06e58a4b8e",
    "reshape_split1_1gb_p16": "266f4c37f19f",
}


def _pr9_hand_fingerprint():
    """The PR 9 hand-rolled prefix scan the registry derivation
    replaced — kept here as the oracle the derivation must match."""
    return tuple(
        sorted(
            (k, v)
            for k, v in os.environ.items()
            if k.startswith("HEAT_TPU_")
            and not k.startswith(("HEAT_TPU_SERVING", "HEAT_TPU_TELEMETRY"))
        )
    )


class TestCacheKeyByteIdentity(TestCase):
    def test_golden_plan_ids_unchanged_from_pr11(self):
        got = {
            name: planner.plan(spec).plan_id
            for name, spec in planner.golden_specs()
        }
        self.assertEqual(got, _PR11_PLAN_IDS)

    def test_golden_dump_bytes_unchanged_from_pr11(self):
        """The `scripts/redist_plans.py` dump — every canonical plan
        serialization, quant twins included — byte-identical to PR 11
        HEAD (sha256 captured there), flat and at the forced 2x8
        two-tier topology. ISSUE 19 APPENDED the five factorization
        rows (``golden_factorization_plans``): the PR 11 pin now holds
        over the dump minus that suffix — the pre-existing rows must
        never drift — and a second pin holds the full dump including
        the appended rows."""
        import hashlib
        import subprocess
        import sys

        pinned = {
            (): (
                "7f180a82cfcb327cc839728fb972cac0d6cfc37374119da1082d46c40318854e",
                "5148ccf9de9537c1e56050b913655deb51e7ea9e5d77415acb5840bace3cdb9d",
            ),
            ("--topology", "2x8"): (
                "415455b3a8d83a21b050763f26ababb4d1b3ff3876b5fe992434544565d330a4",
                "fb6fe31cd1b67a9c76ea4e815c9752d8fa75b5035cf40ea88475e5976e433674",
            ),
        }
        n_fac = 5  # the ISSUE 19 factorization rows, appended last
        for extra, (want_pr11, want_full) in pinned.items():
            out = subprocess.run(
                [sys.executable, os.path.join(ROOT, "scripts", "redist_plans.py"), *extra],
                capture_output=True, check=True, cwd=ROOT,
            ).stdout
            prefix = b"".join(out.splitlines(keepends=True)[:-n_fac])
            self.assertEqual(hashlib.sha256(prefix).hexdigest(), want_pr11, extra)
            self.assertEqual(hashlib.sha256(out).hexdigest(), want_full, extra)

    def test_aot_fingerprint_empty_at_defaults(self):
        with env_pin("HEAT_TPU_OOC", None), env_pin("HEAT_TPU_WIRE_QUANT", None):
            fp = {
                k: v for k, v in gates.aot_fingerprint()
                if k in ("HEAT_TPU_OOC", "HEAT_TPU_WIRE_QUANT")
            }
            self.assertEqual(fp, {})

    def test_aot_fingerprint_matches_pr9_hand_filter(self):
        """Key-for-key equality with the retired prefix scan, across
        gate combinations (including an UNREGISTERED name, which stays
        conservatively key material exactly as before)."""
        combos = [
            {},
            {"HEAT_TPU_OOC": "1"},
            {"HEAT_TPU_WIRE_QUANT": "bf16", "HEAT_TPU_TOPOLOGY": "2x4"},
            {"HEAT_TPU_TELEMETRY": "1", "HEAT_TPU_SERVING_AOT": "1"},
            {"HEAT_TPU_FUTURE_UNREGISTERED": "x", "HEAT_TPU_HBM_BYTES": "123"},
        ]
        for combo in combos:
            pins = [env_pin(k, v) for k, v in combo.items()]
            try:
                for p in pins:
                    p.__enter__()
                self.assertEqual(
                    gates.aot_fingerprint(), _pr9_hand_fingerprint(), combo
                )
            finally:
                for p in reversed(pins):
                    p.__exit__(None, None, None)

    def test_new_program_gate_invalidates_aot_envelopes(self):
        """The roster pin: an envelope stored today is refused as
        version_mismatch — never served stale — once a new
        program-affecting gate is registered."""
        import tempfile

        with tempfile.TemporaryDirectory() as root:
            store = aot_cache.AOTStore(root)
            self.assertTrue(store.store("deadbeef", b"blob", None))
            self.assertIsNotNone(store.load("deadbeef"))
            self.assertEqual(store.stats["version_mismatch"], 0)
            fake = gates.GateSpec(
                "HEAT_TPU_TEST_ONLY_FAKE", default="0", values=("0", "1"),
                affects_programs=True, scopes=("program", "aot"),
                key_params=("fake",), help="test-only",
            )
            gates.declare(fake)
            try:
                self.assertIsNone(store.load("deadbeef"))
                self.assertEqual(store.stats["version_mismatch"], 1)
            finally:
                gates.GATES.pop("HEAT_TPU_TEST_ONLY_FAKE")
            # roster restored: the envelope (overwritten semantics aside)
            # verifies again
            self.assertIsNotNone(store.load("deadbeef"))


# ------------------------------------------------------------------ #
# golden bad fixtures: each rule fires                               #
# ------------------------------------------------------------------ #
class TestGoldenBadFixtures(TestCase):
    def test_sl401_use_after_donate(self):
        x = ht.ones((64, 8), split=0 if P > 1 else None)
        rep = effectcheck.check_donation(fx.use_after_donate_program, x)
        self.assertEqual({f.rule for f in rep}, {"SL401"})
        self.assertEqual(rep.findings[0].severity, "error")
        clean = effectcheck.check_donation(fx.donate_then_done_program, x)
        self.assertEqual(list(clean), [])

    def test_sl401_folds_into_ircheck(self):
        x = ht.ones((64, 8), split=0 if P > 1 else None)
        rep = ht.analysis.check(fx.use_after_donate_program, x)
        self.assertIn("SL401", rep.rule_ids)
        self.assertFalse(rep.ok)

    def test_sl402_stale_lru_builder(self):
        found = effectcheck.lint_source(fx.STALE_KEY_BUILDER_SRC, "heat_tpu/x.py")
        self.assertEqual({f.rule for f in found}, {"SL402"})
        self.assertIn("HEAT_TPU_REDIST_OVERLAP", found[0].message)
        self.assertIn("pipelined", found[0].message)  # the named fix

    def test_sl402_stale_dict_key(self):
        found = effectcheck.lint_source(fx.STALE_DICT_KEY_SRC, "heat_tpu/x.py")
        self.assertEqual({f.rule for f in found}, {"SL402"})
        self.assertIn("HEAT_TPU_TOPOLOGY", found[0].message)

    def test_sl403_raw_reads(self):
        found = effectcheck.lint_source(fx.RAW_GATE_READ_SRC, "heat_tpu/x.py")
        self.assertEqual({f.rule for f in found}, {"SL403"})
        self.assertEqual(len(found), 3)  # get, enumeration, containment
        # the registry module itself is the sanctioned read site
        self.assertEqual(
            effectcheck.lint_source(fx.RAW_GATE_READ_SRC, "heat_tpu/core/gates.py"),
            [],
        )

    def test_sl403_resolves_module_constant_names(self):
        """The codebase's historical read idiom — the gate name in a
        module-level ``*_ENV`` constant — is a raw read too."""
        src = (
            'import os\n'
            'OVERLAP_ENV = "HEAT_TPU_REDIST_OVERLAP"\n'
            'def overlap_mode():\n'
            '    return os.environ.get(OVERLAP_ENV, "auto")\n'
        )
        found = effectcheck.lint_source(src, "heat_tpu/x.py")
        self.assertEqual({f.rule for f in found}, {"SL403"})
        self.assertIn("HEAT_TPU_REDIST_OVERLAP", found[0].message)

    def test_sl403_ignores_prefix_free_env_enumeration(self):
        src = (
            'import os\n'
            'def diag():\n'
            '    return {k: v for k, v in os.environ.items() if k.startswith("SLURM_")}\n'
        )
        self.assertEqual(effectcheck.lint_source(src, "heat_tpu/x.py"), [])

    def test_snapshot_recognizes_spellings(self):
        with env_pin("HEAT_TPU_REDIST_OVERLAP", "force"):
            self.assertTrue(gates.snapshot()["HEAT_TPU_REDIST_OVERLAP"]["recognized"])
        with env_pin("HEAT_TPU_WIRE_QUANT", "int8"):
            self.assertTrue(gates.snapshot()["HEAT_TPU_WIRE_QUANT"]["recognized"])
        with env_pin("HEAT_TPU_OOC", "banana"):
            self.assertFalse(gates.snapshot()["HEAT_TPU_OOC"]["recognized"])
        with env_pin("HEAT_TPU_SERVING_CACHE", "/any/path"):
            self.assertTrue(gates.snapshot()["HEAT_TPU_SERVING_CACHE"]["recognized"])

    def test_sl404_unguarded_attr(self):
        found = effectcheck.lint_source(fx.UNGUARDED_ATTR_SRC, "heat_tpu/x.py")
        self.assertEqual({f.rule for f in found}, {"SL404"})
        self.assertIn("_counts", found[0].message)

    def test_sl404_annotation_declares_lock_free(self):
        annotated = fx.UNGUARDED_ATTR_SRC.replace(
            'self._counts = {"batches": 0}',
            'self._counts = {"batches": 0}  # racecheck: guarded-by(GIL; test-only tallies)',
        )
        self.assertEqual(effectcheck.lint_source(annotated, "heat_tpu/x.py"), [])

    def test_sl405_pipeline_protocol(self):
        found = effectcheck.lint_source(fx.PIPELINE_PROTOCOL_SRC, "heat_tpu/x.py")
        self.assertEqual({f.rule for f in found}, {"SL405"})
        by_line = {f.line: f.message for f in found}
        self.assertEqual(len(found), 3)  # inverted, unfenced, dropped
        self.assertTrue(any("consumes lap k before" in m for m in by_line.values()))
        self.assertTrue(any("JUST issued" in m for m in by_line.values()))
        self.assertTrue(any("never consumed" in m for m in by_line.values()))

    def test_rules_catalogued(self):
        for rule in ("SL401", "SL402", "SL403", "SL404", "SL405", "SL406"):
            self.assertIn(rule, findings.RULES)


# ------------------------------------------------------------------ #
# clean pins                                                         #
# ------------------------------------------------------------------ #
class TestCleanPins(TestCase):
    def test_tree_is_sl4xx_clean(self):
        report = effectcheck.lint_paths([HT], root=ROOT)
        self.assertEqual(list(report), [], [repr(f) for f in report])

    def test_threaded_and_cached_modules_individually_clean(self):
        for rel in (
            "heat_tpu/serving/dispatcher.py",
            "heat_tpu/serving/aot_cache.py",
            "heat_tpu/observability/telemetry.py",
            "heat_tpu/redistribution/executor.py",
            "heat_tpu/redistribution/staging.py",
            "heat_tpu/redistribution/planner.py",
            "heat_tpu/utils/data/partial_dataset.py",
        ):
            found = effectcheck.lint_source(_read(rel), rel)
            self.assertEqual(found, [], (rel, [repr(f) for f in found]))

    def test_golden_plan_forms_protocol_clean(self):
        """The plan-side SL405 sweep over every golden form the ci.sh
        determinism leg dumps: flat + 2x4 + 2x8, quant off and forced,
        plus the staged window schedules."""
        n = 0
        for topo in (None, (2, 4), (2, 8)):
            for quant in ("0", "int8"):
                for name, spec in planner.golden_specs():
                    if topo and spec.mesh_size != topo[0] * topo[1]:
                        continue
                    sched = planner.plan(
                        spec, quant=quant, topology=topo if topo else "flat"
                    )
                    self.assertEqual(
                        effectcheck.check_plan_protocol(sched), [], (name, topo, quant)
                    )
                    n += 1
        for name, sched in staging.golden_staged_plans():
            self.assertEqual(effectcheck.check_plan_protocol(sched), [], name)
            n += 1
        self.assertGreaterEqual(n, 60)

    def test_shipped_double_buffer_loops_clean(self):
        """_run_laps and stream_windows ARE depth-2 claimants — the
        detector must recognize and pass them (not skip them)."""
        src = _read("heat_tpu/redistribution/executor.py")
        self.assertIn("def _run_laps", src)
        self.assertEqual(
            [f for f in effectcheck.lint_source(src, "heat_tpu/redistribution/executor.py")],
            [],
        )


# ------------------------------------------------------------------ #
# seeded-bug mutations (the ci.sh proof)                             #
# ------------------------------------------------------------------ #
class TestSeededBugMutations(TestCase):
    """Acceptance: remove ONE invariant from the real source, the lint
    trips at error. Each mutation asserts its anchor still exists, so
    source drift fails loudly instead of silently weakening the proof."""

    def test_mutation_gate_dropped_from_program_cache_key_trips_sl402(self):
        """Invariant: HEAT_TPU_REDIST_OVERLAP is a component of every
        executor program-cache key (the ``pipelined`` parameter).
        Mutation: drop the parameter and resolve the gate inside the
        cached builder — the post-PR-5 review line made mechanical."""
        src = _read("heat_tpu/redistribution/executor.py")
        anchor = "def _move_program(\n    comm, spec: RedistSpec, budget: int, pipelined: bool = False,"
        self.assertIn(anchor, src)
        mutated = src.replace(
            anchor,
            "def _move_program(\n    comm, spec: RedistSpec, budget: int,",
        ).replace(
            "    sched = _planner.plan(\n        spec, budget, quant=wire or \"0\", topology=topo if topo else \"flat\"\n    )\n    mesh, axis_name = comm.mesh, comm.axis_name\n    p = spec.mesh_size\n    i, j = spec.src_split, spec.dst_split",
            "    sched = _planner.plan(\n        spec, budget, quant=wire or \"0\", topology=topo if topo else \"flat\"\n    )\n    pipelined = _overlap_active(sched)\n    mesh, axis_name = comm.mesh, comm.axis_name\n    p = spec.mesh_size\n    i, j = spec.src_split, spec.dst_split",
            1,
        )
        self.assertNotEqual(mutated, src)
        found = effectcheck.lint_source(mutated, "heat_tpu/redistribution/executor.py")
        hits = [f for f in found if f.rule == "SL402" and "HEAT_TPU_REDIST_OVERLAP" in f.message]
        self.assertTrue(hits, [repr(f) for f in found])
        self.assertTrue(all(f.severity == "error" for f in hits))

    def test_mutation_gate_dropped_from_plan_cache_key_trips_sl402(self):
        """Invariant: the resolved topology is a component of the
        planner's dict-cache key. Mutation: delete it from the tuple."""
        src = _read("heat_tpu/redistribution/planner.py")
        anchor = 'key = (spec, b, qmode or "0", topo, cal["profile_id"] if cal else None)'
        self.assertIn(anchor, src)
        mutated = src.replace(
            anchor, 'key = (spec, b, qmode or "0", cal["profile_id"] if cal else None)'
        )
        found = effectcheck.lint_source(mutated, "heat_tpu/redistribution/planner.py")
        hits = [f for f in found if f.rule == "SL402" and "HEAT_TPU_TOPOLOGY" in f.message]
        self.assertTrue(hits, [repr(f) for f in found])

    def test_mutation_lock_dropped_from_dispatcher_path_trips_sl404(self):
        """Invariant: every access of Dispatcher._counts/_lat holds
        _counts_lock. Mutation: remove one acquisition (any of them)."""
        src = _read("heat_tpu/serving/dispatcher.py")
        acquisitions = src.count("with self._counts_lock:")
        self.assertGreaterEqual(acquisitions, 4)
        for i in range(acquisitions):
            # rebuild the source with occurrence i (and only it) replaced
            pieces = src.split("with self._counts_lock:")
            mutated = ""
            for j, piece in enumerate(pieces):
                mutated += piece
                if j < len(pieces) - 1:
                    mutated += (
                        "if True:  # mutated" if j == i else "with self._counts_lock:"
                    )
            found = effectcheck.lint_source(mutated, "heat_tpu/serving/dispatcher.py")
            hits = [f for f in found if f.rule == "SL404"]
            self.assertTrue(hits, f"occurrence {i}: no SL404 on lock removal")
            self.assertTrue(all(f.severity == "error" for f in hits))

    def test_mutation_inverted_loop_trips_sl405(self):
        """Invariant: _run_laps issues lap k+1 before consuming lap k.
        Mutation: swap the two statements (the sequential regression)."""
        src = _read("heat_tpu/redistribution/executor.py")
        anchor = (
            "        nxt = issue(idx[i])  # lap i on the wire ...\n"
            "        state = consume(state, prev, idx[i - 1])  # ... while i-1 relayouts\n"
        )
        self.assertIn(anchor, src)
        mutated = src.replace(
            anchor,
            "        state = consume(state, prev, idx[i - 1])\n"
            "        nxt = issue(idx[i])\n",
        )
        found = effectcheck.lint_source(mutated, "heat_tpu/redistribution/executor.py")
        hits = [f for f in found if f.rule == "SL405"]
        self.assertTrue(hits, [repr(f) for f in found])


# ------------------------------------------------------------------ #
# threading stress: exact totals on the SL404-clean paths            #
# ------------------------------------------------------------------ #
class TestConcurrencyExactTotals(TestCase):
    def test_dispatcher_counts_exact_under_concurrent_clients(self):
        ep = Endpoint({8: jax.jit(lambda b: b * 2.0)}, (4,), np.float32)
        n_threads, per_thread = 8, 25
        ok, rejected = [], []
        with Dispatcher(ep, max_queue=256) as d:
            def client(seed):
                rng = np.random.default_rng(seed)
                for _ in range(per_thread):
                    x = rng.standard_normal((2, 4)).astype(np.float32)
                    try:
                        fut = d.submit(x)
                    except Exception:
                        rejected.append(1)
                        continue
                    np.testing.assert_allclose(
                        np.asarray(fut.result(timeout=30)), x * 2.0, rtol=1e-6
                    )
                    ok.append(1)

            threads = [
                threading.Thread(target=client, args=(s,)) for s in range(n_threads)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            stats = d.stats()
        total = n_threads * per_thread
        self.assertEqual(len(ok) + len(rejected), total)
        self.assertEqual(stats["requests"], len(ok))
        self.assertEqual(stats["rejected"], len(rejected))
        self.assertEqual(stats["rows"] + stats["shed"] * 0, 2 * len(ok))
        self.assertEqual(len(ok), total)  # queue is deep enough: no rejects

    def test_telemetry_counters_exact_under_concurrent_recorders(self):
        from heat_tpu.observability import telemetry

        telemetry.reset()
        telemetry.enable()
        try:
            n_threads, per_thread = 16, 500

            def recorder():
                for _ in range(per_thread):
                    telemetry.inc("effectcheck.stress")
                    telemetry.observe("effectcheck.stress.t", 0.001)

            threads = [threading.Thread(target=recorder) for _ in range(n_threads)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            snap = telemetry.snapshot()
            self.assertEqual(
                snap["counters"]["effectcheck.stress"], n_threads * per_thread
            )
            self.assertEqual(
                snap["timers"]["effectcheck.stress.t"]["calls"],
                n_threads * per_thread,
            )
        finally:
            telemetry.disable()
            telemetry.reset()


if __name__ == "__main__":
    import unittest

    unittest.main()
