"""Block-quantized wire codec (ISSUE 7): ``heat_tpu.kernels.quant``,
the planner/executor codec steps, and the quantized-gradient DP mode.

The contract pinned here, four ways:

1. **Codec** — round-trip property tests: per-tile scale correctness
   (error ≤ the pinned tolerance × tile absmax), ±0/±inf/NaN payloads
   survive exactly, int dtypes are rejected (they ship lossless),
   determinism.
2. **Plans** — the golden matrix's strategy / collective census / lap
   structure is IDENTICAL gate-on vs gate-off (the codec wraps
   collectives, it never reroutes them); codec steps and the ``quant``
   annotation fold into the canonical serialization and plan_id;
   ``HEAT_TPU_WIRE_QUANT=0`` restores byte-identical PR 6 plans.
3. **Movement** — executed quantized redistributions land within the
   pinned tolerance, sequential-vs-pipelined issue orders stay
   bit-identical to each other, lossless paths (small/int/non-admissible
   specs, the escape hatch) stay exact-bit, and wire bytes on the
   admissible plans come in ≤ 0.5× raw (int8: ~0.25×).
4. **DP** — the opt-in quantized-gradient mode trains a toy problem to
   the same quality as the exact psum (error feedback carries the
   compression residual), its program's census is one all-to-all + one
   all-gather, and the analytic v5e-64 model shows ≥ 1.5× step time on
   ICI-bound layers.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import heat_tpu as ht

from heat_tpu import nn as htnn
from heat_tpu import optim as htoptim
from heat_tpu.core import _padding
from heat_tpu.kernels import quant
from heat_tpu.redistribution import RedistSpec, executor, planner

from test_suites.basic_test import TestCase, env_pin

P = len(jax.devices())
BUDGET = planner.DEFAULT_BUDGET_MB << 20


def _QuantEnv(mode):
    """Pin HEAT_TPU_WIRE_QUANT for a block (shared env_pin helper)."""
    return env_pin(planner.WIRE_QUANT_ENV, mode)


# --------------------------------------------------------------------- #
# 1. codec round-trip properties                                        #
# --------------------------------------------------------------------- #
class TestCodec(TestCase):
    def _roundtrip(self, x, mode):
        w = quant.encode_blocks(jnp.asarray(x), mode)
        self.assertEqual(w.dtype, jnp.int8)
        self.assertEqual(w.shape, (x.shape[0], quant.wire_bytes(x.shape[1], mode)))
        return np.asarray(quant.decode_blocks(w, x.shape[1], mode))

    def test_tile_scale_correctness(self):
        """Per-tile scaling: error is bounded by tol × THAT tile's
        absmax, not the global one — tiles of wildly different
        magnitude coexist losslessly-enough."""
        rng = np.random.default_rng(0)
        n = 5 * quant.TILE
        x = rng.standard_normal((2, n)).astype(np.float32)
        # tile t scaled by 10^t: global absmax is 10^4 of tile 0's
        for t in range(5):
            x[:, t * quant.TILE : (t + 1) * quant.TILE] *= 10.0 ** t
        for mode in quant.MODES:
            back = self._roundtrip(x, mode)
            tol = quant.tolerance(mode)
            for t in range(5):
                sl = slice(t * quant.TILE, (t + 1) * quant.TILE)
                amax = np.abs(x[:, sl]).max()
                err = np.abs(back[:, sl] - x[:, sl]).max()
                self.assertLessEqual(err, tol * amax, (mode, t))

    def test_special_payloads_survive(self):
        """±inf and NaN round-trip exactly; -0 collapses to +0 (int8 has
        no signed zero — same documented tie-class collapse as the sort
        transforms) while bf16 keeps the sign bit."""
        x = np.zeros((1, quant.TILE + 7), np.float32)
        x[0, 0] = np.inf
        x[0, 1] = -np.inf
        x[0, 2] = np.nan
        x[0, 3] = -0.0
        x[0, 4] = 3.25
        x[0, quant.TILE] = -1.5  # tail tile
        for mode in quant.MODES:
            back = self._roundtrip(x, mode)
            self.assertEqual(back[0, 0], np.inf, mode)
            self.assertEqual(back[0, 1], -np.inf, mode)
            self.assertTrue(np.isnan(back[0, 2]), mode)
            self.assertEqual(back[0, 3], 0.0, mode)
            if mode == "bf16":
                self.assertTrue(np.signbit(back[0, 3]))
            else:
                self.assertFalse(np.signbit(back[0, 3]))
        # specials do not poison their tile's finite values: the scale
        # comes from the FINITE absmax
        back = self._roundtrip(x, "int8")
        self.assertLessEqual(abs(back[0, 4] - 3.25), quant.tolerance("int8") * 3.25)
        self.assertLessEqual(abs(back[0, quant.TILE] + 1.5), quant.tolerance("int8") * 1.5)

    def test_zero_tiles_and_subnormals(self):
        x = np.zeros((1, quant.TILE), np.float32)
        for mode in quant.MODES:
            np.testing.assert_array_equal(self._roundtrip(x, mode), x)
        x[0, 0] = np.float32(1e-40)  # subnormal: scale stays finite
        back = self._roundtrip(x, "int8")
        self.assertTrue(np.isfinite(back).all())

    def test_int_dtypes_rejected(self):
        # (f64 inputs cannot exist without x64 mode — the planner-side
        # f64 admissibility pin lives in TestQuantPlans)
        for bad in (np.int32, np.int8, np.bool_):
            with self.assertRaises(TypeError):
                quant.encode_blocks(jnp.zeros((1, 8), bad), "int8")

    def test_unknown_mode_rejected(self):
        with self.assertRaises(ValueError):
            quant.encode_blocks(jnp.zeros((1, 8), jnp.float32), "fp4")
        with self.assertRaises(ValueError):
            quant.tolerance("fp4")

    def test_wire_bytes_arithmetic(self):
        # int8: payload + one f32 scale per 1024-elem tile
        self.assertEqual(quant.wire_bytes(quant.TILE, "int8"), quant.TILE + 4)
        self.assertEqual(quant.wire_bytes(quant.TILE + 1, "int8"), 2 * quant.TILE + 8)
        self.assertEqual(quant.wire_bytes(100, "bf16"), 200)
        self.assertLess(quant.wire_ratio(1 << 20, "int8"), 0.26)
        self.assertEqual(quant.wire_ratio(1 << 20, "bf16"), 0.5)
        # both modes land under the acceptance ceiling
        for mode in quant.MODES:
            self.assertLessEqual(quant.wire_ratio(1 << 20, mode), 0.5)

    def test_deterministic(self):
        """Round-to-nearest, no stochastic rounding: two encodes of the
        same buffer are byte-identical (plans and programs pin
        run-to-run determinism everywhere else; the codec must not be
        the one nondeterministic stage)."""
        rng = np.random.default_rng(3)
        x = jnp.asarray(rng.standard_normal((4, 3000)).astype(np.float32))
        for mode in quant.MODES:
            np.testing.assert_array_equal(
                np.asarray(quant.encode_blocks(x, mode)),
                np.asarray(quant.encode_blocks(x, mode)),
            )


# --------------------------------------------------------------------- #
# 2. plans: gate-invariant census, annotated plan_ids, escape hatch     #
# --------------------------------------------------------------------- #
class TestQuantPlans(TestCase):
    def test_golden_census_unchanged_gate_on_off(self):
        """The acceptance pin: for EVERY golden spec, strategy,
        collective census, and lap structure are identical with the
        codec forced on, forced bf16, and off — the codec wraps
        collectives, it never reroutes movement."""
        for name, spec in planner.golden_specs():
            plain = planner.plan(spec, BUDGET, quant="0")
            for mode in ("int8", "bf16"):
                q = planner.plan(spec, BUDGET, quant=mode)
                self.assertEqual(q.strategy, plain.strategy, name)
                self.assertEqual(q.collective_counts(), plain.collective_counts(), name)
                self.assertEqual(q.n_collectives, plain.n_collectives, name)
                # same pipe/lap structure: the tagged collective chunks
                coll_tags = [
                    (s.kind, s.chunk, s.overlap) for s in q.steps if s.is_collective
                ]
                plain_tags = [
                    (s.kind, s.chunk, s.overlap) for s in plain.steps if s.is_collective
                ]
                self.assertEqual(coll_tags, plain_tags, name)

    def test_quant_annotation_folds_into_plan_id(self):
        spec = RedistSpec.normalize((4096, 2048), "float32", 0, 1, 8)
        plain = planner.plan(spec, BUDGET, quant="0")
        q = planner.plan(spec, BUDGET, quant="int8")
        self.assertIsNone(plain.quant)
        self.assertIsNotNone(q.quant)
        self.assertNotEqual(plain.plan_id, q.plan_id)
        self.assertIn('"quant":', q.canonical_json())
        self.assertEqual(q.quant["tol"], quant.tolerance("int8"))

    def test_admissibility_policy(self):
        """The numerics-tolerance policy: f32 transient exchanges over
        the group threshold quantize; ints, small moves, and the
        materializing strategies never do."""
        big_f32 = RedistSpec.normalize((4096, 2048), "float32", 0, 1, 8)
        self.assertIsNotNone(planner.plan(big_f32, BUDGET, quant="int8").quant)
        # int dtype: rejected-as-lossless
        big_i32 = RedistSpec.normalize((4096, 2048), "int32", 0, 1, 8)
        self.assertIsNone(planner.plan(big_i32, BUDGET, quant="int8").quant)
        # f64: exact on the wire
        big_f64 = RedistSpec.normalize((4096, 2048), "float64", 0, 1, 8)
        self.assertIsNone(planner.plan(big_f64, BUDGET, quant="int8").quant)
        # small move: latency-bound, stays exact
        small = RedistSpec.normalize((64, 48), "float32", 0, 1, 8)
        self.assertIsNone(planner.plan(small, BUDGET, quant="int8").quant)
        # replicate materializes consumed values: never quantized
        repl = RedistSpec.normalize((4096, 2048), "float32", 0, None, 8)
        self.assertIsNone(planner.plan(repl, BUDGET, quant="int8").quant)

    def test_wire_bytes_at_least_halved_on_admissible_rows(self):
        """Acceptance: wire_bytes_sent / wire_bytes_raw ≤ 0.5 on the
        int8-admissible gated bench specs (≈ 0.25 + scale overhead)."""
        names = {"resplit_chunked_2gb_p8", "reshape_split1_1gb_p8", "reshape_lane_1gb_p8"}
        seen = 0
        for name, spec in planner.golden_specs():
            if name not in names:
                continue
            # flat-wire pin: a tiered plan quantizes only its DCN hop
            # (whole-plan ratio ~0.7) — the per-tier ratio pins live in
            # tests/test_topology.py
            q = planner.plan(spec, BUDGET, quant="int8", topology="flat")
            self.assertIsNotNone(q.quant, name)
            self.assertLessEqual(q.wire_bytes_sent, 0.5 * q.wire_bytes_raw, name)
            self.assertLessEqual(q.quant["ratio"], 0.5, name)
            seen += 1
        self.assertEqual(seen, len(names))

    def test_escape_hatch_restores_pr6_plans(self):
        """HEAT_TPU_WIRE_QUANT=0 (and the CPU default `auto`) serialize
        byte-identically: no codec steps, no annotation — the exact
        PR 6 plan and plan_id."""
        spec = RedistSpec.normalize((4096, 2048), "float32", 0, 1, 8)
        dumps = []
        for mode in ("0", None):
            with _QuantEnv(mode):
                planner.clear_plan_cache()
                dumps.append(planner.plan(spec, BUDGET).canonical_json())
        self.assertEqual(dumps[0], dumps[1])
        self.assertNotIn('"quantize"', dumps[0])
        planner.clear_plan_cache()

    def test_env_gate_resolution(self):
        cases = {
            "0": None, "off": None,
            "1": "int8", "force": "int8", "int8": "int8",
            "bf16": "bf16",
        }
        for raw, want in cases.items():
            with _QuantEnv(raw):
                self.assertEqual(planner.wire_quant_gate(), want, raw)
        with _QuantEnv(None):  # auto: lossy int8 engages on TPU only
            want = "int8" if jax.default_backend() == "tpu" else None
            self.assertEqual(planner.wire_quant_gate(), want)

    def test_plan_cache_keyed_on_gate(self):
        """A gate flip must re-plan, never serve the other mode's plan."""
        spec = RedistSpec.normalize((4096, 2048), "float32", 0, 1, 8)
        with _QuantEnv("1"):
            q = planner.plan(spec, BUDGET)
        with _QuantEnv("0"):
            plain = planner.plan(spec, BUDGET)
        self.assertIsNotNone(q.quant)
        self.assertIsNone(plain.quant)
        self.assertNotEqual(q.plan_id, plain.plan_id)

    def test_describe_renders_codec_steps(self):
        """Satellite: explain().describe() renders quantize/dequantize
        steps with the modeled bytes saved."""
        spec = RedistSpec.normalize((4096, 2048), "float32", 0, 1, 8)
        text = planner.plan(spec, BUDGET, quant="int8").describe()
        self.assertIn("quantize", text)
        self.assertIn("dequantize", text)
        self.assertIn("saved", text)
        self.assertIn("quant: int8 wire codec", text)
        plain = planner.plan(spec, BUDGET, quant="0").describe()
        self.assertIn("quant: none", plain)


# --------------------------------------------------------------------- #
# 3. executed movement: tolerance, parity, lossless pins                #
# --------------------------------------------------------------------- #
@pytest.mark.skipif(P < 2, reason="needs a real mesh")
class TestQuantExecutor(TestCase):
    def _quantized_resplit(self, sched, oracle, src, dst):
        x = ht.array(oracle, split=src)
        y = executor.execute(self.comm, x._phys, sched.spec, sched)
        return np.asarray(_padding.unpad(y, oracle.shape, dst))

    def test_resplit_within_tolerance_both_modes(self):
        rng = np.random.default_rng(0)
        oracle = rng.standard_normal((4096, 2048)).astype(np.float32)
        spec = RedistSpec.normalize((4096, 2048), "float32", 0, 1, P)
        for mode in quant.MODES:
            sched = planner.plan(spec, BUDGET, quant=mode)
            if P < 8 and sched.quant is None:
                continue  # odd meshes may fall under the group threshold
            got = self._quantized_resplit(sched, oracle, 0, 1)
            err = np.abs(got - oracle).max()
            self.assertLessEqual(err, quant.tolerance(mode) * np.abs(oracle).max(), mode)

    def test_chunked_and_ring_seq_vs_pipelined_bit_identical(self):
        """The codec composes with the PR 6 pipelining: the two issue
        orders of the SAME quantized collectives are bit-identical."""
        rng = np.random.default_rng(1)
        oracle = rng.standard_normal((4096, 2048)).astype(np.float32)
        spec = RedistSpec.normalize((4096, 2048), "float32", 0, 1, P)
        tol = quant.tolerance("int8") * np.abs(oracle).max()
        x = ht.array(oracle, split=0)
        for budget in (4 << 20, 1 << 20):
            sched = planner.plan(spec, budget, quant="int8")
            outs = {}
            for ov in ("0", "1"):
                with env_pin(planner.OVERLAP_ENV, ov):
                    y = executor.execute(self.comm, x._phys, spec, sched)
                outs[ov] = np.asarray(y)
                got = np.asarray(_padding.unpad(y, (4096, 2048), 1))
                self.assertLessEqual(np.abs(got - oracle).max(), tol)
            np.testing.assert_array_equal(outs["0"], outs["1"], err_msg=str(budget))

    @pytest.mark.skipif(P != 8, reason="pivot geometry is 8-mesh-shaped")
    def test_reshape_pivot_within_tolerance(self):
        rng = np.random.default_rng(2)
        oracle = rng.standard_normal((8192, 1024)).astype(np.float32)
        spec = RedistSpec.normalize(
            (8192, 1024), "float32", 1, 1, 8, reshape_to=(4096, 2048)
        )
        sched = planner.plan(spec, BUDGET, quant="int8")
        self.assertIsNotNone(sched.quant)
        x = ht.array(oracle, split=1)
        y = executor.execute(self.comm, x._phys, spec, sched)
        got = np.asarray(_padding.unpad(y, (4096, 2048), 1))
        err = np.abs(got - oracle.reshape(4096, 2048)).max()
        self.assertLessEqual(err, quant.tolerance("int8") * np.abs(oracle).max())

    def test_lossless_paths_exact_bit_under_forced_gate(self):
        """Exact-bit pins: int dtypes, small f32 moves, and the
        replicate strategy stay bit-identical to the oracle even with
        the gate forced on — the admissibility policy, executed."""
        with _QuantEnv("1"):
            ints = np.arange(64 * 48, dtype=np.int32).reshape(64, 48)
            self.assert_array_equal(ht.array(ints, split=0).resplit(1), ints)
            small = np.arange(64 * 48, dtype=np.float32).reshape(64, 48)
            self.assert_array_equal(ht.array(small, split=0).resplit(1), small)
            self.assert_array_equal(ht.array(small, split=0).resplit(None), small)

    def test_escape_hatch_parity_with_pr6_program_forms(self):
        """HEAT_TPU_WIRE_QUANT=0 executes the exact PR 6 programs:
        bit-identical to the legacy direct reshard, shard for shard."""
        oracle = np.arange(4096 * 512, dtype=np.float32).reshape(4096, 512)
        with _QuantEnv("0"):
            x = ht.array(oracle, split=0)
            planned = executor.resplit_phys(self.comm, x._phys, (4096, 512), 0, 1)
            legacy = executor._reshard_direct(self.comm, x._phys, (4096, 512), 0, 1)
            np.testing.assert_array_equal(np.asarray(planned), np.asarray(legacy))

    def test_wire_telemetry_counters(self):
        from heat_tpu.observability import telemetry

        spec = RedistSpec.normalize((4096, 2048), "float32", 0, 1, P)
        sched = planner.plan(spec, BUDGET, quant="int8")
        if sched.quant is None:
            pytest.skip("group under threshold on this mesh")
        oracle = np.zeros((4096, 2048), np.float32)
        x = ht.array(oracle, split=0)
        telemetry.reset()
        telemetry.enable()
        try:
            executor.execute(self.comm, x._phys, spec, sched)
            snap = telemetry.snapshot()["counters"]
            self.assertEqual(snap["redist.wire.bytes_raw"], sched.wire_bytes_raw)
            self.assertEqual(snap["redist.wire.bytes_sent"], sched.wire_bytes_sent)
            self.assertEqual(
                snap["redist.wire.saved"],
                sched.wire_bytes_raw - sched.wire_bytes_sent,
            )
            if sched.topology is None:
                self.assertLessEqual(
                    snap["redist.wire.bytes_sent"], 0.5 * snap["redist.wire.bytes_raw"]
                )
            else:
                # tiered: only the DCN hop encodes — savings are real
                # but the whole-plan ratio includes the exact ICI leg
                self.assertLess(
                    snap["redist.wire.bytes_sent"], snap["redist.wire.bytes_raw"]
                )
        finally:
            telemetry.disable()
            telemetry.reset()

    def test_caller_pinned_quant_schedule_executes_regardless_of_gate(self):
        """execute(sched=...) pins the codec the plan was built with —
        the explicit-plan analog of the DP constructor opt-in."""
        spec = RedistSpec.normalize((4096, 2048), "float32", 0, 1, P)
        sched = planner.plan(spec, BUDGET, quant="int8")
        if sched.quant is None:
            pytest.skip("group under threshold on this mesh")
        with _QuantEnv("0"):
            rng = np.random.default_rng(5)
            oracle = rng.standard_normal((4096, 2048)).astype(np.float32)
            x = ht.array(oracle, split=0)
            y = executor.execute(self.comm, x._phys, spec, sched)
            got = np.asarray(_padding.unpad(y, (4096, 2048), 1))
            err = np.abs(got - oracle).max()
            self.assertGreater(err, 0.0)  # it really quantized
            self.assertLessEqual(err, quant.tolerance("int8") * np.abs(oracle).max())


# --------------------------------------------------------------------- #
# 4. quantized-gradient DP mode                                         #
# --------------------------------------------------------------------- #
def _toy_problem(n=512, d=16, classes=4, seed=0):
    rng = np.random.default_rng(seed)
    w = rng.standard_normal((d, classes)).astype(np.float32)
    x = rng.standard_normal((n, d)).astype(np.float32)
    y = np.argmax(x @ w, axis=1)
    return x, y.astype(np.int32)


def _mlp(d=16, classes=4):
    return htnn.Sequential(htnn.Linear(d, 32), htnn.ReLU(), htnn.Linear(32, classes))


@pytest.mark.skipif(P < 2, reason="needs a real mesh")
class TestQuantizedDP(TestCase):
    def test_error_feedback_converges_like_exact_dp(self):
        """The toy DP loop: int8/bf16 gradient wire with error feedback
        must reach the exact psum's training quality (EF re-injects the
        compression residual — the long-run gradient is unbiased)."""
        x_np, y_np = _toy_problem()
        x = ht.array(x_np, split=0)
        y = ht.array(y_np, split=0)
        finals = {}
        for mode in (None, "bf16", "int8"):
            dp = htnn.DataParallel(_mlp(), key=1)
            opt = htoptim.DataParallelOptimizer(
                htoptim.Adam(lr=0.01), dp, wire_quant=mode
            )
            losses = [float(opt.step(x, y)) for _ in range(50)]
            self.assertLess(losses[-1], 0.3 * losses[0], mode)
            preds = np.argmax(dp(x).numpy(), axis=1)
            finals[mode] = (preds == y_np).mean()
            self.assertGreater(finals[mode], 0.9, mode)
            if mode is not None:
                # the EF carry stays bounded (no residual blow-up)
                carry = np.asarray(opt._ef_carry)
                self.assertLess(np.abs(carry).max(), 1.0, mode)
        # quantized quality tracks exact within a few points
        self.assertGreaterEqual(finals["int8"], finals[None] - 0.05)
        self.assertGreaterEqual(finals["bf16"], finals[None] - 0.05)

    def test_quant_step_census_is_a2a_plus_gather(self):
        """The decomposed all-reduce: exactly one all-to-all (the
        reduce-scatter leg) + encoded all-gather(s) carry the gradient;
        no gradient-sized all-reduce remains. At a flat topology the
        wire is 1 a2a + 1 all-gather (both encoded); at a tiered one
        (ISSUE 8) the hierarchical form is 1 intra-slice a2a (f32) + 1
        inter-slice encoded all-gather + 1 intra-slice all-gather."""
        x_np, y_np = _toy_problem(n=64, seed=3)
        dp = htnn.DataParallel(_mlp(), key=5)
        opt = htoptim.DataParallelOptimizer(
            htoptim.SGD(lr=0.1), dp, wire_quant="int8"
        )
        x = ht.array(x_np, split=0)
        y = ht.array(y_np, split=0)
        opt.step(x, y)  # builds the carry and the cached program
        xb, yb = x._phys, y._phys
        fn = opt._get_quant_step(
            tuple(xb.shape), str(xb.dtype), tuple(yb.shape), str(yb.dtype), x.shape[0]
        )
        rep = ht.observability.collective_counts(
            fn, opt.model.params, opt.opt_state, opt._ef_carry, xb, yb,
            jax.random.PRNGKey(0),
        )
        topo = planner.resolve_topology(P)
        tiered = topo is not None and topo[1] > 1
        self.assertEqual(rep.counts.get("all-to-all", 0), 1)
        self.assertEqual(rep.counts.get("all-gather", 0), 2 if tiered else 1)
        n = opt._flat_param_count()
        if not tiered:
            # the wire is int8: the a2a ships exactly the encoded blocks
            # (per-device block of ceil(n/p) elements, one wire row each —
            # tile padding dominates at toy sizes, the RATIO story lives in
            # wire_bytes_at_least_halved on the bench-scale specs)
            k = -(-n // P)
            self.assertEqual(
                rep.bytes_by_op["all-to-all"], P * quant.wire_bytes(k, "int8")
            )
        else:
            # the intra-slice reduce-scatter a2a stays f32 full width
            # (the ICI tier is exact); only the inter-slice gather is
            # encoded — C-fold fewer encoded bytes than the flat wire
            S, C = topo
            k = -(-n // C)
            self.assertEqual(rep.bytes_by_op["all-to-all"], C * k * 4)

    def test_codec_narrowing_reports_as_info_not_error(self):
        """Satellite pin: the STAMPED codec converts inside the DP quant
        step downgrade SL104 to info; the program gates clean."""
        x_np, y_np = _toy_problem(n=64, seed=4)
        dp = htnn.DataParallel(_mlp(), key=2)
        opt = htoptim.DataParallelOptimizer(
            htoptim.SGD(lr=0.1), dp, wire_quant="int8"
        )
        x = ht.array(x_np, split=0)
        y = ht.array(y_np, split=0)
        opt.step(x, y)
        xb, yb = x._phys, y._phys
        fn = opt._get_quant_step(
            tuple(xb.shape), str(xb.dtype), tuple(yb.shape), str(yb.dtype), x.shape[0]
        )
        rep = ht.analysis.check(
            fn, opt.model.params, opt.opt_state, opt._ef_carry, xb, yb,
            jax.random.PRNGKey(0),
        )
        sl104 = [f for f in rep.findings if f.rule == "SL104"]
        self.assertTrue(sl104)
        for f in sl104:
            self.assertEqual(f.severity, "info")
            self.assertIn("wire-codec", f.message)
        self.assertTrue(rep.ok)

    def test_invalid_mode_rejected(self):
        dp = htnn.DataParallel(_mlp(), key=0)
        with self.assertRaises(ValueError):
            htoptim.DataParallelOptimizer(htoptim.SGD(lr=0.1), dp, wire_quant="fp8")


class TestDPStepModel(TestCase):
    def test_ici_bound_layer_improves_at_least_1_5x(self):
        """Acceptance: on the analytic v5e-64 model, an ICI-bound layer
        (100M f32 params, 1 ms compute — wire ≈ 3.9 ms) improves ≥ 1.5×
        under the int8 codec."""
        m = quant.dp_step_model(400_000_000, compute_s=1e-3, p=64, mode="int8")
        self.assertTrue(m["ici_bound"])
        self.assertGreaterEqual(m["model_speedup"], 1.5)
        self.assertLessEqual(m["wire_ratio"], 0.5)
        # bf16 halves the wire: still ≥ 1.5x while the layer stays bound
        mb = quant.dp_step_model(400_000_000, compute_s=1e-3, p=64, mode="bf16")
        self.assertGreaterEqual(mb["model_speedup"], 1.5)

    def test_compute_bound_layer_gains_nothing(self):
        """max(compute, wire): once compute binds, the codec cannot
        fabricate speedup — the model says exactly 1.0."""
        m = quant.dp_step_model(1_000_000, compute_s=1e-2, p=64, mode="int8")
        self.assertFalse(m["ici_bound"])
        self.assertEqual(m["model_speedup"], 1.0)

    def test_wire_arithmetic(self):
        m = quant.dp_step_model(400_000_000, compute_s=1e-3, p=64, mode="int8")
        # 2*(p-1)/p * 400 MB / 200 GB/s
        self.assertAlmostEqual(m["wire_s_raw"], 2 * 63 / 64 * 4e8 / 200e9, places=9)
        self.assertAlmostEqual(
            m["wire_s_quant"], m["wire_s_raw"] * m["wire_ratio"], places=6
        )


if __name__ == "__main__":
    import unittest

    unittest.main()
