"""Sparse package tests, modeled on the reference's sparse test scenarios
(/root/reference/heat/sparse/tests/: factories from torch/scipy CSR and
is_split stitching, component properties, add/mul patterns, to_dense)."""

import numpy as np
import pytest
import scipy.sparse as sp

import jax
import jax.numpy as jnp

import heat_tpu as ht
from heat_tpu.sparse import (
    DCSR_matrix,
    sparse_csr_matrix,
    sparse_add,
    sparse_mul,
    to_dense,
    to_sparse,
)


def _ref_matrix(seed=0, m=9, n=7, density=0.3):
    rng = np.random.default_rng(seed)
    dense = rng.standard_normal((m, n)).astype(np.float32)
    dense[rng.random((m, n)) > density] = 0.0
    return sp.csr_matrix(dense)


class TestFactories:
    def test_from_scipy(self):
        ref = _ref_matrix()
        s = sparse_csr_matrix(ref, split=0)
        assert isinstance(s, DCSR_matrix)
        assert s.shape == ref.shape
        assert s.nnz == ref.nnz
        np.testing.assert_array_equal(np.asarray(s.indptr), ref.indptr)
        np.testing.assert_array_equal(np.asarray(s.indices), ref.indices)
        np.testing.assert_allclose(np.asarray(s.data), ref.data)

    def test_from_torch_sparse_csr(self):
        import torch

        ref = _ref_matrix(seed=1)
        t = torch.sparse_csr_tensor(
            torch.tensor(ref.indptr, dtype=torch.int64),
            torch.tensor(ref.indices, dtype=torch.int64),
            torch.tensor(ref.data),
            size=ref.shape,
        )
        s = sparse_csr_matrix(t, split=0)
        np.testing.assert_array_equal(np.asarray(s.indptr), ref.indptr)
        np.testing.assert_allclose(np.asarray(s.data), ref.data)

    def test_from_dense_listlike(self):
        dense = [[1.0, 0.0, 2.0], [0.0, 0.0, 0.0], [0.0, 3.0, 0.0]]
        s = sparse_csr_matrix(dense, split=0)
        assert s.nnz == 3
        np.testing.assert_array_equal(np.asarray(s.indptr), [0, 2, 2, 3])
        np.testing.assert_array_equal(np.asarray(s.indices), [0, 2, 1])

    def test_is_split_stitches_blocks(self):
        ref = _ref_matrix(seed=2, m=8)
        blocks = [ref[:3], ref[3:5], ref[5:]]
        s = sparse_csr_matrix(blocks, is_split=0)
        assert s.split == 0
        assert s.shape == ref.shape
        np.testing.assert_array_equal(np.asarray(s.indptr), ref.indptr)
        np.testing.assert_allclose(np.asarray(s.data), ref.data)

    def test_invalid_split(self):
        with pytest.raises(ValueError):
            sparse_csr_matrix(_ref_matrix(), split=1)

    def test_dtype_override(self):
        s = sparse_csr_matrix(_ref_matrix(), dtype=ht.float64, split=0)
        assert s.dtype == ht.float64


class TestProperties:
    def test_local_row_block_views(self):
        ref = _ref_matrix(seed=3, m=16)
        s = sparse_csr_matrix(ref, split=0)
        r0, r1 = s._row_block()
        blk = ref[r0:r1]
        np.testing.assert_array_equal(np.asarray(s.lindptr), blk.indptr)
        np.testing.assert_array_equal(np.asarray(s.lindices), blk.indices)
        np.testing.assert_allclose(np.asarray(s.ldata), blk.data)
        assert s.lnnz == blk.nnz
        assert s.lshape[0] == r1 - r0

    def test_global_indptr_and_counts(self):
        ref = _ref_matrix(seed=4, m=12)
        s = sparse_csr_matrix(ref, split=0)
        gp = s.global_indptr()
        np.testing.assert_array_equal(gp.numpy(), ref.indptr)
        counts, displs = s.counts_displs_nnz()
        assert sum(counts) == ref.nnz
        assert len(counts) == s.comm.size
        # displacements must be consistent with counts
        for c, d, d_next in zip(counts[:-1], displs[:-1], displs[1:]):
            assert d + c == d_next

    def test_astype(self):
        s = sparse_csr_matrix(_ref_matrix(), split=0)
        d = s.astype(ht.float64)
        assert d.dtype == ht.float64
        np.testing.assert_allclose(np.asarray(d.data), np.asarray(s.data))

    def test_nnz_sharded_physical_layout(self):
        """Values/indices are evenly nnz-sharded over the mesh (the
        TPU-native load-balance replacing row-block distribution)."""
        ref = _ref_matrix(seed=5, m=32, n=32, density=0.4)
        s = sparse_csr_matrix(ref, split=0)
        phys = s._DCSR_matrix__data
        sizes = {sh.data.shape[0] for sh in phys.addressable_shards}
        assert len(sizes) == 1  # even blocks


class TestArithmetics:
    def test_add_union_pattern(self):
        a = _ref_matrix(seed=6)
        b = _ref_matrix(seed=7)
        sa = sparse_csr_matrix(a, split=0)
        sb = sparse_csr_matrix(b, split=0)
        out = sparse_add(sa, sb)
        ref = (a + b).tocsr()
        ref.sort_indices()
        np.testing.assert_array_equal(np.asarray(out.indptr), ref.indptr)
        np.testing.assert_array_equal(np.asarray(out.indices), ref.indices)
        np.testing.assert_allclose(np.asarray(out.data), ref.data, rtol=1e-6)

    def test_add_dunder_and_overlap(self):
        a = _ref_matrix(seed=8)
        sa = sparse_csr_matrix(a, split=0)
        out = sa + sa
        ref = (a + a).tocsr()
        np.testing.assert_allclose(np.asarray(out.data), ref.data, rtol=1e-6)
        np.testing.assert_array_equal(np.asarray(out.indptr), ref.indptr)

    def test_mul_intersection_pattern(self):
        a = _ref_matrix(seed=9)
        b = _ref_matrix(seed=10)
        sa = sparse_csr_matrix(a, split=0)
        sb = sparse_csr_matrix(b, split=0)
        out = sparse_mul(sa, sb)
        ref = a.multiply(b).tocsr()
        ref.sort_indices()
        np.testing.assert_array_equal(np.asarray(out.indptr), ref.indptr)
        np.testing.assert_array_equal(np.asarray(out.indices), ref.indices)
        np.testing.assert_allclose(np.asarray(out.data), ref.data, rtol=1e-6)

    def test_mul_scalar(self):
        a = _ref_matrix(seed=11)
        sa = sparse_csr_matrix(a, split=0)
        out = sa * 2.5
        np.testing.assert_allclose(np.asarray(out.data), a.data * 2.5, rtol=1e-6)
        np.testing.assert_array_equal(np.asarray(out.indptr), a.indptr)

    def test_huge_column_space_no_key_overflow(self):
        """Linearized keys must widen to int64 when m*ncols > 2^31."""
        m, n = 3, 2**30
        a = sp.csr_matrix(
            (np.array([1.0, 2.0, 3.0], dtype=np.float32),
             np.array([n - 1, 0, 5]),
             np.array([0, 1, 2, 3])),
            shape=(m, n),
        )
        sa = sparse_csr_matrix(a, split=0)
        out = sparse_add(sa, sa)
        np.testing.assert_array_equal(np.asarray(out.indices), [n - 1, 0, 5])
        np.testing.assert_allclose(np.asarray(out.data), [2.0, 4.0, 6.0])

    def test_mul_scalar_promotes_int_matrix(self):
        a = sp.csr_matrix(np.array([[3, 0], [0, 4]], dtype=np.int32))
        sa = sparse_csr_matrix(a, split=0)
        out = sa * 2.5
        assert out.dtype == ht.float32
        np.testing.assert_allclose(np.asarray(out.data), [7.5, 10.0])

    def test_add_scalar_raises(self):
        sa = sparse_csr_matrix(_ref_matrix(), split=0)
        with pytest.raises(TypeError):
            sa + 1.0

    def test_shape_mismatch_raises(self):
        sa = sparse_csr_matrix(_ref_matrix(m=4), split=0)
        sb = sparse_csr_matrix(_ref_matrix(m=5), split=0)
        with pytest.raises(ValueError):
            sparse_add(sa, sb)

    def test_empty_operands(self):
        m, n = 5, 4
        empty = sparse_csr_matrix(sp.csr_matrix((m, n), dtype=np.float32), split=0)
        out = sparse_add(empty, empty)
        assert out.nnz == 0
        np.testing.assert_array_equal(np.asarray(out.indptr), np.zeros(m + 1))
        dense = to_dense(out)
        np.testing.assert_array_equal(dense.numpy(), np.zeros((m, n), dtype=np.float32))

    def test_promotion(self):
        a = _ref_matrix(seed=12)
        sa = sparse_csr_matrix(a, dtype=ht.float32, split=0)
        sb = sparse_csr_matrix(a, dtype=ht.float64, split=0)
        assert sparse_add(sa, sb).dtype == ht.float64


class TestManipulations:
    def test_to_dense_round_trip(self):
        ref = _ref_matrix(seed=13)
        s = sparse_csr_matrix(ref, split=0)
        dense = to_dense(s)
        assert dense.split == 0
        np.testing.assert_allclose(dense.numpy(), ref.toarray(), rtol=1e-6)

    def test_to_sparse_from_dndarray(self):
        ref = _ref_matrix(seed=14)
        x = ht.array(ref.toarray(), split=0)
        s = x.to_sparse()
        assert isinstance(s, DCSR_matrix)
        assert s.split == 0
        np.testing.assert_array_equal(np.asarray(s.indptr), ref.indptr)
        np.testing.assert_allclose(np.asarray(s.data), ref.data, rtol=1e-6)

    def test_to_dense_out_param(self):
        ref = _ref_matrix(seed=15)
        s = sparse_csr_matrix(ref, split=0)
        out = ht.zeros(ref.shape, split=0)
        res = to_dense(s, out=out)
        assert res is out
        np.testing.assert_allclose(out.numpy(), ref.toarray(), rtol=1e-6)

    def test_to_dense_out_mismatch_raises(self):
        s = sparse_csr_matrix(_ref_matrix(seed=16), split=0)
        with pytest.raises(ValueError):
            to_dense(s, out=ht.zeros(s.shape, split=None))
        with pytest.raises(ValueError):
            to_dense(s, out=ht.zeros((s.shape[0] + 1, s.shape[1]), split=0))

    def test_repr_smoke(self):
        s = sparse_csr_matrix(_ref_matrix(m=3, n=3), split=0)
        assert "indptr" in repr(s)


class TestSparseMatmul:
    """SpMV/SpMM (heat_tpu extension beyond reference parity — the
    reference's sparse type has no multiplication)."""

    def _mk(self, split, density=0.3, shape=(13, 9)):
        import scipy.sparse as sp

        rng = np.random.default_rng(0)
        dense = ((rng.random(shape) < density) * rng.standard_normal(shape)).astype(np.float32)
        return ht.sparse.sparse_csr_matrix(sp.csr_matrix(dense), split=split), dense

    def test_spmv_matches_scipy(self):
        for split in (0, None):
            A, dense = self._mk(split)
            x = np.random.default_rng(1).standard_normal(9).astype(np.float32)
            y = A @ ht.array(x, split=0 if split == 0 else None)
            np.testing.assert_allclose(y.numpy(), dense @ x, rtol=1e-5, atol=1e-6)
            assert y.split == split

    def test_spmm_matches_scipy(self):
        A, dense = self._mk(0)
        X = np.random.default_rng(2).standard_normal((9, 4)).astype(np.float32)
        Y = ht.sparse.matmul(A, X)
        np.testing.assert_allclose(Y.numpy(), dense @ X, rtol=1e-5, atol=1e-6)
        assert Y.gshape == (13, 4)

    def test_dtype_promotion_and_errors(self):
        A, dense = self._mk(0)
        xi = np.arange(9, dtype=np.int32)
        y = A @ ht.array(xi)
        np.testing.assert_allclose(y.numpy(), dense @ xi, rtol=1e-5, atol=1e-5)
        with pytest.raises(ValueError):
            ht.sparse.matmul(A, np.zeros(5, np.float32))
        with pytest.raises(TypeError):
            ht.sparse.matmul(dense, xi)

    def test_empty_rows_and_all_zero(self):
        import scipy.sparse as sp

        dense = np.zeros((11, 6), np.float32)
        dense[3, 2] = 5.0
        A = ht.sparse.sparse_csr_matrix(sp.csr_matrix(dense), split=0)
        x = np.ones(6, np.float32)
        np.testing.assert_allclose((A @ ht.array(x)).numpy(), dense @ x)


class TestSpMVEdgeCases:
    """ISSUE 18 satellite: the DCSR segment-sum SpMV at its degenerate
    geometries — empty rows, all-zero matrices, nnz not divisible by the
    mesh, sub-f32 data — pinned against the scipy/numpy oracle at EVERY
    mesh size (the 5-device CI leg replays this suite on the odd mesh)."""

    def test_all_zero_matrix_short_circuits(self):
        import scipy.sparse as sp

        A = ht.sparse.sparse_csr_matrix(sp.csr_matrix((12, 7), dtype=np.float32), split=0)
        assert A.nnz == 0
        y = A @ np.ones(7, np.float32)
        np.testing.assert_array_equal(y.numpy(), np.zeros(12, np.float32))
        Y = ht.sparse.matmul(A, np.ones((7, 3), np.float32))
        np.testing.assert_array_equal(Y.numpy(), np.zeros((12, 3), np.float32))
        assert Y.split == 0

    def test_nnz_not_divisible_by_mesh(self):
        """nnz coprime to every plausible device count: the padded
        nnz-sharding must stay contribution-free."""
        import scipy.sparse as sp

        p = len(jax.devices())
        rng = np.random.default_rng(31)
        m, n, nnz = 23, 17, 97  # all prime — never divisible by p > 1
        rows = rng.integers(0, m, nnz)
        cols = rng.integers(0, n, nnz)
        csr = sp.csr_matrix(
            (rng.standard_normal(nnz).astype(np.float32), (rows, cols)), shape=(m, n)
        )
        csr.sum_duplicates()
        assert csr.nnz % max(p, 2) != 0 or p == 1
        A = ht.sparse.sparse_csr_matrix(csr, split=0)
        x = rng.standard_normal(n).astype(np.float32)
        np.testing.assert_allclose((A @ x).numpy(), csr @ x, rtol=1e-5, atol=1e-5)

    def test_bf16_accumulates_in_f32(self):
        """Sub-f32 data widens to f32 inside the contraction (SL601) —
        long rows keep far better error than a bf16 accumulator would."""
        import scipy.sparse as sp

        rng = np.random.default_rng(32)
        n = 4096
        dense = np.zeros((4, n), np.float32)
        dense[1] = rng.random(n).astype(np.float32)  # one long row
        A = ht.sparse.sparse_csr_matrix(sp.csr_matrix(dense), split=0).astype(ht.bfloat16)
        x = jnp.ones(n, jnp.bfloat16)
        y = A @ x
        assert y.dtype == ht.bfloat16  # bf16 in, bf16 out (promotion)
        ref = dense.astype(np.float32) @ x
        # bf16 accumulation over 4096 terms would drift percents; the
        # f32 accumulator keeps the relative error at bf16 ULP scale
        np.testing.assert_allclose(
            y.numpy().astype(np.float32)[1], ref[1], rtol=1e-2
        )

    def test_odd_mesh_parity_vs_oracle(self):
        """Shape/nnz sweep vs scipy — the divisibility sweep the odd
        (5-device) CI leg exists for."""
        import scipy.sparse as sp

        rng = np.random.default_rng(33)
        for m, n, nnz, k in ((5, 5, 3, None), (41, 29, 111, 2), (64, 128, 513, 7)):
            rows = rng.integers(0, m, nnz)
            cols = rng.integers(0, n, nnz)
            csr = sp.csr_matrix(
                (rng.standard_normal(nnz).astype(np.float32), (rows, cols)),
                shape=(m, n),
            )
            csr.sum_duplicates()
            A = ht.sparse.sparse_csr_matrix(csr, split=0)
            x = rng.standard_normal((n,) if k is None else (n, k)).astype(np.float32)
            np.testing.assert_allclose(
                ht.sparse.matmul(A, x).numpy(), csr @ x, rtol=1e-5, atol=1e-5
            )
