"""Shared test base class.

The analog of the reference's ``TestCase``
(/root/reference/heat/core/tests/test_suites/basic_test.py:12):
``assert_array_equal(heat_array, expected)`` verifies the global result
against a NumPy oracle AND checks every device shard against the
corresponding slice of the oracle — the single-controller equivalent of
"each MPI rank's local tensor matches its numpy slice"
(reference basic_test.py:65-120). ``assert_func_equal`` applies a function
via heat_tpu and numpy over several splits.
"""

import contextlib
import os
import unittest

import numpy as np

import heat_tpu as ht


@contextlib.contextmanager
def env_pin(name, value):
    """Pin one environment gate for a block and restore it on exit —
    the save/set/restore pattern every gated-feature suite (sort,
    relayout, overlap, quant) needs. ``value=None`` unsets the var
    (the gate's default resolution)."""
    old = os.environ.get(name)
    try:
        if value is None:
            os.environ.pop(name, None)
        else:
            os.environ[name] = value
        yield
    finally:
        if old is None:
            os.environ.pop(name, None)
        else:
            os.environ[name] = old


class TestCase(unittest.TestCase):
    __comm = None
    __device = None

    @property
    def comm(self):
        if TestCase.__comm is None:
            TestCase.__comm = ht.get_comm()
        return TestCase.__comm

    @property
    def device(self):
        if TestCase.__device is None:
            TestCase.__device = ht.get_device()
        return TestCase.__device

    def get_rank(self):
        return self.comm.rank

    def get_size(self):
        return self.comm.size

    def assert_array_equal(self, heat_array, expected_array, rtol=1e-5, atol=1e-8):
        """Global result matches the oracle; every device shard matches its
        slice of the oracle."""
        self.assertIsInstance(
            heat_array, ht.DNDarray, f"The array to test was not a DNDarray, but {type(heat_array)}"
        )
        expected_array = np.asarray(expected_array)
        self.assertEqual(
            tuple(heat_array.shape),
            tuple(expected_array.shape),
            f"Global shapes do not match: {heat_array.shape} != {expected_array.shape}",
        )

        got = heat_array.numpy()
        if np.issubdtype(expected_array.dtype, np.floating) or np.issubdtype(
            expected_array.dtype, np.complexfloating
        ):
            np.testing.assert_allclose(got, expected_array, rtol=rtol, atol=atol)
        else:
            np.testing.assert_array_equal(got, expected_array)

        # shard-level check: each device's (physical) shard equals the
        # oracle slice from the chunk geometry, pad rows excluded
        split = heat_array.split
        if split is not None:
            comm = heat_array.comm
            shards_by_device = {
                id(sh.device): sh for sh in heat_array._phys.addressable_shards
            }
            for r, dev in enumerate(comm.devices):
                shard = shards_by_device.get(id(dev))
                if shard is None:
                    continue
                _, lshape, slices = comm.chunk(heat_array.shape, split, rank=r)
                shard_np = np.asarray(shard.data)
                if shard_np.dtype.kind not in "biufc":
                    shard_np = shard_np.astype(np.float32)
                valid = [slice(0, int(e)) for e in lshape]
                shard_np = shard_np[tuple(valid)]
                expected_slice = expected_array[slices]
                if np.issubdtype(expected_array.dtype, np.floating):
                    np.testing.assert_allclose(
                        shard_np, expected_slice, rtol=rtol, atol=atol,
                        err_msg=f"shard {r} does not match oracle slice {slices}",
                    )
                else:
                    np.testing.assert_array_equal(shard_np, expected_slice)

    def assert_func_equal(
        self,
        shape,
        heat_func,
        numpy_func,
        distributed_result=True,
        heat_args=None,
        numpy_args=None,
        data_types=(np.int32, np.int64, np.float32, np.float64),
        low=-10000,
        high=10000,
    ):
        """Apply the same function via heat_tpu and numpy over all splits
        (reference basic_test.py assert_func_equal)."""
        heat_args = heat_args or {}
        numpy_args = numpy_args or {}
        if not isinstance(shape, (tuple, list)):
            raise ValueError(f"shape must be tuple or list, got {type(shape)}")

        for dtype in data_types:
            if np.issubdtype(dtype, np.floating):
                np_array = np.random.randn(*shape).astype(dtype)
            else:
                np_array = np.random.randint(low=low, high=high, size=shape, dtype=dtype)
            expected = numpy_func(np_array.copy(), **numpy_args)
            for split in [None] + list(range(len(shape))):
                ht_array = ht.array(np_array, split=split)
                result = heat_func(ht_array, **heat_args)
                if isinstance(result, ht.DNDarray):
                    self.assert_array_equal(result, expected)
                else:
                    np.testing.assert_allclose(np.asarray(result), expected, rtol=1e-5)

    def assertTrue_memory_layout(self, tensor, order):
        # XLA owns physical layout on TPU; nothing to assert
        return True
