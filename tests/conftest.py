"""Test configuration: run the suite on a virtual 8-device CPU mesh.

The analog of the reference's ``mpirun -n N pytest heat/`` CI runs
(/root/reference/.github/workflows/ci.yaml:54-56): multi-device behavior is
exercised without hardware by forcing N host platform devices. Must run
before any jax backend initialization.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax

jax.config.update("jax_platforms", "cpu")
