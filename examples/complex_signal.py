"""Complex arrays on a complex-less accelerator — the planar surface.

The TPU behind this environment has no XLA complex implementation, so
heat_tpu runs complex DNDarrays in PLANAR form: split real/imaginary f32
planes computed by ordinary XLA programs (``core/complex_planar.py``;
reference parity target: ``heat/core/complex_math.py``). This demo walks
a small quadrature-signal workload through the surface: factories →
arithmetic → ``complex_math`` → reductions → the Gauss 3-matmul — and
shows the actionable refusal for an op outside the surface.

    python examples/complex_signal.py                       # real TPU
    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python examples/complex_signal.py                   # 8-dev CPU mesh
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

if os.environ.get("JAX_PLATFORMS"):
    import jax

    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import numpy as np

import heat_tpu as ht
from heat_tpu.core import devices


def main() -> None:
    # force the accelerator policy so the demo shows planar everywhere
    # (on the real TPU this is already the default)
    ht.use_complex("planar")
    print(f"complex mode: {devices.complex_mode()}")

    # a complex exponential sweep (quadrature signal), sharded over the mesh
    n = 4096
    t = np.linspace(0.0, 1.0, n).astype(np.float32)
    sig_np = np.exp(2j * np.pi * 40.0 * t).astype(np.complex64)
    sig = ht.array(sig_np, split=0)
    assert sig._is_planar and sig.split == 0
    print(f"signal: {sig.shape} {sig.dtype.__name__}, split={sig.split} (planar planes)")

    # complex_math surface (reference complex_math.py parity)
    inst_phase = ht.angle(sig)
    print(f"instantaneous phase range: [{float(inst_phase.min()):+.3f}, "
          f"{float(inst_phase.max()):+.3f}] rad")

    # demodulate: multiply by the conjugate carrier -> DC
    carrier = ht.array(np.exp(2j * np.pi * 40.0 * t).astype(np.complex64), split=0)
    base = sig * ht.conj(carrier)
    dc = ht.mean(base)
    print(f"demodulated mean (expect ~1+0j): {complex(dc):.4f}")

    # energy via the conjugate product, all on-device plane arithmetic
    energy = float(ht.sum((sig * ht.conj(sig)).real).numpy())
    print(f"signal energy (expect {n}): {energy:.1f}")

    # Gauss 3-matmul: a complex Gram matrix on the MXU
    m = ht.reshape(sig, (64, 64))
    gram = ht.matmul(m, ht.conj(m).resplit(None).T, precision="highest")
    oracle = sig_np.reshape(64, 64) @ np.conj(sig_np.reshape(64, 64)).T
    err = float(np.max(np.abs(gram.numpy() - oracle)))
    print(f"complex gram via 3 real MXU matmuls, max |err| vs numpy: {err:.2e}")

    # outside the surface: loud, actionable — never silently wrong
    try:
        ht.sort(sig)
    except TypeError as exc:
        print(f"sort refused as documented: {str(exc)[:72]}...")


if __name__ == "__main__":
    main()
