"""Data-parallel MLP training — the analog of the reference's
examples/nn/mnist.py (BASELINE config #5), written against heat_tpu's
nn/optim/data layers.

Runs on real MNIST when IDX files are available (point --data-root at a
directory containing MNIST/raw/...); otherwise trains on a synthetic
separable classification task so the example is runnable offline.

    python examples/mnist.py [--epochs 3] [--data-root PATH]
"""

from __future__ import annotations

import argparse
import os
import sys

# allow running straight from a checkout: examples/.. is the repo root
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# honor JAX_PLATFORMS even when a site PJRT plugin overrides it (see
# tests/conftest.py: env alone is not reliably honored)
if os.environ.get("JAX_PLATFORMS"):
    import jax

    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])


import numpy as np

import heat_tpu as ht
from heat_tpu import nn, optim


def load_data(data_root, cnn=False):
    if data_root:
        from heat_tpu.utils.data.mnist import MNISTDataset

        ds = MNISTDataset(data_root, train=True)
        # MNISTDataset already scales pixels to [0, 1]
        x = np.asarray(ds.data).astype(np.float32)
        x = x.reshape(len(x), 1, 28, 28) if cnn else x.reshape(len(x), -1)
        y = ds.targets.astype(np.int32)
        return ht.array(x[:8192], split=0), ht.array(y[:8192], split=0), 784, 10
    # offline fallback: separable blobs, one per class (as 8x8 "images"
    # in cnn mode)
    rng = np.random.default_rng(0)
    n, d, k = 4096, 64 if cnn else 16, 4
    centers = rng.standard_normal((k, d)).astype(np.float32) * 4
    y = rng.integers(0, k, n).astype(np.int32)
    x = (centers[y] + rng.standard_normal((n, d))).astype(np.float32)
    if cnn:
        x = x.reshape(n, 1, 8, 8)
    return ht.array(x, split=0), ht.array(y, split=0), d, k


def cnn_net(n_cls, side):
    """The reference example's CNN (examples/nn/mnist.py:23-31: two 3x3
    convs, max-pool, dropout, two fc layers) built from heat_tpu layers."""
    flat = 64 * ((side - 4) // 2) ** 2
    return nn.Sequential(
        nn.Conv2d(1, 32, 3),
        nn.ReLU(),
        nn.Conv2d(32, 64, 3),
        nn.ReLU(),
        nn.MaxPool2d(2),
        nn.Dropout2d(0.25),
        nn.Flatten(),
        nn.Linear(flat, 128),
        nn.ReLU(),
        nn.Dropout(0.5),
        nn.Linear(128, n_cls),
    )


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=3)
    p.add_argument("--lr", type=float, default=0.05)
    p.add_argument("--data-root", default=None)
    p.add_argument("--cnn", action="store_true",
                   help="train the reference example's Conv2d net instead of the MLP")
    args = p.parse_args()

    x, y, d_in, n_cls = load_data(args.data_root, cnn=args.cnn)
    if args.cnn:
        model = cnn_net(n_cls, x.shape[-1])
    else:
        model = nn.Sequential(nn.Linear(d_in, 128), nn.ReLU(), nn.Linear(128, n_cls))
    dp = nn.DataParallel(model)                      # grad-psum over the mesh
    opt = optim.DataParallelOptimizer(optim.SGD(lr=args.lr), dp)

    steps_per_epoch = 20
    for epoch in range(args.epochs):
        loss = None
        for _ in range(steps_per_epoch):
            loss = opt.step(x, y)
        preds = ht.argmax(dp(x), axis=1)
        acc = float(ht.mean((preds == y).astype(ht.float32)))
        ht.print0(f"epoch {epoch}: loss={float(loss):.4f} acc={acc:.3f}")


if __name__ == "__main__":
    main()
