"""K-clustering family demo on synthetic spherical data — the analog of
the reference's examples/cluster/demo_kClustering.py (fit KMeans,
KMedians and KMedoids on a 4-cluster spherical dataset and report the
recovered centroids).

    python examples/kcluster.py [--samples 5000]
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 python examples/kcluster.py
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

if os.environ.get("JAX_PLATFORMS"):
    import jax

    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import numpy as np

import heat_tpu as ht
from heat_tpu.utils.data.spherical import create_spherical_dataset


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--samples", type=int, default=5000, help="samples per cluster")
    args = ap.parse_args()

    data = create_spherical_dataset(
        num_samples_cluster=args.samples, radius=1.0, offset=4.0,
        dtype=ht.float32, random_state=1,
    )
    print(f"data: {data.shape} split={data.split} over {data.comm.size} device(s)")

    for name, algo in [
        ("KMeans", ht.cluster.KMeans(n_clusters=4, init="kmeans++", random_state=1)),
        ("KMedians", ht.cluster.KMedians(n_clusters=4, init="kmedians++", random_state=1)),
        ("KMedoids", ht.cluster.KMedoids(n_clusters=4, init="kmedoids++", random_state=1)),
    ]:
        algo.fit(data)
        centers = np.sort(np.asarray(algo.cluster_centers_.numpy()).round(1), axis=0)
        print(f"{name:9s} n_iter={getattr(algo, 'n_iter_', '?'):>3} centers (sorted):")
        print(centers)
        # the spherical generator plants clusters at diag(-8), diag(-4),
        # diag(4), diag(8); sorted recovered centers must sit near them
        planted = np.array([[-8.0] * 3, [-4.0] * 3, [4.0] * 3, [8.0] * 3])
        assert centers.shape == (4, 3)
        assert np.abs(centers - planted).max() < 1.0, centers


if __name__ == "__main__":
    main()
