"""Sequence-parallel attention over a long context — sequence length
shards across the mesh; K/V blocks ride the ICI ring (heat_tpu.nn
ring_attention). Run under a virtual mesh to see the sharding:

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/ring_attention_longctx.py --seq 8192
"""

from __future__ import annotations

import argparse
import os
import sys

# allow running straight from a checkout: examples/.. is the repo root
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# honor JAX_PLATFORMS even when a site PJRT plugin overrides it (see
# tests/conftest.py: env alone is not reliably honored)
if os.environ.get("JAX_PLATFORMS"):
    import jax

    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import time

import heat_tpu as ht


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--seq", type=int, default=4096)
    p.add_argument("--heads", type=int, default=8)
    p.add_argument("--dim", type=int, default=64)
    args = p.parse_args()

    ht.random.seed(0)
    shape = (1, args.heads, args.seq, args.dim)
    q = ht.random.randn(*shape, split=2)   # sequence axis sharded
    k = ht.random.randn(*shape, split=2)
    v = ht.random.randn(*shape, split=2)
    ht.print0(f"q/k/v: {q.shape} seq-split over {q.comm.size} device(s)")

    t0 = time.perf_counter()
    out = ht.nn.ring_attention(q, k, v, causal=True)
    _ = out.numpy()
    dt = time.perf_counter() - t0
    flops = args.heads * 2 * 2 * args.seq**2 * args.dim * 0.5
    ht.print0(f"causal attention S={args.seq}: {dt*1000:.1f} ms ({flops/dt/1e12:.2f} TFLOP/s)")


if __name__ == "__main__":
    main()
