"""Distributed Lasso regression on the diabetes dataset — the analog of
the reference's examples/lasso/demo.py (load diabetes.h5 split=0,
feature-normalize, fit coordinate-descent Lasso, report coefficients
and training error; the reference additionally plots, which has no
terminal analog).

    python examples/lasso.py [--lam 0.1] [--max-iter 100]
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 python examples/lasso.py
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

if os.environ.get("JAX_PLATFORMS"):
    import jax

    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import numpy as np

import heat_tpu as ht
from heat_tpu import datasets
from heat_tpu.regression import Lasso


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--lam", type=float, default=0.1)
    ap.add_argument("--max-iter", type=int, default=100)
    args = ap.parse_args()

    x = ht.load_hdf5(datasets.path("diabetes.h5"), dataset="x", split=0)
    y = ht.load_hdf5(datasets.path("diabetes.h5"), dataset="y", split=0)

    # feature normalization, as the reference demo does before fitting
    x = x / ht.sqrt(ht.mean(x ** 2, axis=0))

    estimator = Lasso(lam=args.lam, max_iter=args.max_iter)
    estimator.fit(x, y)

    pred = estimator.predict(x)
    mse = float(ht.mean((pred - y) ** 2))
    coef = np.asarray(estimator.coef_.numpy()).ravel()
    nz = int(np.sum(np.abs(coef) > 1e-8))
    print(f"lasso(lam={args.lam}) on diabetes {x.shape}: mse={mse:.1f}")
    print(f"nonzero coefficients: {nz}/{coef.size}")
    print("coef:", np.round(coef, 2))
    assert np.isfinite(mse)


if __name__ == "__main__":
    main()
