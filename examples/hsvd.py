"""Hierarchical SVD of a distributed matrix — the north-star operation
(reference blog: hSVD of a 200 GB dataset; BASELINE.json target).

    python examples/hsvd.py [--rows 16384] [--cols 2048] [--rank 10]
"""

from __future__ import annotations

import argparse
import os
import sys

# allow running straight from a checkout: examples/.. is the repo root
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# honor JAX_PLATFORMS even when a site PJRT plugin overrides it (see
# tests/conftest.py: env alone is not reliably honored)
if os.environ.get("JAX_PLATFORMS"):
    import jax

    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import time

import heat_tpu as ht


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--rows", type=int, default=16384)
    p.add_argument("--cols", type=int, default=2048)
    p.add_argument("--rank", type=int, default=10)
    args = p.parse_args()

    ht.random.seed(0)
    a = ht.random.randn(args.rows, args.cols, split=0)
    ht.print0(f"A: {a.shape} split={a.split} over {a.comm.size} device(s)")

    # first call compiles (~seconds); measure the warm path
    u, sigma, v, err = ht.linalg.hsvd_rank(a, args.rank, compute_sv=True)
    _ = u.numpy()
    t0 = time.perf_counter()
    u, sigma, v, err = ht.linalg.hsvd_rank(a, args.rank, compute_sv=True)
    _ = u.numpy()  # materialize before stopping the clock
    dt = time.perf_counter() - t0

    gb = args.rows * args.cols * 4 / 1e9
    per_chip = gb / dt / a.comm.size
    ht.print0(
        f"hsvd_rank(r={args.rank}): {dt*1000:.1f} ms  "
        f"({gb/dt:.1f} GB/s aggregate, {per_chip:.1f} GB/s/chip)  "
        f"rel-err estimate {float(err):.3f}"
    )
    ht.print0(f"sigma: {sigma.numpy().round(2)}")

    # one-view variant (r5): reads A exactly ONCE — ~1.7x on TPU for
    # near-low-rank data (docs/PERF.md documents the quality trade).
    # NOTE this demo's random matrix is flat-spectrum — OUT of one-view's
    # domain, so expect a large (and honest) error estimate; the row
    # demonstrates the throughput, not the approximation.
    u1, err1 = ht.linalg.hsvd_rank(a, args.rank, single_pass=True)
    _ = u1.numpy()
    t0 = time.perf_counter()
    u1, err1 = ht.linalg.hsvd_rank(a, args.rank, single_pass=True)
    _ = u1.numpy()
    dt1 = time.perf_counter() - t0
    ht.print0(
        f"hsvd_rank(single_pass=True): {dt1*1000:.1f} ms  "
        f"({gb/dt1:.1f} GB/s aggregate)  rel-err estimate {float(err1):.3f}"
    )


if __name__ == "__main__":
    main()
