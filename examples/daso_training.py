"""Hierarchical data-parallel training with DASO — the analog of the
reference's examples/nn/imagenet-DASO.py pattern (node-local sync every
batch, staggered global syncs, bf16-compressed wire) on a two-level
("node", "local") device mesh.

Trains the same MLP classification task as examples/mnist.py, but through
``heat_tpu.optim.DASO``: each node group holds its own parameter replica
(sharded over the "node" mesh axis), local batches update it every step,
and every ``--global-skip`` steps the replicas average over the slow axis
— the reference's skip-batch schedule with bf16 compression on the wire.

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/daso_training.py [--steps 80] [--global-skip 4]
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

if os.environ.get("JAX_PLATFORMS"):
    import jax

    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import numpy as np

import heat_tpu as ht
from heat_tpu import nn, optim


def synthetic_task(n: int = 2048, d: int = 32, classes: int = 4, seed: int = 0):
    """Linearly-separable-ish blobs (offline stand-in for MNIST)."""
    rng = np.random.default_rng(seed)
    centers = rng.standard_normal((classes, d)).astype(np.float32) * 3.0
    y = rng.integers(0, classes, size=n)
    x = centers[y] + rng.standard_normal((n, d)).astype(np.float32)
    return x.astype(np.float32), y.astype(np.int32)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=80)
    ap.add_argument("--global-skip", type=int, default=4)
    ap.add_argument("--lr", type=float, default=0.01)
    args = ap.parse_args()

    comm = ht.get_comm()
    if comm.size % 2:
        print(f"mesh size {comm.size} is odd - DASO needs an even device count; "
              f"run under the 8-device CPU mesh (see module docstring)")
        return

    x_np, y_np = synthetic_task()
    x = ht.array(x_np, split=0)
    y = ht.array(y_np, split=0)

    model = nn.DataParallelMultiGPU(
        nn.Sequential(nn.Linear(32, 64), nn.ReLU(), nn.Linear(64, 4)), key=1
    )
    daso = optim.DASO(
        optim.Adam(lr=args.lr), model,
        n_nodes=2, global_skip=args.global_skip, compression=True,
    )
    print(f"mesh: {comm.size} devices as (node={daso.n_nodes}, local={daso.local_size}); "
          f"global sync every {args.global_skip} steps, bf16 wire")

    for step in range(1, args.steps + 1):
        loss = float(daso.step(x, y))
        if step % 10 == 0 or step == 1:
            preds = np.argmax(np.asarray(model(x).numpy()), axis=1)
            acc = float((preds == y_np).mean())
            print(f"step {step:3d}: loss={loss:.4f} acc={acc:.3f}")

    daso.sync_params()
    preds = np.argmax(np.asarray(model(x).numpy()), axis=1)
    acc = float((preds == y_np).mean())
    print(f"final (synced): acc={acc:.3f}")
    assert acc > 0.8, "DASO training should fit the synthetic task"


if __name__ == "__main__":
    main()
