"""Fusing whole pipelines with ``ht.jit`` — no reference analog (the
reference is torch-eager; a chain of heat calls cannot be fused there).

Demonstrates the round-4 fused-program surface on a small end-to-end
feature pipeline: standardize → gram → spectral row-norms, plus a fitted
estimator's ``predict`` traced into one program.

    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python examples/jit_pipeline.py
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

if os.environ.get("JAX_PLATFORMS"):
    import jax

    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import numpy as np

import heat_tpu as ht


@ht.jit
def feature_pipeline(x):
    """Five public ops — ONE compiled XLA program, one dispatch."""
    x = (x - ht.mean(x, axis=0)) / (ht.std(x, axis=0) + 1e-6)
    g = ht.matmul(ht.transpose(x), x)          # (d, d) across the sharded axis
    return ht.sqrt(ht.sum(g * g, axis=1))      # spectral row-norms


def main() -> None:
    ht.random.seed(0)
    x = ht.random.randn(200_000, 64, split=0)

    t0 = time.perf_counter()
    norms = feature_pipeline(x)                # compiles on first call
    norms.numpy()
    t_compile = time.perf_counter() - t0

    t0 = time.perf_counter()
    norms = feature_pipeline(x + 0.0)          # cached program, one dispatch
    norms.numpy()
    t_cached = time.perf_counter() - t0

    # eager comparison: the same chain, one program PER op. Warm the
    # per-op programs first — timing the cold pass would charge one-time
    # compiles to the eager side (bench.py's methodology: warm, THEN time)
    def eager_chain(a):
        ae = (a - ht.mean(a, axis=0)) / (ht.std(a, axis=0) + 1e-6)
        g = ht.matmul(ht.transpose(ae), ae)
        return ht.sqrt(ht.sum(g * g, axis=1))

    eager_chain(x).numpy()  # warmup/compile
    t0 = time.perf_counter()
    ref = eager_chain(x + 0.0)
    ref.numpy()
    t_eager = time.perf_counter() - t0

    np.testing.assert_allclose(norms.numpy(), ref.numpy(), rtol=1e-3, atol=1e-3)
    ht.print0(
        f"pipeline: compile {t_compile:.3f}s, fused {t_cached*1e3:.1f}ms, "
        f"eager chain {t_eager*1e3:.1f}ms (same results)"
    )

    # estimators compose: a fitted model's predict as one program
    km = ht.cluster.KMeans(n_clusters=4, init="kmeans++", random_state=0).fit(
        x[:20_000]
    )
    fused_predict = ht.jit(km.predict)
    labels = fused_predict(x[:20_000])
    ht.print0(f"fused predict: {labels.shape} labels, split={labels.split}")


if __name__ == "__main__":
    main()
