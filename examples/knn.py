"""Distributed K-nearest-neighbour classification on the iris dataset —
the analog of the reference's examples/classification/demo_knn.py
(reference behavior: load iris.h5 split=0, 5-fold-style verification with
a held-out slice, report accuracy).

    python examples/knn.py [--neighbours 5]
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 python examples/knn.py
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

if os.environ.get("JAX_PLATFORMS"):
    import jax

    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import numpy as np

import heat_tpu as ht
from heat_tpu import datasets
from heat_tpu.classification import KNeighborsClassifier


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--neighbours", type=int, default=5)
    args = ap.parse_args()

    x = ht.load_hdf5(datasets.path("iris.h5"), dataset="data", split=0)
    # iris ships sorted by class: 50 setosa, 50 versicolor, 50 virginica
    y = ht.array(np.repeat(np.arange(3), 50), split=0)

    # hold out every 5th sample (deterministic analog of the reference's
    # random fold) — the mask routes through distributed boolean indexing
    idx = np.arange(x.shape[0])
    test_mask = idx % 5 == 0
    train_x, train_y = x[ht.array(~test_mask)], y[ht.array(~test_mask)]
    test_x, test_y = x[ht.array(test_mask)], y[ht.array(test_mask)]

    clf = KNeighborsClassifier(n_neighbors=args.neighbours)
    clf.fit(train_x, train_y)
    pred = clf.predict(test_x)

    acc = float(ht.mean((pred.astype(ht.int32) == test_y.astype(ht.int32)).astype(ht.float32)))
    print(f"kNN(k={args.neighbours}) on iris: {train_x.shape[0]} train / {test_x.shape[0]} test")
    print(f"accuracy: {acc:.3f}")
    assert acc > 0.9, "iris kNN should be >90% accurate"


if __name__ == "__main__":
    main()
